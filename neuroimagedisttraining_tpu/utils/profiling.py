"""Runtime profiling hooks (the reference has none — SURVEY §5.1).

Wraps ``jax.profiler`` so any federated round can be captured as an XLA
trace viewable in TensorBoard/Perfetto. The wall-clock ``Timer`` that
lived here is deprecated in favor of the observability subsystem
(``obs.metrics.SectionTimer`` / ``MetricsRegistry.timer``); a shim
remains so old imports keep working. Host-side span tracing (Chrome
trace events aligned with the XLA trace) lives in ``obs.trace``.
"""
from __future__ import annotations

import contextlib
import logging
from typing import Any, Dict

import jax

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(log_dir: str):
    """``with trace("/tmp/prof"):`` — captures an XLA/host trace."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def trace_one_round(algo, state, log_dir: str, round_idx: int = 0) -> None:
    """Profile a single federated round (compile excluded: one warm-up
    round runs first so the trace shows steady-state device time).

    Borrows: the caller keeps using ``state`` afterwards (the runner
    profiles before its round loop), so under the state-ownership
    protocol the warm-up runs on a clone — ``run_round`` would
    otherwise consume (donate) the caller's state."""
    if getattr(algo, "_donate", False):
        state = algo.clone_state(state)
    state2, _ = algo.run_round(state, round_idx)
    jax.block_until_ready(jax.tree_util.tree_leaves(state2)[0])
    with trace(log_dir):
        state3, metrics = algo.run_round(state2, round_idx + 1)
        jax.block_until_ready(jax.tree_util.tree_leaves(state3)[0])
    logger.info("wrote profiler trace for one round to %s", log_dir)


class Timer:
    """DEPRECATED shim over ``obs.metrics.SectionTimer`` — same
    ``section``/``summary`` surface, now backed by a registry
    distribution per section. Import ``SectionTimer`` (or use
    ``MetricsRegistry.timer``) directly in new code."""

    def __init__(self):
        import warnings

        from ..obs.metrics import SectionTimer

        warnings.warn(
            "utils.profiling.Timer is deprecated; use "
            "obs.metrics.SectionTimer (or MetricsRegistry.timer)",
            DeprecationWarning, stacklevel=2)
        self._impl = SectionTimer()

    def section(self, name: str):
        return self._impl.section(name)

    def summary(self) -> Dict[str, Any]:
        return self._impl.summary()
