"""Utilities: checkpointing, profiling/cost accounting, logging."""
