"""Cost accounting: FLOPs + communication-parameter counters.

Rebuild of the reference's sparsity-aware FLOPs counter
(``fedml_api/utils/main_flops_counter.py:30-159``) with two upgrades:

* **Exact compiled FLOPs** straight from XLA's cost model
  (``jit(f).lower(...).compile().cost_analysis()``) — covers every op the
  model actually runs, on any backend.
* **Any-rank analytical counter** — the reference's hook-based counter only
  handles Conv2d/Linear and has no input-resolution entry for ABCD, so the
  flagship 3D path could never be counted (SalientGrads approximates FLOPs
  as ``epochs*samples``, ``sailentgrads/client.py:70-76``). Here per-layer
  FLOPs are derived from parameter/activation *shapes* via ``jax.eval_shape``
  + ``capture_intermediates`` — Conv1d/2d/3d and Dense all fall out of the
  same formula, and the sparsity scaling honors each layer's nonzero
  fraction (``(w != 0).sum()`` semantics).

``count_training_flops = 3 x inference`` keeps the reference's convention
(``main_flops_counter.py:146-157``); nonzero-weight communication-size
accounting mirrors ``ModelTrainer.count_communication_params``
(``fedml_core/trainer/model_trainer.py:49-53``).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

TRAIN_TO_INFER_RATIO = 3.0  # fwd + bwd ~= 3x fwd (reference convention)


# -- exact XLA cost -----------------------------------------------------------

def xla_cost_analysis(fn, *example_args) -> Dict[str, float]:
    """FLOPs / bytes of the compiled ``fn`` from XLA's cost model."""
    compiled = jax.jit(fn).lower(*example_args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returned [dict]
        ca = ca[0] if ca else {}
    return dict(ca or {})


def inference_flops_xla(apply_fn, params, sample_shape: Tuple[int, ...],
                        batch_size: int = 1) -> float:
    x = jnp.zeros((batch_size,) + tuple(sample_shape), jnp.float32)
    cost = xla_cost_analysis(
        lambda p, xb: apply_fn(p, xb, train=False, rng=None), params, x)
    return float(cost.get("flops", 0.0))


# -- analytical per-layer (sparsity-aware) ------------------------------------

def _walk(tree, path=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk(v, path + (k,))
    else:
        yield path, tree


def _lookup(tree, path):
    for k in path:
        if not isinstance(tree, dict) or k not in tree:
            return None
        tree = tree[k]
    return tree


def per_layer_flops(model, params, sample_shape: Tuple[int, ...]
                    ) -> Dict[Tuple[str, ...], float]:
    """Per-sample dense FLOPs for every parametric layer (conv of any
    rank + dense), keyed by the layer's param-tree path."""
    x = jax.ShapeDtypeStruct((1,) + tuple(sample_shape), jnp.float32)

    def fwd(p, xb):
        return model.apply({"params": p}, xb, train=False,
                           capture_intermediates=True)

    _, state = jax.eval_shape(fwd, params, x)
    inter = state["intermediates"]

    out: Dict[Tuple[str, ...], float] = {}
    for path, leaf in _walk(params):
        if path[-1] != "kernel":
            continue
        layer_path = path[:-1]
        called = _lookup(inter, layer_path)
        kshape = tuple(leaf.shape)
        if called is not None and "conv_out" in called:
            # fused stages (e.g. S2DStemStage) expose their conv output
            # explicitly — their __call__ returns the pooled tensor, which
            # would undercount the conv's spatial extent by the pool factor
            y = called["conv_out"][0]
            yshape = tuple(np.asarray(y.shape, dtype=np.int64))
        elif called is not None and "__call__" in called:
            y = called["__call__"][0]
            yshape = tuple(np.asarray(y.shape, dtype=np.int64))
        else:
            yshape = None
        if len(kshape) >= 3:  # conv kernel: (*window, Cin/groups, Cout)
            if yshape is None:
                continue
            out_spatial = int(np.prod(yshape[1:-1]))
            out[layer_path] = 2.0 * out_spatial * float(np.prod(kshape))
        elif len(kshape) == 2:  # dense: (in, out)
            mult = 1.0
            if yshape is not None and len(yshape) > 2:
                mult = float(np.prod(yshape[1:-1]))
            out[layer_path] = 2.0 * mult * float(np.prod(kshape))
    return out


def nonzero_fraction(params, mask=None) -> Dict[Tuple[str, ...], float]:
    """Per-layer nonzero fraction of kernels (after masking)."""
    fracs: Dict[Tuple[str, ...], float] = {}
    for path, leaf in _walk(params):
        if path[-1] != "kernel":
            continue
        w = np.asarray(leaf)
        if mask is not None:
            m = _lookup(mask, path)
            if m is not None:
                w = w * np.asarray(m)
        total = w.size or 1
        fracs[path[:-1]] = float(np.count_nonzero(w)) / total
    return fracs


def _scaled_flops(dense: Dict[Tuple[str, ...], float],
                  fracs: Dict[Tuple[str, ...], float]) -> float:
    """Sparsity-scaled total of a per-layer dense-FLOPs dict (the single
    place the scaling convention lives — layers without a recorded
    fraction count dense)."""
    return float(sum(f * fracs.get(p, 1.0) for p, f in dense.items()))


def inference_flops(model, params, sample_shape: Tuple[int, ...],
                    mask=None) -> float:
    """Per-sample analytical inference FLOPs, honoring weight sparsity."""
    dense = per_layer_flops(model, params, sample_shape)
    return _scaled_flops(dense, nonzero_fraction(params, mask))


def training_flops(model, params, sample_shape, mask=None,
                   n_samples: int = 1) -> float:
    return TRAIN_TO_INFER_RATIO * n_samples * inference_flops(
        model, params, sample_shape, mask)


def avg_inference_flops(model, state, sample_shape, num_clients: int,
                        cost_snapshot_fn) -> float:
    """Cohort-mean per-sample inference FLOPs of the final model(s) —
    ``record_avg_inference_flops`` (sailentgrads_api.py:319-332).

    Global-mask algorithms: one count stands for the cohort. Per-client
    masks (DisPFL/SubAvg, incl. --diff_spa's mixed densities): average the
    mask-aware count over every client's slice, with the dense per-layer
    FLOPs computed once."""
    import jax

    masks = getattr(state, "masks", None)
    params = getattr(state, "global_params", None)
    stacked = getattr(state, "personal_params", None)
    if masks is None:
        p, m = cost_snapshot_fn(state)
        if p is None:
            return 0.0
        return inference_flops(model, p, sample_shape, mask=m)
    # per-client masks: average over the cohort. Params are either the
    # stacked personal models (DisPFL) or one global model (SubAvg).
    def slice_c(tree, c):
        return jax.tree_util.tree_map(lambda l: l[c], tree)

    def params_of(c):
        return slice_c(stacked, c) if stacked is not None else params

    dense = per_layer_flops(model, params_of(0), sample_shape)
    total = 0.0
    for c in range(num_clients):
        total += _scaled_flops(
            dense, nonzero_fraction(params_of(c), slice_c(masks, c)))
    return total / max(1, num_clients)


# -- communication accounting -------------------------------------------------

def count_params(params) -> int:
    return int(sum(np.prod(l.shape) for _, l in _walk(params)
                   if hasattr(l, "shape")))


def count_communication_params(params, mask=None) -> int:
    """Nonzero elements actually shipped (model_trainer.py:49-53)."""
    total = 0
    for path, leaf in _walk(params):
        w = np.asarray(leaf)
        if mask is not None:
            m = _lookup(mask, path)
            if m is not None:
                w = w * np.asarray(m)
        total += int(np.count_nonzero(w))
    return total


# -- per-round stat_info counters ---------------------------------------------

class CostTracker:
    """Cumulative FLOPs/comm counters, the rebuild of ``stat_info``'s
    ``sum_training_flops`` / ``sum_comm_params``
    (``sailentgrads_api.py:137-138,334-346``)."""

    def __init__(self, model=None, sample_shape: Optional[Tuple[int, ...]] = None):
        self.model = model
        self.sample_shape = sample_shape
        self.sum_training_flops = 0.0
        self.sum_comm_params = 0
        self.per_round: list = []
        self._dense_flops = None  # per-layer cache: shapes are static

    def _dense_per_layer(self, params) -> Dict[Tuple[str, ...], float]:
        if self._dense_flops is None:
            self._dense_flops = per_layer_flops(
                self.model, params, self.sample_shape)
        return self._dense_flops

    def record_round(self, params, mask=None, n_clients: int = 1,
                     samples_per_client: int = 1) -> Dict[str, float]:
        flops = 0.0
        if self.model is not None and self.sample_shape is not None:
            dense = self._dense_per_layer(params)
            per_sample = _scaled_flops(dense, nonzero_fraction(params, mask))
            flops = (n_clients * TRAIN_TO_INFER_RATIO * samples_per_client
                     * per_sample)
        comm = n_clients * count_communication_params(params, mask)
        self.sum_training_flops += flops
        self.sum_comm_params += comm
        rec = {"training_flops": flops, "comm_params": comm,
               "sum_training_flops": self.sum_training_flops,
               "sum_comm_params": self.sum_comm_params}
        self.per_round.append(rec)
        return rec

    def snapshot_totals(self) -> Dict[str, float]:
        """JSON-serializable totals for the checkpoint metadata sidecar."""
        last = self.per_round[-1] if self.per_round else None
        return {
            "sum_training_flops": self.sum_training_flops,
            "sum_comm_params": self.sum_comm_params,
            "last_training_flops": last["training_flops"] if last else 0.0,
            "last_comm_params": last["comm_params"] if last else 0,
        }

    def restore_totals(self, meta: Dict[str, float]) -> None:
        """Seed the counters from a checkpoint sidecar — exact for
        evolving-mask algorithms, where re-estimating the pre-checkpoint
        rounds from the restored state's current density would diverge
        from the uninterrupted run's totals."""
        self.sum_training_flops = float(meta["sum_training_flops"])
        self.sum_comm_params = int(meta["sum_comm_params"])
        self.per_round = [{
            "training_flops": float(meta["last_training_flops"]),
            "comm_params": int(meta["last_comm_params"]),
            "sum_training_flops": self.sum_training_flops,
            "sum_comm_params": self.sum_comm_params,
        }]

    def record_repeat(self) -> Dict[str, float]:
        """Accumulate another round identical to the last recorded one —
        avoids the device→host param pull when masks are static (dense
        FedAvg, fixed SNIP masks)."""
        last = self.per_round[-1]
        self.sum_training_flops += last["training_flops"]
        self.sum_comm_params += last["comm_params"]
        rec = {"training_flops": last["training_flops"],
               "comm_params": last["comm_params"],
               "sum_training_flops": self.sum_training_flops,
               "sum_comm_params": self.sum_comm_params}
        self.per_round.append(rec)
        return rec
