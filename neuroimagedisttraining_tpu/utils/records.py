"""One-round-deferred metric materialization.

Converting a device scalar to a python float blocks the host on the
accelerator; on a tunneled TPU that sync costs ~5x the per-round eval's
own device time (RESULTS.md round-4 eval anatomy). Both round-loop
drivers (``FedAlgorithm.run`` and the CLI runner) therefore hold each
round's record as device values and materialize+log it only after the
NEXT round's programs are dispatched — same values, same cadence, the
device queue stays full.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np


def to_float(v):
    """0-d device/numpy arrays -> float; python ints/strings/etc. pass
    through untouched (record keys like ``round`` stay ints)."""
    if isinstance(v, (jax.Array, np.ndarray)) and np.ndim(v) == 0:
        return float(v)
    return v


class DeferredRecords:
    """Holds at most one pending record; ``push`` flushes the previous one.

    ``timed=True`` stamps ``round_time_s`` at flush boundaries (the time
    since the previous flush), so the SUM over a run equals wall time
    exactly and per-round attribution is ±1 round — the honest semantics
    under deferred fetching, where the blocking conversion itself happens
    between rounds. Call ``flush`` in a ``finally`` so a crash in round r
    still emits round r-1's already-computed metrics (best-effort: the
    pending fetch may itself raise if the device is gone).
    """

    def __init__(self, log: Callable[[Dict[str, Any]], None],
                 timed: bool = False):
        self._log = log
        self._timed = timed
        self._pending: Optional[Dict[str, Any]] = None
        self._last_t = time.perf_counter()

    def push(self, record: Dict[str, Any]) -> None:
        self.flush()
        self._pending = record

    def flush(self) -> None:
        rec, self._pending = self._pending, None
        if rec is None:
            return
        for k, v in rec.items():
            rec[k] = to_float(v)
        if self._timed:
            t = time.perf_counter()
            rec["round_time_s"] = t - self._last_t
            self._last_t = t
        self._log(rec)

    def flush_safely(self) -> None:
        """``flush`` for exception paths: swallow a fetch that dies with
        the device so the original error propagates instead."""
        try:
            self.flush()
        except Exception:  # pragma: no cover - device-loss path
            self._pending = None


class RunCounters:
    """Run-level fault/recovery totals, accumulated from per-round records.

    The fault-tolerance subsystem (robust/faults.py, robust/guard.py)
    emits its per-round counters as ordinary float record fields
    (``clients_dropped``, ``clients_quarantined``); both round-loop
    drivers feed records through :meth:`update` — including attempts the
    watchdog rolled back, so totals cover every fault that occurred —
    and :meth:`summary` lands in stat_info as ``fault_recovery``
    (alongside the watchdog's own ``rounds_retried``/``rounds_skipped``
    totals, which are authoritative for retry accounting). Values may
    still be device scalars when a record is pushed (DeferredRecords
    materializes late) — ``to_float`` handles both."""

    FIELDS = ("clients_dropped", "clients_quarantined")

    def __init__(self, registry=None) -> None:
        """``registry`` (an ``obs.metrics.MetricsRegistry``) mirrors each
        accumulated field into a ``fault_<field>_total`` counter — the
        obs absorption path; None (the default) keeps the standalone
        behavior the robust layer has always had."""
        self._totals: Dict[str, float] = {}
        self._registry = registry

    def update(self, record: Dict[str, Any]) -> None:
        for field in self.FIELDS:
            v = record.get(field)
            if v is not None:
                fv = float(to_float(v))
                self._totals[field] = self._totals.get(field, 0.0) + fv
                if self._registry is not None and fv:
                    self._registry.counter(
                        "fault_" + field + "_total").inc(fv)

    def summary(self) -> Dict[str, float]:
        return dict(self._totals)
