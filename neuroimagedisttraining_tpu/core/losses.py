"""Loss functions.

Reference semantics: ABCD sex classification uses ``nn.BCEWithLogitsLoss`` on a
single logit with float labels (``sailentgrads/my_model_trainer.py:191-206``);
CIFAR paths use ``nn.CrossEntropyLoss`` (``fedavg/my_model_trainer.py:38-67``).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def _first_output(out):
    # Several reference models return [logits, features]
    # (salient_models.py:139,297); losses consume only the logits.
    if isinstance(out, (tuple, list)):
        return out[0]
    return out


def bce_with_logits_per_example(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example binary cross-entropy with logits; logits [B,1] or [B]."""
    logits = _first_output(logits)
    logits = logits.reshape(logits.shape[0], -1)[:, 0]
    labels = labels.astype(logits.dtype)
    # x*(1-y) + softplus(-x): same value as the max/abs stable form but
    # smooth, so the gradient is sigmoid(x)-y EVERYWHERE — the max/abs
    # form's subgradient at x == 0 is -1 (not torch's analytic -0.5) from
    # JAX's tie-splitting through maximum() and abs()
    return logits * (1.0 - labels) + jax.nn.softplus(-logits)


def softmax_ce_per_example(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example softmax cross-entropy; logits [B, K], labels [B] int."""
    logits = _first_output(logits)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(
        logp, labels[:, None].astype(jnp.int32), axis=-1
    )[:, 0]


def mse_per_example(preds: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-example squared error (AlexNet3D_Dropout_Regression head,
    salient_models.py:248-297)."""
    preds = _first_output(preds)
    preds = preds.reshape(preds.shape[0], -1)[:, 0]
    return jnp.square(preds - targets.astype(preds.dtype))


PER_EXAMPLE_LOSSES = {
    "bce": bce_with_logits_per_example,
    "ce": softmax_ce_per_example,
    "mse": mse_per_example,
}


def bce_with_logits_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean(bce_with_logits_per_example(logits, labels))


def softmax_ce_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean(softmax_ce_per_example(logits, labels))


def mse_loss(preds: jax.Array, targets: jax.Array) -> jax.Array:
    return jnp.mean(mse_per_example(preds, targets))


def predictions(logits: jax.Array, loss_type: str) -> jax.Array:
    """Hard predictions matching the reference's eval rules.

    BCE: sigmoid >= 0.5 (``my_model_trainer.py:243-248``); CE: argmax.
    """
    logits = _first_output(logits)
    if loss_type == "bce":
        logits = logits.reshape(logits.shape[0], -1)[:, 0]
        return (logits >= 0.0).astype(jnp.int32)  # sigmoid(x) >= .5  <=>  x >= 0
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def make_loss_fn(loss_type: str) -> Callable[[jax.Array, jax.Array], jax.Array]:
    if loss_type not in PER_EXAMPLE_LOSSES:
        raise ValueError(f"unknown loss type: {loss_type!r}")
    per_ex = PER_EXAMPLE_LOSSES[loss_type]
    return lambda logits, labels: jnp.mean(per_ex(logits, labels))
