"""Hand-rolled SGD matching torch.optim.SGD semantics, as pure pytree ops.

Reference local optimizer (``sailentgrads/my_model_trainer.py:191-216``):
``torch.nn.utils.clip_grad_norm_(params, 10)`` then
``SGD(lr*decay**round, momentum, weight_decay)``. Torch's update order is
  g   <- g + wd * p          (weight decay added to the *clipped* grad)
  buf <- momentum * buf + g  (buf initialised to g on first step == 0-init)
  p   <- p - lr * buf
We keep that order exactly so convergence comparisons are apples-to-apples.
Written as plain tree-maps (not optax) so the whole update stays transparent
inside a `lax.scan` and fuses into one elementwise XLA kernel per leaf.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))


def clip_by_global_norm(grads: Any, max_norm: float) -> Any:
    """torch.nn.utils.clip_grad_norm_ semantics: scale = max_norm/(norm+1e-6), cap 1."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * scale.astype(g.dtype), grads)


def sgd_momentum_step(
    params: Any,
    momentum_buf: Any,
    grads: Any,
    lr: jax.Array,
    momentum: float,
    weight_decay: float,
) -> Tuple[Any, Any]:
    """One torch-order SGD step. Returns (new_params, new_momentum_buf)."""

    def leaf(p, m, g):
        g = g + weight_decay * p if weight_decay else g
        m = momentum * m + g if momentum else g
        return p - lr.astype(p.dtype) * m, m

    flat = jax.tree_util.tree_map(leaf, params, momentum_buf, grads)
    new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                        is_leaf=lambda t: isinstance(t, tuple))
    new_mom = jax.tree_util.tree_map(lambda t: t[1], flat,
                                     is_leaf=lambda t: isinstance(t, tuple))
    return new_params, new_mom
