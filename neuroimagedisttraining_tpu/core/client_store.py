"""Population-scale client store: host/disk-resident per-client rows.

The simulated federation keeps every per-client array — the ``[C, model]``
personal stack, the topk ``agg_residual`` — fully device-resident, so
population size is capped by HBM (PR 9 proved C=256 on one chip; the
ROADMAP north star needs populations that dwarf device memory). This
module is the memory hierarchy below the device: the device holds only
the active cohort's S trained rows, host RAM holds a pinned hot-client
LRU cache, and a memory-mapped on-disk store holds the full population
keyed by client id — all behind one gather/stage/commit API (the
ZeRO-Offload shape: host-resident state, overlapped transfers, the hot
working set on device).

Residency contract (pinned by tests/test_client_store.py): a streamed
run is **bit-identical** to the fully-resident run. The store never
computes — it moves byte-exact rows between device, host RAM, and disk,
and rows synthesized from a field's registered default are byte-exact
copies of the default template (zero storage until a row is actually
trained: a C=10^6 population with S=8 trained/round materializes 8 rows
per round, not 10^6 zeros — the ``--track_personal 0`` + topk residual
fix rides on exactly this laziness).

Staging protocol (the watchdog/no-poison composition):

* ``stage(name, ids, slab)`` parks a round's output rows WITHOUT
  touching storage — the slab may still be an in-flight device array
  (``np.asarray`` is deferred so dispatch pipelining survives);
* ``commit()`` materializes staged slabs into storage (one host
  transfer per leaf); ``gather``/``gather_all`` commit first, so reads
  always see the newest adopted rows;
* ``discard()`` drops staged slabs unconverted — the watchdog's
  rollback-retry path: a rolled-back round's rows never reach storage,
  extending PR 7's no-poison-leak pin to host RAM and disk.

``prefetch`` warms a host-side row cache off the gather clock (the
double-buffering hook: the driver prefetches the next block's
not-dirtied rows while the current block computes); ``stats`` exposes
the ``mem_store_*`` gauges/counters and the cumulative
``store_gather_ms`` the obs ledger records per round.
"""
from __future__ import annotations

import json
import logging
import os
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)

__all__ = ["ClientStore", "STORE_MODES"]

#: residency modes below "device" (device = no store at all)
STORE_MODES = ("host", "disk")


def _np_leaves(tree: Any) -> Tuple[List[np.ndarray], Any]:
    """Flatten ``tree`` to host numpy leaves + treedef (no-copy for
    arrays already on host)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return [np.asarray(x) for x in leaves], treedef


class _Field:
    """One registered per-client field: a default row template plus the
    materialized rows (host dict in ``host`` mode; LRU-capped hot dict
    over per-leaf ``np.memmap`` files in ``disk`` mode)."""

    def __init__(self, name: str, template: Any, num_clients: int,
                 mode: str, hot_clients: int, root: Optional[str]):
        self.name = name
        leaves, self.treedef = _np_leaves(template)
        self.leaf_templates = leaves
        self.num_clients = num_clients
        self.mode = mode
        self.hot_clients = max(1, int(hot_clients))
        #: host-RAM rows: the whole materialized set (host mode) or the
        #: pinned hot-client LRU (disk mode) — id -> list of np leaves
        self.rows: "OrderedDict[int, List[np.ndarray]]" = OrderedDict()
        self.materialized = np.zeros(num_clients, dtype=bool)
        self.mmaps: List[np.memmap] = []
        self._mmap_paths: List[str] = []
        if mode == "disk":
            if root is None:
                raise ValueError(
                    "ClientStore(mode='disk') needs a root directory "
                    "for the per-leaf memmap files")
            os.makedirs(root, exist_ok=True)
            for i, leaf in enumerate(leaves):
                path = os.path.join(root, f"{name}_leaf{i}.mmap")
                mm = np.memmap(path, dtype=leaf.dtype, mode="w+",
                               shape=(num_clients,) + leaf.shape)
                self.mmaps.append(mm)
                self._mmap_paths.append(path)

    def default_row(self) -> List[np.ndarray]:
        # a fresh copy per synthesis: callers may mutate rows in place
        return [t.copy() for t in self.leaf_templates]

    def read_row(self, cid: int) -> Tuple[List[np.ndarray], bool]:
        """(leaves, host_hit). Synthesizes the default for a row that
        was never written — byte-exact, zero storage."""
        row = self.rows.get(cid)
        if row is not None:
            if self.mode == "disk":  # LRU touch
                self.rows.move_to_end(cid)
            return row, True
        if self.mode == "disk" and self.materialized[cid]:
            return [np.array(mm[cid]) for mm in self.mmaps], False
        return self.default_row(), False

    def write_row(self, cid: int, leaves: List[np.ndarray]) -> None:
        self.materialized[cid] = True
        if self.mode == "host":
            self.rows[cid] = leaves
            return
        self.rows[cid] = leaves
        self.rows.move_to_end(cid)
        while len(self.rows) > self.hot_clients:
            old_id, old_leaves = self.rows.popitem(last=False)
            for mm, leaf in zip(self.mmaps, old_leaves):
                mm[old_id] = leaf

    def flush_hot(self) -> None:
        """Disk mode: spill every hot row to its memmap (checkpoint
        snapshots read the authoritative bytes from one place)."""
        if self.mode != "disk":
            return
        for cid, leaves in self.rows.items():
            for mm, leaf in zip(self.mmaps, leaves):
                mm[cid] = leaf

    def host_cache_bytes(self) -> int:
        row_bytes = sum(int(t.nbytes) for t in self.leaf_templates)
        return row_bytes * len(self.rows)

    def disk_bytes(self) -> int:
        return sum(int(mm.nbytes) for mm in self.mmaps)


class ClientStore:
    """Host/disk-resident per-client state keyed by client id.

    One store instance serves every registered field (``personal_params``,
    ``agg_residual``) uniformly; rows move device->host through the
    stage/commit protocol and host->device through ``gather`` (the
    caller ``jax.device_put``s the returned slab)."""

    def __init__(self, num_clients: int, mode: str = "host",
                 hot_clients: int = 64, root: Optional[str] = None):
        if mode not in STORE_MODES:
            raise ValueError(
                f"client store mode {mode!r} not in {STORE_MODES} "
                "(mode 'device' means: no store)")
        if num_clients < 1:
            raise ValueError("ClientStore needs num_clients >= 1")
        self.num_clients = int(num_clients)
        self.mode = mode
        self.hot_clients = int(hot_clients)
        self._root = root
        if mode == "disk" and root is None:
            import tempfile

            self._root = tempfile.mkdtemp(prefix="client_store_")
        self._fields: Dict[str, _Field] = {}
        #: staged (uncommitted) round outputs: list of (name, ids, slab)
        #: — slab leaves may be device arrays (np.asarray deferred)
        self._staged: List[Tuple[str, np.ndarray, Any]] = []
        #: prefetched committed rows: name -> {id: leaves}
        self._prefetched: Dict[str, Dict[int, List[np.ndarray]]] = {}
        # counters (floats: the obs record contract)
        self.hits = 0
        self.misses = 0
        self.prefetched_rows = 0
        self.gather_ms = 0.0

    # -- registration -------------------------------------------------------
    def register(self, name: str, template: Any) -> None:
        """Register field ``name`` with its lazy per-row default
        (``template`` — e.g. the init params row for the personal
        stack, zeros for the topk residual). Unwritten rows synthesize
        byte-exact copies of the default on gather, with no storage.
        Re-registration resets the field (a fresh ``init_state``)."""
        self._fields[name] = _Field(
            name, template, self.num_clients, self.mode,
            self.hot_clients, self._root)
        self._prefetched.pop(name, None)
        self._staged = [s for s in self._staged if s[0] != name]

    def has_field(self, name: str) -> bool:
        return name in self._fields

    def field_names(self) -> Tuple[str, ...]:
        return tuple(sorted(self._fields))

    def _field(self, name: str) -> _Field:
        f = self._fields.get(name)
        if f is None:
            raise KeyError(
                f"client store has no field {name!r} (registered: "
                f"{self.field_names()}) — init_state registers fields "
                "before the first round")
        return f

    # -- staging protocol ---------------------------------------------------
    def stage(self, name: str, ids: Sequence[int], slab: Any) -> None:
        """Park a round's output rows (``slab`` = pytree with leading
        axis ``len(ids)``) without converting or writing — commit()
        materializes, discard() (the watchdog rollback) drops them."""
        self._field(name)  # fail fast on unknown fields
        self._staged.append((name, np.asarray(ids), slab))

    def commit(self) -> None:
        """Write staged slabs into storage (one host transfer per leaf;
        later stages of the same id win — round order)."""
        staged, self._staged = self._staged, []
        for name, ids, slab in staged:
            field = self._field(name)
            leaves, treedef = jax.tree_util.tree_flatten(slab)
            host_leaves = [np.asarray(x) for x in leaves]
            pre = self._prefetched.get(name)
            for pos, cid in enumerate(ids):
                cid = int(cid)
                if pre is not None:  # staged rows outdate prefetched
                    pre.pop(cid, None)
                field.write_row(
                    cid, [np.array(hl[pos]) for hl in host_leaves])

    def discard(self) -> None:
        """Drop staged slabs unconverted (watchdog RETRY/SKIP: the
        rolled-back round's rows never reach host RAM or disk)."""
        self._staged = []

    def dirty_ids(self) -> np.ndarray:
        """Ids with staged (uncommitted) rows — the checkpoint layer
        flushes these before snapshotting."""
        if not self._staged:
            return np.zeros((0,), np.int64)
        return np.unique(np.concatenate(
            [ids for _, ids, _ in self._staged]))

    # -- reads --------------------------------------------------------------
    def gather(self, name: str, ids: Sequence[int]) -> Any:
        """Stacked host rows ``[len(ids), ...]`` for ``ids`` (commits
        staged rows first so reads see the newest adopted state). The
        caller device-puts the returned pytree."""
        t0 = time.perf_counter()
        self.commit()
        field = self._field(name)
        pre = self._prefetched.get(name)
        stacked: Optional[List[np.ndarray]] = None
        for pos, cid in enumerate(ids):
            cid = int(cid)
            row = pre.pop(cid, None) if pre is not None else None
            if row is not None:
                self.hits += 1
            else:
                row, host_hit = field.read_row(cid)
                if host_hit:
                    self.hits += 1
                else:
                    self.misses += 1
            if stacked is None:
                stacked = [
                    np.empty((len(ids),) + leaf.shape, leaf.dtype)
                    for leaf in row]
            for li, leaf in enumerate(row):
                stacked[li][pos] = leaf
        self.gather_ms += (time.perf_counter() - t0) * 1e3
        if stacked is None:  # zero-id gather
            stacked = [np.empty((0,) + t.shape, t.dtype)
                       for t in field.leaf_templates]
        return jax.tree_util.tree_unflatten(field.treedef, stacked)

    def gather_all(self, name: str) -> Any:
        """The full ``[C, ...]`` stack (store-backed full personal eval
        / reseed). O(C) host RAM transiently — population-scale callers
        use the incremental paths instead."""
        return self.gather(name, np.arange(self.num_clients))

    def prefetch(self, name: str, ids: Sequence[int]) -> None:
        """Warm the host row cache for ``ids`` off the gather clock —
        the double-buffering hook (the driver calls it for the NEXT
        block's not-dirtied rows right after dispatching the current
        block, so disk reads / default synthesis overlap device
        compute). Only committed rows are prefetched; commit()
        invalidates any entry a newer staged row outdates."""
        if not self.has_field(name):
            return
        field = self._field(name)
        staged_ids = set(int(i) for i in self.dirty_ids())
        pre = self._prefetched.setdefault(name, {})
        for cid in ids:
            cid = int(cid)
            if cid in pre or cid in staged_ids:
                continue
            row, _ = field.read_row(cid)
            pre[cid] = row
            self.prefetched_rows += 1

    # -- observability ------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """The obs ledger's per-round store sample: ``mem_``-prefixed
        gauges (volatile for the fleet comparator by the existing
        prefix rule) plus the cumulative ``store_gather_ms``."""
        host_bytes = sum(f.host_cache_bytes()
                         for f in self._fields.values())
        pre_bytes = 0
        for name, rows in self._prefetched.items():
            f = self._fields.get(name)
            if f is None or not rows:
                continue
            pre_bytes += sum(int(t.nbytes)
                             for t in f.leaf_templates) * len(rows)
        disk_bytes = sum(f.disk_bytes() for f in self._fields.values())
        return {
            "mem_host_cache_bytes": float(host_bytes + pre_bytes),
            "mem_store_disk_bytes": float(disk_bytes),
            "mem_store_hits": float(self.hits),
            "mem_store_misses": float(self.misses),
            "mem_store_prefetched": float(self.prefetched_rows),
            "store_gather_ms": float(self.gather_ms),
        }

    # -- checkpoint lineage -------------------------------------------------
    def snapshot_save(self, path: str) -> None:
        """One-file npz snapshot: every MATERIALIZED row of every field
        plus a manifest (population size, field layouts). Default-only
        rows are not stored — the restoring side re-synthesizes them
        from its own registered defaults, which the deterministic
        ``init_state`` reproduces bit-exactly."""
        self.commit()
        arrays: Dict[str, np.ndarray] = {}
        manifest: Dict[str, Any] = {"num_clients": self.num_clients,
                                    "fields": {}}
        for name, field in self._fields.items():
            field.flush_hot()
            ids = np.nonzero(field.materialized)[0]
            manifest["fields"][name] = {
                "n_leaves": len(field.leaf_templates),
                "n_rows": int(ids.size),
            }
            arrays[f"{name}::ids"] = ids.astype(np.int64)
            for li in range(len(field.leaf_templates)):
                if field.mode == "disk":
                    rows = np.stack(
                        [np.array(field.mmaps[li][int(i)])
                         for i in ids]) if ids.size else np.empty(
                        (0,) + field.leaf_templates[li].shape,
                        field.leaf_templates[li].dtype)
                else:
                    rows = np.stack(
                        [field.rows[int(i)][li] for i in ids]) \
                        if ids.size else np.empty(
                        (0,) + field.leaf_templates[li].shape,
                        field.leaf_templates[li].dtype)
                arrays[f"{name}::leaf{li}"] = rows
        arrays["__manifest__"] = np.frombuffer(
            json.dumps(manifest).encode(), dtype=np.uint8)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)  # atomic: a SIGKILL mid-write cannot
        # leave a truncated sidecar that poisons every later --resume

    def snapshot_load(self, path: str) -> None:
        """Replace this store's contents with a snapshot's. Fields must
        already be registered (init_state ran) — the snapshot carries
        rows, not layouts; a field-set mismatch is the store analogue
        of the checkpoint schema mismatch and raises."""
        with np.load(path) as z:
            manifest = json.loads(bytes(z["__manifest__"]).decode())
            snap_fields = set(manifest["fields"])
            if snap_fields != set(self._fields):
                raise RuntimeError(
                    f"client-store snapshot {path} carries fields "
                    f"{sorted(snap_fields)} but this run registered "
                    f"{list(self.field_names())} — the lineage was "
                    "written under different flags (track_personal / "
                    "agg_impl)")
            if int(manifest["num_clients"]) != self.num_clients:
                raise RuntimeError(
                    f"client-store snapshot {path} was written for "
                    f"C={manifest['num_clients']}, this run has "
                    f"C={self.num_clients}")
            self._staged = []
            self._prefetched = {}
            for name, field in self._fields.items():
                # reset to all-default, then write the snapshot rows
                field.rows = OrderedDict()
                field.materialized[:] = False
                ids = z[f"{name}::ids"]
                leaves = [z[f"{name}::leaf{li}"]
                          for li in range(len(field.leaf_templates))]
                for pos, cid in enumerate(ids):
                    field.write_row(
                        int(cid),
                        [np.array(lf[pos]) for lf in leaves])
