"""Jittable local-training and evaluation kernels.

This replaces the reference's per-client Python training loop
(``sailentgrads/my_model_trainer.py:185-219``: SGD(lr*decay**round) + BCE +
clip(10) + post-step ``param *= mask``) with a pure function over one client's
state that is `vmap`ed over the leading client axis and `lax.scan`ned over
local steps — so a whole cohort's local epoch is one XLA program with no
host round-trips (the reference pays a GPU→CPU ``state_dict`` deepcopy per
client per round, ``my_model_trainer.py:131-132``).

Batching model: each client's local shard lives padded at ``[n_max, ...]``
with a valid-count scalar. The default ``hp.batching == "epoch"`` draws
per-epoch shuffled batches — each client consumes exactly its own
``ceil(n_i/batch)`` batches per epoch, the last one partial, matching the
reference's ``DataLoader(shuffle=True, drop_last=False)`` iteration
(``ABCD/data_loader.py:202``, ``my_model_trainer.py:194-216``); steps past a
client's own count are masked no-ops so shapes stay static under jit/vmap.
``hp.batching == "replacement"`` keeps the round-1/2 uniform
with-replacement draws (also unbiased; marginally cheaper per step).
"""
from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .losses import PER_EXAMPLE_LOSSES, make_loss_fn, predictions
from .optim import clip_by_global_norm, sgd_momentum_step
from .state import HyperParams


ApplyFn = Callable[..., Any]  # apply_fn(params, x, train: bool, rng) -> logits


def epoch_permutations(rng: jax.Array, n_valid: jax.Array, epochs: int,
                       length: int, n_rows: int = 0) -> jax.Array:
    """``[epochs, length]`` shuffles for epoch batching: per epoch, the first
    ``min(n_valid, length)`` entries are a uniform draw without replacement
    from ALL valid row indices ``[0, n_valid)`` — a full permutation when
    ``length >= n_valid``; the remaining entries point at padded rows
    (``>= n_valid``) and are masked out by the per-example batch weights.
    Static-shape replacement for the reference's per-epoch DataLoader
    shuffle (``ABCD/data_loader.py:202``).

    ``n_rows`` is the padded shard size; the draw domain is
    ``max(length, n_rows)`` so a caller-truncated epoch (``steps_per_epoch *
    batch_size < n_i``) consumes a fresh random subset of the WHOLE shard
    each epoch rather than a fixed prefix."""
    domain = max(length, int(n_rows))
    positions = jnp.arange(domain)

    def one(key):
        scores = jnp.where(positions < n_valid,
                           jax.random.uniform(key, (domain,)), jnp.inf)
        return jnp.argsort(scores)[:length].astype(jnp.int32)

    return jax.vmap(one)(jax.random.split(rng, epochs))


def make_client_update(
    apply_fn: ApplyFn,
    loss_type: str,
    hp: HyperParams,
    mask_grads: bool = False,
    mask_params_post_step: bool = True,
    prox_lambda: float = 0.0,
    remat: bool = False,
    fused_kernels: bool = False,
    full_batches: bool = False,
    augment_fn: Callable = None,
):
    """Build the per-client local-training function.

    ``mask_grads``: also zero gradients through the mask (DisPFL/SubAvg-style
    masked SGD, ``DisPFL/my_model_trainer.py:147-172``).
    ``mask_params_post_step``: multiply params by mask after each optimizer
    step (SalientGrads, ``my_model_trainer.py:213-216``).
    ``prox_lambda``: Ditto's personalization pull — after each step,
    ``w -= lr * lambda * (w - w_global)`` (``ditto/my_model_trainer.py:63-64``).
    ``remat``: rematerialize the per-batch loss (activations recomputed in
    the backward pass) — trades FLOPs for HBM so more clients fit
    concurrently under the vmap (``client_chunk`` can rise).
    ``fused_kernels``: route the optimizer update through the Pallas fused
    masked-SGD kernel (ops/pallas_kernels.py) instead of the XLA chain.
    ``augment_fn``: jittable ``(rng, xb) -> xb`` training-time augmentation
    (e.g. :func:`data.cifar.random_crop_flip`), applied to every gathered
    training batch inside the scanned step — the device-side equivalent of
    the reference's torchvision train transform running in the DataLoader
    (``cifar10/data_loader.py:46-50``). Eval paths never see it.
    ``full_batches``: caller-asserted static guarantee that EVERY client's
    ``n_valid >= steps_per_epoch * batch_size`` (checkable host-side from
    the concrete shard counts). Epoch mode then skips the provably-no-op
    machinery — per-example batch weights, active-step selects — with
    bit-identical semantics (every batch is full, every step active).

    Returns ``client_update(params, momentum, mask, rng, x, y, n_valid,
    round_idx, prox_target) -> (params, momentum, mean_loss)``; vmap over a
    leading client axis on everything except ``round_idx``. ``prox_target``
    is ignored (and DCE'd) unless ``prox_lambda > 0``.
    """
    per_example = PER_EXAMPLE_LOSSES[loss_type]
    epoch_mode = hp.batching == "epoch"

    def batch_loss(params, xb, yb, wb, dropout_rng):
        logits = apply_fn(params, xb, train=True, rng=dropout_rng)
        if wb is None:
            # full batch: plain mean, reduced in f32 like the masked
            # branch so the full_batches fast path and the masked path
            # keep identical reduction precision under bf16 compute
            return jnp.mean(per_example(logits, yb).astype(jnp.float32))
        # partial final epoch batch: mean over the batch's own valid
        # examples, exactly the reference's smaller-last-batch loss.mean()
        w = wb.astype(jnp.float32)
        per_ex = per_example(logits, yb).astype(jnp.float32)
        return jnp.sum(per_ex * w) / jnp.maximum(jnp.sum(w), 1.0)

    if remat:
        batch_loss = jax.checkpoint(
            batch_loss, policy=jax.checkpoint_policies.nothing_saveable)
    grad_fn = jax.value_and_grad(batch_loss)

    def apply_update(params, momentum, grads, mask, prox_target, lr):
        """One optimizer step: clip + (masked) SGD + prox pull + re-mask."""
        grads = clip_by_global_norm(grads, hp.grad_clip)
        if fused_kernels and not prox_lambda:
            from ..ops.pallas_kernels import fused_masked_sgd_step

            ones = mask if (mask_grads or mask_params_post_step) \
                else jax.tree_util.tree_map(jnp.ones_like, params)
            return fused_masked_sgd_step(
                params, momentum, grads, ones, lr,
                momentum=hp.momentum, wd=hp.weight_decay,
                mask_grads=mask_grads)
        if mask_grads:
            grads = jax.tree_util.tree_map(lambda g, m: g * m, grads, mask)
        params, momentum = sgd_momentum_step(
            params, momentum, grads, lr, hp.momentum, hp.weight_decay
        )
        if prox_lambda:
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr.astype(p.dtype) * prox_lambda * (p - g),
                params, prox_target,
            )
        if mask_params_post_step:
            params = jax.tree_util.tree_map(lambda p, m: p * m, params, mask)
        return params, momentum

    def client_update(params, momentum, mask, rng, x, y, n_valid, round_idx,
                      prox_target):
        lr = hp.lr * jnp.power(hp.lr_decay, round_idx.astype(jnp.float32))

        if epoch_mode:
            spe, bs = hp.steps_per_epoch, hp.batch_size
            k_perm, k_steps = jax.random.split(rng)
            # [E, spe*bs] per-epoch shuffles, flattened for dynamic slicing
            flat_perms = epoch_permutations(
                k_perm, n_valid, hp.local_epochs, spe * bs,
                n_rows=x.shape[0]).reshape(-1)

            def step(carry, s):
                params, momentum = carry
                k_drop = jax.random.fold_in(k_steps, s)
                pos = s % spe
                start = (s // spe) * (spe * bs) + pos * bs
                idx = lax.dynamic_slice(flat_perms, (start,), (bs,))
                # perm slots past n_valid point past the padded shard when
                # spe*bs > n_rows; clamp (their loss terms are masked by wb
                # anyway, but jnp.take's default OOB fill is NaN)
                idx = jnp.minimum(idx, x.shape[0] - 1)
                xb = jnp.take(x, idx, axis=0)
                yb = jnp.take(y, idx, axis=0)
                if augment_fn is not None:
                    k_aug, k_drop = jax.random.split(k_drop)
                    xb = augment_fn(k_aug, xb)
                if full_batches:
                    # statically guaranteed: every batch full, every step
                    # active — same math without the masking machinery
                    loss, grads = grad_fn(params, xb, yb, None, k_drop)
                    params, momentum = apply_update(
                        params, momentum, grads, mask, prox_target, lr)
                    return (params, momentum), (loss, jnp.bool_(True))
                # validity of this batch's slots within the client's epoch
                offs = pos * bs + jnp.arange(bs)
                wb = offs < n_valid
                loss, grads = grad_fn(params, xb, yb, wb, k_drop)
                new_params, new_momentum = apply_update(
                    params, momentum, grads, mask, prox_target, lr)
                # steps past this client's own ceil(n_i/bs) are no-ops
                active = (pos * bs) < n_valid
                params = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(active, a, b), new_params, params)
                momentum = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(active, a, b), new_momentum,
                    momentum)
                return (params, momentum), (loss, active)

            (params, momentum), (losses, actives) = lax.scan(
                step, (params, momentum), jnp.arange(hp.local_steps))
            act = actives.astype(jnp.float32)
            mean_loss = jnp.sum(losses * act) / jnp.maximum(jnp.sum(act), 1.0)
            return params, momentum, mean_loss

        def step(carry, key):
            params, momentum = carry
            k_idx, k_drop = jax.random.split(key)
            idx = jax.random.randint(k_idx, (hp.batch_size,), 0,
                                     jnp.maximum(n_valid, 1))
            xb = jnp.take(x, idx, axis=0)
            yb = jnp.take(y, idx, axis=0)
            if augment_fn is not None:
                k_aug, k_drop = jax.random.split(k_drop)
                xb = augment_fn(k_aug, xb)
            loss, grads = grad_fn(params, xb, yb, None, k_drop)
            params, momentum = apply_update(
                params, momentum, grads, mask, prox_target, lr)
            return (params, momentum), loss

        keys = jax.random.split(rng, hp.local_steps)
        (params, momentum), losses = lax.scan(step, (params, momentum), keys)
        return params, momentum, jnp.mean(losses)

    return client_update


def make_eval_fn(apply_fn: ApplyFn, loss_type: str, eval_batch: int = 32):
    """Build the per-client evaluation function.

    Implements the reference's test protocol (``my_model_trainer.py:222-260``:
    sigmoid>=.5 / argmax accuracy + summed loss over the local test set) over a
    padded ``[m_max, ...]`` shard; entries at index >= n_valid are ignored.
    Returns ``eval_client(params, x, y, n_valid) -> (correct, loss_sum, total)``.
    """
    loss_fn = make_loss_fn(loss_type)

    def eval_client(params, x, y, n_valid):
        m_max = x.shape[0]
        # never batch wider than the shard: tiny test shards (small ABCD
        # sites) would otherwise be padded up to eval_batch and burn a
        # full-width forward on padding rows (floor 1 keeps the zero-row
        # shard edge well-defined: nb = 0, empty scan, zero totals)
        eb = max(1, min(eval_batch, m_max))
        pad = (-m_max) % eb
        if pad:  # static — pad the shard so chunking is exact
            x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            y = jnp.pad(y, [(0, pad)])
            m_max += pad
        nb = m_max // eb

        def body(carry, i):
            correct, loss_sum = carry
            start = i * eb
            xb = lax.dynamic_slice_in_dim(x, start, eb, axis=0)
            yb = lax.dynamic_slice_in_dim(y, start, eb, axis=0)
            logits = apply_fn(params, xb, train=False, rng=None)
            preds = predictions(logits, loss_type)
            valid = (start + jnp.arange(eb)) < n_valid
            correct += jnp.sum((preds == yb.astype(jnp.int32)) & valid)
            # per-example loss, masked by validity
            per_ex = PER_EXAMPLE_LOSSES[loss_type](logits, yb)
            loss_sum += jnp.sum(per_ex * valid.astype(per_ex.dtype))
            return (correct, loss_sum), None

        (correct, loss_sum), _ = lax.scan(
            body, (jnp.zeros((), jnp.int32), jnp.zeros((), jnp.float32)),
            jnp.arange(nb),
        )
        return correct, loss_sum, n_valid

    return eval_client
