from .state import ClientState, HyperParams
from .losses import make_loss_fn, bce_with_logits_loss, softmax_ce_loss
from .optim import clip_by_global_norm, sgd_momentum_step
from .trainer import make_client_update, make_eval_fn

__all__ = [
    "ClientState",
    "HyperParams",
    "make_loss_fn",
    "bce_with_logits_loss",
    "softmax_ce_loss",
    "clip_by_global_norm",
    "sgd_momentum_step",
    "make_client_update",
    "make_eval_fn",
]
