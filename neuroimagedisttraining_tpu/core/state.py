"""Client/server state pytrees.

Replaces the reference's per-client CPU ``state_dict`` dicts
(``fedml_core/trainer/model_trainer.py:8-58`` — get/set params around a single
shared ``nn.Module``) with a stacked, device-resident pytree: every field has a
leading client axis so an entire federated cohort is one SPMD value.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class HyperParams:
    """Static local-training hyperparameters.

    Mirrors the reference's flag surface for the local SGD loop
    (``my_model_trainer.py:185-216``): torch.optim.SGD(lr * lr_decay**round,
    momentum, weight_decay), grad-norm clip at ``grad_clip``, ``epochs`` local
    epochs of ``steps_per_epoch`` batches of ``batch_size``.
    """

    lr: float = struct.field(pytree_node=False, default=1e-3)
    lr_decay: float = struct.field(pytree_node=False, default=0.998)
    momentum: float = struct.field(pytree_node=False, default=0.0)
    weight_decay: float = struct.field(pytree_node=False, default=0.0)
    grad_clip: float = struct.field(pytree_node=False, default=10.0)
    local_epochs: int = struct.field(pytree_node=False, default=2)
    steps_per_epoch: int = struct.field(pytree_node=False, default=4)
    batch_size: int = struct.field(pytree_node=False, default=16)
    # "epoch" (default): per-epoch shuffled batches, each client consuming
    # exactly its own ceil(n_i/batch) batches per epoch with a partial final
    # batch — the reference's DataLoader(shuffle=True, drop_last=False)
    # semantics (my_model_trainer.py:194-216); steps beyond a client's own
    # count are masked no-ops so shapes stay static under jit/vmap.
    # "replacement": uniform with-replacement draws (round 1/2 behavior).
    batching: str = struct.field(pytree_node=False, default="epoch")

    @property
    def local_steps(self) -> int:
        return self.local_epochs * self.steps_per_epoch


@struct.dataclass
class ClientState:
    """Per-client training state; stacked along a leading client axis.

    ``params``    — model parameter pytree ([C, ...] per leaf when stacked)
    ``momentum``  — SGD momentum buffers, same structure as params
    ``mask``      — {0,1} float pytree, same structure (sparse-FL algorithms);
                    all-ones for dense algorithms
    ``rng``       — per-client PRNG key
    """

    params: Any
    momentum: Any
    mask: Any
    rng: jax.Array


def zeros_like_tree(tree: Any) -> Any:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def ones_like_tree(tree: Any) -> Any:
    return jax.tree_util.tree_map(jnp.ones_like, tree)


def stack_trees(trees: list) -> Any:
    """Stack a list of identically-structured pytrees along a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def broadcast_tree(tree: Any, n: int) -> Any:
    """Replicate a pytree n times along a new leading client axis.

    This is the SPMD analogue of the reference broadcasting the global model to
    each simulated client via ``set_model_params`` (``sailentgrads/client.py:57-66``).
    """
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), tree
    )


def tree_index(tree: Any, idx: jax.Array) -> Any:
    """Gather rows ``idx`` from the leading (client) axis of every leaf."""
    return jax.tree_util.tree_map(lambda x: jnp.take(x, idx, axis=0), tree)


def tree_scatter_update(tree: Any, idx: jax.Array, update: Any) -> Any:
    """Scatter ``update`` (leading axis = len(idx)) back into the client axis."""
    return jax.tree_util.tree_map(
        lambda x, u: x.at[idx].set(u), tree, update
    )


def weighted_tree_sum(tree: Any, weights: jax.Array) -> Any:
    """Weighted sum over the leading client axis of every leaf.

    The TPU-native form of the reference's CPU dict-arithmetic FedAvg
    aggregation loop (``fedavg_api.py:102-117`` / ``sailentgrads_api.py:212-227``):
    with the client axis sharded over the mesh, XLA lowers this contraction to a
    weighted all-reduce over ICI.
    """
    return jax.tree_util.tree_map(
        lambda x: jnp.tensordot(weights.astype(x.dtype), x, axes=1), tree
    )


def mix_over_clients(mix_matrix: jax.Array, stacked: Any) -> Any:
    """Contract a [C, C] mixing/adjacency matrix against the leading client
    axis of every leaf: out_i = sum_j A[i, j] * leaf_j.

    This is the TPU-native form of gossip aggregation — the reference loops
    over neighbor state_dicts per client (``dpsgd_api.py:169-178``,
    ``dispfl_api.py:222-240``); here one contraction covers the whole cohort
    and XLA turns it into all-gather + local GEMM over ICI when the client
    axis is sharded.
    """
    return jax.tree_util.tree_map(
        lambda x: jnp.tensordot(mix_matrix.astype(x.dtype), x, axes=1),
        stacked,
    )
