"""TPU-native federated learning framework for neuroimaging.

A ground-up JAX/XLA re-design of the capabilities of
riohib/NeuroImageDistTraining (a FedML fork for federated sex-classification
on the site-partitioned ABCD neuroimaging cohort): nine FL algorithms,
SNIP/ERK sparse training, 3D CNN model zoo, non-IID partitioning,
Byzantine-robust aggregation, and gossip topologies.

Design stance (see SURVEY.md §7): the reference simulates clients
*sequentially* in one Python loop with CPU weight averaging
(`fedml_api/standalone/*/\\*_api.py`). Here a federated round is a single
jitted SPMD program: every per-client quantity (params, optimizer momentum,
masks, RNG, data) is a pytree with a leading client axis, sharded over a
`clients` mesh axis; local SGD is a `lax.scan` vmapped over clients;
aggregation is a weighted reduction that XLA lowers to ICI collectives.
"""

__version__ = "0.1.0"
