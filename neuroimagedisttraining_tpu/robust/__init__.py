from .aggregation import (
    RobustAggregator,
    add_gaussian_noise,
    norm_diff_clipping,
    vectorize_weights,
)
from .faults import FaultSpec, make_fault_fn, parse_fault_spec
from .guard import (
    carry_if_empty,
    finite_screen,
    guarded_aggregate,
    merge_updates,
    quarantine,
)
from .recovery import RoundWatchdog, tree_finite

__all__ = [
    "RobustAggregator",
    "add_gaussian_noise",
    "norm_diff_clipping",
    "vectorize_weights",
    "FaultSpec",
    "make_fault_fn",
    "parse_fault_spec",
    "carry_if_empty",
    "finite_screen",
    "guarded_aggregate",
    "merge_updates",
    "quarantine",
    "RoundWatchdog",
    "tree_finite",
]
