from .aggregation import (
    RobustAggregator,
    add_gaussian_noise,
    norm_diff_clipping,
    vectorize_weights,
)

__all__ = [
    "RobustAggregator",
    "add_gaussian_noise",
    "norm_diff_clipping",
    "vectorize_weights",
]
