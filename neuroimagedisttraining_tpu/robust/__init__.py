from .aggregation import (
    ROBUST_AGGS,
    RobustAggregator,
    add_gaussian_noise,
    norm_diff_clipping,
    resolve_krum_f,
    robust_combine_mat,
)
from .faults import (
    FaultSpec,
    fault_trace_round,
    make_fault_fn,
    make_labelflip_fn,
    parse_fault_spec,
)
from .guard import (
    carry_if_empty,
    finite_screen,
    guarded_aggregate,
    merge_updates,
    quarantine,
)
from .recovery import RoundWatchdog, tree_finite

__all__ = [
    "ROBUST_AGGS",
    "RobustAggregator",
    "add_gaussian_noise",
    "norm_diff_clipping",
    "resolve_krum_f",
    "robust_combine_mat",
    "FaultSpec",
    "fault_trace_round",
    "make_fault_fn",
    "make_labelflip_fn",
    "parse_fault_spec",
    "carry_if_empty",
    "finite_screen",
    "guarded_aggregate",
    "merge_updates",
    "quarantine",
    "RoundWatchdog",
    "tree_finite",
]
