"""In-jit non-finite quarantine: detect, degrade, recover — inside the round.

The reference's only defense (``robust_aggregation.py``) clips norms; a
single NaN/Inf update (a diverged client, a bit flip on the wire, an
injected fault from ``robust.faults``) still poisons the aggregate and
every subsequent round. This module screens the ``[C, ...]``-stacked
local updates with ONE per-client bool reduce before ``_aggregate``,
zero-weights the quarantined clients, renormalizes the aggregation
weights over the survivors, and — when nobody survives — carries the
previous global model unchanged.

Design invariants (tests/test_guard.py pins all three):

* **bit-identity when clean** — every transform is a ``jnp.where``
  *select*, never arithmetic, so a round with zero quarantined clients
  produces bit-for-bit the unguarded aggregate (weights untouched, rows
  untouched, aggregate selected as-is);
* **wire-agnostic** — sanitized rows are exact zeros with zero weight,
  so every ``agg_impl`` (dense / bucketed / bf16 / int8 / sparse)
  aggregates the survivor subset exactly as if the quarantined clients
  had never reported (adding zero-weighted zero rows is exact in fp);
* **no NaN propagation** — quarantined rows are select-replaced with
  zeros BEFORE any contraction (``0 * NaN`` is NaN, so zero-weighting
  alone would not be enough).
"""
from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

#: renormalization floor — only reachable when every client is
#: quarantined, in which case the aggregate is discarded anyway
#: (``carry_if_empty``)
_EPS = 1e-12


def _row_select(ok: jax.Array, ndim: int) -> jax.Array:
    """Broadcast the per-client bool vector against an [C, ...] leaf."""
    return ok.reshape(ok.shape + (1,) * (ndim - 1))


def finite_screen(stacked: Any) -> jax.Array:
    """Per-client all-finite flag over every leaf of a [C, ...]-stacked
    pytree: ONE [C] bool reduce (the in-graph screen the round program
    runs before aggregation)."""
    flags = None
    for x in jax.tree_util.tree_leaves(stacked):
        f = jnp.all(jnp.isfinite(x), axis=tuple(range(1, x.ndim))) \
            if x.ndim > 1 else jnp.isfinite(x)
        flags = f if flags is None else jnp.logical_and(flags, f)
    if flags is None:
        raise ValueError("finite_screen: empty pytree")
    return flags


def quarantine(stacked: Any, weights: jax.Array,
               ok: jax.Array) -> Tuple[Any, jax.Array, jax.Array]:
    """Quarantine the ``~ok`` clients: select-replace their rows with
    exact zeros, zero their weights, and renormalize the weights over the
    survivors. Returns ``(sanitized, new_weights, survivors)`` with
    ``survivors`` the int32 survivor count.

    When every client is ok this is a bitwise no-op: the row select
    returns the input rows and the weight renormalization is bypassed by
    a scalar select (dividing by the re-summed weights would perturb the
    last bit of an already-normalized vector). The sanitize is an
    unconditional O(C x params) write — the round program never pays it
    on clean rounds because :func:`guarded_aggregate` (which calls this
    inside its bad branch) gates the whole thing behind one
    ``lax.cond``."""
    w_masked = jnp.where(ok, weights, jnp.zeros_like(weights))
    total = jnp.sum(w_masked)
    any_bad = jnp.logical_not(jnp.all(ok))
    new_weights = jnp.where(
        any_bad, w_masked / jnp.maximum(total, _EPS), weights)
    sanitized = jax.tree_util.tree_map(
        lambda x: jnp.where(
            _row_select(ok, x.ndim), x, jnp.zeros_like(x)),
        stacked)
    survivors = jnp.sum(ok.astype(jnp.int32))
    return sanitized, new_weights, survivors


def guarded_aggregate(stacked: Any, weights: jax.Array, ok: jax.Array,
                      aggregate_fn, fallback: Any) -> Any:
    """The round's fused quarantine+aggregate spelling: ONE ``lax.cond``
    over the whole aggregation. The clean branch runs ``aggregate_fn``
    on the untouched inputs — bitwise the unguarded aggregate, and the
    only full-tree work a clean round pays beyond it is the read-only
    finite screen that produced ``ok`` (measured +2.9% of the scale-32
    dry-run round vs +13% for an unconditional row-sanitize, RESULTS.md
    "Round-7"). The bad branch select-zeroes the quarantined rows,
    renormalizes the weights over the survivors, aggregates, and carries
    ``fallback`` (the previous global model) when nobody survived.

    ``aggregate_fn(stacked, weights)`` must be traceable under
    ``lax.cond`` — every ``agg_impl`` wire qualifies (the collectives
    see a replicated predicate)."""
    any_bad = jnp.logical_not(jnp.all(ok))

    def bad(args):
        st, wv = args
        sanitized, w_new, survivors = quarantine(st, wv, ok)
        return carry_if_empty(
            aggregate_fn(sanitized, w_new), fallback, survivors)

    def clean(args):
        st, wv = args
        return aggregate_fn(st, wv)

    return jax.lax.cond(any_bad, bad, clean, (stacked, weights))


def carry_if_empty(aggregate: Any, fallback: Any,
                   survivors: jax.Array) -> Any:
    """Survivor count 0 ⇒ the round degrades to a no-op: select the
    previous global model instead of the (all-zero-weight) aggregate."""
    keep = survivors > 0
    return jax.tree_util.tree_map(
        lambda a, f: jnp.where(keep, a, f.astype(a.dtype)),
        aggregate, fallback)


def merge_residual(ok: jax.Array, new_rows: Any, prev_rows: Any) -> Any:
    """Error-feedback residual × quarantine (``agg_impl='topk'``): a
    quarantined client never shipped anything this round, and its
    compensated delta may carry the very poison the screen caught — so
    its residual row KEEPS the previous value. A pure row select (never
    arithmetic): NaN in ``new_rows`` cannot propagate through it, which
    is the 'a quarantined client's residual must not leak into later
    rounds' invariant (tests/test_agg_topk_hier.py pins it). Clean
    rounds (all ok) select every new row bitwise."""
    return jax.tree_util.tree_map(
        lambda n, p: jnp.where(_row_select(ok, n.ndim), n, p),
        new_rows, prev_rows)


def merge_updates(ok: jax.Array, updates: Any, personal: Any,
                  sel_idx: jax.Array) -> Any:
    """The personal-stack protection: the rows to scatter back into the
    [C, ...] personal stack — each selected client's update where it
    survived, its PREVIOUS personal row where it was quarantined or
    dropped (those clients never delivered anything). The fallback gather
    (``personal[sel_idx]``) runs inside the rare branch, so a clean round
    pays nothing beyond the ``all(ok)`` scalar."""
    def _fix(args):
        upd, pers, sel = args
        from ..core.state import tree_index

        return jax.tree_util.tree_map(
            lambda u, p: jnp.where(_row_select(ok, u.ndim), u, p),
            upd, tree_index(pers, sel))

    return jax.lax.cond(
        jnp.all(ok), lambda args: args[0], _fix,
        (updates, personal, sel_idx))
