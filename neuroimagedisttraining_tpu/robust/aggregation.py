"""Byzantine-robust aggregation: transform defenses + robust estimators.

Two generations of defense live here:

* **Transform defenses** (re-design of
  ``fedml_core/robustness/robust_aggregation.py``): norm-difference
  clipping (:38-50, ``diff / max(1, |diff|/bound)``) and weak-DP Gaussian
  noise (:52-55), pure pytree functions vmappable over the client axis so
  the whole defense runs inside the jitted round program. They transform
  every client's update and leave the weighted mean in place — a *finite*
  poisoned update still votes (bounded, but it votes).
* **Robust estimators** (``--robust_agg``): the weighted mean itself is
  REPLACED by a Byzantine-robust statistic over the stacked client
  deltas — coordinate-wise median / β-trimmed mean (Yin et al., 2018,
  "Byzantine-Robust Distributed Learning") and Krum / Multi-Krum
  pairwise-distance selection (Blanchard et al., 2017, "Machine Learning
  with Adversaries"), plus ``norm_krum`` = Krum with the transform
  defenses' norm clip as its pre-selection stage. All are jit-pure
  functions of a ``[S, D]`` delta matrix and the aggregation weights,
  traceable under ``lax.cond`` so they slot into
  ``guard.guarded_aggregate`` unchanged.

Quarantine convention: the estimators take the guard's survivor set from
the WEIGHTS — a zero aggregation weight means "this row never reported"
(exactly what ``guard.quarantine`` produces). This matters because order
statistics are not weighted-linear: the guard's zero-row trick is exact
for the weighted mean but a zeroed row would VOTE in a median, so the
estimators mask on ``weights > 0`` instead of trusting row contents.

The estimators are UNWEIGHTED over the survivor set (the classical
definitions): sample-count weights gate membership, not influence —
a deliberate deviation recorded in PARITY.md.

The reference's ``is_weight_param`` filter (:28-29) exists to skip BN
running stats; this framework uses GroupNorm (no running stats), so every
parameter leaf participates. The flattening shared with the aggregation
buckets is ONE definition: ``parallel.collectives.tree_to_vec`` (the
former ``vectorize_weights`` alias — an orphaned duplicate with no
callers — is deleted; see tests/test_robust_e2e.py).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

#: the ``--robust_agg`` family ("none" = plain weighted mean)
ROBUST_AGGS = ("none", "median", "trimmed_mean", "krum", "multikrum",
               "norm_krum")


def resolve_krum_f(krum_f: int, n: int) -> int:
    """The Krum Byzantine allowance ``f`` for an ``n``-row cohort:
    an explicit positive setting wins; 0 (the ``--robust_krum_f``
    default) auto-resolves to ``max(1, ceil(0.2 * n))`` — the ≤20%
    attacker budget the acceptance scenario assumes. Static (python int):
    the neighbor count must be shape-level, not traced."""
    if krum_f > 0:
        return int(krum_f)
    return max(1, -(-n // 5))


def _masked_median(mat: jax.Array, ok: jax.Array,
                   m: jax.Array) -> jax.Array:
    """Coordinate-wise median over the ``ok`` rows of ``[S, D]`` ``mat``.
    Masked rows sort to +inf (a select, never arithmetic — NaN in a
    quarantined row cannot propagate); with ``m`` survivors the median
    reads sorted rows ``(m-1)//2`` and ``m//2`` (equal for odd ``m``, so
    the 0.5*(x+x) spelling is bit-exact there)."""
    big = jnp.where(ok[:, None], mat, jnp.inf)
    srt = jnp.sort(big, axis=0)
    lo = jnp.maximum((m - 1) // 2, 0)
    hi = jnp.maximum(m // 2, 0)
    return 0.5 * (srt[lo] + srt[hi])


def _masked_trimmed_mean(mat: jax.Array, ok: jax.Array, m: jax.Array,
                         trim_frac: float) -> jax.Array:
    """Coordinate-wise β-trimmed mean: per coordinate, drop the
    ``floor(β·m)`` largest and smallest survivor values, average the
    rest. The trim clamps to ``(m-1)//2`` per side so at least one row
    always remains (a tiny cohort with a big β degrades toward the
    median, never to an empty mean)."""
    s = mat.shape[0]
    big = jnp.where(ok[:, None], mat, jnp.inf)
    srt = jnp.sort(big, axis=0)
    t = jnp.floor(trim_frac * m.astype(jnp.float32)).astype(jnp.int32)
    t = jnp.clip(t, 0, jnp.maximum((m - 1) // 2, 0))
    idx = jnp.arange(s)[:, None]
    keep = jnp.logical_and(idx >= t, idx < m - t)
    cnt = jnp.maximum(m - 2 * t, 1).astype(jnp.float32)
    return jnp.sum(jnp.where(keep, srt, 0.0), axis=0) / cnt


def _krum_scores(rows: jax.Array, ok: jax.Array, m: jax.Array,
                 f_eff: int) -> jax.Array:
    """Krum scores: for each survivor row, the sum of its ``m - f - 2``
    smallest squared distances to OTHER survivors (non-survivors are
    masked out of both the candidate and neighbor sets). Distances via
    the Gram expansion (an [S,S,D] broadcast would materialize the whole
    cohort squared), clamped at 0 against cancellation."""
    s = rows.shape[0]
    sq = jnp.sum(rows * rows, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (rows @ rows.T)
    d2 = jnp.maximum(d2, 0.0)
    eye = jnp.eye(s, dtype=bool)
    valid = jnp.logical_and(ok[None, :], jnp.logical_not(eye))
    d2 = jnp.where(valid, d2, jnp.inf)
    srt = jnp.sort(d2, axis=1)
    nb = jnp.clip(m - f_eff - 2, 1, jnp.maximum(m - 1, 1))
    nbmask = jnp.arange(s)[None, :] < nb
    scores = jnp.sum(jnp.where(nbmask, srt, 0.0), axis=1)
    return jnp.where(ok, scores, jnp.inf)


def robust_combine_mat(mat: jax.Array, weights: jax.Array, kind: str, *,
                       trim_frac: float = 0.2, krum_f: int = 0,
                       norm_bound: float = 5.0) -> jax.Array:
    """Combine the ``[S, D]`` delta rows into ONE ``[D]`` robust delta.

    ``weights`` are the round's aggregation weights — their only role
    here is the survivor mask (``weights > 0``; see module docstring).
    Jit-pure and ``lax.cond``-traceable; deterministic tie-breaks
    (argmin/argsort pick the first/lowest index). With zero survivors
    the result is garbage by construction — ``guard.carry_if_empty``
    selects the fallback before it can matter."""
    if kind not in ROBUST_AGGS or kind == "none":
        raise ValueError(
            f"robust_combine_mat: kind {kind!r} not a robust estimator "
            f"(one of {ROBUST_AGGS[1:]})")
    mat = mat.astype(jnp.float32)
    ok = weights > 0
    m = jnp.sum(ok.astype(jnp.int32))
    if kind == "median":
        return _masked_median(mat, ok, m)
    if kind == "trimmed_mean":
        return _masked_trimmed_mean(mat, ok, m, trim_frac)
    f_eff = resolve_krum_f(krum_f, mat.shape[0])
    rows = mat
    if kind == "norm_krum":
        # the transform defenses' norm clip (norm_diff_clipping's
        # diff/max(1, |diff|/bound) formula) as Krum's pre-selection
        # stage: selection runs on clipped rows and the WINNER is the
        # clipped row, so even a mis-selected attacker is norm-bounded
        norms = jnp.sqrt(jnp.sum(rows * rows, axis=1, keepdims=True))
        rows = rows / jnp.maximum(1.0, norms / norm_bound)
    scores = _krum_scores(rows, ok, m, f_eff)
    if kind in ("krum", "norm_krum"):
        # one survivor ⇒ every score is inf (no neighbors); return it
        sel = jnp.where(m > 1, jnp.argmin(scores),
                        jnp.argmax(ok.astype(jnp.int32)))
        return rows[sel]
    # multikrum: uniform mean of the q lowest-scoring survivors
    q = jnp.clip(m - f_eff - 2, 1, jnp.maximum(m, 1))
    order = jnp.argsort(scores)
    qmask = jnp.arange(mat.shape[0]) < q
    picked = rows[order]
    return (jnp.sum(jnp.where(qmask[:, None], picked, 0.0), axis=0)
            / q.astype(jnp.float32))


def norm_diff_clipping(local: Any, global_: Any, norm_bound: float) -> Any:
    """Clip the local-vs-global weight difference to ``norm_bound``
    (robust_aggregation.py:38-50): w_g + diff/max(1, |diff|/bound)."""
    diff = jax.tree_util.tree_map(lambda l, g: l - g, local, global_)
    norm = jnp.sqrt(sum(
        jnp.sum(jnp.square(d)) for d in jax.tree_util.tree_leaves(diff)
    ))
    scale = 1.0 / jnp.maximum(1.0, norm / norm_bound)
    return jax.tree_util.tree_map(
        lambda g, d: g + d * scale.astype(d.dtype), global_, diff
    )


def add_gaussian_noise(tree: Any, rng: jax.Array, stddev: float) -> Any:
    """Weak-DP defense: additive Gaussian noise on every leaf
    (robust_aggregation.py:52-55)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        x + stddev * jax.random.normal(k, x.shape, x.dtype)
        for x, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


class RobustAggregator:
    """Configurable defense applied to client updates before averaging
    (robust_aggregation.py:32-55).

    defense_type: "none" | "norm_diff_clipping" | "weak_dp"
    (weak_dp = clipping + noise, as in the reference's pairing).
    """

    def __init__(self, defense_type: str = "none", norm_bound: float = 5.0,
                 stddev: float = 0.025):
        if defense_type not in ("none", "norm_diff_clipping", "weak_dp"):
            raise ValueError(f"unknown defense type {defense_type!r}")
        self.defense_type = defense_type
        self.norm_bound = norm_bound
        self.stddev = stddev

    def apply(self, stacked_locals: Any, global_: Any,
              rng: Optional[jax.Array]) -> Any:
        """Defend a [C, ...]-stacked pytree of local models; jit-safe."""
        if self.defense_type == "none":
            return stacked_locals
        clipped = jax.vmap(
            lambda l: norm_diff_clipping(l, global_, self.norm_bound)
        )(stacked_locals)
        if self.defense_type == "norm_diff_clipping":
            return clipped
        c = jax.tree_util.tree_leaves(clipped)[0].shape[0]
        keys = jax.random.split(rng, c)
        return jax.vmap(
            lambda l, k: add_gaussian_noise(l, k, self.stddev)
        )(clipped, keys)
