"""Byzantine-robust aggregation defenses.

Re-design of ``fedml_core/robustness/robust_aggregation.py``: norm-difference
clipping (:38-50, ``diff / max(1, |diff|/bound)``) and weak-DP Gaussian noise
(:52-55), as pure pytree functions vmappable over the client axis so the
whole defense runs inside the jitted round program.

The reference's ``is_weight_param`` filter (:28-29) exists to skip BN running
stats; this framework uses GroupNorm (no running stats), so every parameter
leaf participates — ``vectorize_weights`` keeps the name for parity.

Composition with the aggregation subsystem (``parallel/collectives.py``):
defenses transform the [C, ...]-stacked LOCAL models before the central
weighted mean runs, so every ``agg_impl`` (dense / bucketed / bf16 / int8 /
sparse) consumes defended trees unchanged — the defense never sees, and
never needs to see, the wire format. The flattening both layers use is one
definition (``collectives.tree_to_vec``).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..parallel.collectives import tree_to_vec


def vectorize_weights(tree: Any) -> jax.Array:
    """Flatten a parameter pytree into one vector
    (robust_aggregation.py:4-9; shared with the aggregation buckets —
    ``parallel.collectives.tree_to_vec``)."""
    return tree_to_vec(tree)


def norm_diff_clipping(local: Any, global_: Any, norm_bound: float) -> Any:
    """Clip the local-vs-global weight difference to ``norm_bound``
    (robust_aggregation.py:38-50): w_g + diff/max(1, |diff|/bound)."""
    diff = jax.tree_util.tree_map(lambda l, g: l - g, local, global_)
    norm = jnp.sqrt(sum(
        jnp.sum(jnp.square(d)) for d in jax.tree_util.tree_leaves(diff)
    ))
    scale = 1.0 / jnp.maximum(1.0, norm / norm_bound)
    return jax.tree_util.tree_map(
        lambda g, d: g + d * scale.astype(d.dtype), global_, diff
    )


def add_gaussian_noise(tree: Any, rng: jax.Array, stddev: float) -> Any:
    """Weak-DP defense: additive Gaussian noise on every leaf
    (robust_aggregation.py:52-55)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        x + stddev * jax.random.normal(k, x.shape, x.dtype)
        for x, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


class RobustAggregator:
    """Configurable defense applied to client updates before averaging
    (robust_aggregation.py:32-55).

    defense_type: "none" | "norm_diff_clipping" | "weak_dp"
    (weak_dp = clipping + noise, as in the reference's pairing).
    """

    def __init__(self, defense_type: str = "none", norm_bound: float = 5.0,
                 stddev: float = 0.025):
        if defense_type not in ("none", "norm_diff_clipping", "weak_dp"):
            raise ValueError(f"unknown defense type {defense_type!r}")
        self.defense_type = defense_type
        self.norm_bound = norm_bound
        self.stddev = stddev

    def apply(self, stacked_locals: Any, global_: Any,
              rng: Optional[jax.Array]) -> Any:
        """Defend a [C, ...]-stacked pytree of local models; jit-safe."""
        if self.defense_type == "none":
            return stacked_locals
        clipped = jax.vmap(
            lambda l: norm_diff_clipping(l, global_, self.norm_bound)
        )(stacked_locals)
        if self.defense_type == "norm_diff_clipping":
            return clipped
        c = jax.tree_util.tree_leaves(clipped)[0].shape[0]
        keys = jax.random.split(rng, c)
        return jax.vmap(
            lambda l, k: add_gaussian_noise(l, k, self.stddev)
        )(clipped, keys)
