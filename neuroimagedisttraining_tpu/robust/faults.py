"""Deterministic, PRNG-keyed fault injection for federated rounds.

The reference codebase has no fault path at all — a diverging client or a
dropped SLURM task loses the run (``DisPFL/error3469448.err``). At the
north-star scale (ROADMAP) client dropout, stragglers, and corrupted
updates are the steady state, so this module gives the round loop a
*model* of them that is

* **in-jit** — faults are applied to the ``[S, ...]``-stacked local
  updates inside the round program, so the guarded round (``robust.guard``)
  stays one SPMD dispatch and composes with every ``agg_impl`` wire;
* **deterministic** — every draw is keyed off
  ``fold_in(fold_in(fold_in(PRNGKey(run_seed), SALT), round), client_id)``,
  a pure function of (run seed, round index, GLOBAL client id). A killed
  and ``--resume``-d run replays the *identical* fault trace, and the
  fused ``lax.scan`` round loop produces the same trace bit-for-bit as
  the unfused loop (tests/test_faults.py pins both).

``--fault_spec`` grammar (comma-separated ``kind=prob`` entries):

    drop=0.2,straggle=0.1,nan=0.05,scale=0.02:100x

* ``drop``     — the client drops out: its update never reaches the
                 server (the guard zero-weights it and keeps its
                 personal model unchanged);
* ``straggle`` — the client is preempted mid-round and returns
                 partial-epoch work: its update delta is scaled by a
                 per-(round, client) uniform draw in [0.25, 0.75);
* ``nan``      — non-finite poison: the whole update is NaN (a diverged
                 or bit-flipped client), to be caught by the guard's
                 finite-screen;
* ``scale``    — Byzantine scaled update (the classic model-replacement
                 attack): delta scaled by ``factor`` (default 100;
                 ``scale=p:Fx`` sets it — the trailing ``x`` is
                 optional);
* ``signflip`` — Byzantine sign-flip: the delta is negated (the client
                 pushes the model AWAY from its own descent direction —
                 finite, norm-preserving, invisible to the guard);
* ``collude``  — colluding scaled clients: every client whose draw fires
                 in a round ships the SAME forged delta — ``factor`` ×
                 a per-(seed, round) Rademacher direction shared by all
                 colluders (``collude=p:Fx`` sets the factor). Mutually
                 identical updates are Krum's known blind spot: the
                 colluders look maximally "close" to each other;
* ``labelflip``— data poisoning via the DATA path: the flagged client
                 trains on flipped labels (``C-1-y`` for integer
                 class labels, ``1-y`` for binary targets) — the update
                 itself is an honest SGD step on dishonest data, so no
                 post-hoc screen on the update can see it.

Faults compose per client in a fixed order: ``labelflip`` acts upstream
(on the training data); post-training, nan overrides the delta
transforms; ``collude`` REPLACES the delta (overriding ``scale`` /
``straggle`` / ``signflip``); ``scale`` overrides ``straggle``;
``signflip`` negates whatever factor survived; ``drop`` is orthogonal
(a dropped client's payload is irrelevant — the guard discards it).

Key-derivation note: the original four kinds draw from
``uniform(k, (4,))`` and the straggle fraction from ``fold_in(k, 1)`` —
those draws are FROZEN (recorded traces replay bit-for-bit across
versions). The newer kinds (signflip/collude/labelflip) draw from the
separately-folded ``fold_in(k, 2)``, so enabling them never perturbs an
existing spec's trace.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

#: domain-separation salt so fault draws never collide with training keys
#: derived from the same run seed ("faul")
FAULT_SALT = 0x6661756C

#: round-level salt for the colluders' shared direction ("col")
COLLUDE_SALT = 0x636F6C

_KINDS = ("drop", "straggle", "nan", "scale", "signflip", "collude",
          "labelflip")

#: kinds taking a ``=p:Fx`` factor suffix -> FaultSpec factor field
_FACTOR_KINDS = {"scale": "scale_factor", "collude": "collude_factor"}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Parsed ``--fault_spec``: per-round, per-client fault probabilities."""

    drop: float = 0.0
    straggle: float = 0.0
    nan: float = 0.0
    scale: float = 0.0
    scale_factor: float = 100.0
    signflip: float = 0.0
    collude: float = 0.0
    collude_factor: float = 100.0
    labelflip: float = 0.0

    @property
    def any_active(self) -> bool:
        return max(self.drop, self.straggle, self.nan, self.scale,
                   self.signflip, self.collude, self.labelflip) > 0.0

    def describe(self) -> str:
        parts = []
        for k in _KINDS:
            p = getattr(self, k)
            if p <= 0:
                continue
            if k in _FACTOR_KINDS:
                fac = getattr(self, _FACTOR_KINDS[k])
                parts.append(f"{k}={p:g}:{fac:g}x")
            else:
                parts.append(f"{k}={p:g}")
        return ",".join(parts) or "none"


def parse_fault_spec(spec: Optional[str]) -> Optional[FaultSpec]:
    """``"drop=0.2,straggle=0.1,nan=0.05,scale=0.02:100x"`` -> FaultSpec;
    empty/None -> None (fault injection off). Raises ValueError on unknown
    kinds or out-of-range probabilities — an explicit raise, not an
    assert: a typo'd chaos config silently injecting nothing would defeat
    the test it powers (the python -O hazard, ADVICE r5)."""
    if not spec:
        return None
    fields = {}
    factors = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"fault_spec entry {entry!r} is not kind=prob "
                f"(kinds: {_KINDS})")
        kind, _, val = entry.partition("=")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} (kinds: {_KINDS})")
        if ":" in val:
            if kind not in _FACTOR_KINDS:
                raise ValueError(
                    f"fault kind {kind!r} takes no :factor suffix "
                    f"(only {tuple(_FACTOR_KINDS)})")
            val, _, fac = val.partition(":")
            factor = float(fac.rstrip("xX"))
            if factor <= 0:
                raise ValueError(
                    f"{kind} factor must be positive, got {factor}")
            factors[_FACTOR_KINDS[kind]] = factor
        p = float(val)
        if not 0.0 <= p <= 1.0:
            raise ValueError(
                f"fault probability {kind}={p} outside [0, 1]")
        if kind in fields:
            raise ValueError(f"duplicate fault kind {kind!r}")
        fields[kind] = p
    return FaultSpec(**factors, **fields)


FaultFn = Callable[[Any, Any, jax.Array, jax.Array], Tuple[Any, jax.Array]]


def make_fault_fn(spec: FaultSpec, seed: int) -> FaultFn:
    """Build the jit-traceable injector.

    ``inject(stacked, global_params, sel_idx, round_idx) ->
    (faulted_stacked, dropped[S])``: applies the spec's faults to the
    ``[S, ...]``-stacked post-training local models (``global_params`` is
    the unbatched pre-round global the deltas are measured against) and
    returns the per-client dropout flags. Keys depend only on
    (seed, round, global client id), so the trace is independent of
    cohort composition, participation fraction, retry nonce, and
    fused-vs-unfused execution.
    """
    base = jax.random.fold_in(jax.random.PRNGKey(seed), FAULT_SALT)
    nan_p, drop_p = spec.nan, spec.drop
    straggle_p, scale_p = spec.straggle, spec.scale
    scale_factor = spec.scale_factor
    signflip_p, collude_p = spec.signflip, spec.collude
    collude_factor = spec.collude_factor

    def inject(stacked: Any, global_params: Any, sel_idx: jax.Array,
               round_idx: jax.Array) -> Tuple[Any, jax.Array]:
        rkey = jax.random.fold_in(
            base, jnp.asarray(round_idx).astype(jnp.int32))
        coll_dir = None
        if collude_p > 0:
            # the colluders' shared direction: ONE Rademacher tree per
            # (seed, round) — every colluding client in the round ships
            # the identical forged delta, independent of which clients'
            # draws fired (the shared-direction contract)
            dkey = jax.random.fold_in(rkey, COLLUDE_SALT)
            leaves, treedef = jax.tree_util.tree_flatten(global_params)
            dkeys = jax.random.split(dkey, len(leaves))
            coll_dir = jax.tree_util.tree_unflatten(treedef, [
                jax.random.rademacher(k, x.shape, x.dtype)
                for k, x in zip(dkeys, leaves)])

        def per_client(update, cid):
            k = jax.random.fold_in(rkey, cid)
            u = jax.random.uniform(k, (4,))
            frac = jax.random.uniform(
                jax.random.fold_in(k, 1), minval=0.25, maxval=0.75)
            # newer kinds draw from a SEPARATE folded key: the (4,)
            # vector and the fold_in(k, 1) fraction above are frozen —
            # extending them would silently rewrite every recorded trace
            u2 = jax.random.uniform(jax.random.fold_in(k, 2), (3,))
            dropped = u[0] < drop_p
            straggles = u[1] < straggle_p
            poisoned = u[2] < nan_p
            byzantine = u[3] < scale_p
            signflips = u2[0] < signflip_p
            colludes = u2[1] < collude_p
            factor = jnp.where(straggles, frac, 1.0)
            factor = jnp.where(byzantine, scale_factor, factor)
            factor = jnp.where(signflips, -factor, factor)
            rescaled = jnp.logical_or(
                jnp.logical_or(straggles, byzantine), signflips)

            def leaf(p, g, d):
                # select-guard the delta transform: a client with no
                # fired fault passes through BIT-EXACT (g + (p - g) is
                # not p in IEEE arithmetic, so an unconditional rewrite
                # would smear round-off over the whole cohort and
                # contaminate faulted-vs-clean ablations)
                out = jnp.where(
                    rescaled, g + (p - g) * factor.astype(p.dtype), p)
                if d is not None:
                    out = jnp.where(
                        colludes,
                        g + jnp.asarray(collude_factor, p.dtype) * d,
                        out)
                return jnp.where(
                    poisoned, jnp.full_like(out, jnp.nan), out)

            if coll_dir is None:
                faulted = jax.tree_util.tree_map(
                    lambda p, g: leaf(p, g, None), update, global_params)
            else:
                faulted = jax.tree_util.tree_map(
                    leaf, update, global_params, coll_dir)
            return faulted, dropped

        return jax.vmap(per_client, in_axes=(0, 0))(stacked, sel_idx)

    return inject


def make_labelflip_fn(spec: FaultSpec, seed: int, num_classes: int):
    """The DATA-path twin of :func:`make_fault_fn` for ``labelflip``:
    ``flip(y_sel, sel_idx, round_idx) -> y_flipped`` runs BEFORE local
    training (label poisoning corrupts what the client learns from, not
    what it ships). Integer class labels flip to ``C-1-y``; float
    (binary/bce) targets to ``1-y``. Keys match the injector's
    ``fold_in(k, 2)`` draw vector, so :func:`fault_trace_round`
    attributes the same clients. Returns None when the spec never
    flips."""
    if spec is None or spec.labelflip <= 0:
        return None
    base = jax.random.fold_in(jax.random.PRNGKey(seed), FAULT_SALT)
    flip_p = spec.labelflip

    def flip(y_sel: jax.Array, sel_idx: jax.Array,
             round_idx: jax.Array) -> jax.Array:
        rkey = jax.random.fold_in(
            base, jnp.asarray(round_idx).astype(jnp.int32))

        def per_client(y, cid):
            k = jax.random.fold_in(rkey, cid)
            u2 = jax.random.uniform(jax.random.fold_in(k, 2), (3,))
            flagged = u2[2] < flip_p
            if jnp.issubdtype(y.dtype, jnp.integer):
                flipped = (num_classes - 1) - y
            else:
                flipped = jnp.asarray(1.0, y.dtype) - y
            return jnp.where(flagged, flipped, y)

        return jax.vmap(per_client, in_axes=(0, 0))(y_sel, sel_idx)

    return flip


def fault_trace_round(spec: FaultSpec, seed: int, round_idx: int,
                      client_ids) -> dict:
    """Host-side replay of one round's fault draws — the offline twin of
    :func:`make_fault_fn`.

    Because every draw is a pure function of (run seed, round, global
    client id), the telemetry analyzer (``obs/health.py`` /
    ``obs/analyze.py``) can reconstruct exactly which clients dropped,
    straggled, were poisoned, or went Byzantine in any recorded round —
    WITHOUT the round program recording any of it. The key derivation
    below must stay bit-for-bit in sync with ``make_fault_fn``'s
    (``tests/test_obs_analyze.py`` pins the parity).

    Returns ``{"dropped", "straggled", "poisoned", "byzantine",
    "signflipped", "colluding", "labelflipped"}``, each a ``bool`` numpy
    array aligned with ``client_ids``.
    """
    import contextlib

    import numpy as np

    # the replay runs mid-round-loop on the runner's obs path: pin it to
    # the CPU backend so a TPU run's device queue never sees these tiny
    # host-side programs
    try:
        ctx = jax.default_device(jax.local_devices(backend="cpu")[0])
    except Exception:  # no CPU backend registered
        ctx = contextlib.nullcontext()
    with ctx:
        base = jax.random.fold_in(jax.random.PRNGKey(seed), FAULT_SALT)
        rkey = jax.random.fold_in(
            base, jnp.asarray(round_idx).astype(jnp.int32))
        cids = jnp.asarray(client_ids, jnp.int32)
        keys = jax.vmap(lambda c: jax.random.fold_in(rkey, c))(cids)
        u = np.asarray(jax.vmap(
            lambda k: jax.random.uniform(k, (4,)))(keys))
        u2 = np.asarray(jax.vmap(
            lambda k: jax.random.uniform(
                jax.random.fold_in(k, 2), (3,)))(keys))
    return {
        "dropped": u[:, 0] < spec.drop,
        "straggled": u[:, 1] < spec.straggle,
        "poisoned": u[:, 2] < spec.nan,
        "byzantine": u[:, 3] < spec.scale,
        "signflipped": u2[:, 0] < spec.signflip,
        "colluding": u2[:, 1] < spec.collude,
        "labelflipped": u2[:, 2] < spec.labelflip,
    }
