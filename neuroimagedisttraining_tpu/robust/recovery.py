"""Round-granular recovery: the divergence watchdog + rollback-retry loop.

The in-jit guard (``robust.guard``) catches *non-finite* poison; this
host-side watchdog catches the faults the guard cannot see — a finite but
diverging aggregate (Byzantine scaled updates that slip under the clip
bound, a genuinely unstable round) — and recovers at round granularity:

1. after every round the driver asks the watchdog to ``judge`` the round's
   metrics (train loss finiteness, optional loss / update-norm
   thresholds);
2. an unhealthy round is NOT adopted: the driver rolls back to the
   last-good state (the pre-round state it still holds; after a process
   loss, the checkpoint lineage — which only ever contains
   watchdog-approved states, because the runner saves AFTER the verdict)
   and retries the round with a re-sampled cohort
   (``sample_client_indexes(..., retry=k)``) under bounded retries with
   linear backoff;
3. a round still unhealthy after ``max_retries`` is SKIPPED: the
   last-good state carries forward (training degrades to a no-op round
   instead of dying), and the skip is counted.

Determinism: verdicts are pure functions of deterministic round metrics,
and retry cohorts are seeded by (round, retry) — so a killed-and-resumed
run replays the identical retry/skip sequence and lands on bit-identical
parameters (tests/test_faults.py pins it).

Detection-lag caveat: ``train_loss`` is measured DURING round r's local
training, i.e. against the round r-1 aggregate — so the default
loss-only checks flag a poisoned aggregate one round LATE, after it has
already been adopted (and checkpointed) as last-good; rollback then
re-trains from the poisoned state and cannot recover. To catch a
finite-divergent (Byzantine-scaled) aggregate in the SAME round it is
produced — before adoption — set ``--watchdog_norm``: the global-update
L2 norm is a property of the candidate aggregate itself. The non-finite
case needs no threshold: the in-jit guard (robust/guard.py) quarantines
it before aggregation ever sees it.
"""
from __future__ import annotations

import logging
import math
import time
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

OK = "ok"
RETRY = "retry"
SKIP = "skip"


def _global_update_norm(new_state: Any, prev_state: Any) -> Optional[float]:
    """L2 norm of the global-model update, or None when the state has no
    ``global_params`` (decentralized algorithms)."""
    new = getattr(new_state, "global_params", None)
    old = getattr(prev_state, "global_params", None)
    if new is None or old is None:
        return None
    import jax

    sq = sum(jnp.sum(jnp.square(a - b)) for a, b in zip(
        jax.tree_util.tree_leaves(new), jax.tree_util.tree_leaves(old)))
    return float(jnp.sqrt(sq))


class RoundWatchdog:
    """Divergence watchdog with bounded rollback-retry.

    ``loss_threshold`` / ``norm_threshold`` of 0 disable the magnitude
    checks; non-finite train loss (or update norm, when the norm check is
    on) always trips. ``sleep`` is injectable for tests.
    """

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.0,
                 loss_threshold: float = 0.0, norm_threshold: float = 0.0,
                 ckpt_mgr=None,
                 template_fn: Optional[Callable[[], Any]] = None,
                 store=None,
                 sleep: Callable[[float], None] = time.sleep):
        self.max_retries = max(0, int(max_retries))
        self.backoff_s = float(backoff_s)
        self.loss_threshold = float(loss_threshold)
        self.norm_threshold = float(norm_threshold)
        self.ckpt_mgr = ckpt_mgr
        self.template_fn = template_fn
        # --client_store lineage: the checkpoint-restore rollback path
        # must reload the per-client row sidecar with the state
        self.store = store
        self._sleep = sleep
        # cumulative run counters (flow into records / stat_info)
        self.rounds_retried = 0
        self.rounds_skipped = 0
        # per-round retry state
        self._round: Optional[int] = None
        self._retries = 0

    def attempt_input(self, algo, state: Any) -> Any:
        """The state to hand a round attempt. Under the state-ownership
        protocol (``donate_state``) the attempt CONSUMES its input —
        but the watchdog's whole design rests on the pre-round state
        surviving as last-good (``judge`` reads it for the update norm,
        ``rollback`` returns it). So a donating algorithm's attempt
        gets a borrowed clone (``algo.clone_state``) and the original
        stays valid; a borrowing algorithm's attempt gets the state
        itself, exactly as before. One full state copy per round — the
        price of per-round rollback, only paid when the watchdog is
        armed (it is opt-in)."""
        if getattr(algo, "_donate", False):
            return algo.clone_state(state)
        return state

    def retries_at(self, round_idx: int) -> int:
        """Retry nonce for this attempt of ``round_idx`` (0 on the first
        attempt); resets when the driver moves to a new round."""
        if round_idx != self._round:
            self._round = round_idx
            self._retries = 0
        return self._retries

    def healthy(self, record: Dict[str, Any], new_state: Any,
                prev_state: Any) -> bool:
        """Whether the round's outcome passes every enabled check. Reads
        ``record['train_loss']`` (materializes the device scalar — the
        watchdog deliberately trades the deferred-fetch pipelining for
        per-round verdicts; it is opt-in)."""
        loss = record.get("train_loss")
        if loss is not None:
            loss = float(loss)
            record["train_loss"] = loss  # already materialized; keep it
            if not math.isfinite(loss):
                return False
            if self.loss_threshold and loss > self.loss_threshold:
                return False
        if self.norm_threshold:
            # prefer the in-jit global-update norm the numerics
            # telemetry already computed inside the round program
            # (obs/numerics.py, --obs_numerics): materializing that ONE
            # scalar replaces re-materializing every leaf of both states
            # on host. Same quantity — tests/test_obs_numerics.py pins
            # the parity. Fallback preserved when numerics is off.
            norm = record.get("num_update_norm")
            if norm is not None:
                norm = float(norm)
                record["num_update_norm"] = norm  # keep materialized
            else:
                norm = _global_update_norm(new_state, prev_state)
            if norm is not None and (
                    not math.isfinite(norm) or norm > self.norm_threshold):
                return False
        return True

    def judge(self, round_idx: int, record: Dict[str, Any], new_state: Any,
              prev_state: Any) -> str:
        """Verdict for this attempt of ``round_idx``: OK (adopt), RETRY
        (roll back, re-sample, re-run), or SKIP (retries exhausted — carry
        the last-good state)."""
        self.retries_at(round_idx)  # (re)initialize per-round state
        if self.healthy(record, new_state, prev_state):
            return OK
        if self._retries < self.max_retries:
            self._retries += 1
            self.rounds_retried += 1
            logger.warning(
                "watchdog: round %d unhealthy (train_loss=%s); rolling "
                "back and retrying with a re-sampled cohort (%d/%d)",
                round_idx, record.get("train_loss"), self._retries,
                self.max_retries)
            if self.backoff_s:
                self._sleep(self.backoff_s * self._retries)
            return RETRY
        self.rounds_skipped += 1
        logger.error(
            "watchdog: round %d still unhealthy after %d retries; "
            "carrying the last-good state (round skipped)",
            round_idx, self.max_retries)
        return SKIP

    def rollback(self, prev_state: Any) -> Any:
        """The state to retry from. The driver normally still holds the
        pre-round (last-good) state — rolling back is then free. When it
        does not (``None`` — e.g. recovery after a device loss), restore
        the newest checkpoint: the lineage only ever contains
        watchdog-approved states, so 'latest checkpoint' IS 'last good'."""
        if prev_state is not None:
            return prev_state
        if self.ckpt_mgr is None or self.template_fn is None:
            raise RuntimeError(
                "watchdog rollback: no in-memory last-good state and no "
                "checkpoint manager to restore from")
        restored = self.ckpt_mgr.restore_latest(self.template_fn(),
                                                store=self.store)
        if restored is None:
            raise RuntimeError(
                "watchdog rollback: checkpoint directory is empty")
        state, step = restored
        logger.warning("watchdog: rolled back to checkpoint step %d", step)
        return state

    def round_counters(self) -> Dict[str, float]:
        """Per-round record fields (float — the packed-metric contract)."""
        return {"rounds_retried": float(self._retries)}

    def totals(self) -> Dict[str, float]:
        return {"rounds_retried": float(self.rounds_retried),
                "rounds_skipped": float(self.rounds_skipped)}


def tree_finite(tree: Any) -> bool:
    """Host-side convenience: every leaf of ``tree`` all-finite (used by
    chaos tooling to assert a final state is clean)."""
    import jax

    return all(bool(np.all(np.isfinite(np.asarray(x))))
               for x in jax.tree_util.tree_leaves(tree))
