"""TurboAggregate — secure aggregation over additive secret shares.

Re-design of ``fedml_api/standalone/turboaggregate/`` (arXiv:2002.04156
scaffold): the reference provides finite-field MPC primitives
(``mpc_function.py:4-275``) and a trainer whose round is FedAvg with a
topology placeholder between train and aggregate (``TA_trainer.py:38-72``).
Here the protocol is actually wired end-to-end for the centralized-sum case:
each client's locally-trained model is fixed-point quantized into F_p,
split into additive secret shares (one per simulated aggregation group),
the shares are summed share-wise (no party sees a plaintext model), and the
reconstructed field sum is dequantized into the sample-weighted average.

The local-training leg is the same jitted SPMD program as FedAvg; the
secret-sharing transport is host-side numpy int64 (correctness-only, per
SURVEY.md §7.7 — TPUs have no native int64 modular arithmetic path worth
building for this).
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core.state import broadcast_tree, zeros_like_tree
from ..core.trainer import make_client_update
from ..models import init_params
from ..ops import mpc
from .base import FedAlgorithm, sample_client_indexes


@struct.dataclass
class TurboAggregateState:
    global_params: Any
    rng: jax.Array


class TurboAggregate(FedAlgorithm):
    name = "turboaggregate"

    def __init__(self, *args, n_groups: int = 3, quant_scale: int = 2 ** 16,
                 prime: int = mpc.DEFAULT_PRIME, **kwargs):
        self.n_groups = n_groups
        self.quant_scale = quant_scale
        self.prime = prime
        super().__init__(*args, **kwargs)

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=False, mask_params_post_step=False,
            remat=self.remat_local, full_batches=self._full_batches(),
            augment_fn=self.augment_fn,
        )

        def local_fn(global_params, sel_idx, round_idx, round_key,
                     x_train, y_train, n_train):
            n_sel = jnp.take(n_train, sel_idx)
            x_sel = jnp.take(x_train, sel_idx, axis=0)
            y_sel = jnp.take(y_train, sel_idx, axis=0)
            s = sel_idx.shape[0]
            params0 = broadcast_tree(global_params, s)
            mom0 = zeros_like_tree(params0)
            keys = jax.random.split(round_key, s)
            params_out, _, losses = self._vmap_clients(
                self.client_update, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0)
            )(params0, mom0, params0, keys, x_sel, y_sel, n_sel, round_idx,
              params0)
            return params_out, n_sel, jnp.mean(losses)

        self._local_jit = jax.jit(local_fn)
        self._eval_global = self._make_global_eval()

    def _secure_weighted_sum(self, stacked_locals: Any,
                             weights: np.ndarray) -> Any:
        """Sum pre-weighted local models through additive secret shares."""
        p, scale = self.prime, self.quant_scale
        leaves, treedef = jax.tree_util.tree_flatten(stacked_locals)
        out = []
        rng = np.random.RandomState(0)
        for leaf in leaves:
            arr = np.asarray(leaf, np.float64)
            weighted = arr * weights.reshape((-1,) + (1,) * (arr.ndim - 1))
            # each client secret-shares its quantized weighted model
            share_sum = np.zeros((self.n_groups,) + arr.shape[1:], np.int64)
            for c in range(arr.shape[0]):
                q = mpc.quantize(weighted[c], scale, p)
                shares = mpc.additive_shares(q, self.n_groups, p, rng)
                share_sum = np.mod(share_sum + shares, p)
            # groups reveal only their share totals; the sum reconstructs
            total = np.mod(share_sum.sum(axis=0), p)
            out.append(jnp.asarray(
                mpc.dequantize(total, scale, p).astype(np.float32)
            ))
        return jax.tree_util.tree_unflatten(treedef, out)

    def init_state(self, rng: jax.Array) -> TurboAggregateState:
        p_rng, s_rng = jax.random.split(rng)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        return TurboAggregateState(global_params=params, rng=s_rng)

    def run_round(self, state: TurboAggregateState, round_idx: int):
        sel = sample_client_indexes(
            round_idx, self.num_clients, self.clients_per_round
        )
        rng, round_key = jax.random.split(state.rng)
        params_out, n_sel, loss = self._local_jit(
            state.global_params, jnp.asarray(sel),
            jnp.asarray(round_idx, jnp.float32), round_key,
            self.data.x_train, self.data.y_train, self.data.n_train,
        )
        w = np.asarray(n_sel, np.float64)
        w = w / w.sum()
        new_global = self._secure_weighted_sum(params_out, w)
        return (
            TurboAggregateState(global_params=new_global, rng=rng),
            {"train_loss": loss},
        )

    def evaluate(self, state: TurboAggregateState) -> Dict[str, Any]:
        ev = self._eval_global(
            state.global_params, self.data.x_test, self.data.y_test,
            self.data.n_test,
        )
        return {"global_acc": ev["acc"], "global_loss": ev["loss"],
                "acc_per_client": ev["acc_per_client"]}
