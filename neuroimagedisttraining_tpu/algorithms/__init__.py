from .base import FedAlgorithm, sample_client_indexes
from .fedavg import FedAvg
from .salientgrads import SalientGrads
from .dispfl import DisPFL
from .subavg import SubAvg
from .dpsgd import DPSGD
from .ditto import Ditto
from .fedfomo import FedFomo
from .local_only import LocalOnly
from .turboaggregate import TurboAggregate

ALGORITHMS = {
    a.name: a
    for a in (FedAvg, SalientGrads, DisPFL, SubAvg, DPSGD, Ditto, FedFomo,
              LocalOnly, TurboAggregate)
}

__all__ = [
    "ALGORITHMS",
    "DPSGD",
    "DisPFL",
    "Ditto",
    "FedAlgorithm",
    "FedAvg",
    "FedFomo",
    "LocalOnly",
    "SalientGrads",
    "SubAvg",
    "TurboAggregate",
    "sample_client_indexes",
]
