from .base import FedAlgorithm, sample_client_indexes
from .fedavg import FedAvg
from .salientgrads import SalientGrads

__all__ = ["FedAlgorithm", "FedAvg", "SalientGrads", "sample_client_indexes"]
