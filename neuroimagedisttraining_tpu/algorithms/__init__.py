from .base import FedAlgorithm, sample_client_indexes
from .fedavg import FedAvg

__all__ = ["FedAlgorithm", "FedAvg", "sample_client_indexes"]
