"""FedAvg — canonical centralized federated averaging.

Re-design of ``fedml_api/standalone/fedavg/fedavg_api.py:40-117``: sample
frac*N clients, local SGD on each, sample-count-weighted average. The
reference runs clients sequentially and averages CPU state_dicts
(``fedavg_api.py:102-117``); here the entire round — broadcast, vmapped local
training, weighted aggregation — is a single jitted program, and with the
client axis sharded over a mesh the weighted sum lowers to an ICI all-reduce.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..core.trainer import make_client_update
from ..models import init_params
from .base import FedAlgorithm, sample_client_indexes


@struct.dataclass
class FedAvgState:
    global_params: Any
    rng: jax.Array


class FedAvg(FedAlgorithm):
    name = "fedavg"

    def __init__(self, *args, defense=None, **kwargs):
        # optional robust.RobustAggregator (fedml_core/robustness wiring)
        self.defense = defense
        super().__init__(*args, **kwargs)

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=False, mask_params_post_step=False,
        )

        def round_fn(state: FedAvgState, sel_idx, round_idx,
                     x_train, y_train, n_train):
            rng, round_key = jax.random.split(state.rng)
            new_global, mean_loss = self._train_selected_weighted(
                self.client_update, state.global_params,
                state.global_params,  # dense path: mask unused, DCE'd
                sel_idx, round_idx, round_key, x_train, y_train, n_train,
                defense=self.defense,
            )
            return FedAvgState(global_params=new_global, rng=rng), mean_loss

        self._round_jit = jax.jit(round_fn)
        self._eval_global = self._make_global_eval()

    def init_state(self, rng: jax.Array) -> FedAvgState:
        p_rng, s_rng = jax.random.split(rng)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        return FedAvgState(global_params=params, rng=s_rng)

    def run_round(self, state: FedAvgState, round_idx: int):
        sel = sample_client_indexes(
            round_idx, self.num_clients, self.clients_per_round
        )
        state, loss = self._round_jit(
            state, jnp.asarray(sel), jnp.asarray(round_idx, jnp.float32),
            self.data.x_train, self.data.y_train, self.data.n_train,
        )
        return state, {"train_loss": loss}

    def evaluate(self, state: FedAvgState) -> Dict[str, Any]:
        ev = self._eval_global(
            state.global_params, self.data.x_test, self.data.y_test,
            self.data.n_test,
        )
        return {"global_acc": ev["acc"], "global_loss": ev["loss"],
                "acc_per_client": ev["acc_per_client"]}
