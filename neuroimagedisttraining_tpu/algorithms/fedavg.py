"""FedAvg — canonical centralized federated averaging.

Re-design of ``fedml_api/standalone/fedavg/fedavg_api.py:40-117``: sample
frac*N clients, local SGD on each, sample-count-weighted average. The
reference runs clients sequentially and averages CPU state_dicts
(``fedavg_api.py:102-117``); here the entire round — broadcast, vmapped local
training, weighted aggregation — is a single jitted program, and with the
client axis sharded over a mesh the weighted sum lowers to an ICI all-reduce.

Like the reference, each client's last locally-trained weights are kept as
its *personal* model (``w_per_mdls``, ``fedavg_api.py:42-45,66-67``) and both
global and personal models are evaluated per round
(``_test_on_all_clients(w_global, w_per_mdls, round_idx)``, ``:119-173``).
After the last round every client fine-tunes once from the final global
model with ``round_idx = -1`` and the pair is evaluated one final time
(``fedavg_api.py:79-88``).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core.state import (
    broadcast_tree,
    zeros_like_tree,
)
from ..core.trainer import make_client_update
from ..models import init_params
from ..obs import trace as obs_trace
from .base import FedAlgorithm


@struct.dataclass
class FedAvgState:
    global_params: Any
    # [C, ...] — w_per_mdls (fedavg_api.py:42-45), or None when personal
    # tracking is off. NOTE the HBM scaling: the stack is one full model per
    # client ON DEVICE (the reference keeps w_per_mdls in host RAM), so very
    # large --client_num_in_total simulations should pass --track_personal 0
    # unless they need per-client personal models/eval.
    personal_params: Any
    rng: jax.Array
    # [C, ...] error-feedback residual of agg_impl='topk' (the unsent
    # remainder of each client's compensated delta — Deep Gradient
    # Compression semantics), or None for every other impl. Real state:
    # checkpointed with the same lineage rules as personal_params (a
    # topk lineage is identity-split from the other impls, whose states
    # have no residual — the r5 track_personal migration pattern).
    agg_residual: Any = None
    # per-client personal-eval cache {correct[C], loss_sum[C], total[C]}
    # (--eval_cache), or None when off. Real state: the round body
    # refreshes only the trained clients' rows (O(S) forwards), evals
    # re-reduce it with zero forwards, it rides the fused scan carry,
    # and it checkpoints — an evcache lineage splits identity (the same
    # r5/topk state-structure rule).
    eval_cache: Any = None


class FedAvg(FedAlgorithm):
    name = "fedavg"
    supports_fused = True
    guard_metrics_supported = True
    numerics_supported = True
    topk_supported = True
    donate_supported = True
    store_supported = True

    def __init__(self, *args, defense=None, track_personal: bool = True,
                 eval_cache: bool = False, **kwargs):
        # optional robust.RobustAggregator (fedml_core/robustness wiring)
        self.defense = defense
        # track_personal=False drops the on-device w_per_mdls stack (and the
        # final fine-tune that exists to produce it) — O(C x model) HBM
        self.track_personal = track_personal
        # eval_cache: the in-state incremental personal-eval cache
        # (base.py "--eval_cache" section); validated in the base ctor
        self.eval_cache = bool(eval_cache)
        super().__init__(*args, **kwargs)

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=False, mask_params_post_step=False,
            remat=self.remat_local, full_batches=self._full_batches(),
            augment_fn=self.augment_fn,
        )

        def round_fn(state: FedAvgState, sel_idx, round_idx,
                     x_train, y_train, n_train, *test_args):
            rng, round_key = jax.random.split(state.rng)
            new_global, locals_, mean_loss, fstats, new_residual = \
                self._train_selected_weighted(
                    self.client_update, state.global_params,
                    state.global_params,  # dense path: mask unused, DCE'd
                    sel_idx, round_idx, round_key, x_train, y_train,
                    n_train, defense=self.defense,
                    residual=state.agg_residual,
                )
            new_personal = self._guarded_personal_update(
                state.personal_params, locals_, sel_idx, fstats)
            # --eval_cache: refresh ONLY the trained clients' cache rows
            # from their post-guard personal rows (quarantined rows
            # re-evaluate their kept previous models — poison-free)
            new_cache = state.eval_cache
            if self.eval_cache:
                new_cache = self._update_eval_cache(
                    state.eval_cache, new_personal, sel_idx, *test_args)
            # in-jit numerics telemetry (--obs_numerics): pure readout
            # on the round's live arrays, () when off
            nums = self._numerics_outputs(
                state.global_params, new_global, locals_)
            return self._round_outputs(
                FedAvgState(global_params=new_global,
                            personal_params=new_personal, rng=rng,
                            agg_residual=new_residual,
                            eval_cache=new_cache),
                mean_loss, fstats, nums)

        self._round_fn = round_fn
        self._round_jit = self._jit_entry(round_fn)

        def finetune_fn(state: FedAvgState, x_train, y_train, n_train):
            """Final fine-tune: every client trains once from the final
            global model at round_idx=-1 (fedavg_api.py:79-88)."""
            rng, key = jax.random.split(state.rng)
            c = self.num_clients
            params0 = broadcast_tree(state.global_params, c)
            mom0 = zeros_like_tree(params0)
            keys = jax.random.split(key, c)
            params_out, _, _ = self._vmap_clients(
                self.client_update, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0)
            )(params0, mom0, params0, keys, x_train, y_train, n_train,
              jnp.asarray(-1.0, jnp.float32), params0)
            # eval_cache passes through for donation aliasing; finalize
            # drops it on the host (the fine-tune retrained EVERY row,
            # so the cache is stale wholesale)
            return FedAvgState(global_params=state.global_params,
                               personal_params=params_out, rng=rng,
                               agg_residual=state.agg_residual,
                               eval_cache=state.eval_cache)

        self._finetune_jit = self._jit_entry(finetune_fn)
        self._eval_global = self._make_global_eval()
        self._eval_personal = self._make_personal_eval()

    def init_state(self, rng: jax.Array) -> FedAvgState:
        p_rng, s_rng = jax.random.split(rng)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        if self._store is not None:
            # store mode: the per-client rows live in the client store
            # (lazy defaults — init params / zero residual; nothing
            # materializes until a row trains) and state holds None
            # between rounds. The eval cache is seeded from a TRANSIENT
            # resident broadcast — identical values to the resident
            # seed — and freed right after.
            self._store_register_fields(params)
            ev_cache = None
            if self.eval_cache:
                ev_cache = self._seed_eval_cache(
                    broadcast_tree(params, self.num_clients))
            return FedAvgState(
                global_params=params, personal_params=None, rng=s_rng,
                agg_residual=None, eval_cache=ev_cache)
        personal = (broadcast_tree(params, self.num_clients)
                    if self.track_personal else None)
        return FedAvgState(
            global_params=params,
            personal_params=personal,
            rng=s_rng,
            # topk: zero residual per client (same [C, model] HBM
            # footprint caveat as personal_params)
            agg_residual=(zeros_like_tree(
                broadcast_tree(params, self.num_clients))
                if self.agg_impl == "topk" else None),
            # --eval_cache: seed with one full personal eval (one-time
            # O(C); every later round refreshes O(S) rows in-graph)
            eval_cache=self._seed_eval_cache(personal),
        )

    def run_round(self, state: FedAvgState, round_idx: int):
        if self._store is not None:
            # streamed cohort residency: gather [S] rows host->device,
            # run the same round body at slab width, stage rows back
            return self._run_round_store(state, round_idx)
        sel = self._selected_client_indexes(round_idx)
        d = self.data
        # read BEFORE dispatch: under donate_state the call consumes
        # `state` (the host cache only compares object identity, but
        # the ownership lint holds driver paths to read-before-donate)
        old_pers = state.personal_params
        extra = ((d.x_test, d.y_test, d.n_test)
                 if self.eval_cache else ())
        # dispatch-time span (async): the round's device phases are
        # labeled by named_scope inside the jitted body instead
        with obs_trace.span("dispatch_round"):
            out = self._round_jit(
                state, jnp.asarray(sel),
                jnp.asarray(round_idx, jnp.float32),
                d.x_train, d.y_train, d.n_train, *extra,
            )
        new_state = out[0]
        # only the trained clients' personal models changed — feed the
        # incremental personal-eval cache (base._personal_eval_cached)
        self._note_personal_update(
            old_pers, new_state.personal_params, sel)
        return new_state, dict(zip(self._round_metric_names, out[1:]))

    def finalize(self, state: FedAvgState):
        if not self.track_personal:
            # the fine-tune pass exists to produce the personal models
            # (fedavg_api.py:79-88); nothing to produce when untracked
            return state, None
        with obs_trace.span("finetune"):
            state = self._finetune_jit(
                state, self.data.x_train, self.data.y_train,
                self.data.n_train)
        if self._store is not None:
            # the fine-tune retrained EVERY client from the final
            # global — a transient O(C) device stack (population-scale
            # runs skip finalize; this serves the reference protocol at
            # moderate C). Adopt it into the store wholesale, drop it
            # from state; the final eval below re-seeds from the store.
            self._store.stage("personal_params",
                              np.arange(self.num_clients),
                              state.personal_params)
            self._store.commit()
            self._store_eval_cache = None
            self._store_eval_dirty = []
            state = state.replace(personal_params=None)
        if self.eval_cache:
            # the fine-tune retrained EVERY personal row: the cache is
            # stale wholesale — drop it so evaluate falls back to the
            # full personal eval (None marks "not live on this state")
            state = state.replace(eval_cache=None)
        ev = self.evaluate(state)
        record = {"round": -1, "finetune": True,
                  **{k: v for k, v in ev.items()
                     if not k.startswith("acc_per")}}
        return state, record

    def _eval_impl(self, state, x_test, y_test, n_test,
                   personal_fn) -> Dict[str, Any]:
        # routed by the base wrappers: eval_metrics passes the traceable
        # full personal eval, evaluate the incremental cached one
        ev = self._eval_global(state.global_params, x_test, y_test, n_test)
        out = {"global_acc": ev["acc"], "global_loss": ev["loss"],
               "acc_per_client": ev["acc_per_client"]}
        if state.personal_params is not None or \
                self._store_has_personal():
            evp = personal_fn(
                state.personal_params, x_test, y_test, n_test)
            out.update(personal_acc=evp["acc"], personal_loss=evp["loss"])
        return out
