"""DisPFL — decentralized sparse personalized FL (CVPR'22).

Re-design of ``fedml_api/standalone/DisPFL/dispfl_api.py:46-184``:
  * per-client random masks at ERK-allocated layer sparsities
    (``my_model_trainer.py:28-38,40-114``)
  * per round: client dropout coin-flips (``--active``, :96), neighbor
    choice random/ring/full (``_benefit_choose`` :196-220),
    count-mask-weighted aggregation of neighbors' sparse personal models
    re-masked by the local mask (``_aggregate_func`` :222-240),
    masked-gradient local SGD (trainer :147-172), then mask evolution:
    screen one dense gradient batch (:128-144), cosine-annealed magnitude
    fire + gradient-magnitude regrow (``client.py:71-99``)
  * mask hamming-distance tracking (``slim_util.py:14-19``).

TPU-native: masks and personal models are [C, ...] stacked pytrees; the
count-mask aggregation is two adjacency contractions (weights and mask
counts) + a safe reciprocal — all inside one jitted round program. Inactive
clients keep their previous state via a select, preserving the reference's
dropout-simulation semantics without host branching.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core.losses import make_loss_fn
from ..core.state import broadcast_tree, mix_over_clients
from ..core.trainer import make_client_update
from ..models import init_params
from ..ops.sparsity import (
    cosine_annealing,
    erk_sparsities,
    uniform_sparsities,
    fire_mask,
    kernel_flags,
    live_counts,
    mask_density,
    param_shapes,
    random_masks_from_sparsities,
    regrow_mask,
)
from ..parallel.topology import neighbor_adjacency
from .base import FedAlgorithm


@struct.dataclass
class DisPFLState:
    personal_params: Any  # [C, ...] sparse personal models
    masks: Any            # [C, ...] personal masks
    rng: jax.Array


class DisPFL(FedAlgorithm):
    name = "dispfl"

    def cost_trained_clients_per_round(self) -> int:
        # inactive clients skip only the aggregation; all train
        # (dispfl_api.py:96,105-142)
        return self.num_clients

    def __init__(self, *args, dense_ratio: float = 0.5,
                 anneal_factor: float = 0.5, neighbor_mode: str = "random",
                 active: float = 1.0, static_masks: bool = False,
                 total_rounds: int = 100, erk_power_scale: float = 1.0,
                 sparsity_distribution: str = "erk",
                 different_initial: bool = False, diff_spa: bool = False,
                 dis_gradient_check: bool = False,
                 record_local_tests: bool = True,
                 **kwargs):
        """Mask-init variants (``dispfl_api.py:48-71``):
        ``sparsity_distribution``: "erk" (default) or "uniform"
        (``--uniform``). ``different_initial``: per-client independent
        initial masks (reference default is one shared initial mask).
        ``diff_spa``: clients cycle dense ratios [0.2,0.4,0.6,0.8,1.0]
        (implies different_initial); densities persist through fire/regrow
        because evolution preserves per-client live counts."""
        self.dense_ratio = dense_ratio
        self.anneal_factor = anneal_factor
        self.neighbor_mode = neighbor_mode
        self.active = active
        self.static_masks = static_masks
        self.masks_evolve = not static_masks  # fire/regrow changes density
        self.total_rounds = total_rounds
        self.erk_power_scale = erk_power_scale
        if sparsity_distribution not in ("erk", "uniform"):
            raise ValueError(
                f"sparsity_distribution {sparsity_distribution!r} not in "
                "('erk', 'uniform')")
        self.sparsity_distribution = sparsity_distribution
        self.different_initial = different_initial or diff_spa
        self.diff_spa = diff_spa
        # --dis_gradient_check: regrow uniformly at random among dead
        # weights instead of by |grad| (and skip the screening batch) —
        # DisPFL/client.py:54,91-98
        self.dis_gradient_check = dis_gradient_check
        # record_local_tests: the reference tests every client locally
        # around local training EVERY round (dispfl_api.py:150-155) — kept
        # as the default; disable to drop the two per-round full-cohort
        # test passes when eval cost matters (the runner turns it off at
        # --frequency_of_the_test 0)
        self.record_local_tests = record_local_tests
        super().__init__(*args, **kwargs)

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=True, mask_params_post_step=True,
            remat=self.remat_local, full_batches=self._full_batches(),
            augment_fn=self.augment_fn,
        )
        loss_fn = make_loss_fn(self.loss_type)

        def screen_gradients(params, x, y, n_valid, rng):
            """One dense-batch gradient for regrow scoring
            (DisPFL/my_model_trainer.py:128-144); the reference feeds it
            train-loader batches, so augmentation applies like training."""
            k_idx, k_drop = jax.random.split(rng)
            idx = jax.random.randint(
                k_idx, (self.hp.batch_size,), 0, jnp.maximum(n_valid, 1)
            )
            xb = jnp.take(x, idx, axis=0)
            yb = jnp.take(y, idx, axis=0)
            if self.augment_fn is not None:
                k_aug, k_drop = jax.random.split(k_drop)
                xb = self.augment_fn(k_aug, xb)
            return jax.grad(
                lambda p: loss_fn(self.apply_fn(p, xb, train=True,
                                                rng=k_drop), yb)
            )(params)

        eval_client = self.eval_client

        def local_test_means(params_stack, x_test, y_test, n_test):
            """Per-client local test, reported as the reference's means:
            acc = mean_c(correct_c/total_c), loss = mean_c(loss_c/total_c)
            (dispfl_api.py:242-301). Chunked like the training vmap so the
            two default-on eval passes respect the same --client_chunk HBM
            bound as training (ADVICE r3)."""
            correct, loss_sum, total = self._vmap_clients(
                eval_client, in_axes=(0, 0, 0, 0))(
                params_stack, x_test, y_test, n_test)
            totals = jnp.maximum(total, 1).astype(jnp.float32)
            return (jnp.mean(correct.astype(jnp.float32) / totals),
                    jnp.mean(loss_sum / totals))

        def round_fn(state: DisPFLState, adjacency, active_vec, round_idx,
                     x_train, y_train, n_train, x_test, y_test, n_test):
            rng, k_train, k_screen = jax.random.split(state.rng, 3)
            params, masks = state.personal_params, state.masks

            # --- count-mask-weighted neighbor aggregation (:222-240) ------
            counts = mix_over_clients(adjacency, masks)
            inv = jax.tree_util.tree_map(
                lambda c: jnp.where(c != 0, 1.0 / jnp.maximum(c, 1e-9), 0.0),
                counts,
            )
            sums = mix_over_clients(adjacency, params)
            consensus = jax.tree_util.tree_map(jnp.multiply, sums, inv)
            w_agg = jax.tree_util.tree_map(jnp.multiply, consensus, masks)

            # inactive clients skip ONLY the aggregation — they still train
            # from their own previous personal model and evolve their masks
            # (dispfl_api.py:105-142: w_local falls back to the lstrd copy,
            # client.train runs unconditionally)
            def pick_active(agg, own):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(
                        active_vec.reshape((-1,) + (1,) * (a.ndim - 1)) > 0,
                        a, b,
                    ),
                    agg, own,
                )

            w_local = pick_active(w_agg, params)

            # per-round local test of the aggregated model BEFORE local
            # training ("new mask" series, dispfl_api.py:150-151,271-301)
            nanv = jnp.float32(jnp.nan)
            pre_acc = pre_loss = nanv
            if self.record_local_tests:
                pre_acc, pre_loss = local_test_means(
                    w_local, x_test, y_test, n_test)

            # --- masked local SGD ----------------------------------------
            trained, _, losses = self._train_stacked(
                self.client_update, w_local, masks, round_idx, k_train,
                x_train, y_train, n_train,
            )

            # per-round local test AFTER local training, before mask
            # evolution — the tst_results each client.train returns
            # ("old mask" series, dispfl_api.py:154-155,242-269)
            post_acc = post_loss = nanv
            if self.record_local_tests:
                post_acc, post_loss = local_test_means(
                    trained, x_test, y_test, n_test)

            # --- mask evolution (fire/regrow, client.py:55-99) -----------
            if self.static_masks:
                new_masks = masks
            else:
                c = x_train.shape[0]
                keys = jax.random.split(k_screen, c)
                if self.dis_gradient_check:
                    # random regrow: uniform scores stand in for |grad| —
                    # top-n random dead == multinomial without replacement
                    # (DisPFL/client.py:96-98); no screening batch runs
                    def rand_tree(p, key):
                        leaves, treedef = jax.tree_util.tree_flatten(p)
                        ks = jax.random.split(key, len(leaves))
                        return jax.tree_util.tree_unflatten(
                            treedef,
                            [jax.random.uniform(k2, l.shape)
                             for l, k2 in zip(leaves, ks)])

                    grads = jax.vmap(rand_tree)(trained, keys)
                else:
                    grads = self._vmap_clients(
                        screen_gradients, in_axes=(0, 0, 0, 0, 0)
                    )(trained, x_train, y_train, n_train, keys)
                rate = cosine_annealing(
                    self.anneal_factor, round_idx, self.total_rounds
                )
                before = jax.vmap(live_counts)(masks)  # per-client counts
                fired = jax.vmap(partial(fire_mask, drop_rate=rate))(
                    masks, trained
                )
                n_regrow = jax.tree_util.tree_map(
                    lambda b, f: b - f, before, jax.vmap(live_counts)(fired)
                )
                new_masks = jax.vmap(regrow_mask)(fired, grads, n_regrow)
                trained = jax.tree_util.tree_map(
                    jnp.multiply, trained, new_masks
                )

            # mask-change tracking (hamming fraction, slim_util.py:14-19)
            ham = _hamming_fraction(masks, new_masks)
            out = (
                DisPFLState(personal_params=trained, masks=new_masks,
                            rng=rng),
                jnp.mean(losses), ham,
            )
            if self.record_local_tests:
                out += (pre_acc, pre_loss, post_acc, post_loss)
            return out

        self._round_jit = jax.jit(round_fn)
        self._eval_personal = self._make_personal_eval()

    def _client_sparsities(self, shapes, client_idx: int):
        """Per-layer sparsities for one client's initial mask."""
        ratio = self.dense_ratio
        if self.diff_spa:
            # dispfl_api.py:63-71: cycle dense ratios over clients
            ratio = (0.2, 0.4, 0.6, 0.8, 1.0)[client_idx % 5]
        if self.sparsity_distribution == "uniform":
            return uniform_sparsities(shapes, ratio)
        return erk_sparsities(shapes, ratio, self.erk_power_scale)

    def init_state(self, rng: jax.Array) -> DisPFLState:
        p_rng, m_rng, s_rng = jax.random.split(rng, 3)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        shapes = param_shapes(params)
        if self.different_initial:
            mask_keys = jax.random.split(m_rng, self.num_clients)
            per_client = [
                random_masks_from_sparsities(
                    params,
                    (lambda sp: lambda n, s: sp[n])(
                        self._client_sparsities(shapes, i)),
                    mask_keys[i],
                )
                for i in range(self.num_clients)
            ]
            masks = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *per_client)
        else:
            # reference default: ONE shared initial mask — compute once
            # and broadcast (not num_clients identical recomputations)
            sp = self._client_sparsities(shapes, 0)
            one = random_masks_from_sparsities(
                params, lambda n, s: sp[n], m_rng)
            masks = broadcast_tree(one, self.num_clients)
        stacked = broadcast_tree(params, self.num_clients)
        personal = jax.tree_util.tree_map(jnp.multiply, stacked, masks)
        return DisPFLState(personal_params=personal, masks=masks, rng=s_rng)

    # every per-round host input is a pure function of round_idx (the
    # reference's np.random.seed(round_idx) dropout coin-flips,
    # dispfl_api.py:96, and the seeded _benefit_choose adjacency,
    # :196-220) — data-INDEPENDENT host RNG, so a K-round block can
    # precompute the (adjacency, active) stacks and fuse like DPSGD.
    # Mask evolution (fire/regrow) is data-dependent but lives entirely
    # in-graph, so it scans fine.
    supports_fused = True

    @property
    def _round_metric_names(self):
        names = ("train_loss", "mask_change")
        if self.record_local_tests:
            # reference stat_info key names (dispfl_api.py:269,301):
            # "old_mask" = after local training, "new_mask" = the
            # aggregated model under the refreshed shared mask, before
            # local training
            names += ("new_mask_test_acc", "new_mask_test_loss",
                      "old_mask_test_acc", "old_mask_test_loss")
        return names

    def _fused_host_inputs(self, round_idx: int):
        # exact unfused draw order: seed, coin-flip the active vector,
        # then the adjacency (which reseeds its own RandomState)
        np.random.seed(round_idx)
        active_vec = np.random.choice(
            [0, 1], size=self.num_clients,
            p=[1.0 - self.active, self.active],
        )
        adj = neighbor_adjacency(
            round_idx, self.num_clients, self.clients_per_round,
            mode=self.neighbor_mode, active=active_vec,
        )
        return (adj, active_vec)

    def _fused_data_args(self):
        d = self.data
        # the round program itself consumes the test arrays (the two
        # per-round local-test passes); the fused driver appends them
        # again for the eval branch — same buffers, no copies
        return (d.x_train, d.y_train, d.n_train,
                d.x_test, d.y_test, d.n_test)

    def run_round(self, state: DisPFLState, round_idx: int):
        adj, active_vec = self._fused_host_inputs(round_idx)
        out = self._round_jit(
            state, jnp.asarray(adj), jnp.asarray(active_vec),
            jnp.asarray(round_idx, jnp.float32),
            self.data.x_train, self.data.y_train, self.data.n_train,
            self.data.x_test, self.data.y_test, self.data.n_test,
        )
        return out[0], dict(zip(self._round_metric_names, out[1:]))

    def eval_metrics(self, state: DisPFLState, x_test, y_test,
                     n_test) -> Dict[str, Any]:
        ev = self._eval_personal(
            state.personal_params, x_test, y_test, n_test)
        dens = jax.vmap(mask_density)(state.masks)
        return {
            "personal_acc": ev["acc"], "personal_loss": ev["loss"],
            "mean_mask_density": jnp.mean(dens),
            "acc_per_client": ev["acc_per_client"],
        }

    def mask_distance_matrix(self, state: DisPFLState) -> np.ndarray:
        """Pairwise hamming-fraction matrix over client masks — the end-of-
        run diagnostic the reference stores (dispfl_api.py:170-175)."""
        flat = jnp.concatenate([
            m.reshape(m.shape[0], -1)
            for m, k in zip(jax.tree_util.tree_leaves(state.masks),
                            jax.tree_util.tree_leaves(
                                kernel_flags(state.masks)))
            if k
        ], axis=1)
        a = (flat != 0).astype(jnp.float32)
        return np.asarray(
            jnp.mean(jnp.abs(a[:, None, :] - a[None, :, :]), axis=-1)
        )


def _hamming_fraction(masks_a: Any, masks_b: Any) -> jax.Array:
    # only kernel leaves evolve (fire/regrow gate on kernel_flags); counting
    # bias/scale leaves in the denominator would dilute the metric
    flags = jax.tree_util.tree_leaves(kernel_flags(masks_a))
    num = sum(
        jnp.sum((a != 0) != (b != 0))
        for a, b, k in zip(jax.tree_util.tree_leaves(masks_a),
                           jax.tree_util.tree_leaves(masks_b), flags)
        if k
    )
    tot = sum(a.size
              for a, k in zip(jax.tree_util.tree_leaves(masks_a), flags)
              if k)
    return num / tot
