"""Ditto — personalized FL with a proximal pull toward the global model.

Re-design of ``fedml_api/standalone/ditto/ditto_api.py:40-78``: each sampled
client (a) trains a copy of the global model normally (contributing to the
sample-weighted FedAvg aggregate) and (b) trains its *personal* model with
the manual post-step proximal update ``w -= lr*lambda*(w - w_global)``
(``ditto/my_model_trainer.py:63-64``), pulling it toward the pre-round
global. The reference uses ``--epochs`` for the global leg and
``--local_epochs`` for the personal leg; both default to the shared
HyperParams here (override via ``personal_hp``).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

from ..core.state import (
    broadcast_tree,
    tree_index,
    tree_scatter_update,
)
from ..core.trainer import make_client_update
from ..core.state import HyperParams
from ..models import init_params
from .base import FedAlgorithm


@struct.dataclass
class DittoState:
    global_params: Any
    personal_params: Any  # [C, ...]
    rng: jax.Array


class Ditto(FedAlgorithm):
    name = "ditto"
    supports_fused = True
    donate_supported = True
    store_supported = True
    _round_metric_names = ("train_loss", "personal_train_loss")

    def cost_trained_clients_per_round(self) -> int:
        # each selected client trains a global AND a personal leg
        return 2 * self.clients_per_round

    def __init__(self, *args, lamda: float = 0.5,
                 personal_hp: Optional[HyperParams] = None, **kwargs):
        self.lamda = lamda
        self._personal_hp = personal_hp
        super().__init__(*args, **kwargs)

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=False, mask_params_post_step=False,
            remat=self.remat_local, full_batches=self._full_batches(),
            augment_fn=self.augment_fn,
        )
        self.personal_update = make_client_update(
            self.apply_fn, self.loss_type, self._personal_hp or self.hp,
            mask_grads=False, mask_params_post_step=False,
            prox_lambda=self.lamda,
            remat=self.remat_local,
            full_batches=self._full_batches(self._personal_hp or self.hp),
            augment_fn=self.augment_fn,
        )

        def round_fn(state: DittoState, sel_idx, round_idx,
                     x_train, y_train, n_train):
            rng, k_global, k_personal = jax.random.split(state.rng, 3)
            # (a) global leg: standard FedAvg round (the guard, when on,
            # protects this aggregate too; Ditto does not thread the
            # quarantine counters into its metrics — guard_metrics_supported)
            new_global, _, mean_loss, _fstats, _res = \
                self._train_selected_weighted(
                    self.client_update, state.global_params,
                    state.global_params, sel_idx, round_idx, k_global,
                    x_train, y_train, n_train,
                )
            # (b) personal leg: prox-pulled toward the PRE-round global
            s = sel_idx.shape[0]
            p_sel = tree_index(state.personal_params, sel_idx)
            prox_target = broadcast_tree(state.global_params, s)
            trained_p, _, p_losses = self._train_stacked(
                self.personal_update, p_sel, p_sel, round_idx, k_personal,
                jnp.take(x_train, sel_idx, axis=0),
                jnp.take(y_train, sel_idx, axis=0),
                jnp.take(n_train, sel_idx),
                prox_target=prox_target,
            )
            new_personal = tree_scatter_update(
                state.personal_params, sel_idx, trained_p
            )
            return (
                DittoState(global_params=new_global,
                           personal_params=new_personal, rng=rng),
                mean_loss,
                jnp.mean(p_losses),
            )

        self._round_fn = round_fn
        self._round_jit = self._jit_entry(round_fn)
        self._eval_global = self._make_global_eval()
        self._eval_personal = self._make_personal_eval()

    def init_state(self, rng: jax.Array) -> DittoState:
        p_rng, s_rng = jax.random.split(rng)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        if self._store is not None:
            # store mode: the personal stack lives in the client store
            # (lazy init-params default rows); state holds None between
            # rounds. See FedAvg.init_state.
            self._store_register_fields(params)
            return DittoState(global_params=params,
                              personal_params=None, rng=s_rng)
        return DittoState(
            global_params=params,
            personal_params=broadcast_tree(params, self.num_clients),
            rng=s_rng,
        )

    def run_round(self, state: DittoState, round_idx: int):
        if self._store is not None:
            # streamed cohort residency: same round body at slab width
            return self._run_round_store(state, round_idx)
        sel = self._selected_client_indexes(round_idx)
        # read BEFORE dispatch: under donate_state the call consumes
        # `state` (the ownership lint holds driver paths to this order)
        old_pers = state.personal_params
        new_state, g_loss, p_loss = self._round_jit(
            state, jnp.asarray(sel), jnp.asarray(round_idx, jnp.float32),
            self.data.x_train, self.data.y_train, self.data.n_train,
        )
        # only the selected clients' personal legs trained — feed the
        # incremental personal-eval cache (base._personal_eval_cached)
        self._note_personal_update(
            old_pers, new_state.personal_params, sel)
        return new_state, {"train_loss": g_loss,
                           "personal_train_loss": p_loss}

    def _eval_impl(self, state, x_test, y_test, n_test,
                   personal_fn) -> Dict[str, Any]:
        # routed by the base wrappers: eval_metrics passes the traceable
        # full personal eval, evaluate the incremental cached one
        ev_g = self._eval_global(state.global_params, x_test, y_test, n_test)
        ev_p = personal_fn(
            state.personal_params, x_test, y_test, n_test)
        return {
            "global_acc": ev_g["acc"], "global_loss": ev_g["loss"],
            "personal_acc": ev_p["acc"], "personal_loss": ev_p["loss"],
            "acc_per_client": ev_p["acc_per_client"],
        }
