"""FedFomo — personalized aggregation by first-order model optimization.

Re-design of ``fedml_api/standalone/fedfomo/fedfomo_api.py:53-217``: each
round every client (1) trains its personal model, (2) picks a neighbor set
(biased toward accumulated helpfulness ``p_choose`` with probability 1/2,
else uniform — ``_benefit_choose`` :130-144), (3) scores each neighbor j by
``w_ij = (L_i(own pre-round model) - L_i(model_j)) / ||theta_j - theta_i||``
on its own *validation* split (``_updates_weight_local`` :147-171; j=self
uses the freshly trained model), and (4) applies the positively-clipped,
normalized weighted deltas to its pre-round model (``_aggregate_func``
:200-217 — if no neighbor helps, the client keeps its pre-round model).

Requires per-client validation shards (the reference's 9-element
``data_val_loader`` tuple, ``cifar10/data_val_loader.py:275-326``).

TPU-native: the neighbor evaluation is a [C, K] gather of stacked models
evaluated by a doubly-vmapped loss pass — the O(C*K) cross-evaluation the
reference does sequentially becomes one jitted program.
"""
from __future__ import annotations

import random as _pyrandom
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core.state import broadcast_tree, tree_index
from ..core.trainer import make_client_update
from ..models import init_params
from .base import FedAlgorithm


@struct.dataclass
class FedFomoState:
    personal_params: Any     # [C, ...]
    p_choose: jax.Array      # [C, C] accumulated helpfulness
    rng: jax.Array


class FedFomo(FedAlgorithm):
    name = "fedfomo"

    def cost_trained_clients_per_round(self) -> int:
        # every client trains its own model each round (fedfomo_api.py:53-118)
        return self.num_clients

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.data.x_val is None:
            raise ValueError(
                "FedFomo needs per-client validation shards "
                "(FederatedData.x_val; see data_val_loader in the reference)"
            )

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=False, mask_params_post_step=False,
            remat=self.remat_local, full_batches=self._full_batches(),
            augment_fn=self.augment_fn,
        )
        self._n_nei = min(self.clients_per_round, self.num_clients - 1)

        def val_loss(params, x, y, n_valid):
            _, loss_sum, total = self.eval_client(params, x, y, n_valid)
            return loss_sum / jnp.maximum(total, 1)

        def round_fn(state: FedFomoState, nei_idx, round_idx,
                     x_train, y_train, n_train, x_val, y_val, n_val):
            rng, k_train = jax.random.split(state.rng)
            lstrd = state.personal_params  # pre-round snapshot

            # (1) every client trains its own model
            trained, _, losses = self._train_stacked(
                self.client_update, lstrd, lstrd, round_idx, k_train,
                x_train, y_train, n_train,
            )

            # (2+3+4) fused per-(client, neighbor) pass: build each
            # neighbor's delta once, score it, and aggregate the
            # positively-clipped normalized deltas
            c = nei_idx.shape[0]
            self_loss = jax.vmap(val_loss)(lstrd, x_val, y_val, n_val)

            def client_round(i, js):
                base = jax.tree_util.tree_map(lambda l: l[i], lstrd)

                # scan over neighbors, accumulating the positively-clipped
                # weighted delta sum in the carry — normalization by the
                # weight sum is linear, so dividing once at the end equals
                # weighting by w/wsum per neighbor. Keeps exactly one
                # neighbor delta live instead of a [K+1, |model|] stack
                # (which at AlexNet3D scale would hold C*(K+1) model copies
                # in HBM at once).
                def per_neighbor(carry, j):
                    acc, wsum = carry
                    model_j = jax.tree_util.tree_map(
                        lambda t, l: jnp.where(j == i, t[i], l[j]),
                        trained, lstrd,
                    )
                    delta = jax.tree_util.tree_map(
                        lambda mj, b: mj - b, model_j, base
                    )
                    l_j = val_loss(model_j, x_val[i], y_val[i], n_val[i])
                    nrm = jnp.sqrt(sum(
                        jnp.sum(jnp.square(d))
                        for d in jax.tree_util.tree_leaves(delta)
                    ))
                    w = jnp.where(
                        nrm > 0,
                        (self_loss[i] - l_j) / jnp.maximum(nrm, 1e-12),
                        0.0,
                    )
                    w_pos = jnp.maximum(w, 0.0)
                    acc = jax.tree_util.tree_map(
                        lambda a, d: a + w_pos.astype(d.dtype) * d,
                        acc, delta,
                    )
                    return (acc, wsum + w_pos), w

                zeros = jax.tree_util.tree_map(jnp.zeros_like, base)
                (acc, wsum), ws = jax.lax.scan(
                    per_neighbor, (zeros, jnp.float32(0.0)), js
                )
                new_p = jax.tree_util.tree_map(
                    lambda b, a: jnp.where(
                        wsum > 0,
                        b + a / jnp.maximum(wsum, 1e-12).astype(a.dtype),
                        b,
                    ),
                    base, acc,
                )
                return new_p, ws

            new_personal, nei_w = jax.vmap(client_round)(
                jnp.arange(c), nei_idx
            )

            # p_choose accumulation over visited neighbors (:93)
            upd = jnp.zeros_like(state.p_choose)
            upd = upd.at[jnp.arange(c)[:, None], nei_idx].add(nei_w)
            return (
                FedFomoState(personal_params=new_personal,
                             p_choose=state.p_choose + upd, rng=rng),
                jnp.mean(losses),
            )

        self._round_jit = jax.jit(round_fn)
        self._eval_personal = self._make_personal_eval()

    def init_state(self, rng: jax.Array) -> FedFomoState:
        p_rng, s_rng = jax.random.split(rng)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        return FedFomoState(
            personal_params=broadcast_tree(params, self.num_clients),
            p_choose=jnp.ones((self.num_clients, self.num_clients)),
            rng=s_rng,
        )

    def _choose_neighbors(self, round_idx: int,
                          p_choose: np.ndarray) -> np.ndarray:
        """Host-side neighbor choice (fedfomo_api.py:130-144): with prob 1/2
        the top-p_choose clients, else uniform (self excluded); self always
        appended."""
        c, k = self.num_clients, self._n_nei
        rng = np.random.RandomState(round_idx)
        coin = _pyrandom.Random(round_idx)
        out = np.zeros((c, k + 1), dtype=np.int32)
        for i in range(c):
            p = p_choose[i].copy()
            p[i] = 0
            if coin.random() >= 0.5:
                idx = np.argsort(p)[-k:]
            else:
                others = np.delete(np.arange(c), i)
                idx = rng.choice(others, k, replace=False)
            out[i, :k] = idx
            out[i, k] = i
        return out

    def run_round(self, state: FedFomoState, round_idx: int):
        nei = self._choose_neighbors(round_idx, np.asarray(state.p_choose))
        state, loss = self._round_jit(
            state, jnp.asarray(nei), jnp.asarray(round_idx, jnp.float32),
            self.data.x_train, self.data.y_train, self.data.n_train,
            self.data.x_val, self.data.y_val, self.data.n_val,
        )
        return state, {"train_loss": loss}

    def evaluate(self, state: FedFomoState) -> Dict[str, Any]:
        ev = self._eval_personal(
            state.personal_params, self.data.x_test, self.data.y_test,
            self.data.n_test,
        )
        return {"personal_acc": ev["acc"], "personal_loss": ev["loss"],
                "acc_per_client": ev["acc_per_client"]}
