"""SalientGrads — the flagship algorithm: SNIP-masked sparse federated
training on site-partitioned neuroimaging data.

Re-design of ``fedml_api/standalone/sailentgrads/sailentgrads_api.py``:
  1. Before round 0, every client computes SNIP saliency scores on its own
     shard (itersnip iterations, ``client.py:29-50``), the server averages
     them (``snip.py:120-140``) and thresholds a single *global* mask at
     ``dense_ratio`` (``snip.py:80-116``, via ``sailentgrads_api.py:47-66``).
  2. Then FedAvg rounds where every local SGD step re-masks the weights
     (``my_model_trainer.py:213-216``) and aggregation is the
     sample-weighted mean (``sailentgrads_api.py:212-227``).

Here the scoring pass is a vmapped ``jax.grad`` w.r.t. an all-ones mask
multiplier (mean over clients = the "saliency psum"), and the training round
is the same single jitted SPMD program as FedAvg with the mask broadcast
along the client axis.

Like the reference, each trained client's locally-trained weights are kept
as its *personal* model (``w_per_mdls[cur_clnt] = w_per``,
``sailentgrads_api.py:107-110,133``) and the per-round eval protocol tests
BOTH the global model and every client's personal model on its local test
set (``_test_on_all_clients(w_global, w_per_mdls, round_idx)``,
``:238,262-283``), plus one final eval at round -1 after the loop
(``:147``). ``track_personal=False`` drops the on-device stack for
large-C simulations (same opt-out as FedAvg's).
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..core.state import broadcast_tree
from ..core.trainer import make_client_update
from ..models import init_params
from ..obs import trace as obs_trace
from ..ops.sparsity import make_snip_score_fn, mask_density, mask_from_scores
from .base import FedAlgorithm


@struct.dataclass
class SalientGradsState:
    global_params: Any
    mask: Any
    # [C, ...] — w_per_mdls (sailentgrads_api.py:107-110), or None when
    # personal tracking is off. Initialized to dense copies of the initial
    # global model (the reference's mask multiply at init is commented
    # out, :110) and updated with each trained client's masked local
    # weights. Same HBM caveat as FedAvgState.personal_params.
    personal_params: Any
    rng: jax.Array
    # [C, ...] error-feedback residual of agg_impl='topk', or None for
    # every other impl (see FedAvgState.agg_residual). Locals honor the
    # static SNIP mask, so deltas — and inductively the residual — are
    # exact zeros on dead coordinates: the top-k selection (compressed
    # to the plan's live set) can never ship a dead coordinate.
    agg_residual: Any = None
    # per-client personal-eval cache (--eval_cache), or None — see
    # FedAvgState.eval_cache (same semantics, same lineage split)
    eval_cache: Any = None


class SalientGrads(FedAlgorithm):
    name = "salientgrads"
    supports_fused = True
    guard_metrics_supported = True
    numerics_supported = True
    numerics_with_mask = True
    topk_supported = True
    donate_supported = True
    store_supported = True

    def __init__(self, *args, dense_ratio: float = 0.5,
                 itersnip_iterations: int = 1, defense=None,
                 fused_kernels: bool = False, snip_mask: bool = True,
                 stratified_sampling: bool = False,
                 stratified_mode: str = "exact",
                 track_personal: bool = True,
                 eval_cache: bool = False, **kwargs):
        self.dense_ratio = dense_ratio
        self.itersnip_iterations = itersnip_iterations
        # optional robust.RobustAggregator (fedml_core/robustness wiring)
        self.defense = defense
        self.fused_kernels = fused_kernels
        # --snip_mask 0: all-ones mask, the reference's dense-control mode
        # (sailentgrads_api.py:91-103)
        self.snip_mask = snip_mask
        # --stratified_sampling: per-class-balanced SNIP scoring.
        # stratified_mode="exact" (default) replays the reference's
        # StratifiedKFold(25, shuffle, seed 42) schedule, scoring each
        # split's TRAIN side (client.py:32-42) via a host-computed
        # pad+mask index schedule; "balanced" is the fast path — 25
        # class-balanced random batch draws (documented approximation,
        # see ops/sparsity.make_snip_score_fn).
        self.stratified_sampling = stratified_sampling
        if stratified_mode not in ("exact", "balanced"):
            raise ValueError(
                f"stratified_mode {stratified_mode!r} not in "
                "('exact', 'balanced')")
        self.stratified_mode = stratified_mode
        # track_personal=False drops the on-device w_per_mdls stack and the
        # personal half of the per-round eval — O(C x model) HBM
        self.track_personal = track_personal
        # eval_cache: the in-state incremental personal-eval cache
        # (base.py "--eval_cache" section); validated in the base ctor
        self.eval_cache = bool(eval_cache)
        super().__init__(*args, **kwargs)

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=False, mask_params_post_step=True,
            remat=self.remat_local,
            fused_kernels=self.fused_kernels,
            full_batches=self._full_batches(),
            augment_fn=self.augment_fn,
        )
        self._fold_sched = None
        if self.snip_mask and self.stratified_sampling and \
                self.stratified_mode == "exact":
            # the reference's exact StratifiedKFold(25, shuffle, seed 42)
            # schedule, computed host-side per client (labels are tiny;
            # multihost cohorts should use stratified_mode="balanced" —
            # the schedule needs every client's labels on every host)
            import numpy as np

            from ..ops.sparsity import (
                make_snip_fold_score_fn,
                stacked_fold_schedules,
            )

            idx, w = stacked_fold_schedules(
                np.asarray(self.data.y_train),
                np.asarray(self.data.n_train))
            self._fold_sched = (jnp.asarray(idx), jnp.asarray(w))
            self.snip_fold_scores = make_snip_fold_score_fn(
                self.apply_fn, self.loss_type, augment_fn=self.augment_fn)
        else:
            self.snip_scores = make_snip_score_fn(
                self.apply_fn, self.loss_type, self.hp.batch_size,
                stratified=self.stratified_sampling,
                num_classes=self.data.class_num,
                augment_fn=self.augment_fn,
            )

        def global_mask_fn(params, x_train, y_train, n_train, rng):
            """All clients score their own shards; mean; global top-k."""
            c = x_train.shape[0]
            keys = jax.random.split(rng, c)
            params_b = broadcast_tree(params, c)
            if self._fold_sched is not None:
                idx, w = self._fold_sched
                scores = self._vmap_clients(
                    self.snip_fold_scores, in_axes=(0, 0, 0, 0, 0, 0),
                )(params_b, x_train, y_train, idx, w, keys)
            else:
                # balanced mode scores over 25 balanced batches (the
                # reference's n_splits=25, client.py:36)
                n_iters = 25 if self.stratified_sampling \
                    else self.itersnip_iterations
                scores = self._vmap_clients(
                    lambda p, x, y, n, k: self.snip_scores(
                        p, x, y, n, k, n_iters
                    ),
                    in_axes=(0, 0, 0, 0, 0),
                )(params_b, x_train, y_train, n_train, keys)
            # server-side mean over clients (snip.py:120-140)
            mean_scores = jax.tree_util.tree_map(
                lambda s: jnp.mean(s, axis=0), scores
            )
            # params returned unchanged: under donate_state the donated
            # params buffers alias to this pass-through output, so the
            # caller (init_state) keeps a valid handle while XLA reuses
            # the buffers for the scoring pass's scratch
            return mask_from_scores(mean_scores, self.dense_ratio,
                                    kernels=self.agg_kernels), params

        self._global_mask_jit = self._jit_entry(global_mask_fn)

        def round_fn(state: SalientGradsState, sel_idx, round_idx,
                     x_train, y_train, n_train, *test_args):
            rng, round_key = jax.random.split(state.rng)
            new_global, locals_, mean_loss, fstats, new_residual = \
                self._train_selected_weighted(
                    self.client_update, state.global_params, state.mask,
                    sel_idx, round_idx, round_key, x_train, y_train,
                    n_train, defense=self.defense,
                    residual=state.agg_residual,
                )
            if self.defense is not None or self.agg_impl == "topk":
                # weak-DP noise lands on every leaf — and the topk
                # delta update leaves round 0's dense init on dead
                # coordinates (g + update touches only live coords);
                # re-mask so the global model keeps the SNIP sparsity
                # invariant either way (one fused pass per leaf under
                # the pallas backend; p*m is elementwise, so the
                # backends are trivially bit-identical)
                if self.agg_kernels == "pallas":
                    from ..ops.pallas_kernels import fused_mask_apply

                    new_global = fused_mask_apply(new_global, state.mask)
                else:
                    new_global = jax.tree_util.tree_map(
                        lambda p, m: p * m, new_global, state.mask)
            # w_per_mdls[cur_clnt] = the client's (pre-defense) locally
            # trained weights (sailentgrads_api.py:133), guard-aware
            new_personal = self._guarded_personal_update(
                state.personal_params, locals_, sel_idx, fstats)
            # --eval_cache: refresh ONLY the trained clients' cache rows
            # (see FedAvg.round_fn — identical semantics)
            new_cache = state.eval_cache
            if self.eval_cache:
                new_cache = self._update_eval_cache(
                    state.eval_cache, new_personal, sel_idx, *test_args)
            # in-jit numerics telemetry (--obs_numerics) incl. mask
            # churn / cross-client agreement; AFTER the defense re-mask
            # so the update norms see the adopted global. () when off
            nums = self._numerics_outputs(
                state.global_params, new_global, locals_,
                mask=state.mask)
            return self._round_outputs(
                SalientGradsState(global_params=new_global,
                                  mask=state.mask,
                                  personal_params=new_personal, rng=rng,
                                  agg_residual=new_residual,
                                  eval_cache=new_cache),
                mean_loss, fstats, nums)

        self._round_fn = round_fn
        self._round_jit = self._jit_entry(round_fn)
        self._eval_global = self._make_global_eval()
        self._eval_personal = self._make_personal_eval()

    def init_state(self, rng: jax.Array) -> SalientGradsState:
        p_rng, m_rng, s_rng = jax.random.split(rng, 3)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        if not self.snip_mask:
            # --snip_mask 0: dense-control mode, all-ones mask
            # (sailentgrads/client.py:95-103)
            mask = jax.tree_util.tree_map(jnp.ones_like, params)
        else:
            with obs_trace.span("snip_mask"):
                # params rebound to the pass-through output: under
                # donate_state the input buffers were donated and THIS
                # is the valid (aliased) handle
                mask, params = self._global_mask_jit(
                    params, self.data.x_train, self.data.y_train,
                    self.data.n_train, m_rng,
                )
        from ..core.state import zeros_like_tree

        if self._store is not None:
            # store mode: per-client rows live in the client store with
            # lazy defaults (dense init-params rows — the reference's
            # commented-out init mask multiply — / zero residual); state
            # holds None between rounds. See FedAvg.init_state.
            self._store_register_fields(params)
            ev_cache = None
            if self.eval_cache:
                ev_cache = self._seed_eval_cache(
                    broadcast_tree(params, self.num_clients))
            return SalientGradsState(
                global_params=params, mask=mask, personal_params=None,
                rng=s_rng, agg_residual=None, eval_cache=ev_cache)
        personal = (broadcast_tree(params, self.num_clients)
                    if self.track_personal else None)
        return SalientGradsState(
            global_params=params, mask=mask,
            # w_per_mdls init: dense copies of the initial global model —
            # the reference's init-time mask multiply is commented out
            # (sailentgrads_api.py:107-110)
            personal_params=personal,
            rng=s_rng,
            # topk: zero residual per client (masked by construction —
            # deltas of mask-honoring locals are zero on dead coords)
            agg_residual=(zeros_like_tree(
                broadcast_tree(params, self.num_clients))
                if self.agg_impl == "topk" else None),
            # --eval_cache: seeded by one full personal eval (one-time
            # O(C); later rounds refresh O(S) rows in-graph)
            eval_cache=self._seed_eval_cache(personal))

    def _ensure_agg_plan(self, state: SalientGradsState) -> None:
        """Host-side, before the round program traces: build the
        mask-aware sparse gather plan from the CONCRETE mask. Valid for
        the whole run — the SNIP mask is fixed after init
        (``masks_evolve=False``), which is exactly why SalientGrads can
        run ``agg_impl='sparse'`` (and compressed-selection
        ``'topk'`` / the ``'hier'`` sparse cross-slice wire): the
        live-coordinate set is static per round-block. With a weak-DP
        defense the compressed reduce also drops the noise landing on
        dead kernel coordinates — the same invariant the explicit
        post-aggregation re-mask enforces."""
        needs_plan = self.agg_impl in ("sparse", "topk") or (
            self.agg_impl == "hier" and self.agg_hier_wire == "sparse")
        if needs_plan and self._agg_sparse_plan is None:
            from ..parallel.collectives import build_sparse_plan

            self._agg_sparse_plan = build_sparse_plan(state.mask)

    def run_round(self, state: SalientGradsState, round_idx: int):
        self._ensure_agg_plan(state)  # host-side, before any trace
        if self._store is not None:
            # streamed cohort residency: same round body at slab width
            return self._run_round_store(state, round_idx)
        sel = self._selected_client_indexes(round_idx)
        d = self.data
        # read BEFORE dispatch: under donate_state the call consumes
        # `state` (the ownership lint holds driver paths to this order)
        old_pers = state.personal_params
        extra = ((d.x_test, d.y_test, d.n_test)
                 if self.eval_cache else ())
        # dispatch-time span (async): the round's device phases are
        # labeled by named_scope inside the jitted body instead
        with obs_trace.span("dispatch_round"):
            out = self._round_jit(
                state, jnp.asarray(sel),
                jnp.asarray(round_idx, jnp.float32),
                d.x_train, d.y_train, d.n_train, *extra,
            )
        new_state = out[0]
        # only the trained clients' personal models changed — feed the
        # incremental personal-eval cache (base._personal_eval_cached)
        self._note_personal_update(
            old_pers, new_state.personal_params, sel)
        return new_state, dict(zip(self._round_metric_names, out[1:]))

    def run_rounds_fused(self, state, start_round, n_rounds, eval_every=0):
        self._ensure_agg_plan(state)  # before the fused program traces
        return super().run_rounds_fused(state, start_round, n_rounds,
                                        eval_every=eval_every)

    def finalize(self, state: SalientGradsState):
        """One final global+personal eval after the last round — the
        reference's ``_test_on_all_clients(w_global, w_per_mdls, -1)``
        (``sailentgrads_api.py:147``; no fine-tune, unlike FedAvg)."""
        ev = self.evaluate(state)
        record = {"round": -1,
                  **{k: v for k, v in ev.items()
                     if not k.startswith("acc_per")}}
        return state, record

    def _eval_impl(self, state, x_test, y_test, n_test,
                   personal_fn) -> Dict[str, Any]:
        # routed by the base wrappers (eval_metrics = traceable full
        # personal eval; evaluate = incremental cached one). The
        # reference protocol tests the global model AND every client's
        # personal model on its local test set (sailentgrads_api.py:238,
        # 262-283); global params are already masked (the aggregate of
        # masked locals; assert via density)
        ev = self._eval_global(state.global_params, x_test, y_test, n_test)
        out = {
            "global_acc": ev["acc"],
            "global_loss": ev["loss"],
            "mask_density": mask_density(state.mask),
            "acc_per_client": ev["acc_per_client"],
        }
        if state.personal_params is not None or \
                self._store_has_personal():
            evp = personal_fn(
                state.personal_params, x_test, y_test, n_test)
            out.update(personal_acc=evp["acc"], personal_loss=evp["loss"])
        return out
