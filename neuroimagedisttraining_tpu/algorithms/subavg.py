"""SubAvg — iterative-magnitude-pruning federated averaging.

Re-design of ``fedml_api/standalone/subavg/``: each sampled client trains
with masked gradients from the masked global model
(``my_model_trainer.py:48-82``), derives candidate masks by magnitude
percentile after the first and last local epoch (``fake_prune``,
``prune_func.py:9-30``), and accepts the new mask only if the two candidates
differ by more than ``dist_thresh`` hamming, the current density is above
``dense_ratio``, and post-prune local accuracy clears ``acc_thresh``
(``subavg/client.py:36-63``). The server then does mask-count-weighted
averaging, keeping its previous value where no client had a live weight
(``subavg_api.py:123-140`` — the ``isfinite`` guard).

TPU-native: the accept decision is a traced three-way AND selecting between
mask pytrees; the count-weighted aggregate is two contractions over the
selected-client axis.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..core.state import (
    HyperParams,
    broadcast_tree,
    tree_index,
    tree_scatter_update,
)
from ..core.trainer import make_client_update
from ..models import init_params
from ..ops.sparsity import (
    magnitude_prune_mask,
    mask_density,
    mask_distance,
)
from .base import FedAlgorithm, sample_client_indexes


@struct.dataclass
class SubAvgState:
    global_params: Any
    masks: Any  # [C, ...] per-client masks
    rng: jax.Array


class SubAvg(FedAlgorithm):
    name = "subavg"
    supports_fused = True
    masks_evolve = True  # pruning changes per-client density

    def __init__(self, *args, each_prune_ratio: float = 0.2,
                 dist_thresh: float = 0.001, acc_thresh: float = 0.5,
                 dense_ratio: float = 0.5, **kwargs):
        self.each_prune_ratio = each_prune_ratio
        self.dist_thresh = dist_thresh
        self.acc_thresh = acc_thresh
        self.dense_ratio = dense_ratio
        super().__init__(*args, **kwargs)

    def _build(self) -> None:
        hp = self.hp
        hp_first = hp.replace(local_epochs=1)
        hp_rest = hp.replace(local_epochs=max(0, hp.local_epochs - 1))
        self._update_first = make_client_update(
            self.apply_fn, self.loss_type, hp_first,
            mask_grads=True, mask_params_post_step=False,
            remat=self.remat_local, full_batches=self._full_batches(hp_first),
            augment_fn=self.augment_fn,
        )
        self._update_rest = (
            make_client_update(
                self.apply_fn, self.loss_type, hp_rest,
                mask_grads=True, mask_params_post_step=False,
                remat=self.remat_local,
                full_batches=self._full_batches(hp_rest),
                augment_fn=self.augment_fn,
            )
            if hp_rest.local_epochs > 0 else None
        )

        def client_round(params, mask, rng, x, y, n_valid, round_idx):
            mom0 = jax.tree_util.tree_map(jnp.zeros_like, params)
            p1, mom1, loss1 = self._update_first(
                params, mom0, mask, rng, x, y, n_valid, round_idx, params
            )
            m1 = magnitude_prune_mask(mask, p1, self.each_prune_ratio)
            if self._update_rest is not None:
                p2, _, loss2 = self._update_rest(
                    p1, mom1, mask, jax.random.fold_in(rng, 1), x, y,
                    n_valid, round_idx, p1,
                )
                loss = (loss1 + loss2) / 2
            else:
                p2, loss = p1, loss1
            m2 = magnitude_prune_mask(mask, p2, self.each_prune_ratio)

            # accept gates (subavg/client.py:50-60)
            dist = mask_distance(m1, m2)
            density = mask_density(p2)  # nonzero fraction of the weights themselves
            correct, _, total = self.eval_client(
                jax.tree_util.tree_map(jnp.multiply, p2, m2), x, y, n_valid
            )
            acc = correct.astype(jnp.float32) / jnp.maximum(total, 1)
            accept = (
                (dist > self.dist_thresh)
                & (density > self.dense_ratio)
                & (acc > self.acc_thresh)
            )
            new_mask = jax.tree_util.tree_map(
                lambda a, b: jnp.where(accept, a, b), m2, mask
            )
            new_params = jax.tree_util.tree_map(
                lambda p, m: jnp.where(accept, p * m, p), p2, new_mask
            )
            return new_params, new_mask, loss

        def round_fn(state: SubAvgState, sel_idx, round_idx,
                     x_train, y_train, n_train):
            rng, round_key = jax.random.split(state.rng)
            s = sel_idx.shape[0]
            masks_sel = tree_index(state.masks, sel_idx)
            # client starts from the mask-pruned global (client.py:40-42)
            params0 = jax.tree_util.tree_map(
                jnp.multiply, broadcast_tree(state.global_params, s),
                masks_sel,
            )
            keys = jax.random.split(round_key, s)
            trained, new_masks, losses = self._vmap_clients(
                client_round, in_axes=(0, 0, 0, 0, 0, 0, None)
            )(params0, masks_sel, keys,
              jnp.take(x_train, sel_idx, axis=0),
              jnp.take(y_train, sel_idx, axis=0),
              jnp.take(n_train, sel_idx), round_idx)

            # mask-count-weighted server update (subavg_api.py:123-140).
            # Counts use the PRE-round masks: the reference appends
            # (mask_pers[idx], w_client) to w_locals BEFORE the post-
            # aggregation mask update loop (subavg_api.py:66-70,83-84), so
            # freshly pruned coordinates count in the denominator there too.
            counts = jax.tree_util.tree_map(
                lambda m: jnp.sum(m, axis=0), masks_sel
            )
            sums = jax.tree_util.tree_map(
                lambda w: jnp.sum(w, axis=0), trained
            )
            new_global = jax.tree_util.tree_map(
                lambda srv, s_, c: jnp.where(c > 0, s_ / jnp.maximum(c, 1e-9),
                                             srv),
                state.global_params, sums, counts,
            )
            all_masks = tree_scatter_update(state.masks, sel_idx, new_masks)
            return (
                SubAvgState(global_params=new_global, masks=all_masks,
                            rng=rng),
                jnp.mean(losses),
            )

        self._round_jit = jax.jit(round_fn)
        self._eval_global = self._make_global_eval()
        self._eval_personal = self._make_personal_eval()

    def init_state(self, rng: jax.Array) -> SubAvgState:
        p_rng, s_rng = jax.random.split(rng)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        # all clients start from the SAME all-ones mask (subavg_api.py:45-47)
        masks = broadcast_tree(
            jax.tree_util.tree_map(jnp.ones_like, params), self.num_clients
        )
        return SubAvgState(global_params=params, masks=masks, rng=s_rng)

    def run_round(self, state: SubAvgState, round_idx: int):
        sel = sample_client_indexes(
            round_idx, self.num_clients, self.clients_per_round
        )
        state, loss = self._round_jit(
            state, jnp.asarray(sel), jnp.asarray(round_idx, jnp.float32),
            self.data.x_train, self.data.y_train, self.data.n_train,
        )
        return state, {"train_loss": loss}

    def eval_metrics(self, state: SubAvgState, x_test, y_test,
                     n_test) -> Dict[str, Any]:
        # reference evaluates the global model through each client's mask
        # (subavg_api.py _local_test_on_all_clients)
        c = self.num_clients
        per_client = jax.tree_util.tree_map(
            jnp.multiply, broadcast_tree(state.global_params, c), state.masks
        )
        ev = self._eval_personal(per_client, x_test, y_test, n_test)
        dens = jax.vmap(mask_density)(state.masks)
        return {
            "personal_acc": ev["acc"], "personal_loss": ev["loss"],
            "mean_mask_density": jnp.mean(dens),
            "acc_per_client": ev["acc_per_client"],
        }
