"""Local-only baseline: no communication, each client trains its own model.

Re-design of ``fedml_api/standalone/local/local_api.py:51-84``: the sampled
clients continue training their personal models; there is no aggregation.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..core.state import broadcast_tree, tree_index, tree_scatter_update
from ..core.trainer import make_client_update
from ..models import init_params
from .base import FedAlgorithm, sample_client_indexes


@struct.dataclass
class LocalOnlyState:
    personal_params: Any  # [C, ...]
    rng: jax.Array


class LocalOnly(FedAlgorithm):
    name = "local"
    supports_fused = True

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=False, mask_params_post_step=False,
            remat=self.remat_local, full_batches=self._full_batches(),
            augment_fn=self.augment_fn,
        )

        def round_fn(state: LocalOnlyState, sel_idx, round_idx,
                     x_train, y_train, n_train):
            rng, round_key = jax.random.split(state.rng)
            p_sel = tree_index(state.personal_params, sel_idx)
            trained, _, losses = self._train_stacked(
                self.client_update, p_sel, p_sel, round_idx, round_key,
                jnp.take(x_train, sel_idx, axis=0),
                jnp.take(y_train, sel_idx, axis=0),
                jnp.take(n_train, sel_idx),
            )
            new_personal = tree_scatter_update(
                state.personal_params, sel_idx, trained
            )
            return (LocalOnlyState(personal_params=new_personal, rng=rng),
                    jnp.mean(losses))

        self._round_jit = jax.jit(round_fn)
        self._eval_personal = self._make_personal_eval()

    def init_state(self, rng: jax.Array) -> LocalOnlyState:
        p_rng, s_rng = jax.random.split(rng)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        return LocalOnlyState(
            personal_params=broadcast_tree(params, self.num_clients),
            rng=s_rng,
        )

    def run_round(self, state: LocalOnlyState, round_idx: int):
        sel = sample_client_indexes(
            round_idx, self.num_clients, self.clients_per_round
        )
        state, loss = self._round_jit(
            state, jnp.asarray(sel), jnp.asarray(round_idx, jnp.float32),
            self.data.x_train, self.data.y_train, self.data.n_train,
        )
        return state, {"train_loss": loss}

    def eval_metrics(self, state: LocalOnlyState, x_test, y_test,
                     n_test) -> Dict[str, Any]:
        ev = self._eval_personal(
            state.personal_params, x_test, y_test, n_test)
        return {"personal_acc": ev["acc"], "personal_loss": ev["loss"],
                "acc_per_client": ev["acc_per_client"]}
