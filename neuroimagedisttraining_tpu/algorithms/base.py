"""Algorithm base class + shared federated-round machinery.

The reference gives every algorithm an API class with a Python round loop
(``fedml_api/standalone/<algo>/<algo>_api.py``) that iterates clients
sequentially. Here the round is one jitted SPMD program; the host loop only
(a) samples the round's client subset (tiny, and kept on host to preserve the
reference's cross-algorithm reproducibility contract — ``np.random.seed(
round_idx)`` before sampling, ``fedavg_api.py:92-100``) and (b) logs metrics.
"""
from __future__ import annotations

import abc
import contextlib
import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.state import HyperParams
from ..core.trainer import make_eval_fn
from ..data.types import FederatedData
from ..models import make_apply_fn
from ..obs import trace as obs_trace

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def _no_persistent_cache_write():
    """Donated executables must not round-trip the persistent
    compilation cache: on this jaxlib (0.4.37, XLA:CPU) a DESERIALIZED
    donated executable carries corrupt input-output-aliasing metadata —
    executing one reloaded from a warm cache corrupts the heap (a
    resumed run whose twin populated the cache dies in the cache read
    or at a later allocation). ``jax_enable_compilation_cache`` cannot
    gate this per call (``compilation_cache.is_cache_used`` memoizes
    its first read), but the WRITE threshold
    ``jax_persistent_cache_min_compile_time_secs`` is consulted on
    every ``_cache_write`` — raising it to +inf around a donated
    compile keeps the donated executable out of the cache, and since a
    donated program's HLO (which carries the aliasing) hashes to its
    own cache key, its lookups then always miss and compile fresh.
    No retrace, no effect on in-memory executables or on borrowing
    entry points. Remove when upstream serialization handles
    aliasing."""
    name = "jax_persistent_cache_min_compile_time_secs"
    prev = getattr(jax.config, name, None)
    if prev is None:
        yield
        return
    jax.config.update(name, float("inf"))
    try:
        yield
    finally:
        jax.config.update(name, prev)


def _personal_metrics(correct, loss_sum, total):
    """Per-client eval terms -> the personal-eval protocol metrics
    (mean of per-client accuracies AND mean of per-client MEAN losses —
    sailentgrads_api.py:276-283 appends each client's ``test_loss`` and
    reports ``sum/len``, so uneven test shards do NOT reweight the
    protocol loss; the earlier sample-weighted ``sum(loss_sum)/
    sum(total)`` here was an unrecorded deviation, fixed per ADVICE r5 —
    see PARITY.md). The ONE definition all three personal eval paths
    share (full, incremental merge, cache-only re-reduce): the
    incremental cache's bitwise-identity contract rests on these
    reductions being literally the same code."""
    totals = jnp.maximum(total, 1)
    acc = correct.astype(jnp.float32) / totals
    return {
        "acc_per_client": acc,
        "acc": jnp.mean(acc),
        "loss": jnp.mean(loss_sum / totals),
        # raw per-client terms seed/refresh the incremental-eval cache
        "correct": correct, "loss_sum": loss_sum, "total": total,
    }


def sample_client_indexes(
    round_idx: int, client_num_in_total: int, client_num_per_round: int,
    retry: int = 0,
) -> np.ndarray:
    """Seeded per-round client sampling (fedavg_api.py:92-100 semantics:
    reseed numpy with the round index so every algorithm draws the same
    subsets — the reference's intentional comparability contract).

    ``retry`` re-samples the cohort for a watchdog rollback-retry
    (robust/recovery.py): the draw stays a pure function of
    (round_idx, retry) — no host RNG state — so a killed-and-resumed run
    replays the identical retry cohorts. ``retry=0`` is bit-compatible
    with the reference contract. Full participation is arange regardless
    (there is no alternative cohort to draw)."""
    if client_num_in_total == client_num_per_round:
        return np.arange(client_num_in_total, dtype=np.int32)
    if retry:
        # golden-ratio stride keeps retry seeds disjoint from every round
        # index a realistic run can reach
        np.random.seed((round_idx + 0x9E3779B1 * retry) % (2 ** 32))
    else:
        np.random.seed(round_idx)
    return np.random.choice(
        range(client_num_in_total), client_num_per_round, replace=False
    ).astype(np.int32)


class FusedMetrics:
    """A fused block's per-round metric series, fetched lazily in ONE
    host transfer (the in-graph ``packed`` stack; see ``_get_fused_fn``).
    Until materialized, holding it costs nothing — the driver dispatches
    the next block first, then materializes the previous one."""

    def __init__(self, ys_device, packed):
        self._ys = ys_device
        self._packed = packed
        self._host = None

    def materialize(self) -> Dict[str, Any]:
        if self._host is None:
            flat, treedef = jax.tree_util.tree_flatten(self._ys)
            vals = np.asarray(self._packed)  # one transfer for the block
            self._host = jax.tree_util.tree_unflatten(
                treedef, [vals[i] for i in range(len(flat))])
            self._ys = self._packed = None  # free the device buffers
        return self._host

    def __getitem__(self, key):
        return self.materialize()[key]

    def __contains__(self, key):
        return key in self.materialize()


class FedAlgorithm(abc.ABC):
    """Base class: owns model apply fn, data, hyperparams, and jitted kernels."""

    name: str = "base"

    def __init__(
        self,
        model,
        data: FederatedData,
        hp: HyperParams,
        loss_type: str = "bce",
        frac: float = 1.0,
        eval_batch: int = 32,
        seed: int = 0,
        client_chunk: Optional[int] = None,
        compute_dtype: Optional[str] = None,
        channel_inject: bool = False,
        remat_local: bool = False,
        eval_clients: int = 0,
        augment="auto",
        agg_impl: str = "dense",
        agg_bucket_size: int = 0,
        agg_topk_density: float = 0.1,
        agg_topk_sample: int = 0,
        agg_hier_wire: str = "bf16",
        agg_hier_inner: int = 0,
        agg_overlap: bool = True,
        agg_kernels: str = "xla",
        fault_spec: str = "",
        guard: Optional[bool] = None,
        robust_agg: str = "none",
        robust_trim: float = 0.2,
        robust_krum_f: int = 0,
        robust_norm_bound: float = 5.0,
        obs_numerics: bool = False,
        donate_state: bool = False,
        client_store: str = "device",
        store_hot_clients: int = 64,
        store_dir: Optional[str] = None,
    ):
        from ..parallel.collectives import AGG_IMPLS, DEFAULT_BUCKET_SIZE

        self.model = model
        self.data = data
        self.hp = hp
        self.loss_type = loss_type
        self.seed = seed
        self.num_clients = data.num_clients
        self.clients_per_round = max(1, int(round(self.num_clients * frac)))
        self.client_chunk = client_chunk
        # mixed precision: f32 master weights + (e.g.) bf16 conv/matmul
        # compute — see make_apply_fn. "bfloat16" is the TPU-native choice.
        self.compute_dtype = (
            jnp.dtype(compute_dtype) if compute_dtype is not None else None
        )
        # channel_inject: volumes stored channel-less, channel appended at
        # apply time (see make_apply_fn docstring for the HBM-tiling why)
        self.channel_inject = channel_inject
        # remat_local: rematerialized local steps (core/trainer.py) — more
        # concurrent clients per chip at the cost of a second forward pass
        self.remat_local = remat_local
        # agg_impl: the cross-chip aggregation path of the central
        # weighted mean (parallel/collectives.py). "dense" (default) is
        # the exact monolithic contraction of weighted_tree_sum;
        # "bucketed" pipelines fixed-size per-bucket reduces; "bf16"/
        # "int8" add a low-precision wire with f32 accumulation; "sparse"
        # (static-mask algorithms only — SalientGrads) reduces on the
        # mask's live coordinates. Consumed by _aggregate; algorithms
        # without a central aggregate ignore it.
        if agg_impl not in AGG_IMPLS:
            raise ValueError(f"agg_impl {agg_impl!r} not in {AGG_IMPLS}")
        self.agg_impl = agg_impl
        self.agg_bucket_size = agg_bucket_size or DEFAULT_BUCKET_SIZE
        # topk: error-feedback top-k sparsification — the residual is
        # ALGORITHM STATE (State.agg_residual, checkpointed), so only
        # algorithms that declare topk_supported (and thread the
        # residual through their round bodies) may select it
        from ..parallel.collectives import topk_count

        # validated on EVERY impl, not just topk: the --obs_comm what-if
        # table prices the topk wire at this density on every run, so an
        # out-of-range value must fail here, not mid-run in WireCostModel
        topk_count(1, agg_topk_density)
        if agg_impl == "topk":
            if not self.topk_supported:
                raise ValueError(
                    f"{self.name}: agg_impl='topk' carries an error-"
                    "feedback residual in algorithm state; only the "
                    "central-aggregate algorithms that thread it "
                    "(fedavg/salientgrads) support it")
        self.agg_topk_density = agg_topk_density
        # 0 = exact per-group top-k; N = deterministic strided-subsample
        # threshold estimate (~N candidates/group — the DGC sampling
        # trick; EF absorbs the approximate shipped count)
        self.agg_topk_sample = int(agg_topk_sample)
        # hier: two-stage reduce — full-precision psum inside each
        # agg_hier_inner-device slice, agg_hier_wire across slices
        # (0 = auto slice split; 'sparse' wire = compressed-plan f32,
        # static-mask algorithms only)
        from ..parallel.collectives import HIER_WIRES

        if agg_hier_wire not in HIER_WIRES:
            raise ValueError(
                f"agg_hier_wire {agg_hier_wire!r} not in {HIER_WIRES}")
        self.agg_hier_wire = agg_hier_wire
        if int(agg_hier_inner) < 0:
            # the collectives layer uses -1 internally as the auto-split
            # sentinel; from config, 0 IS auto — a negative here is a
            # typo that would otherwise silently run the auto split
            # while run_identity records the never-applied request
            raise ValueError(
                f"agg_hier_inner {agg_hier_inner} must be >= 0 "
                "(0 = balanced auto split)")
        self.agg_hier_inner = int(agg_hier_inner)
        # overlap: group-ordered dispatch — each leaf-group bucket's
        # collective is emitted right after its own local contraction
        # (bit-identical math; scheduling freedom only, so it never
        # enters run identity)
        self.agg_overlap = bool(agg_overlap)
        # agg_kernels: XLA-vs-pallas backend for the wire's selection /
        # quantize kernels (ops/topk_select.py, ops/pallas_kernels.py).
        # Bit-identical by the tie-break contract, so it never enters
        # run identity (census class: inert, like agg_overlap /
        # donate_state); interpret mode keeps CPU runs on the same
        # kernel code a TPU session compiles for real.
        from ..ops.topk_select import check_kernels

        self.agg_kernels = check_kernels(agg_kernels)
        self._agg_sparse_plan = None   # set by static-mask subclasses
        self._agg_mesh_known = False   # lazily discovered from the data
        self._agg_mesh_val = None
        # fault_spec: deterministic PRNG-keyed fault injection on the
        # stacked local updates (robust/faults.py) — per-round dropout,
        # stragglers, NaN poison, Byzantine scaling, all derived from the
        # run seed so a resumed run replays the identical trace. guard:
        # the in-jit non-finite quarantine before _aggregate
        # (robust/guard.py); None = auto (on exactly when faults are
        # injected). Both live in the shared central-aggregate round body
        # (_train_selected_weighted) — algorithms without one ignore them
        # (and the CLI runner refuses the flags for those).
        from ..robust.faults import (make_fault_fn, make_labelflip_fn,
                                     parse_fault_spec)

        self.fault_spec = parse_fault_spec(fault_spec)
        self.fault_fn = (make_fault_fn(self.fault_spec, seed)
                         if self.fault_spec is not None
                         and self.fault_spec.any_active else None)
        # labelflip rides the DATA path (poisoned labels corrupt what the
        # client learns from, before training) — a separate hook from the
        # post-training delta injector, same key derivation
        self.labelflip_fn = make_labelflip_fn(
            self.fault_spec, seed,
            num_classes=int(getattr(model, "num_classes", 2) or 2))
        self.guard_enabled = (bool(guard) if guard is not None
                              else self.fault_fn is not None)
        if self.fault_fn is not None and not self.guard_enabled \
                and self.fault_spec.drop > 0:
            # nan/scale/straggle without the guard is a legitimate
            # undefended-chaos ablation (the poison really propagates);
            # drop WITHOUT the guard is silently inert — the 'dropped'
            # client's untouched update still aggregates at full weight
            raise ValueError(
                "fault_spec drop=... requires the guard (it is what "
                "excludes dropped clients from the aggregate); don't "
                "pass guard=False, or remove drop from the spec")
        if self.guard_enabled and self.guard_metrics_supported:
            # instance override: the guarded round also reports its
            # per-round quarantine counters (floats — the fused packed-
            # metric contract)
            self._round_metric_names = tuple(self._round_metric_names) + (
                "clients_dropped", "clients_quarantined")
        # robust_agg: Byzantine-robust replacement for the central
        # weighted mean (robust/aggregation.py — median / trimmed_mean /
        # krum / multikrum / norm_krum over the stacked client deltas).
        # Composes with every agg_impl: on a compressed wire the
        # statistic runs on the wire-DECODED rows
        # (collectives.wire_roundtrip_mat — ranking what the server
        # receives, not what the sender held), and under agg_impl='topk'
        # on the sparsified error-feedback rows. Orthogonal to the
        # transform defenses (defense clips/noises the stacked locals
        # first; the robust statistic then consumes the defended rows)
        # and to the guard (the estimators read the quarantine's
        # renormalized weights as their survivor mask).
        from ..robust.aggregation import ROBUST_AGGS

        if robust_agg not in ROBUST_AGGS:
            raise ValueError(
                f"robust_agg {robust_agg!r} not in {ROBUST_AGGS}")
        self.robust_agg = robust_agg
        if not 0.0 <= float(robust_trim) < 0.5:
            raise ValueError(
                f"robust_trim {robust_trim} must be in [0, 0.5) — "
                "trimming half or more per side leaves no survivors")
        self.robust_trim = float(robust_trim)
        if int(robust_krum_f) < 0:
            raise ValueError(
                f"robust_krum_f {robust_krum_f} must be >= 0 "
                "(0 = auto ceil(0.2 * cohort))")
        self.robust_krum_f = int(robust_krum_f)
        if float(robust_norm_bound) <= 0:
            raise ValueError(
                f"robust_norm_bound {robust_norm_bound} must be > 0")
        self.robust_norm_bound = float(robust_norm_bound)
        self._retry_nonce = 0  # watchdog rollback-retry cohort re-draw
        # eval_clients: sampled-eval mode (SURVEY §7's O(N^2)-eval
        # hard-part): evaluate a fixed seeded subset of clients instead of
        # the whole cohort; 0 = all. Reported means are over the subset.
        self._eval_idx = None
        if eval_clients and eval_clients < self.num_clients:
            self._eval_idx = jnp.asarray(np.sort(
                np.random.RandomState(seed).choice(
                    self.num_clients, eval_clients, replace=False)
            ).astype(np.int32))
        # shape used for parameter init: stored sample shape plus the
        # injected channel axis
        self.init_sample_shape = tuple(data.sample_shape) + (
            (1,) if channel_inject else ())
        # obs_numerics: in-jit training-dynamics telemetry
        # (obs/numerics.py) — per-layer-group update/grad norms,
        # non-finite precursor gauges, per-client drift/cosine, mask
        # dynamics — appended to _round_metric_names as ordinary f32
        # scalars so both the unfused record path and the fused
        # packed-metric transfer carry them sync-free. The plan's layer
        # groups come from the eval_shape params template (no compute);
        # off (the default) is bit-inert. Like every obs knob it never
        # enters run/checkpoint identity.
        self._numerics_plan = None
        if obs_numerics and self.numerics_supported:
            from ..models import init_params
            from ..obs.numerics import NumericsPlan

            template = jax.eval_shape(lambda: init_params(
                self.model, jax.random.PRNGKey(0),
                self.init_sample_shape))
            self._numerics_plan = NumericsPlan.from_params(
                template, slots=self.clients_per_round,
                with_mask=self.numerics_with_mask)
            self._round_metric_names = tuple(self._round_metric_names) \
                + self._numerics_plan.metric_names
        if hp.batching == "epoch":
            from ..parallel.multihost import host_client_counts

            n_biggest = int(np.max(host_client_counts(data.n_train)))
            budget = hp.steps_per_epoch * hp.batch_size
            if budget < n_biggest:
                logger.warning(
                    "epoch batching with steps_per_epoch*batch_size=%d < "
                    "largest client shard (%d): epochs are truncated — each "
                    "epoch trains on a fresh random %d-subset per client "
                    "instead of the full shard (the runner sizes "
                    "steps_per_epoch to ceil(max(n_i)/batch) and never "
                    "hits this)", budget, n_biggest, budget)
        # Training-time augmentation (reference parity: every CIFAR/tiny
        # batch goes through RandomCrop(H,4)+flip, cifar10/data_loader.py:
        # 46-50 — there is no off switch in the reference). "auto" turns it
        # on exactly when the loader declared the dataset augmentable
        # (data.aug_pad_value set); False disables; a callable is used as
        # the (rng, xb) -> xb augmentation directly.
        if callable(augment):
            self.augment_fn = augment
        elif augment in ("auto", True, 1) and \
                getattr(data, "aug_pad_value", None) is not None:
            import functools

            from ..data.cifar import random_crop_flip

            self.augment_fn = functools.partial(
                random_crop_flip, padding=4,
                pad_value=np.asarray(data.aug_pad_value, np.float32))
        else:
            self.augment_fn = None
        self.apply_fn = make_apply_fn(
            model, compute_dtype=self.compute_dtype,
            channel_inject=channel_inject)
        self.eval_client = make_eval_fn(self.apply_fn, loss_type, eval_batch)
        # donate_state: the state-ownership protocol (README "State
        # ownership & donation"). When on (and the algorithm declares
        # donate_supported), the round/finetune/fused/mask entry points
        # take OWNERSHIP of their input state via donate_argnums — the
        # [C, model] personal stack (and topk residual / eval cache)
        # aliases in place instead of being rewritten into a fresh
        # (1+C)-model allocation every call. The caller's input state is
        # INVALID after the call; any caller that deliberately re-runs
        # from a saved state must borrow a copy via clone_state first.
        # Bit-identical to the borrow path (aliasing only) — inert for
        # run identity; pinned by tests/test_donation.py.
        self._donate = bool(donate_state) and self.donate_supported
        # eval_cache: the in-state incremental personal-eval cache
        # (subclasses that support it set self.eval_cache before
        # super().__init__; everyone else is False). Validated here so
        # an unsupported combination dies at construction.
        self.eval_cache = bool(getattr(self, "eval_cache", False))
        if self.eval_cache:
            if not getattr(self, "track_personal", True):
                raise ValueError(
                    f"{self.name}: eval_cache caches the per-client "
                    "personal-eval terms — it needs the personal stack "
                    "(track_personal=True)")
            if self._eval_idx is not None:
                raise ValueError(
                    f"{self.name}: eval_cache indexes the full [C] "
                    "cohort; the sampled-eval subset (eval_clients) "
                    "composes poorly with it — use one or the other")
            # the O(S) in-graph row eval of the round body; an attr so
            # the forward-count test can wrap it and pin the width
            self._eval_cache_rows = self._vmap_clients(
                self.eval_client, in_axes=(0, 0, 0, 0))
        # client_store: the population-residency mode (core/client_store
        # .py — ROADMAP Open item 2). "device" (default) is today's
        # fully-resident layout; "host"/"disk" move the per-client rows
        # (personal_params, topk agg_residual) OFF device: state holds
        # None between rounds, each round attaches a transient [S]
        # cohort slab gathered from the store and stages the trained
        # slab back. The round program is the SAME round_fn traced at
        # slab width — sel_idx becomes stack positions arange(S) and the
        # population ids ride in through _trace_pop_idx for the two
        # reads that need them (fault keying, eval-cache scatter) — so
        # streamed runs are bit-identical to resident runs
        # (tests/test_client_store.py pins it) with HBM flat in C.
        # Residency never enters run identity (inert, like donate_state).
        self._trace_pop_idx = None  # set ONLY while tracing a store round
        self._store = None
        self._round_jit_store = None
        self._store_round_raw = None
        self._store_eval_cache = None   # host (correct, loss_sum, total)
        self._store_eval_dirty: List[np.ndarray] = []
        self._host_data = None          # cached numpy views of the shards
        self._host_test = None
        self.client_store = client_store
        self.store_hot_clients = int(store_hot_clients)
        if client_store != "device":
            from ..core.client_store import STORE_MODES, ClientStore

            if client_store not in ("device",) + STORE_MODES:
                raise ValueError(
                    f"client_store {client_store!r} not in "
                    f"{('device',) + STORE_MODES}")
            if not self.store_supported:
                raise ValueError(
                    f"{self.name}: client_store={client_store!r} needs "
                    "the store-backed round entry (fedavg/salientgrads/"
                    "ditto — the central-aggregate algorithms whose "
                    "per-client rows stream by cohort)")
            if self.clients_per_round >= self.num_clients:
                raise ValueError(
                    f"{self.name}: client_store streams the SAMPLED "
                    "cohort; full participation keeps every row on "
                    "device each round, so there is nothing to stream "
                    "— use client_store='device' (or frac < 1)")
            if self._eval_idx is not None:
                raise ValueError(
                    f"{self.name}: eval_clients indexes the resident "
                    "[C] personal stack; with client_store the stack "
                    "is not resident — use one or the other")
            if not getattr(self, "track_personal", True) \
                    and self.agg_impl != "topk":
                raise ValueError(
                    f"{self.name}: client_store={client_store!r} with "
                    "track_personal=False and no topk residual has no "
                    "per-client rows to stream — drop --client_store "
                    "(the run is already O(S) in device memory)")
            self._store = ClientStore(
                self.num_clients, mode=client_store,
                hot_clients=store_hot_clients, root=store_dir)
            # The residency contract covers the DATA shards too: loaders
            # hand back device-backed [C] stacks (pad_stack ends in
            # jnp.asarray), and a full-[C] x_train alone defeats
            # HBM-flat-in-C before the first round runs. Pull the shards
            # to host once so the device copies free; every store-mode
            # read goes through the numpy views in _store_host_rows.
            self.data = jax.tree_util.tree_map(
                lambda a: np.array(jax.device_get(a), copy=True),
                self.data)
        self._fused_cache: Dict[Any, Any] = {}  # (block, eval_every) -> jit
        self._personal_cache_reset()
        self._build()

    # -- per-algorithm pieces -------------------------------------------------
    @abc.abstractmethod
    def _build(self) -> None:
        """Construct jitted round/eval functions."""

    @abc.abstractmethod
    def init_state(self, rng: jax.Array) -> Any:
        """Build the initial server state (params replicated / stacked)."""

    @abc.abstractmethod
    def run_round(self, state: Any, round_idx: int) -> Any:
        """Execute one federated round; returns (state, train_metrics dict)."""

    def eval_metrics(self, state: Any, x_test, y_test,
                     n_test) -> Dict[str, Any]:
        """Traceable eval hook (the fused round loop calls it in-graph).
        Subclasses implement this, or implement ``_eval_impl(state, x, y,
        n, personal_fn)`` (the algorithms with a partial-participation
        personal stack — the shared wrappers below route it), or override
        ``evaluate``; this guard restores the fail-fast contract that
        de-abstracting ``evaluate`` removed."""
        impl = getattr(self, "_eval_impl", None)
        if impl is not None:
            # traceable: the in-state eval cache when it is live (the
            # O(C)-forwards-free re-reduce), else the full personal
            # eval. Store mode without the cache routes to the host-side
            # store eval (NOT traceable — but the only in-graph caller,
            # the fused eval cadence, is refused with the store)
            pf = self._cache_personal_fn(state) or (
                self._personal_eval_store if self._store is not None
                else self._eval_personal)
            return impl(state, x_test, y_test, n_test, pf)
        raise NotImplementedError(
            f"{type(self).__name__} must implement eval_metrics (traceable"
            " eval over explicit test arrays), _eval_impl, or override"
            " evaluate")

    def evaluate(self, state: Any) -> Dict[str, Any]:
        """Evaluate per the reference protocol (global and/or personal
        per-client accuracy, mean over clients — sailentgrads_api.py:231-285).

        Default: algorithms providing ``_eval_impl`` get the host path
        with the INCREMENTAL personal eval (``_personal_eval_cached``);
        everyone else delegates to the traceable ``eval_metrics`` hook.
        Algorithms with host-side eval composition (DisPFL's per-round
        local tests, FedFomo) override ``evaluate`` directly."""
        d = self.data
        impl = getattr(self, "_eval_impl", None)
        if impl is not None:
            # in-state eval cache first (jitted [C] re-reduce, zero
            # forwards), then the store-backed incremental eval (the
            # personal stack is not resident), then the host-side
            # incremental cache
            pf = self._cache_personal_fn(state, jit=True) or (
                self._personal_eval_store if self._store is not None
                else self._personal_eval_cached)
            return impl(state, d.x_test, d.y_test, d.n_test, pf)
        return self.eval_metrics(state, d.x_test, d.y_test, d.n_test)

    def finalize(self, state: Any):
        """Optional end-of-training pass after the last round. Returns
        ``(state, record_or_None)``; the record (if any) is appended to the
        run history with ``round = -1`` (the reference's convention for the
        FedAvg final fine-tune pass, ``fedavg_api.py:79-88``)."""
        return state, None

    # whether per-client masks change between rounds (DisPFL fire/regrow,
    # SubAvg pruning) — if False the per-round cost record is constant and
    # the runner reuses it instead of pulling params to host every round
    masks_evolve: bool = False

    #: whether this algorithm's _round_jit threads the guard's per-round
    #: quarantine counters into its metric outputs (FedAvg/SalientGrads).
    #: Algorithms sharing _train_selected_weighted without threading the
    #: counters (Ditto's global leg) still get the guard itself.
    guard_metrics_supported: bool = False

    #: whether this algorithm's round body threads the in-jit numerics
    #: telemetry (obs/numerics.py) through its outputs — same support
    #: surface as guard_metrics_supported (the central-aggregate round).
    numerics_supported: bool = False

    #: whether the numerics plan also emits mask dynamics (churn /
    #: cross-client agreement) — static-mask algorithms (SalientGrads)
    numerics_with_mask: bool = False

    #: whether this algorithm's State carries the error-feedback
    #: residual (``agg_residual``) and its round body threads it through
    #: ``_train_selected_weighted`` — the ``agg_impl='topk'`` support
    #: surface (FedAvg/SalientGrads). The residual is real state: it is
    #: checkpointed, and a topk lineage is NOT interchangeable with
    #: other impls' checkpoints (run_identity splits it).
    topk_supported: bool = False

    #: whether this algorithm's jit entry points honor ``donate_state``
    #: (FedAvg/SalientGrads/Ditto — the central-aggregate rounds whose
    #: round bodies return every state field, so donation aliases the
    #: whole state in place). Requires the base ``_fused_data_args``
    #: layout: the donating fused program returns the threaded data
    #: arrays and ``run_rounds_fused`` rebinds ``self.data`` from them.
    donate_supported: bool = False

    #: whether this algorithm's round entry composes with the population
    #: client store (``--client_store host|disk``): its round_fn takes
    #: (state, sel_idx, round_idx, x, y, n[, test...]) with the
    #: per-client rows living on State.personal_params/agg_residual, and
    #: its body is width-polymorphic — the same trace runs at cohort-slab
    #: width [S] with sel_idx = arange(S) (FedAvg/SalientGrads/Ditto).
    store_supported: bool = False

    def clone_state(self, state: Any) -> Any:
        """Borrow API of the state-ownership protocol: a deep on-device
        copy of ``state``. Under ``donate_state`` every round/fused/
        finetune call CONSUMES its input state, so a caller that still
        needs the original afterwards — the watchdog's last-good, a
        bench harness re-running from a saved state, an equivalence
        gate replaying both spellings from one s0 — clones first and
        donates the clone (or donates the original and keeps the
        clone). A same-size copy when donation is off too, so caller
        code stays mode-independent."""
        return jax.tree_util.tree_map(jnp.copy, state)

    def _jit_entry(self, fn, donate=0):
        """jit an entry point under the ownership protocol:
        ``donate_argnums=donate`` when this instance donates, plain jit
        otherwise. Entry points donated here must return (or pass
        through) every input-state leaf so XLA can alias each donated
        buffer to an output — an unmatched donated leaf degrades to a
        copy-with-warning, never to corruption. Donated entries call
        through :func:`_no_persistent_cache_write` (a corrupt
        deserialized donated executable crashes the process — see its
        docstring); ``.lower`` is forwarded for the jaxpr donation
        audit's ``args_info`` introspection."""
        if not self._donate:
            return jax.jit(fn)
        jitted = jax.jit(fn, donate_argnums=donate)

        def entry(*args):
            # every donated entry here is fixed-shape (one compilation
            # per fn: the round's cohort/sel shapes are static, each
            # fused (block, eval_every) is its own fn), so after the
            # first successful call the guard — which briefly mutates
            # process-global jax.config — is skipped
            if entry._compiled:
                return jitted(*args)
            with _no_persistent_cache_write():
                out = jitted(*args)
            entry._compiled = True
            return out

        entry._compiled = False
        entry.lower = jitted.lower
        return entry

    def cost_trained_clients_per_round(self) -> int:
        """Client training passes one round actually runs (cost accounting).
        Default: the sampled subset. Decentralized/personalized algorithms
        that train the whole cohort (DisPFL/DPSGD/FedFomo) or several legs
        per client (Ditto) override this."""
        return self.clients_per_round

    def cost_snapshot(self, state: Any):
        """(params, mask) of one representative client for the per-round
        FLOPs/comm accounting (``stat_info``'s ``sum_training_flops`` /
        ``sum_comm_params``, ``sailentgrads_api.py:137-138``). For stacked
        personalized states the representative is the client whose overall
        mask density is closest to the cohort mean — client 0 would bias
        the counters when densities differ systematically across clients
        (DisPFL ``--diff_spa`` assigns client 0 the sparsest mask)."""
        params = getattr(state, "global_params", None)
        mask = getattr(state, "mask", None)
        rep = 0
        if mask is None:
            masks = getattr(state, "masks", None)
            if masks is not None:
                nz = sum(
                    jnp.count_nonzero(
                        m, axis=tuple(range(1, m.ndim))).astype(jnp.float32)
                    for m in jax.tree_util.tree_leaves(masks))
                dens = nz / jnp.maximum(jnp.sum(nz), 1.0)  # relative is enough
                rep = int(jnp.argmin(jnp.abs(dens - jnp.mean(dens))))
                mask = jax.tree_util.tree_map(lambda m: m[rep], masks)
        if params is None:
            stacked = getattr(state, "personal_params", None)
            if stacked is not None:
                params = jax.tree_util.tree_map(lambda p: p[rep], stacked)
        return params, mask

    # -- shared helpers -------------------------------------------------------
    def _selected_client_indexes(self, round_idx: int) -> np.ndarray:
        """``sample_client_indexes`` plus the full-participation contract
        check: ``_train_selected_weighted`` statically SKIPS the sel_idx
        gathers when ``clients_per_round == num_clients`` (the gathers
        would materialize a second full cohort copy on TPU), so the draw
        must be exactly ``arange(C)`` — a future permuted/sorted draw
        would silently misalign shards, sample weights, and the
        locals_-to-personal_params scatter. Cheap host-side guard
        (ADVICE r5); runs before dispatch, never under trace."""
        # retry passed only when set: the 3-arg call stays the reference
        # contract's exact signature (and test monkeypatch surface)
        with obs_trace.span("sample"):
            sel = sample_client_indexes(
                round_idx, self.num_clients, self.clients_per_round,
                retry=self._retry_nonce) if self._retry_nonce else \
                sample_client_indexes(
                    round_idx, self.num_clients, self.clients_per_round)
        if self.clients_per_round == self.num_clients and \
                not np.array_equal(sel, np.arange(self.num_clients)):
            raise ValueError(
                f"{self.name}: full participation requires sel_idx == "
                f"arange({self.num_clients}) — the round program "
                "statically skips the client gathers on that invariant; "
                f"got {sel!r}")
        return sel

    def set_retry_nonce(self, nonce: int) -> None:
        """Watchdog rollback-retry hook (robust/recovery.py): subsequent
        ``_selected_client_indexes`` draws re-sample the cohort with this
        nonce (0 = the reference draw). The fused path never retries —
        ``_fused_host_inputs`` precomputes draws with whatever nonce is
        set, which the runner pins to 0."""
        self._retry_nonce = int(nonce)

    def _agg_mesh(self):
        """The ``clients`` mesh the data lives on (None off-mesh), for the
        shard_map aggregation paths. Resolved once, lazily: the data is
        placed before the algorithm is built (bench.py / the runner)."""
        if not self._agg_mesh_known:
            from ..parallel.mesh import mesh_of

            self._agg_mesh_val = mesh_of(self.data.x_train)
            self._agg_mesh_known = True
        return self._agg_mesh_val

    def _require_plan(self, what: str):
        if self._agg_sparse_plan is None:
            raise ValueError(
                f"{self.name}: {what} needs a static-mask gather plan "
                "(_agg_sparse_plan) built from the concrete mask before "
                "the round traces — only fixed-mask algorithms "
                "(SalientGrads) support it")
        return self._agg_sparse_plan

    def _aggregate(self, stacked, weights, rng=None):
        """The central weighted mean over the stacked client axis, routed
        by ``agg_impl`` (parallel/collectives.py). ``dense`` is bit-for-
        bit today's ``weighted_tree_sum``; every other impl trades exact
        association (and, for bf16/int8, wire precision — f32 master
        weights and accumulation always) for smaller / pipelined
        cross-chip transfers. Robust defenses already transformed
        ``stacked`` before this point, so they compose with every impl.

        ``topk`` here is the WIRE KERNEL only — top-k selection + reduce
        of whatever ``stacked`` holds (probes and benches time this
        path); the round body's :meth:`_topk_aggregate` owns the
        delta/residual bookkeeping around it."""
        with jax.named_scope("aggregate"):
            if self.agg_impl == "dense":
                from ..core.state import weighted_tree_sum

                return weighted_tree_sum(stacked, weights)
            from ..parallel import collectives

            kw = dict(mesh=self._agg_mesh(),
                      bucket_size=self.agg_bucket_size,
                      overlap=self.agg_overlap,
                      kernels=self.agg_kernels)
            if self.agg_impl == "topk":
                return collectives.topk_weighted_mean(
                    stacked, weights, self.agg_topk_density,
                    plan=self._agg_sparse_plan,
                    sample=self.agg_topk_sample, **kw)[0]
            if self.agg_impl == "hier":
                if self.agg_hier_wire == "sparse":
                    return collectives.sparse_weighted_mean(
                        stacked, weights,
                        self._require_plan("agg_hier_wire='sparse'"),
                        wire="f32", hier_inner=self.agg_hier_inner or -1,
                        **kw)
                return collectives.weighted_mean(
                    stacked, weights, wire=self.agg_hier_wire,
                    hier_inner=self.agg_hier_inner or -1, rng=rng, **kw)
            kw["rng"] = rng
            if self.agg_impl == "sparse":
                return collectives.sparse_weighted_mean(
                    stacked, weights,
                    self._require_plan("agg_impl='sparse'"), **kw)
            wire = {"bucketed": "f32", "bf16": "bf16", "int8": "int8"}[
                self.agg_impl]
            return collectives.weighted_mean(
                stacked, weights, wire=wire, **kw)

    def _robust_wire(self) -> str:
        """The wire format whose decode the robust statistic must rank:
        the agg_impl's cross-chip payload format. f32 for the exact
        impls (dense/bucketed/sparse are bit-equal contractions; topk
        has its own sparsified-row path in :meth:`_topk_aggregate`)."""
        if self.agg_impl in ("bf16", "int8"):
            return self.agg_impl
        if self.agg_impl == "hier" and \
                self.agg_hier_wire in ("bf16", "int8"):
            return self.agg_hier_wire
        return "f32"

    def _robust_aggregate(self, stacked, weights, global_params,
                          rng=None):
        """The ``--robust_agg`` central aggregate: replace the weighted
        mean with a Byzantine-robust statistic over the stacked client
        DELTAS (local − global; the estimators are shift-equivariant, so
        working in delta space changes nothing for median/trimmed-mean/
        Krum selection — but it is what norm_krum's clip stage and the
        wire roundtrip are defined on).

        On a compressed wire (bf16/int8, or hier's cross-slice wire)
        each delta row is first pushed through the wire's encode/decode
        (``collectives.wire_roundtrip_mat``): order statistics do not
        commute with quantization, so the statistic must rank the values
        the server would decode — int8 uses the round's ``agg_rng``
        stochastic-rounding draw, keeping the round bit-deterministic.

        ``lax.cond``-traceable with the same (stacked, weights)
        signature as :meth:`_aggregate`, so ``guard.guarded_aggregate``
        threads it unchanged: quarantine renormalizes the weights
        (quarantined rows exactly 0 — the estimators' survivor mask) and
        ``carry_if_empty`` covers the zero-survivor round."""
        from ..parallel import collectives
        from ..robust.aggregation import robust_combine_mat

        with jax.named_scope("robust_aggregate"):
            spec = collectives.flat_spec(stacked, stacked=True)
            mat = collectives.stacked_to_mat(stacked)
            gvec = collectives.tree_to_vec(global_params).astype(
                jnp.float32)
            deltas = mat - gvec[None]
            deltas = collectives.wire_roundtrip_mat(
                deltas, self._robust_wire(),
                bucket_size=self.agg_bucket_size, rng=rng)
            combined = robust_combine_mat(
                deltas, weights, self.robust_agg,
                trim_frac=self.robust_trim, krum_f=self.robust_krum_f,
                norm_bound=self.robust_norm_bound)
            return collectives.vec_to_tree(gvec + combined, spec)

    def _full_batches(self, hp: Optional[HyperParams] = None) -> bool:
        """Static guarantee for core.trainer's epoch fast path: every
        client's shard covers steps_per_epoch*batch_size samples, so all
        batches are full and all steps active (checked host-side on the
        concrete counts at build time; bit-identical semantics)."""
        hp = hp or self.hp
        if hp.batching != "epoch":
            return False
        from ..parallel.multihost import host_client_counts

        n = host_client_counts(self.data.n_train)
        return bool((n >= hp.steps_per_epoch * hp.batch_size).all())

    def _vmap_clients(self, fn, in_axes):
        """vmap ``fn`` over the leading client axis, optionally chunked.

        On a pod, the full vmap is the right thing: each client's work lands
        on its own device. On fewer devices than clients, the vmapped
        activations of every client are live at once and can exceed HBM
        (AlexNet3D at full ABCD resolution); ``client_chunk`` trades that
        concurrency for a ``lax.map`` over chunks of clients — still one
        jitted program with zero host round-trips.
        """
        vfn = jax.vmap(fn, in_axes=in_axes)
        max_chunk = self.client_chunk
        if not max_chunk:
            return vfn

        def chunked(*args):
            # snap the chunk to the largest divisor of this call's client
            # count (the round uses clients_per_round, the SNIP pass all
            # clients — both shapes are static at trace time)
            first_mapped = next(
                a for ax, a in zip(in_axes, args) if ax is not None
            )
            n = jax.tree_util.tree_leaves(first_mapped)[0].shape[0]
            chunk = min(max_chunk, n)
            while n % chunk:
                chunk -= 1

            if chunk == 1:
                # no reshape: lax.map over the raw client axis. The
                # (C, n, ...) -> (C, 1, n, ...) reshape of the general
                # path materializes a full tiled COPY of the cohort on
                # TPU (measured 10.9 GB for the 32-client ABCD cohort —
                # the copy, not the model, is what OOMed the C=32 cell);
                # per-slice expand_dims inside the scan body is free
                def body1(chunk_args):
                    rebuilt = []
                    si = 0
                    for ax, a in zip(in_axes, args):
                        if ax is None:
                            rebuilt.append(a)  # closed-over, unbatched
                        else:
                            rebuilt.append(jax.tree_util.tree_map(
                                lambda x: x[None], chunk_args[si]))
                            si += 1
                    return jax.tree_util.tree_map(
                        lambda x: x[0], vfn(*rebuilt))

                mapped_in = tuple(
                    a for ax, a in zip(in_axes, args) if ax is not None
                )
                return jax.lax.map(body1, mapped_in)

            def reshape_in(ax, a):
                if ax is None:
                    return a
                return jax.tree_util.tree_map(
                    lambda x: x.reshape((x.shape[0] // chunk, chunk) + x.shape[1:]),
                    a,
                )

            stacked = [reshape_in(ax, a) for ax, a in zip(in_axes, args)]

            def body(chunk_args):
                rebuilt = []
                si = 0
                for ax, a in zip(in_axes, args):
                    if ax is None:
                        rebuilt.append(a)  # closed-over, unbatched
                    else:
                        rebuilt.append(chunk_args[si])
                        si += 1
                return vfn(*rebuilt)

            mapped_in = tuple(
                s for ax, s in zip(in_axes, stacked) if ax is not None
            )
            out = jax.lax.map(body, mapped_in)
            return jax.tree_util.tree_map(
                lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]),
                out,
            )

        return chunked

    def _train_selected_weighted(
        self, client_update, global_params, mask, sel_idx, round_idx,
        round_key, x_train, y_train, n_train, defense=None,
        residual=None,
    ):
        """Shared round body for global-model algorithms (FedAvg,
        SalientGrads): gather the selected clients' shards, broadcast the
        global model (and mask) along the client axis, run vmapped local
        SGD, optionally apply a robust-aggregation defense to the local
        models, and return the sample-weighted average, the (pre-defense)
        local models, the mean loss, the fault/guard stats, and the
        updated error-feedback residual
        (fedavg_api.py:40-117 / sailentgrads_api.py:112-147,212-227).

        Fault tolerance (robust/faults.py + robust/guard.py): when a
        ``fault_spec`` is set, the deterministic injector corrupts the
        stacked local models AFTER training (they model wire/client
        faults); when the guard is on, a single [S] finite-screen plus
        the injector's dropout flags quarantine the unusable clients —
        their rows are select-zeroed, the weights renormalize over the
        survivors, and a survivor count of 0 carries the previous global
        model. Both are pure selects when no client faults, so a guarded
        clean round is bit-identical to the unguarded one — and the
        sanitized tree feeds ``_aggregate`` unchanged, so quarantine
        composes with every ``agg_impl`` wire and the clip/DP defenses.

        The 4th return value is ``None`` when the guard is off, else a
        dict with ``ok`` ([S] survivor flags — callers use it to keep
        quarantined clients' previous personal models) and the f32
        ``clients_dropped`` / ``clients_quarantined`` counters.

        ``residual`` is the [C, ...] error-feedback residual stack
        (``agg_impl='topk'`` only — required there, ignored-and-returned
        otherwise): the 5th return value is the updated stack. The topk
        aggregate runs on compensated deltas and composes with the guard
        by construction — see :meth:`_topk_aggregate`."""
        from ..core.state import broadcast_tree, zeros_like_tree

        if self.clients_per_round == self.num_clients:
            # full participation: sample_client_indexes always returns
            # arange (base.py early return), so the gathers are identity
            # — and jnp.take on the cohort materializes a second full
            # copy on TPU (measured 9.1 GB at C=32 full volume, the OOM
            # line of the clients32 cell). Statically skip them.
            n_sel, x_sel, y_sel = n_train, x_train, y_train
        else:
            n_sel = jnp.take(n_train, sel_idx)
            x_sel = jnp.take(x_train, sel_idx, axis=0)
            y_sel = jnp.take(y_train, sel_idx, axis=0)
        if self.labelflip_fn is not None:
            # label-flip poisons the DATA PATH (before training — the
            # other fault kinds corrupt what leaves the client, this one
            # corrupts what the client learns from). Keys off the
            # population client id like the injector.
            lf_idx = sel_idx if self._trace_pop_idx is None \
                else self._trace_pop_idx
            y_sel = self.labelflip_fn(y_sel, lf_idx, round_idx)
        s = sel_idx.shape[0]
        params0 = broadcast_tree(global_params, s)
        mask_b = broadcast_tree(mask, s)
        mom0 = zeros_like_tree(params0)
        keys = jax.random.split(round_key, s + 1)
        # named_scope: trace-time HLO metadata only (zero runtime cost,
        # numerics untouched) — labels the round's phases on the XLA
        # device trace so they line up with the obs host spans
        with jax.named_scope("local_train"):
            params_out, _, losses = self._vmap_clients(
                client_update, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0)
            )(params0, mom0, mask_b, keys[:s], x_sel, y_sel, n_sel,
              round_idx, params0)
        dropped = None
        if self.fault_fn is not None:
            # inject AFTER training: faults model what leaves the client
            # (dropout, partial work, NaN poison, Byzantine scaling), so
            # the faulted tree is also what the personal stack would see.
            # The injector keys each fault off the POPULATION client id;
            # in store mode sel_idx is slab positions arange(S), so the
            # ids ride in via _trace_pop_idx — same values as resident.
            fault_idx = sel_idx if self._trace_pop_idx is None \
                else self._trace_pop_idx
            params_out, dropped = self.fault_fn(
                params_out, global_params, fault_idx, round_idx)
        # the defense guards the *aggregate*; each client's own (personal)
        # model stays its locally-trained weights, as in the reference where
        # w_per_mdls is set before any server-side processing
        defended = params_out
        if defense is not None:
            defended = defense.apply(params_out, global_params, keys[s])
        weights = n_sel.astype(jnp.float32)
        weights = weights / jnp.maximum(jnp.sum(weights), 1.0)
        agg_rng = None
        if self.agg_impl == "int8" or (
                self.agg_impl == "hier"
                and self.agg_hier_wire == "int8"):
            # stochastic-rounding draw; folded off round_key so the
            # client/defense key consumption (and hence the default
            # path's numerics) is untouched
            agg_rng = jax.random.fold_in(round_key, 0x616767)  # "agg"
        fstats = None
        ok = None
        if self.guard_enabled:
            from ..robust import guard as _guard

            with jax.named_scope("guard"):
                finite = _guard.finite_screen(defended)
                if dropped is not None:
                    ok = jnp.logical_and(finite, jnp.logical_not(dropped))
                    n_dropped = jnp.sum(dropped.astype(jnp.float32))
                    # quarantined = screened by the finite guard among the
                    # clients that did report (dropouts counted separately)
                    n_quar = jnp.sum(jnp.logical_and(
                        jnp.logical_not(finite), jnp.logical_not(dropped)
                    ).astype(jnp.float32))
                else:
                    ok = finite
                    n_dropped = jnp.asarray(0.0, jnp.float32)
                    n_quar = jnp.sum(
                        jnp.logical_not(finite).astype(jnp.float32))
            fstats = {"ok": ok, "clients_dropped": n_dropped,
                      "clients_quarantined": n_quar}
        if self.robust_agg != "none" and self.agg_impl != "topk":
            # the robust statistic REPLACES the weighted mean; same
            # (stacked, weights) signature, so the guard threads it
            # through guarded_aggregate unchanged
            def agg_fn(st, wv):
                return self._robust_aggregate(
                    st, wv, global_params, agg_rng)
        else:
            def agg_fn(st, wv):
                return self._aggregate(st, wv, agg_rng)
        if self.agg_impl == "topk":
            new_global, new_residual = self._topk_aggregate(
                defended, global_params, residual, sel_idx, weights, ok)
        elif self.guard_enabled:
            from ..robust import guard as _guard

            new_global = _guard.guarded_aggregate(
                defended, weights, ok, agg_fn, global_params)
            new_residual = residual
        else:
            new_global = agg_fn(defended, weights)
            new_residual = residual
        return (new_global, params_out, jnp.mean(losses), fstats,
                new_residual)

    def _topk_aggregate(self, locals_, global_params, residual, sel_idx,
                        weights, ok):
        """The ``agg_impl='topk'`` round aggregate with error feedback
        (Deep Gradient Compression semantics on the federated round):

        1. each selected client's delta = local − global, COMPENSATED by
           its carried residual row;
        2. per-leaf-group top-k selection + weighted mean of the
           sparsified rows (``collectives.topk_weighted_mean`` — the
           wire);
        3. the unsent remainder (compensated − sparsified) becomes the
           client's new residual row — nothing is dropped, only
           deferred;
        4. ``new_global = global + aggregate(sparsified)``.

        Guard composition (``ok`` = the finite screen's survivor flags,
        None when the guard is off): quarantined rows are select-zeroed
        BEFORE selection and the weights renormalize over survivors —
        the same ``lax.cond``-gated spelling as
        ``guard.guarded_aggregate``, so a clean round runs topk on the
        untouched inputs (bit-identical to guard-off) and never pays
        the O(C x params) sanitize/merge; zero survivors carries the
        previous global; and a quarantined client's residual row keeps
        its PREVIOUS value (``guard.merge_residual`` — the poisoned
        compensated delta must not leak into later rounds through the
        residual)."""
        from ..core.state import tree_index, tree_scatter_update
        from ..parallel import collectives
        from ..robust import guard as _guard

        if residual is None:
            raise ValueError(
                f"{self.name}: agg_impl='topk' round body called without "
                "the residual stack — init_state must seed "
                "State.agg_residual (zeros_like the personal stack "
                "layout) when agg_impl='topk'")
        full = self.clients_per_round == self.num_clients
        # full participation skips the identity gather (the same
        # second-cohort-copy hazard as the data gathers above)
        res_sel = residual if full else tree_index(residual, sel_idx)
        comp = jax.tree_util.tree_map(
            lambda loc, g, r: (loc - g[None]) + r,
            locals_, global_params, res_sel)
        if self._agg_sparse_plan is not None:
            # static-mask composition: dead coordinates never ship (the
            # compressed selection can't see them), so they must not
            # enter the residual either — a select against the plan's
            # live mask (round 0's dense init would otherwise sit in
            # the residual forever)
            comp = collectives.plan_dead_select(
                comp, self._agg_sparse_plan)
        def run_topk(comp_in, w):
            if self.robust_agg != "none":
                # robust statistic under error feedback: sparsify each
                # client's compensated delta as usual (the wire), then
                # combine the SPARSIFIED rows robustly instead of
                # weighted-mean — a rejected client's shipped
                # coordinates still leave its residual (EF subtracts
                # what was SENT, not what the server accepted; the
                # rejected mass is simply gone, which is the point)
                from ..robust.aggregation import robust_combine_mat

                sp = collectives.topk_sparsify(
                    comp_in, self.agg_topk_density,
                    plan=self._agg_sparse_plan,
                    bucket_size=self.agg_bucket_size,
                    sample=self.agg_topk_sample)
                agg_update = collectives.vec_to_tree(
                    robust_combine_mat(
                        collectives.stacked_to_mat(sp), w,
                        self.robust_agg, trim_frac=self.robust_trim,
                        krum_f=self.robust_krum_f,
                        norm_bound=self.robust_norm_bound),
                    collectives.flat_spec(sp, stacked=True))
            else:
                agg_update, sp = collectives.topk_weighted_mean(
                    comp_in, w, self.agg_topk_density,
                    plan=self._agg_sparse_plan, mesh=self._agg_mesh(),
                    bucket_size=self.agg_bucket_size,
                    overlap=self.agg_overlap,
                    sample=self.agg_topk_sample)
            new_global = jax.tree_util.tree_map(
                lambda g, u: (g + u).astype(g.dtype), global_params,
                agg_update)
            new_rows = jax.tree_util.tree_map(
                lambda c, s: c - s, comp_in, sp)
            return new_global, new_rows

        if ok is None:
            new_global, new_rows = run_topk(comp, weights)
        else:
            # the guarded dense path's lax.cond spelling
            # (guard.guarded_aggregate): the clean branch runs topk on
            # the untouched inputs, so a clean round never pays the
            # O(C x params) quarantine sanitize / residual merge — only
            # the read-only finite screen that produced ``ok``
            def bad(args):
                c, wv = args
                comp_in, w, survivors = _guard.quarantine(c, wv, ok)
                ng, nr = run_topk(comp_in, w)
                ng = _guard.carry_if_empty(ng, global_params, survivors)
                nr = _guard.merge_residual(ok, nr, res_sel)
                return ng, nr

            new_global, new_rows = jax.lax.cond(
                jnp.logical_not(jnp.all(ok)), bad,
                lambda args: run_topk(*args), (comp, weights))
        new_residual = new_rows if full else tree_scatter_update(
            residual, sel_idx, new_rows)
        return new_global, new_residual

    def _guarded_personal_update(self, personal, locals_, sel_idx, fstats):
        """Scatter the selected clients' trained models into the [C, ...]
        personal stack (w_per_mdls semantics), guard-aware: quarantined /
        dropped clients never delivered an update, so their previous
        personal rows are kept (and NaN poison stays out of the stack).
        Shared by every round_fn that carries a personal stack."""
        if personal is None:
            return None
        from ..core.state import tree_scatter_update

        upd = locals_
        if fstats is not None:
            from ..robust import guard as _guard

            upd = _guard.merge_updates(
                fstats["ok"], locals_, personal, sel_idx)
        return tree_scatter_update(personal, sel_idx, upd)

    def _numerics_outputs(self, old_global, new_global, locals_,
                          mask=None):
        """The in-jit numerics telemetry scalars (obs/numerics.py) for
        this round, in ``_round_metric_names`` order — ``()`` when
        ``--obs_numerics`` is off (bit-inert). Computed on the round's
        already-live arrays under its own ``named_scope`` so the XLA
        device trace labels the readout alongside local_train / guard /
        aggregate."""
        if self._numerics_plan is None:
            return ()
        with jax.named_scope("numerics"):
            return self._numerics_plan.compute(
                old_global, new_global, locals_, mask=mask)

    def _round_outputs(self, state, mean_loss, fstats, numerics=()):
        """A round_fn's return tuple, matching ``_round_metric_names``:
        ``(state, train_loss)`` plus the guard's per-round counters when
        this algorithm threads them (guard_metrics_supported), plus the
        in-jit numerics scalars when ``--obs_numerics`` is on."""
        if fstats is None or not self.guard_metrics_supported:
            return (state, mean_loss) + tuple(numerics)
        return (state, mean_loss, fstats["clients_dropped"],
                fstats["clients_quarantined"]) + tuple(numerics)

    def _train_stacked(self, client_update, params_stack, mask_stack,
                       round_idx, round_key, x, y, n, prox_target=None):
        """Every client trains its own stacked state on its own shard —
        the whole-cohort local-training pass used by the decentralized /
        personalized algorithms (DisPFL, DPSGD, FedFomo, Local, Ditto's
        personal leg). Returns (params_stack, momentum_stack, losses[C])."""
        from ..core.state import zeros_like_tree

        c = jax.tree_util.tree_leaves(params_stack)[0].shape[0]
        keys = jax.random.split(round_key, c)
        mom0 = zeros_like_tree(params_stack)
        if prox_target is None:
            prox_target = params_stack
        return self._vmap_clients(
            client_update, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0)
        )(params_stack, mom0, mask_stack, keys, x, y, n, round_idx,
          prox_target)

    def _make_global_eval(self):
        eval_client = self.eval_client
        eval_idx = self._eval_idx

        @jax.jit
        def eval_all(params, x_test, y_test, n_test):
            if eval_idx is not None:  # sampled-eval subset
                x_test = jnp.take(x_test, eval_idx, axis=0)
                y_test = jnp.take(y_test, eval_idx, axis=0)
                n_test = jnp.take(n_test, eval_idx)
            correct, loss_sum, total = jax.vmap(
                lambda x, y, n: eval_client(params, x, y, n)
            )(x_test, y_test, n_test)
            totals = jnp.maximum(total, 1)
            acc = correct.astype(jnp.float32) / totals
            return {
                "acc_per_client": acc,
                "acc": jnp.mean(acc),
                "loss": jnp.sum(loss_sum) / jnp.maximum(jnp.sum(total), 1),
            }

        return eval_all

    # -- incremental personal eval --------------------------------------------
    # At frac<1 only the TRAINED clients' personal models change per round
    # (w_per_mdls semantics), so the per-round personal eval can reuse the
    # previous per-client (correct, loss_sum, total) for unsampled clients
    # and re-evaluate only the clients trained since the last eval —
    # O(rounds_since_eval x clients_per_round) forwards instead of O(C).
    # The cache lives OUTSIDE the algorithm State (not checkpointed, not in
    # the fused scan carry): validity is guarded by object identity — the
    # cache applies only to the exact personal_params object produced by
    # this algorithm's own run_round chain, so evaluating any other state
    # (a restored checkpoint, a saved earlier state, a finalize output)
    # falls back to the full eval and reseeds. Accuracies are bitwise
    # identical to the full eval (integer counts / totals over identical
    # params); losses agree to f32 round-off — the subset-width eval
    # program may reassociate a client's loss-sum reduction vs the
    # full-width program (measured 1 ulp; the same tolerance the
    # fused-vs-unfused eval gate carries). tests/test_cost_personal.py
    # pins both.

    def _personal_cache_reset(self) -> None:
        self._pers_cache = None       # (correct[C], loss_sum[C], total[C])
        self._pers_expected = None    # the personal_params object cached
        self._pers_dirty: List[np.ndarray] = []  # sel draws since last eval

    def _note_personal_update(self, old_pers, new_pers, sel_idx) -> None:
        """Called by run_round after the round program is dispatched:
        ``new_pers`` differs from ``old_pers`` only at ``sel_idx``."""
        if old_pers is None or new_pers is None:
            return
        if self._eval_idx is not None:
            # sampled-eval mode never uses the cache — don't accumulate
            # an unbounded dirty list for a statically-disabled path
            return
        if self._pers_expected is not old_pers:
            # unknown lineage (fresh state, resume, fused block):
            # the next eval reseeds from a full pass
            self._pers_cache = None
            self._pers_dirty = []
        self._pers_dirty.append(np.asarray(sel_idx))
        self._pers_expected = new_pers

    def _personal_eval_cached(self, pers, x_test, y_test, n_test):
        """Personal-eval protocol result, incrementally when valid."""
        if (self._pers_cache is None or pers is not self._pers_expected
                or self._eval_idx is not None):
            # full pass (also the sampled-eval mode — its subset indexing
            # composes poorly with the per-client cache)
            ev = self._eval_personal(pers, x_test, y_test, n_test)
            if self._eval_idx is None:
                self._pers_cache = (ev["correct"], ev["loss_sum"],
                                    ev["total"])
                self._pers_expected = pers
                self._pers_dirty = []
            return ev
        dirty = np.concatenate(self._pers_dirty) if self._pers_dirty \
            else np.zeros((0,), np.int32)
        if dirty.size >= self.num_clients:
            ev = self._eval_personal(pers, x_test, y_test, n_test)
        elif dirty.size == 0:
            # nothing changed since the last eval (e.g. the finalize
            # re-eval): recompute the protocol means from the cached
            # per-client terms — same [C]-shaped reductions, no forwards
            if not hasattr(self, "_pers_metrics_fn"):
                self._pers_metrics_fn = jax.jit(_personal_metrics)
            ev = self._pers_metrics_fn(*self._pers_cache)
        else:
            if not hasattr(self, "_eval_personal_merge_fn"):
                self._eval_personal_merge_fn = \
                    self._make_personal_eval_merge()
            ev = self._eval_personal_merge_fn(
                pers, jnp.asarray(dirty.astype(np.int32)),
                *self._pers_cache, x_test, y_test, n_test)
        self._pers_cache = (ev["correct"], ev["loss_sum"], ev["total"])
        self._pers_expected = pers
        self._pers_dirty = []
        return ev

    def _make_personal_eval_merge(self):
        """jit: evaluate ONLY the ``sel`` clients' personal models, merge
        into the cached per-client arrays, return the protocol metrics
        (identical reductions to ``_make_personal_eval``). Duplicate
        entries in ``sel`` recompute identical values — harmless."""
        eval_client = self.eval_client
        vmapped = self._vmap_clients(eval_client, in_axes=(0, 0, 0, 0))

        @jax.jit
        def eval_merge(params_stack, sel, correct, loss_sum, total,
                       x_test, y_test, n_test):
            from ..core.state import tree_index

            sub = tree_index(params_stack, sel)
            c_s, l_s, t_s = vmapped(
                sub, jnp.take(x_test, sel, axis=0),
                jnp.take(y_test, sel, axis=0), jnp.take(n_test, sel))
            correct = correct.at[sel].set(c_s)
            loss_sum = loss_sum.at[sel].set(l_s)
            total = total.at[sel].set(t_s)
            return _personal_metrics(correct, loss_sum, total)

        return eval_merge

    def _make_personal_eval(self):
        """Eval stacked per-client params, each on its own client's test
        set. Runs through ``_vmap_clients`` so ``client_chunk`` bounds the
        concurrent per-client activations — personal eval carries
        per-client WEIGHTS, so XLA cannot fold the client axis into the
        conv batch the way the shared-params global eval does, and the
        full vmap at ABCD volume would hold every client's eval
        activations at once."""
        eval_client = self.eval_client
        eval_idx = self._eval_idx
        vmapped = self._vmap_clients(eval_client, in_axes=(0, 0, 0, 0))

        @jax.jit
        def eval_personal(params_stack, x_test, y_test, n_test):
            if eval_idx is not None:  # sampled-eval subset
                from ..core.state import tree_index

                params_stack = tree_index(params_stack, eval_idx)
                x_test = jnp.take(x_test, eval_idx, axis=0)
                y_test = jnp.take(y_test, eval_idx, axis=0)
                n_test = jnp.take(n_test, eval_idx)
            correct, loss_sum, total = vmapped(
                params_stack, x_test, y_test, n_test
            )
            return _personal_metrics(correct, loss_sum, total)

        return eval_personal

    # -- in-state incremental personal eval (--eval_cache) --------------------
    # The host-side cache above cannot ride the fused scan (its validity
    # is object identity) and dies with the process. eval_cache moves
    # the per-client (correct, loss_sum, total) terms INTO algorithm
    # state: the round body evaluates ONLY the trained clients' post-
    # guard personal rows and scatters them into the cache — O(S)
    # forwards per round instead of O(C) per eval — and the eval (host
    # or in the fused cond branch) is a [C] re-reduce with ZERO
    # forwards. Because the cache is state, it checkpoints, resumes,
    # rides the fused carry, and rolls back with the watchdog (a
    # rolled-back round's cache rows are discarded with the state —
    # a poisoned attempt can never leave a row behind). Quarantined
    # clients keep their previous personal rows (merge_updates), so
    # their re-evaluated cache rows reproduce the previous values:
    # poison-free by construction. State-schema change: eval_cache
    # lineages split both identities ('evcache' — the r5 track_personal
    # / PR-7 agg_residual migration pattern).

    def _seed_eval_cache(self, personal):
        """Initial cache: one full personal eval of the fresh stack
        (a one-time O(C) pass at init; every later round pays O(S))."""
        if not self.eval_cache or personal is None:
            return None
        d = self.data
        ev = self._eval_personal(personal, d.x_test, d.y_test, d.n_test)
        return {"correct": ev["correct"], "loss_sum": ev["loss_sum"],
                "total": ev["total"]}

    def _update_eval_cache(self, cache, new_personal, sel_idx,
                           x_test, y_test, n_test):
        """In-graph cache refresh (round body): evaluate the selected
        clients' (post-guard) personal rows, scatter into the cache.
        Full participation updates every row in place (the sel gathers
        would materialize a second stack copy — same hazard as the
        training-data gathers)."""
        if cache is None:
            return None
        from ..core.state import tree_index

        with jax.named_scope("eval_cache"):
            if self.clients_per_round == self.num_clients:
                c, ls, t = self._eval_cache_rows(
                    new_personal, x_test, y_test, n_test)
                return {"correct": c, "loss_sum": ls, "total": t}
            # store mode: sel_idx addresses the [S] slab (stack
            # positions; the gathers below are identity over the slab
            # and the test rows arrive pre-gathered at the same width),
            # while the [C] cache scatter needs the population ids the
            # store wrapper parked in _trace_pop_idx. Same indices, same
            # values, same width-S eval program as resident.
            scatter_idx = sel_idx if self._trace_pop_idx is None \
                else self._trace_pop_idx
            sub = tree_index(new_personal, sel_idx)
            c, ls, t = self._eval_cache_rows(
                sub, jnp.take(x_test, sel_idx, axis=0),
                jnp.take(y_test, sel_idx, axis=0),
                jnp.take(n_test, sel_idx))
            return {"correct": cache["correct"].at[scatter_idx].set(c),
                    "loss_sum": cache["loss_sum"].at[scatter_idx].set(ls),
                    "total": cache["total"].at[scatter_idx].set(t)}

    def _cache_personal_fn(self, state, jit: bool = False):
        """The personal-eval fn backed by ``state.eval_cache`` (the
        zero-forwards [C] re-reduce), or None when the cache is off or
        not live on this state (e.g. post-finetune, where the stack was
        retrained wholesale and finalize dropped the stale cache) — the
        caller then falls back to the full/host-cached eval."""
        cache = getattr(state, "eval_cache", None)
        if not self.eval_cache or cache is None:
            return None
        if jit and not hasattr(self, "_pers_metrics_fn"):
            self._pers_metrics_fn = jax.jit(_personal_metrics)
        fn = self._pers_metrics_fn if jit else _personal_metrics

        def from_cache(_pers, _x, _y, _n):
            return fn(cache["correct"], cache["loss_sum"],
                      cache["total"])

        return from_cache

    # -- fused multi-round execution ------------------------------------------
    #: True for algorithms whose only host-side per-round work is the
    #: seeded client draw; their whole round block can run as ONE jitted
    #: program (an outer ``lax.scan`` over rounds — the TPU-idiomatic
    #: extension of "no Python between clients" to "no Python between
    #: rounds"). The draws stay host-precomputed with the exact
    #: ``np.random.seed(round_idx)`` calls of the unfused path, so the
    #: reference's cross-algorithm sampling contract (fedavg_api.py:92-100)
    #: is preserved bit-for-bit.
    supports_fused: bool = False

    #: names for the scalars ``_round_jit`` returns after the state
    _round_metric_names = ("train_loss",)

    def _fused_host_inputs(self, round_idx: int):
        """The per-round host-side inputs of ``run_round``, to be stacked
        along a leading round axis for the fused scan. Standard centralized
        algorithms: the seeded (contract-checked) client draw."""
        return (self._selected_client_indexes(round_idx),)

    def _fused_data_args(self):
        """Round-invariant device args of ``_round_jit`` after round_idx."""
        d = self.data
        return (d.x_train, d.y_train, d.n_train)

    def _get_fused_fn(self, block: int, eval_every: int,
                      store: bool = False):
        """Build (and cache per (block, eval_every)) the jitted K-round
        program: ``lax.scan`` over the round body with the eval cadence
        folded in-graph via ``lax.cond`` (zero host round-trips inside a
        block; the reference's ``frequency_of_the_test`` cadence,
        main_sailentgrads.py:90).

        Memory structure (the C=32 OOM fix): the cohort data (and, when
        the eval cadence or the eval cache consumes them, the test
        arrays) ride the scan CARRY as explicit pass-through loop state
        instead of closed-over body constants. A closure constant of a
        scan body lowers to a while-loop invariant that XLA must COPY
        into the loop's buffer space when the jit parameter cannot be
        aliased — the "second cohort copy" that OOMed the C=32 cell
        (bench.py ``_try_fused``). As loop state returned unchanged, the
        buffers alias in-place through the loop; with ``donate_state``
        the whole chain aliases — jit parameter -> loop state -> output
        (the program returns the threaded arrays, and
        ``run_rounds_fused`` rebinds ``self.data`` to the aliased
        outputs so the caller's view stays valid)."""
        cache = self._fused_cache
        key = (block, eval_every, store)
        if key in cache:
            return cache[key]
        # store=True: same program shape over the block-union [U] slab —
        # the two host inputs per round are (slab positions, population
        # ids) instead of the single resident draw, the data args are
        # the union's [U] rows instead of the full cohort, and the round
        # call is the store wrapper (parks the population ids in
        # _trace_pop_idx around the unchanged round_fn). Within-block
        # row chaining rides the carried slab exactly as it rides the
        # carried [C] stack resident — bit-identical by construction.
        n_host = 2 if store else len(self._fused_host_inputs(0))
        n_data = len(self._fused_data_args())
        # test arrays enter the loop only when consumed (eval cadence
        # in-graph, or the per-round eval-cache update); an eval-free
        # block without the cache drops them entirely so they are not
        # made loop-resident for nothing
        use_test = bool(eval_every) or self.eval_cache
        # calling the RAW round fn (not its jitted wrapper) inside the
        # scan body: same primitives inlined, and it keeps a donated
        # _round_jit's donate_argnums from being re-interpreted inside
        # an outer trace
        if store:
            self._get_store_round_jit()  # builds _store_round_raw
            round_call = self._store_round_raw
        else:
            round_call = getattr(self, "_round_fn", None) or \
                self._round_jit

        def fused(state, host_stack, round_ids, *args):
            def body(carry, xs):
                s, data_args, test_args = carry
                hins, r = xs[:n_host], xs[n_host]
                extra = test_args if self.eval_cache else ()
                out = round_call(s, *hins, r, *data_args, *extra)
                s, metrics = out[0], out[1:]
                # fail fast if a subclass's _round_jit outputs drifted from
                # its _round_metric_names — dict(zip(...)) would silently
                # drop or mislabel metrics (ADVICE r4). An explicit raise,
                # not assert: python -O must not strip the trace-time
                # contract (ADVICE r5)
                if len(metrics) != len(self._round_metric_names):
                    raise ValueError(
                        f"{type(self).__name__}._round_jit returned "
                        f"{len(metrics)} metrics but _round_metric_names "
                        f"has {len(self._round_metric_names)}")
                ys = dict(zip(self._round_metric_names, metrics))
                if eval_every:
                    # branches defined HERE so the test arrays they read
                    # are the carry's loop-state views, not hoisted
                    # closure constants (the second-copy hazard again)
                    def eval_branch(sb):
                        return {k: v for k, v in
                                self.eval_metrics(sb, *test_args).items()
                                if not k.startswith("acc_per")}

                    def zero_branch(sb):
                        shapes = jax.eval_shape(eval_branch, sb)
                        return jax.tree_util.tree_map(
                            lambda t: jnp.zeros(t.shape, t.dtype), shapes)

                    do = (r.astype(jnp.int32) + 1) % eval_every == 0
                    ys["eval"] = jax.lax.cond(
                        do, eval_branch, zero_branch, s)
                return (s, data_args, test_args), ys

            carry0 = (state, args[:n_data],
                      args[n_data:] if use_test else ())
            (state, data_out, test_out), ys = jax.lax.scan(
                body, carry0, host_stack + (round_ids,))
            # pack every per-round scalar series into ONE f32 array: the
            # host materializes a block's metrics in a single transfer
            # (on a tunneled TPU each leaf fetch costs ~110 ms — measured
            # 442 ms for 4 leaves — so per-leaf fetches would eat the
            # fusion win). CONTRACT: every _round_metric_names /
            # eval_metrics leaf must be an inexact (floating) scalar — the
            # f32 cast is the canonical record dtype, and an int/bool
            # metric would be silently coerced (raised here, ADVICE r4;
            # explicit raise so python -O cannot strip it, ADVICE r5)
            for x in jax.tree_util.tree_leaves(ys):
                if not jnp.issubdtype(x.dtype, jnp.inexact):
                    raise TypeError(
                        f"per-round metrics must be floating (got "
                        f"{x.dtype}); the packed single-transfer stack "
                        "records f32")
            packed = jnp.stack([
                x.astype(jnp.float32)
                for x in jax.tree_util.tree_leaves(ys)])
            if self._donate:
                # return the threaded arrays so every donated input has
                # an aliasable output (run_rounds_fused rebinds
                # self.data to these — the caller's data stays valid)
                return state, ys, packed, data_out + test_out
            return state, ys, packed

        if self._donate:
            donated = (0,) + tuple(range(
                3, 3 + n_data + (3 if use_test else 0)))
            # _jit_entry: donation + the persistent-cache guard +
            # forwarded .lower for the donation audit
            fn = cache[key] = self._jit_entry(fused, donate=donated)
        else:
            fn = cache[key] = jax.jit(fused)
        return fn

    def run_rounds_fused(self, state: Any, start_round: int,
                         n_rounds: int, eval_every: int = 0):
        """Run ``n_rounds`` federated rounds as one jitted program.

        Returns ``(state, ys)`` where ``ys`` is a :class:`FusedMetrics`:
        indexing it (or calling ``.materialize()``) fetches the whole
        block's metric series in ONE host transfer as a pytree of numpy
        arrays with a leading round axis of length ``n_rounds``. When
        ``eval_every`` is set, ``ys["eval"]`` holds the eval metrics
        (zeros on non-eval rounds — ``lax.cond`` skips their compute).
        Semantically identical to ``n_rounds`` ``run_round`` calls
        (tests/test_fused_rounds.py pins it); the win is dispatch/fetch
        amortization: one program launch and one metric materialization
        per block instead of per round.

        Ownership: under ``donate_state`` this call CONSUMES ``state``
        (and the current ``self.data`` arrays — they are donated into
        the scan carry and ``self.data`` is rebound to the aliased
        outputs). Callers re-running from a saved state must
        ``clone_state`` first; callers holding the pre-call data arrays
        must re-read them from ``self.data``.
        """
        if self._store is not None:
            return self._run_rounds_fused_store(
                state, start_round, n_rounds, eval_every)
        if not self.supports_fused:
            raise ValueError(
                f"{self.name}: fused rounds need every per-round host "
                "input to be a pure function of round_idx; this "
                "algorithm's host work is data-DEPENDENT (FedFomo biases "
                "its neighbor draw by accumulated weights read back from "
                "device, fedfomo_api.py:130-144; TurboAggregate's "
                "share/reconstruct protocol is host-interactive) — run "
                "it with fuse_rounds=1")
        host = [self._fused_host_inputs(r)
                for r in range(start_round, start_round + n_rounds)]
        host_stack = tuple(
            jnp.asarray(np.stack([h[i] for h in host]))
            for i in range(len(host[0])))
        round_ids = jnp.arange(
            start_round, start_round + n_rounds, dtype=jnp.float32)
        fn = self._get_fused_fn(n_rounds, eval_every)
        out = fn(
            state, host_stack, round_ids,
            *self._fused_data_args(), self.data.x_test,
            self.data.y_test, self.data.n_test)
        if self._donate:
            state, ys, packed, rets = out
            self._adopt_fused_args(rets)
        else:
            state, ys, packed = out
        return state, FusedMetrics(ys, packed)

    def _adopt_fused_args(self, rets) -> None:
        """Rebind ``self.data`` to the donated fused program's aliased
        pass-through outputs (same buffers, fresh valid handles). The
        base ``_fused_data_args`` layout (x/y/n train) is the
        donate_supported contract; the test triplet is present exactly
        when the program consumed it."""
        n_data = len(self._fused_data_args())
        d, t = rets[:n_data], rets[n_data:]
        kw = dict(x_train=d[0], y_train=d[1], n_train=d[2])
        if t:
            kw.update(x_test=t[0], y_test=t[1], n_test=t[2])
        self.data = self.data.replace(**kw)

    # -- population client store (--client_store host|disk) -------------------
    # The round program in store mode IS the resident round program with
    # the [C] axis replaced by the cohort slab: sel_idx = arange(S)
    # (unfused) or the block-union stack positions (fused), so every
    # slab gather in the round body is an identity/slab-local take of
    # rows whose VALUES match what the resident gather would have
    # produced — jnp.take of equal rows + the same vmapped per-row math
    # at the same width + the same reductions is bit-identical output.
    # The two places the body needs POPULATION ids (fault keying, the
    # [C] eval-cache scatter) read them from _trace_pop_idx, parked by
    # the wrapper below for the duration of the trace. Quarantined slab
    # rows keep their previous values in the round body (merge_updates /
    # merge_residual) and are staged back unchanged, so the store ends
    # up holding the pre-poison value: the no-poison-leak pin extends to
    # host RAM and disk by construction.

    def _get_store_round_jit(self):
        """The jitted store-mode round entry: the UNCHANGED round_fn
        traced at slab width behind the population-id wrapper. Donates
        its state arg exactly like ``_round_jit`` — under donate_state
        the cohort slab MOVES through the round rather than copying."""
        if self._round_jit_store is None:
            raw = getattr(self, "_round_fn", None)
            if raw is None:
                raise ValueError(
                    f"{self.name}: client_store needs the raw round fn "
                    "(self._round_fn) to wrap")

            def store_round(state, stack_idx, pop_idx, round_idx,
                            *row_args):
                self._trace_pop_idx = pop_idx
                try:
                    return raw(state, stack_idx, round_idx, *row_args)
                finally:
                    self._trace_pop_idx = None

            self._store_round_raw = store_round
            self._round_jit_store = self._jit_entry(store_round)
        return self._round_jit_store

    def _store_host_rows(self, test: bool = False):
        """Cached host (numpy) views of the training/test shards: store
        mode never materializes the full [C] data on device — each
        round's [S] rows are host-side ``np.take`` copies, device_put as
        part of the gather. On numpy-backed data (the population-scale
        path) the cache is a zero-copy view."""
        d = self.data
        if test:
            if self._host_test is None:
                self._host_test = (np.asarray(d.x_test),
                                   np.asarray(d.y_test),
                                   np.asarray(d.n_test))
            return self._host_test
        if self._host_data is None:
            self._host_data = (np.asarray(d.x_train),
                               np.asarray(d.y_train),
                               np.asarray(d.n_train))
        return self._host_data

    def _store_gather_rows(self, state, ids):
        """Host->device staging for one round/block: gather the
        cohort's store rows (timed inside the store — the cumulative
        ``store_gather_ms`` gauge) plus the ids' data/test rows from the
        cached host views. Returns (state.replace kwargs, row args).
        The gather commits any still-staged previous-round slabs first,
        so chained rounds read the newest adopted rows."""
        store = self._store
        kw = {}
        with obs_trace.span("store_gather"):
            if store.has_field("personal_params"):
                kw["personal_params"] = jax.device_put(
                    store.gather("personal_params", ids))
            if store.has_field("agg_residual"):
                kw["agg_residual"] = jax.device_put(
                    store.gather("agg_residual", ids))
            xh, yh, nh = self._store_host_rows()
            row_args = [jnp.asarray(np.take(xh, ids, axis=0)),
                        jnp.asarray(np.take(yh, ids, axis=0)),
                        jnp.asarray(np.take(nh, ids))]
            if self.eval_cache:
                xt, yt, nt = self._store_host_rows(test=True)
                row_args += [jnp.asarray(np.take(xt, ids, axis=0)),
                             jnp.asarray(np.take(yt, ids, axis=0)),
                             jnp.asarray(np.take(nt, ids))]
        return kw, tuple(row_args)

    def _store_adopt_round(self, new_state, ids):
        """Post-round adoption: park the trained row slabs in the
        store's staging area (still device arrays — the host transfer is
        deferred to commit, so the async dispatch pipelining survives)
        and drop them from state. They reach storage at the next
        gather/flush; a watchdog rollback (``store_discard``) drops them
        first, so a rolled-back attempt's rows never touch storage."""
        store = self._store
        kw = {}
        if store.has_field("personal_params"):
            store.stage("personal_params", ids, new_state.personal_params)
            kw["personal_params"] = None
            self._store_eval_dirty.append(np.asarray(ids))
        if store.has_field("agg_residual"):
            store.stage("agg_residual", ids, new_state.agg_residual)
            kw["agg_residual"] = None
        return new_state.replace(**kw) if kw else new_state

    def _store_prefetch_next(self, next_ids, cur_ids) -> None:
        """The double-buffering hook: warm the predicted next cohort's
        host rows while the current (async-dispatched) program runs.
        Rows the current cohort dirtied are excluded — their newest
        values are the staged slabs the next gather commits."""
        cur = set(int(i) for i in np.asarray(cur_ids))
        ids = [int(i) for i in np.asarray(next_ids) if int(i) not in cur]
        if not ids:
            return
        for name in self._store.field_names():
            self._store.prefetch(name, ids)

    def _run_round_store(self, state: Any, round_idx: int):
        """One streamed round (the store-mode ``run_round`` body):
        gather the sampled cohort's rows host->device, run the
        slab-width round program, stage the trained slab back, prefetch
        the next round's cohort."""
        sel = self._selected_client_indexes(round_idx)
        kw, row_args = self._store_gather_rows(state, sel)
        slab_state = state.replace(**kw) if kw else state
        s = int(sel.shape[0])
        with obs_trace.span("dispatch_round"):
            out = self._get_store_round_jit()(
                slab_state, jnp.arange(s, dtype=jnp.int32),
                jnp.asarray(sel), jnp.asarray(round_idx, jnp.float32),
                *row_args)
        new_state, metrics = out[0], out[1:]
        if len(metrics) != len(self._round_metric_names):
            raise ValueError(
                f"{type(self).__name__} store round returned "
                f"{len(metrics)} metrics but _round_metric_names has "
                f"{len(self._round_metric_names)}")
        new_state = self._store_adopt_round(new_state, sel)
        self._store_prefetch_next(
            sample_client_indexes(round_idx + 1, self.num_clients,
                                  self.clients_per_round), sel)
        return new_state, dict(zip(self._round_metric_names, metrics))

    def _run_rounds_fused_store(self, state: Any, start_round: int,
                                n_rounds: int, eval_every: int = 0):
        """Fused blocks over the store: one gather of the block-UNION's
        [U] rows, one jitted scan in which round i addresses the slab at
        ``searchsorted(union, sels[i])`` (so within-block row chaining
        rides the carried slab exactly as it rides the resident [C]
        stack), one writeback of the whole union on the flush path. The
        in-graph eval cadence needs the full cohort resident and is
        refused — the runner evaluates between blocks instead."""
        if eval_every:
            raise ValueError(
                f"{self.name}: the fused in-graph eval cadence "
                "(frequency_of_the_test with fuse_rounds>1) evaluates "
                "the full [C] cohort inside the block; with "
                "--client_store the cohort is not resident — evaluate "
                "between blocks (eval_every=0) or run fuse_rounds=1")
        sels = np.stack([
            self._selected_client_indexes(r)
            for r in range(start_round, start_round + n_rounds)])
        union = np.unique(sels).astype(np.int32)
        views = np.searchsorted(union, sels).astype(np.int32)
        kw, row_args = self._store_gather_rows(state, union)
        slab_state = state.replace(**kw) if kw else state
        host_stack = (jnp.asarray(views),
                      jnp.asarray(sels.astype(np.int32)))
        round_ids = jnp.arange(
            start_round, start_round + n_rounds, dtype=jnp.float32)
        fn = self._get_fused_fn(n_rounds, eval_every, store=True)
        out = fn(slab_state, host_stack, round_ids, *row_args)
        if self._donate:
            new_state, ys, packed, _rets = out
            # _rets: the donated [U] row slabs threaded through the
            # carry so every donated input has an aliasable output —
            # dropped here (self.data still holds the full cohort on
            # host; there is nothing to rebind in store mode)
        else:
            new_state, ys, packed = out
        new_state = self._store_adopt_round(new_state, union)
        nxt = np.unique(np.concatenate([
            sample_client_indexes(r, self.num_clients,
                                  self.clients_per_round)
            for r in range(start_round + n_rounds,
                           start_round + 2 * n_rounds)]))
        self._store_prefetch_next(nxt, union)
        return new_state, FusedMetrics(ys, packed)

    def _store_register_fields(self, params) -> None:
        """init_state hook (store mode): register the streamed fields
        with their lazy per-row defaults — personal rows default to the
        init params (what the resident broadcast would hold), topk
        residual rows to zeros. An untrained row costs NOTHING until
        first written: at --track_personal 0 under topk the residual no
        longer allocates full-population zeros, only trained rows.
        Re-registration resets the store (a fresh init_state)."""
        store = self._store
        if getattr(self, "track_personal", True):
            store.register("personal_params", params)
        if self.agg_impl == "topk":
            store.register(
                "agg_residual",
                jax.tree_util.tree_map(jnp.zeros_like, params))
        self._store_eval_cache = None
        self._store_eval_dirty = []

    def _store_has_personal(self) -> bool:
        """True when the personal stack lives in the client store (state
        holds None between rounds) — ``_eval_impl``'s personal-branch
        test alongside ``state.personal_params is not None``."""
        return self._store is not None and \
            self._store.has_field("personal_params")

    def store_discard(self) -> None:
        """Watchdog RETRY/SKIP hook (the runner calls it on rollback):
        drop the rolled-back attempt's staged rows before anything
        commits them — the no-poison-leak pin extended to host RAM and
        disk — and invalidate the store eval cache (a full reseed at the
        next eval is always correct)."""
        if self._store is None:
            return
        self._store.discard()
        self._store_eval_cache = None
        self._store_eval_dirty = []

    def store_flush(self) -> None:
        """Commit staged rows to storage — the runner's pre-checkpoint
        barrier (the store snapshot must carry the adopted rows)."""
        if self._store is not None:
            self._store.commit()

    def _personal_eval_store(self, _pers, x_test, y_test, n_test):
        """Personal-eval protocol result over the STORE-resident stack —
        the host-side incremental twin of ``_personal_eval_cached``,
        with the dirty-row gather going to the store instead of the (not
        resident) [C] device stack. Same three tiers at the same widths
        and with the same jitted reductions, so results match the
        resident incremental path bitwise (accuracy) / to its documented
        1-ulp loss tolerance. ``_pers`` is ignored (None in store
        mode)."""
        store = self._store
        dirty = np.concatenate(self._store_eval_dirty) \
            if self._store_eval_dirty else np.zeros((0,), np.int64)
        if self._store_eval_cache is None or \
                dirty.size >= self.num_clients:
            # full pass: the one O(C) transfer (seed / post-resume /
            # post-rollback); population-scale runs eval rarely or not
            # at all (the runner's eval cadence flag)
            stack = jax.device_put(store.gather_all("personal_params"))
            ev = self._eval_personal(stack, x_test, y_test, n_test)
        elif dirty.size == 0:
            if not hasattr(self, "_pers_metrics_fn"):
                self._pers_metrics_fn = jax.jit(_personal_metrics)
            ev = self._pers_metrics_fn(*self._store_eval_cache)
        else:
            if not hasattr(self, "_store_eval_merge_fn"):
                self._store_eval_merge_fn = self._make_store_eval_merge()
            sel = dirty.astype(np.int32)
            sub = jax.device_put(store.gather("personal_params", sel))
            ev = self._store_eval_merge_fn(
                sub, jnp.asarray(sel), *self._store_eval_cache,
                x_test, y_test, n_test)
        self._store_eval_cache = (ev["correct"], ev["loss_sum"],
                                  ev["total"])
        self._store_eval_dirty = []
        return ev

    def _make_store_eval_merge(self):
        """jit twin of ``_make_personal_eval_merge`` taking the dirty
        rows PRE-GATHERED (host rows from the store) instead of indexing
        the resident stack: the same vmapped row eval at the same
        |dirty| width, the same scatter, the same reductions."""
        vmapped = self._vmap_clients(self.eval_client,
                                     in_axes=(0, 0, 0, 0))

        @jax.jit
        def eval_merge_rows(sub, sel, correct, loss_sum, total,
                            x_test, y_test, n_test):
            c_s, l_s, t_s = vmapped(
                sub, jnp.take(x_test, sel, axis=0),
                jnp.take(y_test, sel, axis=0), jnp.take(n_test, sel))
            correct = correct.at[sel].set(c_s)
            loss_sum = loss_sum.at[sel].set(l_s)
            total = total.at[sel].set(t_s)
            return _personal_metrics(correct, loss_sum, total)

        return eval_merge_rows

    def _fused_block_loop(self, state, start_round: int, total: int,
                          block: int, eval_every: int, on_record,
                          timed: bool = False, on_block=None):
        """The shared fused-block driver (library ``run(fuse_rounds=K)``
        and the CLI runner's ``--fuse_rounds`` both use it): dispatch
        block b+1, then materialize and emit block b's per-round records
        — the device queue never drains. ``on_record(round_idx, rec,
        state_out)`` receives each round's record in order plus the
        emitting block's (already computed) output state;
        ``on_block(end_round, state_out)`` fires once per flushed block
        (the runner's block-granular checkpoint hook).

        ``timed=True`` stamps ``round_time_s`` as the block's
        flush-to-flush wall time split evenly: flushes happen after the
        blocking materialize, so the per-run SUM equals wall time and
        per-round attribution is ±1 block (the fused analogue of
        DeferredRecords' timed semantics — the dispatch itself is async
        and takes microseconds, so timing it would be meaningless).

        A success-path flush error propagates; only when an exception is
        already unwinding is the final flush best-effort (the pending
        block's device state may be gone)."""
        mark = time.perf_counter()
        pending = None  # previous block, dispatched but not yet fetched

        def flush(p):
            nonlocal mark
            r0, k, ys, state_out = p
            # obs span at the ONE place the fused path already syncs
            # (per-round spans would force device syncs inside the
            # block); whole-block timing is the documented degradation
            with obs_trace.span("fused_block_flush") as sp:
                sp.add("start_round", r0)
                sp.add("rounds", k)
                host = dict(ys.materialize())  # blocks until complete
            now = time.perf_counter()
            wall, mark = now - mark, now
            ev = host.pop("eval", None)
            for i in range(k):
                rec: Dict[str, Any] = {"round": r0 + i}
                for name in self._round_metric_names:
                    rec[name] = float(host[name][i])
                if ev is not None and (r0 + i + 1) % eval_every == 0:
                    rec.update({k2: float(v[i]) for k2, v in ev.items()})
                if timed:
                    rec["round_time_s"] = wall / k
                on_record(r0 + i, rec, state_out)
            if on_block is not None:
                # block boundary: state_out is computed (materialize
                # above waited on it) — checkpoint-granularity hook
                on_block(r0 + k, state_out)

        try:
            for r0 in range(start_round, total, block):
                k = min(block, total - r0)
                if pending is not None and self._donate:
                    # ownership: the next dispatch CONSUMES the pending
                    # block's output state, which flush still reads
                    # (cost snapshot, block-boundary checkpoint) — so a
                    # donating loop flushes BEFORE dispatching. The
                    # dispatch-ahead pipelining below is the borrow
                    # path's; what donation loses is only the overlap of
                    # host record emission with the next block's compute
                    p, pending = pending, None
                    flush(p)
                with obs_trace.span("fused_block_dispatch") as sp:
                    sp.add("start_round", r0)
                    state, ys = self.run_rounds_fused(
                        state, r0, k, eval_every=eval_every)
                if pending is not None:
                    # clear BEFORE flushing: if flush raises mid-way
                    # (e.g. on_block checkpoint save), the finally must
                    # not re-emit the block's already-appended records
                    p, pending = pending, None
                    flush(p)
                pending = (r0, k, ys, state)
            if pending is not None:
                p, pending = pending, None
                flush(p)  # success path: a flush error propagates
        finally:
            if pending is not None:  # an exception is unwinding and this
                try:                 # block's flush never started
                    flush(pending)
                except Exception:  # crashed mid-block: device state gone
                    logger.exception("fused block metrics lost")
        return state

    def _run_fused(self, comm_rounds: int, eval_every: int, state: Any,
                   finalize: bool, block: int):
        """``run`` with the round loop executed in fused blocks
        (``_fused_block_loop``)."""
        if state is None:
            state = self.init_state(jax.random.PRNGKey(self.seed))
        history: List[Dict[str, Any]] = []

        def on_record(r, rec, _state_out):
            history.append(rec)
            logger.info("%s round %d: %s", self.name, r, rec)

        state = self._fused_block_loop(
            state, 0, comm_rounds, block, eval_every, on_record,
            timed=True)
        return self._finalize_into_history(
            state, history, finalize)

    def _finalize_into_history(self, state, history, finalize: bool):
        """Shared tail of both drivers: run the algorithm's end-of-training
        pass and append its record (round = -1) to the history."""
        from ..utils.records import to_float

        final_record = None
        if finalize:
            state, final_record = self.finalize(state)
        if final_record is not None:
            record = {k: to_float(v) for k, v in final_record.items()}
            history.append(record)
            logger.info("%s final: %s", self.name, record)
        return state, history

    # -- driver ---------------------------------------------------------------
    def run(
        self,
        comm_rounds: int,
        eval_every: int = 1,
        state: Any = None,
        callback=None,
        finalize: bool = True,
        fuse_rounds: int = 1,
    ):
        """The federated training driver (the reference's ``API.train()``).

        ``finalize=False`` skips the algorithm's end-of-training pass (e.g.
        FedAvg's final fine-tune) for callers that only need the round loop.

        ``fuse_rounds=K`` (supported algorithms) executes the loop in
        K-round fused programs — see ``run_rounds_fused``. Incompatible
        with ``callback``: per-round host control (checkpointing) is
        exactly what fusion removes.

        ``round_time_s`` is stamped at flush boundaries (see
        utils.records.DeferredRecords): the per-run SUM equals wall time
        exactly, per-round attribution is ±1 round under the deferred
        fetch.
        """
        from ..utils.records import DeferredRecords, to_float

        if fuse_rounds > 1:
            if callback is not None:
                raise ValueError(
                    "fuse_rounds > 1 removes per-round host control; "
                    "per-round callbacks (checkpointing) need "
                    "fuse_rounds=1")
            return self._run_fused(
                comm_rounds, eval_every, state, finalize, fuse_rounds)
        if state is None:
            state = self.init_state(jax.random.PRNGKey(self.seed))
        history: List[Dict[str, Any]] = []
        # metric host-fetches run one round late (utils/records.py): a
        # callback opts into immediate conversion since it observes
        # records as they land
        deferred = DeferredRecords(
            log=lambda rec: logger.info(
                "%s round %s: %s", self.name, rec["round"], rec),
            timed=True)
        try:
            for r in range(comm_rounds):
                t0 = time.perf_counter()
                state, train_metrics = self.run_round(state, r)
                record = {"round": r, **dict(train_metrics)}
                if eval_every and (r + 1) % eval_every == 0:
                    ev = self.evaluate(state)
                    record.update({k: v for k, v in ev.items()
                                   if not k.startswith("acc_per")})
                history.append(record)
                if callback is not None:
                    for k, v in record.items():
                        record[k] = to_float(v)
                    record["round_time_s"] = time.perf_counter() - t0
                    logger.info("%s round %d: %s", self.name, r, record)
                    callback(r, state, record)
                else:
                    deferred.push(record)
        except BaseException:
            deferred.flush_safely()  # emit the last completed round
            raise
        deferred.flush()
        return self._finalize_into_history(
            state, history, finalize)
