"""DPSGD — Decentralized Parallel SGD (gossip averaging, not diff. privacy).

Re-design of ``fedml_api/standalone/dpsgd/dpsgd_api.py:41-103``: every round
each client uniformly averages its neighborhood's personal models
(``_aggregate_func`` :169-178, neighborhood from ``_benefit_choose``
:116-139 random/ring/full), then trains locally. The reference additionally
reports a global average and runs a fine-tune pass every 100 rounds
(:88-101); here the global average is computed in ``evaluate``.

TPU-native: all personal models live stacked [C, ...]; the gossip step is
one row-normalized adjacency contraction (``mix_over_clients``) — an
all-gather + GEMM over ICI instead of per-edge sends.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core.state import broadcast_tree, mix_over_clients
from ..core.trainer import make_client_update
from ..models import init_params
from ..parallel.topology import neighbor_adjacency
from .base import FedAlgorithm


@struct.dataclass
class DPSGDState:
    personal_params: Any  # [C, ...]
    rng: jax.Array


class DPSGD(FedAlgorithm):
    name = "dpsgd"
    # the only per-round host input is the neighbor adjacency, a pure
    # function of round_idx (np.random.RandomState(round_idx) inside
    # neighbor_adjacency — _benefit_choose's seeded draw, dpsgd_api.py:
    # 116-139), so a K-round block precomputes the adjacency stack and
    # runs as ONE lax.scan program like the centralized algorithms
    supports_fused = True

    def cost_trained_clients_per_round(self) -> int:
        # gossip rounds train the whole cohort (dpsgd_api.py:41-103)
        return self.num_clients

    def __init__(self, *args, neighbor_mode: str = "random", **kwargs):
        self.neighbor_mode = neighbor_mode
        super().__init__(*args, **kwargs)

    def _build(self) -> None:
        self.client_update = make_client_update(
            self.apply_fn, self.loss_type, self.hp,
            mask_grads=False, mask_params_post_step=False,
            remat=self.remat_local, full_batches=self._full_batches(),
            augment_fn=self.augment_fn,
        )

        def round_fn(state: DPSGDState, adjacency, round_idx,
                     x_train, y_train, n_train):
            rng, round_key = jax.random.split(state.rng)
            # gossip: uniform average over the neighborhood (incl. self)
            row_sum = jnp.maximum(adjacency.sum(axis=1, keepdims=True), 1.0)
            mixed = mix_over_clients(adjacency / row_sum,
                                     state.personal_params)
            params, _, losses = self._train_stacked(
                self.client_update, mixed, mixed, round_idx, round_key,
                x_train, y_train, n_train,
            )
            return DPSGDState(personal_params=params, rng=rng), jnp.mean(losses)

        self._round_jit = jax.jit(round_fn)
        self._eval_global = self._make_global_eval()
        self._eval_personal = self._make_personal_eval()

    def init_state(self, rng: jax.Array) -> DPSGDState:
        p_rng, s_rng = jax.random.split(rng)
        params = init_params(self.model, p_rng, self.init_sample_shape)
        return DPSGDState(
            personal_params=broadcast_tree(params, self.num_clients),
            rng=s_rng,
        )

    def _fused_host_inputs(self, round_idx: int):
        # the round's adjacency, with the exact seeded draw of the unfused
        # path (neighbor_adjacency reseeds from round_idx internally)
        return (neighbor_adjacency(
            round_idx, self.num_clients, self.clients_per_round,
            mode=self.neighbor_mode,
        ),)

    def run_round(self, state: DPSGDState, round_idx: int):
        (adj,) = self._fused_host_inputs(round_idx)
        state, loss = self._round_jit(
            state, jnp.asarray(adj), jnp.asarray(round_idx, jnp.float32),
            self.data.x_train, self.data.y_train, self.data.n_train,
        )
        return state, {"train_loss": loss}

    def eval_metrics(self, state: DPSGDState, x_test, y_test,
                     n_test) -> Dict[str, Any]:
        # global average model (dpsgd_api.py:85 _avg_aggregate) + personal;
        # fully traceable, so the fused block evals in-graph too
        avg = jax.tree_util.tree_map(
            lambda x: jnp.mean(x, axis=0), state.personal_params
        )
        ev_g = self._eval_global(avg, x_test, y_test, n_test)
        ev_p = self._eval_personal(
            state.personal_params, x_test, y_test, n_test)
        return {
            "global_acc": ev_g["acc"], "global_loss": ev_g["loss"],
            "personal_acc": ev_p["acc"], "personal_loss": ev_p["loss"],
            "acc_per_client": ev_p["acc_per_client"],
        }
