"""Federation wire protocol: message types, retry/backoff on send, and
the deterministic key/partition derivations both ends must agree on.

Star topology, aggregator = rank 0, sites = ranks 1..N (the cross-silo
scheme of ``comm/cross_silo.py``, extended with versioned dispatch so
the buffered-async policy can tag every delta with the global-model
version it was computed against).

Messages (all via ``comm/message.py``'s binary pytree framing):

* ``fed_train`` (aggregator -> site): global params + ``version`` +
  ``mode``; sync rounds add the round key, the site's client ids, their
  slot positions and the cohort size so the site reproduces exactly its
  slice of the in-process round program.
* ``fed_update`` (site -> aggregator): sync — the trained local models
  (dense rows, the bit-parity path); buffered — the site's weighted
  local delta in a ``fed/wire.py`` format, tagged with the base
  ``version`` it trained from.
* ``fed_finish`` (aggregator -> site): drain and exit.
* ``fed_hello`` / ``fed_hello_ack``: the clock-sync handshake behind
  cross-process tracing (``obs/xtrace.py``). The initiator stamps its
  wall clock ``t0``; the peer echoes it with its own ``t1``; the
  initiator reads ``t2`` at the ACK and estimates the peer's clock
  offset by the NTP midpoint. Only ever sent when ``--xtrace`` is on
  (the byte-inert contract); both planes reuse the same pair — the
  aggregator initiates toward its sites, the serve worker toward its
  publisher. The aggregator re-initiates every
  ``fed/aggregator.CLOCK_RESYNC_EVERY`` rounds so long runs track
  clock drift instead of freezing the first offset estimate.
* ``fed_heartbeat`` (site -> aggregator; serve worker -> publisher):
  periodic standalone liveness frame carrying only the ``hb_*``
  headers (``obs/live.py``) — mid-round progress for the fleet
  ledger. Only ever sent when ``--obs_heartbeat_every`` is on (the
  byte-inert contract, same as the HELLO pair).
"""
from __future__ import annotations

import logging
import time
from typing import Any, List

import numpy as np

from ..comm.message import Message

logger = logging.getLogger(__name__)

MSG_FED_TRAIN = "fed_train"
MSG_FED_UPDATE = "fed_update"
MSG_FED_FINISH = "fed_finish"
MSG_FED_HELLO = "fed_hello"
MSG_FED_HELLO_ACK = "fed_hello_ack"
MSG_FED_HEARTBEAT = "fed_heartbeat"


def heartbeat_message(sender: int, receiver: int, hb: Any) -> Message:
    """A standalone HEARTBEAT frame: pure control plane (no tensors),
    carrying only the ``hb_*`` headers of ``obs/live.py``. Only ever
    sent when ``--obs_heartbeat_every`` is on (the byte-inert
    contract, same as the HELLO pair)."""
    from ..obs import live as obs_live

    msg = Message(MSG_FED_HEARTBEAT, sender, receiver)
    obs_live.inject_heartbeat(msg, hb)
    return msg


def hello_message(sender: int, receiver: int, t0_ns: int) -> Message:
    """The handshake's first leg: the initiator's wall clock."""
    msg = Message(MSG_FED_HELLO, sender, receiver)
    msg.add("t0_ns", int(t0_ns))
    return msg


def hello_ack(msg: Message, sender: int, rank: int,
              t1_ns: int) -> Message:
    """The echo leg: ``t0`` returned untouched, the peer's ``t1`` and
    rank added (``rank`` keys the initiator's offset table)."""
    reply = Message(MSG_FED_HELLO_ACK, sender, msg.sender_id)
    reply.add("t0_ns", int(msg.get("t0_ns", 0)))
    reply.add("rank", int(rank))
    reply.add("t1_ns", int(t1_ns))
    return reply

#: PRNG domain separator for the buffered policy's per-site key chain
#: ("fed" in ascii) — the same fold-in idiom as robust.faults.FAULT_SALT,
#: a different constant so fault draws and training keys never collide.
FED_SALT = 0x666564


def site_round_key(seed: int, version: int, site_rank: int):
    """Buffered-async training key for (site, global-model version).

    A pure function of ``(run seed, version, site rank)`` — nothing
    about arrival order, wall clock, or process identity — so a site's
    delta is reproducible from its TRAIN message alone and a recorded
    arrival trace replays bit-for-bit (``fed/aggregator.py``).
    """
    import jax

    k = jax.random.fold_in(jax.random.PRNGKey(int(seed)), FED_SALT)
    k = jax.random.fold_in(k, int(version))
    return jax.random.fold_in(k, int(site_rank))


def partition_slots(n_items: int, n_sites: int) -> List[np.ndarray]:
    """Contiguous order-preserving split of ``arange(n_items)`` into
    ``n_sites`` blocks (site k, 1-based, owns block k-1).

    Contiguity is load-bearing for the sync barrier: concatenating the
    sites' reply rows in rank order reassembles the cohort in exact
    slot order, so the aggregate runs over the same [S] stacking as the
    in-process round body.
    """
    if n_sites < 1:
        raise ValueError(f"n_sites must be >= 1, got {n_sites}")
    return np.array_split(np.arange(int(n_items)), int(n_sites))


def send_with_retry(manager: Any, msg: Message, retries: int = 2,
                    backoff_s: float = 0.05) -> None:
    """``send_message`` with bounded retry + exponential backoff.

    Transient transport failures (``OSError`` from the native TCP
    backend, ``ConnectionError`` from a draining inbox) are retried up
    to ``retries`` times with ``backoff_s * 2**attempt`` sleeps; each
    re-issue bumps the manager's ``CommCounters.messages_retried`` so
    degradation is visible in the obs fold. Anything still failing
    after the budget propagates — a dead peer is the caller's quorum
    logic's problem, not this function's.
    """
    comm = getattr(manager, "comm", manager)
    attempt = 0
    while True:
        try:
            manager.send_message(msg)
            return
        except OSError as e:  # ConnectionError is an OSError subclass
            if attempt >= retries:
                raise
            counters = getattr(comm, "counters", None)
            if counters is not None:
                counters.note_retry()
            delay = backoff_s * (2 ** attempt)
            logger.warning(
                "send %s -> rank %s failed (%s); retry %d/%d in %.3fs",
                msg.type, msg.receiver_id, e, attempt + 1, retries, delay)
            time.sleep(delay)
            attempt += 1
