"""Distributed federation runtime — the first genuinely multi-process
deployment mode in the repo's life.

Every "federated" run before this package was one Python process
simulating sites sequentially (the in-mesh SPMD simulation of
``algorithms/``). ``fed/`` wires the until-now orphaned comm stack
(``comm/tcp.py``, ``comm/local.py``, ``comm/message.py``) into a real
deployment: one **aggregator process** and N **site processes**
exchanging model deltas over a wire, driven by
``scripts/run_federation.py`` or a ``--fed_role aggregator|site``
runner entry.

Two aggregation policies behind one surface:

* ``sync`` — barrier per round. On the loopback backend this is
  bit-for-bit the in-process simulation (the correctness anchor:
  ``scripts/fed_smoke.py`` pins params equality via
  ``obs/diff.py params_diff``).
* ``buffered`` — FedBuff-style async (Nguyen et al., AISTATS 2022):
  apply the first K arriving deltas with staleness-discounted weights
  ``n_i / sqrt(1 + tau_i)`` under ``--fed_staleness_bound``; stragglers
  stop gating the round clock. Arrival order is recorded to a trace so
  any buffered run replays bit-for-bit (``--fed_replay``).

Module map: ``wire`` (delta codecs riding the ``agg_impl`` formats),
``protocol`` (message types + send retry/backoff), ``trainer`` (the
local-training split of the fused round body), ``site`` (site-process
worker), ``aggregator`` (both policies + trace record/replay),
``runtime`` (role dispatch, loopback harness, refusals, obs fold).
"""
from .aggregator import FedAggregator
from .protocol import (
    FED_SALT,
    MSG_FED_FINISH,
    MSG_FED_TRAIN,
    MSG_FED_UPDATE,
    partition_slots,
    send_with_retry,
    site_round_key,
)
from .runtime import run_federated
from .site import SiteWorker
from .trainer import SiteTrainer
from .wire import WIRE_IMPLS, decode_update, encode_update

__all__ = [
    "FED_SALT",
    "FedAggregator",
    "MSG_FED_FINISH",
    "MSG_FED_TRAIN",
    "MSG_FED_UPDATE",
    "SiteTrainer",
    "SiteWorker",
    "WIRE_IMPLS",
    "decode_update",
    "encode_update",
    "partition_slots",
    "run_federated",
    "send_with_retry",
    "site_round_key",
]
