"""Delta wire codecs: pytree -> Message tensors in the ``agg_impl``
formats (dense / bf16 / int8 / topk), host-side and deterministic.

The in-mesh aggregation wires (``parallel/collectives.py``) compress
cross-chip transfers inside one XLA program; a federation ships the
same formats over a REAL wire between processes. The codecs here are
their host-side numpy twins — pure functions of the input tree, no
RNG, no device state — so an encoded payload is reproducible and a
recorded buffered-async run replays bit-for-bit.

Contract (pinned by ``tests/test_fed_wire.py``): transport is
bit-transparent — ``decode(wire(encode(tree)))`` equals
``decode(encode(tree))`` exactly, over the local and tcp backends.
The lossy impls (bf16/int8/topk) lose precision at ENCODE time, once;
the wire never adds more.

Top-k selection note: per-leaf magnitude selection under the shared
wire tie-break contract (``ops.topk_select.host_topk_indices``: every
coordinate above the k-th-largest magnitude, then ties at it by
ascending position, shipped in ascending-index canonical order) —
byte-identical to the historical stable ``np.argsort(-|x|)`` spelling
but O(n) via ``np.argpartition``, sized by the shared
``parallel.collectives.topk_count`` rounding rule — the same count the
wire-cost model (``obs/comm.py``) prices.
"""
from __future__ import annotations

from typing import Any

import numpy as np

from ..comm.message import Message
from ..ops.topk_select import host_topk_indices
from ..parallel.collectives import topk_count

try:  # jax's own dtype-extension dependency; present wherever jax is
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - jax guarantees ml_dtypes
    _BF16 = None

WIRE_IMPLS = ("dense", "bf16", "int8", "topk")


def _np_tree(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _q_int8(a: np.ndarray):
    """Per-leaf symmetric int8 quantization: scale = max|a|/127 (1.0 for
    an all-zero leaf so decode is exact zeros), round-half-even like the
    in-mesh int8 wire's deterministic mode."""
    a = np.asarray(a, np.float32)
    m = np.float32(np.max(np.abs(a))) if a.size else np.float32(0.0)
    scale = np.float32(m / np.float32(127.0)) if m > 0 else np.float32(1.0)
    q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
    return q, np.asarray(scale, np.float32)


def _topk_leaf(a: np.ndarray, density: float):
    a = np.asarray(a, np.float32)
    flat = a.ravel()
    k = topk_count(flat.size, density)
    # exactly-k selection under the shared wire tie-break contract
    # (ops/topk_select.host_topk_indices: all >threshold, ties at the
    # threshold by ascending position) — byte-identical payloads to the
    # historical stable np.argsort spelling, via O(n) argpartition
    idx = host_topk_indices(np.abs(flat), k)
    return idx, flat[idx], np.asarray(a.shape, np.int64)


def encode_update(msg: Message, tree: Any, impl: str, *,
                  key: str = "delta", density: float = 0.1) -> None:
    """Attach ``tree`` to ``msg`` under ``key`` in wire format ``impl``.

    ``dense`` ships raw leaves (dtype-preserving — the sync barrier's
    bit-parity path); the compressed impls cast/quantize/sparsify to
    f32-decodable payloads. ``density`` is the topk fraction
    (``--agg_topk_density``).
    """
    if impl not in WIRE_IMPLS:
        raise ValueError(
            f"unknown wire impl {impl!r} (one of {WIRE_IMPLS})")
    msg.add(key + "_wire", impl)
    tree = _np_tree(tree)
    import jax

    if impl == "dense":
        msg.add_tensor(key, tree)
    elif impl == "bf16":
        if _BF16 is None:  # pragma: no cover
            raise RuntimeError("bf16 wire needs ml_dtypes")
        # ships as a uint16 view: the Message codec frames dtypes by
        # numpy dtype string, and ml_dtypes' bfloat16 serializes as an
        # opaque void type ('<V2') that would not survive decode
        msg.add_tensor(key, jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32).astype(_BF16).view(
                np.uint16), tree))
    elif impl == "int8":
        q = jax.tree_util.tree_map(lambda x: _q_int8(x)[0], tree)
        s = jax.tree_util.tree_map(lambda x: _q_int8(x)[1], tree)
        msg.add_tensor(key, {"q": q, "scale": s})
    else:  # topk
        idx = jax.tree_util.tree_map(
            lambda x: _topk_leaf(x, density)[0], tree)
        val = jax.tree_util.tree_map(
            lambda x: _topk_leaf(x, density)[1], tree)
        shp = jax.tree_util.tree_map(
            lambda x: _topk_leaf(x, density)[2], tree)
        msg.add_tensor(key, {"idx": idx, "val": val, "shape": shp})


def _scatter_leaf(idx: np.ndarray, val: np.ndarray,
                  shape: np.ndarray) -> np.ndarray:
    shape = tuple(int(d) for d in np.asarray(shape).ravel())
    size = int(np.prod(shape)) if shape else 1
    out = np.zeros(size, np.float32)
    out[np.asarray(idx)] = np.asarray(val, np.float32)
    return out.reshape(shape)


def decode_update(msg: Message, *, key: str = "delta") -> Any:
    """Recover the (post-compression) tree shipped by ``encode_update``
    as float32 numpy leaves (``dense`` keeps the encoder's dtypes)."""
    import jax

    impl = msg.get(key + "_wire")
    payload = msg.get_tensor(key)
    if impl == "dense":
        return jax.tree_util.tree_map(np.asarray, payload)
    if impl == "bf16":
        return jax.tree_util.tree_map(
            lambda x: np.asarray(x).view(_BF16).astype(np.float32),
            payload)
    if impl == "int8":
        return jax.tree_util.tree_map(
            lambda q, s: q.astype(np.float32) * np.float32(s),
            payload["q"], payload["scale"])
    if impl == "topk":
        return jax.tree_util.tree_map(
            _scatter_leaf, payload["idx"], payload["val"],
            payload["shape"])
    raise ValueError(f"message carries unknown wire impl {impl!r}")
