"""SiteWorker: one federation site process (or loopback thread).

Reacts to the aggregator's ``fed_train`` dispatches — sync rounds train
the slice of the cohort named in the message, buffered rounds train all
of the site's own clients from the shipped base model — and replies
with ``fed_update`` via ``send_with_retry``. Per-site fault specs
(``--fed_site_faults``) turn the chaos harness end-to-end: a
``straggle`` draw here sleeps a REAL process before replying and a
``drop`` draw withholds the reply entirely, exercising the
aggregator's staleness/quorum machinery over an actual wire instead of
a simulated slot. Draws reuse ``robust.faults.fault_trace_round`` keyed
by ``(seed, version, site_rank)`` — deterministic, analyzable offline.

Each site writes its own JSONL round + event streams; the runtime
folds them with the aggregator's via ``obs.export.merge_host_jsonl`` /
``merge_host_events`` (the multihost fold, reused verbatim).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

import numpy as np

from ..comm.manager import ClientManager
from ..comm.message import Message
from ..obs import live as obs_live, xtrace
from ..obs.export import RoundLogWriter
from ..obs.xtrace import XTracer
from ..robust.faults import FaultSpec, fault_trace_round
from . import protocol, wire
from .trainer import SiteTrainer

logger = logging.getLogger(__name__)


class SiteWorker(ClientManager):
    """Rank >= 1 site manager.

    ``fault_spec``/``straggle_s``: this site's process-level fault
    model (None = healthy). ``wire_impl``/``wire_density``: the delta
    codec for buffered replies (``fed/wire.py``; sync replies are
    always dense rows — the bit-parity contract).
    """

    def __init__(self, comm, rank: int, world_size: int,
                 trainer: SiteTrainer, seed: int,
                 wire_impl: str = "dense", wire_density: float = 0.1,
                 fault_spec: Optional[FaultSpec] = None,
                 straggle_s: float = 0.0, kill_after_s: float = 0.0,
                 retries: int = 2,
                 backoff_s: float = 0.05, log_path: str = "",
                 events_path: str = "",
                 tracer: Optional[XTracer] = None,
                 heartbeat: Optional[obs_live.HeartbeatConfig] = None):
        super().__init__(comm, rank=rank, world_size=world_size)
        self.trainer = trainer
        self.seed = int(seed)
        self.wire_impl = wire_impl
        self.wire_density = wire_density
        self.fault_spec = fault_spec
        self.straggle_s = float(straggle_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.tracer = tracer
        self.writer = RoundLogWriter(log_path, force=True) \
            if log_path else None
        self.events = RoundLogWriter(events_path, force=True) \
            if events_path else None
        self.done = threading.Event()
        self.rounds_trained = 0
        self.heartbeat = heartbeat
        # our own threads (receive pump + heartbeat emitter) must not
        # interleave sends on the shared transport
        self._send_lock = threading.Lock()
        self.register_message_receive_handler(
            protocol.MSG_FED_TRAIN, self._on_train)
        self.register_message_receive_handler(
            protocol.MSG_FED_FINISH, self._on_finish)
        # clock-sync echo: registered unconditionally (inert unless the
        # aggregator actually initiates a HELLO, which is xtrace-gated)
        self.register_message_receive_handler(
            protocol.MSG_FED_HELLO, self._on_hello)
        self._hb_thread: Optional[threading.Thread] = None
        if heartbeat is not None:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                name=f"hb:site{rank}", daemon=True)
            self._hb_thread.start()
        # the process-death fault ("rank:kill[:after_s]"): unlike a
        # `drop` draw (alive but withholding one reply) the site goes
        # COMPLETELY silent — no replies, no heartbeats, pump stopped —
        # which is exactly the signal the fleet ledger's SUSPECT/DOWN
        # machine (and nothing else in the repo) can see mid-round
        self.kill_after_s = float(kill_after_s)
        self._killed = False
        if self.kill_after_s > 0:
            threading.Thread(target=self._kill_loop,
                             name=f"kill:site{rank}",
                             daemon=True).start()

    def _kill_loop(self) -> None:
        if self.done.wait(self.kill_after_s):
            return  # run finished before the kill fired
        logger.warning("site %d: injected kill fires after %.2fs — "
                       "going silent", self.rank, self.kill_after_s)
        self._event(self.rounds_trained, "fed_site_kill",
                    after_s=self.kill_after_s)
        self._killed = True
        # done stops the heartbeat emitter AND lets the runtime's
        # bounded join proceed; the pump stop silences the handlers
        self.done.set()
        self.comm.stop_receive_message()

    def _on_hello(self, msg: Message) -> None:
        if self._killed:
            return
        t1 = self.tracer.wall_ns() if self.tracer is not None \
            else time.time_ns()
        reply = protocol.hello_ack(msg, self.rank, self.rank, t1)
        with self._send_lock:
            protocol.send_with_retry(self, reply, retries=self.retries,
                                     backoff_s=self.backoff_s)

    # -- live telemetry ---------------------------------------------------
    def _heartbeat_loop(self) -> None:
        """Periodic standalone HEARTBEAT frames toward the aggregator:
        mid-round progress while ``_on_train`` is still inside its
        train step. Best-effort by design — a LOST heartbeat is exactly
        the signal the fleet ledger detects, so send failures are
        swallowed, never retried."""
        hb = self.heartbeat
        while not self.done.wait(hb.every_s):
            from ..obs.memory import host_rss

            hb.note("mem_rss_mb",
                    host_rss()["rss_bytes"] / 1e6)
            hb.note("comm_messages_sent",
                    self.comm.counters.messages_sent)
            hb.note("comm_bytes_sent", self.comm.counters.bytes_sent)
            try:
                with self._send_lock:
                    self.send_message(protocol.heartbeat_message(
                        self.rank, 0, hb))
            except OSError:
                pass  # aggregator draining/gone: the ledger's problem

    # -- fault model ------------------------------------------------------
    def _draw_faults(self, version: int):
        """(straggled, dropped, byzantine, signflipped) for this round —
        drawn from the shared ``fault_trace_round`` twin keyed by
        ``(seed, version, rank)``, so the aggregator's analyzer can
        reconstruct (and a replay re-forge) every fault offline."""
        if self.fault_spec is None or not self.fault_spec.any_active:
            return False, False, False, False
        tr = fault_trace_round(self.fault_spec, self.seed, version,
                               np.asarray([self.rank]))
        return (bool(tr["straggled"][0]), bool(tr["dropped"][0]),
                bool(tr["byzantine"][0]), bool(tr["signflipped"][0]))

    def _forge_factor(self, byzantine: bool, signflip: bool) -> float:
        """The Byzantine delta multiplier this round: ``scale_factor``
        when the scale draw fired (``rank:byzantine`` sugar = scale=1.0,
        an always-on attacker), negated by a signflip draw."""
        factor = 1.0
        if byzantine:
            factor *= float(self.fault_spec.scale_factor)
        if signflip:
            factor = -factor
        return factor

    def _event(self, version: int, event_type: str, **extra) -> None:
        if self.events is not None:
            self.events.write({"round": int(version),
                               "event_type": event_type,
                               "site": self.rank, **extra})

    # -- protocol ---------------------------------------------------------
    def _on_train(self, msg: Message) -> None:
        if self._killed:
            return
        version = int(msg.get("version"))
        mode = msg.get("mode")
        t0 = time.perf_counter()
        # causal link: the aggregator's dispatch span is this round's
        # parent; absent headers (old peers, tracing off) read as None
        ctx = xtrace.extract(msg) if self.tracer is not None else None
        with xtrace.xspan(self.tracer, "site_round",
                          trace_id=ctx.trace_id if ctx else None,
                          parent=ctx.span_id if ctx else None,
                          args={"site": self.rank,
                                "version": version}) as sr:
            straggled, dropped, byzantine, signflip = \
                self._draw_faults(version)
            forged = byzantine or signflip
            if straggled and self.straggle_s > 0:
                # a REAL straggling process: the aggregator's round
                # clock (sync timeout / buffered staleness bound) sees
                # this delay
                self._event(version, "fed_site_straggle",
                            sleep_s=self.straggle_s)
                with xtrace.xspan(self.tracer, "straggle",
                                  args={"sleep_s": self.straggle_s}):
                    time.sleep(self.straggle_s)
            if dropped:
                # withhold the reply entirely — site death for this
                # round; the aggregator degrades to quorum / flushes
                # without us
                self._event(version, "fed_site_drop")
                sr.add(dropped=True)
                return
            import jax
            import jax.numpy as jnp

            params = jax.tree_util.tree_map(
                jnp.asarray, msg.get_tensor("params"))
            client_ids = np.asarray(msg.get_tensor("client_ids"))
            reply = Message(protocol.MSG_FED_UPDATE, self.rank, 0)
            reply.add("version", version)
            reply.add("site", self.rank)
            reply.add("mode", mode)
            if mode == "sync":
                slot_pos = np.asarray(msg.get_tensor("slot_pos"))
                with xtrace.xspan(self.tracer, "train"):
                    rows, losses = self.trainer.train_sync(
                        params, msg.get_tensor("round_key"), version,
                        client_ids, slot_pos,
                        int(msg.get("cohort_size")))
                if forged:
                    # a LYING site: every row it ships is the forged
                    # delta g + factor*(row - g) — a real adversarial
                    # process on the wire, not a simulated slot. Pure
                    # in (seed, version, rank) + the deterministic
                    # trained rows, so the attack replays bit-for-bit.
                    factor = self._forge_factor(byzantine, signflip)
                    g32 = jax.tree_util.tree_map(
                        lambda x: np.asarray(x, np.float32), params)
                    rows = jax.tree_util.tree_map(
                        lambda r, g: g[None] + np.float32(factor)
                        * (np.asarray(r, np.float32) - g[None]),
                        rows, g32)
                    self._event(version, "fed_site_byzantine",
                                factor=factor)
                with xtrace.xspan(self.tracer, "encode"):
                    reply.add_tensor("rows", rows)
                    reply.add_tensor("losses", losses)
                loss = float(np.mean(losses)) if losses.size \
                    else float("nan")
                n_sum = float(np.sum(np.asarray(
                    self.trainer.algo.data.n_train)[client_ids]))
            else:  # buffered
                base_key = protocol.site_round_key(
                    self.seed, version, self.rank)
                with xtrace.xspan(self.tracer, "train"):
                    delta, n_sum, loss = self.trainer.train_delta(
                        params, base_key, version, client_ids)
                if forged:
                    factor = self._forge_factor(byzantine, signflip)
                    delta = jax.tree_util.tree_map(
                        lambda d: np.float32(factor)
                        * np.asarray(d, np.float32), delta)
                    self._event(version, "fed_site_byzantine",
                                factor=factor)
                with xtrace.xspan(self.tracer, "encode"):
                    wire.encode_update(reply, delta, self.wire_impl,
                                       density=self.wire_density)
                reply.add("n_sum", n_sum)
                reply.add("train_loss", loss)
            if ctx is not None:
                # the reply carries OUR span as the aggregator-side
                # parent plus our send wall clock (its wire-time input)
                xtrace.inject(reply, sr.ctx(),
                              wall_ns=self.tracer.wall_ns())
            if self.heartbeat is not None:
                # piggybacked gauge snapshot: every UPDATE is also a
                # heartbeat (heartbeats off adds not one byte here)
                self.heartbeat.note_round(version)
                self.heartbeat.note("train_loss", loss)
                self.heartbeat.note("local_epoch",
                                    self.rounds_trained + 1)
                obs_live.inject_heartbeat(reply, self.heartbeat)
            if self._killed:
                # the kill fired while we were training: a dead
                # process does not get to finish its send
                return
            with self._send_lock:
                protocol.send_with_retry(self, reply,
                                         retries=self.retries,
                                         backoff_s=self.backoff_s)
        self.rounds_trained += 1
        if self.writer is not None:
            self.writer.write({
                "round": version, "site": self.rank, "mode": mode,
                "train_loss": loss, "n_sum": n_sum,
                "clients": int(client_ids.size),
                "wall_s": time.perf_counter() - t0,
                "fed_straggled": straggled,
                "fed_byzantine": forged,
            })

    def _on_finish(self, msg: Message) -> None:
        ctx = xtrace.extract(msg) if self.tracer is not None else None
        if ctx is not None:
            with xtrace.xspan(self.tracer, "site_finish",
                              trace_id=ctx.trace_id,
                              parent=ctx.span_id,
                              args={"site": self.rank}):
                pass
        if self.writer is not None:
            self.writer.write({"round": -1, "site": self.rank,
                               "rounds_trained": self.rounds_trained,
                               **self.comm.counters.snapshot()})
            self.writer.close()
        if self.events is not None:
            self.events.close()
        self.done.set()
        self.comm.stop_receive_message()
