"""FedAggregator: rank-0 of a federation — two aggregation policies
behind one surface.

**sync** — barrier per round. The aggregator owns the host-side RNG
chain (``split(state.rng)`` per round, exactly the in-process round
body's consumption), ships the global model + round key + slot
assignments, and reassembles the sites' locally-trained rows in slot
order into the SAME [S]-stacked weighted mean the fused program
computes. On the loopback backend this is bit-for-bit the in-process
simulation (``scripts/fed_smoke.py`` pins it via ``params_diff``);
missing sites degrade the round to a survivor-renormalized quorum
aggregate (the ``RoundOutcome`` semantics of ``comm/cross_silo.py``,
here at federation scale), and zero arrivals carry the global model.

**buffered** — FedBuff (Nguyen et al., AISTATS 2022): deltas are
applied in arrival order, K per flush, each weighted
``n_i / sqrt(1 + tau_i)`` (staleness-discounted, normalized over the
buffer) — a straggling site stops gating the round clock. Updates
staler than ``staleness_bound`` are dropped and the site re-dispatched
at the current version. Every flush's ``(site, base_version)`` members
are recorded to an **arrival trace**; replaying the trace re-applies
the same deltas in the same order — and because a site's delta is a
pure function of ``(seed, version, site)`` (``protocol.site_round_key``)
the replayed run is bit-for-bit identical (the async twin of the
repo's determinism contract).
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..comm.manager import ServerManager
from ..comm.message import Message
from ..core.state import weighted_tree_sum
from ..obs import live as obs_live, xtrace
from ..obs.export import RoundLogWriter, record_schema
from ..obs.xtrace import XTracer
from . import protocol, wire

logger = logging.getLogger(__name__)

#: clock-offset re-handshake cadence (rounds/flushes): the NTP-midpoint
#: estimate drifts over long runs, so the aggregator re-initiates the
#: HELLO pair every this many rounds and the FRESHEST offset wins —
#: both here (``fed_wire_ms`` attribution via ``to_ref_ns``) and in the
#: merged-trace lane alignment (``xtrace.merge_docs`` keeps the last
#: offset a stream carries).
CLOCK_RESYNC_EVERY = 16

#: Byzantine norm screen: a member whose delta norm exceeds this factor
#: times the median member norm is flagged (typed BYZANTINE event +
#: fault-attribution naming the site). Detection only — survival comes
#: from ``robust_agg``; an attacker below the screen still gets voted
#: out by the robust statistic, it just isn't NAMED by the screen.
BYZ_NORM_FACTOR = 10.0


class FedAggregator(ServerManager):
    def __init__(self, comm, world_size: int, algo: Any, *, mode: str,
                 rounds: int, seed: int, buffer_k: int = 1,
                 staleness_bound: int = 2, timeout_s: float = 60.0,
                 retries: int = 2, backoff_s: float = 0.05,
                 wire_impl: str = "dense", wire_density: float = 0.1,
                 replay_trace: Optional[Dict[str, Any]] = None,
                 robust_agg: str = "none", robust_trim: float = 0.2,
                 robust_krum_f: int = 0, robust_norm_bound: float = 5.0,
                 log_path: str = "", events_path: str = "",
                 tracer: Optional[XTracer] = None, slo: Any = None,
                 heartbeat_every: float = 0.0):
        super().__init__(comm, rank=0, world_size=world_size)
        import jax

        self.algo = algo
        self.mode = mode
        self.rounds = int(rounds)
        self.seed = int(seed)
        self.n_sites = world_size - 1
        self.buffer_k = max(1, int(buffer_k))
        self.staleness_bound = int(staleness_bound)
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.wire_impl = wire_impl
        self.wire_density = wire_density
        self.replay_trace = replay_trace
        # robust_agg: Byzantine-robust statistic replacing the weighted
        # sum (sync) / discounted delta sum (buffered) — the same
        # robust/aggregation.py estimators the in-process round runs,
        # here over SITE rows/deltas on the aggregator host
        from ..robust.aggregation import ROBUST_AGGS

        if robust_agg not in ROBUST_AGGS:
            raise ValueError(
                f"robust_agg {robust_agg!r} not in {ROBUST_AGGS}")
        self.robust_agg = robust_agg
        self.robust_trim = float(robust_trim)
        self.robust_krum_f = int(robust_krum_f)
        self.robust_norm_bound = float(robust_norm_bound)
        self.byzantine_flags: Dict[int, int] = {}  # site -> flag count
        # buffered sites own fixed client blocks; sync re-partitions the
        # sampled cohort per round
        self.partition = protocol.partition_slots(
            algo.num_clients, self.n_sites)
        # the aggregator owns exactly the in-process state: params from
        # the same init split, the same host-side rng chain
        state0 = algo.init_state(jax.random.PRNGKey(self.seed))
        self.global_params = state0.global_params
        self.rng = state0.rng
        self.version = 0
        self.history: List[Dict[str, Any]] = []
        self.staleness_hist: Dict[int, int] = {}
        self.stale_drops = 0
        self.trace: Dict[str, Any] = {
            "mode": mode, "seed": self.seed, "sites": self.n_sites,
            "buffer_k": self.buffer_k,
            "staleness_bound": self.staleness_bound, "flushes": []}
        self.writer = RoundLogWriter(log_path, force=True) \
            if log_path else None
        self.events = RoundLogWriter(events_path, force=True) \
            if events_path else None
        self._norm_history: List[float] = []
        self.tracer = tracer
        self.slo = slo  # SloEngine observing federation round records
        self._updates: "queue.Queue[Message]" = queue.Queue()
        self.register_message_receive_handler(
            protocol.MSG_FED_UPDATE, self._enqueue_update)
        self._hello_acks: "queue.Queue[Dict[str, float]]" = queue.Queue()
        self.register_message_receive_handler(
            protocol.MSG_FED_HELLO_ACK, self._on_hello_ack)
        # fleet ledger (--obs_heartbeat_every): per-site liveness state
        # machine fed by standalone HEARTBEAT frames + the hb_* headers
        # piggybacked on UPDATE replies. The handler is registered
        # unconditionally (inert unless sites actually send, which is
        # flag-gated — the same idiom as the HELLO echo); the lock
        # serializes pump-thread observations against round-loop ticks.
        self.ledger: Optional[obs_live.FleetLedger] = \
            obs_live.FleetLedger(heartbeat_every) \
            if heartbeat_every > 0 else None
        self._ledger_lock = threading.Lock()
        self.register_message_receive_handler(
            protocol.MSG_FED_HEARTBEAT, self._on_heartbeat)
        if self.ledger is not None:
            now = time.monotonic()
            for k in range(1, self.n_sites + 1):
                # expected peers start LIVE with the silence clock
                # running: a site that dies before its first heartbeat
                # still goes DOWN
                self.ledger.register(f"site{k}", now)
        # per-round wire/queue accumulators (tracing on): reset at every
        # round / flush boundary
        self._xt_wire_ns = 0.0
        self._xt_queue_ns = 0.0
        self._xt_round_t0 = time.perf_counter()
        # buffered-mode re-handshake latch: one resync per flush index
        self._resynced_at = -1

    # -- clock sync / trace plumbing (xtrace-gated, byte-inert off) -------
    def _enqueue_update(self, msg: Message) -> None:
        # arrival stamp BEFORE the queue: dequeue - arrival is queue
        # wait, site-send - arrival (offset-corrected) is the wire leg.
        # The attribute lives on the in-memory Message only — never
        # serialized, so the wire stays byte-identical either way.
        if self.tracer is not None:
            msg.xt_arrival_ns = self.tracer.wall_ns()
        self._observe_heartbeat(msg)
        self._updates.put(msg)

    # -- fleet ledger (heartbeat-gated, byte-inert off) -------------------
    def _observe_heartbeat(self, msg: Message) -> None:
        """Fold an inbound frame's piggybacked ``hb_*`` headers (or a
        standalone HEARTBEAT frame) into the ledger; heartbeat-free
        frames read unchanged."""
        if self.ledger is None:
            return
        hb = obs_live.extract_heartbeat(msg)
        if hb is None:
            return
        with self._ledger_lock:
            events = self.ledger.observe(
                hb["peer"], time.monotonic(),
                round_idx=hb["round"], gauges=hb["gauges"])
        for ev in events:
            self._emit_live_event(ev)

    def _on_heartbeat(self, msg: Message) -> None:
        self._observe_heartbeat(msg)

    def _emit_live_event(self, ev) -> None:
        rec = ev.to_record()
        logger.warning("fleet: %s", ev.message)
        if self.events is not None:
            with self._ledger_lock:
                self.events.write(rec)

    def _ledger_tick(self) -> None:
        """Advance the liveness clocks (SITE_DOWN fires here — from
        the round loop, so detection happens WHILE a collect wait is
        still pending, not after the round timeout)."""
        if self.ledger is None:
            return
        with self._ledger_lock:
            events = self.ledger.tick(time.monotonic())
        for ev in events:
            self._emit_live_event(ev)

    def _get_update(self, timeout: float) -> Message:
        """``_updates.get`` that keeps the ledger ticking: with
        heartbeats on, the blocking wait is sliced at the heartbeat
        interval so a dying site turns SUSPECT/DOWN mid-wait instead
        of only after the round timeout. Raises ``queue.Empty`` after
        ``timeout`` like the plain get."""
        if self.ledger is None:
            return self._updates.get(timeout=timeout)
        deadline = time.monotonic() + max(0.0, float(timeout))
        while True:
            self._ledger_tick()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise queue.Empty
            try:
                return self._updates.get(timeout=min(
                    remaining, self.ledger.interval_s))
            except queue.Empty:
                continue

    def _on_hello_ack(self, msg: Message) -> None:
        t2 = self.tracer.wall_ns() if self.tracer is not None \
            else time.time_ns()
        self._hello_acks.put({"rank": int(msg.get("rank", -1)),
                              "t0": float(msg.get("t0_ns", 0)),
                              "t1": float(msg.get("t1_ns", 0)),
                              "t2": float(t2)})

    def clock_sync(self, timeout_s: Optional[float] = None) -> None:
        """One HELLO handshake per site: NTP-midpoint clock-offset
        estimate (``xtrace.ntp_offset``) recorded on the tracer, keying
        both the merged-trace lane alignment and the per-update wire
        attribution. Only ever called when tracing is on. Re-invoked
        every ``CLOCK_RESYNC_EVERY`` rounds (with a short timeout so a
        dead site cannot stall the round loop); ``note_offset``
        overwrites, so the freshest estimate wins everywhere."""
        if self.tracer is None:
            return
        for k in range(1, self.n_sites + 1):
            self._send(protocol.hello_message(
                0, k, self.tracer.wall_ns()))
        deadline = time.monotonic() + (
            self.timeout_s if timeout_s is None else float(timeout_s))
        got = 0
        while got < self.n_sites:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                ack = self._hello_acks.get(timeout=remaining)
            except queue.Empty:
                break
            offset, rtt = xtrace.ntp_offset(
                ack["t0"], ack["t1"], ack["t2"])
            self.tracer.note_offset(
                f"site{int(ack['rank'])}", offset, rtt)
            got += 1
        if got < self.n_sites:
            logger.warning("fed hello: %d/%d sites answered before "
                           "timeout; missing lanes merge unaligned",
                           got, self.n_sites)

    def _note_arrival(self, msg: Message) -> None:
        """Fold one dequeued update into the round's queue-wait and
        wire-leg accumulators (tracing on; no-op otherwise)."""
        if self.tracer is None:
            return
        arrival = getattr(msg, "xt_arrival_ns", None)
        if arrival is None:
            return
        self._xt_queue_ns += max(
            0.0, self.tracer.wall_ns() - arrival)
        send = xtrace.send_wall_ns(msg)
        if send is None:
            return
        site = msg.get("site")
        peer = f"site{int(site)}" if site is not None else ""
        self._xt_wire_ns += max(
            0.0, arrival - self.tracer.to_ref_ns(send, peer))

    # -- Byzantine screen / robust combine --------------------------------
    def _byzantine_screen(self, round_idx: int, sites: List[int],
                          norms: List[float]) -> List[int]:
        """Flag members whose delta norm exceeds ``BYZ_NORM_FACTOR`` x
        the running median member norm (history + this round — the
        history keeps the baseline honest-dominated even when one flush
        holds too few members for a meaningful within-flush median).
        Emits ONE typed BYZANTINE event naming the flagged sites.
        Norms append in member order at aggregate time, so a trace
        replay reproduces the identical screen decisions."""
        self._norm_history.extend(float(x) for x in norms)
        self._norm_history = self._norm_history[-256:]
        med = float(np.median(np.asarray(self._norm_history,
                                         np.float32)))
        flagged = [int(s) for s, nm in zip(sites, norms)
                   if nm > BYZ_NORM_FACTOR * max(med, 1e-12)]
        if flagged:
            for s in flagged:
                self.byzantine_flags[s] = \
                    self.byzantine_flags.get(s, 0) + 1
            logger.warning(
                "round %d BYZANTINE screen: sites %s ship deltas > "
                "%gx the median member norm (%.3g)", round_idx,
                flagged, BYZ_NORM_FACTOR, med)
            self._event(round_idx, "BYZANTINE", sites=flagged,
                        norm_median=med,
                        norms={str(int(s)): float(n)
                               for s, n in zip(sites, norms)})
        return flagged

    def _robust_combine(self, delta_mat: np.ndarray,
                        weights: np.ndarray) -> np.ndarray:
        """One robust [N] delta from the [M, N] member-delta matrix —
        the same ``robust_combine_mat`` estimator the in-jit round body
        runs, evaluated on the aggregator host (same function, same
        inputs: deterministic for record AND replay)."""
        import jax.numpy as jnp

        from ..robust.aggregation import robust_combine_mat

        return np.asarray(robust_combine_mat(
            jnp.asarray(delta_mat), jnp.asarray(weights),
            self.robust_agg, trim_frac=self.robust_trim,
            krum_f=self.robust_krum_f,
            norm_bound=self.robust_norm_bound), np.float32)

    # -- shared plumbing --------------------------------------------------
    def _send(self, msg: Message) -> None:
        protocol.send_with_retry(self, msg, retries=self.retries,
                                 backoff_s=self.backoff_s)

    def _event(self, round_idx: int, event_type: str, **extra) -> None:
        if self.events is not None:
            self.events.write({"round": int(round_idx),
                               "event_type": event_type, **extra})

    def _record(self, rec: Dict[str, Any]) -> None:
        if self.ledger is not None and int(rec.get("round", -1)) >= 0:
            # federation-scope gauges join the round record BEFORE the
            # SLO engine sees it, so --slo_spec can declare fleet
            # objectives (min sites live, max heartbeat age). The keys
            # are volatile in obs/diff.py — heartbeat-on twins stay
            # ``identical``.
            self._ledger_tick()
            with self._ledger_lock:
                self.ledger.note_round(int(rec["round"]))
                rec = {**rec, **self.ledger.fleet_gauges(
                    time.monotonic())}
        self.history.append(rec)
        if self.slo is not None and int(rec.get("round", -1)) >= 0:
            # live SLO evaluation on the federation round stream
            # (PR 10 engine): p95:fed_round_ms<... style objectives
            # breach DURING the run, not in a postmortem
            rec = dict(rec)
            for ev in self.slo.observe(rec):
                if self.events is not None:
                    with self._ledger_lock:
                        self.events.write(ev.to_record())
            rec["slo_health"] = self.slo.health
            rec["slo_breached"] = float(len(self.slo.breached))
            rec["obs_schema"] = record_schema(rec)
            self.history[-1] = rec
        if self.writer is not None:
            self.writer.write(rec)

    def prom_snapshot(self) -> Dict[str, Any]:
        """The ``/metrics`` body source (``obs/prom.py``): the
        process-global registry snapshot joined with this process's
        comm counters and (heartbeats on) the live fleet gauges —
        rendered at scrape time, so the scrape tracks the run."""
        from ..obs import metrics as obs_metrics

        snap = dict(obs_metrics.get_registry().snapshot())
        for k, v in self.comm.counters.snapshot().items():
            snap[k] = {"type": "counter", "value": float(v)}
        if self.ledger is not None:
            with self._ledger_lock:
                fleet = self.ledger.fleet_gauges(time.monotonic())
            for k, v in fleet.items():
                snap[k] = {"type": "gauge", "value": float(v)}
        return snap

    def execute(self) -> None:
        """Run the configured number of rounds (sync) or flushes
        (buffered), then tell every site to finish."""
        self.clock_sync()
        if self.mode == "sync":
            for r in range(self.rounds):
                self.run_sync_round(r)
        elif self.replay_trace is not None:
            self.run_buffered_replay()
        else:
            self.run_buffered()
        with xtrace.xspan(self.tracer, "finish",
                          trace_id="finish") as fin:
            for dest in range(1, self.world_size):
                msg = Message(protocol.MSG_FED_FINISH, 0, dest)
                if self.tracer is not None:
                    xtrace.inject(msg, fin.ctx(),
                                  wall_ns=self.tracer.wall_ns())
                try:
                    self._send(msg)
                except OSError:
                    logger.warning("site %d unreachable at finish", dest)
        if self.writer is not None:
            self._record({"round": -1, "fed_mode": self.mode,
                          "fed_version": self.version,
                          "fed_stale_drops": self.stale_drops,
                          "fed_staleness_hist": {
                              str(k): v for k, v
                              in sorted(self.staleness_hist.items())},
                          **self.comm.counters.snapshot()})
            self.writer.close()
        if self.events is not None:
            self.events.close()

    # -- synchronous barrier ---------------------------------------------
    def run_sync_round(self, round_idx: int) -> str:
        """One barrier round; returns completed|quorum|timeout."""
        import jax
        import jax.numpy as jnp

        tr = self.tracer
        if tr is not None and round_idx > 0 and \
                round_idx % CLOCK_RESYNC_EVERY == 0:
            # drift fix: refresh the per-site offsets between rounds
            # (sites are idle at the barrier, so acks are immediate; a
            # dead site only costs the short bounded wait)
            self.clock_sync(timeout_s=min(self.timeout_s, 2.0))
        if self.ledger is not None:
            with self._ledger_lock:
                self.ledger.note_round(round_idx)
        self._xt_wire_ns = self._xt_queue_ns = 0.0
        t_round = time.perf_counter()
        # the round's trace tree: minted from the round index, so twin
        # runs produce identical ids (the structure-determinism contract)
        with xtrace.xspan(tr, "fed_round", trace_id=f"r{round_idx}",
                          args={"round": round_idx}) as rspan:
            algo = self.algo
            sel = algo._selected_client_indexes(round_idx)
            s_total = int(sel.shape[0])
            self.rng, round_key = jax.random.split(self.rng)
            parts = protocol.partition_slots(s_total, self.n_sites)
            with xtrace.xspan(tr, "dispatch",
                              args={"sites": self.n_sites}) as dspan:
                for k in range(1, self.n_sites + 1):
                    pos = parts[k - 1]
                    msg = Message(protocol.MSG_FED_TRAIN, 0, k)
                    msg.add("version", round_idx)
                    msg.add("mode", "sync")
                    msg.add("cohort_size", s_total)
                    msg.add_tensor("params", self.global_params)
                    msg.add_tensor("round_key", np.asarray(round_key))
                    msg.add_tensor("client_ids",
                                   sel[pos].astype(np.int32))
                    msg.add_tensor("slot_pos", pos.astype(np.int32))
                    if tr is not None:
                        xtrace.inject(msg, dspan.ctx(),
                                      wall_ns=tr.wall_ns())
                    self._send(msg)
            rows_by_site: Dict[int, Any] = {}
            losses_by_site: Dict[int, np.ndarray] = {}
            with xtrace.xspan(tr, "collect"):
                deadline = time.monotonic() + self.timeout_s
                while len(rows_by_site) < self.n_sites:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        msg = self._get_update(remaining)
                    except queue.Empty:
                        break
                    self._note_arrival(msg)
                    if msg.get("mode") != "sync" or \
                            int(msg.get("version")) != round_idx:
                        logger.warning(
                            "dropping stale fed update (site %s, version "
                            "%s != round %d)", msg.get("site"),
                            msg.get("version"), round_idx)
                        continue
                    site = int(msg.get("site"))
                    if site in rows_by_site:
                        logger.warning(
                            "duplicate update from site %d dropped", site)
                        continue
                    rows_by_site[site] = msg.get_tensor("rows")
                    losses_by_site[site] = np.asarray(
                        msg.get_tensor("losses"))
            received = sorted(rows_by_site)
            missing = [k for k in range(1, self.n_sites + 1)
                       if k not in rows_by_site]
            if not received:
                logger.warning(
                    "sync round %d TIMEOUT: no site reported; global "
                    "carried", round_idx)
                self._event(round_idx, "fed_timeout",
                            sites_missing=missing)
                rspan.add(status="timeout")
                self._record(self._xt_round_rec(
                    {"round": round_idx, "train_loss": float("nan"),
                     "sites_reported": 0, "fed_status": "timeout"},
                    t_round))
                self.version = round_idx + 1
                return "timeout"
            with xtrace.xspan(tr, "combine",
                              args={"robust": self.robust_agg,
                                    "members": len(received)}):
                # reassemble the cohort in slot order: partitions are
                # contiguous blocks, so concatenating the received
                # sites' rows in rank order restores ascending slot
                # positions
                slot_pos = np.concatenate(
                    [parts[k - 1] for k in received])
                stacked = jax.tree_util.tree_map(
                    lambda *xs: jnp.asarray(np.concatenate(xs, axis=0)),
                    *[rows_by_site[k] for k in received])
                losses = jnp.asarray(np.concatenate(
                    [losses_by_site[k] for k in received]))
                n_all = np.asarray(algo.data.n_train)[sel]
                n_sel = jnp.asarray(n_all[slot_pos])
                # the in-process aggregation, verbatim (base.py round
                # body): f32 sample weights normalized over whoever
                # reported — all sites is the bit-parity path, a subset
                # is the survivor-renormalization degradation
                weights = n_sel.astype(jnp.float32)
                weights = weights / jnp.maximum(jnp.sum(weights), 1.0)
                # Byzantine norm screen: per-SITE delta norm of the
                # shipped rows against the running median (detection;
                # typed event)
                gl = [np.asarray(x, np.float32) for x in
                      jax.tree_util.tree_leaves(self.global_params)]
                site_norms = []
                for k in received:
                    d2 = 0.0
                    for rl, g in zip(
                            jax.tree_util.tree_leaves(rows_by_site[k]),
                            gl):
                        d = np.asarray(rl, np.float32) - g[None]
                        d2 += float(np.sum(d * d))
                    site_norms.append(float(np.sqrt(d2)))
                flagged = self._byzantine_screen(
                    round_idx, received, site_norms)
                if self.robust_agg != "none":
                    # the in-process _robust_aggregate, verbatim over
                    # the same [S]-stacked client rows: robust statistic
                    # on the deltas, survivor mask from the
                    # (renormalized) weights — loopback sync stays the
                    # bit-parity anchor under attack too
                    from ..parallel import collectives

                    spec = collectives.flat_spec(stacked, stacked=True)
                    gvec = collectives.tree_to_vec(
                        self.global_params).astype(jnp.float32)
                    combined = self._robust_combine(
                        np.asarray(collectives.stacked_to_mat(stacked)
                                   - gvec[None]),
                        np.asarray(weights, np.float32))
                    self.global_params = collectives.vec_to_tree(
                        jnp.asarray(np.asarray(gvec) + combined), spec)
                else:
                    self.global_params = weighted_tree_sum(
                        stacked, weights)
                loss = float(jnp.mean(losses))
            self.version = round_idx + 1
            status = "completed" if not missing else "quorum"
            if missing:
                logger.warning(
                    "sync round %d QUORUM %d/%d (missing sites %s; "
                    "weights renormalized)", round_idx, len(received),
                    self.n_sites, missing)
                self._event(round_idx, "fed_quorum",
                            sites_missing=missing)
            rspan.add(status=status)
            self._record(self._xt_round_rec(
                {"round": round_idx, "train_loss": loss,
                 "sites_reported": len(received),
                 "fed_status": status,
                 "fed_byzantine_flagged": len(flagged)}, t_round))
        return status

    def _xt_round_rec(self, rec: Dict[str, Any],
                      t_round: float) -> Dict[str, Any]:
        """Join the round's critical-path metrics onto its record
        (tracing on only — the keys are volatile in ``obs/diff.py``, so
        twins with tracing off still gate ``identical``)."""
        if self.tracer is None:
            return rec
        rec["fed_round_ms"] = (time.perf_counter() - t_round) * 1e3
        rec["fed_wire_ms"] = self._xt_wire_ns / 1e6
        rec["fed_queue_ms"] = self._xt_queue_ns / 1e6
        self._xt_wire_ns = self._xt_queue_ns = 0.0
        return rec

    # -- buffered async (FedBuff) ----------------------------------------
    def _np_global(self) -> Any:
        import jax

        return jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32), self.global_params)

    def _dispatch_train(self, site: int, version: int) -> None:
        msg = Message(protocol.MSG_FED_TRAIN, 0, site)
        msg.add("version", int(version))
        msg.add("mode", "buffered")
        msg.add_tensor("params", self.global_params)
        msg.add_tensor(
            "client_ids", self.partition[site - 1].astype(np.int32))
        # buffered trace trees are keyed by the dispatched base version
        # (the async analogue of the sync round id)
        with xtrace.xspan(self.tracer, "dispatch",
                          trace_id=f"v{int(version)}",
                          args={"site": int(site)}) as dspan:
            if self.tracer is not None:
                xtrace.inject(msg, dspan.ctx(),
                              wall_ns=self.tracer.wall_ns())
            self._send(msg)

    def _entry(self, msg: Message) -> Tuple[int, int, Any, float, float]:
        return (int(msg.get("site")), int(msg.get("version")),
                wire.decode_update(msg), float(msg.get("n_sum")),
                float(msg.get("train_loss")))

    def _flush(self, members: List[Tuple[int, int, Any, float, float]],
               flush_idx: int, depth: int, quorum: bool = False) -> None:
        """Apply one buffer of deltas: staleness-discounted weights
        ``n_i / sqrt(1 + tau_i)`` normalized over the members, summed in
        member (arrival) order — all float32 numpy, so a replayed flush
        with the same members in the same order is bit-identical."""
        import jax
        import jax.numpy as jnp

        t_round = self._xt_round_t0
        with xtrace.xspan(self.tracer, "flush",
                          trace_id=f"v{self.version + 1}",
                          args={"members": len(members),
                                "quorum": bool(quorum)}):
            taus = [self.version - base for _, base, _, _, _ in members]
            for t in taus:
                self.staleness_hist[t] = \
                    self.staleness_hist.get(t, 0) + 1
            raw = []
            for (_, _, _, n_sum, _), tau in zip(members, taus):
                raw.append(np.float32(n_sum) /
                           np.float32(np.sqrt(np.float32(1.0 + tau))))
            wsum = np.float32(0.0)
            for w in raw:
                wsum = np.float32(wsum + w)
            wnorm = [np.float32(w / wsum) for w in raw]
            g = self._np_global()
            leaves, treedef = jax.tree_util.tree_flatten(g)
            deltas = [jax.tree_util.tree_flatten(d)[0]
                      for _, _, d, _, _ in members]
            # Byzantine norm screen over the flush members (typed event)
            member_sites = [site for site, _, _, _, _ in members]
            norms = [float(np.sqrt(sum(
                float(np.sum(np.square(np.asarray(dl_i, np.float32))))
                for dl_i in dl))) for dl in deltas]
            flagged = self._byzantine_screen(
                flush_idx, member_sites, norms)
            if self.robust_agg != "none":
                # robust statistic over the member deltas: the
                # staleness-discounted weights keep gating MEMBERSHIP
                # (a zero weight is a masked row) while influence is
                # the estimator's — FedBuff's n/sqrt(1+tau) discount no
                # longer scales a colluding stale attacker's pull, it
                # only ranks it
                mat = np.stack([np.concatenate(
                    [np.asarray(x, np.float32).ravel() for x in dl])
                    for dl in deltas])
                combined = self._robust_combine(
                    mat, np.asarray(wnorm, np.float32))
                new_leaves = []
                off = 0
                for leaf in leaves:
                    n = int(leaf.size)
                    new_leaves.append(
                        leaf + combined[off:off + n].reshape(leaf.shape))
                    off += n
            else:
                new_leaves = []
                for i, leaf in enumerate(leaves):
                    out = leaf.copy()
                    for w, dl in zip(wnorm, deltas):
                        out += w * np.asarray(dl[i], np.float32)
                    new_leaves.append(out)
            self.global_params = jax.tree_util.tree_map(
                jnp.asarray,
                jax.tree_util.tree_unflatten(treedef, new_leaves))
            self.version += 1
        losses = [loss for _, _, _, _, loss in members]
        mean_loss = float(np.mean(np.asarray(losses, np.float32)))
        member_ids = [[site, base] for site, base, _, _, _ in members]
        self.trace["flushes"].append(
            {"version": self.version, "members": member_ids})
        self._event(flush_idx, "fed_flush", members=member_ids,
                    buffer_depth=depth, quorum=quorum)
        # flush-to-flush wall time is the buffered analogue of the sync
        # round clock
        self._xt_round_t0 = time.perf_counter()
        self._record(self._xt_round_rec(
            {"round": flush_idx, "train_loss": mean_loss,
             "fed_version": self.version,
             "fed_buffer_depth": depth,
             "fed_staleness_max": int(max(taus)),
             "fed_staleness_mean": float(np.mean(taus)),
             "fed_quorum_flush": bool(quorum),
             "fed_stale_drops": self.stale_drops,
             "fed_byzantine_flagged": len(flagged)}, t_round))

    def run_buffered(self) -> None:
        for k in range(1, self.n_sites + 1):
            self._dispatch_train(k, 0)
        buffer: List[Tuple[int, int, Any, float, float]] = []
        flushes = 0
        while flushes < self.rounds:
            if self.tracer is not None and flushes > 0 and \
                    flushes % CLOCK_RESYNC_EVERY == 0 and \
                    not self._resynced_at == flushes:
                self._resynced_at = flushes
                self.clock_sync(timeout_s=min(self.timeout_s, 2.0))
            try:
                msg = self._get_update(self.timeout_s)
                self._note_arrival(msg)
            except queue.Empty:
                if buffer:
                    # degrade: flush what arrived rather than stall the
                    # federation on a dead/straggling site
                    members, buffer = buffer, []
                    self._flush(members, flushes, len(members),
                                quorum=True)
                    flushes += 1
                    for site, _, _, _, _ in members:
                        self._dispatch_train(site, self.version)
                    continue
                raise RuntimeError(
                    f"buffered federation stalled: no update within "
                    f"{self.timeout_s}s and the buffer is empty")
            site, base, delta, n_sum, loss = self._entry(msg)
            tau = self.version - base
            if tau > self.staleness_bound:
                self.stale_drops += 1
                self._event(flushes, "fed_stale_drop", site=site,
                            base_version=base, staleness=tau)
                self._dispatch_train(site, self.version)
                continue
            buffer.append((site, base, delta, n_sum, loss))
            if len(buffer) >= self.buffer_k:
                members, buffer = buffer[:self.buffer_k], \
                    buffer[self.buffer_k:]
                self._flush(members, flushes,
                            len(members) + len(buffer))
                flushes += 1
                for site, _, _, _, _ in members:
                    self._dispatch_train(site, self.version)

    # -- deterministic replay --------------------------------------------
    def _replay_dispatch(self, version: int,
                         remaining: List[List[List[int]]]) -> None:
        """Dispatch TRAIN@version to every site the trace says will
        contribute a delta with this base version — the only dispatches
        whose results the replay will consume."""
        sites = sorted({s for flush in remaining for s, b in flush
                        if b == version})
        for s in sites:
            self._dispatch_train(s, version)

    def run_buffered_replay(self) -> None:
        trace = self.replay_trace
        flushes = trace.get("flushes", [])
        if int(trace.get("sites", self.n_sites)) != self.n_sites:
            raise ValueError(
                f"trace was recorded with {trace.get('sites')} sites, "
                f"this federation has {self.n_sites}")
        # record mode dispatches TRAIN@0 to every site at start; the
        # deltas a replay consumes are the traced subset
        for k in range(1, self.n_sites + 1):
            self._dispatch_train(k, 0)
        pool: Dict[Tuple[int, int], Tuple[int, int, Any, float, float]] \
            = {}
        for flush_idx, flush in enumerate(flushes):
            need = [(int(s), int(b)) for s, b in flush["members"]]
            while not all(k in pool for k in need):
                try:
                    msg = self._get_update(self.timeout_s)
                    self._note_arrival(msg)
                except queue.Empty:
                    waiting = [k for k in need if k not in pool]
                    raise RuntimeError(
                        f"trace replay stalled waiting for deltas "
                        f"{waiting} (flush {flush_idx})") from None
                entry = self._entry(msg)
                pool.setdefault((entry[0], entry[1]), entry)
            members = [pool[k] for k in need]
            self._flush(members, flush_idx, len(members))
            rest = [f["members"] for f in flushes[flush_idx + 1:]]
            self._replay_dispatch(self.version, rest)
