"""SiteTrainer: the local-training half of the fused round body, split
out so a site process can train its own clients and ship results.

The in-process simulation runs broadcast -> vmapped local SGD ->
weighted aggregate as ONE jitted program
(``algorithms/base.py _train_selected_weighted``). A federation cuts
that program at the aggregation boundary: each site runs the broadcast
+ vmap half over ITS clients only, and the aggregator owns the
weighted sum. Bit-parity with the fused program rests on two pinned
invariants of this codebase:

* width polymorphism — the vmapped ``client_update`` produces
  bit-identical rows at any batch width (the ``client_chunk`` /
  client-store parity tests), so a site vmapping s rows matches the
  corresponding rows of the S-wide in-process vmap;
* key slotting — sync sites compute the FULL ``split(round_key, S+1)``
  and take their slot positions, so every client consumes exactly the
  key it would have in-process (``keys[S]`` stays the aggregator-side
  defense key, unused here).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.state import broadcast_tree, weighted_tree_sum, zeros_like_tree


class SiteTrainer:
    """Jitted site-local round programs over an algorithm's
    ``client_update`` and data shards. One instance per site process
    (shared across site threads on the loopback backend — jit execution
    is thread-safe and the programs are cached per cohort width)."""

    def __init__(self, algo: Any):
        self.algo = algo
        self._sync_cache: Dict[int, Any] = {}
        self._delta_jit = jax.jit(self._delta_body)

    # -- sync: the bit-parity path ---------------------------------------
    def _sync_fn(self, cohort_size: int):
        """Per-cohort-size jitted body (S is static: it sizes the key
        split exactly as the in-process round body does)."""
        fn = self._sync_cache.get(cohort_size)
        if fn is None:
            algo = self.algo

            def body(global_params, round_key, client_ids, slot_pos,
                     round_idx, x_train, y_train, n_train):
                s = client_ids.shape[0]
                x_sel = jnp.take(x_train, client_ids, axis=0)
                y_sel = jnp.take(y_train, client_ids, axis=0)
                n_sel = jnp.take(n_train, client_ids)
                params0 = broadcast_tree(global_params, s)
                mask_b = broadcast_tree(global_params, s)
                mom0 = zeros_like_tree(params0)
                # the FULL in-process key fan-out, then this site's slots
                keys = jnp.take(
                    jax.random.split(round_key, cohort_size + 1)[
                        :cohort_size],
                    slot_pos, axis=0)
                params_out, _, losses = algo._vmap_clients(
                    algo.client_update,
                    in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0),
                )(params0, mom0, mask_b, keys, x_sel, y_sel, n_sel,
                  round_idx, params0)
                return params_out, losses

            fn = jax.jit(body)
            self._sync_cache[cohort_size] = fn
        return fn

    def train_sync(self, global_params: Any, round_key: Any,
                   round_idx: int, client_ids: np.ndarray,
                   slot_pos: np.ndarray, cohort_size: int
                   ) -> Tuple[Any, np.ndarray]:
        """Train this site's slice of a synchronous round: returns the
        [s]-stacked locally-trained models and their [s] losses, as
        host numpy (bit-preserving device -> host copy)."""
        d = self.algo.data
        rows, losses = self._sync_fn(int(cohort_size))(
            global_params, jnp.asarray(round_key),
            jnp.asarray(client_ids, jnp.int32),
            jnp.asarray(slot_pos, jnp.int32),
            jnp.asarray(round_idx, jnp.float32),
            d.x_train, d.y_train, d.n_train)
        return (jax.tree_util.tree_map(np.asarray, rows),
                np.asarray(losses))

    # -- buffered: delta extraction --------------------------------------
    def _delta_body(self, global_params, base_key, client_ids, round_idx,
                    x_train, y_train, n_train):
        s = client_ids.shape[0]
        x_sel = jnp.take(x_train, client_ids, axis=0)
        y_sel = jnp.take(y_train, client_ids, axis=0)
        n_sel = jnp.take(n_train, client_ids)
        params0 = broadcast_tree(global_params, s)
        mask_b = broadcast_tree(global_params, s)
        mom0 = zeros_like_tree(params0)
        keys = jax.random.split(base_key, s)
        params_out, _, losses = self.algo._vmap_clients(
            self.algo.client_update,
            in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0),
        )(params0, mom0, mask_b, keys, x_sel, y_sel, n_sel,
          round_idx, params0)
        # the site's shipped update: sample-weighted mean of its
        # clients' deltas (FedBuff's per-worker update), plus the
        # weight mass it represents
        w = n_sel.astype(jnp.float32)
        w = w / jnp.maximum(jnp.sum(w), 1.0)
        delta = weighted_tree_sum(
            jax.tree_util.tree_map(
                lambda po, p0: po - p0, params_out, params0), w)
        return delta, jnp.sum(n_sel.astype(jnp.float32)), jnp.mean(losses)

    def train_delta(self, global_params: Any, base_key: Any,
                    version: int, client_ids: np.ndarray
                    ) -> Tuple[Any, float, float]:
        """Train ALL of this site's clients from ``global_params``
        (the model at ``version``) and return
        ``(delta_tree, n_sum, mean_loss)`` as host numpy."""
        d = self.algo.data
        delta, n_sum, loss = self._delta_jit(
            global_params, jnp.asarray(base_key),
            jnp.asarray(client_ids, jnp.int32),
            jnp.asarray(version, jnp.float32),
            d.x_train, d.y_train, d.n_train)
        return (jax.tree_util.tree_map(np.asarray, delta),
                float(n_sum), float(loss))
