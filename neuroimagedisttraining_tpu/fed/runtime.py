"""Federation runtime: role dispatch, the loopback harness, refusals,
and the per-site observability fold.

``run_federated(args, algo_name)`` is the ``--fed_role`` entry the
runner dispatches to (``experiments/runner.py run_experiment``). Three
shapes of run:

* ``--fed_backend local`` — the single-process loopback: one
  ``LocalRouter``, sites on receive-pump threads sharing one built
  algorithm, the aggregator in the calling thread. This is the test
  and CI shape (``scripts/fed_smoke.py``) and the sync bit-parity
  anchor.
* ``--fed_backend tcp --fed_role aggregator`` — rank 0 of a real
  multi-process federation over the native TCP transport.
* ``--fed_backend tcp --fed_role site --fed_site_rank k`` — site
  process k (forked by ``scripts/run_federation.py``).

Every process writes its own JSONL round/event streams into the fed
output directory; the aggregator folds them into ``federation.jsonl``
/ ``federation.events.jsonl`` with ``obs.export.merge_host_jsonl`` /
``merge_host_events`` — the multihost fold, reused verbatim (events
fold with ``dedupe=False``: the same event type in the same round on
two SITES is two events, not a rerun duplicate).
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import xtrace
from ..obs.xtrace import XTracer
from ..robust.faults import FaultSpec, parse_fault_spec
from . import wire
from .aggregator import FedAggregator
from .site import SiteWorker
from .trainer import SiteTrainer

logger = logging.getLogger(__name__)

#: default real-process sleep for a site whose straggle fault fires
DEFAULT_STRAGGLE_S = 2.0


def parse_site_faults(
        spec: str) -> Dict[int, Tuple[Optional[FaultSpec], float, float]]:
    """``"rank:fault_spec[:delay_s];..."`` -> {site_rank: (FaultSpec,
    straggle_sleep_s, kill_after_s)}.

    The fault grammar is ``robust.faults.parse_fault_spec``'s
    (``drop=p,straggle=p,...``); the optional trailing ``:delay_s``
    sets how long a fired straggle sleeps the REAL site process
    (default ``DEFAULT_STRAGGLE_S``). Example:
    ``"3:straggle=1.0:6.0"`` — site 3 always straggles, 6s per round.
    ``"rank:byzantine"`` is sugar for ``rank:scale=1.0`` — an
    always-lying site shipping the 100x-forged delta every round.
    ``"rank:kill[:after_s]"`` is the process-death fault: the site goes
    COMPLETELY silent (no replies, no heartbeats, pump stopped)
    ``after_s`` seconds in — the fleet ledger's SITE_DOWN detection
    target, as distinct from ``drop`` (alive but withholding).
    Raises ``ValueError`` on malformed entries (parse-time validation,
    the derive() contract)."""
    out: Dict[int, Tuple[Optional[FaultSpec], float, float]] = {}
    if not spec:
        return out
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        rank_s, sep, rest = entry.partition(":")
        if not sep or not rest:
            raise ValueError(
                f"fed_site_faults entry {entry!r} is not "
                "rank:fault_spec[:delay_s]")
        try:
            rank = int(rank_s)
        except ValueError:
            raise ValueError(
                f"fed_site_faults rank {rank_s!r} is not an int") from None
        if rank < 1:
            raise ValueError(
                f"fed_site_faults rank {rank} must be >= 1 (site ranks)")
        delay = DEFAULT_STRAGGLE_S
        head, sep2, tail = rest.rpartition(":")
        if sep2 and "=" not in tail:
            try:
                delay = float(tail)
            except ValueError:
                raise ValueError(
                    f"fed_site_faults trailing field {tail!r} is neither "
                    "a fault clause nor a delay") from None
            rest = head
        if rank in out:
            raise ValueError(f"duplicate fed_site_faults rank {rank}")
        if rest == "kill":
            out[rank] = (None, 0.0, delay)
            continue
        if rest == "byzantine":
            # the Byzantine-role sugar: scale fires every round at the
            # default 100x factor (parse_fault_spec's scale_factor)
            rest = "scale=1.0"
        fs = parse_fault_spec(rest)
        if fs is None:
            raise ValueError(
                f"fed_site_faults entry {entry!r} has an empty fault spec")
        out[rank] = (fs, delay, 0.0)
    return out


def parse_endpoints(spec: str, world_size: int
                    ) -> List[Tuple[str, int]]:
    """``"host:port,host:port,..."`` rank-ordered (rank 0 = aggregator)."""
    eps = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, port = part.rpartition(":")
        if not sep:
            raise ValueError(
                f"fed_endpoints entry {part!r} is not host:port")
        eps.append((host, int(port)))
    if len(eps) != world_size:
        raise ValueError(
            f"fed_endpoints has {len(eps)} entries, need "
            f"{world_size} (aggregator + {world_size - 1} sites)")
    return eps


def _refuse(why: str) -> None:
    raise SystemExit(f"federated deployment: {why}")


def validate_fed_args(args, algo_name: str) -> None:
    """The fed-mode refusal cluster (the runner's SystemExit idiom):
    every in-process feature whose semantics a multi-process federation
    does not (yet) reproduce refuses loudly instead of silently
    diverging from the simulation."""
    if algo_name != "fedavg":
        _refuse(f"algo {algo_name!r} unsupported — the federation "
                "ships FedAvg's round body; run --algo fedavg")
    n_sites = int(getattr(args, "fed_sites", 0))
    if n_sites < 1:
        _refuse("--fed_sites must be >= 1")
    mode = getattr(args, "fed_mode", "")
    if mode not in ("sync", "buffered"):
        _refuse(f"unknown --fed_mode {mode!r}")
    if getattr(args, "fuse_rounds", 1) > 1:
        _refuse("--fuse_rounds > 1 fuses rounds into one device program;"
                " a federation advances the model over a wire per round")
    if getattr(args, "watchdog", None):
        _refuse("--watchdog rollback-retry drives the in-process round "
                "loop; the federation's degradation is quorum/staleness")
    if getattr(args, "client_store", "device") != "device":
        _refuse("--client_store host/disk residency is an in-process "
                "optimization; each site already holds only its clients")
    if getattr(args, "multihost", False):
        _refuse("--multihost (one model, many hosts, XLA collectives) "
                "and --fed_role (many models, message passing) are "
                "different distribution axes; pick one")
    if getattr(args, "defense_type", "none") not in ("", "none"):
        _refuse("robust defenses transform the [S]-stacked cohort "
                "inside one program; the aggregator only sees deltas")
    if getattr(args, "fault_spec", ""):
        _refuse("--fault_spec injects simulated in-jit faults; use "
                "--fed_site_faults to fault REAL site processes")
    if getattr(args, "eval_cache", 0):
        _refuse("--eval_cache rides in-process round state")
    if getattr(args, "checkpoint_dir", ""):
        _refuse("--checkpoint_dir round-granular checkpointing is not "
                "wired into the federation lifecycle yet")
    if getattr(args, "mesh_space", 1) > 1:
        _refuse("--mesh_space > 1 shards one simulation over a mesh")
    impl = getattr(args, "agg_impl", "dense")
    if mode == "sync":
        if impl != "dense":
            _refuse("sync federation ships full params dense — the "
                    "bit-parity anchor; compressed delta wires "
                    f"(--agg_impl {impl}) ride --fed_mode buffered")
        # the cohort-must-cover-sites check runs after build (needs C)
    else:
        if impl not in wire.WIRE_IMPLS:
            _refuse(f"--agg_impl {impl!r} has no federation wire codec "
                    f"(supported: {wire.WIRE_IMPLS})")
        if abs(getattr(args, "frac", 1.0) - 1.0) > 1e-9:
            _refuse("buffered federation trains each site's full client "
                    "block every dispatch; --frac sampling is a sync-"
                    "mode concept")
        if not 1 <= int(getattr(args, "fed_buffer_k", 0)) <= n_sites:
            _refuse(f"--fed_buffer_k must be in [1, fed_sites="
                    f"{n_sites}]")
        if int(getattr(args, "fed_staleness_bound", 0)) < 0:
            _refuse("--fed_staleness_bound must be >= 0")
    if getattr(args, "fed_replay", "") and mode != "buffered":
        _refuse("--fed_replay replays a buffered arrival trace; sync "
                "rounds are already deterministic")
    faults = parse_site_faults(getattr(args, "fed_site_faults", ""))
    for rank in faults:
        if rank > n_sites:
            _refuse(f"--fed_site_faults names site {rank} but there are "
                    f"only {n_sites} sites")


def _out_dir(args, identity: str) -> str:
    d = getattr(args, "fed_out", "") or os.path.join(
        getattr(args, "results_dir", "results"), "fed", identity)
    os.makedirs(d, exist_ok=True)
    return d


def _site_paths(out_dir: str, rank: int) -> Tuple[str, str]:
    return (os.path.join(out_dir, f"site{rank}.jsonl"),
            os.path.join(out_dir, f"site{rank}.events.jsonl"))


def _xtrace_dir(args, out_dir: str) -> str:
    return getattr(args, "xtrace_dir", "") or out_dir


def _fed_tracer(args, process: str) -> Optional[XTracer]:
    """One :class:`XTracer` per federation process (``--xtrace`` only;
    ``None`` keeps every wire byte-inert). The aggregator is the
    reference clock for both lanes and offsets."""
    if not getattr(args, "xtrace", 0):
        return None
    return XTracer(process, ref="aggregator")


def _write_stream(tracer: Optional[XTracer], args,
                  out_dir: str) -> str:
    if tracer is None:
        return ""
    return tracer.write(os.path.join(
        _xtrace_dir(args, out_dir),
        tracer.process + xtrace.STREAM_SUFFIX))


def _fed_slo(args):
    """The live federation SLO engine (PR 10's, observing aggregator
    round records) — armed only by ``--slo_spec``."""
    if not getattr(args, "slo_spec", ""):
        return None
    from ..obs.slo import SloEngine, load_slo_spec

    return SloEngine(load_slo_spec(args.slo_spec))


def _fed_heartbeat(args, peer: str):
    """One :class:`obs.live.HeartbeatConfig` per emitting process —
    ``--obs_heartbeat_every`` only; ``None`` keeps every wire
    byte-inert (the HELLO/xtrace gating contract, third instance)."""
    every = float(getattr(args, "obs_heartbeat_every", 0.0) or 0.0)
    if every <= 0:
        return None
    from ..obs import live as obs_live

    return obs_live.HeartbeatConfig(peer, every)


def _fed_prom(args, snapshot_fn):
    """The aggregator's ``/metrics`` endpoint (``--obs_prom_port``;
    0 = off, -1 = ephemeral port). Returns the server or ``None``."""
    from ..obs import prom as obs_prom

    return obs_prom.maybe_prom_server(
        snapshot_fn, int(getattr(args, "obs_prom_port", 0) or 0))


def _make_worker(args, comm, rank: int, world: int,
                 trainer: SiteTrainer, out_dir: str,
                 tracer: Optional[XTracer] = None) -> SiteWorker:
    faults = parse_site_faults(getattr(args, "fed_site_faults", ""))
    fs, delay, kill_after = faults.get(rank, (None, 0.0, 0.0))
    log_path, events_path = _site_paths(out_dir, rank)
    return SiteWorker(
        comm, rank, world, trainer, seed=args.seed,
        wire_impl=getattr(args, "agg_impl", "dense"),
        wire_density=getattr(args, "agg_topk_density", 0.1),
        fault_spec=fs, straggle_s=delay, kill_after_s=kill_after,
        retries=args.fed_retries, backoff_s=args.fed_backoff_s,
        log_path=log_path, events_path=events_path, tracer=tracer,
        heartbeat=_fed_heartbeat(args, f"site{rank}"))


def _make_aggregator(args, comm, world: int, algo, out_dir: str,
                     tracer: Optional[XTracer] = None) -> FedAggregator:
    replay = None
    if getattr(args, "fed_replay", ""):
        with open(args.fed_replay) as f:
            replay = json.load(f)
    return FedAggregator(
        comm, world, algo, mode=args.fed_mode, rounds=args.comm_round,
        seed=args.seed, buffer_k=args.fed_buffer_k,
        staleness_bound=args.fed_staleness_bound,
        timeout_s=args.fed_timeout_s, retries=args.fed_retries,
        backoff_s=args.fed_backoff_s,
        wire_impl=getattr(args, "agg_impl", "dense"),
        wire_density=getattr(args, "agg_topk_density", 0.1),
        replay_trace=replay,
        robust_agg=getattr(args, "robust_agg", "none"),
        robust_trim=getattr(args, "robust_trim", 0.2),
        robust_krum_f=getattr(args, "robust_krum_f", 0),
        robust_norm_bound=getattr(args, "norm_bound", 5.0),
        log_path=os.path.join(out_dir, "aggregator.jsonl"),
        events_path=os.path.join(out_dir, "aggregator.events.jsonl"),
        tracer=tracer, slo=_fed_slo(args),
        heartbeat_every=float(
            getattr(args, "obs_heartbeat_every", 0.0) or 0.0))


def _fold_obs(out_dir: str, n_sites: int) -> Dict[str, str]:
    """Fold the aggregator's + every site's streams into one timeline
    (host 0 = aggregator, host k = site k — the merge functions' host
    tagging is positional, which matches the rank numbering)."""
    from ..obs.export import merge_host_events, merge_host_jsonl

    paths = {"federation_jsonl": "", "federation_events": ""}
    rounds = [os.path.join(out_dir, "aggregator.jsonl")] + \
        [_site_paths(out_dir, k)[0] for k in range(1, n_sites + 1)]
    rounds = [p for p in rounds if os.path.exists(p)]
    if rounds:
        merged = merge_host_jsonl(rounds)
        dst = os.path.join(out_dir, "federation.jsonl")
        with open(dst, "w") as f:
            for rec in merged:
                f.write(json.dumps(rec) + "\n")
        paths["federation_jsonl"] = dst
    events = [os.path.join(out_dir, "aggregator.events.jsonl")] + \
        [_site_paths(out_dir, k)[1] for k in range(1, n_sites + 1)]
    events = [p for p in events if os.path.exists(p)]
    if events:
        # dedupe=False: (round, event_type) collides across SITES by
        # design — they are distinct events, not rerun duplicates
        merged = merge_host_events(events, dedupe=False)
        dst = os.path.join(out_dir, "federation.events.jsonl")
        with open(dst, "w") as f:
            for rec in merged:
                f.write(json.dumps(rec) + "\n")
        paths["federation_events"] = dst
    return paths


def _finish_aggregator(args, agg: FedAggregator, algo, identity: str,
                       out_dir: str, prom_port: int = 0
                       ) -> Dict[str, Any]:
    import jax

    trace_path = ""
    if agg.mode == "buffered" and agg.replay_trace is None:
        trace_path = getattr(args, "fed_trace", "") or \
            os.path.join(out_dir, "trace.json")
        with open(trace_path, "w") as f:
            json.dump(agg.trace, f, indent=1)
    d = algo.data
    ev = algo._eval_global(agg.global_params, d.x_test, d.y_test,
                           d.n_test)
    final_eval = {"global_acc": float(ev["acc"]),
                  "global_loss": float(ev["loss"])}
    fold = _fold_obs(out_dir, agg.n_sites)
    xtrace_path = _write_stream(agg.tracer, args, out_dir)
    merged_trace = ""
    if agg.tracer is not None:
        # loopback: every site stream is on disk by now, so this is the
        # complete merge; TCP: a partial (aggregator-lane) merge the
        # launcher re-runs once the site processes have written theirs
        merged_trace = xtrace.merge_run_dir(
            _xtrace_dir(args, out_dir)) or ""
    fed = {
        "mode": agg.mode, "sites": agg.n_sites,
        "version": agg.version, "stale_drops": agg.stale_drops,
        "staleness_hist": {str(k): v for k, v in
                           sorted(agg.staleness_hist.items())},
        "trace_path": trace_path, "out_dir": out_dir,
        "replayed": agg.replay_trace is not None,
        "robust_agg": agg.robust_agg,
        "byzantine_flags": {str(k): v for k, v in
                            sorted(agg.byzantine_flags.items())},
        **fold, **agg.comm.counters.snapshot(),
    }
    if xtrace_path:
        fed["xtrace_path"] = xtrace_path
        fed["merged_trace"] = merged_trace
    if agg.slo is not None:
        fed["slo"] = agg.slo.summary()
    if agg.ledger is not None:
        # the final fleet snapshot (+ a disk copy for `obs watch`):
        # per-peer liveness states, heartbeat frame counts, gauges
        fed["fleet"] = agg.ledger.snapshot(time.monotonic())
        with open(os.path.join(out_dir, "fleet.json"), "w") as f:
            json.dump(fed["fleet"], f, indent=1)
    if prom_port:
        fed["prom_port"] = int(prom_port)
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump({"identity": identity, "final_eval": final_eval,
                   "rounds": len([r for r in agg.history
                                  if r.get("round", -1) >= 0]),
                   "fed": fed}, f, indent=1)
    return {
        "identity": identity, "history": agg.history,
        "final_eval": final_eval, "stat_path": out_dir, "state": None,
        "global_params": jax.tree_util.tree_map(
            np.asarray, agg.global_params),
        "fed": fed,
    }


def _run_loopback(args, algo_name: str, identity: str,
                  out_dir: str) -> Dict[str, Any]:
    from ..comm.local import LocalRouter
    from ..experiments.runner import build_algorithm

    algo, _ = build_algorithm(args, algo_name)
    if args.fed_mode == "sync" and \
            algo.clients_per_round < args.fed_sites:
        _refuse(f"sync cohort of {algo.clients_per_round} clients "
                f"cannot cover {args.fed_sites} sites")
    world = args.fed_sites + 1
    router = LocalRouter(world)
    trainer = SiteTrainer(algo)
    workers = []
    for k in range(1, world):
        w = _make_worker(args, router.manager(k), k, world, trainer,
                         out_dir, tracer=_fed_tracer(args, f"site{k}"))
        w.run(background=True)
        workers.append(w)
    agg = _make_aggregator(args, router.manager(0), world, algo,
                           out_dir,
                           tracer=_fed_tracer(args, "aggregator"))
    agg.run(background=True)
    prom = _fed_prom(args, agg.prom_snapshot)
    try:
        agg.execute()
    finally:
        for w in workers:
            # a deliberately-straggling site may still be asleep in its
            # handler; bounded wait, daemon pumps die with the process
            w.done.wait(timeout=2.0)
            w.finish()
            _write_stream(w.tracer, args, out_dir)
        agg.finish()
        if prom is not None:
            prom.close()
    return _finish_aggregator(args, agg, algo, identity, out_dir,
                              prom_port=prom.port if prom else 0)


def _run_tcp(args, algo_name: str, identity: str,
             out_dir: str) -> Dict[str, Any]:
    from ..comm.tcp import TcpCommManager
    from ..experiments.runner import build_algorithm

    world = args.fed_sites + 1
    endpoints = parse_endpoints(args.fed_endpoints, world)
    algo, _ = build_algorithm(args, algo_name)
    if args.fed_role == "aggregator":
        if args.fed_mode == "sync" and \
                algo.clients_per_round < args.fed_sites:
            _refuse(f"sync cohort of {algo.clients_per_round} clients "
                    f"cannot cover {args.fed_sites} sites")
        agg = _make_aggregator(
            args, TcpCommManager(0, endpoints), world, algo, out_dir,
            tracer=_fed_tracer(args, "aggregator"))
        agg.run(background=True)
        prom = _fed_prom(args, agg.prom_snapshot)
        try:
            agg.execute()
        finally:
            agg.finish()
            if prom is not None:
                prom.close()
        return _finish_aggregator(args, agg, algo, identity, out_dir,
                                  prom_port=prom.port if prom else 0)
    rank = int(getattr(args, "fed_site_rank", 0))
    if not 1 <= rank <= args.fed_sites:
        _refuse(f"--fed_site_rank {rank} outside [1, fed_sites="
                f"{args.fed_sites}]")
    trainer = SiteTrainer(algo)
    worker = _make_worker(args, TcpCommManager(rank, endpoints), rank,
                          world, trainer, out_dir,
                          tracer=_fed_tracer(args, f"site{rank}"))
    worker.run(background=True)
    worker.done.wait()
    worker.finish()
    xtrace_path = _write_stream(worker.tracer, args, out_dir)
    fed: Dict[str, Any] = {"role": "site", "rank": rank,
                           "rounds_trained": worker.rounds_trained,
                           **worker.comm.counters.snapshot()}
    if xtrace_path:
        fed["xtrace_path"] = xtrace_path
    return {"identity": identity, "history": [], "final_eval": {},
            "stat_path": out_dir, "state": None, "fed": fed}


def run_federated(args, algo_name: str) -> Dict[str, Any]:
    """The ``--fed_role`` entry point: validate, build, run the role."""
    validate_fed_args(args, algo_name)
    from ..experiments.config import run_identity

    identity = run_identity(args, algo_name)
    out_dir = _out_dir(args, identity)
    backend = getattr(args, "fed_backend", "local")
    logger.info("federation: role=%s backend=%s mode=%s sites=%d -> %s",
                args.fed_role, backend, args.fed_mode, args.fed_sites,
                out_dir)
    if backend == "local":
        if args.fed_role == "site":
            _refuse("--fed_backend local runs sites as in-process "
                    "threads; --fed_role site needs a real transport "
                    "(tcp)")
        return _run_loopback(args, algo_name, identity, out_dir)
    if backend == "tcp":
        return _run_tcp(args, algo_name, identity, out_dir)
    _refuse(f"unknown --fed_backend {backend!r} (local|tcp)")
