"""Sparsity engine: SNIP saliency, global top-k masks, ERK allocation,
fire/regrow mask evolution.

TPU-native re-design of the reference's sparse-FL machinery:

* SNIP scores — the reference monkey-patches Conv3d/Linear forwards with a
  multiplicative ``weight_mask`` parameter and backprops to it
  (``sailentgrads/snip.py:9-74``). In JAX the same quantity is one
  ``jax.grad`` w.r.t. an all-ones multiplier: dL/dm at m=1 equals
  (dL/dw)*w — no model surgery, fully jittable, vmappable over clients.
* Global mask — normalize mean scores by their sum, keep the top
  ``dense_ratio`` fraction, mask = score/norm >= kth value
  (``snip.py:80-116``). Only conv/dense *kernels* are masked; biases and
  norm parameters stay dense, exactly like the reference's
  ``final_weight_mask`` fallback to ones (``snip.py:106-112``).
* ERK — Erdos-Renyi-Kernel layer-sparsity allocation
  (``DisPFL/my_model_trainer.py:40-114``), a host-side closed-form loop.
* fire/regrow — DisPFL's mask evolution (``DisPFL/client.py:71-99``):
  drop the k smallest-magnitude live weights (cosine-annealed k), regrow
  the k largest-|gradient| dead ones. Implemented with sort + traced-index
  thresholds so k can vary per round without recompilation.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.losses import make_loss_fn
from ..core.state import ones_like_tree, zeros_like_tree


# ---------------------------------------------------------------------------
# pytree plumbing
# ---------------------------------------------------------------------------

def kernel_flags(params: Any) -> Any:
    """Pytree of python bools: True for conv/dense kernel leaves.

    The reference sparsifies only Conv3d/Linear ``weight`` tensors
    (``snip.py:50-54``); in flax these are the leaves named ``kernel``.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    flags = [_path_is_kernel(path) for path, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, flags)


def _path_is_kernel(path) -> bool:
    last = path[-1]
    key = getattr(last, "key", getattr(last, "name", None))
    return key == "kernel"


def mask_density(mask: Any) -> jax.Array:
    """Fraction of nonzero mask entries over kernel leaves."""
    flags = kernel_flags(mask)
    leaves = [
        m for m, k in zip(
            jax.tree_util.tree_leaves(mask),
            jax.tree_util.tree_leaves(flags),
        ) if k
    ]
    nnz = sum(jnp.sum(m != 0) for m in leaves)
    tot = sum(m.size for m in leaves)
    return nnz / tot


# ---------------------------------------------------------------------------
# SNIP
# ---------------------------------------------------------------------------

def make_snip_score_fn(apply_fn, loss_type: str, batch_size: int,
                       stratified: bool = False, num_classes: int = 2,
                       augment_fn=None):
    """Build the per-client SNIP scoring function.

    ``snip_scores(params, x, y, n_valid, rng, n_iters)`` samples
    ``n_iters`` minibatches from the client shard (the itersnip loop,
    ``sailentgrads/client.py:29-50``), computes |dL/dmask| per batch and
    returns the mean score pytree (zeros on non-kernel leaves).
    vmap over a leading client axis for the all-clients scoring pass.

    ``stratified``: class-balanced batch draws — the reference's
    ``--stratified_sampling`` runs the scoring over 25 label-stratified
    folds (``client.py:32-42``); under jit the static-shape equivalent is
    sampling each scoring batch with per-example probability
    ∝ 1/count(class) so every class contributes equally to the saliency
    mean (documented deviation: balanced draws instead of exact folds).

    ``augment_fn``: the same jittable training-time augmentation the local
    SGD steps apply — the reference's SNIP batches come from the
    transform-bearing train DataLoader (``client.py:45``), so on CIFAR the
    mask is selected from saliency over AUGMENTED images.
    """
    loss_fn = make_loss_fn(loss_type)

    def batch_scores(params, xb, yb, rng):
        flags = kernel_flags(params)
        mask = ones_like_tree(params)

        def loss_of_mask(m):
            masked = jax.tree_util.tree_map(
                lambda p, mm, k: p * mm if k else p, params, m, flags
            )
            logits = apply_fn(masked, xb, train=True, rng=rng)
            return loss_fn(logits, yb)

        grads = jax.grad(loss_of_mask)(mask)
        return jax.tree_util.tree_map(
            lambda g, k: jnp.abs(g) if k else jnp.zeros_like(g), grads, flags
        )

    def snip_scores(params, x, y, n_valid, rng, n_iters: int):
        if stratified:
            # class-balanced draw probabilities: loop-invariant, computed
            # once per client (not inside the scoring scan)
            valid = jnp.arange(y.shape[0]) < n_valid
            yc = jnp.clip(y.astype(jnp.int32), 0, num_classes - 1)
            counts = jnp.zeros((num_classes,)).at[yc].add(
                valid.astype(jnp.float32))
            p = valid / jnp.maximum(counts[yc], 1.0)
            p = p / jnp.maximum(p.sum(), 1e-9)

        def body(carry, key):
            k_idx, k_drop = jax.random.split(key)
            if stratified:
                idx = jax.random.choice(
                    k_idx, y.shape[0], (batch_size,), replace=True, p=p)
            else:
                idx = jax.random.randint(
                    k_idx, (batch_size,), 0, jnp.maximum(n_valid, 1)
                )
            xb = jnp.take(x, idx, axis=0)
            if augment_fn is not None:
                k_aug, k_drop = jax.random.split(k_drop)
                xb = augment_fn(k_aug, xb)
            s = batch_scores(
                params, xb, jnp.take(y, idx, axis=0), k_drop,
            )
            return jax.tree_util.tree_map(jnp.add, carry, s), None

        zeros = zeros_like_tree(params)
        keys = jax.random.split(rng, n_iters)
        total, _ = jax.lax.scan(body, zeros, keys)
        return jax.tree_util.tree_map(lambda t: t / n_iters, total)

    return snip_scores


def stratified_fold_schedule(y: np.ndarray, n_valid: int,
                             n_splits: int = 25, seed: int = 42):
    """Host-side exact replica of the reference's stratified scoring
    schedule for ONE client (``sailentgrads/client.py:32-42``):
    ``StratifiedKFold(n_splits, shuffle=True, random_state=seed)`` over
    the client's labels, scoring each split on its TRAIN side — i.e.
    each of the ``n_splits`` scoring batches is the ~(K-1)/K complement
    of one fold, NOT the small fold itself.

    Returns ``(idx, w)`` of shape [n_splits, L] where L = the largest
    train-side size; rows are padded with index 0 / weight 0 so the
    jitted scorer can consume a static shape (the weighted-mean loss
    ignores padding exactly).
    """
    from sklearn.model_selection import StratifiedKFold

    yv = np.asarray(y[:n_valid])
    splitter = StratifiedKFold(n_splits=n_splits, shuffle=True,
                               random_state=seed)
    trains = [tr for tr, _ in splitter.split(np.zeros_like(yv), yv)]
    L = max(len(t) for t in trains)
    idx = np.zeros((n_splits, L), np.int32)
    w = np.zeros((n_splits, L), np.float32)
    for k, tr in enumerate(trains):
        idx[k, :len(tr)] = tr
        w[k, :len(tr)] = 1.0
    return idx, w


def stacked_fold_schedules(y_all: np.ndarray, n_all: np.ndarray,
                           n_splits: int = 25, seed: int = 42):
    """Per-client fold schedules stacked along a leading client axis
    ([C, n_splits, L] with one global L) for the vmapped scoring pass.
    Raises the same sklearn error the reference hits when a client has
    fewer than ``n_splits`` members of some class."""
    per = []
    for c in range(y_all.shape[0]):
        try:
            per.append(stratified_fold_schedule(
                y_all[c], int(n_all[c]), n_splits=n_splits, seed=seed))
        except ValueError as e:
            # same constraint the reference hits (n_splits=25 hard-coded,
            # client.py:36) — surface which client and the escape hatch
            raise ValueError(
                f"exact stratified SNIP needs >= {n_splits} samples of "
                f"every class on every client; client {c} is too small "
                f"({e}). Use stratified_mode='balanced' "
                "(--stratified_mode balanced) for small shards.") from e
    L = max(i.shape[1] for i, _ in per)

    def pad(a, fill):
        out = np.full((a.shape[0], L), fill, a.dtype)
        out[:, :a.shape[1]] = a
        return out

    idx = np.stack([pad(i, 0) for i, _ in per])
    w = np.stack([pad(wt, 0.0) for _, wt in per])
    return idx, w


def make_snip_fold_score_fn(apply_fn, loss_type: str, augment_fn=None):
    """Exact-fold SNIP scorer: ``fold_scores(params, x, y, fold_idx,
    fold_w, rng)`` scans the [S, L] schedule from
    :func:`stratified_fold_schedule`, computing |dL/dmask| of the
    weight-masked loss ``sum(w * per_example_loss) / sum(w)`` per fold
    batch (padding rows carry w=0, so they contribute exactly nothing)
    and returns the mean score pytree over folds — the reference's
    ``get_mean_sailency_scores`` over the 25 fold scores
    (``client.py:44,49``). Augmentation applies per fold batch like the
    reference's transform-bearing dataset indexing (``client.py:38-40``).
    """
    from ..core.losses import PER_EXAMPLE_LOSSES

    per_ex = PER_EXAMPLE_LOSSES[loss_type]

    def fold_scores(params, x, y, fold_idx, fold_w, rng):
        flags = kernel_flags(params)

        def body(carry, xs):
            idx, w, key = xs
            k_aug, k_drop = jax.random.split(key)
            xb = jnp.take(x, idx, axis=0)
            yb = jnp.take(y, idx, axis=0)
            if augment_fn is not None:
                xb = augment_fn(k_aug, xb)

            def loss_of_mask(m):
                masked = jax.tree_util.tree_map(
                    lambda p, mm, k: p * mm if k else p, params, m, flags
                )
                logits = apply_fn(masked, xb, train=True, rng=k_drop)
                losses = per_ex(logits, yb)
                return jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1.0)

            grads = jax.grad(loss_of_mask)(ones_like_tree(params))
            s = jax.tree_util.tree_map(
                lambda g, k: jnp.abs(g) if k else jnp.zeros_like(g),
                grads, flags)
            return jax.tree_util.tree_map(jnp.add, carry, s), None

        n_splits = fold_idx.shape[0]
        keys = jax.random.split(rng, n_splits)
        total, _ = jax.lax.scan(
            body, zeros_like_tree(params), (fold_idx, fold_w, keys))
        return jax.tree_util.tree_map(lambda t: t / n_splits, total)

    return fold_scores


def mask_from_scores(scores: Any, keep_ratio: float,
                     kernels: str = "xla") -> Any:
    """Global top-k binary mask from a (mean) score pytree.

    Reference semantics (``snip.py:80-116``): concatenate kernel scores,
    normalize by their sum, keep ``int(n * keep_ratio)`` largest, threshold
    with >=; non-kernel leaves get all-ones masks.

    ``kernels`` routes the k-th-largest threshold through
    ``ops.topk_select`` (scores are nonnegative |grad| magnitudes, so
    the bit-space search applies directly) and, for ``'pallas'``, builds
    each kernel leaf's mask with the fused normalize-and-compare kernel
    — both bit-identical to the sort spelling by the tie-break contract
    (``jnp.sort(flat)[::-1][k-1]`` IS the exact k-th largest, the same
    float every backend converges to).
    """
    from .topk_select import select_threshold

    flags = kernel_flags(scores)
    leaves, treedef = jax.tree_util.tree_flatten(scores)
    flag_leaves = jax.tree_util.tree_leaves(flags)
    kernel_scores = [s for s, k in zip(leaves, flag_leaves) if k]
    flat = jnp.concatenate([s.reshape(-1) for s in kernel_scores])
    norm = jnp.sum(flat)
    flat = flat / norm
    n_keep = max(1, int(flat.size * keep_ratio))
    # kth largest threshold (n_keep is static here): the legacy spelling
    # was a full descending sort + static gather — the threshold search
    # prices it at ~31 count passes instead, same float out
    if kernels == "sort":
        threshold = jnp.sort(flat)[::-1][n_keep - 1]
    else:
        threshold = select_threshold(
            flat.reshape(1, -1), n_keep, kernels=kernels).reshape(())
    if kernels == "pallas":
        from . import pallas_kernels as pk

        out = [
            pk.fused_score_mask_leaf(s, norm, threshold).astype(s.dtype)
            if k else jnp.ones_like(s)
            for s, k in zip(leaves, flag_leaves)
        ]
    else:
        out = [
            (s / norm >= threshold).astype(s.dtype) if k
            else jnp.ones_like(s)
            for s, k in zip(leaves, flag_leaves)
        ]
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# ERK allocation + random masks (DisPFL)
# ---------------------------------------------------------------------------

def erk_sparsities(
    shapes: Dict[str, Tuple[int, ...]],
    dense_ratio: float = 0.5,
    erk_power_scale: float = 1.0,
    tabu: Tuple[str, ...] = (),
) -> Dict[str, float]:
    """Erdos-Renyi-Kernel per-layer sparsity allocation.

    Host-side port of the reference's closed-form iteration
    (``DisPFL/my_model_trainer.py:55-130``): raw probability
    ``(sum(shape)/prod(shape))**power``; layers whose scaled probability
    would exceed 1 become dense; epsilon balances the global budget.
    """
    density = dense_ratio
    if density >= 1.0:
        # fully dense (e.g. the diff_spa client at ratio 1.0) — the
        # balancing iteration would divide by zero
        return {name: 0.0 for name in shapes}
    dense_layers = set(tabu)
    while True:
        divisor = 0.0
        rhs = 0.0
        raw = {}
        for name, shape in shapes.items():
            n = float(np.prod(shape))
            if name in dense_layers:
                rhs -= n * (1.0 - density)
            else:
                rhs += n * density
                raw[name] = (np.sum(shape) / np.prod(shape)) ** erk_power_scale
                divisor += raw[name] * n
        eps = rhs / divisor
        max_prob = max(raw.values())
        if max_prob * eps > 1.0:
            for name, p in raw.items():
                if p == max_prob:
                    dense_layers.add(name)
        else:
            break
    out = {}
    for name, shape in shapes.items():
        out[name] = 0.0 if name in dense_layers else 1.0 - eps * raw[name]
    return out


def uniform_sparsities(
    shapes: Dict[str, Tuple[int, ...]],
    dense_ratio: float = 0.5,
    tabu: Tuple[str, ...] = (),
) -> Dict[str, float]:
    """Flat per-layer sparsity: every non-tabu layer at ``1 - dense_ratio``
    (the reference's ``calculate_sparsities(distribution="uniform")``,
    ``DisPFL/my_model_trainer.py:42-46``; enabled by ``--uniform``)."""
    return {name: 0.0 if name in tabu else 1.0 - dense_ratio
            for name in shapes}


def random_mask_array(
    rng: jax.Array, shape: Tuple[int, ...], density: float,
    dtype=jnp.float32,
) -> jax.Array:
    """Random {0,1} mask with exactly ``int(density * size)`` ones: rank
    uniform scores and keep the top-k. Shared by DisPFL mask init and the
    meta-net mask initializer (``cnn_meta.py:59-68``)."""
    size = int(np.prod(shape))
    n_dense = int(density * size)
    if n_dense <= 0:
        return jnp.zeros(shape, dtype)
    if n_dense >= size:
        return jnp.ones(shape, dtype)
    scores = jax.random.uniform(rng, (size,))
    thresh = jnp.sort(scores)[::-1][n_dense - 1]
    return (scores >= thresh).astype(dtype).reshape(shape)


def random_masks_from_sparsities(
    params: Any, sparsities_fn: Callable[[str, Tuple[int, ...]], float],
    rng: jax.Array,
) -> Any:
    """Random binary masks with per-leaf sparsity (DisPFL init_masks,
    ``DisPFL/my_model_trainer.py:28-38``). Non-kernel leaves stay dense."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    keys = jax.random.split(rng, len(flat))
    out = []
    for (path, p), key in zip(flat, keys):
        if not _path_is_kernel(path):
            out.append(jnp.ones_like(p))
            continue
        s = sparsities_fn(_path_name(path), p.shape)
        out.append(random_mask_array(key, p.shape, 1.0 - s, p.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def _path_name(path) -> str:
    parts = []
    for e in path:
        parts.append(str(getattr(e, "key", getattr(e, "name", e))))
    return "/".join(parts)


def param_shapes(params: Any, kernels_only: bool = True) -> Dict[str, Tuple[int, ...]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return {
        _path_name(path): tuple(p.shape)
        for path, p in flat
        if (not kernels_only) or _path_is_kernel(path)
    }


# ---------------------------------------------------------------------------
# fire / regrow (DisPFL mask evolution)
# ---------------------------------------------------------------------------

def cosine_annealing(anneal_factor: float, round_idx, total_rounds: int):
    """DisPFL's drop-rate schedule (``DisPFL/slim_util.py:7-11``)."""
    t = round_idx / max(total_rounds, 1)
    return anneal_factor / 2.0 * (1.0 + jnp.cos(t * math.pi))


def _kth_smallest(values: jax.Array, k: jax.Array) -> jax.Array:
    """k-th smallest (1-indexed) with traced k: sort + dynamic gather."""
    s = jnp.sort(values)
    idx = jnp.clip(k - 1, 0, values.size - 1)
    return s[idx]


def fire_mask(mask: Any, params: Any, drop_rate, rng=None) -> Any:
    """Drop the ``drop_rate`` fraction of smallest-|w| live weights per leaf
    (``DisPFL/client.py:71-82``). ``drop_rate`` may be traced (cosine
    annealed); the count per leaf is rounded up like the reference's
    ``math.ceil``. Non-kernel leaves are untouched."""
    flags = kernel_flags(mask)

    def leaf(m, p, k):
        if not k:
            return m
        n_live = jnp.sum(m != 0)
        n_drop = jnp.ceil(drop_rate * n_live).astype(jnp.int32)
        score = jnp.where(m != 0, jnp.abs(p), jnp.inf).reshape(-1)
        thresh = _kth_smallest(score, n_drop)
        keep = (jnp.abs(p) > thresh) & (m != 0)
        # n_drop == 0 -> keep everything live
        return jnp.where(n_drop > 0, keep.astype(m.dtype), m)

    return jax.tree_util.tree_map(leaf, mask, params, flags)


def regrow_mask(mask: Any, grads: Any, n_regrow_tree: Any) -> Any:
    """Regrow the ``n`` largest-|grad| dead weights per leaf
    (``DisPFL/client.py:86-99``). ``n_regrow_tree`` is a pytree of traced
    int counts (so fire+regrow preserves per-leaf live counts)."""
    flags = kernel_flags(mask)

    def leaf(m, g, n, k):
        if not k:
            return m
        score = jnp.where(m == 0, jnp.abs(g), -jnp.inf).reshape(-1)
        # n-th largest = (size - n + 1)-th smallest
        thresh = _kth_smallest(score, score.size - jnp.maximum(n, 1) + 1)
        grown = (m == 0) & (jnp.abs(g) >= thresh) & jnp.isfinite(thresh)
        return jnp.where(n > 0, jnp.maximum(m, grown.astype(m.dtype)), m)

    return jax.tree_util.tree_map(leaf, mask, grads, n_regrow_tree, flags)


def live_counts(mask: Any) -> Any:
    """Per-leaf live-weight counts (for fire->regrow count preservation)."""
    return jax.tree_util.tree_map(lambda m: jnp.sum(m != 0), mask)


def host_live_indices(mask: Any, stacked: bool = False) -> list:
    """Host-side gather plan for mask-aware sparse aggregation
    (``parallel/collectives.py``): for each leaf, in ``tree_leaves``
    order, the int32 flat indices of live (nonzero) coordinates — or
    ``None`` for leaves that stay dense (non-kernel leaves, which the
    reference never sparsifies, and kernels with no dead coordinate).

    ``stacked=True`` reads [C, ...]-stacked per-client masks and returns
    the UNION of live coordinates over the client axis — the static
    shared index superset ("padded to the max live footprint across
    clients") a cross-client compressed reduce needs. Requires a CONCRETE
    mask (numpy walk; do not call under trace).
    """
    flags = kernel_flags(mask)
    out = []
    for m, k in zip(jax.tree_util.tree_leaves(mask),
                    jax.tree_util.tree_leaves(flags)):
        a = np.asarray(m)
        live = (a != 0).any(axis=0).reshape(-1) if stacked \
            else (a != 0).reshape(-1)
        if not k or bool(live.all()):
            out.append(None)
        else:
            out.append(np.flatnonzero(live).astype(np.int32))
    return out


# ---------------------------------------------------------------------------
# SubAvg iterative magnitude pruning
# ---------------------------------------------------------------------------

def magnitude_prune_mask(mask: Any, params: Any, prune_ratio) -> Any:
    """SubAvg's ``fake_prune`` (``subavg/prune_func.py:9-30``): per kernel
    leaf, threshold = the ``prune_ratio`` percentile of |w| over *alive*
    weights; new mask zeroes entries with |w| < threshold. ``prune_ratio``
    may be traced. Non-kernel leaves untouched."""
    flags = kernel_flags(mask)

    def leaf(m, p, k):
        if not k:
            return m
        n_alive = jnp.sum(m != 0)
        # nearest-rank percentile of alive |w| (reference uses np.percentile)
        rank = jnp.ceil(prune_ratio * n_alive).astype(jnp.int32)
        score = jnp.where(m != 0, jnp.abs(p), jnp.inf).reshape(-1)
        thresh = _kth_smallest(score, jnp.maximum(rank, 1))
        pruned = jnp.where(jnp.abs(p) < thresh, 0.0, m)
        return jnp.where(n_alive > 0, pruned, m)

    return jax.tree_util.tree_map(leaf, mask, params, flags)


def mask_distance(mask_a: Any, mask_b: Any) -> jax.Array:
    """Mean per-leaf hamming fraction between two masks
    (``subavg/prune_func.py:52-66`` dist_masks)."""
    fracs = [
        jnp.mean(((a != 0) != (b != 0)).astype(jnp.float32))
        for a, b in zip(jax.tree_util.tree_leaves(mask_a),
                        jax.tree_util.tree_leaves(mask_b))
    ]
    return sum(fracs) / len(fracs)
