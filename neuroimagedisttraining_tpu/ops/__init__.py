from .sparsity import (
    erk_sparsities,
    fire_mask,
    kernel_flags,
    make_snip_score_fn,
    mask_density,
    mask_from_scores,
    random_masks_from_sparsities,
    regrow_mask,
)

__all__ = [
    "erk_sparsities",
    "fire_mask",
    "kernel_flags",
    "make_snip_score_fn",
    "mask_density",
    "mask_from_scores",
    "random_masks_from_sparsities",
    "regrow_mask",
]
