"""Threshold-refinement top-k selection — the wire's shared selection core.

``jax.lax.top_k`` is SORT-bound on XLA:CPU: the scale-32 exact topk
aggregate measured 26.7 s/agg at ANY density (RESULTS Round-12), which
is why ``--agg_topk_sample`` existed at all. But the selection never
needed the sorted ORDER — only the k-th largest magnitude, used as a
threshold. This module computes that threshold exactly in O(n) passes
with no data-dependent memory traffic, by refining a cut over the f32
bit space:

* nonnegative IEEE-754 floats compare exactly like their bit patterns
  viewed as integers, so "the k-th largest |x|" is "the largest bit
  pattern ``b`` with ``count(bits >= b) >= k``";
* 31 monotone count-above-cut passes binary-search that ``b`` over the
  finite-magnitude bit range — equivalently, a binary search of the
  cumulative magnitude histogram, whose first 8 steps walk the exponent
  byte (the coarse |x| histogram cut) and the remaining 23 refine the
  mantissa;
* the selection itself is then ONE masked compare (``|x| >= thr``) —
  a single pass, no sort, no scatter.

Tie-break contract (pinned by tests/test_pallas_kernels.py and
tests/test_fed_wire.py):

* **In-graph selection** (``collectives.topk_sparsify``, every kernel
  backend) keeps every coordinate whose magnitude is ``>=`` the exact
  k-th largest — coordinates tying the threshold are ALL kept (>= k
  survive; a measure-zero event on continuous deltas). This is exactly
  the legacy sort spelling ``av >= lax.top_k(av, k)[0][..., -1:]``, so
  threshold and sort selection pick IDENTICAL coordinate sets and the
  backends are bit-interchangeable.
* **Host wire encode** (``fed/wire._topk_leaf``) must ship EXACTLY k
  pairs: every coordinate with ``|x| >`` threshold, then ties at the
  threshold by ascending flat index — byte-identical to the historical
  stable ``np.argsort(-|x|)[:k]`` spelling. :func:`host_topk_indices`
  is that rule via ``np.argpartition`` (O(n) expected, no full sort).
* Non-finite magnitudes are OUTSIDE the contract: the guard
  (robust/guard.py) quarantines non-finite client rows before any
  selection runs, and both spellings degrade the same way (a NaN
  threshold selects nothing — every ``>=`` compare is False).

Backends (the ``--agg_kernels`` surface, threaded from
``algorithms/base.py`` down to :func:`select_threshold`):

* ``"xla"`` (default) — pure-XLA bit-space search, the bit-exact
  reference. Replaces the sort with NO trajectory change (same
  coordinate sets, same floats).
* ``"pallas"`` — the fused Pallas kernel (ops/pallas_kernels.py): the
  magnitudes stay VMEM-resident across all 31 count passes, one HBM
  read total. Bit-identical to ``"xla"`` by construction (both converge
  to the same unique integer fixed point); rows too large for VMEM fall
  back to the XLA search, which changes nothing but residency.
* ``"sort"`` — the legacy ``lax.top_k`` spelling, kept as the internal
  reference for parity tests and bench baselines (not a flag choice).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

#: the ``--agg_kernels`` flag surface (analysis/identity.py classifies it
#: inert: backends are bit-identical by the tie-break contract)
KERNEL_BACKENDS = ("xla", "pallas")

#: internal backend spellings accepted by :func:`select_threshold`
#: ("sort" = the legacy lax.top_k reference, tests/bench only)
_ALL_BACKENDS = KERNEL_BACKENDS + ("sort",)

#: one past the +inf bit pattern: the exclusive upper bound of the
#: bit-space search (every finite-or-inf magnitude lies below it)
_BITS_HI = np.int32(0x7F800001)

#: ceil(log2(_BITS_HI)) — halvings until the search interval is one wide
SEARCH_ITERS = 31


def check_kernels(kernels: str) -> str:
    """Validate a kernel-backend name (flag surface + 'sort')."""
    if kernels not in _ALL_BACKENDS:
        raise ValueError(
            f"agg_kernels {kernels!r} not in {_ALL_BACKENDS}")
    return kernels


def _count_ge(bits: jax.Array, cut: jax.Array) -> jax.Array:
    """count(bits >= cut) per row — the monotone search oracle."""
    return jnp.sum((bits >= cut).astype(jnp.int32), axis=-1,
                   keepdims=True)


@functools.partial(jax.jit, static_argnames=("k",))
def exact_threshold(av: jax.Array, k: int) -> jax.Array:
    """Exact k-th largest magnitude per row, no sort: binary-search the
    f32 bit space with :data:`SEARCH_ITERS` count passes.

    ``av`` is ``[..., n]`` nonnegative f32 (magnitudes); returns
    ``[..., 1]`` f32 — the same float ``lax.top_k(av, k)[0][..., -1:]``
    produces, so ``av >= thr`` selects the identical coordinate set
    (the tie-break contract above). Invariant: ``lo`` always satisfies
    ``count >= k`` (true at ``lo=0`` since ``k <= n``), ``hi`` never
    does; the loop is stationary once the interval is one wide, so a
    fixed :data:`SEARCH_ITERS` trip count is exact, trace-friendly,
    and backend-independent (the fixed point is a unique integer —
    any correct search order lands on it)."""
    bits = jax.lax.bitcast_convert_type(av.astype(jnp.float32),
                                        jnp.int32)
    lead = av.shape[:-1] + (1,)
    lo = jnp.zeros(lead, jnp.int32)
    hi = jnp.full(lead, _BITS_HI, jnp.int32)

    def body(_, lohi):
        lo, hi = lohi
        mid = lo + (hi - lo) // 2
        ok = _count_ge(bits, mid) >= k
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo, _ = jax.lax.fori_loop(0, SEARCH_ITERS, body, (lo, hi))
    return jax.lax.bitcast_convert_type(lo, jnp.float32)


@functools.partial(jax.jit, static_argnames=("k", "sample"))
def sampled_threshold(av: jax.Array, k: int, sample: int) -> jax.Array:
    """The ``--agg_topk_sample`` strided threshold estimator (Deep
    Gradient Compression hierarchical sampling, Lin et al. 2018),
    hoisted verbatim out of ``collectives.topk_sparsify`` so both the
    in-graph selection and its calibration test share one spelling:
    deterministic fixed-stride ~``sample``-element subsample, exact
    top-k on the candidates, k scaled by the stride. The shipped count
    is only approximately k — drift the error-feedback residual absorbs
    by construction (tests pin the calibration band against
    :func:`exact_threshold`)."""
    n = av.shape[-1]
    stride = max(1, n // int(sample))
    cand = av[..., ::stride]
    ks = min(cand.shape[-1], max(1, int(round(k / stride))))
    return jax.lax.top_k(cand, ks)[0][..., -1:]


def select_threshold(av: jax.Array, k: int, *, kernels: str = "xla",
                     sample: int = 0) -> jax.Array:
    """Per-row selection threshold for ``av >= thr`` top-k masking,
    routed by kernel backend. ``sample > 0`` uses the strided estimator
    on every backend (the subsample's top_k is tiny — already
    sort-affordable; exact backends make it an optimization, not a
    necessity)."""
    check_kernels(kernels)
    n = av.shape[-1]
    if sample and n > sample:
        return sampled_threshold(av, k, sample)
    if kernels == "sort":
        return jax.lax.top_k(av, k)[0][..., -1:]
    if kernels == "pallas":
        from . import pallas_kernels as pk

        if pk.threshold_supported(n):
            return pk.threshold_topk(av, k)
        # VMEM-oversized rows: the XLA search computes the identical
        # integer fixed point — residency changes, bits do not
    return exact_threshold(av, k)


def host_topk_indices(mag: np.ndarray, k: int) -> np.ndarray:
    """Exactly-k flat indices of the largest magnitudes, host-side,
    under the wire tie-break contract: all ``mag > T`` plus ties at
    ``T`` by ascending index, returned ascending int32 — byte-identical
    to ``np.sort(np.argsort(-mag, kind='stable')[:k])`` without the
    full sort (``np.argpartition`` is O(n) expected). NaNs order last,
    exactly like the stable-argsort spelling (np.sort semantics)."""
    mag = np.asarray(mag).ravel()
    n = mag.size
    k = int(k)
    if k >= n:
        return np.arange(n, dtype=np.int32)
    part = np.argpartition(-mag, k - 1)[:k]
    vals = mag[part]
    if np.isnan(vals).any():
        # >= k non-finites in play: fall back to the reference spelling
        # (outside the contract; correctness over speed)
        order = np.argsort(-mag, kind="stable")[:k]
        return np.sort(order).astype(np.int32)
    thr = vals.min()
    above = np.flatnonzero(mag > thr)
    ties = np.flatnonzero(mag == thr)
    idx = np.concatenate([above, ties[: k - above.size]])
    return np.sort(idx).astype(np.int32)
