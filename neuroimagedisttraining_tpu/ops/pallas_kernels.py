"""Pallas TPU kernels for the framework's hot elementwise chains.

The hottest non-matmul op in every sparse-FL round is the masked optimizer
update (``my_model_trainer.py:207-216``: SGD momentum + weight decay + post-
step ``param *= mask``). Left to XLA this is a chain of small elementwise
kernels *per pytree leaf*; the fused Pallas kernel below does the whole
update — momentum accumulate, decayed step, mask projection — in ONE pass
over HBM per leaf: 4 reads (p, m, g, mask) + 2 writes (p', m').

A second kernel fuses DisPFL-style masked-gradient SGD (mask applied to the
gradient *before* the momentum accumulate, ``DisPFL/my_model_trainer.py:
147-172``).

Layout: each leaf is raveled and padded to (rows, 128) float32 — the VPU
lane width; rows are padded to the (8, 128) f32 tile. On non-TPU backends
the kernels run in interpreter mode so CPU tests exercise identical code.
"""
from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
SUBLANES = 8
_BLOCK_ROWS = 512  # 512x128 f32 = 256 KiB/operand: comfortably inside VMEM


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _to_2d(x: jax.Array) -> Tuple[jax.Array, int]:
    """Ravel + zero-pad to a (rows, LANES) f32 panel; rows % SUBLANES == 0.

    vmap over a leading axis to panel a batch per-element (each element
    padded independently — see fused_weighted_sum_leaf)."""
    flat = x.ravel()
    n = flat.shape[0]
    per_panel = LANES * SUBLANES
    padded = ((n + per_panel - 1) // per_panel) * per_panel
    flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(-1, LANES), n


def _pick_block_rows(rows: int, budget: int = _BLOCK_ROWS) -> int:
    """Largest block size <= budget dividing ``rows`` (rows % SUBLANES == 0,
    guaranteed by _to_2d, so the loop terminates at SUBLANES or below)."""
    block_rows = min(budget, rows)
    while rows % block_rows:
        block_rows -= SUBLANES if block_rows > SUBLANES else 1
    return max(block_rows, 1)


def _from_2d(panel: jax.Array, n: int, shape, dtype) -> jax.Array:
    return panel.ravel()[:n].reshape(shape).astype(dtype)


def _masked_sgd_kernel(lr_ref, p_ref, m_ref, g_ref, mask_ref,
                       p_out, m_out, *, momentum: float, wd: float,
                       mask_grads: bool):
    lr = lr_ref[0]
    g = g_ref[:]
    if mask_grads:
        g = g * mask_ref[:]
    g = g + wd * p_ref[:]
    m_new = momentum * m_ref[:] + g
    p_new = p_ref[:] - lr * m_new
    if not mask_grads:
        p_new = p_new * mask_ref[:]
    p_out[:] = p_new
    m_out[:] = m_new


@functools.partial(jax.jit, static_argnames=("momentum", "wd", "mask_grads"))
def fused_masked_sgd_leaf(p, m, g, mask, lr, momentum: float = 0.0,
                          wd: float = 0.0, mask_grads: bool = False):
    """One leaf's fused update. ``mask_grads=False`` -> SalientGrads
    semantics (post-step ``p *= mask``); ``True`` -> DisPFL masked-gradient
    SGD. Returns (p_new, m_new) with the leaf's original shape/dtype."""
    shape, dtype = p.shape, p.dtype
    p2, n = _to_2d(p.astype(jnp.float32))
    m2, _ = _to_2d(m.astype(jnp.float32))
    g2, _ = _to_2d(g.astype(jnp.float32))
    k2, _ = _to_2d(mask.astype(jnp.float32))
    rows = p2.shape[0]
    block_rows = _pick_block_rows(rows)
    grid = (rows // block_rows,)

    vmem_spec = pl.BlockSpec(
        (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    kernel = functools.partial(
        _masked_sgd_kernel, momentum=momentum, wd=wd, mask_grads=mask_grads)
    p_new, m_new = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lr scalar
            vmem_spec, vmem_spec, vmem_spec, vmem_spec,
        ],
        out_specs=[vmem_spec, vmem_spec],
        out_shape=[
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
            jax.ShapeDtypeStruct(m2.shape, jnp.float32),
        ],
        interpret=_interpret(),
    )(jnp.asarray(lr, jnp.float32).reshape(1), p2, m2, g2, k2)
    # momentum keeps its own dtype (f32 buffers stay f32 under bf16 params)
    return (_from_2d(p_new, n, shape, dtype),
            _from_2d(m_new, n, shape, m.dtype))


def fused_masked_sgd_step(params: Any, momentum_tree: Any, grads: Any,
                          mask: Any, lr, momentum: float = 0.0,
                          wd: float = 0.0, mask_grads: bool = False
                          ) -> Tuple[Any, Any]:
    """Pytree-level fused update (drop-in for optim.sgd_momentum_step +
    mask projection)."""
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_m = treedef.flatten_up_to(momentum_tree)
    flat_g = treedef.flatten_up_to(grads)
    flat_k = treedef.flatten_up_to(mask)
    out_p, out_m = [], []
    for p, m, g, k in zip(flat_p, flat_m, flat_g, flat_k):
        p2, m2 = fused_masked_sgd_leaf(
            p, m, g, k, lr, momentum=momentum, wd=wd, mask_grads=mask_grads)
        out_p.append(p2)
        out_m.append(m2)
    return (jax.tree_util.tree_unflatten(treedef, out_p),
            jax.tree_util.tree_unflatten(treedef, out_m))


# -- fused weighted aggregation ----------------------------------------------

def _wsum_kernel(w_ref, x_ref, out_ref):
    """out = sum_c w[c] * x[c] for one (clients, block, LANES) tile."""
    x = x_ref[:]                       # (C, block_rows, LANES)
    acc = jnp.zeros(x.shape[1:], jnp.float32)
    for c in range(x.shape[0]):        # static unroll over clients
        acc = acc + w_ref[c] * x[c]    # scalar SMEM load per client
    out_ref[:] = acc


@jax.jit
def fused_weighted_sum_leaf(stacked: jax.Array, weights: jax.Array):
    """Sample-weighted FedAvg reduction over a leading client axis in one
    HBM pass (the `psum` in fedavg_api.py:102-117), fused across the whole
    leaf instead of C separate scale+add kernels."""
    c = stacked.shape[0]
    shape = stacked.shape[1:]
    dtype = stacked.dtype
    flat = stacked.reshape(c, -1).astype(jnp.float32)
    n = flat.shape[1]
    panels = jax.vmap(lambda v: _to_2d(v)[0])(flat)  # per-client pad + panel
    rows = panels.shape[1]
    # the input block is (c, block_rows, LANES): shrink block_rows by the
    # client count so VMEM stays ~_BLOCK_ROWS*LANES*4B regardless of c
    block_rows = _pick_block_rows(rows, max(SUBLANES, _BLOCK_ROWS // max(c, 1)))
    grid = (rows // block_rows,)

    out = pl.pallas_call(
        _wsum_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((c, block_rows, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        interpret=_interpret(),
    )(weights.astype(jnp.float32), panels)
    return out.ravel()[:n].reshape(shape).astype(dtype)


def fused_weighted_sum(stacked_tree: Any, weights: jax.Array) -> Any:
    return jax.tree_util.tree_map(
        lambda x: fused_weighted_sum_leaf(x, weights), stacked_tree)
