"""Pallas TPU kernels for the framework's hot elementwise chains.

The hottest non-matmul op in every sparse-FL round is the masked optimizer
update (``my_model_trainer.py:207-216``: SGD momentum + weight decay + post-
step ``param *= mask``). Left to XLA this is a chain of small elementwise
kernels *per pytree leaf*; the fused Pallas kernel below does the whole
update — momentum accumulate, decayed step, mask projection — in ONE pass
over HBM per leaf: 4 reads (p, m, g, mask) + 2 writes (p', m').

A second kernel fuses DisPFL-style masked-gradient SGD (mask applied to the
gradient *before* the momentum accumulate, ``DisPFL/my_model_trainer.py:
147-172``).

Layout: each leaf is raveled and padded to (rows, 128) float32 — the VPU
lane width; rows are padded to the (8, 128) f32 tile. On non-TPU backends
the kernels run in interpreter mode so CPU tests exercise identical code.
"""
from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
SUBLANES = 8
_BLOCK_ROWS = 512  # 512x128 f32 = 256 KiB/operand: comfortably inside VMEM


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _to_2d(x: jax.Array) -> Tuple[jax.Array, int]:
    """Ravel + zero-pad to a (rows, LANES) f32 panel; rows % SUBLANES == 0.

    vmap over a leading axis to panel a batch per-element (each element
    padded independently — see fused_weighted_sum_leaf)."""
    flat = x.ravel()
    n = flat.shape[0]
    per_panel = LANES * SUBLANES
    padded = ((n + per_panel - 1) // per_panel) * per_panel
    flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(-1, LANES), n


def _pick_block_rows(rows: int, budget: int = _BLOCK_ROWS) -> int:
    """Largest block size <= budget dividing ``rows`` (rows % SUBLANES == 0,
    guaranteed by _to_2d, so the loop terminates at SUBLANES or below)."""
    block_rows = min(budget, rows)
    while rows % block_rows:
        block_rows -= SUBLANES if block_rows > SUBLANES else 1
    return max(block_rows, 1)


def _from_2d(panel: jax.Array, n: int, shape, dtype) -> jax.Array:
    return panel.ravel()[:n].reshape(shape).astype(dtype)


def _masked_sgd_kernel(lr_ref, p_ref, m_ref, g_ref, mask_ref,
                       p_out, m_out, *, momentum: float, wd: float,
                       mask_grads: bool):
    lr = lr_ref[0]
    g = g_ref[:]
    if mask_grads:
        g = g * mask_ref[:]
    g = g + wd * p_ref[:]
    m_new = momentum * m_ref[:] + g
    p_new = p_ref[:] - lr * m_new
    if not mask_grads:
        p_new = p_new * mask_ref[:]
    p_out[:] = p_new
    m_out[:] = m_new


@functools.partial(jax.jit, static_argnames=("momentum", "wd", "mask_grads"))
def fused_masked_sgd_leaf(p, m, g, mask, lr, momentum: float = 0.0,
                          wd: float = 0.0, mask_grads: bool = False):
    """One leaf's fused update. ``mask_grads=False`` -> SalientGrads
    semantics (post-step ``p *= mask``); ``True`` -> DisPFL masked-gradient
    SGD. Returns (p_new, m_new) with the leaf's original shape/dtype."""
    shape, dtype = p.shape, p.dtype
    p2, n = _to_2d(p.astype(jnp.float32))
    m2, _ = _to_2d(m.astype(jnp.float32))
    g2, _ = _to_2d(g.astype(jnp.float32))
    k2, _ = _to_2d(mask.astype(jnp.float32))
    rows = p2.shape[0]
    block_rows = _pick_block_rows(rows)
    grid = (rows // block_rows,)

    vmem_spec = pl.BlockSpec(
        (block_rows, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    kernel = functools.partial(
        _masked_sgd_kernel, momentum=momentum, wd=wd, mask_grads=mask_grads)
    p_new, m_new = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lr scalar
            vmem_spec, vmem_spec, vmem_spec, vmem_spec,
        ],
        out_specs=[vmem_spec, vmem_spec],
        out_shape=[
            jax.ShapeDtypeStruct(p2.shape, jnp.float32),
            jax.ShapeDtypeStruct(m2.shape, jnp.float32),
        ],
        interpret=_interpret(),
    )(jnp.asarray(lr, jnp.float32).reshape(1), p2, m2, g2, k2)
    # momentum keeps its own dtype (f32 buffers stay f32 under bf16 params)
    return (_from_2d(p_new, n, shape, dtype),
            _from_2d(m_new, n, shape, m.dtype))


def fused_masked_sgd_step(params: Any, momentum_tree: Any, grads: Any,
                          mask: Any, lr, momentum: float = 0.0,
                          wd: float = 0.0, mask_grads: bool = False
                          ) -> Tuple[Any, Any]:
    """Pytree-level fused update (drop-in for optim.sgd_momentum_step +
    mask projection)."""
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_m = treedef.flatten_up_to(momentum_tree)
    flat_g = treedef.flatten_up_to(grads)
    flat_k = treedef.flatten_up_to(mask)
    out_p, out_m = [], []
    for p, m, g, k in zip(flat_p, flat_m, flat_g, flat_k):
        p2, m2 = fused_masked_sgd_leaf(
            p, m, g, k, lr, momentum=momentum, wd=wd, mask_grads=mask_grads)
        out_p.append(p2)
        out_m.append(m2)
    return (jax.tree_util.tree_unflatten(treedef, out_p),
            jax.tree_util.tree_unflatten(treedef, out_m))


# -- fused weighted aggregation ----------------------------------------------

def _wsum_kernel(w_ref, x_ref, out_ref):
    """out = sum_c w[c] * x[c] for one (clients, block, LANES) tile."""
    x = x_ref[:]                       # (C, block_rows, LANES)
    acc = jnp.zeros(x.shape[1:], jnp.float32)
    for c in range(x.shape[0]):        # static unroll over clients
        acc = acc + w_ref[c] * x[c]    # scalar SMEM load per client
    out_ref[:] = acc


@jax.jit
def fused_weighted_sum_leaf(stacked: jax.Array, weights: jax.Array):
    """Sample-weighted FedAvg reduction over a leading client axis in one
    HBM pass (the `psum` in fedavg_api.py:102-117), fused across the whole
    leaf instead of C separate scale+add kernels."""
    c = stacked.shape[0]
    shape = stacked.shape[1:]
    dtype = stacked.dtype
    flat = stacked.reshape(c, -1).astype(jnp.float32)
    n = flat.shape[1]
    panels = jax.vmap(lambda v: _to_2d(v)[0])(flat)  # per-client pad + panel
    rows = panels.shape[1]
    # the input block is (c, block_rows, LANES): shrink block_rows by the
    # client count so VMEM stays ~_BLOCK_ROWS*LANES*4B regardless of c
    block_rows = _pick_block_rows(rows, max(SUBLANES, _BLOCK_ROWS // max(c, 1)))
    grid = (rows // block_rows,)

    out = pl.pallas_call(
        _wsum_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((c, block_rows, LANES), lambda i: (0, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        interpret=_interpret(),
    )(weights.astype(jnp.float32), panels)
    return out.ravel()[:n].reshape(shape).astype(dtype)


def fused_weighted_sum(stacked_tree: Any, weights: jax.Array) -> Any:
    return jax.tree_util.tree_map(
        lambda x: fused_weighted_sum_leaf(x, weights), stacked_tree)


# -- threshold top-k selection (the --agg_kernels wire leg) -------------------
#
# ops/topk_select.py owns the algorithm and the tie-break contract; the
# kernel below is its pallas backend: the magnitudes stay VMEM-resident
# across all SEARCH_ITERS count passes of the bit-space binary search —
# ONE read of the row from HBM, vs one sweep per pass for the XLA
# spelling. Both converge to the same unique integer fixed point (the
# largest bit pattern with count >= k), so the backends are bit-identical
# by construction, not by tolerance.

#: per-row element cap for the VMEM-resident search: the row (f32), its
#: int32 bit view and one compare temp must share VMEM, so rows above
#: this fall back to the XLA search (same bits, different residency)
THRESHOLD_MAX_N = 1 << 20

#: f32-block byte budget used to pick how many rows share one kernel
#: instance (x + bits + temp keeps the total well under VMEM)
_THRESH_BLOCK_BYTES = 1 << 22


def threshold_supported(n: int) -> bool:
    """Can the pallas threshold kernel hold an n-element row in VMEM?"""
    return int(n) <= THRESHOLD_MAX_N


def _threshold_kernel(k_ref, av_ref, out_ref, *, iters: int,
                      bits_hi: int):
    """Bit-space binary search over one (cb, rows, LANES) magnitude
    block: lo converges to the k-th largest magnitude's bit pattern
    (topk_select.exact_threshold, same invariant/fixed point)."""
    bits = jax.lax.bitcast_convert_type(av_ref[:], jnp.int32)
    k = k_ref[0]
    cb = bits.shape[0]
    lo0 = jnp.zeros((cb, 1, 1), jnp.int32)
    hi0 = jnp.full((cb, 1, 1), bits_hi, jnp.int32)

    def body(_, lohi):
        lo, hi = lohi
        mid = lo + (hi - lo) // 2
        cnt = jnp.sum((bits >= mid).astype(jnp.int32), axis=(1, 2),
                      keepdims=True)
        ok = cnt >= k
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo, _ = jax.lax.fori_loop(0, iters, body, (lo0, hi0))
    thr = jax.lax.bitcast_convert_type(lo, jnp.float32)
    out_ref[:] = jnp.broadcast_to(thr[:, 0], (cb, LANES))


@functools.partial(jax.jit, static_argnames=("k",))
def threshold_topk(av: jax.Array, k: int) -> jax.Array:
    """Exact k-th largest magnitude per row of a [C, n] nonneg f32
    matrix, VMEM-resident; returns [C, 1] f32 — bit-identical to
    ``topk_select.exact_threshold(av, k)`` (and so to the sort
    spelling) under the tie-break contract. Rows are zero-padded to the
    (SUBLANES, LANES) tile; pad bits (0) never reach a count at any
    positive cut, and a cut can only fall to 0 when the true threshold
    IS 0.0, where counting pads is already harmless."""
    from .topk_select import _BITS_HI, SEARCH_ITERS

    c, n = av.shape
    per_panel = LANES * SUBLANES
    n_pad = ((n + per_panel - 1) // per_panel) * per_panel
    rows = n_pad // LANES
    cb = max(1, min(c, _THRESH_BLOCK_BYTES // (n_pad * 4)))
    c_pad = ((c + cb - 1) // cb) * cb
    av2 = jnp.pad(av.astype(jnp.float32),
                  ((0, c_pad - c), (0, n_pad - n)))
    panels = av2.reshape(c_pad, rows, LANES)

    kernel = functools.partial(_threshold_kernel, iters=SEARCH_ITERS,
                               bits_hi=int(_BITS_HI))
    out = pl.pallas_call(
        kernel,
        grid=(c_pad // cb,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # k scalar
            pl.BlockSpec((cb, rows, LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((cb, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((c_pad, LANES), jnp.float32),
        interpret=_interpret(),
    )(jnp.asarray([k], jnp.int32), panels)
    return out[:c, :1]


# -- fused int8 quantize + weighted bucketed reduce ---------------------------
#
# The off-mesh int8 wire (collectives._reduce_mat) is a chain of
# materialized ops per [C, nb, b] bucket tensor: divide -> floor ->
# uniform-compare -> clip -> int8 cast -> dequantize -> tensordot. The
# kernel below fuses the whole quantize/dequantize chain AND the
# weighted client contraction into one pass over the cohort matrix:
# each grid step reads one bucket-aligned chunk of every client's row
# once, stochastic-rounds it with a PRECOMPUTED uniform draw (the same
# rng call and shape as the XLA chain, so the rounding bits are
# identical by construction) and a precomputed per-(client, bucket)
# scale, and contracts the dequantized chunk against the weights with
# ``jnp.dot`` — the SAME dot primitive ``tensordot`` lowers to, and
# per-output-column contractions are independent of how columns are
# chunked, so the kernel's sums are bit-identical to the XLA
# reference's ``tensordot(w, deq)`` (pinned by
# tests/test_pallas_kernels.py). An explicit elementwise accumulate
# spelling was measured to diverge by one ulp instead: XLA:CPU
# contracts ``acc + w*deq`` into an FMA that no barrier/bitcast
# spelling suppresses, while the shared-dot spelling keeps both
# backends inside one primitive. Only the scale's amax reduce stays
# outside the kernel (it must see the whole bucket before the first
# quantized element; max is exact in any association, so it is
# bit-stable and shared by both backends).

#: per-chunk f32 byte budget of the fused kernel (x + u blocks each)
_QR_CHUNK_BYTES = 1 << 21


def quantize_reduce_supported(bucket: int) -> bool:
    """Fused-kernel eligibility: chunks must tile (SUBLANES x LANES)
    exactly and align to bucket boundaries (one scale per chunk), so
    the bucket must be a multiple of the 1024-element panel; anything
    else routes to the bit-identical XLA spelling."""
    per_panel = LANES * SUBLANES
    return int(bucket) % per_panel == 0


def _qreduce_kernel(w_ref, x_ref, u_ref, s_ref, out_ref):
    x = x_ref[:]                        # (C, chunk)
    u = u_ref[:]
    scale = s_ref[:]                    # (C, 1) — this chunk's bucket
    y = x / scale
    f = jnp.floor(y)
    q = jnp.clip(f + (u < (y - f)).astype(jnp.float32), -127.0, 127.0)
    out_ref[:] = jnp.dot(w_ref[:], q * scale)   # (1,C)@(C,chunk)


@jax.jit
def fused_quantize_reduce(buckets: jax.Array, weights: jax.Array,
                          uniforms: jax.Array,
                          scales: jax.Array) -> jax.Array:
    """out[j] = sum_c w[c] * dequant(stochastic_int8(buckets[c, j]))
    for a [C, nb, b] bucketed client matrix, quantize chain + weighted
    contraction fused per chunk. ``uniforms`` is the [C, nb, b]
    stochastic-rounding draw and ``scales`` the [C, nb] per-bucket
    max-abs/127 scale — both computed by the caller with the exact
    spelling of the XLA chain, so backend bit-identity needs only this
    kernel's chunk math to match (it does: shared dot primitive, see
    module comment). Returns [nb, b] f32. Caller guards with
    :func:`quantize_reduce_supported`."""
    c, nb, b = buckets.shape
    n = nb * b
    x = buckets.astype(jnp.float32).reshape(c, n)
    u = uniforms.astype(jnp.float32).reshape(c, n)
    per_panel = LANES * SUBLANES
    budget = max(per_panel,
                 (_QR_CHUNK_BYTES // (max(c, 1) * 4)) // per_panel
                 * per_panel)
    chunk = min(b, budget)
    while b % chunk:                    # b % per_panel == 0 (guard), so
        chunk -= per_panel              # this terminates at per_panel

    block = pl.BlockSpec((c, chunk), lambda ci: (0, ci),
                         memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _qreduce_kernel,
        grid=(n // chunk,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),      # (1, C) weights
            block, block,
            pl.BlockSpec((c, 1), lambda ci: (0, ci * chunk // b),
                         memory_space=pltpu.VMEM),      # bucket scale
        ],
        out_specs=pl.BlockSpec((1, chunk), lambda ci: (0, ci),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.float32),
        interpret=_interpret(),
    )(weights.astype(jnp.float32).reshape(1, c), x, u,
      scales.astype(jnp.float32))
    return out.reshape(nb, b)


# -- fused SNIP mask ops (SalientGrads selection path) ------------------------

def _mask_apply_kernel(p_ref, m_ref, out_ref):
    out_ref[:] = p_ref[:] * m_ref[:]


@jax.jit
def fused_mask_apply_leaf(p: jax.Array, m: jax.Array) -> jax.Array:
    """One-pass ``p * m`` mask projection for one leaf (the SalientGrads
    post-aggregate re-mask) — bit-identical to the jnp spelling (one
    f32 multiply either way; masks are binary)."""
    shape, dtype = p.shape, p.dtype
    p2, n = _to_2d(p.astype(jnp.float32))
    m2, _ = _to_2d(m.astype(jnp.float32))
    rows = p2.shape[0]
    block_rows = _pick_block_rows(rows)
    vmem_spec = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _mask_apply_kernel,
        grid=(rows // block_rows,),
        in_specs=[vmem_spec, vmem_spec],
        out_specs=vmem_spec,
        out_shape=jax.ShapeDtypeStruct(p2.shape, jnp.float32),
        interpret=_interpret(),
    )(p2, m2)
    return _from_2d(out, n, shape, dtype)


def fused_mask_apply(tree: Any, mask: Any) -> Any:
    """Pytree-level fused mask projection (drop-in for
    ``tree_map(lambda p, m: p * m, tree, mask)``)."""
    return jax.tree_util.tree_map(fused_mask_apply_leaf, tree, mask)


def _score_mask_kernel(nt_ref, s_ref, out_ref):
    norm = nt_ref[0]
    thr = nt_ref[1]
    out_ref[:] = (s_ref[:] / norm >= thr).astype(jnp.float32)


@jax.jit
def fused_score_mask_leaf(s: jax.Array, norm: jax.Array,
                          thr: jax.Array) -> jax.Array:
    """One-pass magnitude-score mask build for one leaf:
    ``(s / norm >= thr) -> {0, 1}`` fused (normalize + compare + cast),
    bit-identical to the jnp spelling in ``sparsity.mask_from_scores``.
    Zero-pad is harmless: pad lanes are sliced away before the
    compare's result leaves the kernel wrapper."""
    shape, dtype = s.shape, s.dtype
    s2, n = _to_2d(s.astype(jnp.float32))
    rows = s2.shape[0]
    block_rows = _pick_block_rows(rows)
    vmem_spec = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    nt = jnp.stack([jnp.asarray(norm, jnp.float32).reshape(()),
                    jnp.asarray(thr, jnp.float32).reshape(())])
    out = pl.pallas_call(
        _score_mask_kernel,
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), vmem_spec],
        out_specs=vmem_spec,
        out_shape=jax.ShapeDtypeStruct(s2.shape, jnp.float32),
        interpret=_interpret(),
    )(nt, s2)
    return _from_2d(out, n, shape, dtype)
