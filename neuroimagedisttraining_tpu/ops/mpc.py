"""Finite-field MPC primitives for secure aggregation (TurboAggregate).

Functional equivalents of the reference's
``fedml_api/standalone/turboaggregate/mpc_function.py:4-275`` — modular
inverse, Lagrange coefficients, BGW (Shamir) secret sharing, Lagrange Coded
Computing encode/decode, additive secret shares, and DH-style key agreement
— reimplemented from the underlying mathematics (Fermat inverses, Horner
polynomial evaluation, vectorized numpy int64 field ops) rather than ported.
Correctness-only host-side code per SURVEY.md §7.7; the field arithmetic is
exact for primes p with p^2 < 2^63.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

DEFAULT_PRIME = 2_147_483_647  # 2^31 - 1 (Mersenne)


def mod_inverse(a: int, p: int) -> int:
    """Modular inverse via Fermat's little theorem (p prime)."""
    a = int(a) % p
    if a == 0:
        raise ZeroDivisionError("no inverse for 0")
    return pow(a, p - 2, p)


def field_div(num, den, p: int):
    """Elementwise num/den in F_p."""
    inv = mod_inverse(int(den), p)
    return np.mod(np.asarray(num, np.int64) * np.int64(inv), p)


def _matmul_mod(a: np.ndarray, b: np.ndarray, p: int) -> np.ndarray:
    """``(a @ b) mod p`` without int64 overflow: a plain matmul accumulates
    up to K products of size (p-1)^2 each before reducing, which wraps for
    K >= 3 at p ~ 2^31; reducing after every rank-1 term keeps every partial
    below p^2 + p < 2^63."""
    a = np.mod(np.asarray(a, np.int64), p)
    b = np.mod(np.asarray(b, np.int64), p)
    out = np.zeros((a.shape[0],) + b.shape[1:], np.int64)
    for j in range(a.shape[1]):
        out = np.mod(out + a[:, j, None] * b[j], p)
    return out


def lagrange_coeffs(
    targets: Sequence[int], nodes: Sequence[int], p: int
) -> np.ndarray:
    """L[i, j] = ell_j(targets[i]) over F_p for interpolation nodes
    ``nodes`` — the coefficient matrix for evaluating the interpolating
    polynomial at ``targets``."""
    targets = [int(t) % p for t in targets]
    nodes = [int(b) % p for b in nodes]
    m, n = len(targets), len(nodes)
    out = np.zeros((m, n), dtype=np.int64)
    for j, bj in enumerate(nodes):
        den = 1
        for k, bk in enumerate(nodes):
            if k != j:
                den = den * ((bj - bk) % p) % p
        inv_den = mod_inverse(den, p)
        for i, t in enumerate(targets):
            num = 1
            for k, bk in enumerate(nodes):
                if k != j:
                    num = num * ((t - bk) % p) % p
            out[i, j] = num * inv_den % p
    return out


def _poly_eval(coeffs: np.ndarray, x: int, p: int) -> np.ndarray:
    """Horner evaluation of a coefficient stack [T+1, ...] at scalar x."""
    acc = np.zeros_like(coeffs[0])
    for c in coeffs[::-1]:
        acc = np.mod(acc * np.int64(x) + c, p)
    return acc


def shamir_share(
    x: np.ndarray, n_shares: int, threshold: int, p: int,
    rng: np.random.RandomState = None,
) -> np.ndarray:
    """BGW/Shamir sharing: degree-``threshold`` polynomial with constant
    term x, evaluated at alpha = 1..n (mpc_function.py BGW_encoding).
    Returns [n_shares, *x.shape]."""
    rng = rng or np.random.RandomState()
    x = np.mod(np.asarray(x, np.int64), p)
    coeffs = np.concatenate([
        x[None], rng.randint(0, p, size=(threshold,) + x.shape),
    ]).astype(np.int64)
    return np.stack([
        _poly_eval(coeffs, alpha, p) for alpha in range(1, n_shares + 1)
    ])


def shamir_reconstruct(
    shares: np.ndarray, holder_idx: Sequence[int], p: int
) -> np.ndarray:
    """Reconstruct the secret (evaluation at 0) from >= threshold+1 shares
    held by alpha indices ``holder_idx`` (0-based; alpha = idx+1)
    (mpc_function.py BGW_decoding)."""
    alphas = [i + 1 for i in holder_idx]
    lam = lagrange_coeffs([0], alphas, p)[0]  # [len(shares)]
    acc = np.zeros_like(np.asarray(shares[0], np.int64))
    for l, s in zip(lam, shares):
        acc = np.mod(acc + np.int64(l) * np.asarray(s, np.int64), p)
    return acc


def lcc_encode(
    x: np.ndarray, n_workers: int, k_split: int, t_privacy: int, p: int,
    rng: np.random.RandomState = None,
) -> np.ndarray:
    """Lagrange Coded Computing encode (mpc_function.py LCC_encoding):
    split x's leading axis into K chunks, append T random chunks, pass the
    interpolating polynomial through them at beta nodes, and evaluate at
    alpha nodes for the N workers. Returns [N, len//K, ...]."""
    rng = rng or np.random.RandomState()
    m = x.shape[0]
    if m % k_split:
        # explicit raise, not assert: python -O must not strip the
        # shape contract of the secure-sum encoding (ADVICE r5)
        raise ValueError(
            f"LCC encoding needs the leading axis ({m}) to divide "
            f"into K={k_split} chunks")
    chunk = m // k_split
    subs = [np.mod(np.asarray(x[i * chunk:(i + 1) * chunk], np.int64), p)
            for i in range(k_split)]
    subs += [rng.randint(0, p, size=subs[0].shape).astype(np.int64)
             for _ in range(t_privacy)]
    betas = list(range(1, k_split + t_privacy + 1))
    alphas = list(range(k_split + t_privacy + 1,
                        k_split + t_privacy + 1 + n_workers))
    lam = lagrange_coeffs(alphas, betas, p)  # [N, K+T]
    stacked = np.stack(subs)  # [K+T, chunk, ...]
    flat = stacked.reshape(len(subs), -1)
    enc = _matmul_mod(lam, flat, p)
    return enc.reshape((n_workers,) + stacked.shape[1:])


def lcc_decode(
    worker_outputs: np.ndarray, worker_ids: Sequence[int],
    n_workers: int, k_split: int, t_privacy: int, p: int,
) -> np.ndarray:
    """LCC decode (mpc_function.py LCC_decoding): interpolate worker
    evaluations back to the beta nodes of the data chunks, for degree-1
    (identity / secure-aggregation) computations — the encoding polynomial
    has degree K+T-1, so at least K+T worker outputs are required.
    Returns [K, chunk, ...]."""
    if len(worker_ids) < k_split + t_privacy:
        raise ValueError(
            f"need >= K+T = {k_split + t_privacy} worker outputs to decode, "
            f"got {len(worker_ids)}"
        )
    betas = list(range(1, k_split + t_privacy + 1))
    alphas = list(range(k_split + t_privacy + 1,
                        k_split + t_privacy + 1 + n_workers))
    eval_points = [alphas[i] for i in worker_ids]
    lam = lagrange_coeffs(betas[:k_split], eval_points, p)  # [K, n_used]
    flat = np.mod(np.asarray(worker_outputs, np.int64).reshape(len(worker_ids), -1), p)
    dec = _matmul_mod(lam, flat, p)
    return dec.reshape((k_split,) + worker_outputs.shape[1:])


def additive_shares(
    x: np.ndarray, n_shares: int, p: int,
    rng: np.random.RandomState = None,
) -> np.ndarray:
    """Additive secret sharing (mpc_function.py Gen_Additive_SS): n-1
    uniform shares plus a correction share summing to x mod p."""
    rng = rng or np.random.RandomState()
    x = np.mod(np.asarray(x, np.int64), p)
    shares = rng.randint(0, p, size=(n_shares - 1,) + x.shape).astype(np.int64)
    last = np.mod(x - shares.sum(axis=0), p)
    return np.concatenate([shares, last[None]])


def dh_keygen(sk: int, g: int, p: int) -> int:
    """Public key g^sk mod p (mpc_function.py my_pk_gen)."""
    return pow(int(g), int(sk), int(p))


def dh_key_agreement(their_pk: int, my_sk: int, p: int) -> int:
    """Shared key pk^sk mod p (mpc_function.py my_key_agreement)."""
    return pow(int(their_pk), int(my_sk), int(p))


# ---------------------------------------------------------------------------
# fixed-point quantization for model <-> field transport
# ---------------------------------------------------------------------------

def quantize(x: np.ndarray, scale: int, p: int) -> np.ndarray:
    """Map floats to F_p with fixed-point scale; negatives wrap mod p."""
    q = np.round(np.asarray(x, np.float64) * scale).astype(np.int64)
    return np.mod(q, p)


def dequantize(q: np.ndarray, scale: int, p: int) -> np.ndarray:
    """Inverse of ``quantize``: values above p/2 are negative."""
    q = np.asarray(q, np.int64)
    signed = np.where(q > p // 2, q - p, q)
    return signed.astype(np.float64) / scale
