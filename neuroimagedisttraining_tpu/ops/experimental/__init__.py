"""Unwired research kernels — harness-verified NEGATIVE results.

Nothing in here is on a product path (VERDICT r3 weak #5 quarantine).
These are the round-2/3 Pallas stem-kernel experiments for the AlexNet3D
s2d stem, kept because their measurements justify the product's choice of
the plain XLA convolution:

* ``pallas_stem.py`` — im2col stem forward (r2): exact, ties XLA.
* ``pallas_stem_v3.py`` — staged-unfold forward family (r3): five
  formulations, all tie XLA within noise.
* ``pallas_stem_bwd.py`` / ``pallas_stem_fused.py`` — fused
  conv+pool+stats forward and the fused backward (r3): exact, but the
  backward loses ~2x to XLA (Mosaic cannot block the sublane<->lane
  transpose of (phase, w) tiles on bf16).

See RESULTS.md "Round-3 stem-kernel investigation" for the numbers and
the wall analysis; tests/test_pallas_stem.py pins exactness in
interpret mode so the record stays runnable. The WIRED Pallas kernel
(``ops/pallas_kernels.py``, the fused masked-SGD update behind
``--fused_kernels``) lives in the product package proper.
"""
