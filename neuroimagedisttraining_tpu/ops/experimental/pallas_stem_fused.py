"""Fused stem forward — conv + 3x3x3/s3 max-pool + GN stat partials in one
Pallas pass (r3 mega-kernel starting material; NOT wired into any product
path). Verification is the on-chip harness —
``python -m neuroimagedisttraining_tpu.ops.experimental.pallas_stem_fused`` prints the
error-vs-XLA table (full-size interpret mode on the 1-core CPU host takes
~9 min, so there is deliberately no CPU test; the base im2col kernel IS
CPU-tested in tests/test_pallas_stem.py).

All three outputs are verified exact against the XLA reference on the
canonical phased ABCD shape (zs and pooled bit-exact in bf16; stat
partials to f32 accumulation order, ~1e-5 rel). Status on the v5e
(RESULTS.md r2 close-out): ties the XLA conv+pool+stats trio within
measurement noise — the in-VMEM unfold writes (~4 ms/step floor across
all formulations tried) are the cost XLA's direct-conv emitter does not
pay. The remaining r3 angle is eliminating the unfold: one-write-per-tap
3D tiles with per-slice dots, or a direct-conv MAC formulation.

Hard-won structural pieces captured here:
  * strip/pool d-alignment: SD=3 strips aligned to pool d-groups, with
    the ragged tail strip ordered FIRST so its misaligned pool store is
    overwritten by the last aligned strip (TPU pallas grids execute
    sequentially per core);
  * static h-group schedule H0S covering 71 rows with pool-aligned
    sub-rows and one overlap row, with the overlap statically excluded
    from the stat sums (and the tail strip's re-counted d-plane excluded
    via a program-id predicate);
  * in-kernel w-pooling via transpose + sublane-splitting reshape-max.

This module is fixed to the canonical phased ABCD extents
(61x73x8x61 -> 59x71x59, pool 19x23x19).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax import lax

B, Dp, Hp, P8, Wp = 8, 61, 73, 8, 61
D, H, W = 59, 71, 59          # conv output extents
PD, PH, PW = 19, 23, 19       # pooled extents
F = 64
SD = 3
# strips: s=0 is the ragged tail at d0=56 (its misaligned pool store is
# overwritten later), s=1..19 are the aligned strips at d0=3*(s-1)
# covering d 0..56 — 20 programs total
NSTRIP = 20
HG = 9
H0S = [0, 9, 18, 27, 36, 45, 54, 62]   # static h-group starts (cover 0..70)


def kernel(x_ref, w_ref, ozs_ref, opool_ref, ostat_ref, u_ref, z3_ref):
    s = pl.program_id(1)
    wt = w_ref[:]
    # lane validity masks for stats: slot lanes 64j..64j+58 valid
    lane_ids = jax.lax.broadcasted_iota(jnp.int32, (1, 64 * HG), 1)
    slot_pos = lane_ids % 64
    lane_valid = (slot_pos < W).astype(jnp.float32)

    ssum = jnp.zeros((1, F), jnp.float32)
    ssq = jnp.zeros((1, F), jnp.float32)

    for gi, h0 in enumerate(H0S):
        nj = HG  # every group in H0S spans exactly HG rows
        # build + dot for each of the 3 local d-planes
        for ld in range(SD):
            for dz in range(3):
                for dy in range(3):
                    for dx in range(3):
                        k0 = ((dz * 3 + dy) * 3 + dx) * P8
                        for j in range(nj):
                            blk = x_ref[0, ld + dz, h0 + j + dy, :,
                                        dx:dx + W]
                            u_ref[k0:k0 + 8, 64 * j:64 * j + W] = blk
            z = lax.dot_general(wt, u_ref[:], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            z3_ref[ld] = z
            # zs rows out
            zt = z.T
            for j in range(nj):
                ozs_ref[0, ld, h0 + j, :, :] = \
                    zt[64 * j:64 * j + W, :].astype(ozs_ref.dtype)
            # stats: skip overlap rows (group 7 end 62 vs group 8 start 62)
            jskip = 1 if gi == len(H0S) - 1 else 0
            row_valid = lane_valid * (lane_ids >= 64 * jskip).astype(
                jnp.float32)
            # tail strip (s==0, d0=56): row ld=0 (d=56) is re-counted by
            # the last aligned strip -> zero its contribution
            ld_w = jnp.where((s == 0) & (ld == 0), 0.0, 1.0)
            zm = z * row_valid
            ssum = ssum + ld_w * jnp.sum(zm, axis=1, keepdims=True).T
            ssq = ssq + ld_w * jnp.sum(zm * z, axis=1, keepdims=True).T

        # pooling for this h-group: d-max across the 3 planes
        dmax = jnp.maximum(jnp.maximum(z3_ref[0], z3_ref[1]), z3_ref[2])
        # pool-aligned local h rows: h0 % 3 == 0 -> offsets 0,3,6;
        # group 7 (h0=62): aligned sub-rows start at local 1 (h=63,66)
        off0 = (3 - (h0 % 3)) % 3
        for a in range(3):
            j0 = off0 + 3 * a
            if j0 + 3 > nj or h0 + j0 + 2 > 68:
                continue
            ph = (h0 + j0) // 3
            hmax = jnp.maximum(
                jnp.maximum(dmax[:, 64 * j0:64 * j0 + W],
                            dmax[:, 64 * (j0 + 1):64 * (j0 + 1) + W]),
                dmax[:, 64 * (j0 + 2):64 * (j0 + 2) + W])   # (F, W)
            mt = hmax.T[:57, :]                              # (57, F)
            pw = jnp.max(mt.reshape(PW, 3, F), axis=1)       # (19, F)
            opool_ref[0, 0, ph, :, :] = pw.astype(opool_ref.dtype)

    ostat_ref[0, 0, 0, :] = ssum.reshape(F)
    ostat_ref[0, 0, 1, :] = ssq.reshape(F)


def _d0(s):
    return jnp.where(s == 0, D - SD, 3 * (s - 1))


def fused_stem_fwd(x, wt):
    # element-offset index maps (the pl.Element mode of older jax):
    # unblocked indexing with plain int block shapes
    unblocked = pl.Unblocked()
    kern = kernel
    zs, pooled, stats = pl.pallas_call(
        kern,
        grid=(B, NSTRIP),
        in_specs=[
            pl.BlockSpec((1, SD + 2, Hp, P8, Wp),
                         lambda b, s: (b, _d0(s), 0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, SD, H, W, F),
                         lambda b, s: (b, _d0(s), 0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec((1, 1, PH, PW, F),
                         lambda b, s: (b, jnp.minimum(_d0(s) // 3, PD - 1),
                                       0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec((1, 1, 2, F),
                         lambda b, s: (b, s, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, D, H, W, F), x.dtype),
            jax.ShapeDtypeStruct((B, PD, PH, PW, F), x.dtype),
            jax.ShapeDtypeStruct((B, NSTRIP, 2, F), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((216, 64 * HG), x.dtype),
            pltpu.VMEM((SD, F, 64 * HG), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(x, wt.astype(x.dtype))
    return zs, pooled, stats


def ref(x, w):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NDHCW", "DHWIO", "NDHWC"))
    zs = lax.conv_general_dilated(x, w, (1, 1, 1), "VALID",
                                  dimension_numbers=dn)
    import flax.linen as nn
    pooled = nn.max_pool(zs, (3, 3, 3), strides=(3, 3, 3))
    zf = zs.astype(jnp.float32)
    return zs, pooled, (jnp.sum(zf, axis=(1, 2, 3)),
                        jnp.sum(zf * zf, axis=(1, 2, 3)))


if __name__ == "__main__":  # on-chip check harness
    import time

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, Dp, Hp, P8, Wp), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, P8, F),
                          jnp.bfloat16)
    wt = jnp.transpose(w.reshape(27 * 8, F))
    def timeit(f, *args, n=20):
        for _ in range(3):
            out = f(*args)
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        t0 = time.perf_counter()
        for _ in range(n):
            out = f(*args)
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        return (time.perf_counter() - t0) / n

    jf = jax.jit(fused_stem_fwd)
    jr = jax.jit(ref)
    zs, m, st = jf(x, wt)
    rzs, rm, (rs, rq) = jr(x, w)
    print("zs err:", float(jnp.max(jnp.abs(zs.astype(jnp.float32)
                                           - rzs.astype(jnp.float32)))))
    print("pool err:", float(jnp.max(jnp.abs(m.astype(jnp.float32)
                                             - rm.astype(jnp.float32)))))
    ks = jnp.sum(st[:, :, 0, :], axis=1)
    kq = jnp.sum(st[:, :, 1, :], axis=1)
    print("sum relerr:", float(jnp.max(jnp.abs(ks - rs)
                                       / (jnp.abs(rs) + 1e-3))))
    print("sumsq relerr:", float(jnp.max(jnp.abs(kq - rq)
                                         / (jnp.abs(rq) + 1e-3))))
    print(f"fused: {timeit(jf, x, wt)*1e3:.2f} ms   "
          f"ref(conv+pool+stats): {timeit(jr, x, w)*1e3:.2f} ms")
