"""Pallas im2col stem conv — experimental groundwork for the r3 mega-kernel.

The phased stem conv ((B, D', H', 8, W') x (3,3,3,8,F), ops/s2d.py) as an
explicit in-VMEM im2col + MXU dot: per (batch, d-strip) program, build the
(216, 8x64) unfold tile for 8 output h-rows and contract against the
(F, 216) remapped kernel.

Status (measured on the v5e, RESULTS.md r2):
  * EXACT vs lax.conv (max abs err 0.0 in bf16).
  * Standalone it beats XLA's conv emitter (6.9 vs 7.8 ms incl dispatch).
  * Swapped into the full training step it is NET SLOWER (19.5 vs 17.7
    ms/step): XLA's conv fuses the GroupNorm statistics into its epilogue
    and co-chooses layouts with the pool/backward consumers; a conv-only
    kernel forfeits both.
  * Every Mosaic capability the round-1 attempts lacked now works on this
    toolchain (probed: mid-axis transposes, sublane-offset block writes,
    unaligned lane reads, lane-offset-64 writes, sublane-splitting
    reshape-max, bf16 dots/writes). The winning r3 shape is therefore a
    FUSED forward kernel (conv + GN stats partials + 3x3x3 pool, so the
    full-size conv output never round-trips HBM) and a fused backward
    (pool-scatter + GN dense term + wgrad accumulation); estimated
    step 13.7 -> ~10 ms. Not attempted this round — kept unwired.

Not used by any product path; exercised by tests/test_pallas_stem.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax import lax

R = 3       # remapped kernel extent per dim
P8 = 8      # phases
HG = 8      # h-rows per dot


def _kernel(x_ref, w_ref, o_ref, u_scratch, *, SD, H, W):
    wt = w_ref[:]
    NHG = -(-H // HG)

    def body(ld, _):
        for g in range(NHG):
            h0 = min(g * HG, H - HG)
            for dz in range(R):
                for dy in range(R):
                    for dx in range(R):
                        k0 = ((dz * R + dy) * R + dx) * P8
                        for j in range(HG):
                            blk = x_ref[0, pl.ds(ld + dz, 1),
                                        h0 + j + dy, :, dx:dx + W]
                            u_scratch[k0:k0 + 8, 64 * j:64 * j + W] = \
                                blk.reshape(P8, W)
            z = lax.dot_general(
                wt, u_scratch[:], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            zt = z.T
            for j in range(HG):
                o_ref[0, pl.ds(ld, 1), h0 + j, :, :] = \
                    zt[64 * j:64 * j + W, :].astype(o_ref.dtype).reshape(
                        1, W, o_ref.shape[-1])
        return 0

    jax.lax.fori_loop(0, SD, body, 0)


def stem_conv_pallas(x, wt):
    """x: (B, D', H', 8, W') phased volume; wt: (F, 216) remapped kernel
    (k = (dz*3+dy)*3+dx)*8 + p). Returns the VALID stride-1 conv
    (B, D'-2, H'-2, W'-2, F), matching lax.conv on NDHCW/DHWIO."""
    B, Dp, Hp, P, Wp = x.shape
    F = wt.shape[0]
    D, H, W = Dp - 2, Hp - 2, Wp - 2
    # current tiling preconditions (violations would corrupt silently:
    # negative h0 wraps static indices; W > 64 overlaps the 64-lane j-slots)
    if P != P8:
        raise ValueError(f"phase axis must be {P8}, got {P}")
    if H < HG:
        raise ValueError(
            f"output height {H} < h-group {HG}; this experimental tiling "
            "needs H' >= 10")
    if W > 64:
        raise ValueError(
            f"output width {W} > 64 exceeds the 64-lane j-slot tiling "
            "(canonical phased ABCD W' = 61 fits; the r3 fused kernel "
            "generalizes this)")
    # strip size: bound VMEM (in + 2x out blocks + scratch); f32 halves it
    SD = 4 if x.dtype == jnp.bfloat16 else 2
    SD = min(SD, D)
    NSTRIP = -(-D // SD)

    def start(b, s):
        return (b, jnp.minimum(s * SD, D - SD), 0, 0, 0)

    interpret = jax.default_backend() != "tpu"
    kern = functools.partial(_kernel, SD=SD, H=H, W=W)
    # `start` returns ELEMENT offsets (overlapping d-strips), so these
    # specs use unblocked indexing (the pl.Element mode of older jax)
    return pl.pallas_call(
        kern,
        grid=(B, NSTRIP),
        in_specs=[
            pl.BlockSpec((1, SD + 2, Hp, P, Wp), start,
                         memory_space=pltpu.VMEM,
                         indexing_mode=pl.Unblocked()),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, SD, H, W, F), start,
                               memory_space=pltpu.VMEM,
                               indexing_mode=pl.Unblocked()),
        out_shape=jax.ShapeDtypeStruct((B, D, H, W, F), x.dtype),
        scratch_shapes=[pltpu.VMEM((216, 64 * HG), x.dtype)],
        interpret=interpret,
    )(x, wt.astype(x.dtype))
