"""Fused stem-stage backward: dzs from pool-scatter + stat terms, one pass.

The pool-first stem stage (``models/alexnet3d.py::S2DStemStage``) consumes
the full-size conv output ``zs`` through exactly three reductions: the
3x3x3/s3 max-pool and the GroupNorm statistics sums S1 = sum(zs),
S2 = sum(zs^2) (per sample x channel). Under XLA the backward of that trio
costs three full-size passes — SelectAndScatter (~2.2 ms/step on the v5e),
the GN sum backward (~1.3 ms) — because each re-reads the 253 MB tensor.

``pool_sum_sumsq`` exposes the trio as ONE custom-vjp op whose backward is
a single Pallas pass: read zs once, emit
    dzs = gS1_c + 2 * gS2_c * zs + equal_mask * gm / tie_count
directly. The pool argmax is recovered by comparing zs to the pooled
forward value (saved residual); bf16 ties inside a window split the
cotangent evenly (torch/XLA scatter to the first max instead — an
equivalent subgradient; measurably different only at exact-tie positions,
which the equivalence test handles by masking ties).

Forward stays XLA (its conv+pool+stats fusion already runs at the
bandwidth wall — RESULTS.md r2/r3: every Pallas forward formulation tried,
including the r3 staged-unfold family, only ties it).

MEASURED r3 STATUS (v5e, in-graph fori-loop timings, RESULTS.md r3):
gradient EXACT vs XLA's VJP on every non-tied window (max abs diff 0.0;
~10% of bf16 windows contain ties, where the even-split cotangent differs
from XLA's scatter-to-first — both valid subgradients, total mass
conserved to 1.5e-5) — but the kernel LOSES decisively: fused fwd+bwd
17.1 ms vs XLA's 8.2 ms. The per-(plane,row) (59,64) VPU slice ops
(masks, tie counts, partial-row stores) are overhead-bound where XLA's
fused SelectAndScatter + reduction codegen vectorizes across rows. Ships
UNWIRED as the measured negative result closing the "fused backward"
branch of the r2 roadmap; the remaining credible path to >2 rounds/sec
single-chip is an XLA-level conv emitter improvement or a second chip.

Shapes are the canonical phased-ABCD stem extents: zs (B, 59, 71, 59, 64),
pool (B, 19, 23, 19, 64). ``supported_shape`` gates wiring.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


D, H, W, F = 59, 71, 59, 64
PD, PH, PW = 19, 23, 19
SD = 3          # one pool d-group per program
NSTRIP = PD + 1  # s=0 is the d=56..58 tail (dense-only; 56 rewritten later)


def supported_shape(zs_shape) -> bool:
    return tuple(zs_shape[1:]) == (D, H, W, F)


def _d0(s):
    return jnp.where(s == 0, D - SD, SD * (s - 1))


def _bwd_kernel(zs_ref, m_ref, gm_ref, gs_ref, out_ref):
    s = pl.program_id(1)
    # per-channel scalars for this batch row: dzs_dense = gS1 + 2*gS2*zs
    a = gs_ref[0, 0, :].reshape(1, F)          # gS1_c
    b2 = (2.0 * gs_ref[0, 1, :]).reshape(1, F)  # 2*gS2_c
    # the tail strip (s == 0, planes 56..58) is dense-only: 57/58 are
    # unpooled, and plane 56's windows belong to pool group 18 whose m/gm
    # this program does not hold — the later aligned strip (s == 19)
    # rewrites plane 56 with the correct scatter (sequential grid order).
    scatter_on = (s != 0).astype(jnp.float32)

    for ph in range(PH):
        h0 = 3 * ph
        mrow = m_ref[0, 0, ph, :, :].astype(jnp.float32)       # (PW, F)
        m3 = jnp.broadcast_to(mrow.reshape(PW, 1, F),
                              (PW, 3, F)).reshape(3 * PW, F)    # (57, F)
        gmrow = gm_ref[0, 0, ph, :, :].astype(jnp.float32)

        # equality masks per (plane, row) and the window-global tie count
        count = jnp.zeros((PW, F), jnp.float32)
        masks = {}
        zrows = {}
        for ld in range(SD):
            for r in range(3):
                zrow = zs_ref[0, ld, h0 + r, :, :].astype(jnp.float32)
                zrows[(ld, r)] = zrow
                mk = (zrow[:3 * PW, :] == m3).astype(jnp.float32)
                masks[(ld, r)] = mk
                count = count + jnp.sum(mk.reshape(PW, 3, F), axis=1)
        val = scatter_on * gmrow / jnp.maximum(count, 1.0)      # (PW, F)
        val3 = jnp.broadcast_to(val.reshape(PW, 1, F),
                                (PW, 3, F)).reshape(3 * PW, F)

        for ld in range(SD):
            for r in range(3):
                zrow = zrows[(ld, r)]
                out_ref[0, ld, h0 + r, :3 * PW, :] = (
                    a + b2 * zrow[:3 * PW, :] + masks[(ld, r)] * val3
                ).astype(out_ref.dtype)
                out_ref[0, ld, h0 + r, 3 * PW:, :] = (
                    a + b2 * zrow[3 * PW:, :]).astype(out_ref.dtype)

    # rows beyond the pooled region (h = 69, 70): dense term only
    for ld in range(SD):
        for h in (3 * PH, 3 * PH + 1):
            zrow = zs_ref[0, ld, h, :, :].astype(jnp.float32)
            out_ref[0, ld, h, :, :] = (a + b2 * zrow).astype(out_ref.dtype)


def _pool_sum_sumsq_fwd_impl(zs):
    import flax.linen as nn

    m = nn.max_pool(zs, (3, 3, 3), strides=(3, 3, 3))
    zf = zs.astype(jnp.float32)
    return m, jnp.sum(zf, axis=(1, 2, 3)), jnp.sum(zf * zf, axis=(1, 2, 3))


@jax.custom_vjp
def pool_sum_sumsq(zs):
    """(maxpool3_s3(zs), sum(zs), sum(zs^2)) with a fused one-pass
    backward. Forward is plain XLA."""
    return _pool_sum_sumsq_fwd_impl(zs)


def _fwd(zs):
    out = _pool_sum_sumsq_fwd_impl(zs)
    return out, (zs, out[0])


def _bwd(res, cts):
    zs, m = res
    gm, gs1, gs2 = cts
    gm = jnp.zeros_like(m) if isinstance(gm, jax.interpreters.ad.Zero) \
        else gm
    B = zs.shape[0]
    zero = jnp.zeros((B, F), jnp.float32)
    gs1 = zero if isinstance(gs1, jax.interpreters.ad.Zero) \
        else gs1.astype(jnp.float32)
    gs2 = zero if isinstance(gs2, jax.interpreters.ad.Zero) \
        else gs2.astype(jnp.float32)
    gs = jnp.stack([gs1, gs2], axis=1)  # (B, 2, F)
    # element-offset index maps (the pl.Element mode of older jax):
    # unblocked indexing with plain int block shapes
    unblocked = pl.Unblocked()
    dzs = pl.pallas_call(
        _bwd_kernel,
        grid=(B, NSTRIP),
        in_specs=[
            pl.BlockSpec((1, SD, H, W, F),
                         lambda b, s: (b, _d0(s), 0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec((1, 1, PH, PW, F),
                         lambda b, s: (b, jnp.minimum(_d0(s) // 3, PD - 1),
                                       0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec((1, 1, PH, PW, F),
                         lambda b, s: (b, jnp.minimum(_d0(s) // 3, PD - 1),
                                       0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec((1, 2, F), lambda b, s: (b, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
        ],
        out_specs=pl.BlockSpec((1, SD, H, W, F),
                               lambda b, s: (b, _d0(s), 0, 0, 0),
                               memory_space=pltpu.VMEM,
                               indexing_mode=unblocked),
        out_shape=jax.ShapeDtypeStruct(zs.shape, zs.dtype),
        interpret=jax.default_backend() != "tpu",
    )(zs, m, gm.astype(m.dtype), gs)
    return (dzs,)


pool_sum_sumsq.defvjp(_fwd, _bwd)


if __name__ == "__main__":  # on-chip check harness (see docstring)
    import time

    import numpy as np

    B = 8
    x = jax.random.normal(jax.random.PRNGKey(0), (B, D, H, W, F), jnp.bfloat16)

    def loss_fused(zs, gm, g1, g2):
        m, s1, s2 = pool_sum_sumsq(zs)
        return (jnp.sum(m.astype(jnp.float32) * gm)
                + jnp.sum(s1 * g1) + jnp.sum(s2 * g2))

    def loss_ref(zs, gm, g1, g2):
        m, s1, s2 = _pool_sum_sumsq_fwd_impl(zs)
        return (jnp.sum(m.astype(jnp.float32) * gm)
                + jnp.sum(s1 * g1) + jnp.sum(s2 * g2))

    k = jax.random.split(jax.random.PRNGKey(1), 3)
    gm = jax.random.normal(k[0], (B, 19, 23, 19, F), jnp.float32)
    g1 = jax.random.normal(k[1], (B, F), jnp.float32)
    g2 = jax.random.normal(k[2], (B, F), jnp.float32) * 1e-3

    dz_f = jax.jit(jax.grad(loss_fused))(x, gm, g1, g2)
    dz_r = jax.jit(jax.grad(loss_ref))(x, gm, g1, g2)
    dzf = np.asarray(dz_f, np.float32); dzr = np.asarray(dz_r, np.float32)

    # identify tie windows: where count of (zs == m) in window > 1
    import flax.linen as nn
    m, _, _ = _pool_sum_sumsq_fwd_impl(x)
    mrep = jnp.repeat(jnp.repeat(jnp.repeat(m, 3, 1), 3, 2), 3, 3)
    eq = (x[:, :57, :69, :57, :] == mrep).astype(jnp.float32)
    cnt = nn.avg_pool(eq, (3,3,3), strides=(3,3,3)) * 27
    tied = np.asarray(jnp.repeat(jnp.repeat(jnp.repeat(cnt > 1.5, 3, 1), 3, 2), 3, 3))
    print("tie fraction:", tied.mean())
    mask = np.zeros(dzf.shape, bool); mask[:, :57, :69, :57, :] = tied
    diff = np.abs(dzf - dzr); diff[mask] = 0
    print("max diff (non-tied):", diff.max())
    # conservation: total scatter mass equal even at ties
    print("sum diff:", abs(dzf.sum() - dzr.sum()) / abs(dzr.sum()))

    def timeit(f, *args, n=20):
        for _ in range(3): out = f(*args)
        float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        t0 = time.perf_counter()
        for _ in range(n): out = f(*args)
        float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        return (time.perf_counter() - t0) / n

    def loop_time(gf, args, iters=20):
        @jax.jit
        def f(c0, xx, gm, g1, g2):
            def body(i, carry):
                out = gf(xx + carry.astype(jnp.bfloat16) * 0, gm, g1, g2)
                return carry + 1e-12 * out.astype(jnp.float32)[0, 0, 0, 0, 0]
            return jax.lax.fori_loop(0, iters, body, c0)
        c0 = jnp.zeros((), jnp.float32)
        float(f(c0, *args))
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter(); float(f(c0, *args)); best = min(best, (time.perf_counter()-t0)/iters)
        return best

    gf = jax.jit(jax.grad(loss_fused)); gr = jax.jit(jax.grad(loss_ref))
    print(f"fused fwd+bwd: {timeit(gf, x, gm, g1, g2)*1e3:.2f} ms  "
          f"xla fwd+bwd: {timeit(gr, x, gm, g1, g2)*1e3:.2f} ms")
    print(f"in-graph fused: {loop_time(gf, (x, gm, g1, g2))*1e3:.2f} ms  "
          f"in-graph xla: {loop_time(gr, (x, gm, g1, g2))*1e3:.2f} ms")
