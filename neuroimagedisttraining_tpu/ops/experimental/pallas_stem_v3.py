"""Fused stem forward v3 — staged-unfold formulation (no 216-row im2col).

The r2 fused kernel (ops/experimental/pallas_stem_fused.py) ties XLA because its im2col
copies every input element 27x into VMEM scratch (~956 MB of in-VMEM writes
per step, a measured ~4 ms floor). This kernel eliminates that amplification
with a STAGED unfold:

  * only the (dx, phase) taps are materialized — a 24-row slab per input
    d-plane, built once and stored in a 3-slot ring buffer (72 x 704 VMEM
    scratch). Write volume drops ~7x (each input element is copied 3x, not
    27x).
  * the dy taps become THREE static 64-lane-offset slices of the same ring
    (lane slot j holds input row h0+j, so "row h0+j+dy at slot j" is the
    ring shifted by 64*dy lanes);
  * the dz taps become a slot-rotation of the ring: output plane ld reads
    input planes ld..ld+2 living at slots (ld+dz) % 3, handled by three
    precomputed permutations of the (F, 72) lhs (``make_stem_lhs``).

Per output plane the conv is then 3 MXU dots of K=72 accumulated in
registers, plus the same strip/pool/stat skeleton as the r2 kernel
(tail-strip-first d-alignment, static h-groups with overlap-row stat
exclusion, in-kernel w-pooling). Outputs: conv zs (with bias), 3x3x3/s3
max-pool of zs, and per-(batch, strip) sum/sumsq stat partials of zs —
everything ``models/alexnet3d.py::S2DStemStage`` (pool-first branch) needs
from the full-size tensor, in one read of x.

MEASURED r3 STATUS (v5e, in-graph fori-loop timings, RESULTS.md r3):
correct to one bf16 ulp (the 3x K=72 dot split changes f32 accumulation
order vs XLA's conv; 298 of 126M elements differ by exactly one ulp), and
the staged unfold does kill the r2 unfold cost — but the kernel family
still only TIES XLA end to end: this 3-dot form 6.67 ms vs XLA
conv+pool+stats 6.51 ms; the 9-dot variant with dx as a +1 lane offset
(24-row ring, single-write builds) 8.1-8.4 ms; an untransposed
(B,D,H,F,W) zs output variant 7.82 vs 7.52 ms. With the unfold gone the
cost moved to the VPU side (ring builds, per-row zs stores, in-kernel
pool/stat reductions), which XLA's conv emitter gets for free in its
epilogue fusion. Ships UNWIRED, as measured negative-result evidence
that the stem-forward wall is real across formulations.

Fixed to the canonical phased ABCD extents (61x73x8x61 -> 59x71x59,
pool 19x23x19), like the r2 kernel it supersedes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax import lax

B, Dp, Hp, P8, Wp = 8, 61, 73, 8, 61
D, H, W = 59, 71, 59          # conv output extents
PD, PH, PW = 19, 23, 19       # pooled extents
F = 64
SD = 3
NSTRIP = 20                   # s=0 ragged tail at d0=56, s>=1 at 3*(s-1)
HG = 9
H0S = [0, 9, 18, 27, 36, 45, 54, 62]   # static h-group starts (cover 0..70)
NROW = HG + 2                 # input rows per h-group (9 outputs + 2 halo)


def make_stem_lhs(w):
    """(3 rot, 3 dy, F, 72) lhs variants from the (3,3,3,8,F) kernel.

    Column s*24 + dx*8 + p of variant (rot, dy) holds w[dz, dy, dx, p, :]
    with dz = (s - rot) % 3 — the tap that ring slot s supplies when the
    output plane satisfies ld % 3 == rot."""
    f = w.shape[-1]
    out = jnp.zeros((3, 3, f, 72), w.dtype)
    for rot in range(3):
        for dy in range(3):
            for s in range(3):
                dz = (s - rot) % 3
                blk = w[dz, dy].reshape(24, f).T  # (F, 24), rows (dx, p)
                out = out.at[rot, dy, :, s * 24:(s + 1) * 24].set(blk)
    return out


def kernel(x_ref, lhs_ref, bias_ref, ozs_ref, opool_ref, ostat_ref,
           u_ref, z3_ref):
    s = pl.program_id(1)
    # lane validity masks for stats: slot lanes 64j..64j+58 valid
    lane_ids = jax.lax.broadcasted_iota(jnp.int32, (1, 64 * HG), 1)
    slot_pos = lane_ids % 64
    lane_valid = (slot_pos < W).astype(jnp.float32)
    bias_col = bias_ref[:].reshape(F, 1)

    ssum = jnp.zeros((1, F), jnp.float32)
    ssq = jnp.zeros((1, F), jnp.float32)

    for gi, h0 in enumerate(H0S):
        nj = HG  # every group in H0S spans exactly HG output rows

        def build_plane(lp, slot):
            # stage the (dx, p) slabs of input plane lp for rows
            # h0..h0+NROW-1 into ring slot `slot`
            for j in range(NROW):
                row = x_ref[0, lp, h0 + j, :, :]          # (8, Wp)
                for dx in range(3):
                    u_ref[slot * 24 + dx * 8: slot * 24 + dx * 8 + 8,
                          64 * j: 64 * j + W] = row[:, dx:dx + W]

        for lp in range(3):
            build_plane(lp, lp)

        for ld in range(SD):
            if ld > 0:
                build_plane(ld + 2, (ld + 2) % 3)
            rot = ld % 3
            z = None
            for dy in range(3):
                rhs = u_ref[:, 64 * dy: 64 * dy + 64 * HG]   # (72, 576)
                d = lax.dot_general(
                    lhs_ref[rot, dy], rhs, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                z = d if z is None else z + d
            z = z + bias_col
            z3_ref[ld] = z
            # zs rows out
            zt = z.T
            for j in range(nj):
                ozs_ref[0, ld, h0 + j, :, :] = \
                    zt[64 * j:64 * j + W, :].astype(ozs_ref.dtype)
            # stats: skip overlap rows (group 6 ends 62, group 7 starts 62)
            jskip = 1 if gi == len(H0S) - 1 else 0
            row_valid = lane_valid * (lane_ids >= 64 * jskip).astype(
                jnp.float32)
            # tail strip (s==0, d0=56): plane ld=0 (d=56) is re-counted by
            # the last aligned strip -> zero its contribution
            ld_w = jnp.where((s == 0) & (ld == 0), 0.0, 1.0)
            zm = z * row_valid
            ssum = ssum + ld_w * jnp.sum(zm, axis=1, keepdims=True).T
            ssq = ssq + ld_w * jnp.sum(zm * z, axis=1, keepdims=True).T

        # pooling for this h-group: d-max across the 3 planes
        dmax = jnp.maximum(jnp.maximum(z3_ref[0], z3_ref[1]), z3_ref[2])
        off0 = (3 - (h0 % 3)) % 3
        for a in range(3):
            j0 = off0 + 3 * a
            if j0 + 3 > nj or h0 + j0 + 2 > 68:
                continue
            ph = (h0 + j0) // 3
            hmax = jnp.maximum(
                jnp.maximum(dmax[:, 64 * j0:64 * j0 + W],
                            dmax[:, 64 * (j0 + 1):64 * (j0 + 1) + W]),
                dmax[:, 64 * (j0 + 2):64 * (j0 + 2) + W])   # (F, W)
            mt = hmax.T[:57, :]                              # (57, F)
            pw = jnp.max(mt.reshape(PW, 3, F), axis=1)       # (19, F)
            opool_ref[0, 0, ph, :, :] = pw.astype(opool_ref.dtype)

    ostat_ref[0, 0, 0, :] = ssum.reshape(F)
    ostat_ref[0, 0, 1, :] = ssq.reshape(F)


def _d0(s):
    return jnp.where(s == 0, D - SD, 3 * (s - 1))


def fused_stem_fwd_v3(x, lhs, bias):
    """x: (B, 61, 73, 8, 61) phased bf16; lhs: make_stem_lhs(kernel);
    bias: (F,) f32. Returns (zs+bias, maxpool3(zs+bias), stat partials
    [B, NSTRIP, 2, F])."""
    # element-offset index maps (the pl.Element mode of older jax):
    # unblocked indexing with plain int block shapes
    unblocked = pl.Unblocked()
    zs, pooled, stats = pl.pallas_call(
        kernel,
        grid=(B, NSTRIP),
        in_specs=[
            pl.BlockSpec((1, SD + 2, Hp, P8, Wp),
                         lambda b, s: (b, _d0(s), 0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, SD, H, W, F),
                         lambda b, s: (b, _d0(s), 0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec((1, 1, PH, PW, F),
                         lambda b, s: (b, jnp.minimum(_d0(s) // 3, PD - 1),
                                       0, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
            pl.BlockSpec((1, 1, 2, F),
                         lambda b, s: (b, s, 0, 0),
                         memory_space=pltpu.VMEM,
                         indexing_mode=unblocked),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, D, H, W, F), x.dtype),
            jax.ShapeDtypeStruct((B, PD, PH, PW, F), x.dtype),
            jax.ShapeDtypeStruct((B, NSTRIP, 2, F), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((72, 64 * NROW), x.dtype),
            pltpu.VMEM((SD, F, 64 * HG), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(x, lhs.astype(x.dtype), jnp.asarray(bias, jnp.float32))
    return zs, pooled, stats


def ref(x, w, bias):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NDHCW", "DHWIO", "NDHWC"))
    zs = lax.conv_general_dilated(x, w, (1, 1, 1), "VALID",
                                  dimension_numbers=dn)
    zs = zs + bias.astype(zs.dtype)
    import flax.linen as nn
    pooled = nn.max_pool(zs, (3, 3, 3), strides=(3, 3, 3))
    zf = zs.astype(jnp.float32)
    return zs, pooled, (jnp.sum(zf, axis=(1, 2, 3)),
                        jnp.sum(zf * zf, axis=(1, 2, 3)))


if __name__ == "__main__":  # on-chip check harness
    import time

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, Dp, Hp, P8, Wp), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, P8, F),
                          jnp.bfloat16)
    bias = jax.random.normal(jax.random.PRNGKey(2), (F,), jnp.float32) * 0.1
    lhs = make_stem_lhs(w)

    def timeit(f, *args, n=20):
        for _ in range(3):
            out = f(*args)
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        t0 = time.perf_counter()
        for _ in range(n):
            out = f(*args)
        float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
        return (time.perf_counter() - t0) / n

    jf = jax.jit(fused_stem_fwd_v3)
    jr = jax.jit(ref)
    zs, m, st = jf(x, lhs, bias)
    rzs, rm, (rs, rq) = jr(x, w, bias)
    print("zs err:", float(jnp.max(jnp.abs(zs.astype(jnp.float32)
                                           - rzs.astype(jnp.float32)))))
    print("pool err:", float(jnp.max(jnp.abs(m.astype(jnp.float32)
                                             - rm.astype(jnp.float32)))))
    ks = jnp.sum(st[:, :, 0, :], axis=1)
    kq = jnp.sum(st[:, :, 1, :], axis=1)
    print("sum relerr:", float(jnp.max(jnp.abs(ks - rs)
                                       / (jnp.abs(rs) + 1e-3))))
    print("sumsq relerr:", float(jnp.max(jnp.abs(kq - rq)
                                         / (jnp.abs(rq) + 1e-3))))
    print(f"v3: {timeit(jf, x, lhs, bias)*1e3:.2f} ms   "
          f"ref(conv+pool+stats): {timeit(jr, x, w, bias)*1e3:.2f} ms")
