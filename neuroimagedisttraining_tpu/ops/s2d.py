"""Space-to-depth (phase-decomposed) stem for single-channel 3D volumes.

The reference's AlexNet3D stem (``salient_models.py:146``: Conv3d(1, 64,
kernel 5, stride 2)) is the hottest op in ABCD training but maps terribly
onto the MXU: with C_in=1 the im2col contraction is only the 125 kernel
taps, and the stride-2 window gather defeats XLA's tiling (measured ~1.5
TFLOP/s on TPU). The classic TPU fix (MLPerf ResNet stem) is to
phase-decompose the volume ONCE at data-prep time: the 8 stride-2 phase
subgrids become input channels, turning the stem into a stride-1 kernel-3
conv with C_in=8 — mathematically identical outputs, ~2x measured step
speedup, zero per-step layout cost.

Two layout decisions matter on TPU and are encoded here:
  * Phases ride NEXT-TO-MINOR (NDHCW — sample shape (D', H', 8, W')):
    the phase extent of 8 exactly fills the sublane tile and W' stays the
    lane dim. HBM padding is the same ~2.3x as a leading phase axis;
    isolated gather+conv measures ~14% faster than NCDHW (no relayout
    copy), though the fully-fused training round compiles to the same
    speed either way. A TRAILING phase axis would tile-pad 16x and is
    right out.
  * The remapped kernel has 3^3 x 8 = 216 slots of which 125 carry the
    original taps; the other 91 are structurally zero and are kept zero by
    a constant mask at apply time, so the model class is exactly the
    reference's (no extra capacity, SGD/momentum/SNIP all see zero grads
    there).

Tap bijection (per spatial dim, stride 2, kernel 5): original tap t at
output position o reads input 2o + t = phase (t % 2) at offset o + t//2,
so tap t maps to remapped-kernel offset t//2 in {0,1,2} and phase t % 2;
the (offset=2, phase=1) slot is unused.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

STRIDE = 2
KERNEL = 5  # the AlexNet3D stem spec (k5 s2 VALID) — the module defaults
R_KERNEL = 3  # ceil(KERNEL / STRIDE)
N_PHASES = STRIDE ** 3


def r_kernel(kernel: int = KERNEL) -> int:
    """Remapped per-axis kernel extent: ceil(kernel / stride)."""
    return -(-kernel // STRIDE)


def out_extent(size: int, kernel: int = KERNEL, pad: int = 0) -> int:
    """Stride-2 conv output extent with torch-style integer padding
    (floor mode). The default (k5, p0) is the AlexNet3D stem; the 3D
    ResNet stem is (k3, p3) — ``salient_models.py:92``."""
    return (size + 2 * pad - kernel) // STRIDE + 1


def phase_extent(size: int, kernel: int = KERNEL, pad: int = 0) -> int:
    """Phase-subgrid extent needed so the stride-1 ``r_kernel`` conv over
    it yields exactly ``out_extent(size)`` positions."""
    return out_extent(size, kernel, pad) + r_kernel(kernel) - 1


def phase_decompose(x, kernel: int = KERNEL, pad: int = 0) -> jax.Array:
    """(..., D, H, W) single-channel volume -> (..., D', H', 8, W') phased.

    Works on numpy or jax arrays. The conv's own zero padding ``pad`` is
    folded in HERE (left-pad each spatial dim), so the phased conv is
    always VALID; right zero-padding tops every phase subgrid up to the
    exact extent (never reaching any valid conv window). Phase index is
    ``pd*4 + ph*2 + pw`` over the PADDED frame, stored on the
    next-to-minor axis (see module docstring for the layout rationale).
    """
    xp = jnp if isinstance(x, jax.Array) else np
    D, H, W = x.shape[-3:]
    exts = tuple(phase_extent(s, kernel, pad) for s in (D, H, W))
    need = [2 * e for e in exts]  # phase p covers indices p, p+2, ...
    pads = [(0, 0)] * (x.ndim - 3) + [
        (pad, max(0, n - s - pad)) for n, s in zip(need, (D, H, W))
    ]
    x = xp.pad(x, pads)
    phases = [
        x[..., i::2, j::2, k::2][..., :exts[0], :exts[1], :exts[2]]
        for i in (0, 1) for j in (0, 1) for k in (0, 1)
    ]
    return xp.stack(phases, axis=-2)


def remap_stem_kernel(w, kernel: int = None) -> jax.Array:
    """(k,k,k,1,F) reference stem kernel -> (r,r,r,8,F) phased kernel.

    The tap->slot bijection is over the padded frame, so it is independent
    of the conv's padding: tap t lands at slot ``t // 2``, phase
    ``t % 2`` per axis."""
    xp = jnp if isinstance(w, jax.Array) else np
    k = kernel if kernel is not None else w.shape[0]
    r = r_kernel(k)
    F = w.shape[-1]
    w2 = np.zeros((r,) * 3 + (N_PHASES, F), dtype=np.float32)
    w_np = np.asarray(w, dtype=np.float32)
    for td in range(k):
        for th in range(k):
            for tw in range(k):
                ph = (td % 2) * 4 + (th % 2) * 2 + (tw % 2)
                w2[td // 2, th // 2, tw // 2, ph, :] = w_np[td, th, tw, 0, :]
    return xp.asarray(w2, dtype=w.dtype if hasattr(w, "dtype") else None)


def stem_slot_mask(kernel: int = KERNEL) -> np.ndarray:
    """(r,r,r,8,1) 0/1 mask of remapped-kernel slots that carry real taps
    (125/216 for the AlexNet k5 stem, 27/64 for the ResNet k3 stem).

    Derived from the remap itself so the tap->slot bijection has a single
    source of truth."""
    return np.asarray(
        remap_stem_kernel(np.ones((kernel,) * 3 + (1, 1), np.float32)))


def convert_alexnet3d_params(params) -> dict:
    """Map an :class:`AlexNet3D` param tree to :class:`AlexNet3DS2D`.

    The stem kernel is remapped tap-for-tap into the fused
    ``S2DStemStage`` (which also owns the stem GroupNorm's affine pair);
    every other layer transfers unchanged (the two models share all
    post-stem structure, with the remaining GroupNorms renumbered 0..3).
    """
    feats = params["_Features_0"]
    out = {"S2DStemStage_0": {
        "kernel": remap_stem_kernel(feats["Conv3d_0"]["Conv_0"]["kernel"]),
        "bias": feats["Conv3d_0"]["Conv_0"]["bias"],
        "scale": feats["GroupNorm_0"]["scale"],
        "bias_gn": feats["GroupNorm_0"]["bias"],
    }}
    for i in range(1, 5):
        out[f"Conv3d_{i-1}"] = feats[f"Conv3d_{i}"]
        out[f"GroupNorm_{i-1}"] = feats[f"GroupNorm_{i}"]
    out["Dense_0"] = params["Dense_0"]
    out["Dense_1"] = params["Dense_1"]
    return out


def phased_sample_shape(volume: Tuple[int, int, int], kernel: int = KERNEL,
                        pad: int = 0) -> Tuple[int, ...]:
    """Stored per-sample shape for a (D, H, W) volume: (D', H', 8, W')."""
    d, h, w = volume
    return (phase_extent(d, kernel, pad), phase_extent(h, kernel, pad),
            N_PHASES, phase_extent(w, kernel, pad))
