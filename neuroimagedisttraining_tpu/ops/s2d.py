"""Space-to-depth (phase-decomposed) stem for single-channel 3D volumes.

The reference's AlexNet3D stem (``salient_models.py:146``: Conv3d(1, 64,
kernel 5, stride 2)) is the hottest op in ABCD training but maps terribly
onto the MXU: with C_in=1 the im2col contraction is only the 125 kernel
taps, and the stride-2 window gather defeats XLA's tiling (measured ~1.5
TFLOP/s on TPU). The classic TPU fix (MLPerf ResNet stem) is to
phase-decompose the volume ONCE at data-prep time: the 8 stride-2 phase
subgrids become input channels, turning the stem into a stride-1 kernel-3
conv with C_in=8 — mathematically identical outputs, ~2x measured step
speedup, zero per-step layout cost.

Two layout decisions matter on TPU and are encoded here:
  * Phases ride NEXT-TO-MINOR (NDHCW — sample shape (D', H', 8, W')):
    the phase extent of 8 exactly fills the sublane tile and W' stays the
    lane dim. HBM padding is the same ~2.3x as a leading phase axis;
    isolated gather+conv measures ~14% faster than NCDHW (no relayout
    copy), though the fully-fused training round compiles to the same
    speed either way. A TRAILING phase axis would tile-pad 16x and is
    right out.
  * The remapped kernel has 3^3 x 8 = 216 slots of which 125 carry the
    original taps; the other 91 are structurally zero and are kept zero by
    a constant mask at apply time, so the model class is exactly the
    reference's (no extra capacity, SGD/momentum/SNIP all see zero grads
    there).

Tap bijection (per spatial dim, stride 2, kernel 5): original tap t at
output position o reads input 2o + t = phase (t % 2) at offset o + t//2,
so tap t maps to remapped-kernel offset t//2 in {0,1,2} and phase t % 2;
the (offset=2, phase=1) slot is unused.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

STRIDE = 2
KERNEL = 5
R_KERNEL = 3  # ceil(KERNEL / STRIDE)
N_PHASES = STRIDE ** 3


def out_extent(size: int) -> int:
    """VALID stride-2 kernel-5 output extent (matches torch floor mode)."""
    return (size - KERNEL) // STRIDE + 1


def phase_extent(size: int) -> int:
    """Phase-subgrid extent needed so the stride-1 kernel-3 conv over it
    yields exactly ``out_extent(size)`` positions."""
    return out_extent(size) + R_KERNEL - 1


def phase_decompose(x) -> jax.Array:
    """(..., D, H, W) single-channel volume -> (..., D', H', 8, W') phased.

    Works on numpy or jax arrays; pads each spatial dim with zeros so every
    phase subgrid has the exact extent (padding never reaches any valid
    conv window). Phase index is ``pd*4 + ph*2 + pw``, stored on the
    next-to-minor axis (see module docstring for the layout rationale).
    """
    xp = jnp if isinstance(x, jax.Array) else np
    D, H, W = x.shape[-3:]
    exts = (phase_extent(D), phase_extent(H), phase_extent(W))
    need = [2 * e for e in exts]  # phase p covers indices p, p+2, ...
    pads = [(0, 0)] * (x.ndim - 3) + [
        (0, max(0, n - s)) for n, s in zip(need, (D, H, W))
    ]
    x = xp.pad(x, pads)
    phases = [
        x[..., i::2, j::2, k::2][..., :exts[0], :exts[1], :exts[2]]
        for i in (0, 1) for j in (0, 1) for k in (0, 1)
    ]
    return xp.stack(phases, axis=-2)


def remap_stem_kernel(w) -> jax.Array:
    """(5,5,5,1,F) reference stem kernel -> (3,3,3,8,F) phased kernel."""
    xp = jnp if isinstance(w, jax.Array) else np
    F = w.shape[-1]
    w2 = np.zeros((R_KERNEL,) * 3 + (N_PHASES, F), dtype=np.float32)
    w_np = np.asarray(w, dtype=np.float32)
    for td in range(KERNEL):
        for th in range(KERNEL):
            for tw in range(KERNEL):
                ph = (td % 2) * 4 + (th % 2) * 2 + (tw % 2)
                w2[td // 2, th // 2, tw // 2, ph, :] = w_np[td, th, tw, 0, :]
    return xp.asarray(w2, dtype=w.dtype if hasattr(w, "dtype") else None)


def stem_slot_mask() -> np.ndarray:
    """(3,3,3,8,1) 0/1 mask of remapped-kernel slots that carry real taps.

    Derived from the remap itself so the tap->slot bijection has a single
    source of truth."""
    return np.asarray(
        remap_stem_kernel(np.ones((KERNEL,) * 3 + (1, 1), np.float32)))


def convert_alexnet3d_params(params) -> dict:
    """Map an :class:`AlexNet3D` param tree to :class:`AlexNet3DS2D`.

    The stem kernel is remapped tap-for-tap into the fused
    ``S2DStemStage`` (which also owns the stem GroupNorm's affine pair);
    every other layer transfers unchanged (the two models share all
    post-stem structure, with the remaining GroupNorms renumbered 0..3).
    """
    feats = params["_Features_0"]
    out = {"S2DStemStage_0": {
        "kernel": remap_stem_kernel(feats["Conv3d_0"]["Conv_0"]["kernel"]),
        "bias": feats["Conv3d_0"]["Conv_0"]["bias"],
        "scale": feats["GroupNorm_0"]["scale"],
        "bias_gn": feats["GroupNorm_0"]["bias"],
    }}
    for i in range(1, 5):
        out[f"Conv3d_{i-1}"] = feats[f"Conv3d_{i}"]
        out[f"GroupNorm_{i-1}"] = feats[f"GroupNorm_{i}"]
    out["Dense_0"] = params["Dense_0"]
    out["Dense_1"] = params["Dense_1"]
    return out


def phased_sample_shape(volume: Tuple[int, int, int]) -> Tuple[int, ...]:
    """Stored per-sample shape for a (D, H, W) volume: (D', H', 8, W')."""
    d, h, w = volume
    return (phase_extent(d), phase_extent(h), N_PHASES, phase_extent(w))
