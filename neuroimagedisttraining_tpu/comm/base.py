"""Backend-neutral comm manager ABC + Observer.

Rebuild of ``fedml_core/distributed/communication/base_com_manager.py:7-27``
and ``observer.py:4-7``.
"""
from __future__ import annotations

import abc
import logging
import queue
import threading
from typing import List, Optional

from .message import Message

logger = logging.getLogger(__name__)


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: str, msg: Message) -> None:
        ...


class CommCounters:
    """Per-manager transport accounting: serialized bytes and message
    counts actually sent/received over the wire (the measured side of
    obs/comm.py's analytical wire-cost model). Updated by every backend
    at its send/receive sites; ``snapshot()`` is what a cross-silo
    round loop folds into its telemetry.

    Thread-safe: the receive pump runs on its own thread while round
    loops send from the caller's thread, so the += pairs are guarded —
    an unsynchronized bytes+=/messages+= pair can tear (lost updates,
    or a snapshot observing bytes from a send whose message count
    hasn't landed)."""

    __slots__ = ("bytes_sent", "bytes_received", "messages_sent",
                 "messages_received", "messages_retried", "_lock")

    def __init__(self):
        self.bytes_sent = 0
        self.bytes_received = 0
        self.messages_sent = 0
        self.messages_received = 0
        # send attempts that failed transiently and were re-issued by
        # fed.protocol.send_with_retry — the degradation signal the fed
        # obs fold surfaces alongside the byte counters
        self.messages_retried = 0
        self._lock = threading.Lock()

    def note_sent(self, nbytes: int) -> None:
        with self._lock:
            self.bytes_sent += int(nbytes)
            self.messages_sent += 1

    def note_received(self, nbytes: int) -> None:
        with self._lock:
            self.bytes_received += int(nbytes)
            self.messages_received += 1

    def note_retry(self) -> None:
        with self._lock:
            self.messages_retried += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"comm_bytes_sent": self.bytes_sent,
                    "comm_bytes_received": self.bytes_received,
                    "comm_messages_sent": self.messages_sent,
                    "comm_messages_received": self.messages_received,
                    "comm_messages_retried": self.messages_retried}


class BaseCommunicationManager(abc.ABC):
    """send/receive + observer dispatch contract."""

    def __init__(self):
        self._observers: List[Observer] = []
        self.counters = CommCounters()

    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Run the receive loop, dispatching to observers until stopped."""

    @abc.abstractmethod
    def stop_receive_message(self) -> None:
        ...

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.type, msg)
            except Exception:
                # a failing handler must not kill the rank's receive pump —
                # log with traceback and keep serving later messages
                logger.exception(
                    "handler for %r raised; receive loop continues", msg.type)


class PollingReceiveLoopMixin:
    """``handle_receive_message``/``stop_receive_message`` over a blocking
    ``self.recv(timeout_s)`` — the receive pump every backend shares."""

    def _init_pump(self) -> None:
        self._stop = threading.Event()

    def handle_receive_message(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self.recv(timeout_s=0.1)
            except OSError:
                # covers ConnectionError from the inbox mixin and the plain
                # OSError the native TCP backend raises on transport failure
                logger.error("transport lost; receive pump exiting")
                return
            if msg is not None:
                self._notify(msg)

    def stop_receive_message(self) -> None:
        self._stop.set()


class QueueInboxMixin(PollingReceiveLoopMixin):
    """Receive pump fed by an inbound bytes queue (``self._inbox.put(raw)``
    from the backend's reader thread / RPC servicer).

    ``_fail_inbox()`` marks the transport dead: once the queue drains,
    ``recv`` raises ``ConnectionError`` instead of blocking forever.
    """

    def _init_pump(self) -> None:
        super()._init_pump()
        self._inbox: "queue.Queue[bytes]" = queue.Queue()
        self._lost = threading.Event()

    def _fail_inbox(self) -> None:
        self._lost.set()

    def recv(self, timeout_s: float = -1.0) -> Optional[Message]:
        """Blocking receive of one message (None on timeout); raises
        ``ConnectionError`` once the transport is lost and the queue is
        drained."""
        block_forever = timeout_s < 0
        while True:
            try:
                payload = self._inbox.get(
                    timeout=0.5 if block_forever else timeout_s)
            except queue.Empty:
                if self._lost.is_set():
                    # the reader may have enqueued a final message between
                    # our timeout and the _lost check — drain before failing
                    try:
                        payload = self._inbox.get_nowait()
                    except queue.Empty:
                        raise ConnectionError("transport lost") from None
                    self.counters.note_received(len(payload))
                    return Message.from_bytes(payload)
                if block_forever:
                    continue
                return None
            self.counters.note_received(len(payload))
            return Message.from_bytes(payload)
