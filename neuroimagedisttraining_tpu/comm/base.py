"""Backend-neutral comm manager ABC + Observer.

Rebuild of ``fedml_core/distributed/communication/base_com_manager.py:7-27``
and ``observer.py:4-7``.
"""
from __future__ import annotations

import abc
import logging
from typing import List

from .message import Message

logger = logging.getLogger(__name__)


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: str, msg: Message) -> None:
        ...


class BaseCommunicationManager(abc.ABC):
    """send/receive + observer dispatch contract."""

    def __init__(self):
        self._observers: List[Observer] = []

    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Run the receive loop, dispatching to observers until stopped."""

    @abc.abstractmethod
    def stop_receive_message(self) -> None:
        ...

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        self._observers.remove(observer)

    def _notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            try:
                obs.receive_message(msg.type, msg)
            except Exception:
                # a failing handler must not kill the rank's receive pump —
                # log with traceback and keep serving later messages
                logger.exception(
                    "handler for %r raised; receive loop continues", msg.type)
