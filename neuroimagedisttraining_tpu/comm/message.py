"""Typed key-value Message with JSON and binary-pytree codecs.

Rebuild of ``fedml_core/distributed/communication/message.py:5-74`` (typed
kv message with sender/receiver ids + JSON codec). The reference ships model
weights as pickled torch ``state_dict``s (MPI) or JSON floats (gRPC/MQTT);
here tensor payloads use a zero-copy binary framing — a JSON header with the
pytree structure + dtype/shape table, followed by the raw leaf bytes — so a
cross-silo round never pickles and never base64s.
"""
from __future__ import annotations

import json
import struct as _struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"NIDT"


class Message:
    # op-type constants (message.py:12-15)
    MSG_OP_SEND = "send"
    MSG_OP_RECEIVE = "receive"
    MSG_OP_BROADCAST = "broadcast"
    MSG_OP_REDUCE = "reduce"

    # framework message types (the cross-silo FedAvg protocol)
    MSG_TYPE_INIT = "init_global_model"
    MSG_TYPE_LOCAL_UPDATE = "client_local_update"
    MSG_TYPE_GLOBAL_MODEL = "server_global_model"
    MSG_TYPE_FINISH = "finish"

    ARG_TYPE = "msg_type"
    ARG_SENDER = "sender"
    ARG_RECEIVER = "receiver"

    def __init__(self, msg_type: str = "default", sender_id: int = 0,
                 receiver_id: int = 0):
        self.params: Dict[str, Any] = {
            self.ARG_TYPE: msg_type,
            self.ARG_SENDER: sender_id,
            self.ARG_RECEIVER: receiver_id,
        }
        self.tensors: Dict[str, Any] = {}  # name -> pytree of np/jax arrays

    # -- kv interface (message.py:30-52) --------------------------------------
    def add(self, key: str, value: Any) -> None:
        self.params[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self.params.get(key, default)

    def add_tensor(self, key: str, tree: Any) -> None:
        self.tensors[key] = tree

    def get_tensor(self, key: str) -> Any:
        return self.tensors[key]

    @property
    def type(self) -> str:
        return self.params[self.ARG_TYPE]

    @property
    def sender_id(self) -> int:
        return self.params[self.ARG_SENDER]

    @property
    def receiver_id(self) -> int:
        return self.params[self.ARG_RECEIVER]

    # -- JSON codec (control-plane only) --------------------------------------
    def to_json(self) -> str:
        if self.tensors:
            raise ValueError("tensor payloads need to_bytes(), not JSON")
        return json.dumps(self.params)

    @classmethod
    def from_json(cls, payload: str) -> "Message":
        m = cls()
        m.params = json.loads(payload)
        return m

    # -- binary codec (data plane) --------------------------------------------
    def to_bytes(self) -> bytes:
        leaves_blob: List[bytes] = []
        tensor_index: Dict[str, Any] = {}
        offset = 0
        for key, tree in self.tensors.items():
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(tree)
            entries = []
            for leaf in leaves:
                arr = np.asarray(leaf)
                raw = np.ascontiguousarray(arr).tobytes()
                entries.append({
                    "dtype": arr.dtype.str,
                    "shape": list(arr.shape),
                    "offset": offset,
                    "nbytes": len(raw),
                })
                leaves_blob.append(raw)
                offset += len(raw)
            tensor_index[key] = {
                "treedef": _treedef_to_str(treedef),
                "leaves": entries,
            }
        header = json.dumps(
            {"params": self.params, "tensors": tensor_index}).encode()
        return b"".join([MAGIC, _struct.pack("<I", len(header)), header,
                         *leaves_blob])

    @classmethod
    def from_bytes(cls, payload: bytes) -> "Message":
        if payload[:4] != MAGIC:
            raise ValueError("bad message framing")
        (hlen,) = _struct.unpack("<I", payload[4:8])
        header = json.loads(payload[8:8 + hlen].decode())
        m = cls()
        m.params = header["params"]
        base = 8 + hlen
        for key, spec in header["tensors"].items():
            leaves = []
            for e in spec["leaves"]:
                start = base + e["offset"]
                arr = np.frombuffer(
                    payload, dtype=np.dtype(e["dtype"]),
                    count=int(np.prod(e["shape"])) if e["shape"] else 1,
                    offset=start,
                ).reshape(e["shape"])
                leaves.append(arr)
            m.tensors[key] = _treedef_from_str(spec["treedef"], leaves)
        return m


def _treedef_to_str(treedef) -> str:
    """Serialize a pytree structure. Dict/list/tuple/None nests cover every
    params/mask pytree this framework ships."""
    import jax

    dummy = jax.tree_util.tree_unflatten(
        treedef, list(range(treedef.num_leaves)))
    return json.dumps(_encode_structure(dummy))


def _encode_structure(node) -> Any:
    if isinstance(node, dict):
        # keys ride as [key, value] pairs with the key's type preserved —
        # a bare JSON object would coerce int keys (client-id maps) to str
        return {"__d": [[_encode_key(k), _encode_structure(v)]
                        for k, v in node.items()]}
    if isinstance(node, (list, tuple)):
        tag = "__l" if isinstance(node, list) else "__t"
        return {tag: [_encode_structure(v) for v in node]}
    if node is None:
        return {"__n": True}
    return int(node)  # leaf marker: its flatten index


def _encode_key(k) -> Any:
    if isinstance(k, str):
        return k
    if isinstance(k, bool) or not isinstance(k, int):
        raise TypeError(f"unsupported pytree dict key type: {type(k)!r}")
    return {"__i": k}


def _decode_key(k) -> Any:
    return k["__i"] if isinstance(k, dict) else k


def _treedef_from_str(spec: str, leaves: List[Any]) -> Any:
    return _decode_structure(json.loads(spec), leaves)


def _decode_structure(node, leaves: List[Any]) -> Any:
    if isinstance(node, dict):
        if "__d" in node:
            return {_decode_key(k): _decode_structure(v, leaves)
                    for k, v in node["__d"]}
        if "__l" in node:
            return [_decode_structure(v, leaves) for v in node["__l"]]
        if "__t" in node:
            return tuple(_decode_structure(v, leaves) for v in node["__t"])
        if "__n" in node:
            return None
    return leaves[int(node)]
