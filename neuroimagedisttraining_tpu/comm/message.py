"""Typed key-value Message with JSON and binary-pytree codecs.

Rebuild of ``fedml_core/distributed/communication/message.py:5-74`` (typed
kv message with sender/receiver ids + JSON codec). The reference ships model
weights as pickled torch ``state_dict``s (MPI) or JSON floats (gRPC/MQTT);
here tensor payloads use a zero-copy binary framing — a JSON header with the
pytree structure + dtype/shape table, followed by the raw leaf bytes — so a
cross-silo round never pickles and never base64s.

**The in-band header contract** (what the telemetry planes ride on):
``params`` is an open key-value namespace — a decoder reads the keys
it knows and ignores the rest, so optional control-plane headers
travel on existing frames without a protocol version bump. Two
families use it today, both with the same gating rule (inject only
when the feature's object is non-None, so feature-off is byte-inert
on every wire): the ``xt_*`` trace-context headers (``obs/xtrace.py``)
and the ``hb_*`` heartbeat gauge snapshots (``obs/live.py``). Header
writers must keep values JSON-safe scalars/dicts and prefix their
keys (``xt_``, ``hb_``) — the namespace is shared with the protocol's
own routing and payload metadata.
"""
from __future__ import annotations

import json
import logging
import struct as _struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAGIC = b"NIDT"

#: serialized-size accounting hooks: every ``Message.to_bytes`` call
#: invokes each with ``(msg_type, nbytes)`` — the obs layer's tap for
#: measured wire bytes (obs/comm.py registers one per ObsSession when
#: comm telemetry is on). Hooks must never kill a send: exceptions are
#: logged and dropped.
_NBYTES_HOOKS: List[Callable[[str, int], None]] = []


def add_nbytes_hook(hook: Callable[[str, int], None]
                    ) -> Callable[[str, int], None]:
    _NBYTES_HOOKS.append(hook)
    return hook


def remove_nbytes_hook(hook: Callable[[str, int], None]) -> None:
    try:
        _NBYTES_HOOKS.remove(hook)
    except ValueError:
        pass  # already removed (idempotent teardown)


class _SparseLeaf:
    """Mask-sparse array: nonzero values + a packed 1-bit/element bitmap."""

    __slots__ = ("values", "bitmap", "shape", "dtype")

    def __init__(self, values: np.ndarray, bitmap: np.ndarray,
                 shape: Tuple[int, ...], dtype):
        self.values = values
        self.bitmap = bitmap
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    @classmethod
    def from_dense(cls, leaf, mask) -> "_SparseLeaf":
        arr = np.asarray(leaf)
        m = np.asarray(mask).reshape(-1) != 0
        values = np.ascontiguousarray(arr.reshape(-1)[m])
        return cls(values, np.packbits(m), arr.shape, arr.dtype)

    def to_dense(self) -> np.ndarray:
        n = int(np.prod(self.shape)) if self.shape else 1
        m = np.unpackbits(self.bitmap, count=n).astype(bool)
        out = np.zeros(n, self.dtype)
        out[m] = self.values
        return out.reshape(self.shape)


def _is_msg_leaf(x) -> bool:
    return isinstance(x, _SparseLeaf)


class Message:
    # op-type constants (message.py:12-15)
    MSG_OP_SEND = "send"
    MSG_OP_RECEIVE = "receive"
    MSG_OP_BROADCAST = "broadcast"
    MSG_OP_REDUCE = "reduce"

    # framework message types (the cross-silo FedAvg protocol)
    MSG_TYPE_INIT = "init_global_model"
    MSG_TYPE_LOCAL_UPDATE = "client_local_update"
    MSG_TYPE_GLOBAL_MODEL = "server_global_model"
    MSG_TYPE_FINISH = "finish"

    ARG_TYPE = "msg_type"
    ARG_SENDER = "sender"
    ARG_RECEIVER = "receiver"

    def __init__(self, msg_type: str = "default", sender_id: int = 0,
                 receiver_id: int = 0):
        self.params: Dict[str, Any] = {
            self.ARG_TYPE: msg_type,
            self.ARG_SENDER: sender_id,
            self.ARG_RECEIVER: receiver_id,
        }
        self.tensors: Dict[str, Any] = {}  # name -> pytree of np/jax arrays
        #: serialized size of the last ``to_bytes`` call (None until one)
        self.nbytes: Optional[int] = None

    # -- kv interface (message.py:30-52) --------------------------------------
    def add(self, key: str, value: Any) -> None:
        self.params[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self.params.get(key, default)

    def add_tensor(self, key: str, tree: Any) -> None:
        self.tensors[key] = tree

    def add_masked_tensor(self, key: str, tree: Any, mask: Any) -> None:
        """Attach a sparse pytree: only values where ``mask != 0`` ride the
        wire, plus a 1-bit/element bitmap.

        This is the transport SalientGrads-style sparse FL actually wants:
        the reference *counts* nonzero comm params
        (``model_trainer.py:49-53``) but still ships dense state_dicts;
        here a dense_ratio-0.5 bf16 model costs ~2.5 bytes/param instead
        of 4 (0.7 at ratio 0.05). ``get_tensor`` densifies transparently
        (zeros off-mask).
        """
        import jax

        self.tensors[key] = jax.tree_util.tree_map(
            lambda leaf, m: _SparseLeaf.from_dense(leaf, m), tree, mask)

    def get_tensor(self, key: str) -> Any:
        import jax

        tree = self.tensors[key]
        return jax.tree_util.tree_map(
            lambda leaf: leaf.to_dense()
            if isinstance(leaf, _SparseLeaf) else leaf,
            tree, is_leaf=_is_msg_leaf)

    def get_tensor_mask(self, key: str) -> Any:
        """0/1 float mask tree of a (sparse) tensor entry — the bitmap
        rides free with every sparse payload, so receivers recover the
        sparsity pattern without a separate mask message. Dense leaves
        yield all-ones."""
        import jax

        def leaf_mask(leaf):
            if isinstance(leaf, _SparseLeaf):
                n = int(np.prod(leaf.shape)) if leaf.shape else 1
                return np.unpackbits(leaf.bitmap, count=n).astype(
                    np.float32).reshape(leaf.shape)
            return np.ones(np.asarray(leaf).shape, np.float32)

        return jax.tree_util.tree_map(
            leaf_mask, self.tensors[key], is_leaf=_is_msg_leaf)

    @property
    def type(self) -> str:
        return self.params[self.ARG_TYPE]

    @property
    def sender_id(self) -> int:
        return self.params[self.ARG_SENDER]

    @property
    def receiver_id(self) -> int:
        return self.params[self.ARG_RECEIVER]

    # -- JSON codec (control-plane only) --------------------------------------
    def to_json(self) -> str:
        if self.tensors:
            raise ValueError("tensor payloads need to_bytes(), not JSON")
        payload = json.dumps(self.params)
        # control-plane messages are wire bytes too: without this stamp
        # the comm_msg_bytes counters silently undercount every JSON
        # frame — and the trace-context header overhead rides free
        self.nbytes = len(payload.encode())
        for hook in list(_NBYTES_HOOKS):
            try:
                hook(self.type, self.nbytes)
            except Exception:
                logger.debug("message nbytes hook failed", exc_info=True)
        return payload

    @classmethod
    def from_json(cls, payload: str) -> "Message":
        m = cls()
        m.params = json.loads(payload)
        return m

    # -- binary codec (data plane) --------------------------------------------
    def to_bytes(self) -> bytes:
        leaves_blob: List[bytes] = []
        tensor_index: Dict[str, Any] = {}
        offset = 0
        for key, tree in self.tensors.items():
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(
                tree, is_leaf=_is_msg_leaf)
            entries = []
            for leaf in leaves:
                if isinstance(leaf, _SparseLeaf):
                    vraw = leaf.values.tobytes()
                    braw = leaf.bitmap.tobytes()
                    entries.append({
                        "kind": "sparse",
                        "dtype": leaf.dtype.str,
                        "shape": list(leaf.shape),
                        "offset": offset,
                        "nbytes": len(vraw),
                        "bitmap_nbytes": len(braw),
                    })
                    leaves_blob.append(vraw)
                    leaves_blob.append(braw)
                    offset += len(vraw) + len(braw)
                    continue
                arr = np.asarray(leaf)
                raw = np.ascontiguousarray(arr).tobytes()
                entries.append({
                    "dtype": arr.dtype.str,
                    "shape": list(arr.shape),
                    "offset": offset,
                    "nbytes": len(raw),
                })
                leaves_blob.append(raw)
                offset += len(raw)
            tensor_index[key] = {
                "treedef": _treedef_to_str(treedef),
                "leaves": entries,
            }
        header = json.dumps(
            {"params": self.params, "tensors": tensor_index}).encode()
        out = b"".join([MAGIC, _struct.pack("<I", len(header)), header,
                        *leaves_blob])
        # serialized-size accounting: the exact bytes a backend ships.
        # ``nbytes`` stays on the message for callers that hold it; the
        # module hooks feed the obs registry's measured-bytes counters
        # (obs/comm.py — validated against the analytical wire model by
        # tests/test_comm_model_properties.py)
        self.nbytes = len(out)
        for hook in list(_NBYTES_HOOKS):
            try:
                hook(self.type, self.nbytes)
            except Exception:
                logger.debug("message nbytes hook failed", exc_info=True)
        return out

    @classmethod
    def from_bytes(cls, payload: bytes) -> "Message":
        if payload[:4] != MAGIC:
            raise ValueError("bad message framing")
        (hlen,) = _struct.unpack("<I", payload[4:8])
        header = json.loads(payload[8:8 + hlen].decode())
        m = cls()
        m.params = header["params"]
        base = 8 + hlen
        for key, spec in header["tensors"].items():
            leaves = []
            for e in spec["leaves"]:
                start = base + e["offset"]
                dtype = np.dtype(e["dtype"])
                if e.get("kind") == "sparse":
                    nnz = e["nbytes"] // dtype.itemsize
                    values = np.frombuffer(
                        payload, dtype=dtype, count=nnz, offset=start)
                    bitmap = np.frombuffer(
                        payload, dtype=np.uint8, count=e["bitmap_nbytes"],
                        offset=start + e["nbytes"])
                    leaves.append(_SparseLeaf(
                        values, bitmap, tuple(e["shape"]), dtype))
                    continue
                arr = np.frombuffer(
                    payload, dtype=dtype,
                    count=int(np.prod(e["shape"])) if e["shape"] else 1,
                    offset=start,
                ).reshape(e["shape"])
                leaves.append(arr)
            m.tensors[key] = _treedef_from_str(spec["treedef"], leaves)
        return m


def _treedef_to_str(treedef) -> str:
    """Serialize a pytree structure. Dict/list/tuple/None nests cover every
    params/mask pytree this framework ships."""
    import jax

    dummy = jax.tree_util.tree_unflatten(
        treedef, list(range(treedef.num_leaves)))
    return json.dumps(_encode_structure(dummy))


def _encode_structure(node) -> Any:
    if isinstance(node, dict):
        # keys ride as [key, value] pairs with the key's type preserved —
        # a bare JSON object would coerce int keys (client-id maps) to str
        return {"__d": [[_encode_key(k), _encode_structure(v)]
                        for k, v in node.items()]}
    if isinstance(node, (list, tuple)):
        tag = "__l" if isinstance(node, list) else "__t"
        return {tag: [_encode_structure(v) for v in node]}
    if node is None:
        return {"__n": True}
    return int(node)  # leaf marker: its flatten index


def _encode_key(k) -> Any:
    if isinstance(k, str):
        return k
    if isinstance(k, bool) or not isinstance(k, int):
        raise TypeError(f"unsupported pytree dict key type: {type(k)!r}")
    return {"__i": k}


def _decode_key(k) -> Any:
    return k["__i"] if isinstance(k, dict) else k


def _treedef_from_str(spec: str, leaves: List[Any]) -> Any:
    return _decode_structure(json.loads(spec), leaves)


def _decode_structure(node, leaves: List[Any]) -> Any:
    if isinstance(node, dict):
        if "__d" in node:
            return {_decode_key(k): _decode_structure(v, leaves)
                    for k, v in node["__d"]}
        if "__l" in node:
            return [_decode_structure(v, leaves) for v in node["__l"]]
        if "__t" in node:
            return tuple(_decode_structure(v, leaves) for v in node["__t"])
        if "__n" in node:
            return None
    return leaves[int(node)]
