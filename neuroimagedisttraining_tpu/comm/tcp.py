"""Native TCP comm backend: ctypes binding over ``native/comm/tcp_comm.cpp``.

The cross-silo transport (real-hospital deployment path, SURVEY §5.8) —
the TPU-native replacement for the reference's mpi4py / gRPC / MQTT
backends. The C++ library owns sockets, listener/reader threads, and the
blocking receive queue; Python only frames Messages.

The shared library is built on demand with ``g++ -O2 -shared`` into
``neuroimagedisttraining_tpu/comm/_native/`` (no pip/cmake dependency).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

from .base import BaseCommunicationManager, PollingReceiveLoopMixin
from .message import Message

logger = logging.getLogger(__name__)

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "comm", "tcp_comm.cpp",
)
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
_LIB_PATH = os.path.join(_BUILD_DIR, "libtcpcomm.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def build_native(force: bool = False) -> str:
    """Compile the C++ transport if needed; returns the .so path."""
    with _lib_lock:
        if not force and os.path.exists(_LIB_PATH):
            # deployments may ship only the prebuilt .so without native/
            if not os.path.exists(_SRC) or \
                    os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC):
                return _LIB_PATH
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # compile to a per-process temp path, then rename atomically —
        # concurrent ranks on one host must never load a half-written .so
        tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
               _SRC, "-o", tmp]
        logger.info("building native comm: %s", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp, _LIB_PATH)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return _LIB_PATH


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    path = build_native()
    lib = ctypes.CDLL(path)
    lib.comm_init.restype = ctypes.c_void_p
    lib.comm_init.argtypes = [ctypes.c_int, ctypes.c_int,
                              ctypes.POINTER(ctypes.c_char_p),
                              ctypes.POINTER(ctypes.c_int)]
    lib.comm_send.restype = ctypes.c_int
    # buf as c_char_p: ctypes passes the bytes object's buffer directly
    # (the C side only reads), avoiding a full payload copy per send
    lib.comm_send.argtypes = [ctypes.c_void_p, ctypes.c_int,
                              ctypes.c_char_p, ctypes.c_uint32]
    lib.comm_recv.restype = ctypes.c_int
    lib.comm_recv.argtypes = [ctypes.c_void_p,
                              ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                              ctypes.POINTER(ctypes.c_uint32),
                              ctypes.c_double]
    lib.comm_free_buf.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.comm_pending.restype = ctypes.c_int
    lib.comm_pending.argtypes = [ctypes.c_void_p]
    lib.comm_finalize.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError, FileNotFoundError):
        return False


class TcpCommManager(PollingReceiveLoopMixin, BaseCommunicationManager):
    """One rank of a TCP mesh. ``endpoints`` = [(host, port)] * world_size;
    rank ``i`` listens on endpoints[i] (gRPC backend's port-per-rank scheme,
    ``grpc_comm_manager.py:20-40``, minus the JSON and the broken imports)."""

    def __init__(self, rank: int, endpoints: Sequence[Tuple[str, int]]):
        super().__init__()
        self.rank = rank
        self.world_size = len(endpoints)
        self._lib = _load()
        hosts = (ctypes.c_char_p * self.world_size)(
            *[h.encode() for h, _ in endpoints])
        ports = (ctypes.c_int * self.world_size)(
            *[p for _, p in endpoints])
        self._h = self._lib.comm_init(rank, self.world_size, hosts, ports)
        if not self._h:
            raise OSError(
                f"comm_init failed (rank {rank}, endpoint "
                f"{endpoints[rank]}): port in use?")
        self._init_pump()

    def send_message(self, msg: Message) -> None:
        payload = msg.to_bytes()
        if len(payload) >= 2 ** 32:
            # the wire frame is u32-length; ctypes would silently truncate
            raise ValueError(
                f"message payload {len(payload)} bytes exceeds the 4 GiB "
                "frame limit — shard the pytree across messages")
        rc = self._lib.comm_send(self._h, msg.receiver_id, payload,
                                 len(payload))
        if rc != 0:
            raise OSError(f"comm_send to rank {msg.receiver_id} failed ({rc})")
        self.counters.note_sent(len(payload))

    def recv(self, timeout_s: float = -1.0) -> Optional[Message]:
        """Blocking receive of one message (None on timeout)."""
        buf = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_uint32()
        rc = self._lib.comm_recv(self._h, ctypes.byref(buf),
                                 ctypes.byref(length), timeout_s)
        if rc == 1:
            return None
        if rc != 0:
            raise OSError(f"comm_recv failed ({rc})")
        try:
            payload = ctypes.string_at(buf, length.value)
        finally:
            self._lib.comm_free_buf(buf)
        self.counters.note_received(len(payload))
        return Message.from_bytes(payload)

    # handle_receive_message/stop_receive_message from PollingReceiveLoopMixin

    def finalize(self) -> None:
        self.stop_receive_message()
        if self._h:
            self._lib.comm_finalize(self._h)
            self._h = None

    def __del__(self):
        try:
            self.finalize()
        except Exception:
            pass
