"""In-process comm backend: per-rank queues in shared memory.

The simulation/test backend — plays the role the reference's MPI backend
plays for its (orphaned) multi-process path, without leaving the process.
Serialization still goes through the binary Message codec so tests exercise
the exact bytes the TCP backend ships.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List

from .base import BaseCommunicationManager
from .message import Message


class LocalRouter:
    """Shared mailbox set for N in-process ranks."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.queues: List[queue.Queue] = [
            queue.Queue() for _ in range(world_size)]

    def manager(self, rank: int) -> "LocalCommManager":
        return LocalCommManager(self, rank)


class LocalCommManager(BaseCommunicationManager):
    def __init__(self, router: LocalRouter, rank: int):
        super().__init__()
        self.router = router
        self.rank = rank
        self._stop = threading.Event()

    def send_message(self, msg: Message) -> None:
        payload = msg.to_bytes()  # same wire format as the TCP backend
        self.counters.note_sent(len(payload))
        self.router.queues[msg.receiver_id].put(payload)

    def handle_receive_message(self) -> None:
        while not self._stop.is_set():
            try:
                payload = self.router.queues[self.rank].get(timeout=0.1)
            except queue.Empty:
                continue
            self.counters.note_received(len(payload))
            self._notify(Message.from_bytes(payload))

    def stop_receive_message(self) -> None:
        self._stop.set()
