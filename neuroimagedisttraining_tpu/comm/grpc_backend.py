"""gRPC comm backend: unary RPC mesh over the ``comm_manager.proto`` IDL.

Working rebuild of the reference's gRPC backend
(``fedml_core/distributed/communication/gRPC/grpc_comm_manager.py:20-106``,
``grpc_server.py:9-40``), which is un-importable as shipped (dangling
``fedml_api.distributed.*`` imports at ``grpc_comm_manager.py:17-18``).
Kept from the reference's design: every rank runs an insecure server
(port ``50000 + rank`` when only hosts are given), send = open a channel
to the receiver from an endpoint table and issue one unary
``SendMessage(CommRequest)``, received payloads land in a queue drained
by ``handle_receive_message``. Changed: payloads are the binary
``Message`` framing (raw bytes field) instead of JSON, the 100 MB message
cap is raised to 1 GiB, and channels are cached per receiver instead of
re-dialed per send.

The protobuf stub is generated on demand from
``native/comm/comm_manager.proto`` with ``protoc`` (regen script
``native/comm/generate_grpc.sh``); the service is registered through
``grpc.GenericRpcHandler`` so no grpcio-tools protoc plugin is needed.
"""
from __future__ import annotations

import logging
import os
import subprocess
import threading
from concurrent import futures
from typing import Sequence, Tuple

from .base import BaseCommunicationManager, QueueInboxMixin
from .message import Message

logger = logging.getLogger(__name__)

GRPC_BASE_PORT = 50000  # grpc_comm_manager.py: PORT_BASE = 50000
MAX_MESSAGE_BYTES = 1 << 30
_SERVICE_METHOD = "/nidt.comm.CommManager/SendMessage"

_PROTO_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "comm",
)
_GEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_generated")

_stub_lock = threading.Lock()
_pb2 = None


def _user_cache_gen_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "neuroimagedisttraining_tpu", "_generated")


def _generate_into(gen_dir: str, src: str) -> None:
    os.makedirs(gen_dir, exist_ok=True)
    open(os.path.join(gen_dir, "__init__.py"), "a").close()
    try:
        subprocess.run(
            ["protoc", f"--python_out={gen_dir}", f"-I{_PROTO_DIR}",
             "comm_manager.proto"],
            check=True, capture_output=True)
    except FileNotFoundError as e:
        raise RuntimeError(
            "the gRPC comm backend needs its protobuf stub generated, but "
            "`protoc` is not on PATH. Install protoc (protobuf compiler) "
            f"or pre-generate {src} -> comm_manager_pb2.py with "
            "native/comm/generate_grpc.sh on a machine that has it."
        ) from e
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"protoc failed generating the gRPC stub from {src}: "
            f"{e.stderr.decode(errors='replace').strip()}") from e


def _load_pb2():
    """Import the protobuf stub, protoc-generating it if needed.

    Resolution order: (1) an up-to-date pre-generated stub in the package's
    ``comm/_generated``; (2) regenerate there; (3) if the install dir is
    read-only, generate into a per-user cache dir and import from it
    (ADVICE r1: a site-packages install must not require a writable
    package directory, and a missing protoc must say so by name).
    """
    global _pb2
    with _stub_lock:
        if _pb2 is not None:
            return _pb2
        src = os.path.join(_PROTO_DIR, "comm_manager.proto")
        out = os.path.join(_GEN_DIR, "comm_manager_pb2.py")
        stale = not os.path.exists(out) or (
            os.path.exists(src)
            and os.path.getmtime(out) < os.path.getmtime(src))
        if stale:
            try:
                _generate_into(_GEN_DIR, src)
            except OSError:  # read-only package dir (incl. PermissionError)
                cache_dir = _user_cache_gen_dir()
                cache_out = os.path.join(cache_dir, "comm_manager_pb2.py")
                if not os.path.exists(cache_out) or (
                        os.path.exists(src) and
                        os.path.getmtime(cache_out) < os.path.getmtime(src)):
                    _generate_into(cache_dir, src)
                import importlib.util

                spec = importlib.util.spec_from_file_location(
                    "comm_manager_pb2", cache_out)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
                _pb2 = mod
                return _pb2
        from ._generated import comm_manager_pb2
        _pb2 = comm_manager_pb2
        return _pb2


def grpc_available() -> bool:
    try:
        import grpc  # noqa: F401
        _load_pb2()
        return True
    except Exception:
        return False


class _CommServicer:
    """Queues every inbound CommRequest (grpc_server.py:9-40 equivalent)."""

    def __init__(self, pb2, inbox: "queue.Queue[bytes]", rank: int):
        self._pb2 = pb2
        self._inbox = inbox
        self._rank = rank

    def send_message(self, request, context):
        self._inbox.put(request.message)
        return self._pb2.CommResponse(
            client_id=self._rank, message="ack")

    def handler(self):
        import grpc

        pb2 = self._pb2
        rpc = grpc.unary_unary_rpc_method_handler(
            self.send_message,
            request_deserializer=pb2.CommRequest.FromString,
            response_serializer=pb2.CommResponse.SerializeToString,
        )
        method = _SERVICE_METHOD

        class _Generic(grpc.GenericRpcHandler):
            def service(self, details):
                return rpc if details.method == method else None

        return _Generic()


class GrpcCommManager(QueueInboxMixin, BaseCommunicationManager):
    """One rank of a gRPC mesh.

    ``endpoints``: ``[(host, port)] * world_size`` — the reference's
    ip-config table (``build_ip_table``); a port of 0 in this rank's own
    entry means "bind an ephemeral port" (the chosen port is exposed as
    ``.port`` so tests and dynamic deployments can exchange it out of
    band). Plain host strings get the reference's ``50000 + rank`` scheme
    via :func:`endpoints_from_hosts`.
    """

    def __init__(self, rank: int, endpoints: Sequence[Tuple[str, int]]):
        super().__init__()
        import grpc

        self._pb2 = _load_pb2()
        self.rank = rank
        self.world_size = len(endpoints)
        self._endpoints = [tuple(e) for e in endpoints]
        self._init_pump()
        # receiver rank -> (grpc.Channel, unary-unary callable); the channel
        # reference is kept so finalize() can close it
        self._channels: dict[int, Tuple[object, object]] = {}
        self._chan_lock = threading.Lock()

        opts = [("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
                ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES)]
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4), options=opts)
        self._server.add_generic_rpc_handlers(
            (_CommServicer(self._pb2, self._inbox, rank).handler(),))
        host, port = self._endpoints[rank]
        bound = self._server.add_insecure_port(f"{host}:{port}")
        if bound == 0:
            raise OSError(f"rank {rank}: cannot bind grpc on {host}:{port}")
        self.port = bound
        self._endpoints[rank] = (host, bound)
        self._server.start()

    # -- sending ---------------------------------------------------------------
    def _stub(self, receiver: int):
        import grpc

        with self._chan_lock:
            entry = self._channels.get(receiver)
            if entry is None:
                host, port = self._endpoints[receiver]
                chan = grpc.insecure_channel(
                    f"{host}:{port}",
                    options=[("grpc.max_send_message_length",
                              MAX_MESSAGE_BYTES),
                             ("grpc.max_receive_message_length",
                              MAX_MESSAGE_BYTES)])
                call = chan.unary_unary(
                    _SERVICE_METHOD,
                    request_serializer=(
                        self._pb2.CommRequest.SerializeToString),
                    response_deserializer=(
                        self._pb2.CommResponse.FromString),
                )
                entry = (chan, call)
                self._channels[receiver] = entry
            return entry[1]

    def send_message(self, msg: Message) -> None:
        payload = msg.to_bytes()
        req = self._pb2.CommRequest(
            client_id=self.rank, message=payload)
        self._stub(msg.receiver_id)(req)
        # counted after the unary call returns (ack received) — the
        # same sent-means-transport-accepted semantics as the TCP
        # backend's post-rc check
        self.counters.note_sent(len(payload))

    # -- receiving: recv/pump come from QueueInboxMixin (the servicer feeds
    # self._inbox) — the message_handling_subroutine equivalent, without the
    # reference's 0.3 s sleep poll.

    def finalize(self) -> None:
        self.stop_receive_message()
        # wake any recv() blocked on the inbox: once queued messages drain
        # it raises ConnectionError instead of spinning forever
        self._fail_inbox()
        with self._chan_lock:
            for chan, _call in self._channels.values():
                chan.close()
            self._channels.clear()
        if self._server is not None:
            self._server.stop(grace=1).wait()
            self._server = None


def endpoints_from_hosts(hosts: Sequence[str]) -> list[Tuple[str, int]]:
    """Reference port scheme: rank ``i`` serves on ``50000 + i``."""
    return [(h, GRPC_BASE_PORT + i) for i, h in enumerate(hosts)]
