"""Cross-silo federated training over a real transport.

The deployment adapter SURVEY §5.8/§7.9 calls for: the same FedAvg
aggregation semantics as the in-mesh path (sample-weighted parameter mean,
``fedavg_api.py:102-117``), but with clients on separate processes/hosts
exchanging Messages over a comm backend (native TCP or in-process). In-mesh
SPMD remains the perf path; this layer exists so a real multi-hospital
deployment has a transport with the same math.

Protocol (star topology, server = rank 0):
  server --MSG_TYPE_GLOBAL_MODEL{round}--> each client
  client --MSG_TYPE_LOCAL_UPDATE{round, n_samples, params}--> server
  ... comm_round times ... then server --MSG_TYPE_FINISH--> clients
"""
from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from .manager import ClientManager, ServerManager
from .message import Message

logger = logging.getLogger(__name__)

# local_train_fn(params, round_idx) -> (new_params, n_samples, train_loss)
LocalTrainFn = Callable[[Any, int], Tuple[Any, int, float]]


@dataclasses.dataclass
class RoundOutcome:
    """Typed result of one cross-silo round — the quorum shortfall that
    used to surface only as an unhandled ``queue.Empty`` is now an
    explicit verdict the caller can branch on.

    ``status``:
      * ``"completed"`` — every client reported; full aggregate applied;
      * ``"quorum"`` — the collect window timed out but at least
        ``quorum`` clients reported; their updates aggregated with
        weights renormalized over the survivors (the guard machinery's
        survivor-renormalization rule, applied at the transport layer);
      * ``"timeout"`` — fewer than ``quorum`` clients reported; the
        global model is left untouched (carry, like a zero-survivor
        guarded round).
    """

    status: str                       # completed | quorum | timeout
    round_idx: int
    received: List[int]               # client ranks that reported in time
    missing: List[int]                # client ranks that did not
    record: Dict[str, float]          # the history record (round, loss, ...)

    @property
    def applied(self) -> bool:
        """Whether this round changed the global model."""
        return self.status in ("completed", "quorum")


class CrossSiloServer(ServerManager):
    """Rank-0 aggregator.

    ``mask``: optional 0/1 pytree — when set, params travel sparse (values
    + bitmap, ``Message.add_masked_tensor``), the communication-efficient
    transport SalientGrads' sparse models enable; clients mirror the mask
    in their replies.
    """

    def __init__(self, comm, world_size: int, global_params: Any,
                 mask: Any = None):
        super().__init__(comm, rank=0, world_size=world_size)
        self.global_params = global_params
        self.mask = mask
        self._updates: "queue.Queue[Message]" = queue.Queue()
        self.register_message_receive_handler(
            Message.MSG_TYPE_LOCAL_UPDATE, self._updates.put)
        self.history: List[Dict[str, float]] = []

    def run_round(self, round_idx: int, timeout_s: float = 120.0,
                  quorum: Optional[int] = None) -> RoundOutcome:
        """Broadcast the global model, collect client updates, aggregate.

        ``timeout_s`` bounds the wait for EACH update; ``quorum``
        (default: all clients) is the minimum number of reporting clients
        needed to apply an aggregate at all. See :class:`RoundOutcome`
        for the completed/quorum/timeout semantics — a shortfall is a
        typed verdict, never a silent return or an unhandled
        ``queue.Empty``."""
        n_clients = self.world_size - 1
        quorum = n_clients if quorum is None else max(1, int(quorum))
        sparse_payload = None
        if self.mask is not None:
            # sparsify once; the identical payload goes to every client
            probe = Message(Message.MSG_TYPE_GLOBAL_MODEL, 0, 0)
            probe.add_masked_tensor("params", self.global_params, self.mask)
            sparse_payload = probe.tensors["params"]
        for dest in range(1, self.world_size):
            msg = Message(Message.MSG_TYPE_GLOBAL_MODEL, 0, dest)
            msg.add("round", round_idx)
            if sparse_payload is not None:
                msg.add("sparse", True)
                msg.tensors["params"] = sparse_payload
            else:
                msg.add_tensor("params", self.global_params)
            self.send_message(msg)
        updates: List[Tuple[Any, float]] = []
        losses: List[float] = []
        seen: set = set()
        timed_out = False
        while len(updates) < n_clients:
            try:
                msg = self._updates.get(timeout=timeout_s)
            except queue.Empty:
                timed_out = True
                break
            # drop stragglers from earlier rounds and duplicate senders —
            # averaging a stale round-r update into round r+1 would silently
            # corrupt the global model (a stale ERROR reply must not abort
            # a later valid round either, so the round filter comes first)
            if int(msg.get("round", -1)) != round_idx:
                logger.warning(
                    "dropping stale update from rank %d (round %s != %d)",
                    msg.sender_id, msg.get("round"), round_idx)
                continue
            if msg.get("error"):
                # a client detected a protocol violation (e.g. off-mask
                # updates under sparse transport) — fail the round with
                # the client's reason instead of timing out opaquely
                raise RuntimeError(
                    f"client {msg.sender_id} aborted round {round_idx}: "
                    f"{msg.get('error')}")
            if msg.sender_id in seen:
                logger.warning("duplicate update from rank %d dropped",
                               msg.sender_id)
                continue
            seen.add(msg.sender_id)
            updates.append((msg.get_tensor("params"),
                            float(msg.get("n_samples"))))
            losses.append(float(msg.get("train_loss", float("nan"))))
        received = sorted(seen)
        missing = [r for r in range(1, self.world_size) if r not in seen]
        if timed_out and len(updates) < quorum:
            # below quorum: carry the previous global model untouched —
            # the zero-survivor rule of robust/guard.guarded_aggregate,
            # applied at the transport layer
            logger.warning(
                "cross-silo round %d TIMEOUT: %d/%d updates (< quorum %d);"
                " global model carried", round_idx, len(updates),
                n_clients, quorum)
            rec = {"round": round_idx, "train_loss": float("nan"),
                   "clients_reported": float(len(updates))}
            self.history.append(rec)
            return RoundOutcome("timeout", round_idx, received, missing,
                                rec)
        total = sum(w for _, w in updates)
        # survivor renormalization: weights sum to 1 over the clients
        # that reported, whether that is all of them or a quorum
        weights = [w / total for _, w in updates]
        # sample-weighted FedAvg sum (fedavg_api.py:102-117)
        self.global_params = jax.tree_util.tree_map(
            lambda *leaves: sum(
                np.asarray(l) * w for l, w in zip(leaves, weights)),
            *[u for u, _ in updates],
        )
        status = "quorum" if timed_out else "completed"
        if timed_out:
            logger.warning(
                "cross-silo round %d finished with QUORUM %d/%d "
                "(missing ranks %s; weights renormalized)", round_idx,
                len(updates), n_clients, missing)
        rec = {"round": round_idx, "train_loss": float(np.nanmean(losses)),
               "clients_reported": float(len(updates))}
        self.history.append(rec)
        return RoundOutcome(status, round_idx, received, missing, rec)

    def train(self, comm_rounds: int) -> Any:
        for r in range(comm_rounds):
            outcome = self.run_round(r)
            logger.info("cross-silo round %d: %s", r, outcome.record)
        for dest in range(1, self.world_size):
            self.send_message(Message(Message.MSG_TYPE_FINISH, 0, dest))
        return self.global_params


class CrossSiloClient(ClientManager):
    """Rank >=1 local trainer."""

    def __init__(self, comm, rank: int, world_size: int,
                 local_train_fn: LocalTrainFn):
        super().__init__(comm, rank=rank, world_size=world_size)
        self.local_train_fn = local_train_fn
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.register_message_receive_handler(
            Message.MSG_TYPE_GLOBAL_MODEL, self._on_global_model)
        self.register_message_receive_handler(
            Message.MSG_TYPE_FINISH, self._on_finish)

    def _on_global_model(self, msg: Message) -> None:
        round_idx = int(msg.get("round"))
        params = msg.get_tensor("params")
        new_params, n_samples, loss = self.local_train_fn(params, round_idx)
        reply = Message(Message.MSG_TYPE_LOCAL_UPDATE, self.rank, 0)
        reply.add("round", round_idx)
        reply.add("n_samples", int(n_samples))
        reply.add("train_loss", float(loss))
        if msg.get("sparse"):
            # mirror the server's sparsity pattern (recovered from the
            # sparse payload's bitmap). Sparse transport REQUIRES a
            # mask-respecting train_fn (SalientGrads-style: params are
            # re-masked after every step) — silently dropping off-mask
            # updates would corrupt a dense trainer's result, so verify.
            import jax as _jax

            mask = msg.get_tensor_mask("params")
            off = _jax.tree_util.tree_map(
                lambda p, m: bool(np.any(np.asarray(p)[np.asarray(m) == 0])),
                new_params, mask)
            if any(_jax.tree_util.tree_leaves(off)):
                # the receive pump logs-and-continues on handler
                # exceptions, so raising here would be invisible — tell
                # the SERVER, which fails its round with this reason
                err = ("sparse transport: local_train_fn produced nonzero "
                       "off-mask weights; use a mask-respecting trainer "
                       "(e.g. SalientGrads' post-step re-masking) or run "
                       "the server with mask=None")
                self.error = err
                reply.add("error", err)
                self.send_message(reply)
                return
            reply.add("sparse", True)
            reply.add_masked_tensor("params", new_params, mask)
        else:
            reply.add_tensor("params", new_params)
        self.send_message(reply)

    def _on_finish(self, msg: Message) -> None:
        self.done.set()
        self.comm.stop_receive_message()
