"""Distributed communication layer.

In-mesh training uses XLA collectives over ICI (``parallel/``); this package
is the *cross-silo* message layer — the rebuild of
``fedml_core/distributed/`` (Message, Observer, client/server managers,
MPI/gRPC/MQTT backends) with a native C++ TCP transport
(``native/comm/tcp_comm.cpp``) plus an in-process backend for simulation.
"""
from .base import BaseCommunicationManager, CommCounters, Observer
from .cross_silo import CrossSiloClient, CrossSiloServer, RoundOutcome
from .grpc_backend import GrpcCommManager, endpoints_from_hosts, grpc_available
from .local import LocalCommManager, LocalRouter
from .manager import ClientManager, DistributedManager, ServerManager
from .message import Message
from .pubsub import PubSubBroker, PubSubCommManager
from .tcp import TcpCommManager, build_native, native_available

__all__ = [
    "BaseCommunicationManager",
    "ClientManager",
    "CommCounters",
    "CrossSiloClient",
    "CrossSiloServer",
    "RoundOutcome",
    "DistributedManager",
    "GrpcCommManager",
    "LocalCommManager",
    "LocalRouter",
    "Message",
    "Observer",
    "PubSubBroker",
    "PubSubCommManager",
    "ServerManager",
    "TcpCommManager",
    "build_native",
    "endpoints_from_hosts",
    "grpc_available",
    "native_available",
]
