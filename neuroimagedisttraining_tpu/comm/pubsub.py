"""Pub/sub comm backend: topic-routed broker + client manager.

Rebuild of the reference's MQTT backend
(``fedml_core/distributed/communication/mqtt/mqtt_comm_manager.py:14-126``):
same topology — every rank talks only to a broker, the server publishes to
per-client downlink topics and subscribes to per-client uplink topics —
and the same topic scheme (server→client ``fedml0_<cid>``, client→server
``fedml<cid>``). ``paho-mqtt`` and an external Mosquitto broker are not
assumed: :class:`PubSubBroker` is a self-hosted stdlib-socket broker
(thread per connection, length-prefixed frames), and payloads are the
binary ``Message`` framing instead of JSON floats.

Wire frames (all little-endian):
  SUB:    op=1, u16 topic_len, topic
  PUB:    op=2, u16 topic_len, topic, u32 payload_len, payload
  SUBACK: op=3, u16 topic_len, topic
Broker→subscriber deliveries reuse the PUB frame. The broker acks every
SUB once the topic is registered; clients block on the ack during
construction so a publish issued right after a subscriber comes up can
never race past an unregistered subscription.
"""
from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Dict, Set, Tuple

from .base import BaseCommunicationManager, QueueInboxMixin
from .message import Message

logger = logging.getLogger(__name__)

_OP_SUB = 1
_OP_PUB = 2
_OP_SUBACK = 3
MAX_FRAME_BYTES = 1 << 30
# a subscriber that can't drain a delivery within this window is dropped —
# without it one stalled client's full TCP buffer would head-of-line-block
# every other delivery routed by the same publisher thread
SUBSCRIBER_SEND_TIMEOUT_S = 15.0


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _read_frame(sock: socket.socket) -> Tuple[int, str, bytes]:
    (op,) = struct.unpack("<B", _recv_exact(sock, 1))
    (tlen,) = struct.unpack("<H", _recv_exact(sock, 2))
    topic = _recv_exact(sock, tlen).decode()
    payload = b""
    if op == _OP_PUB:
        (plen,) = struct.unpack("<I", _recv_exact(sock, 4))
        if plen > MAX_FRAME_BYTES:
            raise ValueError(f"frame of {plen} bytes exceeds cap")
        payload = _recv_exact(sock, plen)
    return op, topic, payload


def _pub_frame(topic: str, payload: bytes) -> bytes:
    t = topic.encode()
    return b"".join([struct.pack("<B", _OP_PUB),
                     struct.pack("<H", len(t)), t,
                     struct.pack("<I", len(payload)), payload])


class PubSubBroker:
    """Self-hosted topic broker (the Mosquitto stand-in).

    Pass ``port=0`` to bind an ephemeral port (read it from ``.port``).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._subs: Dict[str, Set[socket.socket]] = {}
        self._locks: Dict[socket.socket, threading.Lock] = {}
        self._warned_topics: Set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # bound outbound blocking (see SUBSCRIBER_SEND_TIMEOUT_S); recv
            # timeouts are surfaced per-frame in _serve and tolerated there
            sec = int(SUBSCRIBER_SEND_TIMEOUT_S)
            usec = int((SUBSCRIBER_SEND_TIMEOUT_S - sec) * 1e6)
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                            struct.pack("ll", sec, usec))
            with self._lock:
                self._locks[conn] = threading.Lock()
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                op, topic, payload = _read_frame(conn)
                if op == _OP_SUB:
                    with self._lock:
                        self._subs.setdefault(topic, set()).add(conn)
                        lock = self._locks.get(conn)
                    if lock is not None:
                        t = topic.encode()
                        with lock:
                            conn.sendall(
                                struct.pack("<B", _OP_SUBACK)
                                + struct.pack("<H", len(t)) + t)
                elif op == _OP_PUB:
                    self._route(topic, payload)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            self._drop(conn)

    def _route(self, topic: str, payload: bytes) -> None:
        frame = _pub_frame(topic, payload)
        with self._lock:
            targets = list(self._subs.get(topic, ()))
        if not targets:
            # QoS-0 drop (reference MQTT semantics) — but log it, so a
            # publish racing a subscriber's startup is diagnosable from
            # broker logs instead of an opaque receive timeout (ADVICE r1).
            # Once per topic: steady-state publishes to an unconsumed topic
            # are legitimate and must not flood the log.
            if topic not in self._warned_topics:
                self._warned_topics.add(topic)
                logger.warning(
                    "dropping publish to %r: no subscriber (QoS-0); "
                    "payload %d bytes (warned once per topic)",
                    topic, len(payload))
        for sub in targets:
            lock = self._locks.get(sub)
            if lock is None:
                continue
            try:
                with lock:
                    sub.sendall(frame)
            except OSError:
                self._drop(sub)

    def _drop(self, conn: socket.socket) -> None:
        with self._lock:
            self._locks.pop(conn, None)
            for subs in self._subs.values():
                subs.discard(conn)
        try:
            # shutdown (not just close) — the conn's serve thread is usually
            # blocked in recv holding the fd open, so a bare close() would
            # neither wake it nor send FIN to the peer
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # close live connections too — their serve threads are blocked in
        # _read_frame and would otherwise outlive the broker, leaving
        # clients unaware the broker is gone
        with self._lock:
            conns = list(self._locks)
        for conn in conns:
            self._drop(conn)


def downlink_topic(client_id: int) -> str:
    """Server→client topic (mqtt_comm_manager.py: ``fedml0_<cid>``)."""
    return f"fedml0_{client_id}"


def uplink_topic(client_id: int) -> str:
    """Client→server topic (mqtt_comm_manager.py: ``fedml<cid>``)."""
    return f"fedml{client_id}"


class PubSubCommManager(QueueInboxMixin, BaseCommunicationManager):
    """One rank of the star topology over a broker.

    ``world_size`` counts every rank including the server: rank
    (``client_id``) 0 is the server and subscribes to uplinks
    ``fedml1 .. fedml<world_size-1>``; ranks >=1 are clients and subscribe
    to their own downlink. ``send_message`` derives the topic from the
    Message's receiver id, mirroring ``MqttCommManager.send_message``. A
    lost broker connection fails fast: once queued deliveries drain,
    ``recv`` raises ``ConnectionError``.
    """

    def __init__(self, client_id: int, broker_host: str, broker_port: int,
                 world_size: int):
        super().__init__()
        self.client_id = client_id
        self.world_size = world_size
        self._init_pump()
        self._send_lock = threading.Lock()
        self._sock = socket.create_connection(
            (broker_host, broker_port), timeout=10)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        if client_id == 0:
            topics = [uplink_topic(c) for c in range(1, world_size)]
        else:
            topics = [downlink_topic(client_id)]
        for topic in topics:
            self._subscribe(topic)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _subscribe(self, topic: str) -> None:
        t = topic.encode()
        with self._send_lock:
            self._sock.sendall(
                struct.pack("<B", _OP_SUB) + struct.pack("<H", len(t)) + t)
        # block until the broker acks the registration — a publish issued
        # right after this constructor returns must not race the SUB.
        # Runs before the reader thread starts, so reading inline is safe;
        # deliveries for already-acked topics that interleave are inboxed.
        while True:
            op, got_topic, payload = _read_frame(self._sock)
            if op == _OP_SUBACK and got_topic == topic:
                return
            if op == _OP_PUB:
                self._inbox.put(payload)

    def _read_loop(self) -> None:
        try:
            while not self._stop.is_set():
                op, _topic, payload = _read_frame(self._sock)
                if op == _OP_PUB:
                    self._inbox.put(payload)
        except (ConnectionError, OSError, ValueError):
            if not self._stop.is_set():
                logger.warning(
                    "rank %d: broker connection lost", self.client_id)
        finally:
            self._fail_inbox()

    def send_message(self, msg: Message) -> None:
        receiver = msg.receiver_id
        topic = (downlink_topic(receiver) if self.client_id == 0
                 else uplink_topic(self.client_id))
        payload = msg.to_bytes()
        if len(payload) > MAX_FRAME_BYTES:
            # the broker would kill the connection on an oversized frame;
            # fail here with an actionable error instead (tcp.py does the
            # same for its u32 wire frames)
            raise ValueError(
                f"message payload {len(payload)} bytes exceeds the "
                f"{MAX_FRAME_BYTES}-byte frame cap — shard the pytree "
                "across messages")
        frame = _pub_frame(topic, payload)
        with self._send_lock:
            self._sock.sendall(frame)
        # Message payload bytes, not the framed size — the same
        # serialized-message basis every other backend counts
        self.counters.note_sent(len(payload))

    # recv/pump come from QueueInboxMixin (fed by _read_loop)

    def finalize(self) -> None:
        self.stop_receive_message()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
