"""Client/Server managers: per-message-type handler dispatch over any
comm backend.

Rebuild of ``fedml_core/distributed/client/client_manager.py:13-73`` and
``server/server_manager.py:13-68`` (Observer registering handler callbacks
and pumping the backend's receive loop). ``finish()`` stops the loop
cleanly instead of the reference's ``MPI.COMM_WORLD.Abort()``.
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, Dict

from .base import BaseCommunicationManager, Observer
from .message import Message

logger = logging.getLogger(__name__)

MessageHandler = Callable[[Message], None]


class DistributedManager(Observer):
    """Shared base for both sides (the reference duplicates this class)."""

    def __init__(self, comm: BaseCommunicationManager, rank: int,
                 world_size: int):
        self.comm = comm
        self.rank = rank
        self.world_size = world_size
        self._handlers: Dict[str, MessageHandler] = {}
        self._thread: threading.Thread | None = None
        comm.add_observer(self)

    # client_manager.py:59-61
    def register_message_receive_handler(self, msg_type: str,
                                         handler: MessageHandler) -> None:
        self._handlers[msg_type] = handler

    def receive_message(self, msg_type: str, msg: Message) -> None:
        handler = self._handlers.get(msg_type)
        if handler is None:
            logger.warning("rank %d: no handler for %r", self.rank, msg_type)
            return
        handler(msg)

    def send_message(self, msg: Message) -> None:
        self.comm.send_message(msg)

    def run(self, background: bool = False) -> None:
        """Pump the receive loop (client_manager.py:36-38); with
        ``background=True`` the loop runs in a daemon thread."""
        if background:
            self._thread = threading.Thread(
                target=self.comm.handle_receive_message, daemon=True)
            self._thread.start()
        else:
            self.comm.handle_receive_message()

    def finish(self) -> None:
        self.comm.stop_receive_message()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # a handler is still running; finalizing the backend under
                # it would hand a freed native handle to live code
                logger.error(
                    "rank %d: receive pump did not stop within 5s "
                    "(handler still running?); leaving backend open",
                    self.rank)
                return
            self._thread = None
        finalize = getattr(self.comm, "finalize", None)
        if finalize is not None:
            finalize()


class ClientManager(DistributedManager):
    pass


class ServerManager(DistributedManager):
    pass
