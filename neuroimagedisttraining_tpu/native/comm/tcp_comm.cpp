// Native TCP message transport for cross-silo federated deployment.
//
// TPU-native rebuild of the reference's native-underneath comm backends
// (fedml_core/distributed/communication/: mpi4py point-to-point with pickled
// payloads + 0.3s polling, gRPC unary JSON, MQTT pub/sub). Design deltas:
//   * one always-on listener thread per rank, blocking condvar queue —
//     no poll loops (the reference sleeps 0.3 s between queue checks,
//     mpi/com_manager.py:90-93)
//   * length-prefixed binary frames — no JSON/pickle in the hot path;
//     payload encoding is the caller's concern (the Python layer ships
//     flattened pytree leaves as raw bytes)
//   * cached outbound connections (the reference's gRPC backend reopens a
//     channel per send, grpc_comm_manager.py:45-55)
//
// C ABI (ctypes-friendly):
//   comm_init(rank, world, hosts, ports) -> handle
//   comm_send(handle, dest, buf, len)    -> 0 on success
//   comm_recv(handle, &buf, &len, timeout_s) -> 0 on message, 1 on timeout
//   comm_free_buf(buf), comm_finalize(handle)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Frame {
  std::vector<uint8_t> data;
};

struct Comm {
  int rank = -1;
  int world = 0;
  int listen_fd = -1;
  std::vector<std::string> hosts;
  std::vector<int> ports;
  std::vector<int> out_fds;  // cached outbound sockets, -1 = not connected
  std::mutex out_mu;

  std::deque<Frame> queue;
  std::mutex mu;
  std::condition_variable cv;
  bool stopping = false;
  int recv_waiters = 0;  // threads inside comm_recv; finalize drains them

  std::thread listener;
  std::vector<std::thread> readers;
  std::vector<int> reader_fds;
  std::mutex readers_mu;
};

bool read_exact(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

void reader_loop(Comm* c, int fd) {
  for (;;) {
    uint32_t len_be = 0;
    if (!read_exact(fd, &len_be, 4)) break;
    uint32_t len = ntohl(len_be);
    Frame f;
    f.data.resize(len);
    if (len > 0 && !read_exact(fd, f.data.data(), len)) break;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      if (c->stopping) break;
      c->queue.push_back(std::move(f));
    }
    c->cv.notify_one();
  }
  // fd is closed by comm_finalize (closing here would race fd reuse
  // against finalize's shutdown() of the same descriptor number)
}

void listen_loop(Comm* c) {
  for (;;) {
    int fd = ::accept(c->listen_fd, nullptr, nullptr);
    if (fd < 0) break;  // listen_fd closed => shutting down
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(c->readers_mu);
    if (c->stopping) {
      ::close(fd);
      break;
    }
    c->reader_fds.push_back(fd);
    c->readers.emplace_back(reader_loop, c, fd);
  }
}

int connect_to(const std::string& host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

}  // namespace

extern "C" {

void* comm_init(int rank, int world, const char** hosts, const int* ports) {
  auto* c = new Comm;
  c->rank = rank;
  c->world = world;
  for (int i = 0; i < world; ++i) {
    c->hosts.emplace_back(hosts[i]);
    c->ports.push_back(ports[i]);
    c->out_fds.push_back(-1);
  }
  c->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (c->listen_fd < 0) {
    delete c;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(c->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(ports[rank]));
  if (::bind(c->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(c->listen_fd, world + 8) != 0) {
    ::close(c->listen_fd);
    delete c;
    return nullptr;
  }
  c->listener = std::thread(listen_loop, c);
  return c;
}

int comm_send(void* handle, int dest, const uint8_t* buf, uint32_t len) {
  auto* c = static_cast<Comm*>(handle);
  if (!c || dest < 0 || dest >= c->world) return -1;
  std::lock_guard<std::mutex> lk(c->out_mu);
  for (int attempt = 0; attempt < 2; ++attempt) {
    if (c->out_fds[dest] < 0) {
      // peers may start in any order: retry connect briefly
      for (int tries = 0; tries < 50 && c->out_fds[dest] < 0; ++tries) {
        c->out_fds[dest] = connect_to(c->hosts[dest], c->ports[dest]);
        if (c->out_fds[dest] < 0)
          std::this_thread::sleep_for(std::chrono::milliseconds(100));
      }
      if (c->out_fds[dest] < 0) return -2;
    }
    uint32_t len_be = htonl(len);
    if (write_exact(c->out_fds[dest], &len_be, 4) &&
        (len == 0 || write_exact(c->out_fds[dest], buf, len))) {
      return 0;
    }
    ::close(c->out_fds[dest]);  // stale cached socket: reconnect once
    c->out_fds[dest] = -1;
  }
  return -3;
}

int comm_recv(void* handle, uint8_t** buf_out, uint32_t* len_out,
              double timeout_s) {
  auto* c = static_cast<Comm*>(handle);
  if (!c) return -1;
  std::unique_lock<std::mutex> lk(c->mu);
  c->recv_waiters++;
  auto ready = [c] { return c->stopping || !c->queue.empty(); };
  bool timed_out = false;
  if (timeout_s < 0) {
    c->cv.wait(lk, ready);
  } else if (!c->cv.wait_for(
                 lk, std::chrono::duration<double>(timeout_s), ready)) {
    timed_out = true;
  }
  c->recv_waiters--;
  // notify while holding the lock: after unlock this thread must not touch
  // *c again (a draining finalize may delete it the moment the lock drops)
  if (timed_out || c->queue.empty()) {
    bool stopping = c->stopping;
    c->cv.notify_all();  // wake a draining finalize to re-check waiters
    lk.unlock();
    return stopping ? -1 : 1;
  }
  Frame f = std::move(c->queue.front());
  c->queue.pop_front();
  c->cv.notify_all();
  lk.unlock();
  *len_out = static_cast<uint32_t>(f.data.size());
  *buf_out = static_cast<uint8_t*>(std::malloc(f.data.size()));
  if (*buf_out == nullptr && !f.data.empty()) return -1;
  std::memcpy(*buf_out, f.data.data(), f.data.size());
  return 0;
}

void comm_free_buf(uint8_t* buf) { std::free(buf); }

int comm_pending(void* handle) {
  auto* c = static_cast<Comm*>(handle);
  if (!c) return 0;
  std::lock_guard<std::mutex> lk(c->mu);
  return static_cast<int>(c->queue.size());
}

void comm_finalize(void* handle) {
  auto* c = static_cast<Comm*>(handle);
  if (!c) return;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    c->stopping = true;
  }
  c->cv.notify_all();
  {
    // drain threads still blocked in comm_recv before tearing down —
    // deleting the mutex/condvar under a live waiter is use-after-free
    std::unique_lock<std::mutex> lk(c->mu);
    c->cv.wait(lk, [c] { return c->recv_waiters == 0; });
  }
  ::shutdown(c->listen_fd, SHUT_RDWR);
  ::close(c->listen_fd);
  if (c->listener.joinable()) c->listener.join();
  {
    std::lock_guard<std::mutex> lk(c->out_mu);
    for (int& fd : c->out_fds) {
      if (fd >= 0) ::close(fd);
      fd = -1;
    }
  }
  {
    // unblock readers stuck in recv() on still-open inbound sockets
    std::lock_guard<std::mutex> lk(c->readers_mu);
    for (int fd : c->reader_fds) ::shutdown(fd, SHUT_RDWR);
    for (auto& t : c->readers)
      if (t.joinable()) t.join();
    for (int fd : c->reader_fds) ::close(fd);
  }
  delete c;
}

}  // extern "C"
