#!/bin/sh
# Regenerate the protobuf message stub for the gRPC comm backend
# (reference: fedml_core/.../gRPC/proto/generate_grpc.sh). The service
# itself is registered via grpc generic handlers (comm/grpc_backend.py),
# so only --python_out is needed — no grpcio-tools plugin dependency.
set -e
cd "$(dirname "$0")"
OUT="../../comm/_generated"
mkdir -p "$OUT"
touch "$OUT/__init__.py"
protoc --python_out="$OUT" -I. comm_manager.proto
echo "wrote $OUT/comm_manager_pb2.py"
