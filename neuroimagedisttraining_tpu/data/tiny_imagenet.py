"""Tiny-ImageNet-200 federated loader.

Rebuild of the reference's custom ``tiny`` VisionDataset
(``fedml_api/data_preprocessing/tiny_imagenet/datasets.py:20-147``), which
walks the on-disk layout
  train/<wnid>/images/*.JPEG        (500 per class)
  val/images/*.JPEG + val_annotations.txt
and its federated partition wrapper (same Dirichlet/class partitioning as
CIFAR). Images load once into a host array (64x64x3, channels-last,
per-channel normalized) and pack into client-stacked device shards.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from .packing import partition_and_pack
from .types import FederatedData

# torchvision's commonly used tiny-imagenet stats
TIN_MEAN = np.array([0.4802, 0.4481, 0.3975], np.float32)
TIN_STD = np.array([0.2770, 0.2691, 0.2821], np.float32)


def _load_image(path: str) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"), dtype=np.uint8)


def _wnid_index(root: str) -> Dict[str, int]:
    """Class ids from sorted train-dir wnids (datasets.py:49-61 builds the
    same mapping via ``wnids.txt``; sorting the train dirs is equivalent and
    robust to a missing wnids.txt)."""
    wnids_file = os.path.join(root, "wnids.txt")
    if os.path.exists(wnids_file):
        with open(wnids_file) as f:
            wnids = [line.strip() for line in f if line.strip()]
    else:
        wnids = sorted(os.listdir(os.path.join(root, "train")))
    return {w: i for i, w in enumerate(wnids)}


def load_tiny_imagenet_raw(
    root: str,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Read the full train + val splits into host arrays (uint8 HWC)."""
    wnid_to_cls = _wnid_index(root)
    xs: List[np.ndarray] = []
    ys: List[int] = []
    train_dir = os.path.join(root, "train")
    for wnid in sorted(os.listdir(train_dir)):
        if wnid not in wnid_to_cls:
            continue
        img_dir = os.path.join(train_dir, wnid, "images")
        if not os.path.isdir(img_dir):
            continue
        for name in sorted(os.listdir(img_dir)):
            xs.append(_load_image(os.path.join(img_dir, name)))
            ys.append(wnid_to_cls[wnid])
    X_train = np.stack(xs)
    y_train = np.asarray(ys, np.int64)

    # val split doubles as the test set (datasets.py:96-120: labels come
    # from val_annotations.txt)
    val_dir = os.path.join(root, "val")
    ann = os.path.join(val_dir, "val_annotations.txt")
    xs2: List[np.ndarray] = []
    ys2: List[int] = []
    with open(ann) as f:
        for line in f:
            parts = line.split("\t")
            if len(parts) < 2 or parts[1] not in wnid_to_cls:
                continue
            xs2.append(_load_image(os.path.join(val_dir, "images", parts[0])))
            ys2.append(wnid_to_cls[parts[1]])
    X_test = np.stack(xs2)
    y_test = np.asarray(ys2, np.int64)
    return X_train, y_train, X_test, y_test


def _normalize(x: np.ndarray) -> np.ndarray:
    return (x.astype(np.float32) / 255.0 - TIN_MEAN) / TIN_STD


def load_partition_data_tiny_imagenet(
    data_dir: str,
    partition_method: str = "dir",
    partition_alpha: float = 0.3,
    client_number: int = 100,
    val_fraction: float = 0.0,
    seed: Optional[int] = None,
) -> FederatedData:
    X_train, y_train, X_test, y_test = load_tiny_imagenet_raw(data_dir)
    # class count from the wnid table, not max observed label — a partial
    # checkout missing the last classes' images must not shrink the head
    n_classes = len(_wnid_index(data_dir))
    # RandomCrop(64, padding=4) + flip pipeline, same as CIFAR
    # (tiny_imagenet/data_loader.py:51-56)
    from .cifar import black_pad_value

    return partition_and_pack(
        _normalize(X_train), y_train, _normalize(X_test), y_test,
        n_classes, client_number, partition_method, partition_alpha,
        val_fraction, seed,
        aug_pad_value=black_pad_value(TIN_MEAN, TIN_STD),
    )
