from .types import FederatedData
from .synthetic import make_synthetic_federated

__all__ = ["FederatedData", "make_synthetic_federated"]
