from .types import FederatedData, pad_stack
from .synthetic import make_synthetic_federated
from .partition import (
    class_prior_partition,
    contiguous_reshard,
    dirichlet_partition,
    proportional_test_indices,
    record_data_stats,
    site_partition,
)
from .abcd import (
    load_abcd_h5,
    load_partition_data_abcd,
    load_partition_data_abcd_rescale,
    site_train_test_split,
    write_abcd_h5,
)
from .cifar import (
    load_partition_data_cifar,
    random_crop_flip,
)

# Dataset names (as dispatched below) whose loaders declare the reference's
# RandomCrop+flip train transform by setting FederatedData.aug_pad_value —
# the ONE source of truth for "is this dataset augmentable", used both by
# FedAlgorithm's auto-wiring input (via the loaded data's aug_pad_value)
# and by the runner's pre-load checkpoint-lineage guard. Keep in sync with
# the dispatch cases below.
AUGMENTABLE_DATASETS = (
    "cifar10", "cifar100", "tiny_imagenet", "tiny-imagenet-200", "tiny")


def dataset_is_augmentable(dataset: str) -> bool:
    return dataset.lower() in AUGMENTABLE_DATASETS


def load_federated_data(
    dataset: str,
    data_dir: str = "",
    client_number: int = 8,
    partition_method: str = "dir",
    partition_alpha: float = 0.3,
    val_fraction: float = 0.0,
    seed: int = 42,
    **kwargs,
) -> FederatedData:
    """Dataset dispatcher — the rebuild of each experiment main's
    ``load_data`` switch (``main_sailentgrads.py:130-161``)."""
    name = dataset.lower()
    if name in ("abcd", "abcd_rescale"):
        if name == "abcd" and not client_number:
            return load_partition_data_abcd(
                data_dir, val_fraction=val_fraction, **kwargs)
        return load_partition_data_abcd_rescale(
            data_dir, client_number, val_fraction=val_fraction, **kwargs)
    if name in ("abcd_site",):
        return load_partition_data_abcd(
            data_dir, val_fraction=val_fraction, **kwargs)
    if name in ("cifar10", "cifar100"):
        return load_partition_data_cifar(
            data_dir, dataset=name, partition_method=partition_method,
            partition_alpha=partition_alpha, client_number=client_number,
            val_fraction=val_fraction, seed=seed, **kwargs)
    if name in ("tiny_imagenet", "tiny-imagenet-200", "tiny"):
        from .tiny_imagenet import load_partition_data_tiny_imagenet

        return load_partition_data_tiny_imagenet(
            data_dir, partition_method=partition_method,
            partition_alpha=partition_alpha, client_number=client_number,
            val_fraction=val_fraction, seed=seed, **kwargs)
    if name in ("synthetic", "abcd_synth"):
        spc = kwargs.get("samples_per_client", 24)
        val_per_client = (
            max(1, int(val_fraction * spc)) if val_fraction > 0 else 0)
        return make_synthetic_federated(
            seed=seed, n_clients=client_number,
            val_per_client=val_per_client, **kwargs)
    raise ValueError(f"unknown dataset {dataset!r}")


__all__ = [
    "FederatedData",
    "pad_stack",
    "make_synthetic_federated",
    "load_federated_data",
    "class_prior_partition",
    "contiguous_reshard",
    "dirichlet_partition",
    "proportional_test_indices",
    "record_data_stats",
    "site_partition",
    "load_abcd_h5",
    "load_partition_data_abcd",
    "load_partition_data_abcd_rescale",
    "site_train_test_split",
    "write_abcd_h5",
    "load_partition_data_cifar",
    "random_crop_flip",
]
