"""Offline ABCD preprocessing: BIDS tree -> masked volumes -> HDF5 cohort.

Script-form rebuild of the reference's ``Preprocess_ABCD.ipynb`` notebook:

* cells 2-6   — walk the BIDS tree for smoothed modulated gray-matter T1 maps
  (``Sm6mwc1pT1.nii``, 121x145x121) and join subject ids against the
  ``ABCDSexSiteInfo.txt`` metadata table (subject, sex, site columns);
* cells 12-21 — mean volume across subjects -> brain mask ``mean > 0.2`` ->
  mask every subject's volume;
* cells 28-31 — stack X, label-encode site, y = sex, write
  ``final_dataset_<N>subs.h5`` with keys X/y/site (the file
  ``ABCD/data_loader.py:105-136`` consumes).

``nibabel`` is not part of this image; volume loading is injected via a
``load_volume`` callable (defaults to nibabel when importable) so the
pipeline itself — discovery, masking, stacking, HDF5 write — is fully
testable without it.
"""
from __future__ import annotations

import logging
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .abcd import write_abcd_h5

logger = logging.getLogger(__name__)

T1_FILENAME = "Sm6mwc1pT1.nii"  # Preprocess_ABCD.ipynb cell 3
MASK_THRESHOLD = 0.2            # Preprocess_ABCD.ipynb cell 14


def _nibabel_loader(path: str) -> np.ndarray:  # pragma: no cover
    import nibabel as nib

    return np.asarray(nib.load(path).get_fdata(), dtype=np.float32)


def discover_t1_volumes(
    bids_root: str, filename: str = T1_FILENAME
) -> Dict[str, str]:
    """Walk a BIDS-like tree and map subject id (the ``sub-*`` path
    component) to its T1 map path."""
    found: Dict[str, str] = {}
    for dirpath, _dirnames, filenames in os.walk(bids_root):
        if filename in filenames:
            parts = dirpath.split(os.sep)
            subject = next(
                (p for p in parts if p.startswith("sub-")),
                os.path.basename(dirpath),
            )
            found[subject] = os.path.join(dirpath, filename)
    return found


def read_site_info(path: str) -> Dict[str, Tuple[int, str]]:
    """Parse ``ABCDSexSiteInfo.txt``-style metadata: whitespace/comma rows of
    (subject, sex, site). Returns {subject: (sex_code, site_name)} with
    sex_code 1 for female (the reference's y = female indicator)."""
    table: Dict[str, Tuple[int, str]] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.lower().startswith(("subject", "src_subject")):
                continue
            row = line.replace(",", " ").split()
            if len(row) < 3:
                continue
            subject, sex, site = row[0], row[1], row[2]
            sex_code = 1 if sex.upper() in ("F", "FEMALE", "2") else 0
            table[subject] = (sex_code, site)
    return table


def compute_brain_mask(
    volumes: Sequence[np.ndarray], threshold: float = MASK_THRESHOLD
) -> np.ndarray:
    """Mean volume across subjects thresholded at ``mean > threshold`` —
    the notebook's group-level gray-matter mask (cells 12-14)."""
    acc = np.zeros_like(np.asarray(volumes[0], np.float64))
    for v in volumes:
        acc += v
    mean = acc / len(volumes)
    return (mean > threshold).astype(np.float32)


def preprocess_abcd(
    bids_root: str,
    site_info_path: str,
    out_path: Optional[str] = None,
    load_volume: Optional[Callable[[str], np.ndarray]] = None,
    mask_threshold: float = MASK_THRESHOLD,
    limit: Optional[int] = None,
):
    """Full pipeline: discover -> load -> mask -> stack -> HDF5.

    Two passes over the subject list (first for the mean/mask, second to
    apply it) so peak memory is one volume + the accumulator, not the cohort
    — the notebook loads everything at once and could only ever process
    <=3000 subjects (cell 21).
    """
    load_volume = load_volume or _nibabel_loader
    paths = discover_t1_volumes(bids_root)
    meta = read_site_info(site_info_path)
    subjects = sorted(set(paths) & set(meta))
    if limit:
        subjects = subjects[:limit]
    if not subjects:
        raise ValueError(
            "no subjects found with both a T1 volume and metadata")
    logger.info("preprocessing %d subjects", len(subjects))

    # pass 1: group mean -> mask
    acc = None
    for s in subjects:
        v = np.asarray(load_volume(paths[s]), np.float32)
        acc = v.astype(np.float64) if acc is None else acc + v
    mask = ((acc / len(subjects)) > mask_threshold).astype(np.float32)

    # pass 2: apply mask, stack, encode labels
    sites = sorted({meta[s][1] for s in subjects})
    site_code = {name: i for i, name in enumerate(sites)}
    X = np.zeros((len(subjects),) + mask.shape, np.float32)
    y = np.zeros(len(subjects), np.int64)
    site = np.zeros(len(subjects), np.int64)
    for i, s in enumerate(subjects):
        X[i] = np.asarray(load_volume(paths[s]), np.float32) * mask
        y[i] = meta[s][0]
        site[i] = site_code[meta[s][1]]

    out_path = out_path or os.path.join(
        bids_root, f"final_dataset_{len(subjects)}subs.h5")
    write_abcd_h5(out_path, X, y, site)
    logger.info("wrote %s (%d subjects, %d sites)", out_path, len(subjects),
                len(sites))
    return out_path, mask
