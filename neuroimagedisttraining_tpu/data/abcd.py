"""ABCD neuroimaging data path: HDF5 cohort -> device-ready FederatedData.

Rebuild of ``fedml_api/data_preprocessing/ABCD/data_loader.py``:

* ``load_abcd_h5``            <- ``load_abcd_data`` (``data_loader.py:105-136``)
  but *lazy per-site* instead of read-everything-into-RAM (SURVEY.md §7 memory
  hard-part: the full 11.5k-subject cohort at 121x145x121 f32 is ~97 GB; we
  read one site's rows at a time through h5py).
* ``site_train_test_split``   <- the per-site 80/20 split with the fixed
  seed-42 shuffle (``data_loader.py:67-102``, ``np.random.seed(42)`` before
  every site's shuffle — reproduced exactly so convergence comparisons against
  the reference see identical splits).
* ``load_partition_data_abcd``          <- one client per site
  (``data_loader.py:164-216``, hardcoded 21 sites there; dynamic here).
* ``load_partition_data_abcd_rescale``  <- merge sites then contiguous equal
  reshard to ``client_number`` (``data_loader.py:220-319``) — the entry
  SalientGrads uses (``main_sailentgrads.py:135``).

Instead of TensorDataset/DataLoader pairs, both entries return a single
:class:`FederatedData` pytree (stacked [C, n_max, D, H, W, 1] arrays + valid
counts) that ships to the TPU mesh once; batching happens on device inside the
jitted round (``core/trainer.py``).
"""
from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

import numpy as np

from .types import FederatedData, pad_stack

logger = logging.getLogger(__name__)

ABCD_VOLUME_SHAPE = (121, 145, 121)  # data_loader.py:115-117
ABCD_SPLIT_SEED = 42                 # data_loader.py:81
ABCD_TEST_RATIO = 0.2                # data_loader.py:74


def load_abcd_h5(path: str):
    """Open the preprocessed cohort file ``final_dataset_<N>subs.h5``
    (written by the preprocessing pipeline, see ``preprocess.py``) and return
    ``(X, y, site)`` h5py datasets / arrays. ``X`` stays an h5py dataset so
    callers can slice per site without loading the cohort."""
    import h5py

    f = h5py.File(path, "r")
    return f["X"], np.asarray(f["y"][()]), np.asarray(f["site"][()])


def site_train_test_split(
    site: np.ndarray,
    test_ratio: float = ABCD_TEST_RATIO,
    seed: int = ABCD_SPLIT_SEED,
) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    """Per-site train/test index split with the reference's RNG contract:
    the same fixed seed re-applied before each site's shuffle
    (``data_loader.py:80-86``). Returns {site_value: (train_idx, test_idx)}."""
    site = np.asarray(site).ravel()
    out = {}
    for s in np.unique(site):
        idx = np.where(site == s)[0]
        n_test = int(len(idx) * test_ratio)
        n_train = len(idx) - n_test
        np.random.seed(seed)
        np.random.shuffle(idx)
        out[int(s)] = (np.sort(idx[:n_train]), np.sort(idx[n_train:]))
    return out


def _gather_rows(X, idx: np.ndarray) -> np.ndarray:
    """Read rows ``idx`` from an h5py dataset (or ndarray). h5py fancy
    indexing requires strictly increasing indices — we sort, read, and the
    row order within a client shard is irrelevant (batching reshuffles on
    device)."""
    idx = np.sort(np.asarray(idx))
    if len(idx) == 0:
        shape = (0,) + tuple(X.shape[1:])
        return np.zeros(shape, dtype=np.float32)
    return np.asarray(X[idx], dtype=np.float32)


LAYOUTS = ("channels", "flat", "s2d")


def _finalize(
    xs_tr, ys_tr, xs_te, ys_te, val_fraction: float, seed: int,
    normalize: bool, layout: str = "channels", pad_to=None,
    client_ids=None, s2d_spec=None,
) -> FederatedData:
    """Stack per-client splits into FederatedData; optional per-volume
    standardization; optional val split carved from train (the FedFomo
    9-tuple variant, ``data_val_loader.py:275-326``).

    ``layout`` picks the on-device storage (see SURVEY §5.7 / ops/s2d.py):
      * ``"channels"`` — (..., D, H, W, 1), the reference's NDHWC shape;
        note the trailing C=1 tile-pads 8-16x in HBM.
      * ``"flat"``     — (..., D, H, W) channel-less; pair with the
        algorithms' ``channel_inject=True`` (apply-time unsqueeze).
      * ``"s2d"``      — (..., D', H', 8, W') phase-decomposed for the
        ``3dcnn_s2d`` stem (fastest ABCD path on TPU).

    ``pad_to``: optional (train, test) padded lengths. Filtered
    (per-process multi-host) loads MUST pass the global maxima here — each
    process pads to the same extents so every host computes identical
    global array shapes (sites have unequal subject counts).
    """
    if layout not in LAYOUTS:
        raise ValueError(f"layout {layout!r} not in {LAYOUTS}")

    def prep(x):
        x = np.asarray(x, np.float32)
        if normalize and x.size:
            flat = x.reshape(x.shape[0], -1)
            mu = flat.mean(axis=1)
            sd = flat.std(axis=1) + 1e-6
            x = (x - mu[(...,) + (None,) * (x.ndim - 1)]) / \
                sd[(...,) + (None,) * (x.ndim - 1)]
        if layout == "channels":
            if x.ndim >= 2 and x.shape[-1] != 1:
                x = x[..., None]  # NDHWC channel for conv kernels
        else:
            # flat/s2d interpret the last three dims as the volume — drop a
            # stored trailing channel axis first (cohort files come both
            # ways; the channels branch above absorbs the same variance)
            if x.ndim >= 3 and x.shape[-1] == 1:
                x = x[..., 0]
            if layout == "s2d":
                from ..ops.s2d import phase_decompose

                # (kernel, pad) of the stem the phases feed: (5, 0) for
                # the AlexNet3D stem (default), (3, 3) for ResNet_l3
                k, pd = s2d_spec or (5, 0)
                x = np.asarray(phase_decompose(x, kernel=k, pad=pd))
        return x

    xs_va, ys_va = [], []
    if val_fraction > 0:
        # per-client RNG keyed by the GLOBAL client id: a filtered
        # (multi-host) load must carve the exact same train/val membership
        # as the full load, independent of which other clients are present
        ids = (client_ids if client_ids is not None
               else list(range(len(xs_tr))))
        new_tr_x, new_tr_y = [], []
        for gid, (x, y) in zip(ids, zip(xs_tr, ys_tr)):
            rng = np.random.RandomState((seed * 100003 + int(gid)) % 2**31)
            n_val = int(len(y) * val_fraction)
            perm = rng.permutation(len(y))
            new_tr_x.append(x[perm[n_val:]])
            new_tr_y.append(y[perm[n_val:]])
            xs_va.append(x[perm[:n_val]])
            ys_va.append(y[perm[:n_val]])
        xs_tr, ys_tr = new_tr_x, new_tr_y

    pad_tr, pad_te = pad_to if pad_to is not None else (None, None)
    pad_va = None
    if val_fraction > 0 and pad_tr is not None:
        # the val split carves int(n*val) rows out of each train shard;
        # both n - int(n*vf) and int(n*vf) are nondecreasing in n, so the
        # global maxima follow from the global max train count
        pad_va = int(pad_tr * val_fraction)
        pad_tr = pad_tr - pad_va
    x_train, n_train = pad_stack([prep(x) for x in xs_tr], pad_to=pad_tr)
    y_train, _ = pad_stack([np.asarray(y, np.int32) for y in ys_tr],
                           pad_to=pad_tr)
    x_test, n_test = pad_stack([prep(x) for x in xs_te], pad_to=pad_te)
    y_test, _ = pad_stack([np.asarray(y, np.int32) for y in ys_te],
                          pad_to=pad_te)
    kwargs = {}
    if val_fraction > 0:
        x_val, n_val = pad_stack([prep(x) for x in xs_va], pad_to=pad_va)
        y_val, _ = pad_stack([np.asarray(y, np.int32) for y in ys_va],
                             pad_to=pad_va)
        kwargs = dict(x_val=x_val, y_val=y_val, n_val=n_val)
    return FederatedData(
        x_train=x_train, y_train=y_train, n_train=n_train,
        x_test=x_test, y_test=y_test, n_test=n_test,
        class_num=2, **kwargs,
    )


def abcd_site_count(data_path: str) -> int:
    """Number of acquisition sites (= site-clients) in a cohort file.
    Reads only the tiny ``site`` vector — used by the multi-host path to
    size the clients mesh before any volume IO."""
    import h5py

    with h5py.File(data_path, "r") as f:
        return len(np.unique(np.asarray(f["site"][()])))


def load_partition_data_abcd(
    data_path: str,
    val_fraction: float = 0.0,
    normalize: bool = False,
    seed: int = ABCD_SPLIT_SEED,
    layout: str = "channels",
    client_filter=None,
    s2d_spec=None,
) -> FederatedData:
    """One federated client per acquisition site (``data_loader.py:164-216``).

    Reads site by site (lazy), splits 80/20 with the reference's seed
    contract, and stacks into one device-ready pytree.

    ``client_filter``: load only these client (site-position) indices, in
    the given order — the multi-host path passes each process's
    ``local_client_indices`` so no host ever reads the full cohort."""
    X, y, site = load_abcd_h5(data_path)
    splits = site_train_test_split(site, seed=seed)
    items = list(splits.items())
    pad_to = None
    if client_filter is not None:
        # pad every process's shards to the GLOBAL maxima (sites are
        # unequal-sized; computed from index lengths, no volume IO)
        pad_to = (max(len(tr) for tr, _ in splits.values()),
                  max(len(te) for _, te in splits.values()))
        items = [items[int(c)] for c in client_filter]
    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    for s, (tr, te) in items:
        xs_tr.append(_gather_rows(X, tr))
        ys_tr.append(y[tr])
        xs_te.append(_gather_rows(X, te))
        ys_te.append(y[te])
        logger.info("site %s: %d train / %d test", s, len(tr), len(te))
    _close_if_h5(X)
    ids = (list(range(len(splits))) if client_filter is None
           else [int(c) for c in client_filter])
    return _finalize(xs_tr, ys_tr, xs_te, ys_te, val_fraction, seed,
                     normalize, layout, pad_to=pad_to, client_ids=ids,
                     s2d_spec=s2d_spec)


def load_partition_data_abcd_rescale(
    data_path: str,
    client_number: int,
    val_fraction: float = 0.0,
    normalize: bool = False,
    seed: int = ABCD_SPLIT_SEED,
    layout: str = "channels",
    client_filter=None,
    s2d_spec=None,
) -> FederatedData:
    """Merge all sites' train/test pools (site order), then contiguous equal
    reshard to ``client_number`` clients — ``data_loader.py:220-319``. Client
    i's train rows are ``[i*s, (i+1)*s)`` of the merged train pool and its
    test rows the matching 20%-scaled window of the merged test pool
    (``data_loader.py:286-296``)."""
    X, y, site = load_abcd_h5(data_path)
    splits = site_train_test_split(site, seed=seed)
    tr_idx = np.concatenate([tr for tr, _ in splits.values()])
    te_idx = np.concatenate([te for _, te in splits.values()])

    s_tr = len(tr_idx) // client_number
    clients = (range(client_number) if client_filter is None
               else [int(c) for c in client_filter])
    pad_to = None
    if client_filter is not None:
        # test windows vary by +-1 row from the int() rounding — pad to
        # the global maxima so all processes agree on shapes
        te_sizes = [int((c + 1) * s_tr * ABCD_TEST_RATIO)
                    - int(c * s_tr * ABCD_TEST_RATIO)
                    for c in range(client_number)]
        pad_to = (s_tr, max(te_sizes))
    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    for c in clients:
        rows_tr = tr_idx[c * s_tr: (c + 1) * s_tr]
        lo = int(c * s_tr * ABCD_TEST_RATIO)
        hi = int((c + 1) * s_tr * ABCD_TEST_RATIO)
        rows_te = te_idx[lo:hi]
        xs_tr.append(_gather_rows(X, rows_tr))
        ys_tr.append(y[np.sort(rows_tr)])
        xs_te.append(_gather_rows(X, rows_te))
        ys_te.append(y[np.sort(rows_te)])
        logger.info("client %d: %d train / %d test", c, len(rows_tr),
                    len(rows_te))
    _close_if_h5(X)
    return _finalize(xs_tr, ys_tr, xs_te, ys_te, val_fraction, seed,
                     normalize, layout, pad_to=pad_to, s2d_spec=s2d_spec,
                     client_ids=list(clients))


def _close_if_h5(X) -> None:
    f = getattr(X, "file", None)
    if f is not None:
        try:
            f.close()
        except Exception:  # pragma: no cover
            pass


def write_abcd_h5(path: str, X: np.ndarray, y: np.ndarray,
                  site: np.ndarray) -> None:
    """Write a cohort file in the layout ``load_abcd_h5`` expects
    (keys X/y/site — the format ``Preprocess_ABCD.ipynb`` cell 31 produces)."""
    import h5py

    with h5py.File(path, "w") as f:
        f.create_dataset("X", data=np.asarray(X, np.float32),
                         chunks=(1,) + tuple(np.asarray(X).shape[1:]))
        f.create_dataset("y", data=np.asarray(y))
        f.create_dataset("site", data=np.asarray(site))
