"""Federated dataset container.

Replaces the reference's 8-element dataset list
(``train_data_num, test_data_num, train_data_global, test_data_global,
train_data_local_num_dict, train_data_local_dict, test_data_local_dict,
class_num`` — ``ABCD/data_loader.py:164-216``) with a single device-ready
pytree: per-client shards padded to a common length with valid-count vectors,
so the whole cohort ships to the mesh as stacked arrays sharded over the
``clients`` axis.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class FederatedData:
    """Stacked per-client shards.

    x_train: [C, n_max, *sample_shape]   y_train: [C, n_max]
    x_test:  [C, m_max, *sample_shape]   y_test:  [C, m_max]
    n_train, n_test: [C] int32 valid counts
    x_val/y_val/n_val: optional per-client validation split (FedFomo needs
    one — the reference's 9-element ``data_val_loader`` variant,
    ``cifar10/data_val_loader.py:275-326``).
    """

    x_train: jax.Array
    y_train: jax.Array
    n_train: jax.Array
    x_test: jax.Array
    y_test: jax.Array
    n_test: jax.Array
    class_num: int = struct.field(pytree_node=False, default=2)
    x_val: Optional[jax.Array] = None
    y_val: Optional[jax.Array] = None
    n_val: Optional[jax.Array] = None
    # Training-time augmentation contract (the 2D image loaders set this):
    # per-channel value of a BLACK padding pixel in this dataset's
    # normalized space, i.e. (0 - mean) / std. Non-None marks the dataset
    # as crop+flip-augmentable with the reference's RandomCrop(H, padding=4)
    # + RandomHorizontalFlip pipeline (cifar10/data_loader.py:46-50, where
    # torchvision pads the RAW image with 0 BEFORE ToTensor+Normalize —
    # so the padded ring is -mean/std after normalization, not 0).
    aug_pad_value: Optional[tuple] = struct.field(
        pytree_node=False, default=None)

    @property
    def num_clients(self) -> int:
        return self.x_train.shape[0]

    @property
    def sample_shape(self):
        return self.x_train.shape[2:]


def pad_stack(arrays, pad_to=None, dtype=None):
    """Stack variable-length per-client arrays into [C, n_max, ...] + counts."""
    import numpy as np

    n = [len(a) for a in arrays]
    n_max = pad_to or max(n)
    first = np.asarray(arrays[0])
    out = np.zeros((len(arrays), n_max) + first.shape[1:],
                   dtype or first.dtype)
    for i, a in enumerate(arrays):
        a = np.asarray(a)
        out[i, : len(a)] = a
    return jnp.asarray(out), jnp.asarray(np.array(n, np.int32))
