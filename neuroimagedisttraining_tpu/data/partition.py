"""Non-IID partitioners: sample-index assignment per client.

TPU-native rebuild of the reference's two partitioner families:

* the LDA/Dirichlet partitioner of
  ``fedml_core/non_iid_partition/noniid_partition.py:6-103``
  (``non_iid_partition_with_dirichlet_distribution`` +
  ``partition_class_samples_with_dirichlet_distribution`` +
  ``record_data_stats``), and
* the class-prior samplers of
  ``fedml_api/data_preprocessing/cifar10/data_loader.py:75-195``
  (``partition == 'n_cls' | 'dir' | 'my_part'`` — lognormal client sizes,
  per-client class priors, sequential draw with class depletion), plus the
  per-client proportional *test* resampling of
  ``load_partition_data_cifar10`` (``data_loader.py:208-250``).

Everything here is pure numpy on host (partitioning is a one-time setup cost,
negligible next to training); outputs are index arrays that feed
``FederatedData`` stacking so the actual tensors ship to the device mesh once.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# LDA / Dirichlet partition (noniid_partition.py parity)
# ---------------------------------------------------------------------------

def dirichlet_partition(
    labels: np.ndarray,
    n_clients: int,
    n_classes: int,
    alpha: float,
    min_size: int = 10,
    rng: Optional[np.random.RandomState] = None,
) -> Dict[int, np.ndarray]:
    """Latent-Dirichlet-Allocation non-IID split (arXiv:1909.06335).

    For each class k, draw client proportions ~ Dir(alpha) and split class-k
    indices accordingly; retry whole assignments until every client holds at
    least ``min_size`` samples — the semantics of
    ``non_iid_partition_with_dirichlet_distribution``
    (``noniid_partition.py:42-73``), including the balancing rule that zeroes
    a client's proportion once it already holds >= N/n_clients samples
    (``noniid_partition.py:84-86``).
    """
    labels = np.asarray(labels).ravel()
    n = labels.shape[0]
    rng = rng or np.random.RandomState()
    current_min = 0
    batches: List[List[int]] = []
    while current_min < min_size:
        batches = [[] for _ in range(n_clients)]
        for k in range(n_classes):
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            props = rng.dirichlet(np.repeat(alpha, n_clients))
            # cap already-full clients (reference's load-balancing trick)
            full = np.array([len(b) >= n / n_clients for b in batches])
            props = np.where(full, 0.0, props)
            props = props / props.sum()
            cuts = (np.cumsum(props) * len(idx_k)).astype(int)[:-1]
            for b, chunk in zip(batches, np.split(idx_k, cuts)):
                b.extend(chunk.tolist())
        current_min = min(len(b) for b in batches)
    out = {}
    for i, b in enumerate(batches):
        arr = np.array(b, dtype=np.int64)
        rng.shuffle(arr)
        out[i] = arr
    return out


def record_data_stats(
    labels: np.ndarray, mapping: Dict[int, np.ndarray]
) -> Dict[int, Dict[int, int]]:
    """Per-client class histogram (``record_data_stats``,
    ``noniid_partition.py:94-103``)."""
    labels = np.asarray(labels).ravel()
    stats = {}
    for client, idx in mapping.items():
        unq, cnt = np.unique(labels[np.asarray(idx, dtype=np.int64)],
                             return_counts=True)
        stats[client] = {int(u): int(c) for u, c in zip(unq, cnt)}
    logger.debug("Data statistics: %s", stats)
    return stats


# ---------------------------------------------------------------------------
# Class-prior partitions ('n_cls' / 'dir' / 'my_part' modes)
# ---------------------------------------------------------------------------

def _draw_with_priors(
    labels: np.ndarray,
    n_clients: int,
    n_classes: int,
    cls_priors: np.ndarray,
    rng: np.random.RandomState,
) -> Dict[int, np.ndarray]:
    """Assign every training index to a client according to per-client class
    priors, with class depletion.

    Vectorized equivalent of the reference's one-sample-at-a-time
    draw-until-valid loop (``cifar10/data_loader.py:97-115`` et al.): instead
    of N sequential coin flips we (1) give every client an equal target size
    (the reference's lognormal(sigma=0) collapses to exactly that,
    ``data_loader.py:83-85``), (2) draw each client's class counts from a
    multinomial over its prior, then (3) repair overflow against the true
    per-class availability by redistributing excess to clients whose priors
    still want those classes. Same marginal behavior, O(C*K) instead of O(N).
    """
    labels = np.asarray(labels).ravel()
    n = labels.shape[0]
    class_avail = np.bincount(labels, minlength=n_classes).astype(np.int64)
    sizes = np.full(n_clients, n // n_clients, dtype=np.int64)
    sizes[: n % n_clients] += 1

    # target per-(client, class) counts from the priors
    want = np.zeros((n_clients, n_classes), dtype=np.int64)
    for c in range(n_clients):
        want[c] = rng.multinomial(sizes[c], cls_priors[c] / cls_priors[c].sum())

    # repair: scale down classes that are over-subscribed, topping up from
    # under-subscribed classes the client's prior allows
    for _ in range(n_classes + 2):
        total = want.sum(axis=0)
        over = total - class_avail
        changed = False
        for k in np.where(over > 0)[0]:
            # remove `over[k]` draws from class k, proportionally to holdings
            holders = np.where(want[:, k] > 0)[0]
            take = _proportional_take(want[holders, k], int(over[k]))
            want[holders, k] -= take
            changed = True
        if not changed:
            break
        # top-up clients back to their size from classes with spare capacity
        total = want.sum(axis=0)
        spare = class_avail - total
        for c in range(n_clients):
            deficit = int(sizes[c] - want[c].sum())
            if deficit <= 0:
                continue
            # top up only from classes the client's prior allows — clients
            # whose allowed classes are exhausted stay short rather than
            # receive off-prior samples (the reference instead re-draws
            # already-assigned indices, data_loader.py:109-111, i.e.
            # duplicates samples across clients; we keep shards disjoint)
            prefs = cls_priors[c] * (spare > 0)
            if prefs.sum() <= 0:
                continue
            add = rng.multinomial(deficit, prefs / prefs.sum())
            add = np.minimum(add, spare)
            want[c] += add
            spare -= add

    # materialize index assignment per class
    mapping: Dict[int, List[int]] = {c: [] for c in range(n_clients)}
    for k in range(n_classes):
        idx_k = np.where(labels == k)[0]
        rng.shuffle(idx_k)
        cursor = 0
        for c in range(n_clients):
            take = int(min(want[c, k], len(idx_k) - cursor))
            mapping[c].extend(idx_k[cursor: cursor + take].tolist())
            cursor += take
    out = {}
    for c in range(n_clients):
        arr = np.array(mapping[c], dtype=np.int64)
        rng.shuffle(arr)
        out[c] = arr
    return out


def _proportional_take(holdings: np.ndarray, amount: int) -> np.ndarray:
    """Remove ``amount`` units across ``holdings`` proportionally (largest
    remainders), never below zero."""
    if holdings.sum() <= amount:
        return holdings.copy()
    frac = holdings / holdings.sum() * amount
    take = np.floor(frac).astype(np.int64)
    rem = amount - take.sum()
    order = np.argsort(-(frac - take))
    for i in order[:rem]:
        if take[i] < holdings[i]:
            take[i] += 1
    return np.minimum(take, holdings)


def class_prior_partition(
    labels: np.ndarray,
    n_clients: int,
    n_classes: int,
    partition: str = "dir",
    alpha: float = 0.3,
    seed: Optional[int] = None,
) -> Dict[int, np.ndarray]:
    """The cifar-loader partition modes (``cifar10/data_loader.py:79-195``):

    * ``'n_cls'`` — each client uniform over ``int(alpha)`` randomly chosen
      classes (``data_loader.py:86-88``)
    * ``'dir'``   — per-client class prior ~ Dir(alpha)
      (``data_loader.py:124``)
    * ``'my_part'`` — ``int(alpha)`` shard groups; clients in a group share a
      Dir(0.3) prior (``data_loader.py:158-165``)
    * ``'homo'``  — IID equal random split
    """
    labels = np.asarray(labels).ravel()
    rng = np.random.RandomState(seed)
    if partition == "homo":
        idx = rng.permutation(labels.shape[0])
        return {c: np.sort(chunk).astype(np.int64)
                for c, chunk in enumerate(np.array_split(idx, n_clients))}
    if partition == "n_cls":
        k = max(1, int(alpha))
        priors = np.zeros((n_clients, n_classes))
        for c in range(n_clients):
            chosen = rng.choice(n_classes, size=k, replace=False)
            priors[c, chosen] = 1.0 / k
    elif partition == "dir":
        priors = rng.dirichlet([alpha] * n_classes, size=n_clients)
    elif partition == "my_part":
        n_shards = max(1, int(alpha))
        group_priors = rng.dirichlet([0.3] * n_classes, size=n_shards)
        group_of = (np.arange(n_clients) //
                    max(1, n_clients // n_shards)) % n_shards
        priors = group_priors[group_of]
    else:
        raise ValueError(f"unknown partition mode {partition!r}")
    return _draw_with_priors(labels, n_clients, n_classes, priors, rng)


# ---------------------------------------------------------------------------
# Proportional per-client test resampling
# ---------------------------------------------------------------------------

def proportional_test_indices(
    y_test: np.ndarray,
    train_cls_counts: Dict[int, Dict[int, int]],
    n_clients: int,
    n_classes: int,
    rng: Optional[np.random.RandomState] = None,
) -> Dict[int, np.ndarray]:
    """Give each client a test set whose label mix mirrors its *train* label
    histogram — the eval protocol of ``load_partition_data_cifar10``
    (``cifar10/data_loader.py:224-243``): per client, per label, draw
    ``ceil(train_frac_of_label * (n_test/n_clients))`` random test indices of
    that label (with replacement across clients, as in the reference)."""
    y_test = np.asarray(y_test).ravel()
    rng = rng or np.random.RandomState()
    idx_by_label = [np.where(y_test == k)[0] for k in range(n_classes)]
    per_client = int(np.ceil(len(y_test) / n_clients))
    out = {}
    for c in range(n_clients):
        counts = train_cls_counts.get(c, {})
        total = max(1, sum(counts.values()))
        picked = []
        for k in range(n_classes):
            frac = counts.get(k, 0) / total
            m = int(np.ceil(frac * per_client))
            if m == 0 or len(idx_by_label[k]) == 0:
                continue
            perm = rng.permutation(len(idx_by_label[k]))[:m]
            picked.append(idx_by_label[k][perm])
        out[c] = (np.concatenate(picked) if picked
                  else np.array([], dtype=np.int64))
    return out


# ---------------------------------------------------------------------------
# Site + contiguous partitions (ABCD semantics)
# ---------------------------------------------------------------------------

def site_partition(site: np.ndarray) -> Dict[int, np.ndarray]:
    """One client per unique acquisition site (the ABCD cross-silo mapping,
    ``ABCD/data_loader.py:183`` — the reference hardcodes 21 sites; here the
    client count follows the data)."""
    site = np.asarray(site).ravel()
    return {i: np.where(site == s)[0]
            for i, s in enumerate(np.unique(site))}


def contiguous_reshard(n_total: int, n_clients: int) -> Dict[int, np.ndarray]:
    """Equal contiguous shards of the merged cohort — the ``_rescale`` entry's
    resharding (``ABCD/data_loader.py:286-296``): client i gets
    ``[i*s, (i+1)*s)`` with ``s = n_total // n_clients`` (the remainder tail
    is dropped, as in the reference)."""
    s = n_total // n_clients
    return {i: np.arange(i * s, (i + 1) * s, dtype=np.int64)
            for i in range(n_clients)}
