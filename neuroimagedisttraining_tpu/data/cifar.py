"""CIFAR-10/100 federated loaders + device-side augmentation.

Rebuild of ``fedml_api/data_preprocessing/cifar10/`` and ``cifar100/``:

* raw loading <- ``load_cifar10_data`` (``cifar10/data_loader.py:63-72``)
  but reading the standard python-pickled batch files directly (no
  torchvision dependency);
* partition modes 'homo'/'n_cls'/'dir'/'my_part'
  (``data_loader.py:75-195``) via :mod:`.partition`;
* per-client proportional test resampling
  (``data_loader.py:224-243``) via
  :func:`.partition.proportional_test_indices`;
* the FedFomo validation variant (``data_val_loader.py:275-326``) via
  ``val_fraction=0.1``.

The reference's torchvision transform pipeline (RandomCrop(32,4) + flip +
normalize, ``data_loader.py:34-57``) runs per-sample on CPU; here
normalization is baked into the stacked arrays once and the random
crop/flip is a jittable batched op (:func:`random_crop_flip`) that fuses
into the device training step — the TPU-idiomatic replacement for a host
DataLoader worker pool.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, Optional, Tuple

import numpy as np

from .packing import partition_and_pack
from .types import FederatedData

# torchvision-normalization constants used by the reference
# (cifar10/data_loader.py:36-37, cifar100/data_loader.py)
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
CIFAR100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
CIFAR100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)


def _unpickle(path: str) -> dict:
    with open(path, "rb") as f:
        return pickle.load(f, encoding="latin1")


def load_cifar10_raw(data_dir: str):
    """Read the standard ``cifar-10-batches-py`` layout into NHWC uint8 +
    int labels."""
    base = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(base):
        base = data_dir
    xs, ys = [], []
    for i in range(1, 6):
        d = _unpickle(os.path.join(base, f"data_batch_{i}"))
        xs.append(d["data"])
        ys.extend(d["labels"])
    X_train = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y_train = np.asarray(ys, np.int32)
    d = _unpickle(os.path.join(base, "test_batch"))
    X_test = np.asarray(d["data"]).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y_test = np.asarray(d["labels"], np.int32)
    return X_train, y_train, X_test, y_test


def load_cifar100_raw(data_dir: str):
    base = os.path.join(data_dir, "cifar-100-python")
    if not os.path.isdir(base):
        base = data_dir
    d = _unpickle(os.path.join(base, "train"))
    X_train = np.asarray(d["data"]).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y_train = np.asarray(d["fine_labels"], np.int32)
    d = _unpickle(os.path.join(base, "test"))
    X_test = np.asarray(d["data"]).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    y_test = np.asarray(d["fine_labels"], np.int32)
    return X_train, y_train, X_test, y_test


def _normalize(x: np.ndarray, mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    return ((x.astype(np.float32) / 255.0) - mean) / std


def black_pad_value(mean: np.ndarray, std: np.ndarray) -> tuple:
    """Per-channel value of a BLACK padding pixel in normalized space:
    torchvision's RandomCrop pads the raw image with 0 BEFORE
    ToTensor+Normalize (``cifar10/data_loader.py:46-50``), so the padded
    ring lands at (0 - mean) / std. The loaders stamp this on
    ``FederatedData.aug_pad_value``."""
    return tuple(((0.0 - np.asarray(mean)) / np.asarray(std)).tolist())


def load_partition_data_cifar(
    data_dir: str,
    dataset: str = "cifar10",
    partition_method: str = "dir",
    partition_alpha: float = 0.3,
    client_number: int = 100,
    val_fraction: float = 0.0,
    seed: Optional[int] = None,
) -> FederatedData:
    """Federated CIFAR with the reference's partition + eval protocol
    (``load_partition_data_cifar10``, ``cifar10/data_loader.py:208-250``):
    train indices from the chosen partition mode; per-client test sets
    resampled proportional to the client's train label histogram."""
    if dataset == "cifar10":
        X_train, y_train, X_test, y_test = load_cifar10_raw(data_dir)
        mean, std, n_classes = CIFAR10_MEAN, CIFAR10_STD, 10
    elif dataset == "cifar100":
        X_train, y_train, X_test, y_test = load_cifar100_raw(data_dir)
        mean, std, n_classes = CIFAR100_MEAN, CIFAR100_STD, 100
    else:
        raise ValueError(f"unknown cifar dataset {dataset!r}")

    return partition_and_pack(
        _normalize(X_train, mean, std), y_train,
        _normalize(X_test, mean, std), y_test,
        n_classes, client_number, partition_method, partition_alpha,
        val_fraction, seed,
        aug_pad_value=black_pad_value(mean, std),
    )


def random_crop_flip(rng, batch, padding: int = 4, pad_value=None):
    """Jittable batched random crop (pad-and-slice) + horizontal flip.

    Device-side replacement for the reference's torchvision
    ``RandomCrop(32, padding=4) + RandomHorizontalFlip``
    (``cifar10/data_loader.py:46-50``): one fused op over the whole batch,
    traced inside the training step, so augmentation costs no host round-trip.

    ``pad_value``: per-channel constant for the padded ring. torchvision
    pads the RAW image with black (0) *before* ToTensor+Normalize, so in
    normalized space the ring is ``(0 - mean) / std`` — pass the dataset's
    :attr:`FederatedData.aug_pad_value` to reproduce that exactly. ``None``
    pads with 0 (the mean pixel in normalized space).
    """
    import jax
    import jax.numpy as jnp

    b, h, w, c = batch.shape
    k1, k2, k3 = jax.random.split(rng, 3)
    padded = jnp.pad(
        batch, ((0, 0), (padding, padding), (padding, padding), (0, 0)))
    if pad_value is not None:
        pv = jnp.asarray(pad_value, batch.dtype)
        ih = (jnp.arange(h + 2 * padding) >= padding) \
            & (jnp.arange(h + 2 * padding) < padding + h)
        iw = (jnp.arange(w + 2 * padding) >= padding) \
            & (jnp.arange(w + 2 * padding) < padding + w)
        interior = ih[:, None] & iw[None, :]
        # interior pixels pass through bit-exactly; only the ring is set
        padded = jnp.where(interior[None, :, :, None], padded, pv)
    dy = jax.random.randint(k1, (b,), 0, 2 * padding + 1)
    dx = jax.random.randint(k2, (b,), 0, 2 * padding + 1)

    def crop_one(img, y0, x0):
        return jax.lax.dynamic_slice(img, (y0, x0, 0), (h, w, c))

    cropped = jax.vmap(crop_one)(padded, dy, dx)
    flip = jax.random.bernoulli(k3, 0.5, (b,))
    flipped = jnp.where(flip[:, None, None, None],
                        cropped[:, :, ::-1, :], cropped)
    return flipped
