"""Shared partition -> pad-stack packing for image classification datasets.

Factors the common tail of the reference's per-dataset loaders
(``cifar10/data_loader.py:208-250``, ``tiny_imagenet/data_loader.py`` — the
same code copy-pasted per dataset): class-prior partition of train indices,
per-client test sets resampled proportional to the client's train label
histogram, optional FedFomo validation split, pad-stacked device arrays.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .partition import (
    class_prior_partition,
    proportional_test_indices,
    record_data_stats,
)
from .types import FederatedData, pad_stack


def partition_and_pack(
    X_train: np.ndarray,
    y_train: np.ndarray,
    X_test: np.ndarray,
    y_test: np.ndarray,
    n_classes: int,
    client_number: int,
    partition_method: str = "dir",
    partition_alpha: float = 0.3,
    val_fraction: float = 0.0,
    seed: Optional[int] = None,
    aug_pad_value: Optional[tuple] = None,
) -> FederatedData:
    mapping = class_prior_partition(
        y_train, client_number, n_classes, partition_method,
        partition_alpha, seed=seed,
    )
    cls_counts = record_data_stats(y_train, mapping)
    rng = np.random.RandomState(seed)
    test_map = proportional_test_indices(
        y_test, cls_counts, client_number, n_classes, rng=rng,
    )

    xs_tr = [X_train[mapping[c]] for c in range(client_number)]
    ys_tr = [y_train[mapping[c]] for c in range(client_number)]
    xs_te = [X_test[test_map[c]] for c in range(client_number)]
    ys_te = [y_test[test_map[c]] for c in range(client_number)]

    xs_va, ys_va = [], []
    if val_fraction > 0:
        # FedFomo's 9-tuple variant (cifar10/data_val_loader.py:275-279)
        new_x, new_y = [], []
        for x, y in zip(xs_tr, ys_tr):
            n_val = int(len(y) * val_fraction)
            perm = rng.permutation(len(y))
            new_x.append(x[perm[n_val:]])
            new_y.append(y[perm[n_val:]])
            xs_va.append(x[perm[:n_val]])
            ys_va.append(y[perm[:n_val]])
        xs_tr, ys_tr = new_x, new_y

    x_train, n_train = pad_stack(xs_tr)
    y_tr, _ = pad_stack([y.astype(np.int32) for y in ys_tr])
    x_test, n_test = pad_stack(xs_te)
    y_te, _ = pad_stack([y.astype(np.int32) for y in ys_te])
    kwargs = {}
    if val_fraction > 0:
        x_val, n_val = pad_stack(xs_va)
        y_va, _ = pad_stack([y.astype(np.int32) for y in ys_va])
        kwargs = dict(x_val=x_val, y_val=y_va, n_val=n_val)
    return FederatedData(
        x_train=x_train, y_train=y_tr, n_train=n_train,
        x_test=x_test, y_test=y_te, n_test=n_test,
        class_num=n_classes, aug_pad_value=aug_pad_value, **kwargs,
    )
