"""Synthetic ABCD-like federated data for tests and benchmarks.

Generates site-partitioned 3D "volumes" whose class signal is a linear probe
planted in the voxels, with per-site intensity shifts emulating acquisition-
site non-IIDness (the reason the reference partitions by site,
``ABCD/data_loader.py:67-102``). Used where the reference would load
``final_dataset_*subs.h5``; shapes default to small cubes for CI.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np
import jax.numpy as jnp

from .types import FederatedData, pad_stack


def make_synthetic_federated(
    seed: int = 42,
    n_clients: int = 8,
    samples_per_client: int = 24,
    test_per_client: int = 8,
    val_per_client: int = 0,
    sample_shape: Tuple[int, ...] = (8, 8, 8, 1),
    class_num: int = 2,
    loss_type: str = "bce",
    site_shift: float = 0.3,
    signal: float = 1.5,
    uneven: bool = True,
) -> FederatedData:
    rng = np.random.RandomState(seed)
    # Smooth, positive "anatomical" probe pattern: a constant component plus
    # low-frequency structure, RMS-normalized. Class k shifts the volume along
    # this pattern — a conv net can recover it from few samples (a pure
    # white-noise probe would make the task information-theoretically hard at
    # CI sample counts).
    probe = 1.0 + 0.5 * np.abs(rng.randn(*sample_shape)).astype(np.float32)
    probe /= np.sqrt(np.mean(probe**2))

    xs_tr, ys_tr, xs_te, ys_te = [], [], [], []
    xs_va, ys_va = [], []
    for c in range(n_clients):
        n_tr = samples_per_client + (rng.randint(0, samples_per_client // 2 + 1)
                                     if uneven else 0)
        n_te = test_per_client
        n_va = val_per_client
        n = n_tr + n_te + n_va
        y = rng.randint(0, class_num, size=n)
        x = rng.randn(n, *sample_shape).astype(np.float32)
        x += site_shift * rng.randn()  # per-site intensity shift (non-IID)
        # plant signal: class k shifts along the probe direction
        coef = (y - (class_num - 1) / 2.0).astype(np.float32)
        x += signal * coef[(...,) + (None,) * len(sample_shape)] * probe
        xs_tr.append(x[:n_tr])
        ys_tr.append(y[:n_tr])
        xs_te.append(x[n_tr:n_tr + n_te])
        ys_te.append(y[n_tr:n_tr + n_te])
        xs_va.append(x[n_tr + n_te:])
        ys_va.append(y[n_tr + n_te:])

    x_train, n_train = pad_stack(xs_tr)
    y_train, _ = pad_stack([y.astype(np.int32) for y in ys_tr])
    x_test, n_test = pad_stack(xs_te)
    y_test, _ = pad_stack([y.astype(np.int32) for y in ys_te])
    kwargs = {}
    if val_per_client:
        x_val, n_val = pad_stack(xs_va)
        y_val, _ = pad_stack([y.astype(np.int32) for y in ys_va])
        kwargs = dict(x_val=x_val, y_val=y_val, n_val=n_val)
    return FederatedData(
        x_train=x_train, y_train=y_train, n_train=n_train,
        x_test=x_test, y_test=y_test, n_test=n_test,
        class_num=class_num, **kwargs,
    )
