"""Checkpoint publisher: the training-side model push hook.

``CheckpointPublisher.publish(params, version)`` does three things in
one deterministic motion:

1. **Encode** the update for the wire. The first publish ships the
   full params dense (nothing exists to delta from); every later one
   ships ``params - base`` in a ``fed/wire`` codec (int8 default,
   ``--serve_wire``), where ``base`` is the previous *reconstructed*
   version.
2. **Reconstruct** the servable model by decoding its own payload:
   ``base' = base + decode(encode(delta))``. The lossy impls lose
   precision exactly once, at encode — so the worker decoding the
   identical payload lands on the identical float32 bytes. This is the
   error-feedback trick from the top-k wire applied to model pushes:
   quantization error is carried in ``params - base`` and re-shipped
   next version, it never compounds silently.
3. **Checkpoint** the reconstruction to disk (atomic tmp+rename,
   ``comm/message.py`` binary pytree framing — the same serializer the
   wire uses, so "bit-identical to loading the checkpoint from disk"
   is a structural property, not a numerical hope).

The worker ACKs each adopted version (``serve_ack``); ``wait_acked``
is the publisher's pacing/accounting hook and the smoke's proof that
>= N pushes actually landed.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..comm.manager import ServerManager
from ..comm.message import Message
from ..fed import protocol, wire
from ..fed.protocol import send_with_retry
from ..obs import xtrace
from ..obs.xtrace import XTracer
from . import (MSG_SERVE_ACK, MSG_SERVE_FINISH, MSG_SERVE_PUSH,
               PUSH_WIRE_IMPLS)

logger = logging.getLogger(__name__)


def _np_f32_tree(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32), tree)


def _tree_add(a: Any, b: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(
        lambda x, y: (np.asarray(x, np.float32)
                      + np.asarray(y, np.float32)), a, b)


def _tree_sub(a: Any, b: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(
        lambda x, y: (np.asarray(x, np.float32)
                      - np.asarray(y, np.float32)), a, b)


# -- checkpoint files ----------------------------------------------------

def checkpoint_path(ckpt_dir: str, version: int) -> str:
    return os.path.join(ckpt_dir, f"model_v{int(version):05d}.bin")


def save_checkpoint(ckpt_dir: str, version: int, params: Any) -> str:
    """Write one servable model version (atomic: a concurrent loader
    never sees a torn file)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    msg = Message("serve_ckpt", 0, 0)
    msg.add("version", int(version))
    msg.add_tensor("params", _np_f32_tree(params))
    path = checkpoint_path(ckpt_dir, version)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(msg.to_bytes())
    os.replace(tmp, path)
    return path


def load_checkpoint(path: str) -> Tuple[int, Any]:
    """``(version, params)`` — the disk half of the bit-identity
    contract the smoke gates."""
    with open(path, "rb") as f:
        msg = Message.from_bytes(f.read())
    return int(msg.get("version")), msg.get_tensor("params")


class CheckpointPublisher(ServerManager):
    """Rank-0 manager the training loop calls ``publish`` on.

    ``worker_ranks`` is the fan-out set (default ``[worker_rank]``):
    every push/finish broadcasts to each subscribed worker, ACKs keep
    a **per-rank watermark**, and ``wait_acked`` waits for the SLOWEST
    subscriber — pacing degrades to the laggard, never past it.
    ``heartbeat_every > 0`` arms a :class:`obs.live.FleetLedger` over
    the workers (peer ``worker<rank>``), fed by their standalone
    HEARTBEAT frames and the gauge snapshots piggybacked on ACKs.
    """

    def __init__(self, comm, rank: int = 0, world_size: int = 2,
                 worker_rank: int = 1,
                 worker_ranks: Optional[List[int]] = None,
                 ckpt_dir: str = "",
                 wire_impl: str = "int8", retries: int = 2,
                 backoff_s: float = 0.05,
                 tracer: Optional[XTracer] = None,
                 heartbeat_every: float = 0.0):
        super().__init__(comm, rank=rank, world_size=world_size)
        if wire_impl not in PUSH_WIRE_IMPLS:
            raise ValueError(
                f"push wire {wire_impl!r} not in {PUSH_WIRE_IMPLS}")
        self.worker_ranks = [int(r) for r in (
            worker_ranks if worker_ranks else [worker_rank])]
        self.worker_rank = self.worker_ranks[0]
        self.ckpt_dir = ckpt_dir
        self.wire_impl = wire_impl
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.tracer = tracer
        self._base: Optional[Any] = None  # last reconstructed version
        self.pushes = 0
        self.bytes_pushed = 0
        self._ack_cond = threading.Condition()
        self._acked = {r: -1 for r in self.worker_ranks}
        self.ledger = None
        if float(heartbeat_every) > 0:
            from ..obs import live as obs_live

            self.ledger = obs_live.FleetLedger(float(heartbeat_every))
            now = time.monotonic()
            for r in self.worker_ranks:
                self.ledger.register(f"worker{r}", now)
        self._ledger_lock = threading.Lock()
        self.register_message_receive_handler(MSG_SERVE_ACK,
                                              self._on_ack)
        # clock-sync echo for the worker-initiated HELLO (the serving
        # plane's reference clock is the publisher); registered
        # unconditionally, only ever exercised when tracing is on
        self.register_message_receive_handler(
            protocol.MSG_FED_HELLO, self._on_hello)
        # liveness frames: same inert-unless-sent idiom as the HELLO
        self.register_message_receive_handler(
            protocol.MSG_FED_HEARTBEAT, self._on_heartbeat)

    # -- protocol ---------------------------------------------------------
    def _on_hello(self, msg: Message) -> None:
        t1 = self.tracer.wall_ns() if self.tracer is not None \
            else time.time_ns()
        reply = protocol.hello_ack(msg, self.rank, self.rank, t1)
        send_with_retry(self, reply, retries=self.retries,
                        backoff_s=self.backoff_s)

    def _observe_heartbeat(self, msg: Message) -> None:
        if self.ledger is None:
            return
        from ..obs import live as obs_live

        hb = obs_live.extract_heartbeat(msg)
        if hb is None:
            return
        with self._ledger_lock:
            events = self.ledger.observe(
                hb["peer"], time.monotonic(), hb["round"], hb["gauges"])
            events += self.ledger.tick(time.monotonic())
        for ev in events:
            logger.warning("serve fleet: %s %s", ev.type, ev.message)

    def _on_heartbeat(self, msg: Message) -> None:
        self._observe_heartbeat(msg)

    def _on_ack(self, msg: Message) -> None:
        self._observe_heartbeat(msg)
        rank = int(msg.sender_id)
        with self._ack_cond:
            if rank not in self._acked:
                self._acked[rank] = -1  # late subscriber: track anyway
            self._acked[rank] = max(self._acked[rank],
                                    int(msg.get("version")))
            self._ack_cond.notify_all()

    @property
    def acked_version(self) -> int:
        """The fleet watermark: the highest version EVERY worker has
        adopted (the slowest subscriber's ack)."""
        with self._ack_cond:
            return min(self._acked.values())

    def acked_versions(self) -> Dict[int, int]:
        """Per-rank ack watermarks (the fan-out accounting view)."""
        with self._ack_cond:
            return dict(self._acked)

    def wait_acked(self, version: int, timeout_s: float = 30.0) -> bool:
        deadline = time.perf_counter() + float(timeout_s)
        with self._ack_cond:
            while min(self._acked.values()) < int(version):
                left = deadline - time.perf_counter()
                if left <= 0:
                    return False
                self._ack_cond.wait(left)
        return True

    # -- the push ---------------------------------------------------------
    def _retarget(self, msg: Message, receiver: int) -> Message:
        """A routing clone: the SAME encoded payload (params copied
        minus the routing triple, tensor trees shared read-only)
        addressed to another subscriber — every worker decodes
        byte-identical wire content, the fan-out's bit-identity
        anchor."""
        out = Message(msg.type, self.rank, int(receiver))
        for k, v in msg.params.items():
            if k not in (Message.ARG_TYPE, Message.ARG_SENDER,
                         Message.ARG_RECEIVER):
                out.params[k] = v
        out.tensors = dict(msg.tensors)
        return out

    def publish(self, params: Any, version: int) -> str:
        """Ship one model version to every subscribed worker and
        checkpoint the reconstruction; returns the checkpoint path (''
        if ckpt_dir is unset). The encode (and the reconstruction-chain
        advance) runs ONCE per version regardless of fan-out width."""
        with xtrace.xspan(self.tracer, "publish",
                          trace_id=f"v{int(version)}",
                          args={"version": int(version)}) as pspan:
            params = _np_f32_tree(params)
            msg = Message(MSG_SERVE_PUSH, self.rank,
                          self.worker_ranks[0])
            msg.add("version", int(version))
            with xtrace.xspan(self.tracer, "encode"):
                if self._base is None:
                    # the baseline: full params, dense — bit-exact by
                    # construction, and the only push that may not be a
                    # delta
                    msg.add("kind", "full")
                    wire.encode_update(msg, params, "dense", key="delta")
                    self._base = wire.decode_update(msg, key="delta")
                else:
                    delta = _tree_sub(params, self._base)
                    msg.add("kind", "delta")
                    wire.encode_update(msg, delta, self.wire_impl,
                                       key="delta")
                    # decode OUR OWN payload: the worker's
                    # reconstruction twin
                    self._base = _tree_add(
                        self._base, wire.decode_update(msg, key="delta"))
            if self.tracer is not None:
                # the workers' adopt spans parent to THIS publish; the
                # send stamp is their adopt-lag input
                xtrace.inject(msg, pspan.ctx(),
                              wall_ns=self.tracer.wall_ns())
            payload = msg.to_bytes()
            self.bytes_pushed += len(payload) * len(self.worker_ranks)
            send_with_retry(self, msg, retries=self.retries,
                            backoff_s=self.backoff_s)
            for r in self.worker_ranks[1:]:
                send_with_retry(self, self._retarget(msg, r),
                                retries=self.retries,
                                backoff_s=self.backoff_s)
            self.pushes += 1
            path = ""
            if self.ckpt_dir:
                with xtrace.xspan(self.tracer, "checkpoint"):
                    path = save_checkpoint(self.ckpt_dir, version,
                                           self._base)
        if self.ledger is not None:
            with self._ledger_lock:
                self.ledger.note_round(int(version))
                events = self.ledger.tick(time.monotonic())
            for ev in events:
                logger.warning("serve fleet: %s %s", ev.type,
                               ev.message)
        logger.info("serve publish v%d -> %d worker(s): %s wire, %d B%s",
                    version, len(self.worker_ranks), msg.get("kind"),
                    len(payload), f" -> {path}" if path else "")
        return path

    def finish_worker(self) -> None:
        """Tell every worker to drain and exit (``serve_finish``)."""
        with xtrace.xspan(self.tracer, "finish",
                          trace_id="finish") as fin:
            for r in self.worker_ranks:
                msg = Message(MSG_SERVE_FINISH, self.rank, r)
                if self.tracer is not None:
                    xtrace.inject(msg, fin.ctx(),
                                  wall_ns=self.tracer.wall_ns())
                send_with_retry(self, msg, retries=self.retries,
                                backoff_s=self.backoff_s)

    def fleet_snapshot(self) -> Optional[Dict[str, Any]]:
        """The ledger's point-in-time fleet view (None when heartbeats
        are off) — the serve runtime's ``fleet.json`` source."""
        if self.ledger is None:
            return None
        with self._ledger_lock:
            return self.ledger.snapshot(time.monotonic())

    @property
    def servable_params(self) -> Optional[Any]:
        """The current reconstructed model — what the worker serves
        after adopting the latest push (and what the checkpoint
        holds)."""
        return self._base
