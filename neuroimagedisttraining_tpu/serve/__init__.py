"""Serving subsystem: checkpoint-streaming inference under load.

The inference half of the production story (ROADMAP item 3): a
**serving worker** answers batched per-client inference requests
against personal models held in a ``core/client_store.py`` tier (disk
population, host-RAM LRU hot set, device-resident ``[B, model]`` slab
per micro-batch), while a live **training run streams checkpoints** to
it as ``fed/wire`` delta pushes over the real comm backends — the same
codecs, transports, and retry machinery the federation runs on.

Star-of-two topology: rank 0 = the **publisher** (the training
process; ``publisher.py`` hooks its round loop), rank 1 = the
**worker** (``worker.py``). Messages (``comm/message.py`` binary
pytree framing):

* ``serve_push`` (publisher -> worker): one model version. The first
  push ships the full params dense (the baseline nothing can delta
  from); every later push ships the delta against the previous
  *reconstructed* version in a ``fed/wire.py`` codec (int8 by
  default). Both ends apply the identical decode to the identical
  payload, so the worker's swapped model is bit-identical to the
  checkpoint the publisher writes to disk — even through the lossy
  int8 encode (lossy exactly once, at encode; the reconstruction
  chain is shared).
* ``serve_ack`` (worker -> publisher): version adopted — the
  publisher's pacing/accounting signal.
* ``serve_finish`` (publisher -> worker): drain the request queue,
  write the final record, exit.

Traffic is synthetic but adversarially shaped: ``traffic.py`` draws
(client, sample) requests from ``data/synthetic.py`` volumes under a
Zipf-skewed client popularity (the head-heavy profile that exercises
the store's LRU), open-loop at a target requests/sec. ``batcher.py``
coalesces them into micro-batches for the one vmapped jitted forward.

Everything is wired into the existing production machinery: per-tick
records flow through a real ``obs.export.ObsSession`` (JSONL stream,
metrics registry, the PR 10 SLO engine on ``serve_latency_ms``-style
objectives, typed events, run catalog), and every ``--serve_*`` flag
is census-classified inert — serving never touches training lineage.
"""
from __future__ import annotations

MSG_SERVE_PUSH = "serve_push"
MSG_SERVE_ACK = "serve_ack"
MSG_SERVE_FINISH = "serve_finish"

#: PRNG domain separator for serving-plane draws ("srv" in ascii) —
#: the FED_SALT idiom, a different constant so traffic/popularity
#: draws never collide with training or fault key chains.
SERVE_SALT = 0x737276

#: wire codecs a model push may ride (``fed/wire.py``; topk is a
#: gradient-sparsity format — a *parameter* delta is dense by nature,
#: so the push path offers the dense/bf16/int8 family only)
PUSH_WIRE_IMPLS = ("dense", "bf16", "int8")

from .batcher import MicroBatcher, ServeRequest  # noqa: E402
from .publisher import (CheckpointPublisher, load_checkpoint,  # noqa: E402
                        save_checkpoint)
from .traffic import TrafficGenerator  # noqa: E402
from .worker import ServeWorker  # noqa: E402

__all__ = [
    "MSG_SERVE_PUSH", "MSG_SERVE_ACK", "MSG_SERVE_FINISH",
    "SERVE_SALT", "PUSH_WIRE_IMPLS",
    "MicroBatcher", "ServeRequest", "TrafficGenerator",
    "CheckpointPublisher", "save_checkpoint", "load_checkpoint",
    "ServeWorker",
]
