"""ServeWorker: the rank-1 inference process.

Three planes, one class:

* **Model plane** — ``serve_push`` handler: decode the ``fed/wire``
  payload, reconstruct (full push = the new base; delta push =
  ``base + decode(delta)``, the publisher's reconstruction twin), and
  swap atomically under a lock: requests batched before the swap ran
  on the old version, requests after it run on the new one, nothing
  ever sees a half-updated tree. The swap is host-side (the device
  copy is ``jax.device_put`` of the finished tree), so "atomic" is a
  single reference assignment.
* **Data plane** — the serve loop: pull a micro-batch, gather the
  clients' personal-delta rows from the ``core/client_store.py`` tier
  (disk population, host LRU hot set — the hit/miss counters become
  the per-tick ``serve_hit_rate`` gauge), pad to the fixed slab width
  (one compiled shape; padding rows are replicas, their outputs
  dropped), run the ONE vmapped jitted forward
  ``vmap(apply(g + delta_c, x_c))``, block, stamp latencies.
* **Obs plane** — every tick writes one record through a real
  ``obs.export.ObsSession`` (``record_round`` with the tick index as
  the round key): latency/throughput/hit-rate/staleness/version land
  on the JSONL line, the SLO engine evaluates objectives like
  ``p99:serve_latency_ms<50@w=200`` live, breaches become typed
  events, and the catalog entry at close carries the serving gauges.

The drain contract (the satellite-6 fix rides it): on
``serve_finish`` the loop finishes the queue, writes a final
``{"round": -1, "serve_drained": true, ...}`` totals record — the
serving stream's graceful-completion trace, which both the live
session (``finish()`` -> ``completed=true``) and the offline catalog
rebuild (``obs/catalog.py entry_from_run``) recognize.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..comm.manager import ClientManager
from ..comm.message import Message
from ..fed import protocol, wire
from ..fed.protocol import send_with_retry
from ..obs import live as obs_live, xtrace
from ..obs.xtrace import XTracer
from . import MSG_SERVE_ACK, MSG_SERVE_FINISH, MSG_SERVE_PUSH
from .batcher import MicroBatcher

logger = logging.getLogger(__name__)

#: store field holding each client's personal delta against the global
#: model (served params = global + delta_c; unwritten rows synthesize
#: byte-exact zeros — an unpersonalized client serves the global model)
PERSONAL_FIELD = "personal_delta"


class ServeWorker(ClientManager):
    """``apply_fn`` is the algorithm's own
    (``models.make_apply_fn``) so serving runs the exact training
    forward; ``init_params`` seeds version 0 (served until the first
    push lands); ``data_x``/``data_n`` are the synthetic volumes the
    requests index."""

    def __init__(self, comm, rank: int, world_size: int, apply_fn,
                 init_params: Any, store, data_x, data_n,
                 batcher: MicroBatcher, session=None,
                 retries: int = 2, backoff_s: float = 0.05,
                 tracer: Optional[XTracer] = None,
                 probe_every: int = 0,
                 probe_data: Optional[Tuple[Any, Any]] = None,
                 heartbeat: Optional[
                     obs_live.HeartbeatConfig] = None):
        super().__init__(comm, rank=rank, world_size=world_size)
        import jax

        self.apply_fn = apply_fn
        self.store = store
        self.data_x = np.asarray(data_x)
        self.data_n = np.asarray(data_n)
        self.batcher = batcher
        self.session = session
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.tracer = tracer
        # accuracy-under-staleness probe: every ``probe_every`` ticks
        # run the CURRENT served model over a fixed labeled probe set
        # and stamp ``serve_probe_acc`` beside the tick's
        # ``serve_model_staleness_s`` (the analyzer joins the pairs)
        self.probe_every = int(probe_every)
        self._probe_x = self._probe_y = None
        if probe_data is not None:
            self._probe_x = np.asarray(probe_data[0])
            self._probe_y = np.asarray(probe_data[1])
        self._jprobe = None
        self._last_adopt_lag_ms: Optional[float] = None
        self._hello_acks: "queue.Queue[Dict[str, float]]" = queue.Queue()
        # model plane
        self._swap_lock = threading.Lock()
        self._g_host = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32), init_params)
        self._g_dev = jax.device_put(self._g_host)
        self.version = 0
        self._last_swap_t = time.perf_counter()
        self.pushes_adopted = 0
        # data plane
        self.done = threading.Event()       # serve_finish received
        self.traffic_done = threading.Event()  # all requests submitted
        self.drained = threading.Event()    # serve loop exited
        self.requests_served = 0
        self.batches_served = 0
        self._hits0 = self._miss0 = 0.0
        self._t_prev_tick: Optional[float] = None

        def _serve_batch(deltas, x, g):
            def one(delta, xi):
                p = jax.tree_util.tree_map(
                    lambda a, b: a + b, g, delta)
                return self.apply_fn(p, xi[None], False, None)[0]

            return jax.vmap(one)(deltas, x)

        self._jserve = jax.jit(_serve_batch)
        self.register_message_receive_handler(MSG_SERVE_PUSH,
                                              self._on_push)
        self.register_message_receive_handler(MSG_SERVE_FINISH,
                                              self._on_finish)
        self.register_message_receive_handler(
            protocol.MSG_FED_HELLO_ACK, self._on_hello_ack)
        # live telemetry: ACKs carry a piggybacked gauge snapshot and a
        # daemon thread emits standalone HEARTBEAT frames toward the
        # publisher's fleet ledger (--obs_heartbeat_every only — every
        # wire stays byte-inert otherwise, the HELLO/xtrace contract)
        self.heartbeat = heartbeat
        # our own threads (receive pump + heartbeat emitter + the
        # caller's clock_sync) must not interleave sends on the shared
        # transport
        self._send_lock = threading.Lock()
        self._hb_thread: Optional[threading.Thread] = None
        if heartbeat is not None:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                name=f"hb:worker{rank}", daemon=True)
            self._hb_thread.start()

    # -- live telemetry ---------------------------------------------------
    def _heartbeat_loop(self) -> None:
        """Best-effort by design — a LOST heartbeat is exactly the
        signal the fleet ledger detects, so send failures are
        swallowed, never retried."""
        hb = self.heartbeat
        while not self.done.wait(hb.every_s):
            from ..obs.memory import host_rss

            hb.note("mem_rss_mb", host_rss()["rss_bytes"] / 1e6)
            hb.note("serve_queue_depth", self.batcher.depth())
            hb.note("serve_requests_total", self.requests_served)
            hb.note("comm_messages_sent",
                    self.comm.counters.messages_sent)
            hb.note("comm_bytes_sent", self.comm.counters.bytes_sent)
            try:
                with self._send_lock:
                    self.send_message(protocol.heartbeat_message(
                        self.rank, 0, hb))
            except OSError:
                pass  # publisher draining/gone: the ledger's problem

    def prom_snapshot(self) -> Dict[str, Any]:
        """The worker's ``/metrics`` source: the session registry
        (latency/throughput/hit-rate distributions and gauges) joined
        with the transport counters — rendered by ``obs/prom.py`` at
        scrape time."""
        snap: Dict[str, Any] = {}
        if self.session is not None:
            snap.update(self.session.registry.snapshot())
        for k, v in self.comm.counters.snapshot().items():
            snap[k] = {"type": "counter", "value": float(v)}
        return snap

    # -- clock sync (xtrace-gated) ----------------------------------------
    def _on_hello_ack(self, msg: Message) -> None:
        t2 = self.tracer.wall_ns() if self.tracer is not None \
            else time.time_ns()
        self._hello_acks.put({"t0": float(msg.get("t0_ns", 0)),
                              "t1": float(msg.get("t1_ns", 0)),
                              "t2": float(t2)})

    def clock_sync(self, timeout_s: float = 10.0) -> bool:
        """Worker-initiated HELLO toward the publisher (the serving
        plane's reference clock): the NTP-midpoint estimate lands on
        ``tracer.offset_ns`` as THIS clock minus the publisher's —
        adopt-lag and the merged-trace lane alignment both key off it.
        No-op (False) when tracing is off."""
        if self.tracer is None:
            return False
        with self._send_lock:
            send_with_retry(
                self, protocol.hello_message(self.rank, 0,
                                             self.tracer.wall_ns()),
                retries=self.retries, backoff_s=self.backoff_s)
        try:
            ack = self._hello_acks.get(timeout=float(timeout_s))
        except queue.Empty:
            logger.warning("serve hello: no ACK from publisher within "
                           "%.1fs; lanes merge unaligned", timeout_s)
            return False
        # ntp_offset returns publisher-minus-worker; offset_ns is
        # worker-minus-reference, hence the sign flip
        est, rtt = xtrace.ntp_offset(ack["t0"], ack["t1"], ack["t2"])
        self.tracer.offset_ns = -est
        self.tracer.hello["publisher"] = {"offset_ns": -est,
                                          "rtt_ns": rtt}
        return True

    # -- model plane ------------------------------------------------------
    @property
    def global_params(self) -> Any:
        """The served model's host tree (the bit-identity gate compares
        this against the publisher's on-disk checkpoint)."""
        with self._swap_lock:
            return self._g_host

    def _on_push(self, msg: Message) -> None:
        import jax

        version = int(msg.get("version"))
        kind = msg.get("kind")
        ctx = xtrace.extract(msg) if self.tracer is not None else None
        with xtrace.xspan(self.tracer, "adopt",
                          trace_id=ctx.trace_id if ctx else None,
                          parent=ctx.span_id if ctx else None,
                          args={"version": version,
                                "kind": str(kind)}) as aspan:
            if ctx is not None:
                send_ns = xtrace.send_wall_ns(msg)
                if send_ns is not None:
                    # publish-to-adopt lag on the PUBLISHER clock:
                    # our wall mapped through the HELLO offset minus
                    # the push's send stamp
                    lag_ms = (self.tracer.to_ref_ns(
                        self.tracer.wall_ns()) - send_ns) / 1e6
                    self._last_adopt_lag_ms = lag_ms
                    aspan.add(lag_ms=lag_ms)
                    if self.session is not None:
                        self.session.registry.distribution(
                            "serve_adopt_lag_ms").observe(float(lag_ms))
            payload = wire.decode_update(msg, key="delta")
            if kind == "full":
                new_host = jax.tree_util.tree_map(
                    lambda x: np.asarray(x, np.float32), payload)
            else:
                with self._swap_lock:
                    base = self._g_host
                new_host = jax.tree_util.tree_map(
                    lambda b, d: (np.asarray(b, np.float32)
                                  + np.asarray(d, np.float32)),
                    base, payload)
            new_dev = jax.device_put(new_host)
            with self._swap_lock:
                self._g_host = new_host
                self._g_dev = new_dev
                self.version = version
                self._last_swap_t = time.perf_counter()
            self.pushes_adopted += 1
            if self.session is not None:
                self.session.registry.gauge("serve_model_version").set(
                    float(version))
                self.session.registry.counter(
                    "serve_pushes_adopted_total").inc()
            ack = Message(MSG_SERVE_ACK, self.rank, msg.sender_id)
            ack.add("version", version)
            if ctx is not None:
                xtrace.inject(ack, aspan.ctx(),
                              wall_ns=self.tracer.wall_ns())
            if self.heartbeat is not None:
                # piggybacked gauge snapshot: every ACK is also a
                # heartbeat (heartbeats off adds not one byte here)
                self.heartbeat.note_round(version)
                self.heartbeat.note("serve_model_version",
                                    float(version))
                self.heartbeat.note("serve_requests_total",
                                    self.requests_served)
                obs_live.inject_heartbeat(ack, self.heartbeat)
            with self._send_lock:
                send_with_retry(self, ack, retries=self.retries,
                                backoff_s=self.backoff_s)
        logger.info("serve worker adopted v%d (%s push)", version, kind)

    def _on_finish(self, msg: Message) -> None:
        self.done.set()
        # wake the serve loop if it is parked in next_batch
        self.batcher.wake()

    def mark_traffic_done(self) -> None:
        """The traffic pump's last act. The serve loop may not exit on
        a momentarily-empty queue while submissions are still coming
        (``serve_finish`` from a remote publisher races the local
        pump); this event closes that hole."""
        self.traffic_done.set()
        self.batcher.wake()

    # -- data plane -------------------------------------------------------
    def warmup(self) -> None:
        """Compile the serve program off the latency clock (first-batch
        latency would otherwise be the XLA compile, not the serve)."""
        import jax

        ids = [0] * self.batcher.max_batch
        deltas = self.store.gather(PERSONAL_FIELD, ids)
        x = self.data_x[ids, 0]
        out = self._jserve(jax.device_put(deltas), jax.device_put(x),
                           self._g_dev)
        jax.block_until_ready(out)

    def _probe_acc(self) -> float:
        """Accuracy of the CURRENT served global model over the fixed
        probe set — the staleness-vs-accuracy joint the analyzer pins
        (a stale model is only a problem if this number says so)."""
        import jax

        if self._jprobe is None:
            def _probe(g, x):
                return self.apply_fn(g, x, False, None)

            self._jprobe = jax.jit(_probe)
        with self._swap_lock:
            g = self._g_dev
        logits = np.asarray(self._jprobe(g, self._probe_x))
        return float(np.mean(
            np.argmax(logits, axis=-1) == self._probe_y))

    def _tick_record(self, tick: int, batch, lat_ms: np.ndarray,
                     wall_s: float) -> Dict[str, Any]:
        hits = float(self.store.hits)
        misses = float(self.store.misses)
        dh, dm = hits - self._hits0, misses - self._miss0
        self._hits0, self._miss0 = hits, misses
        now = time.perf_counter()
        rps = (len(batch) / (now - self._t_prev_tick)
               if self._t_prev_tick is not None and
               now > self._t_prev_tick else 0.0)
        self._t_prev_tick = now
        with self._swap_lock:
            version = self.version
            staleness = now - self._last_swap_t
        rec = {
            "round": int(tick),
            "serve_requests": float(len(batch)),
            "serve_batch_fill": len(batch) / self.batcher.max_batch,
            "serve_latency_ms": float(np.max(lat_ms)),
            "serve_latency_mean_ms": float(np.mean(lat_ms)),
            "serve_wall_ms": wall_s * 1e3,
            "serve_rps": float(rps),
            "serve_queue_depth": float(self.batcher.depth()),
            "serve_hit_rate": (dh / (dh + dm)) if dh + dm else 1.0,
            "serve_model_version": float(version),
            "serve_model_staleness_s": float(staleness),
        }
        if self._last_adopt_lag_ms is not None:
            rec["serve_adopt_lag_ms"] = float(self._last_adopt_lag_ms)
        if self.probe_every > 0 and self._probe_x is not None \
                and tick % self.probe_every == 0:
            acc = self._probe_acc()
            rec["serve_probe_acc"] = acc
            if self.session is not None:
                self.session.registry.gauge(
                    "serve_probe_acc").set(acc)
        return rec

    def _serve_one(self, batch, tick: int) -> None:
        import jax

        t0 = time.perf_counter()
        ids = [r.client_id for r in batch]
        deltas = self.store.gather(PERSONAL_FIELD, ids)
        x = self.data_x[ids, [r.sample_idx for r in batch]]
        pad = self.batcher.max_batch - len(batch)
        if pad:
            # fixed slab width = one compiled shape; pad AFTER the
            # gather (replicated rows must not inflate hit counters)
            deltas = jax.tree_util.tree_map(
                lambda a: np.concatenate(
                    [a, np.repeat(a[:1], pad, axis=0)]), deltas)
            x = np.concatenate([x, np.repeat(x[:1], pad, axis=0)])
        with self._swap_lock:
            g = self._g_dev
        out = self._jserve(jax.device_put(deltas), jax.device_put(x), g)
        jax.block_until_ready(out)
        t1 = time.perf_counter()
        lat_ms = np.asarray([(t1 - r.t_submit) * 1e3 for r in batch])
        self.requests_served += len(batch)
        self.batches_served += 1
        if self.session is not None:
            reg = self.session.registry
            reg.counter("serve_requests_total").inc(float(len(batch)))
            reg.counter("serve_batches_total").inc()
            reg.distribution("serve_latency_ms").observe(
                float(np.max(lat_ms)))
            self.session.record_round(
                self._tick_record(tick, batch, lat_ms, t1 - t0))

    def serve_loop(self) -> None:
        """Drain-aware consumer loop (run in its own thread): serve
        until ``serve_finish`` has landed, the traffic pump is done
        submitting, AND the queue is empty."""
        tick = 0
        try:
            while True:
                batch = self.batcher.next_batch(timeout_s=0.05)
                if batch:
                    self._serve_one(batch, tick)
                    tick += 1
                elif (self.done.is_set() and self.traffic_done.is_set()
                        and self.batcher.depth() == 0):
                    break
        finally:
            self.drained.set()

    def drain_record(self) -> Dict[str, Any]:
        """The graceful-drain totals record (``round=-1`` +
        ``serve_drained`` — the serving stream's completion trace)."""
        hits = float(self.store.hits)
        misses = float(self.store.misses)
        return {
            "round": -1,
            "serve_drained": True,
            "serve_requests_total": float(self.requests_served),
            "serve_batches_total": float(self.batches_served),
            "serve_hit_rate_total": (hits / (hits + misses)
                                     if hits + misses else 1.0),
            "serve_pushes_adopted": float(self.pushes_adopted),
            "serve_model_version": float(self.version),
            **self.comm.counters.snapshot(),
        }
