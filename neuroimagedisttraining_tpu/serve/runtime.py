"""Serving runtime: role dispatch, the loopback harness, refusals.

``run_serving(args, algo_name)`` is the ``--serve_role`` entry the
runner dispatches to (before the fed dispatch — the two roles refuse
each other). Three shapes of run, mirroring ``fed/runtime.py``:

* ``--serve_backend local --serve_role worker`` — the single-process
  loopback: one ``LocalRouter(2)``, the worker on a receive-pump
  thread with its serve loop and traffic pump, the publisher's
  training loop in the calling thread. The test and CI-adjacent shape.
* ``--serve_backend tcp --serve_role worker`` — rank 1 over the
  native TCP transport: builds the same model/data from the argv,
  serves its own ``--serve_requests`` of Zipf traffic, adopts pushes
  until ``serve_finish``.
* ``--serve_backend tcp --serve_role publisher`` — rank 0: trains
  ``--comm_round`` rounds, pushing every ``--serve_push_every``
  rounds, then drains the worker. ``scripts/serve_smoke.py`` runs the
  two roles concurrently and gates the cross-process contract.

Unlike the training path, the serving worker constructs its
``ObsSession`` unconditionally — latency/hit-rate/staleness gauges ARE
the product of a serving run, there is no obs-off serving — and
``--slo_spec`` arms the engine directly (no ``--obs 1`` prerequisite;
that gate guards the training hot path, which serving never enters).

The bit-identity gate: after drain, the worker's reconstructed model
must compare ``identical`` (``obs/diff.py params_diff``) against the
publisher's last on-disk checkpoint. A lossy wire that survives this
gate is lossy exactly once, at encode — the reconstruction chains on
both ends are twins. Failure is a ``SystemExit``, not a warning.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import xtrace
from ..obs.xtrace import XTracer
from . import PUSH_WIRE_IMPLS, SERVE_SALT
from .batcher import MicroBatcher, ServeRequest
from .publisher import (CheckpointPublisher, checkpoint_path,
                        load_checkpoint)
from .traffic import TrafficGenerator, trace_load, trace_save
from .worker import PERSONAL_FIELD, ServeWorker

logger = logging.getLogger(__name__)

#: serving store modes (``--serve_store``): the population lives on
#: disk by default — the tier the LRU hot set is measured against
SERVE_STORE_MODES = ("disk", "host")


def _refuse(why: str) -> None:
    raise SystemExit(f"serving deployment: {why}")


def validate_serve_args(args, algo_name: str) -> None:
    """The serve-mode refusal cluster (the fed runtime's SystemExit
    idiom): anything the serving plane cannot honor refuses loudly at
    parse/derive time instead of silently diverging."""
    role = getattr(args, "serve_role", "")
    if role not in ("worker", "publisher"):
        _refuse(f"unknown --serve_role {role!r} (worker|publisher)")
    if getattr(args, "fed_role", ""):
        _refuse("--serve_role and --fed_role are different processes; "
                "run the federation and the serving worker separately")
    if algo_name != "fedavg":
        _refuse(f"algo {algo_name!r} unsupported — the publisher ships "
                "FedAvg's round body; run --algo fedavg")
    if getattr(args, "multihost", False):
        _refuse("--multihost shards ONE training run over hosts; the "
                "serving plane is its own process pair")
    backend = getattr(args, "serve_backend", "local")
    if backend not in ("local", "tcp"):
        _refuse(f"unknown --serve_backend {backend!r} (local|tcp)")
    if backend == "local" and role != "worker":
        _refuse("--serve_backend local runs the publisher as the "
                "calling thread of the worker process; --serve_role "
                "publisher needs a real transport (tcp)")
    if backend == "tcp" and not getattr(args, "serve_endpoints", ""):
        _refuse("--serve_backend tcp needs --serve_endpoints "
                "host:port,host:port (rank 0 = publisher, 1 = worker)")
    if getattr(args, "serve_wire", "int8") not in PUSH_WIRE_IMPLS:
        _refuse(f"--serve_wire {getattr(args, 'serve_wire', '')!r} has "
                f"no push codec (supported: {PUSH_WIRE_IMPLS})")
    if getattr(args, "serve_store", "disk") not in SERVE_STORE_MODES:
        _refuse(f"--serve_store {getattr(args, 'serve_store', '')!r} "
                f"not in {SERVE_STORE_MODES}")
    if int(getattr(args, "serve_requests", 0)) < 1:
        _refuse("--serve_requests must be >= 1")
    if float(getattr(args, "serve_rps", 0.0)) <= 0:
        _refuse("--serve_rps must be > 0")
    if int(getattr(args, "serve_batch", 0)) < 1:
        _refuse("--serve_batch must be >= 1")
    if float(getattr(args, "serve_linger_ms", 0.0)) < 0:
        _refuse("--serve_linger_ms must be >= 0")
    if float(getattr(args, "serve_zipf", 0.0)) <= 0:
        _refuse("--serve_zipf must be > 0")
    if int(getattr(args, "serve_push_every", 0)) < 1:
        _refuse("--serve_push_every must be >= 1")
    if float(getattr(args, "serve_timeout_s", 0.0)) <= 0:
        _refuse("--serve_timeout_s must be > 0")
    n_workers = int(getattr(args, "serve_workers", 1) or 1)
    if n_workers < 1:
        _refuse("--serve_workers must be >= 1")
    if n_workers > 1 and backend == "tcp":
        _refuse("--serve_workers > 1 is the loopback fan-out harness; "
                "a tcp deployment runs one --serve_role worker process "
                "per rank against a single publisher")


def _out_dir(args, identity: str) -> str:
    d = getattr(args, "serve_out", "") or os.path.join(
        getattr(args, "results_dir", "results"), "serve", identity)
    os.makedirs(d, exist_ok=True)
    return d


def _make_session(args, algo_name: str, identity: str, out_dir: str,
                  suffix: str = "", catalog: bool = True):
    """A real ObsSession for the worker (runner template, minus the
    --obs gate): JSONL stream, SLO engine straight off --slo_spec,
    catalog entry at close. ``suffix`` keys extra fan-out workers'
    streams (``catalog=False`` for those — one catalog entry per run,
    not per subscriber)."""
    from ..experiments.config import run_identity
    from ..obs.export import ObsSession

    slo_engine = None
    if getattr(args, "slo_spec", ""):
        from ..obs.slo import SloEngine, load_slo_spec

        slo_engine = SloEngine(load_slo_spec(args.slo_spec))
    identity = identity + suffix
    jsonl = os.path.join(out_dir, identity + ".obs.jsonl")
    cat_path, cat_info = "", None
    if catalog and getattr(args, "obs_catalog", 1) and \
            getattr(args, "results_dir", ""):
        from ..obs import catalog as obs_catalog
        from ..obs.regress import git_sha as _git_sha

        cat_path = obs_catalog.catalog_path(args.results_dir)
        cat_info = {
            "config": vars(args),
            "checkpoint_identity": run_identity(
                args, algo_name, for_checkpoint=True),
            "git_sha": _git_sha(),
            # serving runs have no stat_info sidecar; the session's own
            # metrics.json is the summary artifact
            "stat_json": "",
        }
    session = ObsSession(
        jsonl_path=jsonl, identity=identity, slo=slo_engine,
        catalog_path=cat_path, catalog_info=cat_info)
    logger.info("serve obs: per-tick JSONL -> %s", jsonl)
    if slo_engine is not None:
        logger.info("serve slo: %d objective(s) armed, events -> %s",
                    len(slo_engine.objectives), session.events_path)
    return session


def _populate_store(args, out_dir: str, init_params, num_clients: int,
                    rank: int = 1):
    """The personal-model population: one deterministic per-client
    delta row, REALLY staged+committed (a disk-mode store ends up with
    real row files — the tier the Zipf head's LRU is measured against).
    Row c is a pure function of (seed, SERVE_SALT, c): re-deriving the
    population on the publisher side (or in a test) is byte-exact.
    Fan-out workers (rank > 1) stage into their own root — two LRU
    tiers must not share row files."""
    import jax

    from ..core.client_store import ClientStore

    store = ClientStore(
        num_clients, mode=getattr(args, "serve_store", "disk"),
        hot_clients=int(getattr(args, "store_hot_clients", 64)),
        root=os.path.join(out_dir,
                          "store" if rank == 1 else f"store{rank}"))
    zeros = jax.tree_util.tree_map(
        lambda x: np.zeros_like(np.asarray(x, np.float32)), init_params)
    store.register(PERSONAL_FIELD, zeros)
    for c in range(num_clients):
        rng = np.random.default_rng((int(args.seed), SERVE_SALT, 2, c))
        row = jax.tree_util.tree_map(
            lambda z: (0.01 * rng.standard_normal(
                (1,) + z.shape)).astype(np.float32), zeros)
        store.stage(PERSONAL_FIELD, [c], row)
    store.commit()
    return store


def _requests(args, num_clients: int, n_train) -> List[Tuple[int, int]]:
    """Materialize the request stream: a fresh Zipf draw, or a recorded
    trace (``--serve_replay``). ``--serve_trace`` records whichever
    stream actually ran (the replay-equality contract's artifact)."""
    if getattr(args, "serve_replay", ""):
        reqs = trace_load(args.serve_replay)
        for c, s in reqs:
            if not 0 <= c < num_clients:
                _refuse(f"--serve_replay names client {c} but the run "
                        f"has {num_clients}")
    else:
        gen = TrafficGenerator(
            num_clients, n_train,
            zipf_s=float(getattr(args, "serve_zipf", 1.1)),
            seed=int(args.seed))
        reqs = [(int(c), int(s))
                for c, s in gen.draw(int(args.serve_requests))]
    if getattr(args, "serve_trace", ""):
        trace_save(args.serve_trace, reqs,
                   meta={"seed": int(args.seed),
                         "zipf_s": float(getattr(args, "serve_zipf",
                                                 1.1)),
                         "num_clients": int(num_clients)})
    return reqs


def _pump_traffic(worker: ServeWorker, reqs, rps: float) -> None:
    """Open-loop submission at the target rate: the schedule advances
    by 1/rps per request regardless of service time, so a slow worker
    builds queue depth instead of silently shedding load."""
    interval = 1.0 / float(rps)
    t_next = time.perf_counter()
    try:
        for c, s in reqs:
            now = time.perf_counter()
            if t_next > now:
                time.sleep(t_next - now)
            worker.batcher.submit(ServeRequest(c, s))
            t_next += interval
    finally:
        worker.mark_traffic_done()


def _serve_tracer(args, process: str) -> Optional[XTracer]:
    """Per-process tracer for the serving pair (``--xtrace`` only).
    The publisher is the plane's reference clock."""
    if not getattr(args, "xtrace", 0):
        return None
    return XTracer(process, ref="publisher")


def _serve_xtrace_dir(args, out_dir: str) -> str:
    return getattr(args, "xtrace_dir", "") or out_dir


def _write_serve_stream(tracer: Optional[XTracer], args,
                        out_dir: str) -> str:
    if tracer is None:
        return ""
    return tracer.write(os.path.join(
        _serve_xtrace_dir(args, out_dir),
        tracer.process + xtrace.STREAM_SUFFIX))


def _probe_data(args, algo) -> Optional[Tuple[Any, Any]]:
    """The fixed labeled probe slab for ``--serve_probe_every``: the
    first training volume of the first few clients (deterministic, one
    compiled shape)."""
    if int(getattr(args, "serve_probe_every", 0)) < 1:
        return None
    d = algo.data
    n = min(8, int(np.asarray(d.x_train).shape[0]))
    ids = np.arange(n)
    return (np.asarray(d.x_train)[ids, 0],
            np.asarray(d.y_train)[ids, 0])


def _serve_heartbeat(args, peer: str):
    """One ``HeartbeatConfig`` per emitting process
    (``--obs_heartbeat_every`` only; ``None`` keeps every wire
    byte-inert — the fed runtime's gating contract, shared)."""
    every = float(getattr(args, "obs_heartbeat_every", 0.0) or 0.0)
    if every <= 0:
        return None
    from ..obs import live as obs_live

    return obs_live.HeartbeatConfig(peer, every)


def _serve_prom(args, snapshot_fn):
    """The worker's ``/metrics`` endpoint (``--obs_prom_port``; 0 =
    off, -1 = ephemeral). Returns the server or ``None``."""
    from ..obs import prom as obs_prom

    return obs_prom.maybe_prom_server(
        snapshot_fn, int(getattr(args, "obs_prom_port", 0) or 0))


def _make_worker(args, algo, comm, session, out_dir: str,
                 init_params, rank: int = 1, world_size: int = 2,
                 tracer: Optional[XTracer] = None) -> ServeWorker:
    d = algo.data
    num_clients = int(np.asarray(d.x_train).shape[0])
    store = _populate_store(args, out_dir, init_params, num_clients,
                            rank=rank)
    batcher = MicroBatcher(
        max_batch=int(getattr(args, "serve_batch", 16)),
        linger_ms=float(getattr(args, "serve_linger_ms", 2.0)))
    return ServeWorker(
        comm, rank=rank, world_size=world_size,
        apply_fn=algo.apply_fn,
        init_params=init_params, store=store, data_x=d.x_train,
        data_n=d.n_train, batcher=batcher, session=session,
        retries=int(getattr(args, "fed_retries", 2)),
        backoff_s=float(getattr(args, "fed_backoff_s", 0.05)),
        tracer=tracer,
        probe_every=int(getattr(args, "serve_probe_every", 0)),
        probe_data=_probe_data(args, algo),
        heartbeat=_serve_heartbeat(args, f"worker{rank}"))


def _ckpt_dir(args, out_dir: str) -> str:
    return getattr(args, "serve_ckpt_dir", "") or os.path.join(
        out_dir, "ckpt")


def _bit_identity_gate(worker: ServeWorker, ckpt_dir: str) -> bool:
    """Compare the worker's live reconstruction against the checkpoint
    for the version it serves. Returns False (no gate) if no push was
    ever adopted or the checkpoint is not visible on this filesystem
    (a genuinely remote publisher); divergence is fatal."""
    from ..obs import diff as obs_diff

    if worker.pushes_adopted == 0:
        return False
    path = checkpoint_path(ckpt_dir, worker.version)
    if not os.path.exists(path):
        logger.warning("serve: checkpoint %s not visible; skipping "
                       "bit-identity gate", path)
        return False
    version, disk_params = load_checkpoint(path)
    pd = obs_diff.params_diff(worker.global_params, disk_params)
    if not pd["identical"]:
        _refuse(f"served model v{version} diverged from its disk "
                f"checkpoint: {len(pd['diverged'])} leaves, first "
                f"{pd['diverged'][:3]} — the push wire is NOT "
                "bit-transparent")
    logger.info("serve: v%d bit-identical to %s", version, path)
    return True


def _drain(args, worker: ServeWorker, session,
           serve_thread: threading.Thread, ckpt_dir: str,
           wall_s: float) -> Dict[str, Any]:
    """The graceful-drain path (satellite: the catalog must record
    completed=true for a serving stream): final round=-1 record,
    bit-identity gate, session finish."""
    timeout = float(getattr(args, "serve_timeout_s", 60.0))
    if not worker.drained.wait(timeout=timeout):
        _refuse(f"serve loop did not drain within {timeout}s "
                f"(queue depth {worker.batcher.depth()})")
    serve_thread.join(timeout=5.0)
    rec = worker.drain_record()
    session.record_round(rec)
    gated = _bit_identity_gate(worker, ckpt_dir)
    slo_summary = session.slo.summary() if session.slo is not None \
        else None
    session.finish()
    worker.finish()
    served = worker.requests_served
    return {
        "requests": served, "batches": worker.batches_served,
        "pushes_adopted": worker.pushes_adopted,
        "model_version": worker.version,
        "hit_rate": rec["serve_hit_rate_total"],
        "bit_identical": gated, "wall_s": wall_s,
        "rps": served / wall_s if wall_s > 0 else 0.0,
        "slo": slo_summary, "jsonl": session.jsonl_path,
        "events": session.events_path if session.slo is not None
        else "", "metrics_json": session.metrics_json_path,
        "ckpt_dir": ckpt_dir,
    }


def _train_and_push(args, algo, state, pub: CheckpointPublisher
                    ) -> Tuple[Any, int]:
    """The publisher's round loop: version 0 is the init full push (the
    baseline), then train ``--comm_round`` rounds pushing every
    ``--serve_push_every``."""
    pub.publish(state.global_params, 0)
    last_version = 0
    every = int(getattr(args, "serve_push_every", 1))
    for r in range(int(args.comm_round)):
        state, metrics = algo.run_round(state, r)
        if (r + 1) % every == 0:
            pub.publish(state.global_params, r + 1)
            last_version = r + 1
        logger.info("serve publisher round %d: %s", r, metrics)
    return state, last_version


def _run_loopback(args, algo_name: str, identity: str,
                  out_dir: str) -> Dict[str, Any]:
    import jax

    from ..comm.local import LocalRouter
    from ..experiments.runner import build_algorithm

    algo, _ = build_algorithm(args, algo_name)
    state = algo.init_state(jax.random.PRNGKey(args.seed))
    init_params = state.global_params
    d = algo.data
    num_clients = int(np.asarray(d.x_train).shape[0])
    n_workers = int(getattr(args, "serve_workers", 1) or 1)
    router = LocalRouter(1 + n_workers)
    ckpt_dir = _ckpt_dir(args, out_dir)
    workers: List[ServeWorker] = []
    sessions = []
    for r in range(1, n_workers + 1):
        sess = _make_session(args, algo_name, identity, out_dir) \
            if r == 1 else _make_session(
                args, algo_name, identity, out_dir,
                suffix=f".w{r}", catalog=False)
        w = _make_worker(
            args, algo, router.manager(r), sess, out_dir, init_params,
            rank=r, world_size=1 + n_workers,
            tracer=_serve_tracer(
                args, "serve_worker" if r == 1 else f"serve_worker{r}"))
        w.run(background=True)
        workers.append(w)
        sessions.append(sess)
    worker, session = workers[0], sessions[0]
    pub = CheckpointPublisher(
        router.manager(0), world_size=1 + n_workers,
        worker_ranks=list(range(1, n_workers + 1)), ckpt_dir=ckpt_dir,
        wire_impl=getattr(args, "serve_wire", "int8"),
        retries=int(getattr(args, "fed_retries", 2)),
        backoff_s=float(getattr(args, "fed_backoff_s", 0.05)),
        tracer=_serve_tracer(args, "publisher"),
        heartbeat_every=float(
            getattr(args, "obs_heartbeat_every", 0.0) or 0.0))
    pub.run(background=True)
    for w in workers:
        w.clock_sync()
    worker.warmup()
    threads = []
    for w in workers:
        th = threading.Thread(target=w.serve_loop, daemon=True)
        th.start()
        threads.append(th)
        if w is not worker:
            # fan-out subscribers take no traffic in this harness —
            # they exist to adopt every push identically; an immediate
            # traffic_done lets their drain fire on serve_finish
            w.mark_traffic_done()
    reqs = _requests(args, num_clients, d.n_train)
    traffic = threading.Thread(
        target=_pump_traffic,
        args=(worker, reqs, float(getattr(args, "serve_rps", 200.0))),
        daemon=True)
    t0 = time.perf_counter()
    traffic.start()
    prom = _serve_prom(args, worker.prom_snapshot)
    try:
        # the training loop IS the calling thread: checkpoints stream
        # to the worker(s) while rank 1 absorbs the open-loop traffic
        state, last_version = _train_and_push(args, algo, state, pub)
        traffic.join()
        if not pub.wait_acked(last_version, timeout_s=float(
                getattr(args, "serve_timeout_s", 60.0))):
            _refuse(f"worker(s) never acked v{last_version} "
                    f"(watermarks {pub.acked_versions()})")
        pub.finish_worker()
        wall = time.perf_counter() - t0
        serve = _drain(args, worker, session, serve_thread=threads[0],
                       ckpt_dir=ckpt_dir, wall_s=wall)
        extras = [_drain(args, w, s, serve_thread=th,
                         ckpt_dir=ckpt_dir, wall_s=wall)
                  for w, s, th in zip(workers[1:], sessions[1:],
                                      threads[1:])]
    finally:
        pub.finish()
        if prom is not None:
            prom.close()
    _write_serve_stream(pub.tracer, args, out_dir)
    for w in workers:
        _write_serve_stream(w.tracer, args, out_dir)
    if worker.tracer is not None:
        serve["merged_trace"] = xtrace.merge_run_dir(
            _serve_xtrace_dir(args, out_dir)) or ""
    serve.update(pushes=pub.pushes, bytes_pushed=pub.bytes_pushed,
                 acked_version=pub.acked_version, out_dir=out_dir,
                 backend="local")
    if n_workers > 1:
        serve["workers"] = [
            {"rank": r, "requests": s["requests"],
             "pushes_adopted": s["pushes_adopted"],
             "model_version": s["model_version"],
             "bit_identical": s["bit_identical"]}
            for r, s in enumerate([serve] + extras, start=1)]
        serve["acked_versions"] = {
            str(k): v for k, v in sorted(pub.acked_versions().items())}
    fleet = pub.fleet_snapshot()
    if fleet is not None:
        serve["fleet"] = fleet
        with open(os.path.join(out_dir, "fleet.json"), "w") as f:
            import json as _json

            _json.dump(fleet, f, indent=1)
    if prom is not None:
        serve["prom_port"] = prom.port
    return {"identity": identity, "history": [], "final_eval": {},
            "stat_path": out_dir, "state": None, "serve": serve}


def _run_tcp(args, algo_name: str, identity: str,
             out_dir: str) -> Dict[str, Any]:
    import jax

    from ..comm.tcp import TcpCommManager
    from ..experiments.runner import build_algorithm
    from ..fed.runtime import parse_endpoints

    endpoints = parse_endpoints(
        getattr(args, "serve_endpoints", ""), 2)
    algo, _ = build_algorithm(args, algo_name)
    state = algo.init_state(jax.random.PRNGKey(args.seed))
    init_params = state.global_params
    ckpt_dir = _ckpt_dir(args, out_dir)
    if args.serve_role == "publisher":
        pub = CheckpointPublisher(
            TcpCommManager(0, endpoints), ckpt_dir=ckpt_dir,
            wire_impl=getattr(args, "serve_wire", "int8"),
            retries=int(getattr(args, "fed_retries", 2)),
            backoff_s=float(getattr(args, "fed_backoff_s", 0.05)),
            tracer=_serve_tracer(args, "publisher"),
            heartbeat_every=float(
                getattr(args, "obs_heartbeat_every", 0.0) or 0.0))
        pub.run(background=True)
        t0 = time.perf_counter()
        try:
            state, last_version = _train_and_push(args, algo, state,
                                                  pub)
            if not pub.wait_acked(last_version, timeout_s=float(
                    getattr(args, "serve_timeout_s", 60.0))):
                _refuse(f"worker never acked v{last_version}")
            pub.finish_worker()
        finally:
            pub.finish()
        xtrace_path = _write_serve_stream(pub.tracer, args, out_dir)
        serve_pub = {"role": "publisher", "backend": "tcp",
                     "pushes": pub.pushes,
                     "bytes_pushed": pub.bytes_pushed,
                     "acked_version": pub.acked_version,
                     "ckpt_dir": ckpt_dir,
                     "wall_s": time.perf_counter() - t0,
                     "out_dir": out_dir,
                     "xtrace_path": xtrace_path,
                     **pub.comm.counters.snapshot()}
        fleet = pub.fleet_snapshot()
        if fleet is not None:
            serve_pub["fleet"] = fleet
        return {"identity": identity, "history": [], "final_eval": {},
                "stat_path": out_dir, "state": None,
                "serve": serve_pub}
    # worker role: serve own traffic, adopt pushes until serve_finish
    d = algo.data
    num_clients = int(np.asarray(d.x_train).shape[0])
    session = _make_session(args, algo_name, identity, out_dir)
    worker = _make_worker(args, algo, TcpCommManager(1, endpoints),
                          session, out_dir, init_params,
                          tracer=_serve_tracer(args, "serve_worker"))
    worker.run(background=True)
    worker.clock_sync()
    worker.warmup()
    serve_thread = threading.Thread(target=worker.serve_loop,
                                    daemon=True)
    serve_thread.start()
    reqs = _requests(args, num_clients, d.n_train)
    traffic = threading.Thread(
        target=_pump_traffic,
        args=(worker, reqs, float(getattr(args, "serve_rps", 200.0))),
        daemon=True)
    t0 = time.perf_counter()
    traffic.start()
    prom = _serve_prom(args, worker.prom_snapshot)
    timeout = float(getattr(args, "serve_timeout_s", 60.0))
    try:
        if not worker.done.wait(timeout=timeout):
            _refuse(
                f"no serve_finish from the publisher within {timeout}s")
        traffic.join(timeout=timeout)
        wall = time.perf_counter() - t0
        serve = _drain(args, worker, session, serve_thread, ckpt_dir,
                       wall)
    finally:
        if prom is not None:
            prom.close()
    if prom is not None:
        serve["prom_port"] = prom.port
    _write_serve_stream(worker.tracer, args, out_dir)
    if worker.tracer is not None:
        # same filesystem (the smoke's shape): the publisher's stream
        # is already on disk, so this merge holds both lanes
        serve["merged_trace"] = xtrace.merge_run_dir(
            _serve_xtrace_dir(args, out_dir)) or ""
    serve.update(role="worker", backend="tcp", out_dir=out_dir)
    return {"identity": identity, "history": [], "final_eval": {},
            "stat_path": out_dir, "state": None, "serve": serve}


def run_serving(args, algo_name: str) -> Dict[str, Any]:
    """The ``--serve_role`` entry point: validate, build, run the
    role."""
    validate_serve_args(args, algo_name)
    from ..experiments.config import run_identity

    # "-serve" keeps the serving stream's catalog lineage distinct
    # from any training run with the same argv
    identity = run_identity(args, algo_name) + "-serve"
    out_dir = _out_dir(args, identity)
    backend = getattr(args, "serve_backend", "local")
    logger.info("serving: role=%s backend=%s wire=%s -> %s",
                args.serve_role, backend,
                getattr(args, "serve_wire", "int8"), out_dir)
    if backend == "local":
        return _run_loopback(args, algo_name, identity, out_dir)
    return _run_tcp(args, algo_name, identity, out_dir)
