"""Micro-batch coalescing: the request queue in front of the vmapped
forward.

Open-loop traffic arrives one request at a time; the device wants
``[B]``-stacked work. The batcher closes a micro-batch when either
``max_batch`` requests are pending (a full slab) or ``linger_ms`` has
elapsed since the OLDEST pending request (the latency bound: a lone
request on an idle worker never waits longer than the linger). This is
the classic serving trade — linger higher for throughput, lower for
tail latency — and both knobs are ``--serve_*`` flags so the RESULTS
table can sweep them.

Thread contract: any number of producer threads ``submit()``; one
consumer thread (the worker's serve loop) calls ``next_batch()``.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Deque, List, Optional


class ServeRequest:
    """One inference request: which client's personal model, which of
    its samples, and when it entered the queue (the latency clock —
    queueing time is part of what ``serve_latency_ms`` measures)."""

    __slots__ = ("client_id", "sample_idx", "t_submit")

    def __init__(self, client_id: int, sample_idx: int,
                 t_submit: Optional[float] = None):
        self.client_id = int(client_id)
        self.sample_idx = int(sample_idx)
        self.t_submit = (time.perf_counter()
                         if t_submit is None else float(t_submit))


class MicroBatcher:
    def __init__(self, max_batch: int = 16, linger_ms: float = 2.0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if linger_ms < 0:
            raise ValueError(f"linger_ms must be >= 0, got {linger_ms}")
        self.max_batch = int(max_batch)
        self.linger_s = float(linger_ms) / 1e3
        self._q: Deque[ServeRequest] = collections.deque()
        self._cond = threading.Condition()
        self.submitted = 0

    def submit(self, req: ServeRequest) -> None:
        with self._cond:
            self._q.append(req)
            self.submitted += 1
            self._cond.notify()

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def wake(self) -> None:
        """Nudge a consumer parked in ``next_batch`` (the drain path:
        ``serve_finish`` arrives while the queue is empty — without the
        wake the loop only notices after its idle timeout)."""
        with self._cond:
            self._cond.notify_all()

    def next_batch(self, timeout_s: float = 0.1
                   ) -> Optional[List[ServeRequest]]:
        """Block up to ``timeout_s`` for the first pending request;
        then coalesce until the batch is full or the oldest request has
        lingered ``linger_ms``. ``None`` = nothing arrived (the serve
        loop's idle tick — it checks the drain condition and re-arms).
        """
        deadline = time.perf_counter() + float(timeout_s)
        with self._cond:
            while not self._q:
                left = deadline - time.perf_counter()
                if left <= 0:
                    return None
                self._cond.wait(left)
            close_at = self._q[0].t_submit + self.linger_s
            while len(self._q) < self.max_batch:
                left = close_at - time.perf_counter()
                if left <= 0:
                    break
                self._cond.wait(left)
            batch = [self._q.popleft()
                     for _ in range(min(self.max_batch, len(self._q)))]
        return batch
