"""Synthetic request traffic: deterministic Zipf-skewed load.

The millions-of-users load profile at CI scale: requests are
``(client_id, sample_idx)`` pairs over the synthetic federated volumes
(``data/synthetic.py`` — ``sample_idx`` indexes the client's own
``x_train`` rows, request granularity instead of round granularity).
Client popularity is Zipf: rank ``r`` (0-based) draws with weight
``1/(r+1)^s``, and WHICH client holds which rank is a seeded
permutation — so the hot head is a deterministic function of the seed,
not of client numbering. A head-heavy skew is the whole point: it is
what makes the store's LRU hot set earn its keep (the monotonicity
test in ``tests/test_serve_traffic.py`` pins hit-rate vs capacity).

Determinism is the contract, same as everywhere else in the repo: the
generator is a pure function of ``(seed, num_clients, zipf_s)`` plus
its draw count — ``np.random.Generator`` (PCG64), no wall clock — so
two generators with one seed emit identical request streams, and a
recorded trace replays equal to a fresh generator (both pinned by
tests). Traces serialize to JSON for offline analysis / replay.
"""
from __future__ import annotations

import json
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from . import SERVE_SALT


class TrafficGenerator:
    """Deterministic open-loop request source.

    ``n_per_client`` bounds each client's ``sample_idx`` (the
    synthetic data's ``n_train``); a scalar broadcasts. ``zipf_s`` is
    the skew exponent (1.0-1.2 is the classic web-traffic range;
    larger = hotter head).
    """

    def __init__(self, num_clients: int, n_per_client,
                 zipf_s: float = 1.1, seed: int = 0):
        if num_clients < 1:
            raise ValueError("TrafficGenerator needs num_clients >= 1")
        if zipf_s <= 0:
            raise ValueError(f"zipf_s must be > 0, got {zipf_s}")
        self.num_clients = int(num_clients)
        self.zipf_s = float(zipf_s)
        self.seed = int(seed)
        n = np.broadcast_to(np.asarray(n_per_client, np.int64),
                            (self.num_clients,))
        if np.any(n < 1):
            raise ValueError("every client needs >= 1 sample to serve")
        self.n_per_client = np.array(n)
        # popularity: a seeded permutation assigns each client its Zipf
        # rank (domain-separated from the draw stream so adding draws
        # never reshuffles who is popular)
        perm_rng = np.random.default_rng((self.seed, SERVE_SALT, 0))
        ranks = perm_rng.permutation(self.num_clients)
        w = 1.0 / (np.arange(self.num_clients, dtype=np.float64)
                   + 1.0) ** self.zipf_s
        p = w[ranks]
        self.probs = p / p.sum()
        self._rng = np.random.default_rng((self.seed, SERVE_SALT, 1))
        self.drawn = 0

    def hot_clients(self, k: int) -> np.ndarray:
        """The ``k`` most popular client ids, descending popularity —
        what an informed prefetch would pin."""
        return np.argsort(-self.probs, kind="stable")[:int(k)]

    def draw(self, n: int) -> np.ndarray:
        """``[n, 2]`` int64 requests ``(client_id, sample_idx)``."""
        n = int(n)
        clients = self._rng.choice(self.num_clients, size=n,
                                   p=self.probs)
        # a full-width draw modulo the client's own sample count keeps
        # the stream length (and hence determinism) independent of the
        # per-client data sizes
        raw = self._rng.integers(0, np.int64(2) ** 62, size=n)
        samples = raw % self.n_per_client[clients]
        self.drawn += n
        return np.stack([clients, samples], axis=1).astype(np.int64)

    def iter_requests(self, total: int) -> Iterator[Tuple[int, int]]:
        """Yield ``total`` requests one at a time, equal to
        ``draw(total)`` element-for-element. Materialized as ONE draw:
        chunked draws would interleave the client/sample consumption of
        the underlying bit stream differently and fork the sequence."""
        for c, s in self.draw(int(total)):
            yield int(c), int(s)


# -- trace record / replay -----------------------------------------------

def trace_save(path: str, requests: Sequence[Tuple[int, int]],
               meta: Optional[dict] = None) -> str:
    """Serialize a served request stream (list of ``(client, sample)``)
    plus generator metadata to JSON."""
    body = {"meta": dict(meta or {}),
            "requests": [[int(c), int(s)] for c, s in requests]}
    with open(path, "w") as f:
        json.dump(body, f)
    return path


def trace_load(path: str) -> List[Tuple[int, int]]:
    with open(path) as f:
        body = json.load(f)
    return [(int(c), int(s)) for c, s in body["requests"]]


def replay_requests(trace: Sequence[Tuple[int, int]]
                    ) -> Iterator[Tuple[int, int]]:
    """A recorded trace as a request source — drop-in for
    ``TrafficGenerator.iter_requests`` (the replay-equality contract:
    a worker fed the trace serves the identical request sequence)."""
    for c, s in trace:
        yield int(c), int(s)
