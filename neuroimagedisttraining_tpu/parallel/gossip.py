"""Ring gossip over the clients mesh axis via ``lax.ppermute``.

SURVEY §2.6: the reference's decentralized algorithms exchange models by
explicit peer sends (simulated); on TPU a ring-topology gossip step is two
``ppermute`` rotations over ICI plus a weighted sum — no host, no
materialized N×N adjacency. The general-graph path remains the adjacency
contraction used by DisPFL/DPSGD (``mix_over_clients``); this primitive is
the fast path for the reference's ``cs=ring`` neighborhood
(``dispfl_api.py:207-212``: each client averages itself with its two ring
neighbors) when per-client state is sharded one-client-per-device.
"""
from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:  # jax >= 0.7 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax ships it in experimental
    from jax.experimental.shard_map import shard_map


def ring_mix(
    tree: Any,
    mesh: Mesh,
    weights: Tuple[float, float, float] = (1 / 3, 1 / 3, 1 / 3),
    axis_name: str = "clients",
):
    """One gossip round on a ring: ``out_i = w_self*x_i + w_left*x_{i-1}
    + w_right*x_{i+1}`` (indices mod N) for every leaf's leading client
    axis, computed with two ``ppermute`` rotations under ``shard_map``.

    ``weights`` = (self, left-neighbor, right-neighbor); the reference's
    ring average is the default uniform (1/3, 1/3, 1/3)
    (``_benefit_choose`` ring + uniform ``_aggregate_func``,
    ``dpsgd_api.py:169-178``).
    """
    n = mesh.shape[axis_name]
    if n < 3:
        raise ValueError(
            f"ring_mix needs a clients axis of >= 3 (got {n}): with 2 "
            "devices both rotations hit the same neighbor, which doubles "
            "its weight relative to the normalized ring adjacency — use "
            "the adjacency-contraction path for tiny rings")
    w_self, w_left, w_right = weights
    fwd = [(i, (i + 1) % n) for i in range(n)]   # receive from left
    bwd = [(i, (i - 1) % n) for i in range(n)]   # receive from right

    for path, x in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if getattr(x, "ndim", 0) == 0 or x.shape[0] != n:
            raise ValueError(
                f"leaf {jax.tree_util.keystr(path)} leading axis "
                f"{getattr(x, 'shape', ())} != clients extent {n}")

    # ONE shard_map over the whole pytree (prefix spec): a single traced
    # program with all rotations, instead of a separately-dispatched pair
    # of ppermutes per leaf (dispatch costs ~5-6 ms each on the bench env)
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis_name), out_specs=P(axis_name))
    def mix_tree(t):
        def mix_leaf(x):
            from_left = lax.ppermute(x, axis_name, fwd)
            from_right = lax.ppermute(x, axis_name, bwd)
            return w_self * x + w_left * from_left + w_right * from_right

        return jax.tree_util.tree_map(mix_leaf, t)

    return mix_tree(tree)
