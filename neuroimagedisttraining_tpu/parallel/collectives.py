"""Communication-efficient cross-chip aggregation collectives.

The dense federated aggregate (``core.state.weighted_tree_sum``) moves every
f32 parameter across ICI every round as ONE monolithic contraction — at the
scale-32 dry-run configuration that is 55.8% of the round (MULTICHIP_r05).
This module is the ``agg`` subsystem that shrinks and overlaps that transfer,
three composable levers behind one ``weighted_mean`` surface:

* **bucketed** — per-leaf local partials inside ``shard_map``, reduced by
  ONE multi-operand ``psum`` per fixed-size bucket, so XLA can pipeline
  bucket k's collective against bucket k+1's local compute (and against
  the tail of local training) instead of one serialized all-reduce
  barrier. Bucket boundaries snap to leaf boundaries of the
  ``vectorize_weights`` flattening order: measured on the scale-32
  CPU-mesh dry-run, flattening-into-buckets costs a full extra copy of
  the cohort matrix (the copy, not the reduce, dominated) while whole-
  leaf groups cost nothing. Off-mesh the bucketed contraction is
  element-for-element the dense one — bit-equal
  (tests/test_collectives.py).
* **low-precision wire** — per-device f32 local partials are cast to bf16
  (or stochastic-rounded int8 with a per-bucket scale) for the cross-chip
  hop and accumulated in f32 on every receiver (``all_gather`` of the
  wire payload + f32 tree-sum), halving (or quartering) the bytes moved
  while master weights stay f32.
* **mask-aware sparse** — for static-mask algorithms (SalientGrads: the
  SNIP mask is fixed after init) a host-built :class:`SparsePlan` gathers
  only the live coordinates of each kernel leaf (the union over clients
  when masks are stacked — a static shared index set). On-mesh each
  device gathers its LOCAL clients' live columns before the contraction,
  so the local reduce AND the per-bucket collectives run on the
  compressed representation (~density x the work and bytes); the dense
  layout is rebuilt once at the end by a static inverse-permutation
  gather (scatter is pathologically slow on XLA:CPU — measured 65 ms vs
  1.6 ms for the gather spelling at flagship scale). The mask-weighted
  denominator (``sum(masks)``) is computed on the same compressed
  representation when per-client masks are supplied. With honored masks
  the result is bit-equal to the dense mask-weighted aggregate.
* **error-feedback top-k** (``agg_impl='topk'``) — per-leaf-group top-k
  magnitude selection on the clients' COMPENSATED deltas (delta plus the
  error-feedback residual the algorithm carries in state — Deep Gradient
  Compression, Lin et al. 2018). The wire cost scales with information
  (k selected coordinates: value + index), not parameter count; the
  unsent remainder accumulates in the residual so nothing is ever
  dropped, only deferred. :func:`topk_sparsify` is the selection kernel,
  :func:`topk_weighted_mean` the aggregate; the residual bookkeeping
  lives in ``algorithms/base.py`` (it is state, not a wire concern).
  With a :class:`SparsePlan` the selection runs on the compressed live
  coordinates, so k is a fraction of the LIVE set (SalientGrads
  composition).
* **hierarchical two-stage reduce** (``agg_impl='hier'``) — BlueConnect
  (Cho et al. 2019) style: a full-precision ``psum`` over
  ``axis_index_groups`` of ``hier_inner`` adjacent devices (the fast
  intra-slice domain), then ONE cross-slice collective per leaf-group
  bucket in a configurable low-precision wire (bf16 / int8 — f32
  accumulation) across the ``outer = devices/inner`` slices. Off-mesh
  (or with one slice) it degrades to the exact f32 bucketed reduce.
* **compute/comm overlap** (``overlap=True``, the default) — the
  shard_map reduce issues each leaf-group bucket's collective
  immediately after computing THAT group's local partials instead of
  materializing every leaf's partial first: group k's collective and
  group k+1's local contraction have no data dependency, so XLA's
  scheduler can pipeline wire against compute (and, in the fused scan
  path, against the tail of local training that produces later groups'
  leaves). Scheduling-only: per-bucket math is bit-identical either
  way, so the knob never enters run identity. Verified via
  ``obs/devtrace.py``'s collective-vs-compute interval overlap.

Everything is jit-traceable and composes with the Byzantine-robust defenses
(``robust.aggregation`` transforms the stacked locals BEFORE aggregation, so
any ``agg_impl`` consumes defended trees unchanged).
"""
from __future__ import annotations

import time
from functools import partial
from typing import Any, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.7 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax ships it in experimental
    from jax.experimental.shard_map import shard_map

#: 256k f32 = 1 MiB per bucket on the wire — large enough that per-collective
#: latency amortizes, small enough that several buckets cover the 2.57M-param
#: flagship tree and leave XLA real pipelining freedom.
DEFAULT_BUCKET_SIZE = 1 << 18

WIRE_FORMATS = ("f32", "bf16", "int8")

#: the ``agg_impl`` hyperparameter surface (algorithms/base.py)
AGG_IMPLS = ("dense", "bucketed", "bf16", "int8", "sparse", "topk",
             "hier")

#: cross-slice wire choices of the hierarchical reduce ("sparse" =
#: compressed-plan f32 across slices — SalientGrads only)
HIER_WIRES = ("f32", "bf16", "int8", "sparse")


class FlatSpec(NamedTuple):
    """Shape/dtype record to rebuild a pytree from its flat vector."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    dtypes: Tuple[Any, ...]
    total: int


def flat_spec(tree: Any, stacked: bool = False) -> FlatSpec:
    """Describe ``tree``'s leaves; ``stacked=True`` strips the leading
    client axis so the spec describes ONE client's (or the aggregate's)
    tree."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(
        tuple(x.shape[1:] if stacked else x.shape) for x in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    dtypes = tuple(x.dtype for x in leaves)
    return FlatSpec(treedef, shapes, sizes, dtypes, int(sum(sizes)))


def tree_to_vec(tree: Any) -> jax.Array:
    """Flatten a pytree into one vector (the ``vectorize_weights``
    flattening of ``robust.aggregation``, hoisted here so the defense and
    the aggregation buckets share one definition)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.concatenate([x.reshape(-1) for x in leaves])


def vec_to_tree(vec: jax.Array, spec: FlatSpec) -> Any:
    """Rebuild the pytree described by ``spec`` from its flat vector."""
    out = []
    off = 0
    for shape, size, dtype in zip(spec.shapes, spec.sizes, spec.dtypes):
        out.append(vec[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree_util.tree_unflatten(spec.treedef, out)


def stacked_to_mat(stacked: Any) -> jax.Array:
    """[C, ...]-stacked pytree -> one [C, N] f32 matrix (f32 is the master
    weight / accumulation dtype; a no-op cast for the f32 param trees this
    framework aggregates)."""
    leaves = jax.tree_util.tree_leaves(stacked)
    c = leaves[0].shape[0]
    return jnp.concatenate(
        [x.reshape(c, -1).astype(jnp.float32) for x in leaves], axis=1)


# ---------------------------------------------------------------------------
# wire formats
# ---------------------------------------------------------------------------

def _check_wire(wire: str, rng) -> None:
    if wire not in WIRE_FORMATS:
        raise ValueError(f"wire {wire!r} not in {WIRE_FORMATS}")
    if wire == "int8" and rng is None:
        raise ValueError("wire='int8' needs an rng for stochastic rounding")


def _stochastic_round(x: jax.Array, rng: jax.Array) -> jax.Array:
    f = jnp.floor(x)
    return f + (jax.random.uniform(rng, x.shape) < (x - f)).astype(x.dtype)


def _int8_scale(x: jax.Array) -> jax.Array:
    """Per-bucket (last-axis) max-abs/127 scale, keepdims. ONE spelling
    shared by the XLA chain and the fused-kernel routing: XLA's
    algebraic simplifier rewrites the constant divide differently under
    jit than eagerly (measured one-ulp scale drift), so backend
    bit-identity requires both backends to trace the IDENTICAL scale
    subgraph, not merely equivalent math."""
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    return jnp.where(amax > 0, amax / 127.0, 1.0)


def _quantize_int8(x: jax.Array, rng: jax.Array):
    """Per-bucket (last-axis) max-abs scaling + stochastic rounding.
    Returns (int8 payload, f32 scale broadcastable against it)."""
    scale = _int8_scale(x)
    q = jnp.clip(_stochastic_round(x / scale, rng), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def wire_roundtrip_mat(mat: jax.Array, wire: str, *,
                       bucket_size: int = DEFAULT_BUCKET_SIZE,
                       rng: Optional[jax.Array] = None) -> jax.Array:
    """Encode+decode each row of an ``[S, N]`` client-delta matrix
    through the ``wire`` format — WHAT THE SERVER WOULD SEE after the
    cross-chip hop, without reducing.

    The low-precision wires commute with the weighted SUM (cast, then
    accumulate in f32 — the ``_reduce_mat`` contract) but NOT with order
    statistics: a robust aggregator must rank the values the receiver
    decodes, not the f32 values the sender held, or the robust statistic
    silently runs on data the wire never carried. ``robust_agg`` on a
    compressed ``agg_impl`` therefore pushes every row through this
    roundtrip before the statistic.

    bf16 is the plain double cast; int8 pads each row to whole
    ``bucket_size`` buckets and applies the per-(row, bucket)
    stochastic-rounded quantization — the IDENTICAL ``_quantize_int8``
    spelling the reducing wire uses, so one client's decoded row here
    matches its contribution there bit-for-bit when given the same
    rng. f32 is the identity."""
    _check_wire(wire, rng)
    if wire == "f32":
        return mat
    if wire == "bf16":
        return mat.astype(jnp.bfloat16).astype(jnp.float32)
    s, n = mat.shape
    b = min(bucket_size, max(n, 1))
    nb = -(-n // b)
    pad = nb * b - n
    if pad:
        mat = jnp.pad(mat, ((0, 0), (0, pad)))
    buckets = mat.reshape(s, nb, b)
    q, scale = _quantize_int8(buckets, rng)
    deq = q.astype(jnp.float32) * scale
    return deq.reshape(s, -1)[:, :n]


import inspect as _inspect

#: portable "disable the static replication check" kwarg — ``check_vma``
#: on current jax, ``check_rep`` on older releases (same detection as
#: ``spatial.NOCHECK_KW``); computed once at import
_NOCHECK_KW = (
    {"check_rep": False}
    if "check_rep" in _inspect.signature(shard_map).parameters
    else {"check_vma": False})


def _shard_map_kw(wire: str) -> dict:
    """The all_gather wires ARE replicated (every device gathers and sums
    the same partials) but the static rep-checker can't see through the
    gather+sum, so it is disabled for those; the f32 psum path keeps it."""
    return {} if wire == "f32" else dict(_NOCHECK_KW)


def _mesh_axis_rows(mesh, axis_name: str, c: int) -> int:
    """Usable device count along ``axis_name`` for a C-row stacked axis;
    0 disables the shard_map path (no mesh / axis missing / C not
    divisible — e.g. a partial-participation round on an 8-wide mesh)."""
    if mesh is None or axis_name not in getattr(mesh, "axis_names", ()):
        return 0
    d = int(mesh.shape[axis_name])
    if d <= 1 or c % d:
        return 0
    return d


# ---------------------------------------------------------------------------
# leaf-group buckets (the shard_map reduce core)
# ---------------------------------------------------------------------------

def _leaf_groups(sizes, bucket_size: int) -> List[List[int]]:
    """Greedy partition of the leaf list (``tree_leaves`` order — the
    ``vectorize_weights`` flattening order) into contiguous groups of
    >= ``bucket_size`` elements. Each group is ONE multi-operand
    collective; snapping bucket boundaries to leaf boundaries keeps the
    bucketing copy-free (see module docstring)."""
    groups: List[List[int]] = []
    cur: List[int] = []
    acc = 0
    for i, s in enumerate(sizes):
        cur.append(i)
        acc += int(s)
        if acc >= bucket_size:
            groups.append(cur)
            cur, acc = [], 0
    if cur:
        groups.append(cur)
    return groups


def _group_vals(payload, g):
    """One group's payload vectors; thunk entries (the overlap spelling:
    each leaf's local contraction deferred until ITS group reduces, so
    group k's collective and group k+1's contraction are independent
    and XLA can pipeline them) are forced here, at issue time."""
    return tuple(payload[i]() if callable(payload[i]) else payload[i]
                 for i in g)


def _int8_leaf_reduce(v, i, kd, axis_name, bucket_size, groups=None):
    """One leaf's int8-wire reduce: pad to bucket rows, quantize with a
    per-(device,leaf) stochastic-rounding key, all_gather payload +
    scales (optionally over ``axis_index_groups``), f32 accumulate."""
    n = v.shape[0]
    b = min(bucket_size, max(n, 1))
    nb = -(-n // b)
    pad = nb * b - n
    vb = jnp.pad(v, (0, pad)).reshape(nb, b)
    q, s = _quantize_int8(vb, jax.random.fold_in(kd, i))
    gq = jax.lax.all_gather(q, axis_name, axis_index_groups=groups)
    gs = jax.lax.all_gather(s, axis_name, axis_index_groups=groups)
    return jnp.sum(gq.astype(jnp.float32) * gs, axis=0).reshape(-1)[:n]


def _wire_reduce_groups(payload, groups, *, axis_name: str, wire: str,
                        key, bucket_size: int):
    """INSIDE shard_map: reduce a list of per-device flat f32 local-
    partial vectors across ``axis_name``, one collective per leaf-group
    bucket — multi-operand ``psum`` for f32; ``all_gather`` of the
    wire-cast payload + f32 tree-sum for bf16/int8 (low-precision wire,
    f32 accumulation). Independent per-bucket collectives are what XLA
    can pipeline against each other and the producing compute; payload
    entries may be thunks (see :func:`_group_vals`) so each group's
    contraction is emitted right before its own collective."""
    out = [None] * len(payload)
    for g in groups:
        vals = _group_vals(payload, g)
        if wire == "f32":
            red = jax.lax.psum(vals, axis_name)
        elif wire == "bf16":
            gath = jax.lax.all_gather(
                tuple(v.astype(jnp.bfloat16) for v in vals), axis_name)
            red = tuple(jnp.sum(x.astype(jnp.float32), axis=0)
                        for x in gath)
        else:  # int8: per-bucket scales within each leaf payload
            kd = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
            red = tuple(
                _int8_leaf_reduce(v, i, kd, axis_name, bucket_size)
                for i, v in zip(g, vals))
        for i, r in zip(g, red):
            out[i] = r
    return out


def resolve_hier_inner(n_devices: int, requested: int = 0) -> int:
    """Devices per intra-slice group of the hierarchical reduce.

    ``requested > 0`` must divide the axis size (a static config error
    otherwise — raised at trace/build time, never silently adjusted);
    ``requested`` of 1 or >= the axis size means one stage, returned as
    0 (disabled). ``requested == 0`` auto-picks the largest divisor d
    with ``d*d <= n_devices`` (the balanced two-stage split: 8 devices
    -> 2x4, 16 -> 4x4); axes of <= 2 devices have no second stage."""
    if requested and (requested < 0
                      or (requested > 1 and n_devices % requested)):
        # validated BEFORE the small-axis early return: a typo'd inner
        # must fail on the 2-device dev mesh, not only when promoted
        raise ValueError(
            f"hier_inner {requested} must divide the {n_devices}-"
            "device clients axis (intra-slice groups are equal-size "
            "device blocks)")
    if n_devices <= 2:
        return 0
    if requested:
        return requested if 1 < requested < n_devices else 0
    inner = 1
    for d in range(2, n_devices):
        if n_devices % d == 0 and d * d <= n_devices:
            inner = d
    return inner if inner > 1 else 0


def _hier_index_groups(n_devices: int, inner: int):
    """(intra, inter) ``axis_index_groups``: contiguous ``inner``-device
    blocks are one slice; position-matched devices across the
    ``n_devices // inner`` slices form the cross-slice groups."""
    outer = n_devices // inner
    intra = [[s * inner + i for i in range(inner)] for s in range(outer)]
    inter = [[s * inner + i for s in range(outer)] for i in range(inner)]
    return intra, inter


def _hier_reduce_groups(payload, groups, *, axis_name: str, wire: str,
                        key, bucket_size: int, n_devices: int,
                        inner: int):
    """INSIDE shard_map: the two-stage hierarchical reduce. Stage 1 is a
    FULL-PRECISION multi-operand ``psum`` within each ``inner``-device
    slice (the fast domain — ICI inside a slice); stage 2 moves each
    slice's partial across the slow domain once per leaf-group bucket in
    the configured ``wire`` (f32 psum, or bf16/int8 all_gather + f32
    accumulation). Every device ends with the full reduction (the two
    group partitions compose to the whole axis)."""
    intra, inter = _hier_index_groups(n_devices, inner)
    out = [None] * len(payload)
    for g in groups:
        vals = _group_vals(payload, g)
        part = jax.lax.psum(vals, axis_name, axis_index_groups=intra)
        if wire == "f32":
            red = jax.lax.psum(part, axis_name, axis_index_groups=inter)
        elif wire == "bf16":
            gath = jax.lax.all_gather(
                tuple(v.astype(jnp.bfloat16) for v in part), axis_name,
                axis_index_groups=inter)
            red = tuple(jnp.sum(x.astype(jnp.float32), axis=0)
                        for x in gath)
        else:  # int8 cross-slice wire: key per slice, not per device —
            # every device in a slice holds the identical partial and
            # must quantize it identically
            kd = jax.random.fold_in(
                key, jax.lax.axis_index(axis_name) // inner)
            red = tuple(
                _int8_leaf_reduce(v, i, kd, axis_name, bucket_size,
                                  groups=inter)
                for i, v in zip(g, part))
        for i, r in zip(g, red):
            out[i] = r
    return out


# ---------------------------------------------------------------------------
# mask-aware sparse plan
# ---------------------------------------------------------------------------

class SparsePlan(NamedTuple):
    """Host-built gather plan: per leaf the flat live-coordinate indices
    (None = dense leaf — non-kernel leaves, or kernels with no dead
    coordinate). Static per round-block: valid exactly while the mask it
    was built from is the live one (SalientGrads' SNIP mask is fixed for
    the whole run, ``masks_evolve=False``)."""

    idx: Tuple[Optional[np.ndarray], ...]
    dense_size: int
    compressed_size: int

    @property
    def density(self) -> float:
        return self.compressed_size / max(self.dense_size, 1)


def build_sparse_plan(mask: Any, stacked: bool = False) -> SparsePlan:
    """Gather plan from a CONCRETE mask pytree (host-side numpy walk — do
    not call under trace). ``stacked=True`` unions live coordinates over
    the leading client axis, producing the static shared index superset
    the compressed reduce needs."""
    from ..ops.sparsity import host_live_indices

    idx = tuple(host_live_indices(mask, stacked=stacked))
    leaves = jax.tree_util.tree_leaves(mask)
    dense = 0
    comp = 0
    for m, ix in zip(leaves, idx):
        size = int(np.prod(m.shape[1:] if stacked else m.shape))
        dense += size
        comp += size if ix is None else int(ix.size)
    return SparsePlan(idx=idx, dense_size=dense, compressed_size=comp)


def _plan_check(stacked: Any, plan: SparsePlan):
    leaves = jax.tree_util.tree_leaves(stacked)
    if len(leaves) != len(plan.idx):
        raise ValueError(
            f"sparse plan has {len(plan.idx)} leaves, tree has "
            f"{len(leaves)} — the plan was built for a different tree")
    return leaves


def _inverse_idx(ix: np.ndarray, size: int) -> np.ndarray:
    """dense coordinate -> compressed position, out-of-range (= the
    take-fill zero) for dead coordinates."""
    inv = np.full(size, ix.size, np.int32)
    inv[ix] = np.arange(ix.size, dtype=np.int32)
    return inv


def _expand_leaf(red: jax.Array, ix: Optional[np.ndarray],
                 shape, dtype) -> jax.Array:
    """Compressed reduced leaf -> dense layout via the static inverse-
    permutation GATHER (take with fill; scatter is ~40x slower on
    XLA:CPU). Dead coordinates of an honored-mask aggregate are exactly
    0 — the fill value."""
    size = int(np.prod(shape)) if shape else 1
    if ix is None:
        return red.reshape(shape).astype(dtype)
    out = jnp.take(red, jnp.asarray(_inverse_idx(ix, size)),
                   mode="fill", fill_value=0)
    return out.reshape(shape).astype(dtype)


def _compress(stacked: Any, plan: SparsePlan) -> jax.Array:
    """[C, ...]-stacked pytree -> [C, M_compressed] f32 matrix holding
    each dense leaf in full and each sparse leaf's live coordinates
    (the off-mesh spelling; on-mesh the same gather runs per device on
    its local clients inside shard_map)."""
    leaves = _plan_check(stacked, plan)
    c = leaves[0].shape[0]
    cols = []
    for x, ix in zip(leaves, plan.idx):
        flat = x.reshape(c, -1).astype(jnp.float32)
        cols.append(flat if ix is None
                    else jnp.take(flat, jnp.asarray(ix), axis=1))
    return jnp.concatenate(cols, axis=1)


def _expand_vec(vec: jax.Array, stacked: Any, plan: SparsePlan) -> Any:
    """Inverse of :func:`_compress` for the reduced [M_compressed]
    vector."""
    leaves, treedef = jax.tree_util.tree_flatten(stacked)
    out = []
    off = 0
    for x, ix in zip(leaves, plan.idx):
        shape = x.shape[1:]
        n = (int(np.prod(shape)) if shape else 1) if ix is None \
            else int(ix.size)
        out.append(_expand_leaf(vec[off:off + n], ix, shape, x.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# error-feedback top-k selection
# ---------------------------------------------------------------------------

def plan_dead_select(stacked: Any, plan: SparsePlan) -> Any:
    """Select-zero the DEAD coordinates of a [C, ...]-stacked pytree
    (a ``jnp.where`` against the plan's static live mask — never
    arithmetic, so NaN rows cannot smear). The topk round body applies
    it to the compensated deltas when a plan exists: dead coordinates
    must neither enter the residual (they would sit there forever —
    selection never ships them) nor the selection itself."""
    _plan_check(stacked, plan)
    leaves, treedef = jax.tree_util.tree_flatten(stacked)
    out = []
    for x, ix in zip(leaves, plan.idx):
        if ix is None:
            out.append(x)
            continue
        shape = x.shape[1:]
        size = int(np.prod(shape)) if shape else 1
        live = np.zeros(size, bool)
        live[ix] = True
        mask = jnp.asarray(live.reshape(shape))
        out.append(jnp.where(mask, x, jnp.zeros_like(x)))
    return jax.tree_util.tree_unflatten(treedef, out)


def topk_count(n: int, k_frac: float) -> int:
    """Selected-coordinate count for a segment of ``n`` coordinates at
    fraction ``k_frac`` — ``min(n, max(1, ceil(k_frac * n)))``. ONE
    rounding rule shared by the in-jit selection, the wire-cost model
    (obs/comm.py) and the serialization payload builder — but applied
    to different partitions: the model and ``topk_payload`` price/ship
    per LEAF (byte-exact against each other, pinned), while
    :func:`topk_sparsify` selects per leaf-GROUP bucket (many small
    leaves can share one threshold). The counts coincide when a group
    holds one leaf; when a bucket packs several small leaves the
    per-leaf ceil/``max(1,..)`` floors (and exact-threshold ties,
    which selection keeps) bound the difference — drift the
    error-feedback residual absorbs by construction."""
    if not 0.0 < k_frac <= 1.0:
        raise ValueError(f"topk density {k_frac} not in (0, 1]")
    return min(max(int(n), 1), max(1, int(np.ceil(k_frac * n))))


def topk_sparsify(stacked: Any, k_frac: float, *,
                  plan: Optional[SparsePlan] = None,
                  bucket_size: int = DEFAULT_BUCKET_SIZE,
                  sample: int = 0, kernels: str = "xla") -> Any:
    """Per-leaf-group top-k magnitude selection over a [C, ...]-stacked
    pytree: within each leaf-group bucket (the same
    :func:`_leaf_groups` partition every collective uses), each client
    keeps its ``topk_count(group_size, k_frac)`` largest-|value|
    coordinates and zeroes the rest. With a ``plan`` the selection runs
    on the COMPRESSED live coordinates (SalientGrads: k is a fraction
    of the live set, and dead coordinates — exact zeros on every
    honored-mask input — can never be selected ahead of live ones).

    Deterministic and trace-safe: the threshold is the k-th largest
    magnitude per (client, group); coordinates tying it exactly are all
    kept (a measure-zero event on continuous deltas, and the
    all-zero-row edge keeps the row unchanged — sparsifying an exact
    zero contributes exactly zero to wire and residual alike).

    The per-group threshold comes from ``ops.topk_select``'s
    threshold-refinement search (``kernels='xla'`` default / the pallas
    VMEM-resident kernel / the legacy ``'sort'`` ``lax.top_k``
    spelling) — every backend yields the SAME float, so they select
    bit-identical coordinate sets under the module's tie-break contract.
    The sort spelling was the wire's scaling wall (26.7 s/agg exact at
    scale-32, RESULTS Round-12; XLA:CPU ``top_k`` is sort-bound in n at
    any k); the bit-space search replaced it at ~O(31 n) compares with
    no trajectory change.

    ``sample > 0`` estimates each group's threshold from a strided
    ~``sample``-element subsample instead of the full row
    (``topk_select.sampled_threshold`` — the Deep Gradient Compression
    hierarchical-sampling trick): deterministic (fixed stride, no RNG),
    and the shipped count is only approximately k — which error
    feedback absorbs by construction (over- or under-selection just
    shifts coordinates between wire and residual). 0 (the default)
    keeps the exact selection, which the threshold backends price at a
    flat ~31 passes — sampling is an optimization now, not a
    necessity."""
    from ..ops.topk_select import select_threshold

    if plan is not None:
        _plan_check(stacked, plan)
    leaves = jax.tree_util.tree_leaves(stacked)
    idxs = plan.idx if plan is not None else (None,) * len(leaves)
    psizes = [
        (int(np.prod(x.shape[1:])) if x.ndim > 1 else 1)
        if ix is None else int(ix.size)
        for x, ix in zip(leaves, idxs)]
    groups = _leaf_groups(psizes, bucket_size)
    offs = np.concatenate([[0], np.cumsum(psizes)]).astype(int)
    mat = _compress(stacked, plan) if plan is not None \
        else stacked_to_mat(stacked)
    cols = []
    for g in groups:
        start, end = offs[g[0]], offs[g[-1] + 1]
        seg = mat[:, start:end]
        n = int(end - start)
        k = topk_count(n, k_frac)
        av = jnp.abs(seg)
        thr = select_threshold(av, k, kernels=kernels, sample=sample)
        cols.append(jnp.where(av >= thr, seg, jnp.zeros_like(seg)))
    sp_mat = jnp.concatenate(cols, axis=1)
    # rebuild the stacked tree layout (dense leaves reshape; compressed
    # leaves expand by the static inverse-permutation gather per client)
    treedef = jax.tree_util.tree_flatten(stacked)[1]
    out = []
    for i, (x, ix) in enumerate(zip(leaves, idxs)):
        block = sp_mat[:, offs[i]:offs[i + 1]]
        if ix is None:
            out.append(block.reshape(x.shape).astype(x.dtype))
        else:
            size = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
            dense = jnp.take(block, jnp.asarray(_inverse_idx(ix, size)),
                             axis=1, mode="fill", fill_value=0)
            out.append(dense.reshape(x.shape).astype(x.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def topk_weighted_mean(stacked: Any, weights: jax.Array, k_frac: float,
                       *, plan: Optional[SparsePlan] = None, mesh=None,
                       axis_name: str = "clients",
                       bucket_size: int = DEFAULT_BUCKET_SIZE,
                       overlap: bool = True,
                       sample: int = 0,
                       kernels: str = "xla") -> Tuple[Any, Any]:
    """The ``agg_impl='topk'`` aggregate: sparsify each client's row
    (:func:`topk_sparsify`), then the weighted mean of the sparsified
    rows through the bucketed (plan-compressed when given) reduce.
    Returns ``(aggregate, sparsified)`` — the caller owns the
    error-feedback bookkeeping (``residual' = compensated -
    sparsified``); callers without residual state use index [0].

    The selection is per-client-local (element-wise after the
    per-group threshold), so on a ``clients`` mesh it runs where each
    client's row lives and only the sparsified contraction crosses
    chips; the simulated reduce moves the dense-layout zeros, while the
    INFORMATION cost (k values + k indices per group) is what
    ``obs.comm.WireCostModel`` prices and a cross-silo transport ships
    (``obs.comm.topk_payload``)."""
    sp = topk_sparsify(stacked, k_frac, plan=plan,
                       bucket_size=bucket_size, sample=sample,
                       kernels=kernels)
    kw = dict(mesh=mesh, axis_name=axis_name, bucket_size=bucket_size,
              overlap=overlap, kernels=kernels)
    if plan is not None:
        agg = sparse_weighted_mean(sp, weights, plan, **kw)
    else:
        agg = weighted_mean(sp, weights, **kw)
    return agg, sp


# ---------------------------------------------------------------------------
# the public weighted means
# ---------------------------------------------------------------------------

def _reduce_mat(mat: jax.Array, weights: jax.Array, *,
                bucket_size: int = DEFAULT_BUCKET_SIZE,
                wire: str = "f32", rng: Optional[jax.Array] = None,
                kernels: str = "xla") -> jax.Array:
    """Off-mesh reduce: out[j] = sum_c weights[c] * mat[c, j] in bucket
    layout — element-for-element the dense reduction (bit-equal for
    ``wire='f32'``; the wire casts apply per client since there is no
    per-device partial to cast).

    ``kernels='pallas'`` routes the int8 wire through the fused
    quantize+reduce pallas kernel (ops/pallas_kernels.py): the
    stochastic-rounding uniforms and per-bucket scale are computed here
    with the exact rng call and spelling of the XLA chain, so the
    backends are bit-identical (pinned by tests/test_pallas_kernels.py);
    buckets that do not tile the kernel's panel fall back to the XLA
    chain unchanged. The f32/bf16 wires have no quantize chain to fuse
    and always use the tensordot spelling."""
    _check_wire(wire, rng)
    c, n = mat.shape
    w = weights.astype(jnp.float32)
    bucket_size = min(bucket_size, max(n, 1))
    nb = -(-n // bucket_size)
    pad = nb * bucket_size - n
    if pad:
        mat = jnp.pad(mat, ((0, 0), (0, pad)))
    buckets = mat.reshape(c, nb, bucket_size)
    if wire == "bf16":
        buckets = buckets.astype(jnp.bfloat16).astype(jnp.float32)
    elif wire == "int8":
        from ..ops import pallas_kernels as _pk

        if kernels == "pallas" and \
                _pk.quantize_reduce_supported(bucket_size):
            u = jax.random.uniform(rng, buckets.shape)
            scale = _int8_scale(buckets)
            out = _pk.fused_quantize_reduce(buckets, w, u,
                                            scale[..., 0])
            return out.reshape(-1)[:n]
        q, scale = _quantize_int8(buckets, rng)
        buckets = q.astype(jnp.float32) * scale
    out = jnp.tensordot(w, buckets, axes=1)
    return out.reshape(-1)[:n]


def _mesh_reduce_leaves(stacked: Any, weights: jax.Array, *, mesh,
                        axis_name: str, bucket_size: int, wire: str, rng,
                        plan: Optional[SparsePlan] = None,
                        masks: Any = None, hier_inner: int = 0,
                        overlap: bool = True) -> List[jax.Array]:
    """shard_map weighted reduce over the mesh-sharded client axis,
    returning the flat reduced payload per leaf (compressed to the plan's
    live coordinates when given; with ``masks`` the payload list is
    num-leaves followed by den-leaves). Each device contracts only its
    LOCAL clients — compressed BEFORE the contraction on the sparse path,
    so local compute and wire both scale with density — and each
    leaf-group bucket is one collective.

    ``hier_inner > 1`` routes each bucket through the two-stage
    hierarchical reduce (:func:`_hier_reduce_groups`: full-precision
    intra-slice psum, ``wire`` across slices). ``overlap`` (default)
    defers each leaf's local contraction into its group's reduce step so
    group k's collective and group k+1's contraction interleave in
    emission order — scheduling freedom only, bit-identical results."""
    key = rng if rng is not None else jax.random.PRNGKey(0)
    leaves = jax.tree_util.tree_leaves(stacked)
    idxs = plan.idx if plan is not None else (None,) * len(leaves)
    psizes = [
        (int(np.prod(x.shape[1:])) if x.ndim > 1 else 1)
        if ix is None else int(ix.size)
        for x, ix in zip(leaves, idxs)]
    if masks is not None:
        psizes = psizes * 2
    groups = _leaf_groups(psizes, bucket_size)
    jidx = [None if ix is None else jnp.asarray(ix) for ix in idxs]
    # hier_inner: 0 = single-stage (the default reduce); -1 = hier with
    # the auto slice split; > 1 = hier with that many devices per slice
    n_devices = int(mesh.shape[axis_name])
    inner = resolve_hier_inner(n_devices, max(hier_inner, 0)) \
        if hier_inner else 0
    if hier_inner and not inner:
        # one slice (hier_inner >= axis, or a <= 2-device axis): the
        # whole reduce lives inside the full-precision fast domain and
        # the configured CROSS-slice wire never fires — degrade to the
        # exact f32 bucketed reduce, the same degeneration as the
        # off-mesh fallback (weighted_mean's "wire never fires"
        # contract), instead of silently quantizing the intra-slice hop
        wire = "f32"
    if inner:
        def reduce_groups(payload, k):
            return _hier_reduce_groups(
                payload, groups, axis_name=axis_name, wire=wire, key=k,
                bucket_size=bucket_size, n_devices=n_devices,
                inner=inner)
    else:
        def reduce_groups(payload, k):
            return _wire_reduce_groups(
                payload, groups, axis_name=axis_name, wire=wire, key=k,
                bucket_size=bucket_size)

    def local_payload(st_leaves, wv):
        """Per-leaf local-contraction thunks: with ``overlap`` they are
        forced inside the group loop (contraction emitted right before
        its own collective); without, all up front (the serialized
        contract-everything-then-reduce order)."""
        def make(x, ix):
            def thunk():
                xf = x.reshape(x.shape[0], -1).astype(jnp.float32)
                if ix is not None:
                    xf = jnp.take(xf, ix, axis=1)
                return jnp.tensordot(wv, xf, axes=1)
            return thunk

        thunks = [make(x, ix) for x, ix in zip(st_leaves, jidx)]
        return thunks if overlap else [t() for t in thunks]

    # hier's axis_index_groups psums produce slice-varying intermediates
    # the static rep-checker cannot see through, so it is disabled there
    # like on the all_gather wires
    smap_kw = dict(_NOCHECK_KW) if inner else _shard_map_kw(wire)
    in_specs = (P(axis_name), P(axis_name), P())
    if masks is None:
        @partial(shard_map, mesh=mesh, in_specs=in_specs, out_specs=P(),
                 **smap_kw)
        def agg(st, wv, k):
            payload = local_payload(jax.tree_util.tree_leaves(st), wv)
            return tuple(reduce_groups(payload, k))

        return list(agg(stacked, weights.astype(jnp.float32), key))

    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis_name),) + in_specs, out_specs=P(),
             **smap_kw)
    def agg_masked(st, mk, wv, k):
        xm = jax.tree_util.tree_map(
            lambda x, m: x.astype(jnp.float32) * m.astype(jnp.float32),
            st, mk)
        payload = local_payload(jax.tree_util.tree_leaves(xm), wv) + \
            local_payload(jax.tree_util.tree_leaves(mk), wv)
        return tuple(reduce_groups(payload, k))

    return list(agg_masked(stacked, masks, weights.astype(jnp.float32),
                           key))


def weighted_mean(stacked: Any, weights: jax.Array, *, mesh=None,
                  axis_name: str = "clients",
                  bucket_size: int = DEFAULT_BUCKET_SIZE,
                  wire: str = "f32", rng: Optional[jax.Array] = None,
                  hier_inner: int = 0, overlap: bool = True,
                  kernels: str = "xla") -> Any:
    """Weighted mean over the leading client axis, via the bucketed
    (optionally low-precision-wire) reduce. Drop-in for
    ``core.state.weighted_tree_sum`` (callers pass already-normalized
    weights); ``wire='f32'`` off-mesh is bit-equal to it. With a usable
    ``clients`` mesh the whole reduce runs inside ``shard_map`` on
    per-leaf local partials with one collective per leaf-group bucket —
    the [C, N] client matrix is never materialized.

    ``hier_inner`` enables the two-stage hierarchical reduce on-mesh
    (full-precision psum inside each ``hier_inner``-device slice, then
    ``wire`` across slices; 0 = auto-split via
    :func:`resolve_hier_inner`). Off-mesh there are no slices and the
    fallback is the EXACT f32 bucketed contraction — the one-slice
    degeneration, in which the cross-slice wire never fires.

    ``kernels='pallas'`` fuses the off-mesh int8 wire's quantize+reduce
    into one pallas pass (see :func:`_reduce_mat`; bit-identical by
    contract). The on-mesh shard_map path keeps its per-device op chain
    unchanged — its wire quantize runs per DEVICE inside the collective,
    a different (and already collective-fused) dataflow."""
    _check_wire(wire, rng)
    leaves = jax.tree_util.tree_leaves(stacked)
    c = leaves[0].shape[0]
    if _mesh_axis_rows(mesh, axis_name, c):
        red = _mesh_reduce_leaves(
            stacked, weights, mesh=mesh, axis_name=axis_name,
            bucket_size=bucket_size, wire=wire, rng=rng,
            hier_inner=hier_inner, overlap=overlap)
        _, treedef = jax.tree_util.tree_flatten(stacked)
        return jax.tree_util.tree_unflatten(treedef, [
            r.reshape(x.shape[1:]).astype(x.dtype)
            for r, x in zip(red, leaves)])
    spec = flat_spec(stacked, stacked=True)
    vec = _reduce_mat(stacked_to_mat(stacked), weights,
                      bucket_size=bucket_size,
                      wire="f32" if hier_inner else wire, rng=rng,
                      kernels=kernels)
    return vec_to_tree(vec, spec)


def sparse_weighted_mean(stacked: Any, weights: jax.Array, plan: SparsePlan,
                         *, masks: Any = None, mesh=None,
                         axis_name: str = "clients",
                         bucket_size: int = DEFAULT_BUCKET_SIZE,
                         wire: str = "f32",
                         rng: Optional[jax.Array] = None,
                         hier_inner: int = 0,
                         overlap: bool = True,
                         kernels: str = "xla") -> Any:
    """Mask-aware sparse weighted mean: reduce only the plan's live
    coordinates — local compute and the cross-chip transfer scale with
    ~density — then rebuild the dense layout with one static inverse-
    permutation gather per leaf.

    ``masks=None`` (SalientGrads: one global mask, weights already
    normalized) is the plain weighted mean of honored-mask locals —
    bit-equal to the dense aggregate, whose dead coordinates are exactly
    0. With ``masks`` ([C, ...]-stacked per-client masks) the result is
    the mask-weighted mean ``sum(w*m*x) / sum(w*m)`` with BOTH numerator
    and denominator reduced on the compressed representation (coordinates
    no client holds live divide to 0) — bit-equal to the dense
    mask-weighted aggregate.
    """
    _check_wire(wire, rng)
    leaves = _plan_check(stacked, plan)
    treedef = jax.tree_util.tree_flatten(stacked)[1]
    c = leaves[0].shape[0]
    if _mesh_axis_rows(mesh, axis_name, c):
        red = _mesh_reduce_leaves(
            stacked, weights, mesh=mesh, axis_name=axis_name,
            bucket_size=bucket_size, wire=wire, rng=rng, plan=plan,
            masks=masks, hier_inner=hier_inner, overlap=overlap)
        if masks is not None:
            num, den = red[:len(leaves)], red[len(leaves):]
            red = [jnp.where(d > 0, n / jnp.where(d > 0, d, 1.0), 0.0)
                   for n, d in zip(num, den)]
        return jax.tree_util.tree_unflatten(treedef, [
            _expand_leaf(r, ix, x.shape[1:], x.dtype)
            for r, ix, x in zip(red, plan.idx, leaves)])
    kw = dict(bucket_size=bucket_size,
              wire="f32" if hier_inner else wire, rng=rng,
              kernels=kernels)
    if masks is None:
        vec = _reduce_mat(_compress(stacked, plan), weights, **kw)
        return _expand_vec(vec, stacked, plan)
    mmat = _compress(masks, plan)
    num = _reduce_mat(_compress(stacked, plan) * mmat, weights, **kw)
    if rng is not None:
        kw["rng"] = jax.random.fold_in(rng, 1)
    den = _reduce_mat(mmat, weights, **kw)
    vec = jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0)
    return _expand_vec(vec, stacked, plan)


def masked_weighted_mean(stacked: Any, weights: jax.Array,
                         masks: Any) -> Any:
    """Dense reference for the mask-weighted aggregate:
    ``sum_c w_c m_c x_c / sum_c w_c m_c`` per coordinate, 0 where no
    client holds the coordinate live (the ``sum(masks)`` denominator of
    the reference's sparse-personalized aggregation). The sparse path
    (:func:`sparse_weighted_mean` with ``masks``) is bit-equal to this."""
    w = weights.astype(jnp.float32)

    def leaf(x, m):
        xf = x.astype(jnp.float32)
        mf = m.astype(jnp.float32)
        num = jnp.tensordot(w, xf * mf, axes=1)
        den = jnp.tensordot(w, mf, axes=1)
        out = jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0)
        return out.astype(x.dtype)

    return jax.tree_util.tree_map(leaf, stacked, masks)


# ---------------------------------------------------------------------------
# micro-bench
# ---------------------------------------------------------------------------

def time_weighted_agg(agg_fn, stacked: Any, weights: jax.Array,
                      out_template: Any, iters: int = 8) -> float:
    """Wall-clock seconds per aggregation of ``agg_fn(stacked,
    weights, i)`` — THE timing harness for aggregation paths (shared
    by :func:`agg_microbench` and obs/comm.py's ``probe_agg_ms``, so
    probed and benched numbers stay methodology-comparable): an
    in-graph ``fori_loop`` over ``iters`` calls with ``jnp.roll``-ed
    weights so XLA cannot hoist the contraction, accumulated into an
    ``out_template``-shaped f32 tree, timed after one compile+warmup
    run (a scalar fetch forces completion — block_until_ready can
    return early on the tunneled platform)."""

    @jax.jit
    def run(st, wv):
        def body(i, acc):
            out = agg_fn(st, jnp.roll(wv, i), i)
            return jax.tree_util.tree_map(
                lambda a, o: a + o.astype(a.dtype), acc, out)

        acc0 = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, jnp.float32), out_template)
        return jax.lax.fori_loop(0, iters, body, acc0)

    out = run(stacked, weights)  # compile + warmup
    float(jax.tree_util.tree_leaves(out)[0].sum())
    t0 = time.perf_counter()
    out = run(stacked, weights)
    float(jax.tree_util.tree_leaves(out)[0].sum())
    return (time.perf_counter() - t0) / iters


def agg_microbench(mesh=None, n_clients: int = 32, iters: int = 8,
                   dense_ratio: float = 0.5,
                   bucket_size: int = DEFAULT_BUCKET_SIZE,
                   model_key: str = "3dcnn",
                   sample_shape: Tuple[int, ...] = (121, 145, 121, 1),
                   impls: Tuple[str, ...] = AGG_IMPLS,
                   topk_density: float = 0.1, topk_sample: int = 0,
                   hier_inner: int = 0, hier_wire: str = "bf16",
                   overlap: bool = True, kernels: str = "xla") -> dict:
    """Time one weighted-mean aggregation per ``agg_impl`` on the flagship
    parameter tree stacked over ``n_clients`` (honored-mask locals at
    ``dense_ratio``), sharded over ``mesh`` when given. Methodology
    follows ``__graft_entry__._agg_realparams_probe``: in-graph
    ``fori_loop`` bodies with ``jnp.roll``-ed weights so XLA cannot hoist
    the contraction, timed over ``iters`` aggregations after a
    compile+warmup run. Returns ``{"agg_ms_<impl>": ms, ...}`` plus, per
    timed impl, the ``obs.comm.WireCostModel``'s modeled per-device wire
    bytes as ``wire_bytes_<impl>`` (so the gated bench history tracks
    time AND bytes together) and the workload descriptors.

    ``kernels`` picks the selection/quantize backend for the impls that
    have one (int8, topk, hier) — the flag surface plus the internal
    ``'sort'`` legacy spelling, so the bench can still price the
    pre-threshold sort baseline the kernel leg replaced."""
    from ..core.state import weighted_tree_sum
    from ..models import create_model, init_params
    from ..ops.sparsity import kernel_flags
    from ..ops.topk_select import check_kernels

    check_kernels(kernels)

    model = create_model(model_key, num_classes=1)
    shapes = jax.eval_shape(
        lambda k: init_params(model, k, sample_shape), jax.random.PRNGKey(0))
    leaves, treedef = jax.tree_util.tree_flatten(shapes)
    n_params = sum(int(np.prod(l.shape)) for l in leaves)

    sharding = None
    if mesh is not None and "clients" in mesh.axis_names:
        from jax.sharding import NamedSharding

        sharding = NamedSharding(mesh, P("clients"))

    def put(x):
        return x if sharding is None else jax.device_put(x, sharding)

    # honored-mask stacked locals: a host-random SNIP-style mask at
    # dense_ratio on kernel leaves, applied to every client's tree
    flags = jax.tree_util.tree_leaves(
        kernel_flags(jax.tree_util.tree_unflatten(treedef, leaves)))
    rs = np.random.RandomState(0)
    key = jax.random.PRNGKey(0)
    mask_leaves, stacked_leaves = [], []
    for i, (l, k) in enumerate(zip(leaves, flags)):
        m = (rs.rand(*l.shape) < dense_ratio).astype(np.float32) \
            if k else np.ones(l.shape, np.float32)
        mask_leaves.append(jnp.asarray(m))
        x = jax.random.normal(jax.random.fold_in(key, i),
                              (n_clients,) + tuple(l.shape),
                              jnp.float32) * 0.01
        stacked_leaves.append(put(x * m[None]))
    mask = jax.tree_util.tree_unflatten(treedef, mask_leaves)
    stacked = jax.tree_util.tree_unflatten(treedef, stacked_leaves)
    w = rs.rand(n_clients).astype(np.float32)
    w = put(jnp.asarray(w / w.sum()))
    plan = build_sparse_plan(mask)

    kw = dict(mesh=mesh, bucket_size=bucket_size, overlap=overlap)
    hw = "f32" if hier_wire == "sparse" else hier_wire
    agg_fns = {
        "dense": lambda st, wv, i: weighted_tree_sum(st, wv),
        "bucketed": lambda st, wv, i: weighted_mean(st, wv, wire="f32",
                                                    **kw),
        "bf16": lambda st, wv, i: weighted_mean(st, wv, wire="bf16", **kw),
        "int8": lambda st, wv, i: weighted_mean(
            st, wv, wire="int8", rng=jax.random.fold_in(key, i),
            kernels=kernels, **kw),
        "sparse": lambda st, wv, i: sparse_weighted_mean(st, wv, plan,
                                                         wire="f32", **kw),
        "topk": lambda st, wv, i: topk_weighted_mean(
            st, wv, topk_density, plan=plan, sample=topk_sample,
            kernels=kernels, **kw)[0],
        # hier: auto slice split unless requested; int8 cross-slice wire
        # draws its stochastic-rounding key like the int8 impl
        "hier": lambda st, wv, i: (
            sparse_weighted_mean(st, wv, plan, wire="f32",
                                 hier_inner=hier_inner or -1, **kw)
            if hier_wire == "sparse" else weighted_mean(
                st, wv, wire=hw, hier_inner=hier_inner or -1,
                rng=(jax.random.fold_in(key, i) if hw == "int8"
                     else None), kernels=kernels, **kw)),
    }

    def time_agg(agg_fn):
        return time_weighted_agg(agg_fn, stacked, w, shapes, iters)

    # timings flow through the PROCESS-GLOBAL obs registry (labeled by
    # impl) and the bench dict is read back from it — the bench/tooling
    # surface; note an ObsSession snapshots its own per-run registry,
    # so these do NOT land in a run's metrics.json
    from ..obs import metrics as obs_metrics

    agg_dist = obs_metrics.get_registry().distribution("agg_ms")
    result = {}
    n_devices = (int(mesh.shape["clients"]) if mesh is not None
                 and "clients" in mesh.axis_names else 1)
    # modeled per-device wire bytes per impl (obs/comm.py) — recorded
    # beside the timings so the gated history tracks ms AND bytes
    from ..obs.comm import WireCostModel

    wire_model = WireCostModel.from_params(
        shapes, bucket_size=bucket_size, n_devices=n_devices, plan=plan,
        topk_density=topk_density, hier_wire=hier_wire)
    for name in impls:
        if name not in agg_fns:
            # a typo'd --impls must fail loudly, not print a timing-less
            # JSON line that appends nothing to the gated history
            raise ValueError(
                f"unknown agg impl {name!r}; choose from "
                f"{tuple(agg_fns)}")
        agg_dist.labels(impl=name).observe(time_agg(agg_fns[name]) * 1e3)
        result[f"agg_ms_{name}"] = agg_dist.labels(impl=name).last
        result[f"wire_bytes_{name}"] = wire_model.bytes_for(name)
    result.update(
        n_params=n_params, n_clients=n_clients, n_devices=n_devices,
        bucket_size=bucket_size, sparse_density=plan.density,
        topk_density=topk_density, topk_sample=topk_sample,
        hier_wire=hier_wire, hier_inner=hier_inner,
        overlap=int(overlap), model_key=model_key, iters=iters,
        kernels=kernels)
    return result
