"""Device mesh + sharding helpers.

This is the TPU-native replacement for the reference's distributed substrate
(``fedml_core/distributed/``: MPI send/recv daemon threads with pickled
state_dicts, ``mpi/com_manager.py:13-98``): instead of explicit peer sends,
per-client values carry a leading client axis laid out over a ``clients`` mesh
axis, and aggregation/gossip lower to XLA collectives over ICI. Multi-host
(DCN) uses the same mesh spanning all processes after
``jax.distributed.initialize`` — see ``parallel/multihost.py``.

Mesh axes:
  * ``clients`` — the federated axis: one (or more) simulated site/hospital
    client per device.
  * ``space``   — optional spatial axis for sharding a single 3D volume's
    conv grid across devices (this framework's sequence/context-parallel
    analogue; see SURVEY.md §5.7 — consumer lands in parallel/spatial.py).
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_client_devices: Optional[int] = None,
    n_space: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (clients[, space]) mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_client_devices is None:
        n_client_devices = len(devices) // n_space
    n_total = n_client_devices * n_space
    if n_total > len(devices):
        raise ValueError(
            f"mesh needs {n_total} devices, have {len(devices)}"
        )
    arr = np.array(devices[:n_total])
    if n_space == 1:
        return Mesh(arr.reshape(n_client_devices), ("clients",))
    return Mesh(arr.reshape(n_client_devices, n_space), ("clients", "space"))


def fit_client_devices(n_clients: int, available: int) -> int:
    """Largest device count <= available that divides ``n_clients`` (the
    clients mesh axis must divide the client count). Shared by the runner
    and bench.py so device-fitting policy lives in one place."""
    n = min(max(1, available), max(1, n_clients))
    while n_clients % n:
        n -= 1
    return n


def mesh_of(tree: Any) -> Optional[Mesh]:
    """The live :class:`Mesh` behind any ``NamedSharding`` leaf of
    ``tree`` (None when the pytree is unsharded / single-device). Lets the
    aggregation collectives (``parallel/collectives.py``) discover the
    ``clients`` mesh the data was placed on without threading a mesh
    handle through every algorithm constructor."""
    for leaf in jax.tree_util.tree_leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        if isinstance(mesh, Mesh) and mesh.axis_names:
            return mesh
    return None


def shard_over_clients(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree whose leaves have a leading client axis onto the mesh,
    sharded over ``clients``."""
    sharding = NamedSharding(mesh, P("clients"))
    return jax.device_put(tree, sharding)


def shard_federated_hybrid(tree: Any, mesh: Mesh) -> Any:
    """Place a FederatedData pytree on a (clients[, space]) mesh: the client
    axis over ``clients`` and — when the mesh has a ``space`` axis — each
    volume's depth (leaf axis 2 of the [C, n, D, H, W, ...] arrays) over
    ``space``. Labels/counts ([C, n] / [C]) shard over clients only."""
    has_space = "space" in mesh.axis_names

    def put(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x
        if has_space and x.ndim >= 3:
            spec = P("clients", None, "space")
        else:
            spec = P("clients")
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Replicate a pytree (e.g. global model params) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def client_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("clients"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
