"""Multi-host (pod / multi-slice) support: DCN init + global client arrays.

SURVEY §7.9: the reference's only inter-process substrate is the orphaned
MPI/gRPC message layer; scaling there means one SLURM process on one GPU.
Here multi-host is the same SPMD program on more chips:

  1. every process calls :func:`initialize_distributed` (on TPU pods JAX
     auto-detects coordinator/process ids from the TPU environment);
  2. :func:`make_multihost_mesh` lays the ``clients`` axis over ALL global
     devices — contiguous per process, so one federated client's local
     training never straddles DCN, and the per-round weighted-mean
     aggregation is the only cross-host collective;
  3. each process loads only its own clients' shards
     (:func:`local_client_indices`) and assembles the global client-sharded
     arrays with :func:`make_global_client_array` — no host ever
     materializes the full cohort (the reference loads everything into one
     host's RAM, ``ABCD/data_loader.py:105-136``).

Single-process runs degrade to the plain ``make_mesh`` path, so everything
here is exercised by the CPU test mesh too.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Optional, TypeVar

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

_T = TypeVar("_T")


def _with_retries(what: str, fn: Callable[[], _T],
                  max_retries: int = 2,
                  backoff_s: float = 5.0) -> _T:
    """Bounded retries for STARTUP host-sync points (the jax.distributed
    coordinator handshake, where every process retries in lockstep until
    the coordinator appears): transient runtime/IO errors retry with
    linear backoff, the final failure propagates. Mid-run collectives
    are NEVER retried per-process (see host_client_counts) — that would
    break the SPMD collective-matching invariant."""
    retries = max(0, int(max_retries))
    delay = float(backoff_s)
    for attempt in range(retries + 1):
        try:
            return fn()
        except (RuntimeError, OSError, TimeoutError) as e:
            if attempt >= retries:
                raise
            logger.warning(
                "%s failed (%s: %s); retry %d/%d in %.1fs", what,
                type(e).__name__, e, attempt + 1, retries,
                delay * (attempt + 1))
            time.sleep(delay * (attempt + 1))
    raise RuntimeError(f"unreachable: {what} retry loop")  # pragma: no cover


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    timeout_s: Optional[float] = None,
    max_retries: Optional[int] = None,
) -> bool:
    """Idempotent ``jax.distributed.initialize`` wrapper.

    On TPU pods all three arguments are auto-detected from the runtime
    environment; pass them explicitly for CPU/GPU clusters. Returns True if
    a multi-process runtime is active after the call.

    ``timeout_s`` bounds the coordinator handshake (older jax without the
    ``initialization_timeout`` parameter falls back to its default), and
    transient init failures retry under ``max_retries`` bounded retries
    with linear backoff — a slow coordinator degrades to a few logged
    retries instead of hanging the whole SLURM allocation.

    MUST run before anything initializes the XLA backend (even
    ``jax.devices()``/``jax.process_count()`` counts) — which is also why
    this function itself touches no backend state before calling
    ``jax.distributed.initialize``.
    """
    explicit = not (coordinator_address is None and num_processes is None)

    class _Permanent(Exception):
        """Non-transient init outcome — bypasses the retry loop."""

    def _init_once() -> None:
        kw = {}
        if explicit:
            kw = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
        try:
            if timeout_s:
                try:
                    # ceil, floor 1: int() truncation would turn a
                    # sub-second --multihost_timeout_s into an instant
                    # zero-second handshake timeout
                    jax.distributed.initialize(
                        initialization_timeout=max(
                            1, int(-(-float(timeout_s) // 1))), **kw)
                    return
                except TypeError:
                    logger.warning(
                        "this jax has no initialization_timeout parameter;"
                        " using its default handshake timeout")
            jax.distributed.initialize(**kw)
        except (RuntimeError, OSError, TimeoutError) as e:
            msg = str(e)
            if isinstance(e, RuntimeError) and (
                    ("already" in msg and "initialize" in msg) or
                    ("before" in msg and "XLA backend" in msg) or
                    "only be called once" in msg):
                raise _Permanent() from e  # retrying cannot change these
            # transient failure (connect timeout, coordinator refused —
            # any of the retryable error types): jax assigns
            # global_state.client BEFORE the connect, so without a
            # shutdown the re-attempt would die with 'initialize should
            # only be called once' instead of retrying the handshake
            try:
                jax.distributed.shutdown()
            except Exception:  # never-connected client; nothing to undo
                logger.debug("post-failure distributed shutdown noop",
                             exc_info=True)
            raise

    try:
        try:
            _with_retries(
                "jax.distributed.initialize", _init_once,
                # auto-detect mode never retries (a missing cluster env
                # is not transient); None = the default budget
                max_retries=(max_retries if max_retries is not None
                             else 2) if explicit else 0)
        except _Permanent as p:
            raise p.__cause__  # classified below exactly as before
    except RuntimeError as e:
        msg = str(e)
        if "already" in msg and "initialize" in msg:
            pass  # repeated call — fine, keep the existing runtime
        elif "before" in msg and "XLA backend" in msg:
            # too late: something already touched the backend. Silently
            # degrading here would mean every pod host training alone.
            raise RuntimeError(
                "initialize_distributed() was called after the XLA backend "
                "was initialized — call it first (before jax.devices(), "
                "device_put, jit, ...). The CLI does this when --multihost "
                "is set.") from e
        elif explicit:
            raise
        else:
            # auto-detect found no cluster environment: single-process run
            logger.info("single-process run (distributed init skipped: %s)",
                        e)
            return False
    except ValueError as e:
        if explicit:
            raise
        logger.info("single-process run (distributed init skipped: %s)", e)
        return False
    return jax.process_count() > 1


def make_multihost_mesh(n_space: int = 1,
                        num_clients: Optional[int] = None,
                        max_client_devices: Optional[int] = None) -> Mesh:
    """(clients[, space]) mesh over every device of every process.

    Device order keeps each process's devices contiguous along ``clients``
    (jax.devices() global order), so client shards are process-local and
    ICI carries all per-client work; only the aggregation collective
    crosses DCN. ``space`` subdivides each client's devices for volume
    sharding (parallel/spatial.py) and must divide the per-process device
    count so halo exchanges stay on ICI (enforced).

    ``num_clients``/``max_client_devices`` shrink the clients axis (like
    the single-host runner path) until it divides ``num_clients`` and
    splits evenly across processes — e.g. the canonical 8-client workload
    on a 32-chip pod gets an 8-row clients axis, not a crash.
    """
    if n_space > 1 and jax.local_device_count() % n_space:
        raise ValueError(
            f"{n_space=} must divide the per-process device count "
            f"{jax.local_device_count()} so a client's space shards (and "
            "their halo exchanges) stay on one host's ICI")
    devices = jax.devices()
    n_proc = jax.process_count()
    rows = len(devices) // n_space
    if max_client_devices:
        rows = min(rows, max_client_devices)
    if num_clients is not None:
        rows = min(rows, num_clients)
        # rows must divide num_clients and split evenly over processes
        while rows > 1 and (num_clients % rows or rows % n_proc):
            rows -= 1
        if num_clients % rows or rows % n_proc:
            raise ValueError(
                f"cannot lay {num_clients} clients over {n_proc} processes")
    else:
        # even without a client count, rows must split evenly over
        # processes or the balanced device selection below under-fills
        rows -= rows % n_proc
        if rows < n_proc:
            raise ValueError(
                f"clients axis of {rows} rows cannot span {n_proc} "
                "processes; raise max_client_devices")
    # take an equal number of devices from every process, so a shrunk
    # clients axis still spreads across all hosts (a global-order prefix
    # would put every row on the first hosts and starve the rest)
    per_proc = (rows // n_proc) * n_space
    chosen = []
    for p in range(n_proc):
        pdevs = [d for d in devices if d.process_index == p]
        chosen.extend(pdevs[:per_proc])
    arr = np.array(chosen).reshape(rows, n_space)
    if n_space == 1:
        return Mesh(arr.reshape(-1), ("clients",))
    return Mesh(arr, ("clients", "space"))


def local_client_indices(num_clients: int, mesh: Mesh) -> np.ndarray:
    """Client ids whose data THIS process must load.

    Clients are block-distributed over the ``clients`` mesh axis; a
    process owns the clients that land on its addressable devices.
    """
    axis = list(mesh.axis_names).index("clients")
    mesh_devices = np.moveaxis(mesh.devices, axis, 0).reshape(
        mesh.shape["clients"], -1)
    n_rows = mesh_devices.shape[0]
    if num_clients % n_rows:
        raise ValueError(
            f"{num_clients=} must be a multiple of the clients mesh "
            f"extent {n_rows}")
    per_row = num_clients // n_rows
    pid = jax.process_index()
    mine = [r for r in range(n_rows)
            if mesh_devices[r, 0].process_index == pid]
    return np.concatenate([
        np.arange(r * per_row, (r + 1) * per_row) for r in mine
    ]) if mine else np.zeros((0,), np.int64)


def make_global_client_array(local_rows: np.ndarray, global_shape: tuple,
                             mesh: Mesh) -> jax.Array:
    """Assemble a global client-sharded array from this process's rows.

    ``local_rows`` must hold exactly the rows of
    :func:`local_client_indices` in order; the result is a global
    ``jax.Array`` sharded ``P("clients")`` whose addressable shards came
    only from local memory.
    """
    sharding = NamedSharding(mesh, P("clients"))
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_rows), global_shape)


def host_client_counts(n) -> np.ndarray:
    """Per-client sample counts as a host ndarray, safe for multi-host
    global arrays.

    ``n_train`` is client-sharded on a multi-host mesh, so a plain
    ``np.asarray`` raises (non-addressable shards). Every process then
    needs the SAME answer — derived hyperparameters like
    ``steps_per_epoch`` and the epoch fast-path flag feed jitted program
    construction, and divergent values would desync the SPMD programs —
    so the local shards are allgathered (clients are contiguous per
    process, ``local_client_indices``)."""
    try:
        return np.asarray(n)
    except RuntimeError:
        pass
    from jax.experimental import multihost_utils

    shards = sorted(n.addressable_shards,
                    key=lambda s: (s.index[0].start or 0))
    local = np.concatenate([np.asarray(s.data).ravel() for s in shards])
    # NOTE deliberately NOT retried: a mid-run collective must execute in
    # lockstep across processes — one host re-issuing its allgather while
    # peers (which succeeded) have moved on would hang against no
    # counterpart or pair with a LATER collective and garble data. The
    # bounded-retry policy (_with_retries) applies only to the startup
    # handshake (initialize_distributed), where every process is retrying
    # until the coordinator appears; mid-run sync points are protected by
    # the init-time timeout instead (a failure here fails fast).
    gathered = multihost_utils.process_allgather(local)
    return np.asarray(gathered).ravel()


def shard_federated_data_global(local_data: Any, num_clients: int,
                                mesh: Mesh) -> Any:
    """Lift a process-local FederatedData (holding only this process's
    clients, in ``local_client_indices`` order) to the global sharded
    pytree every process passes to the same jitted round.

    On a (clients, space) mesh the volume arrays ([C, n, D, ...]) are
    additionally depth-sharded over ``space`` (context parallelism) — the
    same placement as the single-host ``shard_federated_hybrid``."""
    has_space = "space" in mesh.axis_names

    def lift(x):
        x = np.asarray(x)
        if has_space and x.ndim >= 3:
            spec = P("clients", None, "space")
        else:
            spec = P("clients")
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(x),
            (num_clients,) + x.shape[1:])

    return jax.tree_util.tree_map(lift, local_data)
