"""Spatial (volume) parallelism — this framework's sequence/context-parallel slot.

The reference has no sequence axis (3D CNNs over fixed 121x145x121 volumes;
SURVEY.md §5.7): the analogous long-context scaling axis here is the *conv
grid of a single volume*. When one volume (or the activations of a deep 3D
net on it) exceeds per-core HBM, we shard the depth axis of the volume across
a ``space`` mesh axis, the way ring attention shards the sequence axis.

Two complementary paths:

1. **GSPMD path** (production default): annotate the batch with
   ``PartitionSpec(None, "space")`` (depth axis sharded) and jit the normal
   forward/train step over the mesh. XLA's SPMD partitioner inserts the halo
   exchanges for every conv/pool automatically and overlaps them with
   compute. Use :func:`shard_spatial` + any jitted function.

2. **Explicit halo-exchange path**: :func:`halo_exchange` /
   :func:`sharded_conv3d` implement the ring-communication pattern by hand
   with ``lax.ppermute`` under ``shard_map`` — the direct analogue of ring
   attention's neighbor exchange, for cases where manual scheduling beats
   GSPMD (custom fused kernels, pallas) and as an executable spec that the
   GSPMD path is tested against.

The reference's closest artifact is the host-RAM-bound full-cohort load
(``ABCD/data_loader.py:105-136``) — it has no answer to a volume that does
not fit one device; this module is that answer.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:  # jax >= 0.7 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax ships it in experimental
    from jax.experimental.shard_map import shard_map

import inspect as _inspect

#: disable shard_map's static replication check portably: the kwarg is
#: ``check_vma`` on current jax, ``check_rep`` on older releases
NOCHECK_KW = (
    {"check_rep": False}
    if "check_rep" in _inspect.signature(shard_map).parameters
    else {"check_vma": False})

SPACE_AXIS = "space"


# ---------------------------------------------------------------------------
# GSPMD path
# ---------------------------------------------------------------------------

def spatial_spec(batch_ndim: int = 5, axis_name: str = SPACE_AXIS) -> P:
    """PartitionSpec sharding the depth axis of an (N, D, H, W, C) batch."""
    return P(*([None, axis_name] + [None] * (batch_ndim - 2)))


def shard_spatial(x: jax.Array, mesh: Mesh, axis_name: str = SPACE_AXIS):
    """Place a volume batch on the mesh with the depth axis sharded.

    jax requires the depth extent to divide the ``space`` axis size; for
    volumes that don't (the canonical ABCD 121x145x121 has no power-of-two
    factors), zero-pad the depth first with :func:`pad_depth_to` — neutral
    for brain-masked MRI data whose background is already zero
    (``Preprocess_ABCD.ipynb`` mean-mask step).
    """
    n = mesh.shape[axis_name]
    if x.shape[1] % n:
        raise ValueError(
            f"depth {x.shape[1]} not divisible by space axis {n}; "
            "pad with parallel.spatial.pad_depth_to(x, n) first"
        )
    return jax.device_put(x, NamedSharding(mesh, spatial_spec(x.ndim, axis_name)))


def pad_depth_to(x, multiple: int, depth_axis: int = 1):
    """Zero-pad the depth axis up to the next multiple (background padding).

    Note conv arithmetic sees the padded extent, so model init must use the
    padded shape too — flax infers Dense fan-in at init, nothing else changes.
    Host numpy arrays stay on host (padding a full cohort must not stage it
    onto one device before sharding).
    """
    import numpy as np

    d = x.shape[depth_axis]
    pad = (-d) % multiple
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[depth_axis] = (0, pad)
    xp = jnp if isinstance(x, jax.Array) else np
    return xp.pad(x, widths)


def make_spatial_forward(
    apply_fn: Callable[..., Any],
    mesh: Mesh,
    axis_name: str = SPACE_AXIS,
):
    """Jit the eval-mode forward with params replicated and ``x``
    depth-sharded over ``axis_name``. XLA GSPMD inserts conv halo exchanges.

    Returns ``fwd(params, x) -> logits`` (train=False, no dropout rng);
    ``apply_fn`` must follow the model-zoo signature
    ``apply_fn(params, x, train, rng)``. For a sharded *training* step just
    jit your own step with the same shardings — see
    tests/test_spatial.py::test_hybrid_clients_space_grad_step.
    """
    repl = NamedSharding(mesh, P())

    @functools.partial(
        jax.jit,
        in_shardings=(repl, NamedSharding(mesh, spatial_spec(5, axis_name))),
        static_argnums=(),
    )
    def fwd(params, x):
        return apply_fn(params, x, train=False, rng=None)

    return fwd


# ---------------------------------------------------------------------------
# Explicit halo-exchange path (ring-attention-style neighbor comms)
# ---------------------------------------------------------------------------

def halo_exchange(
    x: jax.Array,
    halo: int,
    axis_name: str = SPACE_AXIS,
    *,
    depth_axis: int = 1,
) -> jax.Array:
    """Exchange ``halo`` planes with ring neighbors along a sharded depth axis.

    Must be called inside ``shard_map``/``pmap`` with ``axis_name`` bound.
    ``x`` is this shard's local block; returns the block extended by ``halo``
    planes on each side. Boundary shards (first/last) receive zeros — i.e.
    non-periodic zero-padding semantics, matching a conv with integer padding.

    This is the framework's ring-communication primitive: two ``ppermute``
    shifts (one per direction) over the ICI ring, exactly the neighbor
    exchange at the heart of ring attention.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)

    def dslice(start, size):
        return lax.slice_in_dim(x, start, start + size, axis=depth_axis)

    d_local = x.shape[depth_axis]
    if halo > d_local:
        raise ValueError(f"halo {halo} exceeds local depth {d_local}")

    # send my top `halo` planes to the next shard (they become its lower halo)
    top = dslice(d_local - halo, halo)
    lo_halo = lax.ppermute(top, axis_name, [(i, (i + 1) % n) for i in range(n)])
    # send my bottom `halo` planes to the previous shard (its upper halo)
    bot = dslice(0, halo)
    hi_halo = lax.ppermute(bot, axis_name, [(i, (i - 1) % n) for i in range(n)])

    zeros = jnp.zeros_like(lo_halo)
    lo_halo = jnp.where(idx == 0, zeros, lo_halo)
    hi_halo = jnp.where(idx == n - 1, zeros, hi_halo)
    return jnp.concatenate([lo_halo, x, hi_halo], axis=depth_axis)


def sharded_conv3d(
    x: jax.Array,
    kernel: jax.Array,
    bias: Optional[jax.Array] = None,
    axis_name: str = SPACE_AXIS,
) -> jax.Array:
    """Depth-sharded stride-1 'same' 3D conv via explicit halo exchange.

    Inside ``shard_map``: ``x`` is the local (N, D_local, H, W, Cin) block of
    a depth-sharded batch; ``kernel`` is the replicated (kd, kh, kw, Cin,
    Cout) filter with odd kd. Produces the local block of the conv with
    torch-style padding ``p = k//2`` on every spatial dim (so global output
    shape == global input shape).
    """
    kd, kh, kw = kernel.shape[:3]
    if kd % 2 != 1:
        raise ValueError("explicit path requires odd depth kernel")
    x = halo_exchange(x, kd // 2, axis_name)
    out = lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1, 1),
        padding=[(0, 0), (kh // 2, kh // 2), (kw // 2, kw // 2)],
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )
    if bias is not None:
        out = out + bias
    return out


def make_sharded_conv3d(mesh: Mesh, axis_name: str = SPACE_AXIS):
    """shard_map-wrapped :func:`sharded_conv3d` over ``mesh``.

    Returns ``f(x, kernel, bias) -> y`` where ``x``/``y`` are global arrays
    depth-sharded over ``axis_name`` and the filter/bias are replicated.
    """
    spec_x = spatial_spec(5, axis_name)

    def local(x, kernel, bias):
        return sharded_conv3d(x, kernel, bias, axis_name)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec_x, P(), P()),
        out_specs=spec_x,
        **NOCHECK_KW,
    )


def pad_federated_depth(data: Any, multiple: int) -> Any:
    """Zero-pad every volume array of a FederatedData so its depth (axis 2
    of the [C, n, D, H, W, ...] layout) divides the ``space`` mesh axis.

    Background padding is neutral for brain-masked MRI (the cohort's
    background is already zero, ``Preprocess_ABCD.ipynb`` mean-mask step);
    model init must use the padded sample shape (flax infers Dense fan-in
    at init), which falls out naturally when the data is padded before the
    algorithm is constructed."""
    if multiple <= 1:
        return data

    def pad(x):
        if x is None:
            return None
        return pad_depth_to(x, multiple, depth_axis=2)

    return data.replace(
        x_train=pad(data.x_train), x_test=pad(data.x_test),
        x_val=pad(data.x_val))


# ---------------------------------------------------------------------------
# Hybrid client x space training-step sharding
# ---------------------------------------------------------------------------

def hybrid_batch_spec(axis_name: str = SPACE_AXIS) -> P:
    """Spec for a federated volume batch (clients, n, D, H, W, C): client
    axis over ``clients``, depth over ``space`` — FL data parallelism and
    volume parallelism composed on one mesh."""
    return P("clients", None, axis_name)


def shard_hybrid(x: jax.Array, mesh: Mesh, axis_name: str = SPACE_AXIS):
    """Place a (clients, n, D, H, W, C) federated batch with the client axis
    over ``clients`` and volume depth over ``space``."""
    return jax.device_put(x, NamedSharding(mesh, hybrid_batch_spec(axis_name)))
