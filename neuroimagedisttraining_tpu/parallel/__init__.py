from .mesh import make_mesh, shard_over_clients, replicate

__all__ = ["make_mesh", "shard_over_clients", "replicate"]
