from .collectives import (
    build_sparse_plan,
    masked_weighted_mean,
    sparse_weighted_mean,
    weighted_mean,
)
from .gossip import ring_mix
from .mesh import make_mesh, mesh_of, shard_over_clients, replicate
from .multihost import (
    initialize_distributed,
    local_client_indices,
    make_global_client_array,
    make_multihost_mesh,
    shard_federated_data_global,
)
from .spatial import (
    halo_exchange,
    make_sharded_conv3d,
    make_spatial_forward,
    shard_hybrid,
    shard_spatial,
    sharded_conv3d,
    spatial_spec,
)

__all__ = [
    "build_sparse_plan",
    "masked_weighted_mean",
    "sparse_weighted_mean",
    "weighted_mean",
    "ring_mix",
    "make_mesh",
    "mesh_of",
    "shard_over_clients",
    "replicate",
    "initialize_distributed",
    "local_client_indices",
    "make_global_client_array",
    "make_multihost_mesh",
    "shard_federated_data_global",
    "halo_exchange",
    "make_sharded_conv3d",
    "make_spatial_forward",
    "shard_hybrid",
    "shard_spatial",
    "sharded_conv3d",
    "spatial_spec",
]
