from .gossip import ring_mix
from .mesh import make_mesh, shard_over_clients, replicate
from .multihost import (
    initialize_distributed,
    local_client_indices,
    make_global_client_array,
    make_multihost_mesh,
    shard_federated_data_global,
)
from .spatial import (
    halo_exchange,
    make_sharded_conv3d,
    make_spatial_forward,
    shard_hybrid,
    shard_spatial,
    sharded_conv3d,
    spatial_spec,
)

__all__ = [
    "ring_mix",
    "make_mesh",
    "shard_over_clients",
    "replicate",
    "initialize_distributed",
    "local_client_indices",
    "make_global_client_array",
    "make_multihost_mesh",
    "shard_federated_data_global",
    "halo_exchange",
    "make_sharded_conv3d",
    "make_spatial_forward",
    "shard_hybrid",
    "shard_spatial",
    "sharded_conv3d",
    "spatial_spec",
]
