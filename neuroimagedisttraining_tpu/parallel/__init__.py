from .mesh import make_mesh, shard_over_clients, replicate
from .spatial import (
    halo_exchange,
    make_sharded_conv3d,
    make_spatial_forward,
    shard_hybrid,
    shard_spatial,
    sharded_conv3d,
    spatial_spec,
)

__all__ = [
    "make_mesh",
    "shard_over_clients",
    "replicate",
    "halo_exchange",
    "make_sharded_conv3d",
    "make_spatial_forward",
    "shard_hybrid",
    "shard_spatial",
    "sharded_conv3d",
    "spatial_spec",
]
