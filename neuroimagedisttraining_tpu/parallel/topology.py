"""Gossip topologies and per-round neighbor adjacency.

TPU-native re-design of two reference subsystems:

* Topology managers (``fedml_core/distributed/topology/``): weighted gossip
  matrices built from Watts-Strogatz graphs with rewiring probability 0 —
  i.e. deterministic ring lattices — symmetric
  (``symmetric_topology_manager.py:16-78``: ring + k-nearest-neighbor links,
  self-loops, row-normalized) and asymmetric
  (``asymmetric_topology_manager.py:17-100``: symmetric base with randomly
  dropped directed links). No networkx needed: ws(n, k, p=0) is the
  circulant lattice.

* Per-round neighbor choice (``DisPFL/dispfl_api.py:196-220`` /
  ``dpsgd_api.py:116-139`` ``_benefit_choose``): random (excluding self),
  ring, or full (active clients only); self is appended when participation
  is partial.

Downstream these become a dense [C, C] mixing matrix contracted against the
client-stacked state pytree — on a sharded mesh XLA lowers that to
all-gather/reduce collectives over ICI, the TPU analogue of the reference's
per-edge message passing.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def ring_lattice(n: int, k: int) -> np.ndarray:
    """Adjacency of the circulant lattice: each node linked to its k nearest
    neighbors (k//2 on each side) — watts_strogatz_graph(n, k, 0)."""
    a = np.zeros((n, n), dtype=np.float32)
    half = max(1, k // 2)
    for off in range(1, half + 1):
        for i in range(n):
            a[i, (i + off) % n] = 1.0
            a[i, (i - off) % n] = 1.0
    return a


class SymmetricTopologyManager:
    """Row-normalized symmetric gossip matrix: ring ∪ k-lattice + self-loops
    (symmetric_topology_manager.py:21-52)."""

    def __init__(self, n: int, neighbor_num: int = 2):
        self.n = n
        self.neighbor_num = neighbor_num
        self.topology: Optional[np.ndarray] = None

    def generate_topology(self) -> np.ndarray:
        a = np.maximum(ring_lattice(self.n, 2),
                       ring_lattice(self.n, self.neighbor_num))
        np.fill_diagonal(a, 1.0)
        self.topology = a / a.sum(axis=1, keepdims=True)
        return self.topology

    def get_in_neighbor_weights(self, node_index: int):
        if self.topology is None or node_index >= self.n:
            return []
        return self.topology[node_index]

    def get_out_neighbor_weights(self, node_index: int):
        if self.topology is None or node_index >= self.n:
            return []
        return self.topology[:, node_index]

    def get_in_neighbor_idx_list(self, node_index: int):
        return [
            j for j in range(self.n)
            if self.topology is not None and self.topology[node_index, j] > 0
            and j != node_index
        ]

    get_out_neighbor_idx_list = get_in_neighbor_idx_list


class AsymmetricTopologyManager:
    """Directed gossip matrix: symmetric lattice with a fraction of directed
    links randomly removed, then row-normalized
    (asymmetric_topology_manager.py:17-100)."""

    def __init__(self, n: int, undirected_neighbor_num: int = 4,
                 out_directed_neighbor: int = 2, seed: int = 0):
        self.n = n
        self.undirected_neighbor_num = undirected_neighbor_num
        self.out_directed_neighbor = out_directed_neighbor
        self.seed = seed
        self.topology: Optional[np.ndarray] = None

    def generate_topology(self) -> np.ndarray:
        rng = np.random.RandomState(self.seed)
        a = np.maximum(ring_lattice(self.n, 2),
                       ring_lattice(self.n, self.undirected_neighbor_num))
        # randomly drop directed links beyond the ring until each row keeps
        # about out_directed_neighbor extra out-links
        ring = ring_lattice(self.n, 2)
        for i in range(self.n):
            extra = [j for j in range(self.n) if a[i, j] > 0 and ring[i, j] == 0]
            rng.shuffle(extra)
            for j in extra[self.out_directed_neighbor:]:
                a[i, j] = 0.0
        np.fill_diagonal(a, 1.0)
        self.topology = a / a.sum(axis=1, keepdims=True)
        return self.topology


def neighbor_adjacency(
    round_idx: int,
    n_clients: int,
    n_per_round: int,
    mode: str = "random",
    active: Optional[np.ndarray] = None,
    seed_with_round: bool = True,
) -> np.ndarray:
    """Per-round 0/1 neighbor matrix A[i, j]=1 iff client i aggregates j.

    Reproduces ``_benefit_choose`` semantics (dispfl_api.py:196-220):
      * ``random``: each client draws ``n_per_round`` others uniformly
        without replacement, excluding itself; self appended when
        participation is partial.
      * ``ring``: left and right neighbors + self.
      * ``full``: all active clients.
    Inactive clients (``active[i]==0``) get empty rows — the DisPFL client
    dropout simulation (dispfl_api.py:96,105-142).
    """
    if active is None:
        active = np.ones(n_clients, dtype=np.int64)
    rng = np.random.RandomState(round_idx if seed_with_round else None)
    a = np.zeros((n_clients, n_clients), dtype=np.float32)
    full_participation = n_per_round >= n_clients
    for i in range(n_clients):
        if active[i] == 0:
            continue
        if mode == "full" or full_participation:
            idx = np.where(active == 1)[0]
        elif mode == "ring":
            idx = np.array([(i - 1) % n_clients, (i + 1) % n_clients, i])
        elif mode == "random":
            others = np.delete(np.arange(n_clients), i)
            idx = rng.choice(others, min(n_per_round, n_clients - 1),
                             replace=False)
            idx = np.append(idx, i)
        else:
            raise ValueError(f"unknown neighbor mode {mode!r}")
        a[i, idx] = 1.0
    return a
