"""Benchmark: federated rounds/sec on the canonical ABCD-shaped workload.

Run on real TPU hardware by the driver. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload (BASELINE.md north star): SalientGrads-style federated round on
full-size ABCD volumes (121x145x121), AlexNet3D, 8 site-clients on the
available chip(s) — broadcast, vmapped local SGD (5 steps x batch 8 per
client), weighted aggregation, all one jitted program.

``vs_baseline`` is the raw ratio against the BASELINE.json north star of
10 federated rounds/sec — a 32-client v4-32 target this single-chip bench
cannot demonstrate, so it reads well below 1 here by construction. The
hardware-normalized auxiliary number ``client_rounds_per_sec_per_chip``
in ``extra`` (target basis: 10 = 10 rounds/sec x 32 clients / 32 chips)
shows how the per-chip work rate compares without assuming anything about
multi-chip scaling. The reference itself publishes no throughput numbers
(BASELINE.md).
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

N_CLIENTS = 8
# 40 = STEPS*BATCH: under the default epoch batching (each client consumes
# exactly ceil(n_i/batch) shuffled batches per epoch, core/trainer.py) the
# round runs the same 5 full batches per client the r1/r2 benches timed
SAMPLES_PER_CLIENT = 40
VOLUME = (121, 145, 121)  # canonical ABCD volume (stored phase-decomposed)
BATCH = 8
STEPS = 5
TARGET_ROUNDS_PER_SEC = 10.0  # BASELINE.json north star (v4-32)
MODEL_KEY = "3dcnn_s2d"  # tests override with a CI-scale model


def _device_synth_data(n_clients, n, shape, key, uneven=False,
                       model_key=None, test_per_client=None):
    """Generate the federated dataset directly on device (HBM-resident).

    ``model_key`` picks the stored sample shape (phased for the s2d
    twins via the runner's S2D_SPECS table — the one source of truth);
    it defaults to the module-global MODEL_KEY for the bench's own use.
    Callers importing this from scripts should pass it explicitly (an r4
    A/B was invalidated by the global defaulting to the AlexNet twin).

    ``uneven=True`` draws per-client counts in [n/2, n] (deterministic) so
    ``_full_batches()`` is False and the masked-epoch machinery — per-
    example batch weights + no-op step selects, what real uneven ABCD
    cohorts exercise — is actually priced (ADVICE r3).

    ``test_per_client`` (default n//4): HBM control for big cohorts. The
    whole construction runs as ONE jitted program so the signal-planting
    add never materializes a second cohort-sized buffer — at C=32 the
    padded train cohort alone is ~11.7 GB of the v5e's 15.75 GB (the
    (…,8,61) phased tail lane-pads 61->128, ~2.1x), so a top-level
    two-step build OOMs before the first round."""
    from neuroimagedisttraining_tpu.data.types import FederatedData
    from neuroimagedisttraining_tpu.experiments.runner import S2D_SPECS
    from neuroimagedisttraining_tpu.ops.s2d import phased_sample_shape

    model_key = model_key or MODEL_KEY
    # volumes live in the TPU-fast phase-decomposed layout (ops/s2d.py),
    # stored bf16 (the compute dtype — skips the per-step convert/relayout);
    # random phased tensors are distributionally the same workload
    spec = S2D_SPECS.get(model_key)
    if spec is not None:
        sshape = phased_sample_shape(shape, kernel=spec[0], pad=spec[1])
    else:
        sshape = tuple(shape) + (1,)
    m = test_per_client or max(4, n // 4)

    def build(k):
        kx, ky, ktx, kty = jax.random.split(k, 4)

        def planted(kk_x, kk_y, rows):
            y = jax.random.bernoulli(
                kk_y, 0.5, (n_clients, rows)).astype(jnp.int32)
            x = jax.random.normal(
                kk_x, (n_clients, rows) + sshape, jnp.bfloat16)
            # plant a mean-shift signal so losses stay realistic
            shift = y[(...,) + (None,) * len(sshape)].astype(x.dtype)
            return x + 0.75 * (shift * 2 - 1), y

        x, y = planted(kx, ky, n)
        # independent test draw (same planted distribution) instead of a
        # slice-copy of train rows: a slice would briefly hold train +
        # test + slice temp, and cannot be smaller than n//4 rows without
        # changing the train cohort
        xt, yt = planted(ktx, kty, m)
        return x, y, xt, yt

    x, y, xt, yt = jax.jit(build)(key)
    if uneven:
        counts = jnp.asarray(
            np.random.RandomState(0).randint(n // 2, n + 1, n_clients),
            jnp.int32)
    else:
        counts = jnp.full((n_clients,), n, jnp.int32)
    return FederatedData(
        x_train=x, y_train=y, n_train=counts,
        x_test=xt, y_test=yt,
        n_test=jnp.full((n_clients,), m, jnp.int32),
        class_num=2,
    )


def _sync_state(state):
    """Force a host transfer: on the experimental axon platform
    block_until_ready can return before execution completes."""
    leaves = jax.tree_util.tree_leaves(
        getattr(state, "global_params", state))
    return float(leaves[0].sum())


def _emit_result(result):
    """Print the one-JSON-line contract AND append the result to the
    durable ``results/bench_history.jsonl`` trajectory (metric, value,
    extra, git SHA) that ``obs/regress.py`` / ``scripts/perf_gate.py``
    gate against. History append is best-effort: a read-only checkout
    must never fail the bench."""
    print(json.dumps(result))
    try:
        import os

        from neuroimagedisttraining_tpu.obs import regress

        root = os.path.dirname(os.path.abspath(__file__))
        regress.append_history(
            os.path.join(root, "results", "bench_history.jsonl"),
            result, source="bench", repo_root=root)
    except Exception as e:  # pragma: no cover - disk/permissions
        import sys

        # stderr, NOT stdout: the one-JSON-line stdout contract feeds
        # `bench.py | tail -1 | perf_gate.py --from-json -`
        print(f"# bench history append skipped: {e}", file=sys.stderr,
              flush=True)
    return result


def _timed_rounds(algo, state, n_rounds=10, eval_every_round=False):
    """Shared timing harness: one warmup/compile round, then n timed.
    ``eval_every_round`` also runs the full per-round eval protocol inside
    the timed region (frequency_of_the_test=1 — the reference evaluates
    every round by default, sailentgrads_api.py:141-143), so the returned
    rate prices the O(clients) eval cost instead of footnoting it. Since
    r5 that protocol includes BOTH halves of the reference's
    _test_on_all_clients: the global model on every client's local test
    set AND every client's personal model on its own test set
    (sailentgrads_api.py:238,262-283) — the personal half carries
    per-client weights, so it cannot use the 80-wide shared-params
    batching the global half gets.

    Metric fetches are delayed ONE round (the r4 eval-path fix, mirrored
    in FedAlgorithm.run): the eval's device cost is ~21 ms but a blocking
    per-round scalar fetch costs ~110 ms through the tunnel — deferring
    the host transfer by one round keeps the device queue full while
    still fetching every round's metrics."""
    def _acc(ev):
        return ev["global_acc"] if "global_acc" in ev else ev["personal_acc"]

    from neuroimagedisttraining_tpu.obs import metrics as obs_metrics

    # ownership: the harness CONSUMES the state chain (run_round donates
    # under donate_state); callers re-running several harnesses from one
    # saved state pass algo.clone_state(state) — the borrow API
    state, _ = algo.run_round(state, 0)
    if eval_every_round:
        float(_acc(algo.evaluate(state)))  # compile outside timed region
    _sync_state(state)
    prev = None
    # the timed section lives in the obs registry (obs/metrics.py): the
    # rate is computed from the registry's recorded section time, so
    # repeated harness calls also leave a timing distribution behind
    reg = obs_metrics.get_registry()
    with reg.timer("bench_timed_rounds" +
                   ("_eval" if eval_every_round else "")) as tm:
        for r in range(1, n_rounds + 1):
            state, _ = algo.run_round(state, r)
            if eval_every_round:
                if prev is not None:
                    float(_acc(prev))
                prev = algo.evaluate(state)
        if prev is not None:
            float(_acc(prev))
        _sync_state(state)
    return n_rounds / tm.elapsed


def _timed_rounds_fused(algo, state, n_rounds=10, eval_every=0):
    """Timing harness for the fused round loop (run_rounds_fused): the
    whole timed region is ONE K-round jitted program — dispatch, then
    materialize every round's metrics at the end, exactly what the
    product's ``run(fuse_rounds=K)`` driver does per block. The warmups
    replay the TIMED call verbatim (same start_round — see the comment
    below on why sibling-args warmups are not enough); the timed block
    runs rounds [K, 2K) from the same initial state."""
    # THREE warmup executions of the IDENTICAL call being timed: beyond
    # the compile, the axon tunnel charges one-time ~0.5 s overheads to
    # the first execution(s) whose argument content it hasn't seen
    # (measured: a block timed 1.52 r/s right after 2 warmups with
    # different start_round, 1.67 on repeats of the same call), so the
    # warmups must replay the timed call verbatim, not a sibling
    from neuroimagedisttraining_tpu.obs import metrics as obs_metrics

    # ownership: each fused dispatch CONSUMES its input state under
    # donate_state, and the warmups + timed call all replay the SAME
    # call — so every dispatch gets a borrowed clone (cloned OUTSIDE
    # the timed region; the caller's state survives for later cells)
    donating = getattr(algo, "_donate", False)

    def borrowed():
        return algo.clone_state(state) if donating else state

    for w in range(3):
        state_w, ys = algo.run_rounds_fused(borrowed(), n_rounds,
                                            n_rounds,
                                            eval_every=eval_every)
        ys.materialize()
        _sync_state(state_w)
    s_in = borrowed()
    with obs_metrics.get_registry().timer("bench_timed_rounds_fused") \
            as tm:
        state, ys = algo.run_rounds_fused(s_in, n_rounds, n_rounds,
                                          eval_every=eval_every)
        # one transfer materializes every round's metrics; the packed
        # stack is a scan output, so its arrival proves the block completed
        ys.materialize()
    return n_rounds / tm.elapsed


def main(uneven: bool = False, test_per_client: int = None):
    from neuroimagedisttraining_tpu.algorithms import SalientGrads
    from neuroimagedisttraining_tpu.core.state import HyperParams
    from neuroimagedisttraining_tpu.models import create_model

    data = _device_synth_data(
        N_CLIENTS, SAMPLES_PER_CLIENT, VOLUME, jax.random.PRNGKey(0),
        uneven=uneven, test_per_client=test_per_client,
    )
    model = create_model(MODEL_KEY, num_classes=1)
    import os
    hp = HyperParams(
        lr=1e-3, lr_decay=0.998, momentum=0.9, weight_decay=5e-4,
        grad_clip=10.0, local_epochs=1, steps_per_epoch=STEPS,
        batch_size=BATCH,
        # default: the product's reference-exact epoch batching;
        # BENCH_BATCHING=replacement isolates its cost for A/B
        batching=os.environ.get("BENCH_BATCHING", "epoch"),
    )
    # On fewer devices than clients, chunk client concurrency to fit HBM
    # (see FedAlgorithm._vmap_clients); a pod runs the full client vmap.
    n_dev = len(jax.devices())
    # Full client vmap: XLA folds the client axis into the conv batch dim
    # (effective batch 64), ~3x the MXU throughput of per-client chunks.
    # Fits single-chip HBM because volumes are stored channel-less (a
    # resident (...,121,1) cohort would tile-pad 8-16x in HBM).
    # per-client weights block cross-client conv batching, so chunked
    # concurrency only adds memory pressure: chunk=1 measured fastest on a
    # single chip (1.40 r/s vs 1.25 at chunk=4; chunk=8 OOMs). On a pod
    # (device per client) the full vmap shards clients across chips.
    chunk = None if n_dev >= N_CLIENTS else 1
    mesh = None
    if n_dev > 1:
        # multi-chip: shard the client axis over the devices so the SAME
        # script measures the real distributed round (vmapped local train
        # per chip + cross-chip weighted-sum aggregation over ICI)
        from neuroimagedisttraining_tpu.parallel import (
            make_mesh,
            shard_over_clients,
        )
        from neuroimagedisttraining_tpu.parallel.mesh import (
            fit_client_devices,
        )

        rows = fit_client_devices(N_CLIENTS, n_dev)
        if rows > 1:
            mesh = make_mesh(rows)
            data = shard_over_clients(data, mesh)
            # full client vmap: anything else (lax.map chunking) would
            # serialize clients and idle the other chips; per-chip
            # concurrency is N_CLIENTS/rows
            chunk = None
    import os
    if os.environ.get("BENCH_CHUNK"):  # perf-tuning override
        chunk = int(os.environ["BENCH_CHUNK"]) or None
    remat = bool(int(os.environ.get("BENCH_REMAT", "0")))
    fused = bool(int(os.environ.get("BENCH_FUSED", "0")))
    # donate_state: the state-ownership protocol (the product default —
    # the round's [C, model] stack aliases in place instead of being
    # re-allocated); harness re-runs from `state` go through the
    # clone_state borrow API below
    algo = SalientGrads(model, data, hp, loss_type="bce", frac=1.0, seed=0,
                        client_chunk=chunk, dense_ratio=0.5,
                        itersnip_iterations=1, compute_dtype="bfloat16",
                        remat_local=remat, fused_kernels=fused,
                        donate_state=True)
    state = algo.init_state(jax.random.PRNGKey(0))  # includes the SNIP pass
    def _try_fused(a, s, **kw):
        """Fused-spelling timing, or None when the K-round program does
        not fit: at C=32 full volume XLA materializes an extra full-
        cohort copy for the scan's while loop (the unfused per-round
        program does not), so the fused spelling OOMs exactly when the
        cohort fills HBM — fall back to the loop numbers and record the
        gap."""
        try:
            return _timed_rounds_fused(a, s, **kw)
        except jax.errors.JaxRuntimeError as e:
            if "RESOURCE_EXHAUSTED" not in str(e) and \
                    "Ran out of memory" not in str(e):
                raise
            print("# fused spelling OOMs at this scale; loop numbers only",
                  flush=True)
            return None

    rps_loop = _timed_rounds(algo, algo.clone_state(state))
    # eval-inclusive rate: the same workload at frequency_of_the_test=1
    # — since r5 this prices the FULL reference protocol (global +
    # per-client personal models, sailentgrads_api.py:262-283)
    rps_with_eval_loop = _timed_rounds(algo, algo.clone_state(state),
                                       n_rounds=8,
                                       eval_every_round=True)
    # fused round loop (run_rounds_fused): K rounds as one program —
    # semantically identical (tests/test_fused_rounds.py), dispatch/fetch
    # amortized. The headline is the better of the two spellings; both
    # are recorded. (_timed_rounds_fused borrows per dispatch itself.)
    rps_fused = _try_fused(algo, state, n_rounds=10)
    rps_with_eval_fused = _try_fused(algo, state, n_rounds=8, eval_every=1)
    # the donated fused runs rebound algo.data to the aliased outputs;
    # re-read it so the instances below see valid arrays, not the
    # donated originals
    data = algo.data
    # --eval_cache cell: the in-state incremental personal eval — the
    # eval_every=1 protocol pays O(trained-clients) forwards per round
    # instead of O(C) per eval (full participation here makes it a
    # wash on FORWARD count; the win it prices is the per-round eval
    # program shrinking to the cache re-reduce)
    algo_ec = SalientGrads(model, data, hp, loss_type="bce", frac=1.0,
                           seed=0, client_chunk=chunk, dense_ratio=0.5,
                           itersnip_iterations=1,
                           compute_dtype="bfloat16",
                           remat_local=remat, fused_kernels=fused,
                           donate_state=True, eval_cache=True)
    state_ec = algo_ec.init_state(jax.random.PRNGKey(0))
    rps_eval_cache_fused = _try_fused(algo_ec, state_ec, n_rounds=8,
                                      eval_every=1)
    rps_eval_cache_loop = _timed_rounds(
        algo_ec, algo_ec.clone_state(state_ec), n_rounds=8,
        eval_every_round=True)
    data = algo_ec.data
    rps_eval_cache = max(x for x in (rps_eval_cache_loop,
                                     rps_eval_cache_fused)
                         if x is not None)
    # secondary: the global-only half (what r3/r4 benches priced) — a
    # personal-less instance isolates the personal half's cost
    algo_g = SalientGrads(model, data, hp, loss_type="bce", frac=1.0,
                          seed=0, client_chunk=chunk, dense_ratio=0.5,
                          itersnip_iterations=1, compute_dtype="bfloat16",
                          remat_local=remat, fused_kernels=fused,
                          track_personal=False, donate_state=True)
    state_g = algo_g.init_state(jax.random.PRNGKey(0))
    # best-of-both-spellings, SAME selection rule as the full-protocol
    # number — mixing spellings would corrupt the personal-half delta
    # these two numbers exist to isolate
    rps_g_fused = _try_fused(algo_g, state_g, n_rounds=8, eval_every=1)
    rps_g_loop = _timed_rounds(algo_g, state_g, n_rounds=8,
                               eval_every_round=True)
    rps_eval_global_only = max(
        x for x in (rps_g_loop, rps_g_fused) if x is not None)
    rounds_per_sec = max(x for x in (rps_loop, rps_fused) if x is not None)
    rps_with_eval = max(x for x in (rps_with_eval_loop, rps_with_eval_fused)
                        if x is not None)
    samples_per_round = N_CLIENTS * STEPS * BATCH
    n_chips = len(jax.devices())
    # target basis: 10 rounds/sec x 32 clients / 32 chips (v4-32 north
    # star) = 10 client-rounds/sec/chip; see module docstring
    client_rounds_per_sec_per_chip = rounds_per_sec * N_CLIENTS / n_chips
    result = {
        "metric": (
            f"salientgrads_rounds_per_sec_abcd_alexnet3d_{N_CLIENTS}clients"
            if MODEL_KEY == "3dcnn_s2d" else
            f"salientgrads_rounds_per_sec_abcd_{MODEL_KEY}_"
            f"{N_CLIENTS}clients")
        + ("_uneven" if uneven else ""),
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(rounds_per_sec / TARGET_ROUNDS_PER_SEC, 4),
        "extra": {
            # full reference eval protocol (global + personal halves)
            "rounds_per_sec_eval_every_1": round(rps_with_eval, 4),
            # same protocol with the in-state incremental eval cache
            # (--eval_cache): the RESULTS.md Round-14 A/B cell
            "rounds_per_sec_eval_every_1_eval_cache": round(
                rps_eval_cache, 4),
            # global-only eval (the r3/r4 definition), kept as secondary
            "rounds_per_sec_eval_every_1_global_only": round(
                rps_eval_global_only, 4),
            "rounds_per_sec_python_loop": round(rps_loop, 4),
            # None = the fused spelling OOMs at this scale (see _try_fused)
            "rounds_per_sec_fused": (
                round(rps_fused, 4) if rps_fused is not None else None),
            "rounds_per_sec_eval_every_1_python_loop": round(
                rps_with_eval_loop, 4),
            "rounds_per_sec_eval_every_1_fused": (
                round(rps_with_eval_fused, 4)
                if rps_with_eval_fused is not None else None),
            "client_samples_per_sec": round(rounds_per_sec * samples_per_round, 2),
            "client_rounds_per_sec_per_chip": round(
                client_rounds_per_sec_per_chip, 2),
            "baseline_basis": "10 client-rounds/sec/chip (v4-32 north star)",
            "n_devices": n_chips,
            "client_mesh_devices": (
                int(mesh.shape["clients"]) if mesh is not None else 1),
            "volume": list(VOLUME),
            "clients": N_CLIENTS,
            "local_steps": STEPS,
            "batch": BATCH,
        },
    }
    return _emit_result(result)


def tracked_config(name: str):
    """Secondary BASELINE.json tracked configs (BENCH_CONFIG=<name>);
    the default invocation keeps the primary one-JSON-line contract."""
    import os

    global MODEL_KEY, VOLUME, N_CLIENTS, BATCH, STEPS
    if name == "cifar":
        # the reference's canonical CIFAR config (Jobs/salientgrads...
        # 70sps.sh:40-53): SalientGrads, resnet18(GroupNorm), 100 clients,
        # frac 0.1 (10 trained/round), bs 16, 5 local epochs, dir alpha=0.3
        # class skew — timed on a CIFAR-shaped synthetic cohort (the real
        # batches are not in this environment; timing depends on shapes,
        # not labels). 500 samples/client = the 50k/100 split.
        import numpy as np

        from neuroimagedisttraining_tpu.algorithms import SalientGrads
        from neuroimagedisttraining_tpu.core.state import HyperParams
        from neuroimagedisttraining_tpu.data.types import FederatedData
        from neuroimagedisttraining_tpu.models import create_model

        n_clients, n_per, bs, epochs = 100, 500, 16, 5
        kx, ky = jax.random.split(jax.random.PRNGKey(0))
        x = jax.random.normal(kx, (n_clients, n_per, 32, 32, 3),
                              jnp.bfloat16)
        y = jax.random.randint(ky, (n_clients, n_per), 0, 10)
        m = 100  # proportional test resample scale (10k/100)
        from neuroimagedisttraining_tpu.data.cifar import (
            CIFAR10_MEAN,
            CIFAR10_STD,
            black_pad_value,
        )

        data = FederatedData(
            x_train=x, y_train=y,
            n_train=jnp.full((n_clients,), n_per, jnp.int32),
            x_test=x[:, :m], y_test=y[:, :m],
            n_test=jnp.full((n_clients,), m, jnp.int32), class_num=10,
            # the reference augments every CIFAR training batch
            # (cifar10/data_loader.py:46-50) — price it here too (r4)
            aug_pad_value=black_pad_value(CIFAR10_MEAN, CIFAR10_STD))
        model = create_model("resnet18", num_classes=10)
        hp = HyperParams(lr=0.1, lr_decay=0.998, momentum=0.9,
                         weight_decay=5e-4, grad_clip=10.0,
                         local_epochs=epochs,
                         steps_per_epoch=-(-n_per // bs), batch_size=bs)
        # chunk=1 measured fastest (0.662 r/s vs 0.592 full vmap on the
        # v5e): per-client weights block cross-client conv batching, as on
        # the ABCD path. BENCH_CHUNK overrides for tuning.
        chunk = int(os.environ.get("BENCH_CHUNK", "1")) or None
        algo = SalientGrads(model, data, hp, loss_type="ce", frac=0.1,
                            seed=0, dense_ratio=0.3, itersnip_iterations=1,
                            compute_dtype="bfloat16", client_chunk=chunk)
        state = algo.init_state(jax.random.PRNGKey(0))
        rps = _timed_rounds(algo, state, n_rounds=3)
        result = {
            "metric": ("salientgrads_rounds_per_sec_cifar_resnet18gn_"
                       "100clients_frac0.1"),
            "value": round(rps, 4),
            "unit": "rounds/sec",
            "vs_baseline": 0.0,  # reference publishes no number
            "extra": {"clients": n_clients, "trained_per_round": 10,
                      "local_epochs": epochs, "batch": bs,
                      "steps_per_epoch": -(-n_per // bs)},
        }
        return _emit_result(result)
    if name == "resnet3d":
        # 3D-ResNet on full-size volumes (BASELINE "3D-ResNet full cohort").
        # Phased-stem twin since r4: the k3/s2/p3 stem at C_in=1 was 66% of
        # the step; the s2d restatement measures 0.80 vs 0.60 r/s dense
        # (exactness-tested, tests/test_s2d.py; RESULTS.md tracked table).
        # BENCH_DENSE=1 runs the reference-layout model for A/B.
        MODEL_KEY, VOLUME = "3dresnet_s2d", (121, 145, 121)
        if os.environ.get("BENCH_DENSE"):
            MODEL_KEY = "3dresnet"
        return main()
    if name == "agg":
        # the aggregation term at REAL parameter scale on the REAL chip
        # (VERDICT r3 item 2): per weighted-sum of the 2.58M-param
        # AlexNet3D tree over 32 stacked client models. On one chip there
        # is no ICI hop — this is the HBM-bound contraction floor; the
        # cross-chip all-reduce adds ~0.2 ms at v4 ICI (BASELINE.md),
        # and the CPU-mesh dryrun measures GSPMD-vs-shard_map parity.
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _agg_realparams_probe

        from neuroimagedisttraining_tpu.parallel import make_mesh

        # largest mesh <= 8 devices that divides the 32-client axis
        # (shard_map needs exact divisibility)
        n_dev = max(d for d in (8, 4, 2, 1) if d <= len(jax.devices()))
        mesh = make_mesh(n_dev)
        d = _agg_realparams_probe(mesh, n_dev, raw=True)
        # the agg-subsystem micro-bench (parallel/collectives.py): dense
        # vs bucketed-psum vs low-precision wires vs mask-aware sparse,
        # same 32-client real-parameter workload (honored 0.5-density
        # SNIP-style mask) — the before/after behind --agg_impl
        from neuroimagedisttraining_tpu.parallel.collectives import (
            agg_microbench,
        )

        for k, v in agg_microbench(mesh if n_dev > 1 else None).items():
            # the probe and the microbench share workload-descriptor keys
            # (n_params/n_clients/n_devices) by construction; if their
            # defaults ever diverge, keep both instead of silently
            # relabeling the probe's measurements
            if k in d and d[k] != v:
                d[f"microbench_{k}"] = v
            else:
                d[k] = v
        result = {
            "metric": "weighted_sum_aggregation_ms_alexnet3d_32clients",
            "value": round(d["gspmd_ms"], 3),
            "unit": "ms/aggregation",
            "vs_baseline": 0.0,  # term measurement, not a rate
            "extra": {k: (round(v, 3) if isinstance(v, float) else v)
                      for k, v in d.items()},
        }
        return _emit_result(result)
    if name == "clients32":
        # the primary workload at the NORTH-STAR client count (C=32) on
        # the one real chip (VERDICT r4 weak #4): measures the scan-length
        # and cohort-residency scaling directly instead of assuming
        # linearity from the 8-client cell. The padded train cohort is
        # ~11.7 GB of 15.75 GB HBM, so the test split shrinks to
        # 4 volumes/client (eval-inclusive extras are therefore NOT
        # comparable to the 8-client cell's 10-volume test shards; the
        # primary eval-free rate is the tracked number).
        N_CLIENTS = 32
        return main(test_per_client=4)
    if name == "cohort":
        # Cohort-scale cell (ROADMAP Open item 2 / ISSUE 9): C=32/64/
        # 128/256 synthetic small-model cohorts on one chip through the
        # DONATED fused path with the in-state eval cache — the
        # "hundreds of clients per chip" configuration whose OOM line
        # this PR's fused-carry restructure moves. Per-round trained
        # work is held constant (8 clients/round at every C) so the
        # sweep isolates cohort RESIDENCY: rounds/sec plus the peak-
        # device-memory ledger (obs/memory.py — memory_stats peak on
        # TPU/GPU, live-arrays watermark on CPU), both appended to the
        # gated results/bench_history.jsonl (perf_gate prefix rules:
        # cohort_mem_bytes_* lower-is-better).
        from neuroimagedisttraining_tpu.algorithms import FedAvg
        from neuroimagedisttraining_tpu.core.state import HyperParams
        from neuroimagedisttraining_tpu.models import create_model
        from neuroimagedisttraining_tpu.obs import memory as obs_memory
        from neuroimagedisttraining_tpu.obs import metrics as obs_metrics
        from neuroimagedisttraining_tpu.obs import regress

        sizes = tuple(int(c) for c in os.environ.get(
            "BENCH_COHORTS", "32,64,128,256").split(","))
        n_per, vol = 8, (16, 16, 16)
        block = int(os.environ.get("BENCH_COHORT_BLOCK", "4"))
        rounds = int(os.environ.get("BENCH_COHORT_ROUNDS", "8"))
        # at least one whole block, and whole blocks only (a remainder
        # would make the timed region's round count disagree with the
        # dispatched blocks; flooring to zero would append a 0.0
        # rounds/sec cell to the gated history)
        rounds = max(block, rounds - rounds % block)
        hp = HyperParams(lr=1e-3, momentum=0.9, local_epochs=1,
                         steps_per_epoch=2, batch_size=4)
        model = create_model("small3dcnn", num_classes=1)
        root = os.path.dirname(os.path.abspath(__file__))
        history = os.path.join(root, "results", "bench_history.jsonl")
        cells = {}
        for n_clients in sizes:
            data = _device_synth_data(
                n_clients, n_per, vol, jax.random.PRNGKey(0),
                model_key="small3dcnn", test_per_client=4)
            algo = FedAvg(model, data, hp, loss_type="bce",
                          frac=min(1.0, 8.0 / n_clients), seed=0,
                          compute_dtype="bfloat16", donate_state=True,
                          eval_cache=True)
            state = algo.init_state(jax.random.PRNGKey(0))
            # warmup block (compile), then timed whole blocks
            state, ys = algo.run_rounds_fused(state, 0, block,
                                              eval_every=1)
            ys.materialize()
            _sync_state(state)
            with obs_metrics.get_registry().timer(
                    f"bench_cohort_c{n_clients}") as tm:
                r0 = block
                while r0 < block + rounds:
                    state, ys = algo.run_rounds_fused(
                        state, r0, block, eval_every=1)
                    r0 += block
                ys.materialize()
                _sync_state(state)
            rps = rounds / tm.elapsed
            devs = obs_memory.device_memory()
            # the GATED per-cell number is bytes_in_use sampled while
            # THIS cohort is live (earlier cohorts were deleted, so it
            # attributes to this C). peak_bytes_in_use is a PROCESS-
            # LIFETIME high-watermark on memory_stats backends — it
            # never resets between cells, so a big early cell would
            # bleed into every later cell's gate; it stays
            # informational in the extras only.
            in_use = max((d["bytes_in_use"] for d in devs), default=0)
            peak = max((d.get("peak_bytes_in_use", d["bytes_in_use"])
                        for d in devs), default=0)
            cells[f"c{n_clients}"] = {
                "rounds_per_sec": round(rps, 4),
                "mem_bytes": int(in_use),
                "mem_peak_process_bytes": int(peak),
                "mem_source": devs[0]["source"] if devs
                else "unavailable",
            }
            for metric, value, unit in (
                    (f"cohort_rounds_per_sec_c{n_clients}", rps,
                     "rounds/sec"),
                    (f"cohort_mem_bytes_c{n_clients}", float(in_use),
                     "bytes")):
                try:
                    regress.append_history(
                        history, {"metric": metric, "value": value,
                                  "unit": unit},
                        source="bench_cohort", repo_root=root)
                except Exception as e:  # read-only checkout
                    import sys

                    print(f"# cohort history append skipped: {e}",
                          file=sys.stderr, flush=True)
            del data, algo, state, ys  # free this cohort before the next
        # Population cells (ISSUE 14): C=1k/4k/16k through the
        # --client_store host streamed-residency path — only the S=8
        # sampled rows (and the fused block's row union) ever reach
        # device, so HBM stays flat in C while the resident cells above
        # grow linearly. Data is HOST numpy (the residency contract:
        # per-round slabs device_put on demand), volumes shrink to 8^3 /
        # 2 samples per client so the 16k cohort's host footprint stays
        # tens of MB. Three gated series per cell: rounds/sec, the
        # device-memory ledger (expected FLAT — the acceptance curve in
        # RESULTS.md), and the new store_gather_ms_* host->device
        # gather timing (per-round mean; lower-is-better prefix).
        from neuroimagedisttraining_tpu.data.synthetic import (
            make_synthetic_federated,
        )

        pop_sizes = tuple(int(c) for c in os.environ.get(
            "BENCH_POP_COHORTS", "1024,4096,16384").split(",") if c)
        for n_clients in pop_sizes:
            data = make_synthetic_federated(
                seed=0, n_clients=n_clients, samples_per_client=2,
                test_per_client=1, sample_shape=(8, 8, 8, 1),
                class_num=2, loss_type="bce")
            algo = FedAvg(model, data, hp, loss_type="bce",
                          frac=8.0 / n_clients, seed=0,
                          donate_state=True,
                          client_store="host", store_hot_clients=64)
            state = algo.init_state(jax.random.PRNGKey(0))
            # warmup block (compile; store mode refuses in-graph eval,
            # so blocks run eval_every=0), then timed whole blocks
            state, ys = algo.run_rounds_fused(state, 0, block,
                                              eval_every=0)
            ys.materialize()
            _sync_state(state)
            g0 = algo._store.stats()["store_gather_ms"]
            with obs_metrics.get_registry().timer(
                    f"bench_pop_c{n_clients}") as tm:
                r0 = block
                while r0 < block + rounds:
                    state, ys = algo.run_rounds_fused(
                        state, r0, block, eval_every=0)
                    r0 += block
                ys.materialize()
                _sync_state(state)
            rps = rounds / tm.elapsed
            gather_ms = (algo._store.stats()["store_gather_ms"] - g0) \
                / rounds
            devs = obs_memory.device_memory()
            in_use = max((d["bytes_in_use"] for d in devs), default=0)
            cells[f"pop_c{n_clients}"] = {
                "rounds_per_sec": round(rps, 4),
                "mem_bytes": int(in_use),
                "store_gather_ms": round(gather_ms, 3),
                "mem_source": devs[0]["source"] if devs
                else "unavailable",
            }
            for metric, value, unit in (
                    (f"cohort_rounds_per_sec_pop_c{n_clients}", rps,
                     "rounds/sec"),
                    (f"cohort_mem_bytes_pop_c{n_clients}",
                     float(in_use), "bytes"),
                    (f"store_gather_ms_c{n_clients}", gather_ms,
                     "ms/round")):
                try:
                    regress.append_history(
                        history, {"metric": metric, "value": value,
                                  "unit": unit},
                        source="bench_cohort", repo_root=root)
                except Exception as e:  # read-only checkout
                    import sys

                    print(f"# cohort history append skipped: {e}",
                          file=sys.stderr, flush=True)
            del data, algo, state, ys
        biggest = f"c{max(sizes)}"
        result = {
            "metric": ("fedavg_cohort_rounds_per_sec_small3dcnn_"
                       f"{biggest}_fused_evcache"),
            "value": cells[biggest]["rounds_per_sec"],
            "unit": "rounds/sec",
            "vs_baseline": 0.0,  # scaling cell, not a rate target
            "extra": {"cells": cells, "block": block,
                      "trained_per_round": 8, "volume": list(vol),
                      "pop_volume": [8, 8, 8],
                      "n_devices": len(jax.devices())},
        }
        return _emit_result(result)
    if name == "uneven":
        # primary workload with uneven shards ([20,40] samples/client): the
        # masked epoch path — per-example weights, no-op step selects —
        # priced instead of assumed (ADVICE r3; the primary cell's equal
        # 40-sample shards take the full_batches fast path)
        return main(uneven=True)
    if name == "byzantine":
        # Byzantine-robust 64-client FedAvg with weak-DP defense
        from neuroimagedisttraining_tpu.algorithms import FedAvg
        from neuroimagedisttraining_tpu.core.state import HyperParams
        from neuroimagedisttraining_tpu.models import create_model
        from neuroimagedisttraining_tpu.robust import RobustAggregator

        MODEL_KEY = "small3dcnn"  # shallow CNN; channel-ful storage path
        n_clients = 64
        data = _device_synth_data(n_clients, STEPS * BATCH, (61, 73, 61),
                                  jax.random.PRNGKey(0))
        model = create_model("small3dcnn", num_classes=1)
        hp = HyperParams(lr=1e-3, momentum=0.9, local_epochs=1,
                         steps_per_epoch=STEPS, batch_size=BATCH)
        # chunk=16 measured best at the real shape (r4 interleaved sweep:
        # 8/16/32 = 0.60/0.63/0.63 r/s; the full 64-client vmap fails the
        # remote compile at this volume). Defense and the personal-model
        # stack are free (on/off within noise) — RESULTS.md r4 anatomy.
        algo = FedAvg(model, data, hp, loss_type="bce", frac=1.0, seed=0,
                      compute_dtype="bfloat16",
                      client_chunk=int(os.environ.get("BENCH_CHUNK", "16"))
                      or None,
                      defense=RobustAggregator("weak_dp", norm_bound=5.0,
                                               stddev=0.025))
        state = algo.init_state(jax.random.PRNGKey(0))
        rps = _timed_rounds(algo, state)
        result = {
            "metric": "byzantine_robust_fedavg_rounds_per_sec_64clients",
            "value": round(rps, 4),
            "unit": "rounds/sec",
            "vs_baseline": 0.0,  # no published number; tracked config
        }
        return _emit_result(result)
    raise SystemExit(f"unknown BENCH_CONFIG {name!r}")


if __name__ == "__main__":
    import os as _os

    cfg = _os.environ.get("BENCH_CONFIG", "")
    if cfg:
        tracked_config(cfg)
    else:
        main()
