"""Cross-run diff engine (obs/diff.py): the three-plane twin gate.

Covers the comparator contract: config-plane bucketing by the
identity census (hard-rule inert prefixes included) and the abstention
on bare streams, trajectory-plane first-bit-divergence semantics
(NaN==NaN is NOT a divergence; volatile wall-clock keys never count),
event/health plane diffs, the twin verdict (inert differences allowed,
identity differences fatal), ``--expect`` exit-code mapping, the
params-plane bit comparator, and the CLI's load-error exit code.
"""
import json
import math
import os

import numpy as np
import pytest

from neuroimagedisttraining_tpu.obs import diff
from neuroimagedisttraining_tpu.obs.__main__ import fleet_diff_cli


def _run(records=None, events=None, config=None, identity="run"):
    return {"identity": identity, "records": records or [],
            "events": events or [], "config": config or {}}


def _rounds(n, **overrides):
    out = []
    for r in range(n):
        rec = {"round": r, "train_loss": 1.0 / (r + 1),
               "sum_comm_params": 100.0 * (r + 1),
               "round_time_s": 0.1 * (r + 1)}  # volatile: may differ
        rec.update({k: v(r) if callable(v) else v
                    for k, v in overrides.items()})
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# config plane
# ---------------------------------------------------------------------------

def test_config_diff_buckets_by_census():
    a = {"fault_spec": "", "fuse_rounds": 1, "obs_comm": 0,
         "not_a_flag": 1, "lr": 0.05}
    b = {"fault_spec": "nan=0.1", "fuse_rounds": 4, "obs_comm": 1,
         "not_a_flag": 2, "lr": 0.05}
    d = diff.config_diff(a, b)
    assert "fault_spec" in d["identity"]  # census: identity-bearing
    assert "fuse_rounds" in d["inert"]  # census: inert
    assert "obs_comm" in d["inert"]  # hard rule: obs_ prefix
    assert "not_a_flag" in d["unclassified"]
    assert "lr" not in d["identity"]  # equal values never listed
    assert not d["identical"] and not d["same_experiment"]


def test_config_diff_identical():
    d = diff.config_diff({"lr": 0.05}, {"lr": 0.05})
    assert d["identical"] and d["same_experiment"]


def test_config_plane_abstains_on_bare_stream():
    # an --obs_jsonl override stream has no stat sidecar: fabricating
    # every-flag differences would poison the twin verdict
    doc = diff.diff_runs(
        _run(records=_rounds(2), config={}),
        _run(records=_rounds(2), config={"dataset": "synthetic",
                                         "fault_spec": "nan=0.1"}))
    cfg = doc["planes"]["config"]
    assert cfg["unavailable"] and cfg["identical"]
    assert doc["identical"]  # streams match → still a twin
    assert "abstains" in diff.render_diff(doc)


# ---------------------------------------------------------------------------
# trajectory plane
# ---------------------------------------------------------------------------

def test_trajectory_identical_streams():
    t = diff.trajectory_diff(_rounds(4), _rounds(4))
    assert t["identical"] and t["first_divergence_round"] is None
    assert t["diverged_metrics"] == []


def test_trajectory_first_bit_divergence_round():
    a = _rounds(5)
    b = _rounds(5)
    b[3]["train_loss"] += 1e-12  # one ULP-ish nudge IS a divergence
    t = diff.trajectory_diff(a, b)
    assert not t["identical"]
    assert t["first_divergence_round"] == 3
    m = t["metrics"]["train_loss"]
    assert m["first_divergence_round"] == 3
    assert m["diverged_rounds"] == 1
    # a tiny nudge is bit-different but NOT significant vs the MAD band
    assert not m["significant"]


def test_trajectory_spike_is_significant():
    # the band is a MAD over the POOLED series — a one-round spike
    # stands clear of the shared noise floor and flags significant
    a = _rounds(6, train_loss=1.0)
    b = _rounds(6, train_loss=lambda r: 100.0 if r == 3 else 1.0)
    t = diff.trajectory_diff(a, b)
    assert "train_loss" in t["significant_metrics"]
    assert t["metrics"]["train_loss"]["first_divergence_round"] == 3


def test_trajectory_nan_matches_nan():
    a = _rounds(3, train_loss=lambda r: float("nan") if r == 1
                else 1.0)
    b = _rounds(3, train_loss=lambda r: float("nan") if r == 1
                else 1.0)
    t = diff.trajectory_diff(a, b)
    assert t["identical"]  # a deterministic twin reproduces its NaNs


def test_trajectory_nan_vs_value_diverges():
    a = _rounds(3, train_loss=lambda r: float("nan") if r == 1
                else 1.0)
    b = _rounds(3, train_loss=1.0)
    t = diff.trajectory_diff(a, b)
    assert t["metrics"]["train_loss"]["first_divergence_round"] == 1
    assert t["metrics"]["train_loss"]["max_abs_delta"] == float("inf")


def test_trajectory_volatile_keys_never_count():
    a = _rounds(3)
    b = _rounds(3, round_time_s=99.0, mem_rss_mb=1e9)
    t = diff.trajectory_diff(a, b)
    assert t["identical"]
    assert "round_time_s" not in t["metrics"]


def test_trajectory_missing_rounds_and_keys():
    a = _rounds(4, extra_metric=1.0)
    b = _rounds(3)
    t = diff.trajectory_diff(a, b)
    assert not t["identical"]
    assert t["rounds_only_a"] == [3]
    assert "extra_metric" in t["keys_only_a"]


def test_trajectory_metric_allowlist():
    a = _rounds(3)
    b = _rounds(3, sum_comm_params=0.0)
    t = diff.trajectory_diff(a, b, metrics=["train_loss"])
    assert t["identical"]  # the diverging metric is filtered out


# ---------------------------------------------------------------------------
# event / health plane
# ---------------------------------------------------------------------------

def _ev(r, t, **kw):
    return {"round": r, "event_type": t, "severity": "warning", **kw}


def test_events_diff_only_and_changed():
    a = [_ev(0, "SLO_BREACH"), _ev(2, "SLO_RECOVERY")]
    b = [_ev(0, "SLO_BREACH", severity="critical"),
         _ev(3, "SLO_BREACH")]
    d = diff.events_diff(a, b)
    assert [(e["round"], e["event_type"]) for e in d["only_a"]] == \
        [(2, "SLO_RECOVERY")]
    assert [(e["round"], e["event_type"]) for e in d["only_b"]] == \
        [(3, "SLO_BREACH")]
    assert d["changed"] == [{"round": 0, "event_type": "SLO_BREACH",
                             "fields": ["severity"]}]
    assert not d["identical"]


def test_events_diff_identical():
    a = [_ev(0, "SLO_BREACH")]
    assert diff.events_diff(a, list(a))["identical"]


def test_health_diff_trajectory_and_divergence():
    a = _rounds(4, slo_health=lambda r: "ok" if r < 2 else "degraded")
    b = _rounds(4, slo_health="ok")
    d = diff.health_diff(a, b)
    assert d["a"] == [[0, "ok"], [2, "degraded"]]
    assert d["b"] == [[0, "ok"]]
    assert d["end_a"] == "degraded" and d["end_b"] == "ok"
    assert d["first_divergence_round"] == 2
    assert not d["identical"]
    assert diff.health_diff(a, list(a))["identical"]


# ---------------------------------------------------------------------------
# the full diff + expect gate
# ---------------------------------------------------------------------------

def test_diff_runs_twin_allows_inert_config_differences():
    cfg_a = {"dataset": "synthetic", "fuse_rounds": 1, "obs_comm": 0}
    cfg_b = {"dataset": "synthetic", "fuse_rounds": 4, "obs_comm": 1}
    doc = diff.diff_runs(_run(records=_rounds(3), config=cfg_a),
                         _run(records=_rounds(3), config=cfg_b))
    assert doc["identical"]  # the inert axes ARE the twin variation
    assert "fuse_rounds" in doc["planes"]["config"]["inert"]
    assert diff.expect_exit_code(doc, "identical") == 0
    assert diff.expect_exit_code(doc, "different") == 1


def test_diff_runs_identity_difference_breaks_twin():
    cfg_a = {"dataset": "synthetic", "fault_spec": ""}
    cfg_b = {"dataset": "synthetic", "fault_spec": "nan=0.1"}
    doc = diff.diff_runs(_run(records=_rounds(3), config=cfg_a),
                         _run(records=_rounds(3), config=cfg_b))
    assert not doc["identical"]
    assert "fault_spec" in doc["planes"]["config"]["identity"]
    assert diff.expect_exit_code(doc, "different") == 0


def test_expect_exit_code_empty_and_unknown():
    doc = diff.diff_runs(_run(records=_rounds(2)),
                         _run(records=_rounds(2)))
    assert diff.expect_exit_code(doc, "") == 0  # report-only
    with pytest.raises(ValueError):
        diff.expect_exit_code(doc, "bogus")


def test_render_diff_names_divergence():
    a = _rounds(4)
    b = _rounds(4)
    b[2]["train_loss"] = 99.0
    doc = diff.diff_runs(_run(a, identity="A"), _run(b, identity="B"))
    text = diff.render_diff(doc)
    assert "DIFFERENT" in text
    assert "first bit divergence at round 2" in text
    doc2 = diff.diff_runs(_run(a), _run(list(a)))
    assert "IDENTICAL (twin)" in diff.render_diff(doc2)


# ---------------------------------------------------------------------------
# params plane
# ---------------------------------------------------------------------------

def test_params_diff_identical_and_nan_bits():
    tree = {"w": np.array([1.0, float("nan")], np.float32),
            "b": np.zeros(3, np.float32)}
    clone = {k: v.copy() for k, v in tree.items()}
    d = diff.params_diff(tree, clone)
    assert d["identical"] and d["leaves"] == 2  # same NaN bytes match


def test_params_diff_names_diverged_leaf():
    a = {"w": np.array([1.0, 2.0], np.float32),
         "b": np.zeros(3, np.float32)}
    b = {"w": np.array([1.0, 2.5], np.float32),
         "b": np.zeros(3, np.float32)}
    d = diff.params_diff(a, b)
    assert not d["identical"]
    (leaf,) = d["diverged"]
    assert "w" in leaf["leaf"] and leaf["n_diff"] == 1
    assert leaf["max_abs_delta"] == 0.5


def test_params_diff_shape_mismatch():
    d = diff.params_diff({"w": np.zeros(2, np.float32)},
                         {"w": np.zeros(3, np.float32)})
    assert not d["identical"]
    assert d["diverged"][0]["reason"] == "shape/dtype"


# ---------------------------------------------------------------------------
# load_run + CLI exit codes
# ---------------------------------------------------------------------------

def _seed_stream(run_dir, identity, records, config=None):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, identity + ".obs.jsonl"),
              "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    if config is not None:
        with open(os.path.join(run_dir, identity + ".json"),
                  "w") as f:
            json.dump({"config": config}, f)


def test_load_run_from_dir_and_stream(tmp_path):
    run_dir = str(tmp_path / "synthetic")
    _seed_stream(run_dir, "run-a", _rounds(2),
                 config={"dataset": "synthetic"})
    by_dir = diff.load_run(run_dir)  # single stream: no identity
    assert by_dir["identity"] == "run-a"
    assert len(by_dir["records"]) == 2
    assert by_dir["config"]["dataset"] == "synthetic"
    by_path = diff.load_run(
        os.path.join(run_dir, "run-a.obs.jsonl"))
    assert by_path["records"] == by_dir["records"]


def test_load_run_ambiguous_dir_raises(tmp_path):
    run_dir = str(tmp_path / "synthetic")
    _seed_stream(run_dir, "run-a", _rounds(1))
    _seed_stream(run_dir, "run-b", _rounds(1))
    with pytest.raises(ValueError):
        diff.load_run(run_dir)
    assert diff.load_run(run_dir, identity="run-b")["identity"] == \
        "run-b"


def test_fleet_diff_cli_exit_codes(tmp_path, capsys):
    run_dir = str(tmp_path / "synthetic")
    _seed_stream(run_dir, "run-a", _rounds(3))
    _seed_stream(run_dir, "run-b", _rounds(3))
    a = os.path.join(run_dir, "run-a.obs.jsonl")
    b = os.path.join(run_dir, "run-b.obs.jsonl")
    assert fleet_diff_cli(a, b, expect="identical") == 0
    assert fleet_diff_cli(a, b, expect="different") == 1
    # ambiguous dir → load error → 2
    assert fleet_diff_cli(run_dir, b) == 2
    out = []
    assert fleet_diff_cli(a, b, as_json=True, out=out.append) == 0
    doc = json.loads(out[0])
    assert doc["identical"] is True
