"""End-to-end FedAvg on an 8-virtual-device CPU mesh: the minimum slice.

This is the milestone test from SURVEY.md §7.4: local steps + weighted psum +
broadcast on synthetic ABCD-like data, learning to above-chance accuracy.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.algorithms import FedAvg, sample_client_indexes
from neuroimagedisttraining_tpu.core.state import HyperParams
from neuroimagedisttraining_tpu.data import make_synthetic_federated
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.parallel import make_mesh, shard_over_clients


def _make_algo(loss_type="bce", frac=1.0, n_clients=8):
    data = make_synthetic_federated(
        n_clients=n_clients, samples_per_client=24, test_per_client=8,
        sample_shape=(8, 8, 8, 1), loss_type=loss_type,
        class_num=2,
    )
    model = create_model("small3dcnn", num_classes=1 if loss_type == "bce" else 2)
    hp = HyperParams(lr=0.05, lr_decay=1.0, momentum=0.9, weight_decay=0.0,
                     grad_clip=10.0, local_epochs=1, steps_per_epoch=4,
                     batch_size=8)
    return FedAvg(model, data, hp, loss_type=loss_type, frac=frac, seed=0)


def test_client_sampling_parity():
    # reference reseeds np with round_idx (fedavg_api.py:92-100)
    a = sample_client_indexes(3, 100, 10)
    np.random.seed(3)
    b = np.random.choice(range(100), 10, replace=False)
    assert np.array_equal(a, b)
    # full participation returns everyone
    assert np.array_equal(sample_client_indexes(0, 4, 4), np.arange(4))


def test_fedavg_learns_bce():
    algo = _make_algo("bce")
    state = algo.init_state(jax.random.PRNGKey(0))
    ev0 = algo.evaluate(state)
    state, hist = algo.run(comm_rounds=10, eval_every=0, state=state, finalize=False)
    ev = algo.evaluate(state)
    assert ev["global_acc"] > 0.8, (float(ev0["global_acc"]), float(ev["global_acc"]))


def test_fedavg_learns_ce():
    algo = _make_algo("ce")
    state, _ = algo.run(comm_rounds=20, eval_every=0, finalize=False)
    ev = algo.evaluate(state)
    assert ev["global_acc"] > 0.8


def test_fedavg_partial_participation():
    algo = _make_algo("bce", frac=0.5)
    assert algo.clients_per_round == 4
    state, hist = algo.run(comm_rounds=4, eval_every=2, finalize=False)
    assert len(hist) == 4
    assert "global_acc" in hist[1]


def test_fedavg_on_sharded_mesh(eight_devices):
    """Client-sharded data: the aggregation contraction crosses devices."""
    algo = _make_algo("bce")
    mesh = make_mesh(8, devices=eight_devices)
    algo.data = jax.tree_util.tree_map(
        lambda x: shard_over_clients(x, mesh)
        if hasattr(x, "shape") and x.ndim and x.shape[0] == 8 else x,
        algo.data,
    )
    state, _ = algo.run(comm_rounds=3, eval_every=0, finalize=False)
    ev = algo.evaluate(state)
    assert np.isfinite(float(ev["global_loss"]))


def test_fedavg_deterministic():
    a1 = _make_algo("bce")
    a2 = _make_algo("bce")
    s1, _ = a1.run(comm_rounds=2, eval_every=0, finalize=False)
    s2, _ = a2.run(comm_rounds=2, eval_every=0, finalize=False)
    l1 = jax.tree_util.tree_leaves(s1.global_params)
    l2 = jax.tree_util.tree_leaves(s2.global_params)
    for x, y in zip(l1, l2):
        assert np.allclose(x, y)


def test_fedavg_learns_bf16_compute():
    """Mixed precision (f32 master weights, bf16 conv/matmul compute) must
    still learn the synthetic task; master params and logits stay f32."""
    data = make_synthetic_federated(
        n_clients=8, samples_per_client=24, test_per_client=8,
        sample_shape=(8, 8, 8, 1), loss_type="bce", class_num=2,
    )
    model = create_model("small3dcnn", num_classes=1)
    hp = HyperParams(lr=0.05, lr_decay=1.0, momentum=0.9, weight_decay=0.0,
                     grad_clip=10.0, local_epochs=1, steps_per_epoch=4,
                     batch_size=8)
    algo = FedAvg(model, data, hp, loss_type="bce", frac=1.0, seed=0,
                  compute_dtype="bfloat16")
    state, _ = algo.run(comm_rounds=10, eval_every=0, finalize=False)
    assert all(
        leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(state.global_params)
    )
    ev = algo.evaluate(state)
    assert ev["global_acc"] > 0.8, float(ev["global_acc"])


def test_fedavg_channel_inject_path():
    """Channel-less volume storage with apply-time channel injection (the
    HBM-tiling-friendly layout) must match the stored-channel path exactly
    given the same data and seeds."""
    kw = dict(n_clients=4, samples_per_client=24, test_per_client=8,
              loss_type="bce", class_num=2)
    with_ch = make_synthetic_federated(sample_shape=(8, 8, 8, 1), **kw)
    # identical volumes, channel axis dropped from storage
    no_ch = with_ch.replace(
        x_train=with_ch.x_train[..., 0], x_test=with_ch.x_test[..., 0])
    model = create_model("small3dcnn", num_classes=1)
    hp = HyperParams(lr=0.05, lr_decay=1.0, momentum=0.9, weight_decay=0.0,
                     grad_clip=10.0, local_epochs=1, steps_per_epoch=4,
                     batch_size=8)
    a = FedAvg(model, with_ch, hp, loss_type="bce", frac=1.0, seed=0)
    b = FedAvg(model, no_ch, hp, loss_type="bce", frac=1.0, seed=0,
               channel_inject=True)
    sa, _ = a.run(comm_rounds=3, eval_every=0, finalize=False)
    sb, _ = b.run(comm_rounds=3, eval_every=0, finalize=False)
    for la, lb in zip(jax.tree_util.tree_leaves(sa.global_params),
                      jax.tree_util.tree_leaves(sb.global_params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6)
    ev = b.evaluate(sb)
    assert np.isfinite(float(ev["global_acc"]))


@pytest.mark.slow
def test_fedavg_learns_2d_cifar_path():
    """The 2D (CIFAR-shaped) model path must LEARN, not just run: FedAvg +
    cnn_cifar10 with CE loss on a 4-class planted-signal task beats chance
    by a wide margin."""
    data = make_synthetic_federated(
        n_clients=4, samples_per_client=24, test_per_client=12,
        sample_shape=(16, 16, 3), loss_type="ce", class_num=4, seed=1)
    model = create_model("cnn_cifar10", num_classes=4)
    hp = HyperParams(lr=0.05, lr_decay=1.0, momentum=0.9, weight_decay=0.0,
                     grad_clip=10.0, local_epochs=1, steps_per_epoch=3,
                     batch_size=8)
    algo = FedAvg(model, data, hp, loss_type="ce", frac=1.0, seed=0)
    state, _ = algo.run(comm_rounds=10, eval_every=0, finalize=False)
    ev = algo.evaluate(state)
    assert ev["global_acc"] > 0.5, float(ev["global_acc"])  # chance = 0.25


def test_fedavg_final_finetune_and_personal_eval():
    """The reference's end-of-training pass (fedavg_api.py:79-88): every
    client fine-tunes once from the final global model (round_idx=-1) into
    its personal model, and the final record evaluates both."""
    algo = _make_algo("bce", n_clients=4)
    state, hist = algo.run(comm_rounds=3, eval_every=0)
    final = hist[-1]
    assert final["round"] == -1 and final.get("finetune")
    assert "personal_acc" in final and "global_acc" in final
    assert np.isfinite(final["personal_loss"])
    # per-round evals also carry personal metrics (w_per_mdls tracking,
    # fedavg_api.py:42-45,66-67 + _test_on_all_clients :119-173)
    ev = algo.evaluate(state)
    assert "personal_acc" in ev
    # the fine-tune actually moved the personal models off the global model
    g = jax.tree_util.tree_leaves(state.global_params)
    p = jax.tree_util.tree_leaves(state.personal_params)
    diffs = [np.abs(np.asarray(pp) - np.asarray(gg)[None]).max()
             for gg, pp in zip(g, p)]
    assert max(diffs) > 0


def test_fedavg_personal_tracking_updates_selected_only():
    """w_per_mdls semantics: a round updates only the sampled clients'
    personal models; the rest keep their previous weights."""
    algo = _make_algo("bce", frac=0.5)  # 4 of 8 clients per round
    state = algo.init_state(jax.random.PRNGKey(0))
    sel = sample_client_indexes(0, algo.num_clients, algo.clients_per_round)
    state2, _ = algo.run_round(state, 0)
    unsel = np.setdiff1d(np.arange(algo.num_clients), sel)
    for l0, l1 in zip(jax.tree_util.tree_leaves(state.personal_params),
                      jax.tree_util.tree_leaves(state2.personal_params)):
        # unselected rows unchanged
        np.testing.assert_array_equal(np.asarray(l0)[unsel],
                                      np.asarray(l1)[unsel])
    changed = any(
        not np.array_equal(np.asarray(l0)[sel], np.asarray(l1)[sel])
        for l0, l1 in zip(jax.tree_util.tree_leaves(state.personal_params),
                          jax.tree_util.tree_leaves(state2.personal_params)))
    assert changed
