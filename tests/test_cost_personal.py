"""Cost-accounting representative selection, checkpoint cost sidecar, and
FedAvg --track_personal 0 (advisor round-2 findings)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from neuroimagedisttraining_tpu.algorithms.fedavg import FedAvg
from neuroimagedisttraining_tpu.core.state import HyperParams
from neuroimagedisttraining_tpu.data.types import FederatedData
from neuroimagedisttraining_tpu.models import create_model
from neuroimagedisttraining_tpu.utils.flops import CostTracker


def _tiny_data(n_clients=4, n=24, d=32, classes=2, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n_clients, n, d, d, 3).astype(np.float32)
    y = rng.randint(0, classes, size=(n_clients, n))
    counts = np.full((n_clients,), n, np.int32)
    return FederatedData(
        x_train=jnp.asarray(x), y_train=jnp.asarray(y),
        n_train=jnp.asarray(counts),
        x_test=jnp.asarray(x[:, :8]), y_test=jnp.asarray(y[:, :8]),
        n_test=jnp.asarray(np.full((n_clients,), 8, np.int32)),
        class_num=classes,
    )


class _StackedMaskState:
    """Duck-typed state: stacked per-client masks with systematically
    different densities (the DisPFL --diff_spa shape)."""

    def __init__(self, densities, rng=np.random.RandomState(0)):
        c = len(densities)
        leaves = []
        for size in (400, 600):
            m = np.zeros((c, size), np.float32)
            for i, d in enumerate(densities):
                k = int(round(d * size))
                m[i, rng.choice(size, k, replace=False)] = 1.0
            leaves.append(jnp.asarray(m))
        self.masks = {"a": leaves[0], "b": leaves[1]}
        self.personal_params = {"a": jnp.arange(c, dtype=jnp.float32)[:, None]
                                * jnp.ones((1, 400)),
                                "b": jnp.ones((c, 600))}


def test_cost_snapshot_picks_mean_density_client():
    # client 0 is the sparsest; the cohort-mean-density client is #2
    densities = [0.2, 0.4, 0.6, 0.8, 1.0]

    class Algo:
        cost_snapshot = FedAvg.cost_snapshot

    state = _StackedMaskState(densities)
    params, mask = Algo().cost_snapshot(state)
    got_density = float(
        sum(jnp.sum(m) for m in jax.tree_util.tree_leaves(mask))) / 1000.0
    # representative density must be the closest to the cohort mean (0.6),
    # not client 0's 0.2
    assert abs(got_density - 0.6) < 0.05
    # params slice must come from the same client
    assert float(params["a"][0]) == pytest.approx(2.0)


def test_cost_tracker_totals_roundtrip():
    t = CostTracker()  # model-less: flops zero, comm counted
    t.record_round({"w": np.ones((4, 4))}, n_clients=3)
    t.record_round({"w": np.ones((4, 4))}, n_clients=2)
    meta = t.snapshot_totals()

    fresh = CostTracker()
    fresh.restore_totals(meta)
    assert fresh.sum_comm_params == t.sum_comm_params
    assert fresh.sum_training_flops == t.sum_training_flops
    # record_repeat must extend from the restored last-round record
    before = fresh.sum_comm_params
    rec = fresh.record_repeat()
    assert rec["comm_params"] == 2 * 16
    assert fresh.sum_comm_params == before + 2 * 16


def test_checkpoint_metadata_sidecar(tmp_path):
    from neuroimagedisttraining_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), "run")
    state = {"w": jnp.ones((3,))}
    mgr.save(2, state, metadata={"cost": {"sum_training_flops": 7.5,
                                          "sum_comm_params": 11,
                                          "last_training_flops": 2.5,
                                          "last_comm_params": 4}})
    meta = mgr.load_metadata(2)
    assert meta["cost"]["sum_comm_params"] == 11
    assert mgr.load_metadata(1) is None
    mgr.close()


def test_checkpoint_sidecar_pruned_with_steps(tmp_path):
    from neuroimagedisttraining_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), "run", max_to_keep=2)
    state = {"w": jnp.ones((3,))}
    for step in (1, 2, 3, 4):
        mgr.save(step, state, metadata={"cost": {}, "batching": "epoch"})
    import glob
    import os

    names = sorted(os.path.basename(p) for p in
                   glob.glob(str(tmp_path / "run" / "meta_*.json")))
    # orbax keeps the last 2 steps; orphaned sidecars must be pruned
    assert names == ["meta_3.json", "meta_4.json"]
    mgr.close()


def test_batching_mismatch_refused_on_resume_and_fresh_run(tmp_path):
    """Metric-protocol tags share checkpoint identities (config.py), so the
    metadata sidecar is the semantics gate: a --batching mismatch is
    refused both when resuming an existing lineage and when a fresh run
    would overwrite one round by round."""
    from neuroimagedisttraining_tpu.experiments.runner import run_experiment

    common = ["--algo", "local", "--model", "small3dcnn",
              "--dataset", "synthetic", "--client_num_in_total", "2",
              "--frac", "1.0", "--epochs", "1", "--batch_size", "4",
              "--comm_round", "1", "--frequency_of_the_test", "0",
              "--checkpoint_dir", str(tmp_path / "ck"),
              "--results_dir", "", "--log_dir", str(tmp_path / "log")]
    from neuroimagedisttraining_tpu.experiments.config import parse_args
    import pytest as _pytest

    run_experiment(parse_args(common))  # epoch-batching lineage, round 1
    # (a) resuming it under replacement semantics is refused
    with _pytest.raises(SystemExit, match="batching"):
        run_experiment(parse_args(
            common + ["--comm_round", "2", "--resume",
                      "--batching", "replacement"]))
    # (b) a FRESH replacement run into the same dir (no --resume) must
    # also be refused before it overwrites the lineage round by round
    with _pytest.raises(SystemExit, match="batching"):
        run_experiment(parse_args(common + ["--batching", "replacement"]))
    # (c) same-mode runs are unaffected
    out = run_experiment(parse_args(
        common + ["--comm_round", "2", "--resume"]))
    assert [h["round"] for h in out["history"]] == [1]


def test_fedavg_track_personal_off():
    data = _tiny_data()
    hp = HyperParams(lr=0.05, local_epochs=1, steps_per_epoch=2,
                     batch_size=8)
    algo = FedAvg(create_model("cnn_cifar10", num_classes=2), data, hp,
                  loss_type="ce", frac=1.0, track_personal=False)
    state = algo.init_state(jax.random.PRNGKey(0))
    assert state.personal_params is None
    state, rec = algo.run_round(state, 0)
    assert np.isfinite(float(rec["train_loss"]))
    ev = algo.evaluate(state)
    assert "global_acc" in ev and "personal_acc" not in ev
    # finalize (the fine-tune that exists to build personal models) no-ops
    state2, final = algo.finalize(state)
    assert final is None


def test_incremental_personal_eval_bitwise_equals_full():
    """The incremental personal-eval cache (base._personal_eval_cached):
    at frac<1 the per-round evaluate() re-evaluates only the clients
    trained since the last eval — ACCURACIES must be bitwise identical
    to a fresh full personal eval of the same state (integer counts /
    totals), LOSSES to f32 round-off (the subset-width eval program may
    reassociate a client's loss-sum reduction vs the full-width program
    — measured 1 ulp; the same standard the fused-vs-unfused eval gate
    uses). Covers cadence>1 accumulation with duplicate draws, finalize
    (empty dirty), and stale-state (identity-miss) fallbacks."""

    def close(a, b):
        return abs(a - b) <= 4e-7 * max(1.0, abs(b))

    from neuroimagedisttraining_tpu.algorithms import (
        Ditto,
        FedAvg,
        SalientGrads,
    )
    from neuroimagedisttraining_tpu.core.state import HyperParams
    from neuroimagedisttraining_tpu.data import make_synthetic_federated
    from neuroimagedisttraining_tpu.models import create_model

    data = make_synthetic_federated(
        n_clients=8, samples_per_client=24, test_per_client=8,
        sample_shape=(8, 8, 8, 1),
    )
    model = create_model("small3dcnn", num_classes=1)
    hp = HyperParams(lr=0.05, lr_decay=0.998, momentum=0.9, local_epochs=1,
                     steps_per_epoch=3, batch_size=8)

    for cls, kw in ((SalientGrads, dict(dense_ratio=0.5,
                                        itersnip_iterations=1)),
                    (FedAvg, {}),
                    (Ditto, dict(lamda=0.5))):
        # frac 0.25 (2 of 8 clients/round): cadence-2 evals accumulate a
        # 4-entry dirty list < C, so the MERGE path (not the >=C full-
        # eval fallback) is what runs — and the seeded draws for rounds
        # 1-4 overlap, so duplicate indices in the concatenated dirty are
        # exercised too
        algo = cls(model, data, hp, loss_type="bce", frac=0.25, seed=0,
                   **kw)
        state = algo.init_state(jax.random.PRNGKey(0))
        states = []
        for r in range(5):
            state, _ = algo.run_round(state, r)
            states.append(state)
            if r % 2 == 0:  # cadence 2: accumulated multi-round dirty
                ev = algo.evaluate(state)
                full = algo._eval_personal(
                    state.personal_params, data.x_test, data.y_test,
                    data.n_test)
                assert float(ev["personal_acc"]) == float(full["acc"]), \
                    (cls.__name__, r)
                assert close(float(ev["personal_loss"]),
                             float(full["loss"])), (cls.__name__, r)
        # empty-dirty path: immediate re-eval of the same state
        ev2 = algo.evaluate(state)
        full2 = algo._eval_personal(
            state.personal_params, data.x_test, data.y_test, data.n_test)
        assert float(ev2["personal_acc"]) == float(full2["acc"])
        # stale state (identity miss): falls back to a full eval, still
        # correct for THAT state
        ev_old = algo.evaluate(states[0])
        full_old = algo._eval_personal(
            states[0].personal_params, data.x_test, data.y_test,
            data.n_test)
        assert float(ev_old["personal_acc"]) == float(full_old["acc"])
