"""DARTS NAS suite: ops, search supernet, architect, genotype, final model."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.nas import (
    DARTS_V2,
    Genotype,
    NetworkFromGenotype,
    PRIMITIVES,
    SearchNetwork,
    derive_genotype,
    gumbel_weights,
    init_alphas,
    search,
    train_genotype,
)
from neuroimagedisttraining_tpu.nas.supernet import n_edges


def _toy_data(n=64, hw=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = rng.randn(n, hw, hw, 3).astype(np.float32) * 0.1
    # class-dependent mean shift makes the task learnable
    x += y[:, None, None, None] * 0.5
    return jnp.asarray(x), jnp.asarray(y)


def test_ops_registry_shapes():
    from neuroimagedisttraining_tpu.nas.ops import OPS

    x = jnp.ones((2, 8, 8, 6))
    for name in PRIMITIVES:
        for stride in (1, 2):
            op = OPS[name](6, stride)
            params = op.init(jax.random.PRNGKey(0), x)
            y = op.apply(params, x)
            expect_hw = 8 if stride == 1 else 4
            assert y.shape == (2, expect_hw, expect_hw, 6), \
                f"{name} stride={stride}: {y.shape}"


@pytest.mark.slow
def test_search_network_forward():
    net = SearchNetwork(C=4, num_classes=3, layers=4, steps=2, multiplier=2)
    alphas = init_alphas(steps=2)
    x = jnp.ones((2, 16, 16, 3))
    params = net.init(jax.random.PRNGKey(0), x, alphas)["params"]
    logits = net.apply({"params": params}, x, alphas)
    assert logits.shape == (2, 3)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_gumbel_weights_hard_one_hot():
    alphas = jnp.zeros((5, len(PRIMITIVES)))
    w = gumbel_weights(alphas, jax.random.PRNGKey(0), tau=0.5, hard=True)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), np.ones(5), rtol=1e-5)
    assert np.allclose(np.sort(np.asarray(w), axis=-1)[:, -1], 1.0)
    # gradient flows through the straight-through estimator
    g = jax.grad(lambda a: gumbel_weights(
        a, jax.random.PRNGKey(0), 0.5, True).sum())(alphas)
    assert np.any(np.asarray(g) != 0)


def test_derive_genotype_valid():
    steps = 4
    rng = jax.random.PRNGKey(1)
    alphas = {
        "normal": jax.random.normal(rng, (n_edges(steps), len(PRIMITIVES))),
        "reduce": jax.random.normal(rng, (n_edges(steps), len(PRIMITIVES))),
    }
    g = derive_genotype(alphas, steps=steps)
    assert isinstance(g, Genotype)
    assert len(g.normal) == 2 * steps and len(g.reduce) == 2 * steps
    for i in range(steps):
        for k in (2 * i, 2 * i + 1):
            name, j = g.normal[k]
            assert name in PRIMITIVES and name != "none"
            assert 0 <= j < 2 + i  # edge from an earlier state only


@pytest.mark.slow
def test_search_learns_and_derives(caplog):
    x, y = _toy_data()
    genotype, alphas, hist = search(
        x[:48], y[:48], x[48:], y[48:], num_classes=4,
        C=4, layers=2, steps=2, epochs=2, steps_per_epoch=3,
        batch_size=16, unrolled=True, seed=0)
    assert len(hist) == 2
    assert np.isfinite(hist[-1]["train_loss"])
    assert isinstance(genotype, Genotype)


@pytest.mark.slow
def test_first_order_architect_runs():
    x, y = _toy_data(n=32)
    genotype, _, hist = search(
        x[:24], y[:24], x[24:], y[24:], num_classes=4,
        C=4, layers=2, steps=2, epochs=1, steps_per_epoch=2,
        batch_size=8, unrolled=False, seed=1)
    assert np.isfinite(hist[-1]["val_loss"])


def test_train_genotype_from_preset_and_derived():
    x, y = _toy_data(n=48)
    net, params, hist = train_genotype(
        DARTS_V2, x, y, num_classes=4, C=4, layers=2,
        epochs=2, steps_per_epoch=4, batch_size=16,
        drop_path_prob=0.1, seed=0)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"] * 1.5
    logits = net.apply({"params": params}, x[:4])
    assert logits.shape == (4, 4)


def test_genotype_visualization():
    """DOT emission for both cells (darts/visualize.py parity)."""
    import os

    from neuroimagedisttraining_tpu.nas.genotypes import DARTS_V2
    from neuroimagedisttraining_tpu.nas.visualize import (
        cell_dot,
        genotype_dot,
        plot,
    )

    normal, reduce = genotype_dot(DARTS_V2)
    # every op edge appears with its primitive label
    for op, j in DARTS_V2.normal:
        assert op in normal
    assert normal.count("->") == len(DARTS_V2.normal) + len(
        DARTS_V2.normal_concat)
    assert '"c_{k-2}"' in reduce and '"c_{k}"' in reduce

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        paths = plot(DARTS_V2, os.path.join(d, "geno"))
        assert len(paths) == 2
        for p in paths:
            assert os.path.exists(p)


# -- auxiliary tower (VERDICT r3 missing #2) ---------------------------------

def test_auxiliary_head_torch_parity():
    """Forward parity of the aux tower against a torch twin built from the
    reference architecture (model.py:63-83, GroupNorm(1) standing in for
    BN per the repo-wide substitution) with transferred weights."""
    torch = pytest.importorskip("torch")
    from neuroimagedisttraining_tpu.nas.model import AuxiliaryHeadCIFAR

    C, classes = 16, 7
    head = AuxiliaryHeadCIFAR(num_classes=classes)
    x = np.random.RandomState(0).randn(3, 8, 8, C).astype(np.float32)
    params = head.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    jx = np.asarray(head.apply({"params": params}, jnp.asarray(x)))

    class TorchAux(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = torch.nn.Conv2d(C, 128, 1, bias=False)
            self.n1 = torch.nn.GroupNorm(1, 128)
            self.c2 = torch.nn.Conv2d(128, 768, 2, bias=False)
            self.n2 = torch.nn.GroupNorm(1, 768)
            self.fc = torch.nn.Linear(768, classes)

        def forward(self, t):
            t = torch.relu(t)
            t = torch.nn.functional.avg_pool2d(
                t, 5, stride=3, padding=0, count_include_pad=False)
            t = torch.relu(self.n1(self.c1(t)))
            t = torch.relu(self.n2(self.c2(t)))
            return self.fc(t.view(t.size(0), -1))

    net = TorchAux()
    sd = net.state_dict()
    sd["c1.weight"] = torch.from_numpy(
        np.asarray(params["Conv_0"]["kernel"]).transpose(3, 2, 0, 1).copy())
    sd["n1.weight"] = torch.from_numpy(
        np.asarray(params["GroupNorm_0"]["scale"]))
    sd["n1.bias"] = torch.from_numpy(np.asarray(params["GroupNorm_0"]["bias"]))
    sd["c2.weight"] = torch.from_numpy(
        np.asarray(params["Conv_1"]["kernel"]).transpose(3, 2, 0, 1).copy())
    sd["n2.weight"] = torch.from_numpy(
        np.asarray(params["GroupNorm_1"]["scale"]))
    sd["n2.bias"] = torch.from_numpy(np.asarray(params["GroupNorm_1"]["bias"]))
    sd["fc.weight"] = torch.from_numpy(
        np.asarray(params["Dense_0"]["kernel"]).T.copy())
    sd["fc.bias"] = torch.from_numpy(np.asarray(params["Dense_0"]["bias"]))
    net.load_state_dict(sd)
    tx = net(torch.from_numpy(x.transpose(0, 3, 1, 2).copy()))
    np.testing.assert_allclose(jx, tx.detach().numpy(), rtol=2e-4, atol=2e-4)


def test_network_auxiliary_tower_and_loss_composition():
    """auxiliary=True: train-mode forward returns both logit sets (aux from
    the 2/3-depth cell), eval-mode aux is None, and the training loss is
    main + 0.4*aux exactly (train.py:159-163)."""
    import optax

    from neuroimagedisttraining_tpu.nas.model import NetworkFromGenotype

    net = NetworkFromGenotype(genotype=DARTS_V2, C=4, num_classes=4,
                              layers=3, auxiliary=True)
    x = jnp.asarray(np.random.RandomState(1).randn(2, 32, 32, 3)
                    .astype(np.float32))
    params = net.init(jax.random.PRNGKey(0), x)["params"]
    assert any(k.startswith("AuxiliaryHead") for k in params)
    logits, logits_aux = net.apply({"params": params}, x, train=True)
    assert logits.shape == (2, 4) and logits_aux.shape == (2, 4)
    # aux and main heads are different functions of the input
    assert not np.allclose(np.asarray(logits), np.asarray(logits_aux))
    eval_logits, eval_aux = net.apply({"params": params}, x, train=False)
    assert eval_aux is None
    np.testing.assert_allclose(np.asarray(eval_logits),
                               np.asarray(logits), atol=1e-5)

    # composition pinned operationally: with weight_decay 0, the aux head's
    # params move IFF its loss is folded into the total (train.py:159-163) —
    # auxiliary_weight=0 must leave the head at its init, 0.4 must move it
    x_np = np.asarray(x)
    y_np = np.array([1, 3])
    common = dict(num_classes=4, C=4, layers=3, epochs=1, steps_per_epoch=3,
                  batch_size=2, weight_decay=0.0, seed=0)
    _, p0, _ = train_genotype(DARTS_V2, x_np, y_np, auxiliary=True,
                              auxiliary_weight=0.0, **common)
    _, p4, hist = train_genotype(DARTS_V2, x_np, y_np, auxiliary=True,
                                 auxiliary_weight=0.4, **common)
    assert np.isfinite(hist[-1]["train_loss"])
    aux_key = next(k for k in p0 if k.startswith("AuxiliaryHead"))
    # white-box replication of train_genotype's init chain (same seed)
    k_init, _ = jax.random.split(jax.random.PRNGKey(0))
    net2 = NetworkFromGenotype(genotype=DARTS_V2, C=4, num_classes=4,
                               layers=3, auxiliary=True)
    p_init = net2.init(k_init, jnp.zeros((1, 32, 32, 3)))["params"]
    flat0 = np.concatenate([np.asarray(v).ravel() for v in
                            jax.tree_util.tree_leaves(p0[aux_key])])
    flat4 = np.concatenate([np.asarray(v).ravel() for v in
                            jax.tree_util.tree_leaves(p4[aux_key])])
    flat_i = np.concatenate([np.asarray(v).ravel() for v in
                             jax.tree_util.tree_leaves(p_init[aux_key])])
    np.testing.assert_allclose(flat0, flat_i, atol=1e-7)  # 0.0: untouched
    assert np.abs(flat4 - flat_i).max() > 1e-6  # 0.4: trained


# -- ImageNet evaluation network (VERDICT r4 missing #2) ---------------------

def test_auxiliary_head_imagenet_torch_parity():
    """Forward parity of the ImageNet aux tower against a torch twin of
    the reference architecture (model.py:86-109): avgpool(5, stride 2,
    count_include_pad=False), 1x1->128 + norm, 2x2->768 with NO second
    norm (the reference comments it out, model.py:98-100), linear.
    GroupNorm(1) stands in for BN per the repo-wide substitution."""
    torch = pytest.importorskip("torch")
    from neuroimagedisttraining_tpu.nas.model import AuxiliaryHeadImageNet

    C, classes = 12, 6
    head = AuxiliaryHeadImageNet(num_classes=classes)
    x = np.random.RandomState(0).randn(3, 7, 7, C).astype(np.float32)
    params = head.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    jx = np.asarray(head.apply({"params": params}, jnp.asarray(x)))
    assert jx.shape == (3, classes)

    class TorchAux(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = torch.nn.Conv2d(C, 128, 1, bias=False)
            self.n1 = torch.nn.GroupNorm(1, 128)
            self.c2 = torch.nn.Conv2d(128, 768, 2, bias=False)
            self.fc = torch.nn.Linear(768, classes)

        def forward(self, t):
            t = torch.relu(t)
            t = torch.nn.functional.avg_pool2d(
                t, 5, stride=2, padding=0, count_include_pad=False)
            t = torch.relu(self.n1(self.c1(t)))
            t = torch.relu(self.c2(t))  # no second norm (model.py:98-100)
            return self.fc(t.view(t.size(0), -1))

    net = TorchAux()
    sd = net.state_dict()
    sd["c1.weight"] = torch.from_numpy(
        np.asarray(params["Conv_0"]["kernel"]).transpose(3, 2, 0, 1).copy())
    sd["n1.weight"] = torch.from_numpy(
        np.asarray(params["GroupNorm_0"]["scale"]))
    sd["n1.bias"] = torch.from_numpy(np.asarray(params["GroupNorm_0"]["bias"]))
    sd["c2.weight"] = torch.from_numpy(
        np.asarray(params["Conv_1"]["kernel"]).transpose(3, 2, 0, 1).copy())
    sd["fc.weight"] = torch.from_numpy(
        np.asarray(params["Dense_0"]["kernel"]).T.copy())
    sd["fc.bias"] = torch.from_numpy(np.asarray(params["Dense_0"]["bias"]))
    net.load_state_dict(sd)
    tx = net(torch.from_numpy(x.transpose(0, 3, 1, 2).copy()))
    np.testing.assert_allclose(jx, tx.detach().numpy(), rtol=2e-4, atol=2e-4)
    # only ONE norm layer exists — the reference omits the 768 norm
    assert sorted(k for k in params if k.startswith("GroupNorm")) == \
        ["GroupNorm_0"]


def test_network_imagenet_stem_matches_torch_and_aux_wiring():
    """NetworkImageNet (model.py:161-247): the dual stride-2 stem halves
    224 three times (s0 56x56, s1 28x28 — torch-parity-pinned with
    transferred weights), cell 0 runs reduction_prev, the aux tower fires
    at 2/3 depth in train mode only, and the 7x7 pool feeds a flat-768…
    classifier of the right arity."""
    torch = pytest.importorskip("torch")
    from neuroimagedisttraining_tpu.nas.model import (
        NetworkImageNetFromGenotype,
    )

    C, classes, layers = 8, 5, 2
    net = NetworkImageNetFromGenotype(
        genotype=DARTS_V2, C=C, num_classes=classes, layers=layers,
        auxiliary=True)
    x = np.random.RandomState(1).randn(1, 224, 224, 3).astype(np.float32)
    params = net.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    assert any(k.startswith("AuxiliaryHeadImageNet") for k in params)
    logits, logits_aux = net.apply({"params": params}, jnp.asarray(x),
                                   train=True)
    assert logits.shape == (1, classes) and logits_aux.shape == (1, classes)
    ev, ev_aux = net.apply({"params": params}, jnp.asarray(x), train=False)
    assert ev_aux is None
    np.testing.assert_allclose(np.asarray(ev), np.asarray(logits),
                               atol=1e-5)

    # stem parity: transferred weights reproduce torch's stem0/stem1
    # (conv k3 s2 p1 chains, model.py:167-179)
    tc0 = torch.nn.Conv2d(3, C // 2, 3, stride=2, padding=1, bias=False)
    tn0 = torch.nn.GroupNorm(1, C // 2)
    tc1 = torch.nn.Conv2d(C // 2, C, 3, stride=2, padding=1, bias=False)
    tn1 = torch.nn.GroupNorm(1, C)
    tc2 = torch.nn.Conv2d(C, C, 3, stride=2, padding=1, bias=False)
    tn2 = torch.nn.GroupNorm(1, C)
    with torch.no_grad():
        tc0.weight.copy_(torch.from_numpy(np.asarray(
            params["Conv_0"]["kernel"]).transpose(3, 2, 0, 1).copy()))
        tn0.weight.copy_(torch.from_numpy(np.asarray(
            params["GroupNorm_0"]["scale"])))
        tn0.bias.copy_(torch.from_numpy(np.asarray(
            params["GroupNorm_0"]["bias"])))
        tc1.weight.copy_(torch.from_numpy(np.asarray(
            params["Conv_1"]["kernel"]).transpose(3, 2, 0, 1).copy()))
        tn1.weight.copy_(torch.from_numpy(np.asarray(
            params["GroupNorm_1"]["scale"])))
        tn1.bias.copy_(torch.from_numpy(np.asarray(
            params["GroupNorm_1"]["bias"])))
        tc2.weight.copy_(torch.from_numpy(np.asarray(
            params["Conv_2"]["kernel"]).transpose(3, 2, 0, 1).copy()))
        tn2.weight.copy_(torch.from_numpy(np.asarray(
            params["GroupNorm_2"]["scale"])))
        tn2.bias.copy_(torch.from_numpy(np.asarray(
            params["GroupNorm_2"]["bias"])))
        tx = torch.from_numpy(x.transpose(0, 3, 1, 2).copy())
        ts0 = tn1(tc1(torch.relu(tn0(tc0(tx)))))
        ts1 = tn2(tc2(torch.relu(ts0)))
    # jax-side stems recomputed from the same params
    import flax.linen as fnn

    def stem_apply(p, xx):
        s = fnn.Conv(C // 2, (3, 3), strides=(2, 2), padding=1,
                     use_bias=False).apply({"params": p["Conv_0"]}, xx)
        s = fnn.GroupNorm(num_groups=1).apply(
            {"params": p["GroupNorm_0"]}, s)
        s = fnn.relu(s)
        s = fnn.Conv(C, (3, 3), strides=(2, 2), padding=1,
                     use_bias=False).apply({"params": p["Conv_1"]}, s)
        s0 = fnn.GroupNorm(num_groups=1).apply(
            {"params": p["GroupNorm_1"]}, s)
        s = fnn.relu(s0)
        s = fnn.Conv(C, (3, 3), strides=(2, 2), padding=1,
                     use_bias=False).apply({"params": p["Conv_2"]}, s)
        s1 = fnn.GroupNorm(num_groups=1).apply(
            {"params": p["GroupNorm_2"]}, s)
        return s0, s1

    js0, js1 = stem_apply(params, jnp.asarray(x))
    assert js0.shape == (1, 56, 56, C) and js1.shape == (1, 28, 28, C)
    np.testing.assert_allclose(
        np.asarray(js0), ts0.numpy().transpose(0, 2, 3, 1),
        rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(js1), ts1.numpy().transpose(0, 2, 3, 1),
        rtol=2e-4, atol=2e-4)
