"""DARTS NAS suite: ops, search supernet, architect, genotype, final model."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.nas import (
    DARTS_V2,
    Genotype,
    NetworkFromGenotype,
    PRIMITIVES,
    SearchNetwork,
    derive_genotype,
    gumbel_weights,
    init_alphas,
    search,
    train_genotype,
)
from neuroimagedisttraining_tpu.nas.supernet import n_edges


def _toy_data(n=64, hw=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    x = rng.randn(n, hw, hw, 3).astype(np.float32) * 0.1
    # class-dependent mean shift makes the task learnable
    x += y[:, None, None, None] * 0.5
    return jnp.asarray(x), jnp.asarray(y)


def test_ops_registry_shapes():
    from neuroimagedisttraining_tpu.nas.ops import OPS

    x = jnp.ones((2, 8, 8, 6))
    for name in PRIMITIVES:
        for stride in (1, 2):
            op = OPS[name](6, stride)
            params = op.init(jax.random.PRNGKey(0), x)
            y = op.apply(params, x)
            expect_hw = 8 if stride == 1 else 4
            assert y.shape == (2, expect_hw, expect_hw, 6), \
                f"{name} stride={stride}: {y.shape}"


@pytest.mark.slow
def test_search_network_forward():
    net = SearchNetwork(C=4, num_classes=3, layers=4, steps=2, multiplier=2)
    alphas = init_alphas(steps=2)
    x = jnp.ones((2, 16, 16, 3))
    params = net.init(jax.random.PRNGKey(0), x, alphas)["params"]
    logits = net.apply({"params": params}, x, alphas)
    assert logits.shape == (2, 3)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_gumbel_weights_hard_one_hot():
    alphas = jnp.zeros((5, len(PRIMITIVES)))
    w = gumbel_weights(alphas, jax.random.PRNGKey(0), tau=0.5, hard=True)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), np.ones(5), rtol=1e-5)
    assert np.allclose(np.sort(np.asarray(w), axis=-1)[:, -1], 1.0)
    # gradient flows through the straight-through estimator
    g = jax.grad(lambda a: gumbel_weights(
        a, jax.random.PRNGKey(0), 0.5, True).sum())(alphas)
    assert np.any(np.asarray(g) != 0)


def test_derive_genotype_valid():
    steps = 4
    rng = jax.random.PRNGKey(1)
    alphas = {
        "normal": jax.random.normal(rng, (n_edges(steps), len(PRIMITIVES))),
        "reduce": jax.random.normal(rng, (n_edges(steps), len(PRIMITIVES))),
    }
    g = derive_genotype(alphas, steps=steps)
    assert isinstance(g, Genotype)
    assert len(g.normal) == 2 * steps and len(g.reduce) == 2 * steps
    for i in range(steps):
        for k in (2 * i, 2 * i + 1):
            name, j = g.normal[k]
            assert name in PRIMITIVES and name != "none"
            assert 0 <= j < 2 + i  # edge from an earlier state only


@pytest.mark.slow
def test_search_learns_and_derives(caplog):
    x, y = _toy_data()
    genotype, alphas, hist = search(
        x[:48], y[:48], x[48:], y[48:], num_classes=4,
        C=4, layers=2, steps=2, epochs=2, steps_per_epoch=3,
        batch_size=16, unrolled=True, seed=0)
    assert len(hist) == 2
    assert np.isfinite(hist[-1]["train_loss"])
    assert isinstance(genotype, Genotype)


@pytest.mark.slow
def test_first_order_architect_runs():
    x, y = _toy_data(n=32)
    genotype, _, hist = search(
        x[:24], y[:24], x[24:], y[24:], num_classes=4,
        C=4, layers=2, steps=2, epochs=1, steps_per_epoch=2,
        batch_size=8, unrolled=False, seed=1)
    assert np.isfinite(hist[-1]["val_loss"])


def test_train_genotype_from_preset_and_derived():
    x, y = _toy_data(n=48)
    net, params, hist = train_genotype(
        DARTS_V2, x, y, num_classes=4, C=4, layers=2,
        epochs=2, steps_per_epoch=4, batch_size=16,
        drop_path_prob=0.1, seed=0)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"] * 1.5
    logits = net.apply({"params": params}, x[:4])
    assert logits.shape == (4, 4)


def test_genotype_visualization():
    """DOT emission for both cells (darts/visualize.py parity)."""
    import os

    from neuroimagedisttraining_tpu.nas.genotypes import DARTS_V2
    from neuroimagedisttraining_tpu.nas.visualize import (
        cell_dot,
        genotype_dot,
        plot,
    )

    normal, reduce = genotype_dot(DARTS_V2)
    # every op edge appears with its primitive label
    for op, j in DARTS_V2.normal:
        assert op in normal
    assert normal.count("->") == len(DARTS_V2.normal) + len(
        DARTS_V2.normal_concat)
    assert '"c_{k-2}"' in reduce and '"c_{k}"' in reduce

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        paths = plot(DARTS_V2, os.path.join(d, "geno"))
        assert len(paths) == 2
        for p in paths:
            assert os.path.exists(p)
