"""Fast tier-1 chaos coverage: the chaos smoke path at CI scale, the
divergence watchdog's rollback-retry-skip ladder, and the checkpoint
satellites (best-effort save, corrupt-step restore fallback)."""
import math

import jax
import numpy as np
import pytest

from neuroimagedisttraining_tpu.experiments import parse_args, run_experiment
from neuroimagedisttraining_tpu.robust.recovery import (
    OK,
    RETRY,
    SKIP,
    RoundWatchdog,
)
from neuroimagedisttraining_tpu.utils.checkpoint import CheckpointManager


def _argv(tmp_path, **over):
    base = {
        "--model": "small3dcnn", "--dataset": "synthetic",
        "--client_num_in_total": "4", "--batch_size": "8",
        "--epochs": "1", "--comm_round": "3", "--lr": "0.05",
        "--log_dir": str(tmp_path / "LOG"),
        "--results_dir": str(tmp_path / "results"),
        "--final_finetune": "0",
    }
    base.update(over)
    argv = []
    for k, v in base.items():
        argv += [k, v]
    return argv


def test_chaos_smoke_ci_scale(tmp_path):
    """The scripts/chaos_smoke.py contract at CI scale: injected dropout
    + NaN, run completes, final loss finite, counters recorded."""
    args = parse_args(_argv(
        tmp_path, **{"--fault_spec": "drop=0.25,straggle=0.1,nan=0.2"}),
        algo="fedavg")
    out = run_experiment(args, "fedavg")
    hist = [h for h in out["history"] if "train_loss" in h]
    assert len(hist) == 3
    assert all(math.isfinite(float(h["train_loss"])) for h in hist)
    assert math.isfinite(float(out["final_eval"]["global_loss"]))
    for x in jax.tree_util.tree_leaves(out["state"].global_params):
        assert np.all(np.isfinite(np.asarray(x)))
    assert all("clients_dropped" in h and "clients_quarantined" in h
               and "rounds_retried" in h for h in hist)
    assert sum(float(h["clients_dropped"])
               + float(h["clients_quarantined"]) for h in hist) > 0


def test_watchdog_recovers_genuine_divergence(tmp_path):
    """A deliberately divergent config (huge lr, loss explodes to
    non-finite): the watchdog retries then skips every bad round, the
    run COMPLETES with finite recorded metrics — degrade, don't die."""
    args = parse_args(_argv(tmp_path, **{
        "--lr": "1e8", "--frac": "0.5", "--client_num_in_total": "8",
        "--watchdog": "1", "--watchdog_loss": "10.0",
        "--max_round_retries": "1",
        "--comm_round": "2"}), algo="fedavg")
    out = run_experiment(args, "fedavg")
    hist = [h for h in out["history"] if "rounds_retried" in h]
    assert len(hist) == 2
    # every round was retried once then skipped (divergence is global)
    assert all(float(h["rounds_retried"]) == 1.0 for h in hist)
    assert all(h.get("round_skipped") == 1.0 for h in hist)
    # the carried last-good state is the (finite) init state
    for x in jax.tree_util.tree_leaves(out["state"].global_params):
        assert np.all(np.isfinite(np.asarray(x)))
    fr = None
    import pickle

    with open(out["stat_path"], "rb") as f:
        fr = pickle.load(f)["fault_recovery"]
    assert fr["rounds_retried"] == 2.0
    assert fr["rounds_skipped"] == 2.0


def test_watchdog_judge_ladder():
    """OK -> RETRY x max -> SKIP, with deterministic counters."""
    naps = []
    wd = RoundWatchdog(max_retries=2, backoff_s=1.0, sleep=naps.append)
    good = {"train_loss": 0.5}

    class S:
        global_params = None

    assert wd.judge(0, dict(good), S(), S()) == OK
    bad = {"train_loss": float("nan")}
    assert wd.judge(1, dict(bad), S(), S()) == RETRY
    assert wd.judge(1, dict(bad), S(), S()) == RETRY
    assert wd.judge(1, dict(bad), S(), S()) == SKIP
    assert naps == [1.0, 2.0]  # linear backoff
    assert wd.rounds_retried == 2 and wd.rounds_skipped == 1
    # threshold checks
    wd2 = RoundWatchdog(max_retries=0, loss_threshold=1.0)
    assert wd2.judge(0, {"train_loss": 2.0}, S(), S()) == SKIP
    assert wd2.judge(1, {"train_loss": 0.9}, S(), S()) == OK


def test_watchdog_retry_resamples_cohort():
    from neuroimagedisttraining_tpu.algorithms.base import (
        sample_client_indexes,
    )

    base = sample_client_indexes(5, 100, 10)
    again = sample_client_indexes(5, 100, 10)
    assert np.array_equal(base, again)  # reference contract intact
    r1 = sample_client_indexes(5, 100, 10, retry=1)
    r2 = sample_client_indexes(5, 100, 10, retry=2)
    assert not np.array_equal(base, r1)
    assert not np.array_equal(r1, r2)
    # deterministic per (round, retry) — the resume-replay property
    assert np.array_equal(r1, sample_client_indexes(5, 100, 10, retry=1))
    # full participation has no alternative cohort
    assert np.array_equal(sample_client_indexes(3, 8, 8, retry=2),
                          np.arange(8))


def test_watchdog_rollback_prefers_memory_then_checkpoint(tmp_path):
    mgr = CheckpointManager(str(tmp_path), "wd")
    state = {"w": np.arange(4, dtype=np.float32)}
    mgr.save(1, state, force=True)
    wd = RoundWatchdog(ckpt_mgr=mgr, template_fn=lambda: state)
    # in-memory last-good wins
    sentinel = object()
    assert wd.rollback(sentinel) is sentinel
    # no in-memory state: restore the checkpoint lineage
    restored = wd.rollback(None)
    np.testing.assert_array_equal(restored["w"], state["w"])
    mgr.close()


# -- checkpoint satellites ---------------------------------------------------

def test_checkpoint_save_is_best_effort(tmp_path):
    mgr = CheckpointManager(str(tmp_path), "be")
    state = {"w": np.ones((3,), np.float32)}

    def boom(*a, **k):
        raise OSError("disk full")

    orig = mgr.mgr.save
    mgr.mgr.save = boom
    assert mgr.save(1, state, force=True) is False  # no raise
    assert mgr.save_failures == 1
    mgr.mgr.save = orig
    assert mgr.save(2, state, force=True) is True  # recovered
    assert mgr.save_failures == 1
    restored = mgr.restore_latest(state)
    assert restored is not None and restored[1] == 2
    mgr.close()


def test_restore_latest_falls_back_to_older_step(tmp_path):
    mgr = CheckpointManager(str(tmp_path), "fb", save_every=1)
    state1 = {"w": np.full((3,), 1.0, np.float32)}
    state2 = {"w": np.full((3,), 2.0, np.float32)}
    assert mgr.save(1, state1, force=True)
    assert mgr.save(2, state2, force=True)

    orig = mgr.mgr.restore

    def corrupt_newest(step, *a, **k):
        if step == 2:
            raise ValueError("partial write: missing array chunk")
        return orig(step, *a, **k)

    mgr.mgr.restore = corrupt_newest
    restored = mgr.restore_latest(state1)
    assert restored is not None
    state, step = restored
    assert step == 1
    np.testing.assert_array_equal(np.asarray(state["w"]), state1["w"])
    mgr.close()


def test_restore_latest_raises_when_every_step_corrupt(tmp_path):
    mgr = CheckpointManager(str(tmp_path), "allbad")
    state = {"w": np.ones((2,), np.float32)}
    mgr.save(1, state, force=True)

    def boom(step, *a, **k):
        raise ValueError("corrupt")

    mgr.mgr.restore = boom
    with pytest.raises(RuntimeError, match="no retained checkpoint"):
        mgr.restore_latest(state)
    mgr.close()


def test_restore_latest_survives_on_disk_corruption(tmp_path):
    """Real on-disk damage (every file of the newest step overwritten —
    a torn write): resume falls back to the older step instead of
    dying."""
    import os

    mgr = CheckpointManager(str(tmp_path), "disk", save_every=1)
    state1 = {"w": np.full((3,), 1.0, np.float32)}
    state2 = {"w": np.full((3,), 2.0, np.float32)}
    mgr.save(1, state1, force=True)
    mgr.save(2, state2, force=True)
    mgr.close()

    step_dir = os.path.join(str(tmp_path), "disk", "2")
    assert os.path.isdir(step_dir)
    for dp, _, fs in os.walk(step_dir):
        for name in fs:
            with open(os.path.join(dp, name), "wb") as fh:
                fh.write(b"CORRUPT")

    mgr2 = CheckpointManager(str(tmp_path), "disk", save_every=1)
    restored = mgr2.restore_latest(state1)
    assert restored is not None
    state, step = restored
    assert step == 1
    np.testing.assert_array_equal(np.asarray(state["w"]), state1["w"])
    mgr2.close()
