"""Unit tests: losses, optimizer semantics, state utilities."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuroimagedisttraining_tpu.core.losses import (
    bce_with_logits_loss,
    predictions,
    softmax_ce_loss,
)
from neuroimagedisttraining_tpu.core.optim import (
    clip_by_global_norm,
    global_norm,
    sgd_momentum_step,
)
from neuroimagedisttraining_tpu.core.state import (
    broadcast_tree,
    weighted_tree_sum,
)


def test_bce_matches_reference_formula():
    logits = jnp.array([0.5, -1.2, 3.0])
    labels = jnp.array([1, 0, 1])
    expected = -np.mean(
        np.array(labels) * np.log(1 / (1 + np.exp(-np.array(logits))))
        + (1 - np.array(labels)) * np.log(1 - 1 / (1 + np.exp(-np.array(logits))))
    )
    got = bce_with_logits_loss(logits[:, None], labels)
    assert np.allclose(got, expected, rtol=1e-4)


def test_ce_matches_nll():
    logits = jnp.array([[2.0, 0.5, -1.0], [0.0, 1.0, 0.0]])
    labels = jnp.array([0, 2])
    p = np.exp(np.array(logits))
    p /= p.sum(-1, keepdims=True)
    expected = -np.mean(np.log(p[np.arange(2), np.array(labels)]))
    assert np.allclose(softmax_ce_loss(logits, labels), expected, rtol=1e-5)


def test_predictions_bce_threshold():
    logits = jnp.array([[0.01], [-0.01], [0.0]])
    preds = predictions(logits, "bce")
    assert preds.tolist() == [1, 0, 1]  # sigmoid>=0.5 <=> logit>=0


def test_clip_by_global_norm_matches_torch_semantics():
    grads = {"a": jnp.full((10,), 3.0), "b": jnp.full((10,), 4.0)}
    norm = float(global_norm(grads))
    assert np.isclose(norm, np.sqrt(10 * 9 + 10 * 16))
    clipped = clip_by_global_norm(grads, 1.0)
    assert np.isclose(float(global_norm(clipped)), 1.0, rtol=1e-4)
    # below threshold: untouched
    small = {"a": jnp.array([0.1]), "b": jnp.array([0.1])}
    out = clip_by_global_norm(small, 10.0)
    assert np.allclose(out["a"], small["a"])


def test_sgd_momentum_matches_torch_update_order():
    # torch: g += wd*p; buf = mu*buf + g; p -= lr*buf
    p = {"w": jnp.array([1.0])}
    m = {"w": jnp.array([0.0])}
    g = {"w": jnp.array([2.0])}
    lr, mu, wd = jnp.float32(0.1), 0.9, 0.01
    p1, m1 = sgd_momentum_step(p, m, g, lr, mu, wd)
    g_eff = 2.0 + 0.01 * 1.0
    assert np.allclose(m1["w"], g_eff)
    assert np.allclose(p1["w"], 1.0 - 0.1 * g_eff)
    # second step accumulates momentum
    p2, m2 = sgd_momentum_step(p1, m1, g, lr, mu, wd)
    g_eff2 = 2.0 + 0.01 * float(p1["w"][0])
    buf2 = 0.9 * g_eff + g_eff2
    assert np.allclose(m2["w"], buf2, rtol=1e-5)
    assert np.allclose(p2["w"], p1["w"] - 0.1 * buf2, rtol=1e-5)


def test_weighted_tree_sum_is_fedavg_aggregate():
    # mirrors fedavg_api.py:102-117: w_global[k] = sum_i (n_i/N) local_i[k]
    stacked = {"w": jnp.array([[1.0, 1.0], [3.0, 3.0]])}
    weights = jnp.array([0.25, 0.75])
    out = weighted_tree_sum(stacked, weights)
    assert np.allclose(out["w"], [2.5, 2.5])


def test_broadcast_tree():
    t = {"w": jnp.ones((2, 3))}
    b = broadcast_tree(t, 4)
    assert b["w"].shape == (4, 2, 3)
