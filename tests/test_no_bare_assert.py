"""Lint gate: no bare ``assert`` on contract paths (the recurring
``python -O`` hazard, ADVICE r5 — ``-O`` strips asserts, so a contract
check spelled as one silently vanishes in optimized deployments).

Contract paths are the modules whose runtime checks gate correctness or
data integrity: the fault-tolerance subsystem, checkpointing, the round
machinery, the aggregation wires, the multihost sync points, and the
runner/config surface. Their checks must be explicit raises. Everything
else (tests, benches, visualization) may keep asserts."""
import ast
import os

import pytest

PKG = os.path.join(os.path.dirname(__file__), "..",
                   "neuroimagedisttraining_tpu")

#: contract-path modules where ``assert`` is forbidden (extend as modules
#: become load-bearing; a new bare assert in any of these fails CI)
CONTRACT_PATHS = [
    "robust/faults.py",
    "robust/guard.py",
    "robust/recovery.py",
    "robust/aggregation.py",
    "obs/trace.py",
    "obs/metrics.py",
    "obs/export.py",
    "obs/memory.py",
    "obs/analyze.py",
    "obs/health.py",
    "obs/regress.py",
    "obs/compile.py",
    "obs/numerics.py",
    "obs/recorder.py",
    "obs/comm.py",
    "obs/devtrace.py",
    "comm/message.py",
    "comm/base.py",
    "utils/checkpoint.py",
    "utils/records.py",
    "utils/flops.py",
    "algorithms/base.py",
    "algorithms/fedavg.py",
    "algorithms/salientgrads.py",
    "parallel/collectives.py",
    "parallel/multihost.py",
    "parallel/mesh.py",
    "core/state.py",
    "core/trainer.py",
    "experiments/runner.py",
    "experiments/config.py",
]


@pytest.mark.parametrize("rel", CONTRACT_PATHS)
def test_no_bare_assert_on_contract_path(rel):
    path = os.path.normpath(os.path.join(PKG, rel))
    assert os.path.exists(path), f"contract path moved/removed: {rel}"
    with open(path) as f:
        tree = ast.parse(f.read(), filename=rel)
    offenders = [
        f"{rel}:{node.lineno}" for node in ast.walk(tree)
        if isinstance(node, ast.Assert)
    ]
    assert not offenders, (
        f"bare assert on a contract path (python -O strips it; raise "
        f"ValueError/RuntimeError instead): {offenders}")
