"""Lint gate: no bare ``assert`` on contract paths (the recurring
``python -O`` hazard, ADVICE r5 — ``-O`` strips asserts, so a contract
check spelled as one silently vanishes in optimized deployments).

Now a thin wrapper over ``analysis/astlint.py``: contract paths are
**auto-discovered** (every package module except the reviewed
``NON_CONTRACT_ALLOWLIST``) instead of the hand-maintained 31-entry
``CONTRACT_PATHS`` list this module used to carry — which had already
drifted (``algorithms/ditto.py``, ``comm/grpc_backend.py``,
``comm/tcp.py``, ``comm/local.py``, and the newer ``robust/`` modules
were unlisted). The full rule set (host-sync, nondeterminism, identity
inertness, jaxpr contracts) runs in ``tests/test_lint_gate.py``; this
module keeps the historical name pointed at the historical rule so the
contract's coverage stays individually visible per module."""
import os

import pytest

from neuroimagedisttraining_tpu.analysis.astlint import (
    NON_CONTRACT_ALLOWLIST,
    PackageLint,
)

PKG = os.path.join(os.path.dirname(__file__), "..",
                   "neuroimagedisttraining_tpu")


@pytest.fixture(scope="module")
def lint():
    return PackageLint(PKG)


def test_no_bare_assert_package_wide(lint):
    offenders = [
        f"{f.file}:{f.line}" for f in lint.lint()
        if f.rule == "bare-assert"]
    assert not offenders, (
        f"bare assert on a contract path (python -O strips it; raise "
        f"ValueError/RuntimeError instead): {offenders}")


def test_contract_paths_auto_discover_the_whole_package(lint):
    """The property the old hand-maintained list could not have: every
    module is a contract path unless the allowlist says otherwise —
    including the modules the old list had drifted past."""
    contract = set(lint.contract_modules())
    for drifted in ("algorithms/ditto.py", "comm/grpc_backend.py",
                    "comm/tcp.py", "comm/local.py",
                    "robust/aggregation.py"):
        assert drifted in contract, drifted
    # allowlisted modules are OUT, and the allowlist can't go stale
    # (prefix entries — trailing / — cover codegen dirs that may be
    # absent on a fresh checkout and are exempt from the existence pin)
    for rel, reason in NON_CONTRACT_ALLOWLIST.items():
        assert reason.strip()
        if rel.endswith("/"):
            assert not any(m.replace(os.sep, "/").startswith(rel)
                           for m in contract)
        else:
            assert rel not in contract
            assert rel in lint.modules, f"stale allowlist entry: {rel}"
