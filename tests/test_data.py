"""Data-layer tests: partitioners, ABCD HDF5 path, CIFAR path, preprocessing.

The reference has no tests (SURVEY.md §4); these pin the partition semantics
it relies on: Dirichlet LDA min-size retry, site-seeded 80/20 splits,
contiguous rescale sharding, proportional test resampling.
"""
import os
import pickle

import numpy as np
import pytest

from neuroimagedisttraining_tpu.data import (
    FederatedData,
    class_prior_partition,
    contiguous_reshard,
    dirichlet_partition,
    load_federated_data,
    load_partition_data_abcd,
    load_partition_data_abcd_rescale,
    load_partition_data_cifar,
    proportional_test_indices,
    random_crop_flip,
    record_data_stats,
    site_partition,
    site_train_test_split,
    write_abcd_h5,
)
from neuroimagedisttraining_tpu.data.preprocess import (
    compute_brain_mask,
    discover_t1_volumes,
    preprocess_abcd,
    read_site_info,
)


# -- Dirichlet / LDA ---------------------------------------------------------

def test_dirichlet_partition_covers_all_indices_once():
    rng = np.random.RandomState(0)
    y = rng.randint(0, 10, size=2000)
    mapping = dirichlet_partition(y, 8, 10, alpha=0.5,
                                  rng=np.random.RandomState(1))
    allidx = np.concatenate([mapping[i] for i in range(8)])
    assert sorted(allidx.tolist()) == list(range(2000))
    assert min(len(mapping[i]) for i in range(8)) >= 10


def test_dirichlet_high_alpha_near_uniform():
    y = np.random.RandomState(0).randint(0, 4, size=4000)
    mapping = dirichlet_partition(y, 4, 4, alpha=100.0,
                                  rng=np.random.RandomState(2))
    sizes = np.array([len(mapping[i]) for i in range(4)])
    assert sizes.min() > 0.7 * sizes.max()


def test_record_data_stats():
    y = np.array([0, 0, 1, 1, 2])
    stats = record_data_stats(y, {0: np.array([0, 1, 2]),
                                  1: np.array([3, 4])})
    assert stats[0] == {0: 2, 1: 1}
    assert stats[1] == {1: 1, 2: 1}


# -- class-prior partitions --------------------------------------------------

def test_n_cls_partition_limits_classes_per_client():
    y = np.random.RandomState(0).randint(0, 10, size=5000)
    mapping = class_prior_partition(y, 10, 10, "n_cls", alpha=2, seed=3)
    allidx = np.concatenate([mapping[c] for c in range(10)])
    assert len(allidx) == len(set(allidx.tolist()))  # no duplicates
    # most clients should see only ~2 classes (repair can add a few extras)
    n_cls_per_client = [len(np.unique(y[mapping[c]])) for c in range(10)
                        if len(mapping[c])]
    assert np.median(n_cls_per_client) <= 4


def test_dir_partition_sizes_roughly_equal():
    y = np.random.RandomState(0).randint(0, 10, size=5000)
    mapping = class_prior_partition(y, 10, 10, "dir", alpha=0.3, seed=4)
    sizes = np.array([len(mapping[c]) for c in range(10)])
    assert sizes.sum() <= 5000
    assert sizes.min() >= 0.5 * 500  # lognormal(sigma=0) -> equal targets


def test_homo_partition():
    y = np.zeros(100, np.int32)
    mapping = class_prior_partition(y, 4, 2, "homo", seed=0)
    assert sorted(np.concatenate(list(mapping.values())).tolist()) == \
        list(range(100))


def test_my_part_groups_share_priors():
    y = np.random.RandomState(0).randint(0, 10, size=3000)
    mapping = class_prior_partition(y, 8, 10, "my_part", alpha=2, seed=5)
    assert sum(len(v) for v in mapping.values()) <= 3000


def test_proportional_test_indices_mirror_train_hist():
    y_test = np.repeat(np.arange(4), 100)
    counts = {0: {0: 90, 1: 10}, 1: {2: 50, 3: 50}}
    out = proportional_test_indices(y_test, counts, 2, 4,
                                    rng=np.random.RandomState(0))
    labels0 = y_test[out[0]]
    assert (labels0 == 0).mean() > 0.7  # mostly class 0
    labels1 = y_test[out[1]]
    assert set(np.unique(labels1)) <= {2, 3}


# -- site / contiguous -------------------------------------------------------

def test_site_partition_and_rescale():
    site = np.array([0, 1, 0, 2, 1, 0])
    mapping = site_partition(site)
    assert len(mapping) == 3
    assert mapping[0].tolist() == [0, 2, 5]
    shards = contiguous_reshard(10, 3)
    assert shards[0].tolist() == [0, 1, 2]
    assert shards[2].tolist() == [6, 7, 8]  # remainder dropped


def test_site_train_test_split_seed42_reproducible():
    site = np.random.RandomState(0).randint(0, 3, size=60)
    s1 = site_train_test_split(site)
    s2 = site_train_test_split(site)
    for k in s1:
        tr1, te1 = s1[k]
        tr2, te2 = s2[k]
        np.testing.assert_array_equal(tr1, tr2)
        np.testing.assert_array_equal(te1, te2)
        assert len(set(tr1) & set(te1)) == 0
        n = len(tr1) + len(te1)
        assert len(te1) == int(n * 0.2)


# -- ABCD HDF5 path ----------------------------------------------------------

@pytest.fixture
def abcd_h5(tmp_path):
    rng = np.random.RandomState(0)
    n = 60
    X = rng.rand(n, 6, 7, 6).astype(np.float32)
    y = rng.randint(0, 2, size=n)
    site = rng.randint(0, 3, size=n)
    path = str(tmp_path / "final_dataset_60subs.h5")
    write_abcd_h5(path, X, y, site)
    return path, X, y, site


def test_load_partition_data_abcd_site_clients(abcd_h5):
    path, X, y, site = abcd_h5
    data = load_partition_data_abcd(path)
    assert isinstance(data, FederatedData)
    assert data.num_clients == len(np.unique(site))
    assert data.class_num == 2
    assert data.x_train.shape[-1] == 1  # channel axis added
    # per-client totals match the site populations
    for i, s in enumerate(np.unique(site)):
        pop = (site == s).sum()
        assert int(data.n_train[i]) + int(data.n_test[i]) == pop


def test_load_partition_data_abcd_rescale(abcd_h5):
    path, X, y, site = abcd_h5
    data = load_partition_data_abcd_rescale(path, client_number=4)
    assert data.num_clients == 4
    sizes = np.asarray(data.n_train)
    assert (sizes == sizes[0]).all()  # equal contiguous shards


def test_abcd_val_fraction(abcd_h5):
    path, *_ = abcd_h5
    data = load_partition_data_abcd_rescale(path, client_number=3,
                                            val_fraction=0.25)
    assert data.x_val is not None
    assert int(np.asarray(data.n_val).sum()) > 0


# -- CIFAR path --------------------------------------------------------------

@pytest.fixture
def cifar_dir(tmp_path):
    """Fake cifar-10-batches-py layout with 500 train / 200 test samples."""
    rng = np.random.RandomState(0)
    base = tmp_path / "cifar-10-batches-py"
    base.mkdir()
    for i in range(1, 6):
        d = {"data": rng.randint(0, 256, size=(100, 3072), dtype=np.uint8)
             .astype(np.uint8),
             "labels": rng.randint(0, 10, size=100).tolist()}
        with open(base / f"data_batch_{i}", "wb") as f:
            pickle.dump(d, f)
    d = {"data": rng.randint(0, 256, size=(200, 3072), dtype=np.uint8),
         "labels": rng.randint(0, 10, size=200).tolist()}
    with open(base / "test_batch", "wb") as f:
        pickle.dump(d, f)
    return str(tmp_path)


def test_load_partition_data_cifar(cifar_dir):
    data = load_partition_data_cifar(
        cifar_dir, "cifar10", partition_method="dir", partition_alpha=0.5,
        client_number=5, seed=0)
    assert data.num_clients == 5
    assert data.class_num == 10
    assert data.x_train.shape[-1] == 3
    assert data.x_train.shape[2:4] == (32, 32)
    # normalized, so roughly zero-centered
    assert abs(float(np.asarray(data.x_train).mean())) < 1.0


def test_cifar_dispatch_and_val(cifar_dir):
    data = load_federated_data("cifar10", cifar_dir, client_number=4,
                               val_fraction=0.1, seed=0)
    assert data.x_val is not None


def test_random_crop_flip_shapes():
    import jax

    x = np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32)
    out = random_crop_flip(jax.random.PRNGKey(0), x)
    assert out.shape == x.shape
    out2 = random_crop_flip(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2))


# -- preprocessing pipeline --------------------------------------------------

def test_preprocess_abcd_pipeline(tmp_path):
    # build a fake BIDS tree of .npy "volumes" + metadata table
    rng = np.random.RandomState(0)
    shape = (5, 6, 5)
    subjects = [f"sub-{i:03d}" for i in range(6)]
    for i, s in enumerate(subjects):
        d = tmp_path / "bids" / s / "anat"
        d.mkdir(parents=True)
        np.save(d / "vol.npy", rng.rand(*shape).astype(np.float32) + 0.1)
        os.rename(d / "vol.npy", d / "Sm6mwc1pT1.nii")
    meta = tmp_path / "ABCDSexSiteInfo.txt"
    lines = ["subject sex site"]
    for i, s in enumerate(subjects):
        lines.append(f"{s} {'F' if i % 2 else 'M'} site{i % 2}")
    meta.write_text("\n".join(lines))

    def load_volume(path):
        return np.load(path, allow_pickle=False)

    found = discover_t1_volumes(str(tmp_path / "bids"))
    assert len(found) == 6
    info = read_site_info(str(meta))
    assert info["sub-001"] == (1, "site1")

    out, mask = preprocess_abcd(
        str(tmp_path / "bids"), str(meta),
        out_path=str(tmp_path / "out.h5"), load_volume=load_volume)
    assert mask.shape == shape
    data = load_partition_data_abcd(out)
    assert data.num_clients == 2  # two sites


def test_compute_brain_mask():
    vols = [np.full((3, 3, 3), 0.5), np.zeros((3, 3, 3))]
    mask = compute_brain_mask(vols, threshold=0.2)
    assert mask.sum() == 27  # mean 0.25 > 0.2 everywhere
    mask = compute_brain_mask(vols, threshold=0.3)
    assert mask.sum() == 0


def test_abcd_layouts(abcd_h5):
    """flat / s2d storage layouts (TPU HBM-tiling-friendly paths)."""
    from neuroimagedisttraining_tpu.ops.s2d import (
        phase_decompose,
        phased_sample_shape,
    )

    path, X, y, site = abcd_h5
    flat = load_partition_data_abcd(path, layout="flat")
    assert flat.sample_shape == (6, 7, 6)  # no channel axis

    s2d = load_partition_data_abcd(path, layout="s2d")
    assert s2d.sample_shape == phased_sample_shape((6, 7, 6))

    # the phased rows must equal phase_decompose of the flat rows
    c0 = int(flat.n_train[0])
    np.testing.assert_allclose(
        np.asarray(s2d.x_train[0, :c0]),
        np.asarray(phase_decompose(np.asarray(flat.x_train[0, :c0]))),
        rtol=1e-6)

    with pytest.raises(ValueError):
        load_partition_data_abcd(path, layout="nope")
