"""Property-based validation of the wire-cost model against the REAL
serialized bytes (hypothesis).

obs/comm.py's analytical model prices the aggregation wire; this pins
its message-payload predictions against what ``Message.to_bytes()``
actually produces, for random pytrees / masks / dtypes: dense f32,
bf16-cast (the low-precision wire's serialization), and masked-sparse
payloads all land within the documented header-overhead budget — so the
modeled bytes the analyzer reports are the bytes a cross-silo transport
would really ship.
"""
import numpy as np
import pytest

# hypothesis is an optional test extra (pyproject `test`); without it
# the deterministic shim keeps the properties exercised (weaker — no
# shrinking — but never a silent skip)
try:
    from hypothesis import given, settings, strategies as st
except ImportError:
    from _hypothesis_fallback import given, settings, strategies as st

from neuroimagedisttraining_tpu.comm.message import Message
from neuroimagedisttraining_tpu.obs.comm import (
    message_overhead_budget,
    message_payload_nbytes,
    topk_payload,
)
from neuroimagedisttraining_tpu.parallel.collectives import topk_count

_DTYPES = [np.float32, np.float16, np.int32, np.uint8]


def _arrays(draw):
    shape = tuple(draw(st.lists(st.integers(0, 5), min_size=0,
                                max_size=3)))
    dtype = draw(st.sampled_from(_DTYPES))
    n = int(np.prod(shape)) if shape else 1
    vals = draw(st.lists(st.integers(-3, 3), min_size=n, max_size=n))
    return np.asarray(vals, np.float64).astype(dtype).reshape(shape)


@st.composite
def pytrees(draw, depth=2):
    if depth == 0 or draw(st.booleans()):
        return _arrays(draw)
    kind = draw(st.sampled_from(["dict", "list", "tuple"]))
    if kind in ("list", "tuple"):
        items = draw(st.lists(pytrees(depth=depth - 1), min_size=0,
                              max_size=3))
        return items if kind == "list" else tuple(items)
    keys = st.text(st.characters(codec="ascii", min_codepoint=97,
                                 max_codepoint=122), min_size=1,
                   max_size=4)
    return draw(st.dictionaries(keys, pytrees(depth=depth - 1),
                                max_size=3))


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def _check_bounds(actual_len, payload_pred, n_leaves):
    # the model predicts the raw leaf blobs EXACTLY; everything on top
    # is the JSON header framing, bounded by the documented budget
    assert actual_len >= payload_pred
    overhead = actual_len - payload_pred
    assert overhead <= message_overhead_budget(n_leaves), (
        f"header overhead {overhead} exceeds the documented budget for "
        f"{n_leaves} leaves")


@settings(max_examples=60, deadline=None)
@given(tree=pytrees())
def test_dense_payload_within_header_budget(tree):
    msg = Message("t", 0, 1)
    msg.add_tensor("p", tree)
    raw = msg.to_bytes()
    _check_bounds(len(raw), message_payload_nbytes(tree),
                  len(_leaves(tree)))
    assert msg.nbytes == len(raw)


@settings(max_examples=30, deadline=None)
@given(tree=pytrees())
def test_bf16_payload_within_header_budget(tree):
    """The bf16 wire's serialization: every leaf cast to bfloat16 costs
    2 bytes/element on the wire — exactly what the model predicts."""
    ml_dtypes = pytest.importorskip("ml_dtypes")
    import jax

    cast = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32).astype(ml_dtypes.bfloat16),
        tree)
    msg = Message("t", 0, 1)
    msg.add_tensor("p", cast)
    raw = msg.to_bytes()
    pred = message_payload_nbytes(cast)
    assert pred == sum(l.size * 2 for l in _leaves(cast))
    _check_bounds(len(raw), pred, len(_leaves(cast)))


@settings(max_examples=60, deadline=None)
@given(data=st.data(),
       shape=st.tuples(st.integers(1, 8), st.integers(1, 8)),
       frac=st.floats(0.01, 1.0))
def test_topk_payload_within_header_budget(data, shape, frac):
    """The error-feedback top-k wire (PR 7): per leaf, topk_count(n,
    frac) coordinates as int32 idx + f32 values — RESIDUAL-FREE (the
    residual is algorithm state, never serialized). The model's 8
    bytes/selected-coordinate prediction is exact on the raw payload;
    the serialized Message lands within the documented budget on top."""
    n = shape[0] * shape[1]
    vals = np.asarray(
        data.draw(st.lists(st.integers(-9, 9), min_size=n, max_size=n)),
        np.float64).astype(np.float32).reshape(shape)
    tree = {"w": vals, "b": vals.reshape(-1)[:shape[0]].copy()}
    payload = topk_payload(tree, frac)
    pred = sum(topk_count(int(np.prod(l.shape)), frac) * (4 + 4)
               for l in tree.values())
    assert message_payload_nbytes(payload) == pred
    msg = Message("t", 0, 1)
    msg.add_tensor("p", payload)
    raw = msg.to_bytes()
    _check_bounds(len(raw), pred, 2 * len(tree))  # idx + val per leaf
    # round-trip: shipped values match the source at the shipped indices
    back = Message.from_bytes(raw).get_tensor("p")
    for key, leaf in tree.items():
        np.testing.assert_array_equal(
            back[key]["val"], leaf.reshape(-1)[back[key]["idx"]])


@settings(max_examples=60, deadline=None)
@given(data=st.data(),
       shape=st.tuples(st.integers(1, 6), st.integers(1, 6)),
       dtype=st.sampled_from(_DTYPES))
def test_masked_sparse_payload_within_header_budget(data, shape, dtype):
    n = shape[0] * shape[1]
    vals = np.asarray(
        data.draw(st.lists(st.integers(-9, 9), min_size=n, max_size=n)),
        np.float64).astype(dtype).reshape(shape)
    bits = data.draw(st.lists(st.booleans(), min_size=n, max_size=n))
    mask = np.asarray(bits, np.float32).reshape(shape)
    tree, mtree = {"w": vals, "b": vals.copy()}, {"w": mask, "b": mask}

    msg = Message("t", 0, 1)
    msg.add_masked_tensor("p", tree, mtree)
    raw = msg.to_bytes()
    pred = message_payload_nbytes(tree, mtree)
    # the prediction is exact per leaf: nnz values + packed bitmap
    nnz = int(np.count_nonzero(mask))
    assert pred == 2 * (nnz * vals.dtype.itemsize + (n + 7) // 8)
    _check_bounds(len(raw), pred, 2)
    # densified round-trip still matches (the bitmap rode along)
    np.testing.assert_array_equal(
        Message.from_bytes(raw).get_tensor("p")["w"],
        (vals * mask.astype(dtype)).astype(dtype))
