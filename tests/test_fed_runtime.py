"""Federation runtime units: protocol determinism, flag validation,
typed round outcomes, counter thread-safety, and the (slow) loopback
end-to-end parity anchors.

The fast tests here exercise everything that does NOT need a built
model: ``fed.protocol`` key/partition determinism, the
``parse_site_faults``/``parse_endpoints`` grammars, the
``validate_fed_args`` refusal cluster, ``send_with_retry``'s
retry/backoff accounting, ``CrossSiloServer.run_round``'s
completed/quorum/timeout verdicts, and the ``CommCounters`` lock. The
``slow``-marked e2e twins mirror ``scripts/fed_smoke.py`` (the CI
gate) for ``-m slow`` sweeps.
"""
import threading

import numpy as np
import pytest

from neuroimagedisttraining_tpu.comm.base import CommCounters
from neuroimagedisttraining_tpu.comm.cross_silo import (CrossSiloClient,
                                                        CrossSiloServer)
from neuroimagedisttraining_tpu.comm.local import LocalRouter
from neuroimagedisttraining_tpu.comm.message import Message
from neuroimagedisttraining_tpu.fed.protocol import (partition_slots,
                                                     send_with_retry,
                                                     site_round_key)
from neuroimagedisttraining_tpu.fed.runtime import (parse_endpoints,
                                                    parse_site_faults,
                                                    validate_fed_args)


# ---------------------------------------------------------------- protocol


def test_partition_slots_contiguous_cover():
    for n_items in (1, 5, 6, 7):
        parts = partition_slots(n_items, 3)
        assert len(parts) == 3
        flat = np.concatenate(parts)
        # contiguity is load-bearing: the sync aggregator reassembles
        # the [S] cohort stack by concatenating site blocks in rank
        # order, which is slot order only because blocks are contiguous
        np.testing.assert_array_equal(flat, np.arange(n_items))
        sizes = [len(p) for p in parts]
        assert max(sizes) - min(sizes) <= 1


def test_site_round_key_deterministic_and_distinct():
    k = site_round_key(0, 3, 1)
    np.testing.assert_array_equal(np.asarray(k),
                                  np.asarray(site_round_key(0, 3, 1)))
    seen = {tuple(np.asarray(site_round_key(s, v, r)).tolist())
            for s in (0, 1) for v in (0, 1, 2) for r in (1, 2, 3)}
    assert len(seen) == 2 * 3 * 3  # no collisions across (seed, v, rank)


def test_send_with_retry_counts_and_reraises():
    class Flaky:
        def __init__(self, fail_n):
            self.fail_n = fail_n
            self.sent = 0
            self.counters = CommCounters()

        def send_message(self, msg):
            if self.fail_n > 0:
                self.fail_n -= 1
                raise ConnectionRefusedError("not yet bound")
            self.sent += 1

    m = Flaky(fail_n=2)
    send_with_retry(m, Message("x", 1, 0), retries=2, backoff_s=0.0)
    assert m.sent == 1
    assert m.counters.snapshot()["comm_messages_retried"] == 2

    m2 = Flaky(fail_n=3)
    with pytest.raises(OSError):
        send_with_retry(m2, Message("x", 1, 0), retries=2, backoff_s=0.0)
    assert m2.counters.snapshot()["comm_messages_retried"] == 2


# ------------------------------------------------------------- flag parsing


def test_parse_site_faults_grammar():
    out = parse_site_faults("3:straggle=1.0:6.0;1:drop=0.5")
    assert set(out) == {1, 3}
    fs3, delay3, kill3 = out[3]
    assert delay3 == 6.0 and kill3 == 0.0
    _fs1, delay1, _kill1 = out[1]
    assert delay1 == 2.0  # DEFAULT_STRAGGLE_S when no trailing delay
    assert parse_site_faults("") == {}


@pytest.mark.parametrize("bad", [
    "3",                      # no fault spec
    "x:drop=1.0",             # non-int rank
    "0:drop=1.0",             # rank < 1 (site ranks start at 1)
    "2:drop=0.1;2:drop=0.2",  # duplicate rank
    "2:drop=0.1:oops",        # trailing field neither clause nor delay
])
def test_parse_site_faults_rejects(bad):
    with pytest.raises(ValueError):
        parse_site_faults(bad)


def test_parse_endpoints():
    eps = parse_endpoints("127.0.0.1:9000, 10.0.0.2:9001", 2)
    assert eps == [("127.0.0.1", 9000), ("10.0.0.2", 9001)]
    with pytest.raises(ValueError):
        parse_endpoints("127.0.0.1:9000", 2)  # count mismatch
    with pytest.raises(ValueError):
        parse_endpoints("nocolon, 1.2.3.4:5", 2)


# -------------------------------------------------------- refusal cluster


def _fed_args(tmp_path, *extra):
    from neuroimagedisttraining_tpu.experiments import parse_args

    return parse_args([
        "--model", "small3dcnn", "--dataset", "synthetic",
        "--client_num_in_total", "6", "--frac", "1.0",
        "--batch_size", "8", "--epochs", "1", "--comm_round", "2",
        "--final_finetune", "0",
        "--results_dir", str(tmp_path / "results"),
        "--fed_role", "aggregator", "--fed_mode", "sync",
        "--fed_sites", "3",
    ] + list(extra), algo="fedavg")


def test_validate_accepts_the_baseline(tmp_path):
    validate_fed_args(_fed_args(tmp_path), "fedavg")


@pytest.mark.parametrize("mutate, fragment", [
    (dict(fuse_rounds=4), "fuse_rounds"),
    (dict(watchdog=2), "watchdog"),
    (dict(client_store="host"), "client_store"),
    (dict(multihost=True), "multihost"),
    (dict(defense_type="krum"), "defenses"),
    (dict(fault_spec="drop=0.2"), "fed_site_faults"),
    (dict(eval_cache=1), "eval_cache"),
    (dict(checkpoint_dir="/tmp/ck"), "checkpoint"),
    (dict(mesh_space=2), "mesh_space"),
    (dict(agg_impl="int8"), "bit-parity"),            # sync + compressed
    (dict(fed_mode="buffered", agg_impl="zfp"), "wire codec"),
    (dict(fed_mode="buffered", frac=0.5), "frac"),
    (dict(fed_mode="buffered", fed_buffer_k=9), "fed_buffer_k"),
    (dict(fed_mode="buffered", fed_buffer_k=2, fed_staleness_bound=-1),
     "staleness"),
    (dict(fed_replay="/tmp/trace.json"), "replay"),   # replay + sync
    (dict(fed_site_faults="9:drop=1.0"), "only 3 sites"),
    (dict(fed_sites=0), "fed_sites"),
])
def test_validate_refuses(tmp_path, mutate, fragment):
    args = _fed_args(tmp_path)
    for k, v in mutate.items():
        setattr(args, k, v)
    with pytest.raises(SystemExit, match=fragment):
        validate_fed_args(args, "fedavg")


def test_validate_refuses_non_fedavg(tmp_path):
    with pytest.raises(SystemExit, match="fedavg"):
        validate_fed_args(_fed_args(tmp_path), "salientgrads")


def test_derive_rejects_mode_without_role(tmp_path):
    from neuroimagedisttraining_tpu.experiments import parse_args

    with pytest.raises(ValueError, match="fed_role"):
        parse_args([
            "--model", "small3dcnn", "--dataset", "synthetic",
            "--results_dir", str(tmp_path / "results"),
            "--fed_mode", "buffered",
        ], algo="fedavg")


def test_derive_resolves_buffer_k_sentinel(tmp_path):
    args = _fed_args(tmp_path, "--fed_mode", "buffered")
    assert args.fed_buffer_k == 2  # max(1, sites - 1) from the 0 sentinel
    assert _fed_args(tmp_path).fed_mode == "sync"  # role defaults the mode


def test_fed_identity_classification(tmp_path):
    from neuroimagedisttraining_tpu.experiments import run_identity

    sync_id = run_identity(_fed_args(tmp_path), "fedavg")
    assert "fedsync" in sync_id and "fs3" in sync_id
    plain = run_identity(_fed_args(tmp_path), "fedavg").replace(
        "-fedsync-fs3", "")
    # inert deployment knobs must NOT move the identity
    moved = _fed_args(tmp_path, "--fed_timeout_s", "5",
                      "--fed_retries", "7", "--fed_backoff_s", "0.5")
    assert run_identity(moved, "fedavg") == sync_id
    assert plain  # sanity: stripping the fed tags leaves the base identity


# ------------------------------------------------------------ CommCounters


def test_comm_counters_threaded_consistency():
    """The regression the lock exists for: concurrent note_* from a
    receive pump and a sending round loop must not tear or lose
    updates. Pre-lock, the += pairs raced (bytes landed, count did
    not)."""
    c = CommCounters()
    n_threads, per_thread = 8, 500

    def worker():
        for _ in range(per_thread):
            c.note_sent(10)
            c.note_received(3)
            c.note_retry()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = c.snapshot()
    total = n_threads * per_thread
    assert snap == {
        "comm_bytes_sent": 10 * total,
        "comm_bytes_received": 3 * total,
        "comm_messages_sent": total,
        "comm_messages_received": total,
        "comm_messages_retried": total,
    }


# ------------------------------------------------------------ RoundOutcome


def _params(v):
    return {"w": np.full((3,), float(v), np.float32)}


def _train_fn(rank):
    def fn(params, round_idx):
        out = {"w": np.asarray(params["w"]) + rank}
        return out, 10 * rank, float(rank)
    return fn


def test_run_round_completed():
    router = LocalRouter(3)
    server = CrossSiloServer(router.manager(0), 3, _params(0.0))
    clients = [CrossSiloClient(router.manager(r), r, 3, _train_fn(r))
               for r in (1, 2)]
    for c in clients:
        c.run(background=True)
    server.run(background=True)
    try:
        outcome = server.run_round(0, timeout_s=30.0)
        assert outcome.status == "completed" and outcome.applied
        assert outcome.received == [1, 2] and outcome.missing == []
        # weighted mean of (0+1)*10/30 + (0+2)*20/30
        np.testing.assert_allclose(server.global_params["w"],
                                   np.full((3,), 5.0 / 3.0), rtol=1e-6)
        assert outcome.record["clients_reported"] == 2.0
    finally:
        server.comm.stop_receive_message()
        for c in clients:
            c.comm.stop_receive_message()


def test_run_round_quorum_renormalizes_over_survivors():
    router = LocalRouter(3)
    server = CrossSiloServer(router.manager(0), 3, _params(0.0))
    # rank 2 exists on the router but never reads its queue: a dead site
    client = CrossSiloClient(router.manager(1), 1, 3, _train_fn(1))
    client.run(background=True)
    server.run(background=True)
    try:
        outcome = server.run_round(0, timeout_s=1.0, quorum=1)
        assert outcome.status == "quorum" and outcome.applied
        assert outcome.received == [1] and outcome.missing == [2]
        # survivor renormalization: rank 1's update at weight 1.0
        np.testing.assert_array_equal(server.global_params["w"],
                                      np.full((3,), 1.0, np.float32))
    finally:
        server.comm.stop_receive_message()
        client.comm.stop_receive_message()


def test_run_round_timeout_carries_global_model():
    router = LocalRouter(3)
    init = _params(7.0)
    server = CrossSiloServer(router.manager(0), 3, init)
    server.run(background=True)
    try:
        outcome = server.run_round(0, timeout_s=0.3)
        assert outcome.status == "timeout" and not outcome.applied
        assert outcome.received == [] and outcome.missing == [1, 2]
        assert np.isnan(outcome.record["train_loss"])
        # untouched, not re-aggregated: the exact same object carries
        assert server.global_params is init
    finally:
        server.comm.stop_receive_message()


# ------------------------------------------------------- e2e (slow twins)


def _smoke_argv(tmp_path, sub, *extra):
    return [
        "--model", "small3dcnn", "--dataset", "synthetic",
        "--client_num_in_total", "6", "--frac", "1.0",
        "--batch_size", "8", "--epochs", "1", "--comm_round", "2",
        "--lr", "0.05", "--final_finetune", "0",
        "--log_dir", str(tmp_path / sub / "LOG"),
        "--results_dir", str(tmp_path / sub / "results"),
    ] + list(extra)


@pytest.mark.slow
def test_loopback_sync_bit_parity(tmp_path):
    import jax

    from neuroimagedisttraining_tpu.experiments import (parse_args,
                                                        run_experiment)
    from neuroimagedisttraining_tpu.obs.diff import params_diff

    fed = run_experiment(parse_args(_smoke_argv(
        tmp_path, "fed", "--fed_role", "aggregator", "--fed_mode",
        "sync", "--fed_sites", "3"), algo="fedavg"), "fedavg")
    # --mesh_devices 1: the parity anchor is the UNSHARDED simulation —
    # sites compute on one device, and a clients-mesh twin reduces in a
    # different order (~1e-7 drift under the conftest's 8 virtual devices)
    twin = run_experiment(parse_args(_smoke_argv(
        tmp_path, "twin", "--mesh_devices", "1"), algo="fedavg"),
        "fedavg")
    twin_params = jax.tree_util.tree_map(
        np.asarray, twin["state"].global_params)
    assert params_diff(fed["global_params"], twin_params)["identical"]


@pytest.mark.slow
def test_loopback_buffered_trace_replays(tmp_path):
    import json

    from neuroimagedisttraining_tpu.experiments import (parse_args,
                                                        run_experiment)
    from neuroimagedisttraining_tpu.obs.diff import params_diff

    buf_extra = ["--fed_role", "aggregator", "--fed_mode", "buffered",
                 "--fed_sites", "3", "--fed_buffer_k", "2",
                 "--fed_site_faults", "3:straggle=1.0:30.0"]
    out = run_experiment(parse_args(_smoke_argv(
        tmp_path, "buf", *buf_extra), algo="fedavg"), "fedavg")
    trace = json.load(open(out["fed"]["trace_path"]))
    assert all(site != 3 for fl in trace["flushes"]
               for site, _b in fl["members"])
    rep = run_experiment(parse_args(_smoke_argv(
        tmp_path, "rep", *buf_extra, "--fed_replay",
        out["fed"]["trace_path"]), algo="fedavg"), "fedavg")
    assert rep["fed"]["replayed"]
    assert params_diff(out["global_params"],
                       rep["global_params"])["identical"]
