"""Per-client epoch batching semantics (reference parity).

The reference iterates each client's own ``DataLoader(shuffle=True)`` —
``ceil(n_i/batch)`` batches per epoch, the last one partial, loss averaged
over the batch's own size (``my_model_trainer.py:194-216``,
``ABCD/data_loader.py:202``). These tests pin the TPU rebuild's static-shape
implementation (``core/trainer.py`` epoch mode) to those semantics exactly:

* every valid sample is consumed exactly once per epoch (permutation test +
  one-hot visit test);
* each client runs exactly its own ``ceil(n_i/batch)`` optimizer steps per
  epoch regardless of the cohort-wide scan bound (scalar-bias model whose
  gradient is independent of batch composition, so the step count is
  recoverable to float precision — including frozen momentum on no-op steps);
* the partial final batch averages over its own ``n_i mod batch`` examples
  (exact numpy replication using the extracted permutations).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from neuroimagedisttraining_tpu.core.state import HyperParams
from neuroimagedisttraining_tpu.core.trainer import (
    epoch_permutations,
    make_client_update,
)


def test_epoch_permutations_cover_each_sample_once():
    n_valid, epochs, length = 13, 4, 24
    perms = np.asarray(epoch_permutations(
        jax.random.PRNGKey(3), jnp.int32(n_valid), epochs, length))
    assert perms.shape == (epochs, length)
    for e in range(epochs):
        # first n_valid slots: a permutation of the valid row indices
        assert sorted(perms[e, :n_valid].tolist()) == list(range(n_valid))
        # the rest point at padded rows and get masked by batch weights
        assert (perms[e, n_valid:] >= n_valid).all()
    # epochs are shuffled independently
    assert not np.array_equal(perms[0, :n_valid], perms[1, :n_valid])


def test_truncated_epoch_samples_whole_shard():
    # steps_per_epoch*batch smaller than the shard: each epoch must draw a
    # fresh random subset of ALL valid rows, not a fixed index prefix
    n_valid, n_rows, length, epochs = 200, 220, 64, 8
    perms = np.asarray(epoch_permutations(
        jax.random.PRNGKey(0), jnp.int32(n_valid), epochs, length,
        n_rows=n_rows))
    assert perms.shape == (epochs, length)
    seen = set()
    for e in range(epochs):
        sub = perms[e]
        assert (sub < n_valid).all()  # valid rows only (length < n_valid)
        assert len(set(sub.tolist())) == length  # without replacement
        seen.update(sub.tolist())
    # across a few epochs the union covers far more than one prefix
    assert len(seen) > 150


def _bias_apply(params, x, train, rng):
    # one logit per example, equal to the scalar bias — the BCE gradient
    # d/db mean(sigmoid(b) - y) is independent of WHICH examples are in the
    # batch, so parameter trajectories depend only on the number of active
    # optimizer steps.
    del train, rng
    return jnp.broadcast_to(params["b"], (x.shape[0], 1))


@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_per_client_step_counts_match_reference(momentum):
    # unequal sites: ceil(10/8)=2, ceil(50/8)=7, ceil(56/8)=7 steps/epoch
    n_clients = [10, 50, 56]
    bs, epochs = 8, 2
    n_max = max(n_clients)
    spe = -(-n_max // bs)
    hp = HyperParams(lr=0.3, lr_decay=1.0, momentum=momentum,
                     weight_decay=0.0, grad_clip=1e9, local_epochs=epochs,
                     steps_per_epoch=spe, batch_size=bs, batching="epoch")
    update = make_client_update(_bias_apply, "bce", hp)

    x = jnp.zeros((n_max, 1))
    y = jnp.ones((n_max,))
    for n_i in n_clients:
        params = {"b": jnp.zeros(())}
        mom = {"b": jnp.zeros(())}
        out_params, out_mom, _ = jax.jit(update)(
            params, mom, {"b": jnp.ones(())}, jax.random.PRNGKey(0),
            x, y, jnp.int32(n_i), jnp.int32(0), params)
        # numpy replication of exactly ceil(n_i/bs) steps per epoch
        b, m = 0.0, 0.0
        ref_steps = epochs * (-(-n_i // bs))
        for _ in range(ref_steps):
            g = 1.0 / (1.0 + np.exp(-b)) - 1.0  # d BCE/d logit, labels=1
            m = momentum * m + g
            b = b - hp.lr * m
        np.testing.assert_allclose(float(out_params["b"]), b, rtol=1e-5)
        # momentum must be FROZEN on masked no-op steps, not decayed
        np.testing.assert_allclose(float(out_mom["b"]), m, rtol=1e-5)


def test_every_sample_visited_padded_rows_untouched():
    # one-hot inputs: pred_i = w[sample_id]; a sample's weight moves iff the
    # sample was drawn. After one epoch every valid id must have moved and
    # every padded id must be bit-identical.
    n_valid, n_max, bs = 11, 16, 4
    spe = -(-n_valid // bs)  # 3 (runner uses the cohort max; equal here)
    hp = HyperParams(lr=0.1, lr_decay=1.0, momentum=0.0, weight_decay=0.0,
                     grad_clip=1e9, local_epochs=1, steps_per_epoch=spe,
                     batch_size=bs, batching="epoch")

    def apply_fn(params, xb, train, rng):
        del train, rng
        return xb @ params["w"]  # [k] predictions

    update = make_client_update(apply_fn, "mse", hp,
                                mask_params_post_step=False)
    w0 = jnp.arange(1.0, n_max + 1.0)
    x = jnp.eye(n_max)
    y = jnp.zeros((n_max,))
    out, _, _ = jax.jit(update)(
        {"w": w0}, {"w": jnp.zeros(n_max)}, {"w": jnp.ones(n_max)},
        jax.random.PRNGKey(7), x, y, jnp.int32(n_valid), jnp.int32(0),
        {"w": w0})
    moved = np.asarray(out["w"]) != np.asarray(w0)
    assert moved[:n_valid].all(), "every valid sample trains once per epoch"
    assert not moved[n_valid:].any(), "padded rows must never train"


def test_partial_batch_mean_exact_numpy_replication():
    # full white-box replication: extract the epoch permutations with the
    # same key derivation as client_update and simulate the reference's
    # loop (partial last batch averaged over its own size) in numpy.
    n_valid, n_max, bs, epochs = 10, 12, 8, 2
    spe = -(-n_valid // bs)  # 2: one full batch + one 2-example batch
    hp = HyperParams(lr=0.05, lr_decay=1.0, momentum=0.0, weight_decay=0.0,
                     grad_clip=1e9, local_epochs=epochs, steps_per_epoch=spe,
                     batch_size=bs, batching="epoch")

    def apply_fn(params, xb, train, rng):
        del train, rng
        return xb @ params["w"]

    update = make_client_update(apply_fn, "mse", hp,
                                mask_params_post_step=False)
    rng = jax.random.PRNGKey(11)
    w0 = np.linspace(-1.0, 1.0, n_max).astype(np.float32)
    x = jnp.eye(n_max)
    y = jnp.zeros((n_max,))
    out, _, mean_loss = jax.jit(update)(
        {"w": jnp.asarray(w0)}, {"w": jnp.zeros(n_max)},
        {"w": jnp.ones(n_max)}, rng, x, y, jnp.int32(n_valid),
        jnp.int32(0), {"w": jnp.asarray(w0)})

    k_perm, _ = jax.random.split(rng)
    perms = np.asarray(epoch_permutations(
        k_perm, jnp.int32(n_valid), epochs, spe * bs))
    w = w0.copy()
    losses = []
    for e in range(epochs):
        order = perms[e, :n_valid]
        for b0 in range(0, n_valid, bs):
            ids = order[b0:b0 + bs]
            per_ex = w[ids] ** 2  # mse vs target 0
            losses.append(per_ex.mean())
            grad = np.zeros_like(w)
            grad[ids] = 2.0 * w[ids] / len(ids)  # mean over the batch's OWN size
            w = w - hp.lr * grad
    np.testing.assert_allclose(np.asarray(out["w"]), w, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(mean_loss), np.mean(losses), rtol=1e-5)
