"""Prometheus exposition (obs/prom.py): renderer golden pins + the
scrape endpoint.

* **Golden body** — ``render_prom`` is a pure function of the registry
  snapshot: the exact text-format-0.0.4 body for a frozen snapshot
  covering every mapped kind (counter, gauge, labeled children,
  distribution->summary, NaN/±Inf spelling, int-collapsed floats) is
  pinned byte-for-byte, and rendering is insertion-order independent.
* **Endpoint** — ``PromServer`` serves exactly that body on
  ``GET /metrics`` with the version-0.0.4 content type, 404s any other
  path, tracks the live snapshot between scrapes, and closes
  idempotently.
* **Gate** — ``maybe_prom_server``: port 0 stays off, -1 binds
  ephemeral, a taken port degrades to None instead of killing the run.
* **Parser** — ``parse_prom_text`` roundtrips the golden body and
  refuses malformed sample lines.
"""
from __future__ import annotations

import math
import socket
import urllib.error
import urllib.request

import pytest

from neuroimagedisttraining_tpu.obs.prom import (CONTENT_TYPE,
                                                 PromServer,
                                                 maybe_prom_server,
                                                 parse_prom_text,
                                                 render_prom)


def _snapshot():
    return {
        "fed_rounds_total": {"type": "counter", "value": 23.0,
                             "labeled": {"site=site2": 7.0}},
        "fleet_sites_live": {"type": "gauge", "value": 3},
        "fleet_round_progress": {"type": "gauge", "value": 0.75},
        "agg_flush_ms": {"type": "distribution",
                         "value": {"p50": 12.0, "p99": 40.5,
                                   "sum": 120.25, "count": 9},
                         "labeled": {"wire=int8": {"p50": 3.5,
                                                   "sum": 7.0,
                                                   "count": 2}}},
        "queue_depth": {"type": "gauge", "value": float("nan"),
                        "labeled": {"site=site1": float("inf"),
                                    "site=site2": float("-inf")}},
    }


_GOLDEN = (
    '# TYPE agg_flush_ms summary\n'
    'agg_flush_ms{quantile="0.5"} 12\n'
    'agg_flush_ms{quantile="0.99"} 40.5\n'
    'agg_flush_ms_sum 120.25\n'
    'agg_flush_ms_count 9\n'
    'agg_flush_ms{wire="int8",quantile="0.5"} 3.5\n'
    'agg_flush_ms_sum{wire="int8"} 7\n'
    'agg_flush_ms_count{wire="int8"} 2\n'
    '# TYPE fed_rounds_total counter\n'
    'fed_rounds_total 23\n'
    'fed_rounds_total{site="site2"} 7\n'
    '# TYPE fleet_round_progress gauge\n'
    'fleet_round_progress 0.75\n'
    '# TYPE fleet_sites_live gauge\n'
    'fleet_sites_live 3\n'
    '# TYPE queue_depth gauge\n'
    'queue_depth NaN\n'
    'queue_depth{site="site1"} +Inf\n'
    'queue_depth{site="site2"} -Inf\n'
)


# ---------------------------------------------------------------------------
# the renderer (pure function, byte-pinned)
# ---------------------------------------------------------------------------

def test_render_golden_body():
    assert render_prom(_snapshot()) == _GOLDEN


def test_render_insertion_order_independent():
    """Output order is sorted metric/label order, not dict order —
    two registries that absorbed the same gauges in different orders
    render byte-identical bodies."""
    snap = _snapshot()
    shuffled = {k: snap[k] for k in reversed(list(snap))}
    assert render_prom(shuffled) == _GOLDEN
    assert render_prom(snap) == render_prom(snap)


def test_render_empty_and_partial():
    """Empty snapshot renders empty; a distribution missing its p99
    drops that quantile row but keeps the _sum/_count pair."""
    assert render_prom({}) == ""
    body = render_prom({"lat_ms": {"type": "distribution",
                                   "value": {"p50": 2.0, "sum": 4.0,
                                             "count": 2}}})
    assert 'lat_ms{quantile="0.5"} 2' in body
    assert 'quantile="0.99"' not in body
    assert "lat_ms_sum 4" in body and "lat_ms_count 2" in body


def test_render_escapes_label_values():
    body = render_prom({"g": {"type": "gauge", "labeled":
                              {'k=a"b\nc': 1.0}}})
    assert body == '# TYPE g gauge\ng{k="a\\"b\\nc"} 1\n'


# ---------------------------------------------------------------------------
# the scrape endpoint
# ---------------------------------------------------------------------------

def test_prom_server_scrape_roundtrip():
    """GET /metrics returns the rendered live snapshot with the
    0.0.4 content type; other paths 404; the body tracks snapshot
    mutation between scrapes; close is idempotent."""
    snap = _snapshot()
    srv = PromServer(lambda: snap, port=0).start()
    try:
        assert srv.port > 0
        with urllib.request.urlopen(srv.url, timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == CONTENT_TYPE
            assert r.read().decode() == _GOLDEN
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/other", timeout=10)
        assert ei.value.code == 404
        snap["fleet_sites_live"]["value"] = 2
        with urllib.request.urlopen(srv.url, timeout=10) as r:
            assert "fleet_sites_live 2" in r.read().decode()
    finally:
        srv.close()
        srv.close()


def test_maybe_prom_server_gate():
    """0 -> off; -1 -> ephemeral port; a port already bound by
    another listener degrades to None (never kills the run)."""
    assert maybe_prom_server(dict, 0) is None
    srv = maybe_prom_server(dict, -1)
    try:
        assert srv is not None and srv.port > 0
    finally:
        srv.close()
    blocker = socket.socket()
    try:
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        taken = blocker.getsockname()[1]
        assert maybe_prom_server(dict, taken) is None
    finally:
        blocker.close()


# ---------------------------------------------------------------------------
# the text parser (the smoke's scrape assertion)
# ---------------------------------------------------------------------------

def test_parse_prom_text_roundtrip():
    samples = parse_prom_text(_GOLDEN)
    assert samples["fleet_sites_live"] == 3.0
    assert samples['fed_rounds_total{site="site2"}'] == 7.0
    assert samples['agg_flush_ms{quantile="0.99"}'] == 40.5
    assert samples['queue_depth{site="site1"}'] == float("inf")
    assert samples['queue_depth{site="site2"}'] == float("-inf")
    assert math.isnan(samples["queue_depth"])
    assert len(samples) == sum(
        1 for ln in _GOLDEN.splitlines() if not ln.startswith("#"))


def test_parse_prom_text_rejects_malformed():
    with pytest.raises(ValueError, match="malformed prom sample"):
        parse_prom_text("just_a_name_no_value")
    with pytest.raises(ValueError, match="line 2"):
        parse_prom_text("ok 1\nbad notafloat")
