"""Training-time CIFAR augmentation wiring (VERDICT r3 missing #1).

The reference trains EVERY CIFAR/tiny batch through
RandomCrop(H, padding=4) + RandomHorizontalFlip
(``cifar10/data_loader.py:46-50`` — the transform lives in the train
DataLoader, there is no off switch). Here the same pipeline is a jittable
op (:func:`data.cifar.random_crop_flip`) applied to every gathered batch
inside the scanned local step (``core/trainer.py``), auto-enabled when the
loader declares the dataset augmentable (``FederatedData.aug_pad_value``).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from neuroimagedisttraining_tpu.algorithms import FedAvg
from neuroimagedisttraining_tpu.core.state import HyperParams
from neuroimagedisttraining_tpu.core.trainer import make_client_update
from neuroimagedisttraining_tpu.data import make_synthetic_federated
from neuroimagedisttraining_tpu.data.cifar import random_crop_flip
from neuroimagedisttraining_tpu.models import create_model, make_apply_fn


# -- the op itself -----------------------------------------------------------

def test_random_crop_flip_pad_value_ring():
    """torchvision pads the RAW image with black before Normalize, so the
    ring must be (0-mean)/std — for a constant image every output pixel is
    either the constant or the per-channel pad value, and with offsets
    forced to the corner the ring is visible."""
    pv = np.array([-1.5, 0.5, 2.0], np.float32)
    x = np.full((64, 8, 8, 3), 7.0, np.float32)
    out = np.asarray(random_crop_flip(
        jax.random.PRNGKey(3), x, padding=4, pad_value=pv))
    assert out.shape == x.shape
    for c in range(3):
        vals = np.unique(out[..., c])
        assert set(np.round(vals, 5)) <= {7.0, np.round(pv[c], 5)}, vals
    # over 64 images with offsets in [0,8], some crop hits the ring
    assert (out != 7.0).any()


def test_random_crop_flip_preserves_interior_pixels_bitexact():
    """Un-padded pixels must pass through bit-exactly (the ring is set via
    select, not arithmetic that would perturb the interior)."""
    x = np.random.RandomState(0).randn(16, 8, 8, 3).astype(np.float32)
    out = np.asarray(random_crop_flip(
        jax.random.PRNGKey(0), x, padding=4,
        pad_value=np.array([9.0, 9.0, 9.0], np.float32)))
    interior = out[out != 9.0]
    pool = set(x.ravel().tolist())
    assert all(v in pool for v in interior.ravel().tolist()[:200])


# -- trainer wiring ----------------------------------------------------------

def _tiny_update(augment_fn):
    model = create_model("cnn_cifar10", num_classes=4)
    apply_fn = make_apply_fn(model)
    hp = HyperParams(lr=0.05, momentum=0.9, local_epochs=1,
                     steps_per_epoch=2, batch_size=4)
    upd = make_client_update(apply_fn, "ce", hp, augment_fn=augment_fn)
    from neuroimagedisttraining_tpu.models import init_params

    params = init_params(model, jax.random.PRNGKey(0), (16, 16, 3))
    mom = jax.tree_util.tree_map(jnp.zeros_like, params)
    mask = jax.tree_util.tree_map(jnp.ones_like, params)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3))
    y = jnp.arange(8) % 4
    out, _, loss = jax.jit(upd)(
        params, mom, mask, jax.random.PRNGKey(2), x, y, jnp.int32(8),
        jnp.float32(0), params)
    return out, float(loss)


def test_augment_fn_applied_inside_step():
    """A zeroing augment_fn must change training (conv kernels get zero
    gradients), while an identity augment_fn reproduces the un-augmented
    run on a dropout-free model — proof the hook sits on the training
    batch path and nowhere else."""
    base, base_loss = _tiny_update(None)
    ident, ident_loss = _tiny_update(lambda k, xb: xb)
    zeros, _ = _tiny_update(lambda k, xb: jnp.zeros_like(xb))
    np.testing.assert_array_equal(
        np.asarray(base["Conv_0"]["kernel"]),
        np.asarray(ident["Conv_0"]["kernel"]))
    assert base_loss == ident_loss
    assert not np.allclose(np.asarray(base["Conv_0"]["kernel"]),
                           np.asarray(zeros["Conv_0"]["kernel"]))


def test_augment_auto_wiring_from_dataset_metadata():
    """augment="auto" (the default) turns on exactly when the loader set
    aug_pad_value; False disables; plain synthetic data gets none."""
    data = make_synthetic_federated(
        n_clients=2, samples_per_client=8, test_per_client=4,
        sample_shape=(16, 16, 3), loss_type="ce", class_num=4, seed=0)
    model = create_model("cnn_cifar10", num_classes=4)
    hp = HyperParams(local_epochs=1, steps_per_epoch=1, batch_size=4)
    assert FedAvg(model, data, hp, loss_type="ce").augment_fn is None

    aug_data = data.replace(aug_pad_value=(-1.9, -2.0, -1.7))
    algo = FedAvg(model, aug_data, hp, loss_type="ce")
    assert algo.augment_fn is not None
    np.testing.assert_allclose(
        algo.augment_fn.keywords["pad_value"], [-1.9, -2.0, -1.7])
    assert FedAvg(model, aug_data, hp, loss_type="ce",
                  augment=False).augment_fn is None


def test_cifar_loader_declares_aug_pad_value(tmp_path):
    """The CIFAR loaders must declare the reference's augmentation contract
    with the black-pixel pad value in normalized space."""
    from neuroimagedisttraining_tpu.data.cifar import (
        CIFAR10_MEAN,
        CIFAR10_STD,
        load_partition_data_cifar,
    )
    import pickle

    base = tmp_path / "cifar-10-batches-py"
    base.mkdir()
    rng = np.random.RandomState(0)
    for i in range(1, 6):
        with open(base / f"data_batch_{i}", "wb") as f:
            pickle.dump({"data": rng.randint(0, 255, (20, 3072), np.uint8),
                         "labels": rng.randint(0, 10, 20).tolist()}, f)
    with open(base / "test_batch", "wb") as f:
        pickle.dump({"data": rng.randint(0, 255, (20, 3072), np.uint8),
                     "labels": rng.randint(0, 10, 20).tolist()}, f)
    data = load_partition_data_cifar(str(tmp_path), "cifar10",
                                     client_number=2, seed=0)
    np.testing.assert_allclose(
        data.aug_pad_value, (0.0 - CIFAR10_MEAN) / CIFAR10_STD, rtol=1e-6)


def test_fedavg_learns_2d_with_augmentation_on():
    """End-to-end: the augmented CIFAR-shaped path still learns well above
    chance — augmentation regularizes, it must not break training."""
    data = make_synthetic_federated(
        n_clients=4, samples_per_client=24, test_per_client=12,
        sample_shape=(16, 16, 3), loss_type="ce", class_num=4, seed=1)
    data = data.replace(aug_pad_value=(0.0, 0.0, 0.0))
    model = create_model("cnn_cifar10", num_classes=4)
    hp = HyperParams(lr=0.05, lr_decay=1.0, momentum=0.9, weight_decay=0.0,
                     grad_clip=10.0, local_epochs=1, steps_per_epoch=3,
                     batch_size=8)
    algo = FedAvg(model, data, hp, loss_type="ce", frac=1.0, seed=0)
    assert algo.augment_fn is not None
    state, _ = algo.run(comm_rounds=10, eval_every=0, finalize=False)
    ev = algo.evaluate(state)
    assert ev["global_acc"] > 0.5, float(ev["global_acc"])  # chance = 0.25


# -- checkpoint lineage guards (ADVICE r3) -----------------------------------

def _args(dataset="cifar10", resume=False, **kw):
    import argparse

    ns = argparse.Namespace(
        dataset=dataset, resume=resume, batching="epoch",
        batching_explicit=False, augment=1, augment_explicit=False)
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def _resolve(args, meta):
    from neuroimagedisttraining_tpu.experiments.runner import (
        _resolve_lineage_semantics,
    )

    return _resolve_lineage_semantics(args, meta, 3, "<dir>")


def test_sidecarless_resume_defaults_to_replacement():
    """A pre-round-3 lineage (no batching sidecar) can only hold
    with-replacement semantics: a resume under the since-flipped default
    must continue THOSE semantics, not warn and mix (ADVICE r3 medium)."""
    args = _args(dataset="synthetic", resume=True)
    _resolve(args, {})
    assert args.batching == "replacement"


def test_sidecarless_resume_explicit_epoch_refused():
    args = _args(dataset="synthetic", resume=True, batching_explicit=True)
    with pytest.raises(SystemExit, match="batching"):
        _resolve(args, {})


def test_sidecarless_fresh_run_refused():
    """The fresh-run overwrite guard must also treat a sidecar-less lineage
    as replacement semantics (ADVICE r3 low #2)."""
    args = _args(dataset="synthetic", resume=False)
    with pytest.raises(SystemExit, match="batching"):
        _resolve(args, {})


def test_preaugment_lineage_resume_defaults_to_noaugment():
    """A pre-round-4 CIFAR lineage trained without augmentation; resuming
    under the new augmented default must continue un-augmented."""
    args = _args(dataset="cifar10", resume=True)
    _resolve(args, {"batching": "epoch"})
    assert args.augment == 0


def test_preaugment_lineage_resume_explicit_augment_refused():
    args = _args(dataset="cifar10", resume=True, augment_explicit=True)
    with pytest.raises(SystemExit, match="augment"):
        _resolve(args, {"batching": "epoch"})


def test_augment_mismatch_fresh_run_refused():
    args = _args(dataset="cifar10", resume=False, augment=0,
                 augment_explicit=True)
    with pytest.raises(SystemExit, match="augment"):
        _resolve(args, {"batching": "epoch", "augment": True})


def test_matching_lineage_passes():
    args = _args(dataset="cifar10", resume=True)
    _resolve(args, {"batching": "epoch", "augment": True})
    assert args.batching == "epoch" and args.augment == 1
    args = _args(dataset="synthetic", resume=False)
    _resolve(args, {"batching": "epoch", "augment": False})


def test_adapted_resume_lands_under_adapted_identity(tmp_path):
    """When a sidecar-less (pre-round-3) lineage adapts the defaulted
    --batching to replacement on resume, the run identity must carry the
    'wr' tag so the adapted run's logs/stat_info split from the
    epoch-semantics lineage (code-review r4 finding)."""
    import jax

    from neuroimagedisttraining_tpu.experiments.config import (
        parse_args,
        run_identity,
    )
    from neuroimagedisttraining_tpu.experiments.runner import (
        build_algorithm,
        run_experiment,
    )
    from neuroimagedisttraining_tpu.utils.checkpoint import CheckpointManager

    common = ["--algo", "local", "--model", "small3dcnn",
              "--dataset", "synthetic", "--client_num_in_total", "2",
              "--frac", "1.0", "--epochs", "1", "--batch_size", "4",
              "--comm_round", "2", "--frequency_of_the_test", "0",
              "--mesh_devices", "1",  # fabricated state is single-device
              "--checkpoint_dir", str(tmp_path / "ck"),
              "--results_dir", "", "--log_dir", ""]
    # fabricate a legacy lineage: round-1 state, NO batching sidecar —
    # the state template must match, so build with replacement semantics
    args0 = parse_args(common + ["--batching", "replacement"])
    algo, _ = build_algorithm(args0, "local")
    mgr = CheckpointManager(str(tmp_path / "ck"),
                            run_identity(args0, "local",
                                         for_checkpoint=True))
    mgr.save(1, algo.init_state(jax.random.PRNGKey(args0.seed)),
             metadata={"cost": {}})
    mgr.close()

    out = run_experiment(parse_args(common + ["--resume"]))
    assert "wr" in out["identity"].split("-"), out["identity"]
    assert [h["round"] for h in out["history"]] == [1]


def test_recorded_lineage_defaulted_resume_adapts():
    """Once an adapted lineage starts RECORDING its semantics
    (batching=replacement / augment=0 sidecars), the same defaulted resume
    command must keep working — defaulted knobs adapt to the recorded
    lineage on resume instead of refusing (code-review r4)."""
    args = _args(dataset="synthetic", resume=True)
    _resolve(args, {"batching": "replacement", "augment": False})
    assert args.batching == "replacement"

    args = _args(dataset="cifar10", resume=True)
    _resolve(args, {"batching": "epoch", "augment": False})
    assert args.augment == 0
