"""Seeded-violation fixtures for the jaxpr auditor (loaded by
``scripts/lint_gate.py --jaxpr-fixture path.py::name``).

Each fixture is a zero-arg callable returning ``(fn, args)``; the gate
traces ``fn(*args)`` with ``jax.make_jaxpr`` (under ``enable_x64`` when
``--x64`` is passed) and applies the hot-path contracts. These model
the regressions the auditor exists to catch *before* they reach pod
hardware: a latent f64 promotion, a host callback on the round path,
and a branch-dependent collective (the SPMD deadlock hazard).
"""
import numpy as np


def f64_round():
    """A round-body fragment with a latent f64 promotion: an np.float64
    weight scalar. With x64 off jax silently demotes it — the exact
    reason the auditor traces fixtures under enable_x64."""
    import jax.numpy as jnp

    w = np.float64(0.5)  # strongly-typed f64 scalar: promotes under x64

    def fn(x):
        return (x * w).sum() / jnp.asarray(x.shape[0], jnp.float32)

    return fn, (np.ones((8, 4), np.float32),)


def callback_round():
    """A round body that smuggles a host callback onto the hot path."""
    import jax
    import jax.numpy as jnp

    def fn(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2.0,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return jnp.sum(y)

    return fn, (np.ones((4,), np.float32),)


def branch_collective():
    """A ``lax.cond`` whose branches issue DIFFERENT collectives — on
    real multi-host SPMD a data-dependent branch like this deadlocks
    (processes disagree on whether to enter the psum)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    try:  # jax >= 0.7 exports shard_map at top level
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("clients",))

    def inner(x):
        return jax.lax.cond(
            jnp.sum(x) > 0,
            lambda v: jax.lax.psum(v, "clients"),
            lambda v: v * 2.0,
            x)

    import inspect

    kw = {"check_rep": False} \
        if "check_rep" in inspect.signature(shard_map).parameters \
        else {"check_vma": False}
    fn = shard_map(inner, mesh=mesh, in_specs=P("clients"),
                   out_specs=P("clients"), **kw)
    return fn, (np.ones((len(devs), 3), np.float32),)


def clean_round():
    """Whitelist-clean control: f32 math, no callbacks, no branches."""
    import jax.numpy as jnp

    def fn(x, w):
        return jnp.sum(x * w[:, None]) / jnp.maximum(jnp.sum(w), 1.0)

    return fn, (np.ones((8, 4), np.float32),
                np.ones((8,), np.float32))
