"""Full-fidelity ABCD disk-path integration (slow tier).

VERDICT r2 missing-item 1: nothing drove ``data/abcd.py`` byte-for-byte the
way a real cohort run would. These tests write a small-N cohort at the REAL
volume shape (121x145x121 — ``ABCD/data_loader.py:115-117``) to disk and:

* drive the flagship CLI end-to-end: h5 -> lazy per-site load -> s2d
  layout -> SalientGrads train -> orbax checkpoint -> resume -> stat_info
  (``main_sailentgrads.py:130-279`` is the reference path being mirrored);
* drive the multi-host ``client_filter`` path on the 2-process
  ``jax.distributed`` harness: each process lazily reads ONLY its own
  sites from the shared cohort file, pads to the global maxima, and a full
  federated round agrees bit-for-bit across controllers
  (``data_loader.py:220-319`` / parallel/multihost.py design note).
"""
import os
import pickle
import socket
import subprocess
import sys

import numpy as np
import pytest


REAL_SHAPE = (121, 145, 121)


def _write_cohort(path, n_sites=4, per_site=5, seed=0):
    from neuroimagedisttraining_tpu.data.abcd import write_abcd_h5

    rng = np.random.RandomState(seed)
    n = n_sites * per_site
    # real-shape volumes with a planted sex signal so training has gradient
    y = rng.randint(0, 2, size=n)
    X = rng.rand(n, *REAL_SHAPE).astype(np.float32) * 0.1
    X += 0.2 * y[:, None, None, None].astype(np.float32)
    site = np.repeat(np.arange(n_sites), per_site)
    write_abcd_h5(str(path), X, y, site)
    return str(path)


@pytest.mark.slow
def test_abcd_disk_salientgrads_checkpoint_resume_stat_info(tmp_path):
    from neuroimagedisttraining_tpu.experiments.config import parse_args
    from neuroimagedisttraining_tpu.experiments.runner import run_experiment

    cohort = _write_cohort(tmp_path / "final_dataset_20subs.h5")
    common = [
        "--model", "3dcnn", "--dataset", "abcd_site", "--data_dir", cohort,
        "--layout", "s2d", "--client_num_in_total", "0",
        "--frac", "1.0", "--epochs", "1", "--batch_size", "2",
        "--lr", "1e-3", "--frequency_of_the_test", "1",
        "--final_finetune", "0",
        # single-device path, like the attached real chip: sharding THIS
        # full-size program over the suite's virtual CPU mesh aborts
        # inside XLA:CPU (observed "Fatal Python error: Aborted" at the
        # result fetch); the multi-device disk path is covered by the
        # 2-process test below with the small model
        "--mesh_devices", "1",
        # chunk the client vmap: XLA:CPU compiles the one-client body once
        # (lax.map) instead of a 4-wide full-size vmapped graph, which
        # takes >30 min to compile on this 1-core host
        "--client_chunk", "1",
        "--checkpoint_dir", str(tmp_path / "ck"),
        "--results_dir", str(tmp_path / "res"),
        "--log_dir", str(tmp_path / "log"),
    ]
    out1 = run_experiment(
        parse_args(common + ["--comm_round", "1"], algo="salientgrads"),
        "salientgrads")
    assert len(out1["history"]) == 1
    rec0 = out1["history"][0]
    assert rec0["round"] == 0 and np.isfinite(rec0["train_loss"])
    # the SNIP global mask actually pruned the stem at dense_ratio 0.5
    with open(out1["stat_path"], "rb") as f:
        stat1 = pickle.load(f)
    assert stat1["sum_training_flops"] > 0
    assert 0 < len(stat1["global_test_acc"])

    # resume: one more round from the persisted checkpoint
    out2 = run_experiment(
        parse_args(common + ["--comm_round", "2", "--resume"],
                   algo="salientgrads"), "salientgrads")
    assert [h["round"] for h in out2["history"]] == [1]
    assert np.isfinite(out2["history"][0]["train_loss"])
    with open(out2["stat_path"], "rb") as f:
        stat2 = pickle.load(f)
    # cost sidecar restored: cumulative counters strictly grow across the
    # resume boundary instead of restarting
    assert stat2["sum_training_flops"] > stat1["sum_training_flops"]
    assert stat2["sum_comm_params"] > stat1["sum_comm_params"]


_FILTER_WORKER = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from neuroimagedisttraining_tpu.parallel import (
    initialize_distributed,
    local_client_indices,
    make_multihost_mesh,
    shard_federated_data_global,
)

port, pid, cohort = sys.argv[1], int(sys.argv[2]), sys.argv[3]
ok = initialize_distributed(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid)
assert ok and jax.process_count() == 2

from neuroimagedisttraining_tpu.algorithms import FedAvg
from neuroimagedisttraining_tpu.core.state import HyperParams
from neuroimagedisttraining_tpu.data import load_federated_data
from neuroimagedisttraining_tpu.models import create_model

N = 4  # sites in the cohort file
mesh = make_multihost_mesh(num_clients=N)
idx = local_client_indices(N, mesh)
assert len(idx) == 2, idx  # each process owns half the sites

# THE path under test: lazy per-site disk reads of only this process's
# sites, padded to the global maxima
local = load_federated_data("abcd_site", data_dir=cohort,
                            client_filter=idx, layout="flat")
gdata = shard_federated_data_global(local, N, mesh)

model = create_model("small3dcnn", num_classes=1)
hp = HyperParams(lr=1e-3, lr_decay=1.0, momentum=0.9, local_epochs=1,
                 steps_per_epoch=2, batch_size=2)
algo = FedAvg(model, gdata, hp, loss_type="bce", frac=1.0, seed=0,
              channel_inject=True)
state = algo.init_state(jax.random.PRNGKey(0))
state, metrics = algo.run_round(state, 0)
loss = float(metrics["train_loss"])
assert np.isfinite(loss)
print(f"RANK{pid} OK loss={loss:.6f}", flush=True)
"""


@pytest.mark.slow
def test_abcd_disk_client_filter_two_process(tmp_path):
    cohort = _write_cohort(tmp_path / "cohort.h5", per_site=4, seed=1)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_FILTER_WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_NUM_CPU_DEVICES", None)
    # hand the workers the pytest process's persistent compile cache
    # (conftest sets it via jax.config, which subprocesses don't inherit):
    # without it every run pays two CONCURRENT cold full-size XLA:CPU
    # compiles on this 1-core host — observed >900 s and a spurious
    # timeout failure
    import jax as _jax

    cache_dir = getattr(_jax.config, "jax_compilation_cache_dir", "")
    if cache_dir:
        env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
        env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "1.0"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(pid), cohort],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=repo_root, text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            # generous: a cold-cache run compiles the full-size program
            # twice concurrently on one core (~12-20 min); warm runs take
            # ~2 min
            out, _ = p.communicate(timeout=1800)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-3000:]}"
        assert f"RANK{pid} OK" in out, out[-3000:]
    # both controllers agree on the aggregated loss bit-for-bit
    l0 = outs[0].split("loss=")[1].split()[0]
    l1 = outs[1].split("loss=")[1].split()[0]
    assert l0 == l1, (l0, l1)
